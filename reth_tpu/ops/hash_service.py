"""Shared device hash service: continuous batching, priority lanes, and
backpressure for every keccak client.

Until now every hashing client owned the device alone: ``RebuildPipeline``
monopolized the backend during a rebuild, ``SparseRootTask`` dispatched
tiny synchronous batches (single keys, even), and ``ProofCalculator`` /
witness hashing never touched the device at all. This module is the
missing scheduling layer between them — one background service owns one
(supervised) backend and multiplexes every client over it, the way the
parallel-hashing literature (Sakura tree hashing, arxiv 1608.00492) and
the async-storage parallel-EVM work (Reddio, arxiv 2503.04595) keep an
accelerator saturated: decouple request arrival from dispatch.

Shape:

- **Priority lanes** (:data:`LANES`): ``live`` (live-tip state root) >
  ``payload`` (payload build) > ``rebuild`` (Merkle rebuild) > ``proof``
  (proof/RPC). Clients submit async requests (:meth:`HashService.submit`
  → :class:`HashFuture`) or call synchronously through a lane-bound
  :class:`HashClient` that satisfies the repo-wide ``hasher`` protocol
  (``list[bytes] -> list[bytes]``).
- **Continuous batching**: a dispatcher thread gathers requests until a
  fused tier fills (``fill_target`` messages) or a coalescing deadline
  (``window_s``) expires, concatenates them into ONE backend dispatch,
  and scatters the digests back through the futures. Many tiny client
  batches become one full-rate device batch. A LONE request dispatches
  immediately — the synchronous latency path never pays the window; the
  window only gathers once a second request is pending, so under load
  the previous dispatch's wall time is the natural gather period.
- **Backpressure**: per-lane queues are bounded in *messages*; a full
  lane blocks the submitter (or raises :class:`LaneOverloaded` with
  ``block=False``) instead of growing without bound.
- **Anti-starvation aging**: drain order is priority lanes first, but any
  request older than ``age_promote_s`` is taken FIRST (FIFO), so a
  saturating live-tip stream cannot starve proof/RPC traffic forever.
- **Exclusive lease** (:meth:`HashService.lease`): ``RebuildPipeline``
  streams pre-packed windows through the array-protocol engine without
  per-call service overhead; the lease pauses coalesced dispatching.
  Requests that age past ``lease_bypass_s`` while a (long) lease is held
  are dispatched on the CPU twin, so a multi-second rebuild window never
  blocks the live tip.
- **Device mesh** (``mesh=``, a ``parallel/mesh.py`` :class:`HashMesh`):
  the service owns a device MESH instead of one backend. A
  partition-rule table (``HashMesh.spec_for``) decides how each
  coalesced dispatch shards: large batches scatter over the live mesh
  (``P(axis)``, one keccak shard per device), scalar and sub-threshold
  requests stay unpartitioned on one device (``P()``) — hash throughput
  only scales with lanes when batching is explicit (arxiv 1608.00492,
  2501.18780). The exclusive lease generalizes to a **sub-mesh lease**:
  a rebuild claims k of n devices (``lease(devices=k)``) while the
  live/payload/proof lanes keep dispatching on the rest — no pause, no
  CPU bypass. Per-device circuit breakers
  (``ops/supervisor.py DeviceBreakerBoard``) give partial-mesh
  degradation: a wedged device SHRINKS the mesh (shardings re-form on
  the survivors and the in-flight batch replays there, bit-identical —
  hashing is stateless); the numpy-twin replay below remains the FINAL
  rung, taken only once every device has tripped.
- **Failover**: the backend is typically an ``ops/supervisor.py``
  :class:`~reth_tpu.ops.supervisor.SupervisedHasher` — circuit-breaker
  trips and watchdog timeouts apply to the shared service. Hashing is
  stateless, so if a dispatch still raises (or service fault injection
  wedges it), the WHOLE in-flight batch is replayed on the numpy twin:
  every future completes exactly once, no request is lost.
- **Fault injection** (:class:`ServiceFaultInjector`):
  ``RETH_TPU_FAULT_SERVICE_WEDGE_EVERY`` / ``RETH_TPU_FAULT_SERVICE_STALL``
  / ``RETH_TPU_FAULT_SERVICE_QUEUE_CAP`` drill the replay, overload, and
  backpressure paths without hardware.
- **Observability**: ``hash_service_*`` metrics (per-lane queue depth,
  coalesce factor, batch occupancy, wait/service-time histograms) plus a
  ``node/events.py`` dashboard fragment via :meth:`snapshot`.

Wiring: ``--hash-service`` (cli.py) hangs a service off the committer;
``TrieCommitter.for_lane`` hands lane-bound clients to ``SparseRootTask``
("live"), the payload builder ("payload"), the hashing/Merkle stages
("rebuild"), and ``ProofCalculator`` ("proof"); ``TurboCommitter``
("auto"/"device") takes the exclusive lease around each rebuild commit.
The parallel sparse commit (``trie/sparse.py``) STREAMS its encode-pool
chunks onto the live lane (``HashClient.submit`` / ``map_chunks``): each
per-depth level arrives as many small requests that the coalescing
window fuses back into one device dispatch while the host keeps
encoding the rest of the level.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

from .. import tracing

# priority order, highest first — index IS the priority
LANES = ("live", "payload", "rebuild", "proof")
_LANE_INDEX = {name: i for i, name in enumerate(LANES)}

# per-lane p99 queue-wait SLO budgets (seconds) — the live lane sits on
# the block-import critical path, the background lanes tolerate queueing
# by design. Kept here, next to the lane definitions, so a new lane must
# declare its budget; consumed by health.py's default SLO rule table.
DEFAULT_WAIT_BUDGETS = {"live": 0.25, "payload": 0.5,
                        "rebuild": 2.0, "proof": 1.0}
# p99 budget for one coalesced dispatch's wall (service time): a healthy
# dispatch is sub-ms..tens of ms; sustained 150ms+ means a stalling
# backend (wedge drill, compile storm, saturated tunnel)
DEFAULT_DISPATCH_BUDGET_S = 0.15


class HashServiceError(RuntimeError):
    """Base class for service-level failures."""


class LaneOverloaded(HashServiceError):
    """Bounded lane queue is full and the submitter asked not to block."""


class ServiceStopped(HashServiceError):
    """The service was stopped while this request was queued."""


class InjectedServiceWedge(HashServiceError):
    """Service fault injection wedged this coalesced dispatch
    (RETH_TPU_FAULT_SERVICE_WEDGE_EVERY) — exercises the replay path."""


class ServiceFaultInjector:
    """Overload/stall fault policies for the shared service, in the style
    of ``ops/supervisor.py``'s FaultInjector.

    ``wedge_every``: every Nth coalesced dispatch raises
    :class:`InjectedServiceWedge` BEFORE touching the backend; the batch
    must complete via the numpy-twin replay (``wedge_every=1`` = every
    dispatch, the full-failover drill).
    ``stall``: fixed seconds added to every coalesced dispatch — an
    overload drill that backs requests up into the bounded lanes.
    ``queue_cap``: overrides every lane's message capacity (small values
    drill backpressure blocking/rejection).

    Env form (:meth:`from_env`): ``RETH_TPU_FAULT_SERVICE_WEDGE_EVERY`` /
    ``RETH_TPU_FAULT_SERVICE_STALL`` / ``RETH_TPU_FAULT_SERVICE_QUEUE_CAP``.
    """

    def __init__(self, wedge_every: int = 0, stall: float = 0.0,
                 queue_cap: int = 0):
        self.wedge_every = wedge_every
        self.stall = stall
        self.queue_cap = queue_cap
        self.dispatches = 0
        self.wedged = 0
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env=None) -> "ServiceFaultInjector | None":
        env = os.environ if env is None else env
        wedge = int(env.get("RETH_TPU_FAULT_SERVICE_WEDGE_EVERY", "0") or 0)
        stall = float(env.get("RETH_TPU_FAULT_SERVICE_STALL", "0") or 0)
        cap = int(env.get("RETH_TPU_FAULT_SERVICE_QUEUE_CAP", "0") or 0)
        if not (wedge or stall or cap):
            return None
        return cls(wedge_every=wedge, stall=stall, queue_cap=cap)

    def active(self) -> bool:
        return bool(self.wedge_every or self.stall or self.queue_cap)

    def on_dispatch(self) -> None:
        """Called before every coalesced dispatch touches the backend."""
        with self._lock:
            self.dispatches += 1
            n = self.dispatches
        if self.stall:
            tracing.fault_event("RETH_TPU_FAULT_SERVICE_STALL",
                                target="ops::hash_service",
                                dispatch=n, stall_s=self.stall)
            time.sleep(self.stall)
        if self.wedge_every and n % self.wedge_every == 0:
            with self._lock:
                self.wedged += 1
            tracing.fault_event("RETH_TPU_FAULT_SERVICE_WEDGE_EVERY",
                                target="ops::hash_service", dispatch=n)
            raise InjectedServiceWedge(
                f"injected service wedge on dispatch #{n} "
                f"(every {self.wedge_every})")


class HashFuture:
    """Completion handle for one submitted request. Completes exactly once
    — either with the digest list or with an exception."""

    __slots__ = ("_event", "_result", "_error", "completions")

    def __init__(self):
        self._event = threading.Event()
        self._result: list[bytes] | None = None
        self._error: BaseException | None = None
        self.completions = 0  # must end at exactly 1 (drill assertion)

    def _complete(self, result=None, error=None) -> None:
        self.completions += 1
        if self.completions > 1:  # pragma: no cover - invariant guard
            raise AssertionError("HashFuture completed twice")
        self._result, self._error = result, error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> list[bytes]:
        if not self._event.wait(timeout):
            raise TimeoutError("hash service request timed out")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("lane", "msgs", "future", "enqueued_at", "ctx", "wall_at")

    window = None  # plain hash request (multi-level requests override)

    def __init__(self, lane: str, msgs: list[bytes]):
        self.lane = lane
        self.msgs = msgs
        self.future = HashFuture()
        self.enqueued_at = time.monotonic()
        # explicit trace handoff across the queue: the dispatcher thread
        # serves many traces per coalesced batch, so each request carries
        # its submitter's context and gets a per-request span on completion
        self.ctx = tracing.current_context()
        self.wall_at = time.time()


class _WindowRequest:
    """One multi-level request: a pre-packed k-level window (per-depth
    packed/branch level arrays, the ``dispatch_packed``/``dispatch_branch``
    wire shape) that the dispatcher runs as ONE whole-subtrie fused
    dispatch instead of one hash call per depth. Completes with the
    fetched digest rows (``fetch`` slots, or the whole buffer)."""

    __slots__ = ("lane", "window", "max_slots", "fetch", "rows", "future",
                 "enqueued_at", "ctx", "wall_at")

    def __init__(self, lane: str, window: list[dict], max_slots: int,
                 fetch=None):
        self.lane = lane
        self.window = window
        self.max_slots = max_slots
        self.fetch = fetch
        self.rows = sum(len(lv["slots"]) for lv in window)
        self.future = HashFuture()
        self.enqueued_at = time.monotonic()
        self.ctx = tracing.current_context()
        self.wall_at = time.time()


def _req_msgs(r) -> int:
    """Queue-accounting size of one request (messages, or window rows)."""
    return r.rows if r.window is not None else len(r.msgs)


class HashClient:
    """Lane-bound callable satisfying the repo-wide ``hasher`` protocol
    (``list[bytes] -> list[bytes]``) — drop-in for ``KeccakDevice
    .hash_batch`` / ``keccak256_batch_np`` / ``SupervisedHasher``."""

    __slots__ = ("service", "lane")

    def __init__(self, service: "HashService", lane: str):
        if lane not in _LANE_INDEX:
            raise ValueError(f"unknown lane {lane!r} (have {LANES})")
        self.service = service
        self.lane = lane

    def __call__(self, msgs: list[bytes]) -> list[bytes]:
        return self.service.hash(self.lane, list(msgs))

    def submit(self, msgs: list[bytes]) -> HashFuture:
        return self.service.submit(self.lane, list(msgs))

    def commit_window(self, window: list[dict], max_slots: int,
                      fetch=None):
        """Multi-level request: hand the service a pre-packed k-level
        window (one dict per level in deepest-first order — the
        ``dispatch_packed``/``dispatch_branch`` array shape) and get the
        digest buffer (or the ``fetch`` slots) back from ONE fused
        dispatch. This is how the live sparse finish and the rebuild
        lanes collapse their per-depth hash calls."""
        return self.service.submit_window(self.lane, window,
                                          max_slots, fetch=fetch).result()

    def map_chunks(self, chunks) -> list[bytes]:
        """Live-lane streaming: submit every chunk as its own request —
        a producer (e.g. the parallel sparse commit's encode pool) keeps
        encoding while earlier chunks already sit in the dispatcher,
        whose continuous batching fuses them back into ONE full-rate
        dispatch — then gather digests in submission order."""
        futs = [self.submit(list(c)) for c in chunks]
        out: list[bytes] = []
        for f in futs:
            out.extend(f.result())
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"HashClient(lane={self.lane!r})"


class PipelineLease:
    """A double-buffered sub-mesh held by the cross-block import
    pipeline: the speculative block's key-prehash batches dispatch on
    the leased devices (via the service's sharded hasher) while the
    committing block's lanes re-form over the rest. Release is
    idempotent — the pipeline's abort ladder releases on every exit
    path, and the chaos drills assert zero leaked leases."""

    def __init__(self, service: "HashService", sub):
        self._service = service
        self._sub = sub
        self.devices = len(sub.indices)
        self.released = False

    def hash(self, msgs: list[bytes]) -> list[bytes]:
        if self.released:  # late straggler batch: CPU twin, never racy
            return self._service._cpu(msgs)
        return self._service._mesh_hasher.hash_sharded(msgs, self._sub.mesh)

    def release(self) -> None:
        if self.released:
            return
        self.released = True
        self._sub.release()


class LeasedTurboBackend:
    """Array-protocol backend proxy that holds the service's exclusive
    lease for the duration of one turbo commit (``begin`` → terminal
    ``finish``/``fetch_slots``). The RebuildPipeline keeps streaming its
    pre-packed windows straight at the inner engine — zero per-dispatch
    service overhead — while coalesced lanes pause (aged requests bypass
    onto the CPU twin, see :meth:`HashService.lease`)."""

    def __init__(self, service: "HashService", inner=None, factory=None):
        if inner is None and factory is None:
            raise ValueError("LeasedTurboBackend needs inner or factory")
        self._service = service
        self._inner = inner
        self._factory = factory
        self._lease = None

    @property
    def effective_kind(self) -> str:
        return getattr(self._inner, "effective_kind", "device")

    @property
    def failed_over(self) -> bool:
        return getattr(self._inner, "failed_over", False)

    def begin(self, max_slots: int) -> None:
        if self._lease is None:
            self._lease = self._service.lease(what="rebuild")
            self._lease.__enter__()
        if self._inner is None:
            # deferred construction: on a meshed service the engine's
            # shardings must form over the sub-mesh the lease carved out,
            # which only exists once the lease is held
            self._inner = self._factory()
        self._inner.begin(max_slots)

    def release(self) -> None:
        """Drop the lease. Idempotent — the terminal fetch calls this, and
        committers also call it from a ``finally`` so an aborted commit
        (pipeline fault drill, sweep rejection) can never wedge the
        service's coalesced lanes."""
        if self._lease is not None:
            lease, self._lease = self._lease, None
            lease.__exit__(None, None, None)

    _release = release

    def ensure(self, max_slots: int) -> None:
        self._inner.ensure(max_slots)

    def alloc_slot(self) -> int:
        return self._inner.alloc_slot()

    def dispatch_level(self, bucket) -> None:
        self._inner.dispatch_level(bucket)

    def dispatch_packed(self, flat, row_off, row_len, slots, holes, b_tier):
        self._inner.dispatch_packed(flat, row_off, row_len, slots, holes,
                                    b_tier)

    def dispatch_branch(self, masks, slots, children) -> None:
        self._inner.dispatch_branch(masks, slots, children)

    def flush_window(self) -> None:
        flush = getattr(self._inner, "flush_window", None)
        if flush is not None:
            flush()

    def fetch_slots(self, slots):
        try:
            return self._inner.fetch_slots(slots)
        finally:
            self._release()

    def finish(self):
        try:
            return self._inner.finish()
        finally:
            self._release()


def _next_tier(n: int, min_tier: int) -> int:
    t = max(1, min_tier)
    while t < n:
        t *= 2
    return t


class HashService:
    """Background device hash service: one (supervised) backend, many
    clients, continuous batching. See the module docstring for semantics.

    ``backend``: the batch hasher (``list[bytes] -> list[bytes]``); when
    None, built from ``supervisor`` (a ``SupervisedHasher``) or, with no
    supervisor either, the plain device front-end.
    ``cpu_hasher``: the replay twin (default ``keccak256_batch_np``).
    ``mesh``: a ``parallel/mesh.py`` HashMesh — coalesced dispatches then
    route through the partition-rule table (sharded over the live mesh or
    unpartitioned on one device) instead of ``backend``; per-device
    breakers shrink the mesh before the CPU twin is ever considered.
    ``rebuild_devices``: sub-mesh lease width (k of n devices for the
    rebuild; default ``RETH_TPU_MESH_REBUILD_DEVICES`` or half the mesh).
    """

    def __init__(self, backend=None, supervisor=None, *,
                 cpu_hasher=None,
                 window_s: float | None = None,
                 fill_target: int | None = None,
                 max_batch: int | None = None,
                 lane_capacity: int | None = None,
                 age_promote_s: float | None = None,
                 lease_bypass_s: float | None = None,
                 min_tier: int = 1024,
                 injector: ServiceFaultInjector | None = None,
                 mesh=None, breaker_board=None, device_injector=None,
                 rebuild_devices: int | None = None, warmup=None,
                 subtrie_levels: int | None = None, registry=None):
        env = os.environ
        # multi-level window requests (submit_window): k levels per fused
        # dispatch; RETH_TPU_SUBTRIE_LEVELS=0 keeps the default of 8 here
        # because a window request is an EXPLICIT multi-level ask
        if subtrie_levels is None:
            subtrie_levels = int(
                env.get("RETH_TPU_SUBTRIE_LEVELS", "0") or 8)
        self.subtrie_levels = max(1, int(subtrie_levels))
        self.warmup = warmup
        self.window_dispatches = 0
        self.supervisor = supervisor
        if backend is None:
            if supervisor is not None:
                from .supervisor import SupervisedHasher

                backend = SupervisedHasher(supervisor, min_tier=min_tier)
            else:
                from .keccak_jax import KeccakDevice

                backend = KeccakDevice(min_tier=min_tier,
                                       block_tier=4).hash_batch
        self._backend = backend
        if cpu_hasher is None:
            from ..primitives.keccak import keccak256_batch_np

            cpu_hasher = keccak256_batch_np
        self._cpu = cpu_hasher
        self.window_s = float(window_s if window_s is not None
                              else env.get("RETH_TPU_SERVICE_WINDOW", "0.002"))
        self.fill_target = int(fill_target or
                               env.get("RETH_TPU_SERVICE_FILL", 0) or min_tier)
        self.max_batch = int(max_batch or 8 * self.fill_target)
        self.injector = (injector if injector is not None
                         else ServiceFaultInjector.from_env())
        cap = int(lane_capacity or
                  env.get("RETH_TPU_SERVICE_LANE_CAP", 0) or 262144)
        if self.injector is not None and self.injector.queue_cap:
            cap = self.injector.queue_cap
        self.lane_capacity = cap
        self.age_promote_s = float(
            age_promote_s if age_promote_s is not None
            else env.get("RETH_TPU_SERVICE_AGE_PROMOTE", "0.05"))
        self.lease_bypass_s = float(
            lease_bypass_s if lease_bypass_s is not None
            else env.get("RETH_TPU_SERVICE_LEASE_BYPASS", "0.02"))
        self.min_tier = min_tier

        from ..metrics import HashServiceMetrics

        self.metrics = HashServiceMetrics(registry)
        # -- device mesh (tentpole): partition-rule routed sharded dispatch,
        # per-device breakers, sub-mesh rebuild leases
        self.mesh = mesh
        self._mesh_hasher = None
        self.breaker_board = breaker_board
        self.device_injector = device_injector
        self.rebuild_devices = rebuild_devices
        if mesh is not None:
            from ..parallel.mesh import MeshKeccak

            self._mesh_hasher = MeshKeccak(mesh, min_tier=min_tier,
                                           block_tier=4, warmup=warmup)
            if breaker_board is None:
                from .supervisor import DeviceBreakerBoard

                self.breaker_board = DeviceBreakerBoard(mesh)
            if device_injector is None:
                from .supervisor import FaultInjector

                self.device_injector = FaultInjector.from_env()
            if rebuild_devices is None:
                self.rebuild_devices = int(
                    env.get("RETH_TPU_MESH_REBUILD_DEVICES", 0)
                    or max(1, mesh.n_devices // 2))
        self._cond = threading.Condition()
        self._queues: dict[str, list[_Request]] = {l: [] for l in LANES}
        self._queued_msgs: dict[str, int] = {l: 0 for l in LANES}
        self._stopping = False
        self._leased = False
        self._lease_what: str | None = None
        self._submesh = None  # active _SubMeshLease (rebuild holds k devices)
        self._dispatching = False
        # counters surfaced via snapshot() (metrics hold the full detail)
        self.dispatches = 0
        self.coalesced_requests = 0
        self.hashed_msgs = 0
        self.replays = 0
        self.rejects = 0
        self.leases = 0
        self.lease_bypasses = 0
        self.submesh_leases = 0
        self.pipeline_leases = 0
        self.mesh_sharded = 0
        self.mesh_single = 0
        self.mesh_replays = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hash-service")
        self._thread.start()

    # -- shared instance (one service per process, like DeviceSupervisor) --

    _shared: "HashService | None" = None
    _shared_lock = threading.Lock()

    @classmethod
    def shared(cls, **kw) -> "HashService":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls(**kw)
            return cls._shared

    @classmethod
    def reset_shared(cls) -> None:
        with cls._shared_lock:
            svc, cls._shared = cls._shared, None
        if svc is not None:
            svc.stop()

    # -- client API --------------------------------------------------------

    def client(self, lane: str) -> HashClient:
        return HashClient(self, lane)

    def submit(self, lane: str, msgs: list[bytes], *,
               block: bool = True, timeout: float | None = None) -> HashFuture:
        """Enqueue one request on ``lane``. A full lane blocks the caller
        (bounded-queue backpressure) unless ``block=False``, which raises
        :class:`LaneOverloaded` instead. Oversized single requests (more
        messages than the lane holds) are admitted alone — they could
        never fit otherwise."""
        if lane not in _LANE_INDEX:
            raise ValueError(f"unknown lane {lane!r} (have {LANES})")
        req = _Request(lane, msgs)
        if not msgs:
            req.future._complete(result=[])
            return req.future
        n = len(msgs)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._stopping:
                    raise ServiceStopped("hash service is stopping")
                room = self.lane_capacity - self._queued_msgs[lane]
                if n <= room or not self._queues[lane]:
                    break
                if not block:
                    self.rejects += 1
                    self.metrics.record_reject(lane)
                    raise LaneOverloaded(
                        f"lane {lane!r} is full "
                        f"({self._queued_msgs[lane]}/{self.lane_capacity} msgs)")
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    self.rejects += 1
                    self.metrics.record_reject(lane)
                    raise LaneOverloaded(
                        f"lane {lane!r} still full after {timeout}s")
                self._cond.wait(remaining)
            self._queues[lane].append(req)
            self._queued_msgs[lane] += n
            self.metrics.record_submit(lane, n)
            self.metrics.set_queue_depth(lane, self._queued_msgs[lane])
            self._cond.notify_all()
        return req.future

    def hash(self, lane: str, msgs: list[bytes]) -> list[bytes]:
        """Synchronous submit-and-wait — the ``hasher``-protocol path."""
        return self.submit(lane, msgs).result()

    def submit_window(self, lane: str, window: list[dict], max_slots: int,
                      *, fetch=None, block: bool = True,
                      timeout: float | None = None) -> HashFuture:
        """Enqueue one multi-level window request on ``lane``: a list of
        level dicts in deepest-first order (``{"flat", "row_off",
        "row_len", "slots", "holes", "b_tier"}`` or ``{"kind": "branch",
        "masks", "slots", "children"}``). The dispatcher runs the whole
        window through a whole-subtrie fused engine — ONE device dispatch
        per k levels — and completes the future with the digest buffer
        (or the requested ``fetch`` slots). Windows never coalesce with
        plain hash requests; they occupy ``rows`` messages of the lane's
        bounded capacity."""
        if lane not in _LANE_INDEX:
            raise ValueError(f"unknown lane {lane!r} (have {LANES})")
        req = _WindowRequest(lane, window, max_slots, fetch=fetch)
        if not window:
            req.future._complete(result=[])
            return req.future
        n = req.rows
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._stopping:
                    raise ServiceStopped("hash service is stopping")
                room = self.lane_capacity - self._queued_msgs[lane]
                if n <= room or not self._queues[lane]:
                    break
                if not block:
                    self.rejects += 1
                    self.metrics.record_reject(lane)
                    raise LaneOverloaded(
                        f"lane {lane!r} is full "
                        f"({self._queued_msgs[lane]}/{self.lane_capacity} msgs)")
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    self.rejects += 1
                    self.metrics.record_reject(lane)
                    raise LaneOverloaded(
                        f"lane {lane!r} still full after {timeout}s")
                self._cond.wait(remaining)
            self._queues[lane].append(req)
            self._queued_msgs[lane] += n
            self.metrics.record_submit(lane, n)
            self.metrics.set_queue_depth(lane, self._queued_msgs[lane])
            self._cond.notify_all()
        return req.future

    # -- exclusive lease ----------------------------------------------------

    @contextmanager
    def lease(self, what: str = "rebuild", devices: int | None = None):
        """Device lease for a turbo commit.

        **Exclusive** (no mesh, or ``devices`` covers the mesh): coalesced
        dispatching pauses until release (in-flight dispatch first
        drains); queued requests that age past ``lease_bypass_s`` are
        hashed on the CPU twin meanwhile, so a long-held lease cannot
        stall the live tip.

        **Sub-mesh** (mesh present, ``devices=k`` leaves >= 1 live
        device): the rebuild claims k devices (``rebuild_mesh()`` exposes
        them to the engine factory) while coalesced dispatching CONTINUES
        on the remaining live sub-mesh — shardings re-form over the
        survivors, nothing pauses and nothing bypasses to the CPU.
        """
        if devices is None and self.mesh is not None:
            devices = self.rebuild_devices
        if self.mesh is not None and devices:
            from ..parallel.mesh import MeshExhausted

            t0 = time.monotonic()
            sub = None
            with self._cond:
                while self._leased or self._submesh is not None:
                    self._cond.wait()
                try:
                    sub = self.mesh.lease_submesh(devices, what=what)
                except MeshExhausted:
                    pass  # not enough live devices: exclusive lease below
                else:
                    self._submesh = sub
                    self._lease_what = what
                    self.leases += 1
                    self.submesh_leases += 1
            if sub is not None:
                self.metrics.record_lease(time.monotonic() - t0)
                try:
                    yield self
                finally:
                    with self._cond:
                        sub.release()
                        self._submesh = None
                        self._lease_what = None
                        self._cond.notify_all()
                return
        t0 = time.monotonic()
        with self._cond:
            while self._leased or self._submesh is not None \
                    or self._dispatching:
                self._cond.wait()
            self._leased = True
            self._lease_what = what
            self.leases += 1
        self.metrics.record_lease(time.monotonic() - t0)
        try:
            yield self
        finally:
            with self._cond:
                self._leased = False
                self._lease_what = None
                self._cond.notify_all()

    def rebuild_mesh(self):
        """The jax Mesh currently leased to the rebuild (``None`` outside
        a sub-mesh lease) — what ``TurboCommitter``'s engine factory
        builds its ``FusedMeshEngine`` over."""
        sub = self._submesh
        return sub.mesh if sub is not None else None

    def lease_backend(self, inner=None, *, factory=None) -> LeasedTurboBackend:
        """Wrap an array-protocol turbo engine so one commit holds the
        lease from ``begin()`` to its terminal fetch. Pass ``factory``
        instead of a built engine to defer construction until AFTER the
        lease is acquired — the mesh path needs this so the engine forms
        its shardings over the sub-mesh the lease just carved out."""
        return LeasedTurboBackend(self, inner, factory=factory)

    def pipeline_lease(self, devices: int | None = None):
        """Double-buffer sub-mesh for the cross-block import pipeline
        (engine/block_pipeline.py): carve ``devices`` (default half the
        mesh) for the speculative block's key prehash while the
        in-commit block's lane dispatches re-form over the remainder —
        the PR 10 rebuild lease generalized to two concurrent users.

        Unlike :meth:`lease` this never pauses coalesced dispatching and
        never waits: the speculation either gets its own devices
        immediately or runs without (``None`` — no mesh, or not enough
        live devices to leave the commit side at least one)."""
        if self.mesh is None or self._mesh_hasher is None:
            return None
        from ..parallel.mesh import MeshExhausted

        k = int(devices) if devices else max(1, self.mesh.n_devices // 2)
        try:
            sub = self.mesh.lease_submesh(k, what="pipeline")
        except MeshExhausted:
            return None
        self.pipeline_leases += 1
        return PipelineLease(self, sub)

    # -- dispatcher ---------------------------------------------------------

    def _total_queued(self) -> int:
        return sum(self._queued_msgs.values())

    def _drain_locked(self, now: float) -> list[_Request]:
        """Pick the next coalesced batch (caller holds the lock): aged
        requests first (FIFO — the anti-starvation rule), then lanes in
        priority order, whole requests, up to ``max_batch`` messages
        (always at least one request)."""
        aged = [r for lane in LANES for r in self._queues[lane]
                if now - r.enqueued_at >= self.age_promote_s]
        aged.sort(key=lambda r: r.enqueued_at)
        aged_ids = {id(r) for r in aged}
        order = aged + [r for lane in LANES for r in self._queues[lane]
                        if id(r) not in aged_ids]
        batch: list[_Request] = []
        total = 0
        if order and order[0].window is not None:
            # multi-level windows dispatch ALONE (one fused engine run,
            # never concatenated with plain hash messages)
            batch = [order[0]]
        else:
            for r in order:
                if r.window is not None:
                    continue  # next round leads with it
                if batch and total + len(r.msgs) > self.max_batch:
                    break
                batch.append(r)
                total += len(r.msgs)
        taken = {id(r) for r in batch}
        for lane in LANES:
            kept = [r for r in self._queues[lane] if id(r) not in taken]
            if len(kept) != len(self._queues[lane]):
                removed = sum(_req_msgs(r) for r in self._queues[lane]
                              if id(r) in taken)
                self._queues[lane] = kept
                self._queued_msgs[lane] -= removed
                self.metrics.set_queue_depth(lane, self._queued_msgs[lane])
        if batch:
            self._cond.notify_all()  # wake submitters blocked on capacity
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopping and (self._total_queued() == 0):
                    self._cond.wait()
                if self._stopping and self._total_queued() == 0:
                    return
                # coalescing window: gather until the fused tier fills or
                # the oldest request's deadline expires
                while not self._stopping:
                    now = time.monotonic()
                    oldest = min(r.enqueued_at for lane in LANES
                                 for r in self._queues[lane])
                    if self._leased:
                        # lease held: the device is busy — requests that
                        # outwait the grace window go to the CPU twin
                        wait = (oldest + self.lease_bypass_s) - now
                        if wait <= 0:
                            batch = self._drain_locked(now)
                            bypass = True
                            break
                        self._cond.wait(wait)
                        continue
                    deadline = oldest + self.window_s
                    pending = sum(len(q) for q in self._queues.values())
                    # a LONE request dispatches immediately — the sync
                    # latency path pays no window; the window only gathers
                    # once a second request is pending (under load the
                    # previous dispatch's wall time is the gather period,
                    # continuous-batching style)
                    if (pending == 1
                            or self._total_queued() >= self.fill_target
                            or now >= deadline):
                        batch = self._drain_locked(now)
                        bypass = False
                        break
                    self._cond.wait(deadline - now)
                else:
                    # stopping: drain what's left (onto the twin if the
                    # device is still leased out)
                    batch = self._drain_locked(time.monotonic())
                    bypass = self._leased
                if not batch:
                    continue
                self._dispatching = not bypass
            try:
                self._dispatch(batch, bypass)
            finally:
                if not bypass:
                    with self._cond:
                        self._dispatching = False
                        self._cond.notify_all()

    def _mesh_dispatch(self, msgs: list[bytes], lane: str) -> list[bytes]:
        """One coalesced batch over the device mesh, with partial-mesh
        degradation. The partition-rule table decides sharded (``P(axis)``
        over the live mesh) vs unpartitioned (``P()`` on one device); a
        failed dispatch feeds the per-device breakers — attributed wedges
        shed their device immediately — and the SAME batch replays on the
        shrunken mesh, shardings re-formed over the survivors (hashing is
        stateless, so the replay is bit-identical). Raises only when no
        device is left: the caller's numpy-twin replay is the final rung.
        """
        from ..parallel.mesh import MeshExhausted

        board = self.breaker_board
        program = "keccak.scalar" if len(msgs) == 1 else "keccak.masked"
        attempts = 0
        while True:
            if board is not None:
                board.poll()  # cooled-down devices rejoin (trial by fire)
            spec, mesh = self.mesh.spec_for(lane, program, len(msgs))
            if mesh is None:
                raise MeshExhausted(
                    "no live mesh device (all breakers open or leased)")
            indices = tuple(self.mesh.devices.index(d)
                            for d in mesh.devices.flat)
            try:
                if self.device_injector is not None:
                    self.device_injector.on_mesh_dispatch(indices)
                out = self._mesh_hasher.hash_sharded(msgs, mesh)
            except BaseException as e:  # noqa: BLE001 — degraded below
                attempts += 1
                idx = getattr(e, "device_index", None)
                if board is None or attempts > self.mesh.n_devices + 1:
                    raise
                if idx is not None:
                    board.record_failure(idx, attributed=True)
                else:
                    # a collective failure with no device attribution:
                    # every participant is suspect (thresholded, so one
                    # flaky dispatch does not shed the whole mesh)
                    for i in indices:
                        board.record_failure(i)
                self.mesh_replays += 1
                self.mesh.metrics.record_replay()
                tracing.event("ops::hash_service", "mesh_replay",
                              msgs=len(msgs), shed=idx,
                              error=type(e).__name__,
                              live=self.mesh.healthy_count)
                continue  # replay the in-flight batch on the survivors
            if board is not None:
                board.record_success(indices)
            if len(spec) and len(indices) > 1:
                self.mesh_sharded += 1
                self.mesh.metrics.record_sharded()
            else:
                self.mesh_single += 1
                self.mesh.metrics.record_single()
            return out

    def _window_engine(self, lane: str, rows: int):
        """Whole-subtrie engine for ONE multi-level window dispatch. With
        a mesh, the partition-rule table routes ``fused.subtrie`` like
        any other program — sharded over the live mesh when every device
        gets a real row shard, a 1-device mesh otherwise; shard-by-
        subtrie holds because the packers keep each subtrie's rows
        contiguous and parent composition reads the replicated buffer."""
        from .fused_commit import SubtrieFusedEngine, SubtrieMeshEngine

        floors = dict(row_floor=max(64, 2 * self.min_tier),
                      hole_floor=max(64, 2 * self.min_tier))
        if self.mesh is not None:
            from ..parallel.mesh import MeshExhausted

            if self.breaker_board is not None:
                self.breaker_board.poll()
            _spec, mesh = self.mesh.spec_for(lane, "fused.subtrie", rows)
            if mesh is None:
                raise MeshExhausted(
                    "no live mesh device (all breakers open or leased)")
            return SubtrieMeshEngine(mesh, min_tier=self.min_tier,
                                     k=self.subtrie_levels,
                                     warmup=self.warmup, **floors)
        return SubtrieFusedEngine(min_tier=self.min_tier,
                                  k=self.subtrie_levels,
                                  warmup=self.warmup, **floors)

    @staticmethod
    def _run_window_on(engine, req: _WindowRequest):
        engine.begin(req.max_slots)
        for lv in req.window:
            if lv.get("kind") == "branch":
                engine.dispatch_branch(lv["masks"], lv["slots"],
                                       lv["children"])
            else:
                engine.dispatch_packed(lv["flat"], lv["row_off"],
                                       lv["row_len"], lv["slots"],
                                       lv.get("holes"), lv["b_tier"])
        if req.fetch is not None:
            import numpy as _np

            return engine.fetch_slots(_np.asarray(req.fetch,
                                                  dtype=_np.int64))
        return engine.finish()

    def _dispatch_window(self, req: _WindowRequest, bypass: bool) -> None:
        """Run one multi-level window as a whole-subtrie fused dispatch.
        Bypass (exclusive lease held) and any device failure land on the
        numpy twin — level replay is exact, the future completes once."""
        t0 = time.monotonic()
        self.metrics.record_wait(req.lane, t0 - req.enqueued_at)
        replayed = False
        replay_err = None
        digests = None
        if not bypass:
            try:
                if self.injector is not None:
                    self.injector.on_dispatch()
                digests = self._run_window_on(
                    self._window_engine(req.lane, req.rows), req)
            except BaseException as e:  # noqa: BLE001 — replayed below
                replayed = True
                replay_err = type(e).__name__
                self.replays += 1
                self.metrics.record_replay()
        else:
            self.lease_bypasses += 1
            self.metrics.record_lease_bypass()
        if digests is None:
            from ..trie.turbo import _NumpyBackend

            try:
                digests = self._run_window_on(_NumpyBackend(), req)
            except BaseException as e:  # pragma: no cover - twin failure
                req.future._complete(error=e)
                raise
        service_s = time.monotonic() - t0
        req.future._complete(result=digests)
        if replayed:
            tracing.event("ops::hash_service", "window_replay",
                          levels=len(req.window), rows=req.rows,
                          error=replay_err)
        self.dispatches += 1
        self.window_dispatches += 1
        self.coalesced_requests += 1
        self.hashed_msgs += req.rows
        now_wall = time.time()
        if req.ctx is not None:
            tracing.record_span(
                "ops::hash_service", "hashsvc.window",
                req.wall_at, now_wall - req.wall_at, ctx=req.ctx,
                fields={"lane": req.lane, "levels": len(req.window),
                        "rows": req.rows,
                        "service_ms": round(service_s * 1e3, 3),
                        "replayed": replayed, "bypass": bypass})
        tracing.record_span(
            "ops::hash_service",
            "hashsvc.replay" if replayed
            else ("hashsvc.bypass" if bypass else "hashsvc.dispatch"),
            now_wall - service_s, service_s,
            fields={"requests": 1, "msgs": req.rows,
                    "levels": len(req.window)})
        self.metrics.record_dispatch(
            requests=1, msgs=req.rows, occupancy=1.0,
            service_s=service_s, replayed=replayed)

    def _dispatch(self, batch: list[_Request], bypass: bool) -> None:
        """ONE backend call for the whole coalesced batch; scatter digests
        back through the futures. Any backend failure (watchdog trip that
        escaped the supervisor, injected service wedge, ...) replays the
        ENTIRE batch on the numpy twin — hashing is stateless, so replay
        is exact and every future completes exactly once."""
        if len(batch) == 1 and batch[0].window is not None:
            self._dispatch_window(batch[0], bypass)
            return
        msgs: list[bytes] = []
        for r in batch:
            msgs.extend(r.msgs)
        t0 = time.monotonic()
        for r in batch:
            self.metrics.record_wait(r.lane, t0 - r.enqueued_at)
        replayed = False
        replay_err = None
        try:
            if bypass:
                self.lease_bypasses += 1
                self.metrics.record_lease_bypass()
                digests = self._cpu(msgs)
            else:
                if self.injector is not None:
                    self.injector.on_dispatch()
                if self.mesh is not None:
                    digests = self._mesh_dispatch(msgs, batch[0].lane)
                else:
                    digests = self._backend(msgs)
        except BaseException as first_error:  # noqa: BLE001 — replayed below
            replayed = True
            replay_err = type(first_error).__name__
            self.replays += 1
            self.metrics.record_replay()
            try:
                digests = self._cpu(msgs)
            except BaseException as e:  # pragma: no cover - twin failure
                for r in batch:
                    r.future._complete(error=e)
                raise first_error
        service_s = time.monotonic() - t0
        if replayed:
            tracing.event("ops::hash_service", "replay",
                          requests=len(batch), msgs=len(msgs),
                          error=replay_err)
        off = 0
        now_wall = time.time()
        for r in batch:
            r.future._complete(result=digests[off:off + len(r.msgs)])
            off += len(r.msgs)
            # per-request attribution under the SUBMITTER's trace: queue
            # wait vs coalesce vs device dispatch vs replay, the split
            # the block wall-budget line prints
            if r.ctx is not None:
                wait_s = t0 - r.enqueued_at
                tracing.record_span(
                    "ops::hash_service", "hashsvc.request",
                    r.wall_at, now_wall - r.wall_at, ctx=r.ctx,
                    fields={"lane": r.lane, "msgs": len(r.msgs),
                            "wait_ms": round(wait_s * 1e3, 3),
                            "service_ms": round(service_s * 1e3, 3),
                            "coalesced_with": len(batch),
                            "replayed": replayed, "bypass": bypass})
        self.dispatches += 1
        self.coalesced_requests += len(batch)
        self.hashed_msgs += len(msgs)
        occupancy = len(msgs) / _next_tier(len(msgs), self.min_tier)
        tracing.record_span(
            "ops::hash_service",
            "hashsvc.replay" if replayed
            else ("hashsvc.bypass" if bypass else "hashsvc.dispatch"),
            now_wall - service_s, service_s,
            fields={"requests": len(batch), "msgs": len(msgs),
                    "occupancy": round(occupancy, 4)})
        self.metrics.record_dispatch(
            requests=len(batch), msgs=len(msgs), occupancy=occupancy,
            service_s=service_s, replayed=replayed)

    # -- lifecycle / observability ------------------------------------------

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the dispatcher. ``drain=True`` completes everything still
        queued first; ``drain=False`` fails pending futures with
        :class:`ServiceStopped`."""
        with self._cond:
            self._stopping = True
            if not drain:
                for lane in LANES:
                    for r in self._queues[lane]:
                        r.future._complete(
                            error=ServiceStopped("hash service stopped"))
                    self._queues[lane].clear()
                    self._queued_msgs[lane] = 0
                    self.metrics.set_queue_depth(lane, 0)
            self._cond.notify_all()
        self._thread.join(timeout)

    def coalesce_factor(self) -> float:
        """Requests per coalesced dispatch (lifetime average) — the
        headline number: >1 means small client batches actually fused."""
        return (self.coalesced_requests / self.dispatches
                if self.dispatches else 0.0)

    def snapshot(self) -> dict:
        """State for the events dashboard line and bench/test triage."""
        with self._cond:
            queued = dict(self._queued_msgs)
            leased = self._lease_what
            sub = self._submesh
        out = {
            "queued": queued,
            "queued_total": sum(queued.values()),
            "dispatches": self.dispatches,
            "window_dispatches": self.window_dispatches,
            "coalesce_factor": round(self.coalesce_factor(), 2),
            "hashed_msgs": self.hashed_msgs,
            "replays": self.replays,
            "rejects": self.rejects,
            "leases": self.leases,
            "lease_bypasses": self.lease_bypasses,
            "leased_by": leased,
            "fault_injection": (self.injector.active()
                                if self.injector is not None else False),
        }
        if self.mesh is not None:
            out["mesh"] = {
                **self.mesh.snapshot(),
                "sharded_dispatches": self.mesh_sharded,
                "single_dispatches": self.mesh_single,
                "mesh_replays": self.mesh_replays,
                "submesh_leases": self.submesh_leases,
                "submesh_held": (list(sub.indices)
                                 if sub is not None else None),
                "pipeline_leases": self.pipeline_leases,
            }
            if self.device_injector is not None:
                out["fault_injection"] = (out["fault_injection"]
                                          or self.device_injector.active())
        return out
