"""Device warm-up manager: supervised AOT compile lifecycle, persistent
compilation cache, and degraded-mode serving.

Every device bench round before this module reported ``value: 0`` — warm-up
XLA compiles wedged the axon tunnel and the node had no bounded, recoverable
path through kernel compilation (BENCH_r01–r05, ROADMAP item 1). Compilation
is now a managed lifecycle instead of an ambush on the first live dispatch:

- **Shape menu** (:func:`default_menu`): the bucketed
  ``(program, block_tier, batch_tier)`` grid already implicit in
  ``keccak_jax.py`` / ``fused_commit.py``, declared explicitly. At node
  start the manager AOT-compiles each menu shape ONE AT A TIME, each
  compile under a per-shape watchdog budget with retry + exponential
  backoff (``RETH_TPU_WARMUP_BUDGET`` / ``_ATTEMPTS`` / ``_BACKOFF``), and
  sequenced behind the supervisor's health probe — a wedged compile trips
  the circuit breaker (``ops/supervisor.py``) instead of freezing startup.
  ``RETH_TPU_FAULT_COMPILE_WEDGE`` drills the wedge path without hardware.
- **Persistent compilation cache** (:class:`CompileCache`): JAX's
  ``jax_compilation_cache_dir`` keyed under the datadir and VERSIONED by a
  digest of the kernel sources (stale caches from older kernels land in a
  different directory). Corrupt entries quarantine the directory and
  rebuild rather than crashing. Because this jax build has deadlocked the
  first jit with the cache enabled over the axon tunnel (measured round 2),
  the cache is only enabled in-process after a SUBPROCESS probe
  (:func:`supervisor.probe_device` with ``cache_dir=``) proves the cache
  loads — a wedged cache wedges the probe child, never the node.
- **Degraded-mode serving**: while warm-up is in progress the hash service
  and the committers run on the CPU twin; individual shapes are promoted
  to the device as each finishes compiling (per-shape
  cold/compiling/warm/failed states, consulted by
  ``KeccakDevice.route_bucket`` per dispatch and by ``SupervisedBackend``
  per fused commit). An un-warmed shape encountered mid-commit routes that
  bucket to the CPU — never a blocking fresh compile inside a commit.
- **Observability**: ``warmup_*`` metrics (``metrics.WarmupMetrics``), a
  ``warmup[...]`` events-dashboard fragment, per-shape ``ops::warmup``
  trace events, and the bench's ``warmup_state`` field.

Wiring: ``--warmup off|background|block`` + ``--compile-cache-dir`` on the
CLI (``[node] warmup`` in reth.toml); :func:`build_warmup` is the shared
constructor the CLI and ``node/node.py`` use.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from dataclasses import dataclass
from pathlib import Path

from .. import tracing

COLD = "cold"
COMPILING = "compiling"
WARM = "warm"
FAILED = "failed"

# Declared ceilings shared with the dispatch front-ends: KeccakDevice chunks
# batches above the batch ceiling and routes messages above the block
# ceiling to the CPU twin, so no request can mint an off-menu program.
DEFAULT_MIN_TIER = 1024
DEFAULT_BLOCK_TIER = 4
DEFAULT_MAX_BATCH_TIER = 16384
DEFAULT_MAX_BLOCK_TIER = 32
# whole-subtrie k-level programs: the row tier the engines route against
# (mirrors ops/fused_commit.MegaFusedEngine._ROW_FLOOR — kept literal here
# so importing the menu never pulls jax in)
DEFAULT_SUBTRIE_TIER = 2048
# default k ladder declared for the k-level programs (--subtrie-levels)
DEFAULT_SUBTRIE_KS: tuple[int, ...] = (8,)


@dataclass(frozen=True)
class MenuShape:
    """One declared device program shape.

    ``program``: "keccak.masked" | "keccak.exact" | "fused.plain" |
    "fused.splice" — the same kind strings the dispatch sites report to the
    compile tracker, so menu states and dispatch attribution line up.
    ``mesh_size``: 1 = single-device; >1 = the SPMD variant sharded over
    that many devices (a sharded dispatch compiles a DIFFERENT executable
    than its single-device twin, so it needs its own menu slot — otherwise
    the first mesh-sharded dispatch ambushes a live commit with a fresh
    compile).
    """

    program: str
    block_tier: int
    batch_tier: int
    mesh_size: int = 1

    def key(self) -> tuple:
        return (self.program, self.block_tier, self.batch_tier,
                self.mesh_size)

    def __str__(self) -> str:  # events/log form
        base = f"{self.program}:{self.block_tier}x{self.batch_tier}"
        return base if self.mesh_size == 1 else f"{base}@m{self.mesh_size}"


def default_menu(min_tier: int = DEFAULT_MIN_TIER,
                 block_tier: int = DEFAULT_BLOCK_TIER,
                 max_batch_tier: int = DEFAULT_MAX_BATCH_TIER,
                 max_block_tier: int = DEFAULT_MAX_BLOCK_TIER,
                 include_fused: bool = True,
                 mesh_sizes: tuple[int, ...] = (),
                 subtrie_ks: tuple[int, ...] = DEFAULT_SUBTRIE_KS) -> list[MenuShape]:
    """The grid the runtime actually dispatches (see ``TrieCommitter``:
    ``KeccakDevice(min_tier=1024, block_tier=4)``): one masked program per
    pow2 batch tier for trie-node-sized messages (<= ``block_tier`` rate
    blocks), plus the pow2 block-tier ladder at the base batch tier for
    large messages (contract code), clamped at the declared ceilings —
    everything beyond the menu is served by the CPU twin, never a fresh
    mid-commit compile. ``include_fused`` adds the fused level-commit
    programs at the base tier (the live-tip sparse/turbo commit shapes).
    ``mesh_sizes`` adds the SPMD variants for each mesh size: the batch
    ladder rounded up to device-count multiples (the tiers the mesh
    front-ends actually mint — ``parallel/mesh.py mesh_tier`` /
    ``FusedMeshEngine``'s rounded floor), so a mesh-sharded dispatch
    never triggers a fresh compile mid-commit either."""
    shapes: list[MenuShape] = []
    t = min_tier
    while t <= max_batch_tier:
        shapes.append(MenuShape("keccak.masked", block_tier, t))
        t *= 2
    bt = 2 * block_tier
    while bt <= max_block_tier:
        shapes.append(MenuShape("keccak.masked", bt, min_tier))
        bt *= 2
    if include_fused:
        shapes.append(MenuShape("fused.plain", block_tier, min_tier))
        shapes.append(MenuShape("fused.splice", block_tier, min_tier))
        # whole-subtrie k-level programs (fused.subtrie): block_tier slot
        # carries k — the levels-per-dispatch the engine was built with;
        # an un-warm (k, tier, mesh) shape routes the commit to the
        # per-level path instead of compiling mid-commit
        for k in subtrie_ks:
            if k > 1:
                shapes.append(
                    MenuShape("fused.subtrie", k, DEFAULT_SUBTRIE_TIER))
    for m in mesh_sizes:
        if m <= 1:
            continue
        floor = -(-min_tier // m) * m  # device-count-multiple rounding
        t = floor
        while t <= max_batch_tier:
            shapes.append(MenuShape("keccak.masked", block_tier, t, m))
            t *= 2
        if include_fused:
            shapes.append(MenuShape("fused.plain", block_tier, floor, m))
            shapes.append(MenuShape("fused.splice", block_tier, floor, m))
            for k in subtrie_ks:
                if k > 1:
                    # device-count-multiple rounding, like every mesh tier
                    sub_t = -(-DEFAULT_SUBTRIE_TIER // m) * m
                    shapes.append(MenuShape("fused.subtrie", k, sub_t, m))
    return shapes


def _mesh_for_shape(mesh_size: int):
    """(Mesh, batch sharding, replicated sharding) for an SPMD menu shape.
    jax interns ``Mesh`` per (devices, axes), so the warm-up's sharded
    dummy dispatch hits the SAME jit cache entries the runtime's
    ``MeshKeccak`` / ``FusedMeshEngine`` use."""
    import numpy as np

    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < mesh_size:
        raise ValueError(
            f"menu shape needs {mesh_size} devices, found {len(devices)}")
    mesh = Mesh(np.array(devices[:mesh_size]), ("data",))
    return mesh, NamedSharding(mesh, P("data")), NamedSharding(mesh, P())


def _build_shape(shape: MenuShape) -> None:
    """Compile ``shape``'s program by dispatching a dummy batch of exactly
    that shape through the SAME jitted callables the runtime uses — the
    in-process jit cache (and, when enabled, the persistent cache) is keyed
    by function + shapes + shardings, so the runtime's first real dispatch
    of the shape is steady-state. The result sync (`np.asarray`) makes the
    wall honest. ``mesh_size > 1`` dispatches the dummy batch SHARDED over
    the first ``mesh_size`` devices — the mesh variant is a different
    executable than its single-device twin."""
    import numpy as np

    import jax

    put_batch = None
    sharding_key = None
    if shape.mesh_size > 1:
        mesh, batch_sh, rep_sh = _mesh_for_shape(shape.mesh_size)
        if shape.batch_tier % shape.mesh_size:
            raise ValueError(
                f"mesh menu tier {shape.batch_tier} not divisible by "
                f"mesh size {shape.mesh_size}")
        put_batch = lambda a: jax.device_put(a, batch_sh)  # noqa: E731
        put_rep = lambda a: jax.device_put(a, rep_sh)      # noqa: E731
        sharding_key = mesh
    else:
        import jax.numpy as jnp

        put_batch = put_rep = jnp.asarray
    if shape.program in ("keccak.masked", "keccak.exact"):
        from .keccak_jax import keccak256_jax_words, keccak256_jax_words_masked

        words = np.zeros((shape.batch_tier, shape.block_tier * 34),
                         dtype=np.uint32)
        if shape.program == "keccak.exact":
            np.asarray(keccak256_jax_words(put_batch(words),
                                           shape.block_tier))
        else:
            counts = np.ones((shape.batch_tier,), dtype=np.int32)
            np.asarray(keccak256_jax_words_masked(
                put_batch(words), shape.block_tier,
                counts=put_batch(counts)))
        return
    if shape.program in ("fused.plain", "fused.splice"):
        from ..primitives.keccak import RATE
        from .fused_commit import _jitted

        n, b = shape.batch_tier, shape.block_tier
        templates = put_batch(np.zeros((n, b * RATE), dtype=np.uint8))
        counts = put_batch(np.ones((n,), dtype=np.int32))
        slots = put_batch(np.zeros((n,), dtype=np.int32))
        buf = put_rep(np.zeros((n, 32), dtype=np.uint8))
        if shape.program == "fused.plain":
            fn = _jitted("plain", b, sharding_key)
            np.asarray(fn(templates, counts, slots, buf))
        else:
            # hole tier mirrors FusedLevelEngine: _HOLE_FACTOR * min batch
            h = 4 * n
            zeros_h = put_batch(np.zeros((h,), dtype=np.int32))
            fn = _jitted("splice", b, sharding_key)
            np.asarray(fn(templates, counts, zeros_h, zeros_h, zeros_h,
                          slots, buf))
        return
    if shape.program == "fused.subtrie":
        # k-level program: stage one packed + one branch level through the
        # REAL engine (so chunk planning mints the exact (b_tier=4,
        # row-floor, hole-floor) key the runtime's first chunk hits) and
        # execute — the loop body compiles BOTH step kinds via its cond
        from .fused_commit import SubtrieFusedEngine, SubtrieMeshEngine

        k = shape.block_tier
        if shape.mesh_size > 1:
            mesh, _batch_sh, _rep_sh = _mesh_for_shape(shape.mesh_size)
            eng = SubtrieMeshEngine(mesh, min_tier=64, k=k,
                                    row_floor=shape.batch_tier,
                                    hole_floor=shape.batch_tier)
        else:
            eng = SubtrieFusedEngine(min_tier=64, k=k,
                                     row_floor=shape.batch_tier,
                                     hole_floor=shape.batch_tier)
        eng.begin(4)
        s1, s2 = eng.alloc_slot(), eng.alloc_slot()
        row = b"\x01" * 40
        eng.dispatch_packed(np.frombuffer(row, dtype=np.uint8),
                            np.zeros((1,), dtype=np.uint32),
                            np.array([len(row)], dtype=np.uint32),
                            np.array([s1], dtype=np.int32), None, 4)
        eng.dispatch_branch(np.array([0x0001], dtype=np.uint16),
                            np.array([s2], dtype=np.int32),
                            np.array([[0], [0], [s1]], dtype=np.int32))
        np.asarray(eng.finish())
        return
    raise ValueError(f"unknown menu program {shape.program!r}")


def kernel_source_digest(paths: list[str | Path] | None = None) -> str:
    """Digest versioning the persistent cache directory: the kernel sources
    whose lowering feeds the cache, plus the jax version — a kernel edit or
    a jax upgrade lands in a fresh cache dir instead of loading stale
    executables."""
    if paths is None:
        here = Path(__file__).parent
        paths = [here / "keccak_jax.py", here / "fused_commit.py",
                 here / "keccak_pallas.py"]
    h = hashlib.sha256()
    for p in paths:
        try:
            h.update(Path(p).read_bytes())
        except OSError:
            h.update(str(p).encode())
    try:
        import jax

        h.update(jax.__version__.encode())
    except Exception:  # noqa: BLE001 — digest still deterministic sans jax
        pass
    return h.hexdigest()[:16]


class CompileCache:
    """Persistent on-disk XLA compilation cache under the datadir.

    The directory is ``<base>/xla-<source digest>`` so restarts and bench
    reruns against the same kernel sources pay compile cost once, while a
    kernel change never loads a stale executable. ``validate()`` detects
    corrupt entries (zero-length / unreadable files) and QUARANTINES the
    whole directory (renamed aside, fresh dir created) rather than letting
    a half-written entry crash or wedge the first jit. ``probe()`` verifies
    in a SUBPROCESS that jax can actually run with this cache dir — the
    deadlock this build has shown with the cache enabled stays in the
    child. Only then does ``enable()`` point the in-process jax config at
    the directory."""

    def __init__(self, base_dir: str | Path, sources=None, *,
                 probe_budget: float | None = None, mesh_size: int = 1):
        self.base = Path(base_dir)
        self.digest = kernel_source_digest(sources)
        self.mesh_size = mesh_size
        # the cache key gains the mesh size: SPMD executables for an
        # n-device topology must never be loaded into a differently-sized
        # mesh (XLA would reject them at best, wedge the tunnel at worst)
        suffix = f"-m{mesh_size}" if mesh_size != 1 else ""
        self.dir = self.base / f"xla-{self.digest}{suffix}"
        self.probe_budget = probe_budget
        self.enabled = False
        self.quarantined = 0
        self.last_report: dict | None = None

    def entry_count(self) -> int:
        try:
            return sum(1 for p in self.dir.rglob("*") if p.is_file())
        except OSError:
            return 0

    def validate(self) -> dict:
        """Scan for corrupt entries; quarantine + rebuild on any. Returns
        ``{"entries", "corrupt", "quarantined"}`` (post-quarantine entry
        count is 0 — the next run repopulates the fresh directory)."""
        corrupt: list[str] = []
        entries = 0
        if self.dir.is_dir():
            for p in sorted(self.dir.rglob("*")):
                if not p.is_file():
                    continue
                entries += 1
                try:
                    if p.stat().st_size == 0:
                        corrupt.append(p.name)
                        continue
                    with open(p, "rb") as f:
                        f.read(16)
                except OSError:
                    corrupt.append(p.name)
        if corrupt:
            k = self.quarantined
            while True:
                dest = self.dir.with_name(f"{self.dir.name}.quarantine-{k}")
                if not dest.exists():
                    break
                k += 1
            try:
                self.dir.rename(dest)
            except OSError:  # cross-device or racing writer: drop in place
                import shutil

                shutil.rmtree(self.dir, ignore_errors=True)
                dest = None
            self.quarantined += 1
            entries = 0
            tracing.event("ops::warmup", "cache_quarantine",
                          dir=str(self.dir), corrupt=len(corrupt),
                          moved_to=str(dest) if dest else "removed")
        self.dir.mkdir(parents=True, exist_ok=True)
        self.last_report = {"entries": entries, "corrupt": len(corrupt),
                            "quarantined": bool(corrupt)}
        return self.last_report

    def probe(self, injector=None) -> bool:
        """Subprocess check that a jit dispatch completes WITH this cache
        dir configured (the opt-in cache-validation probe mode)."""
        from .supervisor import probe_device

        return probe_device(self.probe_budget, cache_dir=str(self.dir),
                            injector=injector).ok

    def enable(self) -> bool:
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", str(self.dir))
            # persist every program: the tunnel's compile cost is exactly
            # what restarts must not pay twice, size thresholds be damned
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            self.enabled = True
        except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
            self.enabled = False
        return self.enabled

    def disable(self) -> None:
        if not self.enabled:
            return
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:  # noqa: BLE001
            pass
        self.enabled = False

    def summary(self) -> dict:
        rep = self.last_report or {}
        state = "off"
        if self.enabled:
            state = "warm" if rep.get("entries", 0) else "cold"
        return {"mode": state, "dir": str(self.dir),
                "entries": rep.get("entries", 0),
                "quarantined": self.quarantined}


class WarmupManager:
    """Owns the compile lifecycle for the device keccak/fused kernels.

    ``run()`` (or ``start()`` for a background thread) walks the menu one
    shape at a time: each compile runs in a worker thread under ``budget``
    seconds; a timeout abandons the wedged thread, counts a breaker failure
    on the attached supervisor, and retries with exponential backoff.
    Shapes settle in WARM or FAILED; the routing queries
    (:meth:`route_bucket`, :meth:`device_ready`) implement degraded-mode
    serving until everything is warm. ``on_device_recovered()`` (called by
    the supervisor's half-open probe success) re-queues FAILED shapes, so
    shapes promote once a fault clears."""

    def __init__(self, menu: list[MenuShape] | None = None, *,
                 supervisor=None, cache: CompileCache | None = None,
                 budget: float | None = None, attempts: int | None = None,
                 backoff: float | None = None, builder=None, injector=None,
                 verify_cache: bool = True, enable_cache: bool = True,
                 registry=None):
        from ..metrics import WarmupMetrics

        self.menu = list(menu if menu is not None else default_menu())
        self.sup = supervisor
        self.cache = cache
        if budget is None:
            budget = float(os.environ.get("RETH_TPU_WARMUP_BUDGET", "240"))
        self.budget = budget
        if attempts is None:
            attempts = int(os.environ.get("RETH_TPU_WARMUP_ATTEMPTS", "3"))
        self.attempts = max(1, attempts)
        if backoff is None:
            backoff = float(os.environ.get("RETH_TPU_WARMUP_BACKOFF", "2"))
        self.backoff = backoff
        self.verify_cache = verify_cache
        # enable_cache=False: validate/quarantine only, never touch the
        # process-global jax config (unit-test scope)
        self.enable_cache = enable_cache
        self._builder = builder or _build_shape
        if injector is None and supervisor is not None:
            injector = supervisor.injector
        if injector is None:
            from .supervisor import FaultInjector

            injector = FaultInjector.from_env()
        self.injector = injector
        self.metrics = WarmupMetrics(registry)
        self._lock = threading.Lock()
        self.states: dict[tuple, str] = {s.key(): COLD for s in self.menu}
        self.compile_walls: dict[tuple, float] = {}
        self.retries = 0
        self.wedges = 0
        self.cpu_routed = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self._current: MenuShape | None = None
        self._active = False      # gating applies from start() onward
        self._retrying = False
        self._done = threading.Event()
        self._thread: threading.Thread | None = None
        if supervisor is not None:
            supervisor.warmup = self
        self._publish()

    # -- routing queries (hot path) -----------------------------------------

    def device_ready(self) -> bool:
        """May a whole fused commit claim the device? True before warm-up
        ever starts (no gating), and once every menu shape is WARM. While
        warming — or degraded with FAILED shapes — commits stay on the CPU
        twin (a fused commit's digest buffer can't switch backends at a
        shape boundary)."""
        if not self._active:
            return True
        return self._done.is_set() and all(
            s == WARM for s in self.states.values())

    def route_bucket(self, program: str, block_tier: int,
                     batch_tier: int, mesh_size: int = 1) -> bool:
        """Per-dispatch routing: True = device, False = CPU twin. A WARM
        shape always gets the device; during warm-up (or degraded) an
        un-warm or off-menu shape routes to the CPU — never a blocking
        fresh compile inside a commit. ``mesh_size`` selects the SPMD
        variant's menu slot."""
        if not self._active:
            return True
        if self.states.get((program, block_tier, batch_tier,
                            mesh_size)) == WARM:
            return True
        if self.device_ready():
            return True  # fully warm: off-menu stragglers ride the watchdog
        with self._lock:
            self.cpu_routed += 1
        self.metrics.record_cpu_routed()
        return False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Run warm-up on a background thread (the node serves degraded on
        the CPU twin meanwhile; shapes promote as they finish)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(target=self.run, daemon=True,
                                            name="device-warmup")
        self._thread.start()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def run(self) -> dict:
        """Blocking warm-up pass: cache validation/enable, then the menu
        one shape at a time. Returns the final snapshot."""
        self._active = True
        self._done.clear()
        t0 = time.monotonic()
        self._publish()
        self._setup_cache()
        for shape in self.menu:
            if self.states.get(shape.key()) != WARM:
                self._compile_shape(shape)
        self._done.set()
        self._publish()
        snap = self.snapshot()
        tracing.event("ops::warmup", "warmup_done", state=snap["state"],
                      warm=snap["warm"], failed=snap["failed"],
                      total=snap["total"],
                      wall_s=round(time.monotonic() - t0, 3),
                      compile_wall_s=snap["compile_wall_s"],
                      cache=snap["cache"]["mode"])
        return snap

    def retry_failed(self) -> int:
        """Re-run FAILED shapes (promotion path after a fault clears);
        returns how many became WARM. Reentrancy-guarded: the supervisor's
        half-open probe success fires mid-retry too."""
        with self._lock:
            if self._retrying:
                return 0
            self._retrying = True
        try:
            failed = [s for s in self.menu
                      if self.states.get(s.key()) == FAILED]
            if not failed:
                return 0
            self._done.clear()
            self._publish()
            promoted = 0
            for shape in failed:
                if self._compile_shape(shape):
                    promoted += 1
            self._done.set()
            self._publish()
            return promoted
        finally:
            with self._lock:
                self._retrying = False

    def on_device_recovered(self) -> None:
        """Supervisor hook: a half-open probe just closed the breaker —
        promote FAILED shapes in the background."""
        if not self._active or self.device_ready():
            return
        if not any(s == FAILED for s in self.states.values()):
            return
        threading.Thread(target=self.retry_failed, daemon=True,
                         name="device-warmup-retry").start()

    # -- internals -----------------------------------------------------------

    def _setup_cache(self) -> None:
        if self.cache is None:
            return
        report = self.cache.validate()
        self.metrics.set_cache_entries(report["entries"])
        if report["quarantined"]:
            self.metrics.record_quarantine()
        if not self.enable_cache:
            return
        if self.verify_cache and not self.cache.probe(injector=self.injector):
            # a cache dir this jax build can't even probe through must not
            # be wired into the live process — warm-up proceeds uncached
            tracing.event("ops::warmup", "cache_disabled",
                          dir=str(self.cache.dir),
                          reason="subprocess cache probe failed")
            return
        self.cache.enable()
        tracing.event("ops::warmup", "cache_enabled",
                      dir=str(self.cache.dir), entries=report["entries"],
                      state="warm" if report["entries"] else "cold")

    def _set_state(self, shape: MenuShape, state: str) -> None:
        with self._lock:
            self.states[shape.key()] = state
            self._current = shape if state == COMPILING else None
        self._publish()

    def _compile_shape(self, shape: MenuShape) -> bool:
        for attempt in range(1, self.attempts + 1):
            if self.sup is not None and not self.sup.allows_device():
                # breaker open: serving stays on the CPU twin; the shape
                # parks FAILED until the supervisor's half-open probe
                # succeeds and on_device_recovered() re-queues it
                self._set_state(shape, FAILED)
                tracing.event("ops::warmup", "shape_deferred",
                              shape=str(shape), reason="breaker open")
                return False
            self._set_state(shape, COMPILING)
            before = (self.cache.entry_count()
                      if self.cache is not None and self.cache.enabled
                      else None)
            t0 = time.perf_counter()
            ok, err = self._guarded_build(shape)
            wall = time.perf_counter() - t0
            if ok:
                hit = None
                if before is not None:
                    hit = self.cache.entry_count() == before
                    with self._lock:
                        if hit:
                            self.cache_hits += 1
                        else:
                            self.cache_misses += 1
                with self._lock:
                    self.compile_walls[shape.key()] = round(wall, 6)
                self._set_state(shape, WARM)
                self.metrics.record_compile(wall, cache_hit=hit)
                if self.sup is not None:
                    self.sup.breaker.record_success()
                tracing.event("ops::warmup", "shape_warm", shape=str(shape),
                              wall_s=round(wall, 4), attempt=attempt,
                              cache_hit=hit)
                return True
            with self._lock:
                self.wedges += 1
            self.metrics.record_wedge()
            if self.sup is not None:
                # a wedged compile is a device failure like any other: it
                # feeds the breaker so repeated wedges trip it and the node
                # keeps serving degraded instead of freezing startup
                if self.sup.breaker.record_failure():
                    self.sup.metrics.record_trip()
                self.sup._publish()
            tracing.event("ops::warmup", "shape_wedged", shape=str(shape),
                          attempt=attempt, budget_s=self.budget,
                          error=str(err)[:200])
            if attempt < self.attempts:
                with self._lock:
                    self.retries += 1
                self.metrics.record_retry()
                time.sleep(self.backoff * (2 ** (attempt - 1)))
        self._set_state(shape, FAILED)
        return False

    def _guarded_build(self, shape: MenuShape) -> tuple[bool, object]:
        """One compile attempt in a worker thread under the watchdog budget
        (a wedged XLA compile cannot be cancelled — the thread is abandoned
        and the shape retried/failed, exactly like a supervised dispatch)."""
        box: list = [False, None]
        injector = self.injector

        def _call():
            try:
                if injector is not None:
                    injector.on_compile(self.budget)
                self._builder(shape)
                box[0] = True
            except BaseException as e:  # noqa: BLE001 — reported below
                box[1] = e

        t = threading.Thread(target=_call, daemon=True,
                             name=f"warmup-{shape.program}")
        t.start()
        t.join(self.budget)
        if t.is_alive():
            tracing.fault_event("warmup_compile_timeout",
                                target="ops::warmup", shape=str(shape),
                                budget_s=self.budget)
            return False, f"compile exceeded {self.budget}s watchdog budget"
        if not box[0]:
            return False, box[1]
        return True, None

    # -- observability -------------------------------------------------------

    def _counts(self) -> tuple[int, int, int]:
        vals = list(self.states.values())
        return (sum(1 for s in vals if s == WARM),
                sum(1 for s in vals if s == FAILED), len(vals))

    def overall_state(self) -> str:
        if not self._active:
            return "off"
        warm, failed, total = self._counts()
        if not self._done.is_set():
            return "warming"
        if warm == total:
            return "warm"
        return "degraded"

    def snapshot(self) -> dict:
        with self._lock:
            states = dict(self.states)
            walls = dict(self.compile_walls)
            current = self._current
        warm = sum(1 for s in states.values() if s == WARM)
        failed = sum(1 for s in states.values() if s == FAILED)
        return {
            "state": self.overall_state(),
            "warm": warm,
            "failed": failed,
            "total": len(states),
            "compiling": str(current) if current is not None else None,
            "compile_wall_s": round(sum(walls.values()), 4),
            "retries": self.retries,
            "wedges": self.wedges,
            "cpu_routed": self.cpu_routed,
            "cache": (self.cache.summary() if self.cache is not None
                      else {"mode": "off", "entries": 0, "quarantined": 0}),
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "shapes": {(f"{k[0]}:{k[1]}x{k[2]}"
                        + (f"@m{k[3]}" if k[3] != 1 else "")): v
                       for k, v in states.items()},
        }

    def _publish(self) -> None:
        warm, failed, total = self._counts()
        self.metrics.set_progress(total=total, warm=warm, failed=failed)
        self.metrics.set_state(self.overall_state())


def build_warmup(supervisor=None, cache_dir: str | Path | None = None,
                 menu: list[MenuShape] | None = None, registry=None,
                 mesh_size: int = 1, **kw) -> WarmupManager:
    """Shared constructor for the CLI and ``node/node.py``: a manager over
    the default menu, with the persistent cache keyed under ``cache_dir``
    when one is given. ``mesh_size > 1`` (the ``--mesh`` wiring) adds the
    SPMD menu variants and keys the cache by the mesh size."""
    if menu is None and mesh_size > 1:
        menu = default_menu(mesh_sizes=(mesh_size,))
    cache = (CompileCache(cache_dir, mesh_size=mesh_size)
             if cache_dir else None)
    return WarmupManager(menu=menu, supervisor=supervisor, cache=cache,
                         registry=registry, **kw)
