"""Device kernels (JAX/XLA/Pallas) — the TPU data plane.

Reference analogue: the `asm-keccak` native fast path and rayon-parallel
keccak loops of the reference (bin/reth/Cargo.toml:94,
crates/stages/stages/src/stages/hashing_account.rs:29-32,
crates/trie/sparse/src/arena/mod.rs:2500-2548). Here those become batched,
shape-stable XLA programs.
"""

# NOTE: do NOT enable jax's persistent compilation cache here — setting
# jax_compilation_cache_dir (or the jax_persistent_cache_min_* knobs)
# deadlocks the first jit in this jax build (0.9.0/axon). Compile cost is
# managed by minimising distinct program shapes instead (see KeccakDevice
# block_tier / batch tiers).

from .keccak_jax import (
    keccak_f1600_jax,
    keccak256_jax_words,
    keccak256_batch_jax,
    KeccakDevice,
)
from .supervisor import (
    CircuitBreaker,
    DeviceSupervisor,
    FaultInjector,
    SupervisedBackend,
    SupervisedHasher,
    probe_device,
    probe_device_retrying,
)
from .hash_service import (
    HashClient,
    HashFuture,
    HashService,
    LaneOverloaded,
    ServiceFaultInjector,
)

__all__ = [
    "keccak_f1600_jax",
    "keccak256_jax_words",
    "keccak256_batch_jax",
    "KeccakDevice",
    "CircuitBreaker",
    "DeviceSupervisor",
    "FaultInjector",
    "SupervisedBackend",
    "SupervisedHasher",
    "probe_device",
    "probe_device_retrying",
    "HashClient",
    "HashFuture",
    "HashService",
    "LaneOverloaded",
    "ServiceFaultInjector",
]
