"""Device kernels (JAX/XLA/Pallas) — the TPU data plane.

Reference analogue: the `asm-keccak` native fast path and rayon-parallel
keccak loops of the reference (bin/reth/Cargo.toml:94,
crates/stages/stages/src/stages/hashing_account.rs:29-32,
crates/trie/sparse/src/arena/mod.rs:2500-2548). Here those become batched,
shape-stable XLA programs.
"""

# NOTE: the persistent compilation cache is NEVER enabled at import time —
# blindly setting jax_compilation_cache_dir has deadlocked the first jit in
# this jax build (0.9.0/axon). The compile lifecycle is owned by the warm-up
# manager (ops/warmup.py): a bounded shape menu AOT-compiled behind the
# supervisor's health probe, and a cache directory that is only wired in
# after a SUBPROCESS probe (probe_device(cache_dir=...)) proves it loads.

from .keccak_jax import (
    keccak_f1600_jax,
    keccak256_jax_words,
    keccak256_batch_jax,
    KeccakDevice,
)
from .supervisor import (
    CircuitBreaker,
    DeviceSupervisor,
    FaultInjector,
    SupervisedBackend,
    SupervisedHasher,
    probe_device,
    probe_device_retrying,
)
from .hash_service import (
    HashClient,
    HashFuture,
    HashService,
    LaneOverloaded,
    ServiceFaultInjector,
)
from .warmup import (
    CompileCache,
    MenuShape,
    WarmupManager,
    build_warmup,
    default_menu,
)

__all__ = [
    "CompileCache",
    "MenuShape",
    "WarmupManager",
    "build_warmup",
    "default_menu",
    "keccak_f1600_jax",
    "keccak256_jax_words",
    "keccak256_batch_jax",
    "KeccakDevice",
    "CircuitBreaker",
    "DeviceSupervisor",
    "FaultInjector",
    "SupervisedBackend",
    "SupervisedHasher",
    "probe_device",
    "probe_device_retrying",
    "HashClient",
    "HashFuture",
    "HashService",
    "LaneOverloaded",
    "ServiceFaultInjector",
]
