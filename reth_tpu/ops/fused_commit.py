"""Fused multi-level trie commit — child digests stay in HBM between levels.

The round-1 committer paid one host↔device round trip per trie depth level:
host RLP-encodes a level (needs child digests), uploads, hashes, downloads
digests, repeats. Over the axon tunnel (~60 ms D2H latency floor) a 10-level
commit burned ~0.6 s in latency alone. This module removes every mid-commit
D2H:

- The host builds per-level **RLP byte templates**: complete node RLP with
  zero-filled 32-byte *holes* where a hashed child's digest goes. Crucially
  this needs NO digest values — whether a child is inlined (<32 B RLP) or
  hashed (0xa0 + 32-byte ref) depends only on lengths, so the template and
  every hole offset are host-computable bottom-up without syncing.
- The device keeps a resident **digest buffer** (S, 32) u8 in HBM. Each
  level dispatch gathers child digests from the buffer, scatter-splices
  them into the level's templates, runs the masked keccak absorb, and
  scatters the level's digests back into the buffer. Dispatches chain
  through the donated buffer, so XLA executes them in order and the host
  never blocks — template building for level d-1 overlaps device hashing
  of level d.
- ONE D2H at the end (the digest buffer) yields every node hash.

Shape discipline (compile-count bounded, see memory: axon-tunnel-pitfalls):
batch tiers grow x4 from ``min_tier``; block tiers are {2, 4, 8, ...}; the
hole tier is fixed at 4x the batch tier (levels with more holes are split
across dispatches). Program count for a bench-style workload with a single
forced batch tier is <=3.

Reference analogue: the rayon subtrie hash loop
(crates/trie/sparse/src/arena/mod.rs:2500-2548) and the per-level batching
seam this replaces (crates/stages/stages/src/stages/hashing_account.rs:29-32).
"""

from __future__ import annotations

import os
import threading
import time as _time
from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp

from ..primitives.keccak import RATE
from ..trie.node import HASH_REF_HOLE  # noqa: F401  (re-export; defined jax-free)
from .keccak_jax import masked_absorb_words


def _timed_call(kind: str, shape, fn, *args):
    """Run one jitted dispatch and report (shape, wall) to the compile
    tracker: the first call of a shape IS its XLA compile (jit compiles
    synchronously, then enqueues), so compile storms split out from the
    near-zero steady-state enqueue cost."""
    from ..metrics import compile_tracker

    t0 = _time.perf_counter()
    out = fn(*args)
    compile_tracker.record(kind, shape, _time.perf_counter() - t0)
    return out


def _bytes_to_words(t):
    """(N, L) u8 templates -> (N, L//4) u32 little-endian lane words."""
    w = t.reshape(t.shape[0], -1, 4).astype(jnp.uint32)
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def _digests_to_bytes(d):
    """(N, 8) u32 digests -> (N, 32) u8 (little-endian per word)."""
    b = jnp.stack([(d >> (8 * k)) & 0xFF for k in range(4)], axis=-1)
    return b.astype(jnp.uint8).reshape(d.shape[0], 32)


def _plain_level(templates, counts, slots, digest_buf, *, b_tier: int):
    d = masked_absorb_words(_bytes_to_words(templates), b_tier, counts)
    return digest_buf.at[slots].set(_digests_to_bytes(d))


def _splice_level(
    templates, counts, hole_node, hole_byte, hole_src, slots, digest_buf, *, b_tier: int
):
    L = b_tier * RATE
    dig = digest_buf[hole_src]  # (H, 32) u8 gather from resident buffer
    flat = templates.reshape(-1)
    idx = (hole_node * L + hole_byte)[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
    flat = flat.at[idx.reshape(-1)].set(dig.reshape(-1))
    d = masked_absorb_words(_bytes_to_words(flat.reshape(templates.shape)), b_tier, counts)
    return digest_buf.at[slots].set(_digests_to_bytes(d))


def _packed_level(
    flat, row_off, row_len, counts, hole_node, hole_byte, hole_src, slots,
    digest_buf, *, b_tier: int
):
    """Unpack tightly-concatenated RLP rows by gather, apply keccak padding,
    splice child digests, hash, scatter digests. The packed form is what
    crosses the host->device wire — no per-row padding is transferred
    (tunnel H2D is the single-chip bottleneck, see memory axon-tunnel-pitfalls)."""
    L = b_tier * RATE
    n = row_off.shape[0]
    col = jnp.arange(L, dtype=jnp.uint32)[None, :]
    idx = jnp.minimum(row_off[:, None] + col, flat.shape[0] - 1)
    rows = jnp.where(col < row_len[:, None], flat[idx], 0)
    # multi-rate padding: 0x01 at the message end, 0x80 at the block end
    rows = rows ^ jnp.where(col == row_len[:, None], 0x01, 0).astype(jnp.uint8)
    last = (counts.astype(jnp.uint32) * RATE - 1)[:, None]
    rows = rows ^ jnp.where(col == last, 0x80, 0).astype(jnp.uint8)
    if hole_node is not None:
        dig = digest_buf[hole_src]
        fr = rows.reshape(-1)
        sidx = (hole_node * L + hole_byte)[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
        rows = fr.at[sidx.reshape(-1)].set(dig.reshape(-1)).reshape(n, L)
    d = masked_absorb_words(_bytes_to_words(rows), b_tier, counts)
    return digest_buf.at[slots].set(_digests_to_bytes(d))


def _branch_level(masks, slots, ch_row, ch_nib, ch_src, digest_buf, *, b_tier: int):
    """Construct whole branch-node RLPs ON DEVICE from 2-byte state masks.

    A secure-trie branch whose 16 children are all hashed has a fully
    determined byte layout: list header (f8 <len> for <=7 children, f9
    <len:2> above), then per nibble (a0 + 32-byte ref) or 80, then 80
    (empty value). Only the mask and the child (row, nibble, digest-slot)
    triples cross the wire — ~250x less H2D than the 532-byte template."""
    L = b_tier * RATE
    n = masks.shape[0]
    nibs = jnp.arange(16, dtype=jnp.int32)[None, :]
    present = ((masks[:, None].astype(jnp.int32) >> nibs) & 1).astype(jnp.int32)  # (n,16)
    sizes = 1 + 32 * present
    csum = jnp.cumsum(sizes, axis=1) - sizes          # exclusive prefix
    payload = jnp.sum(sizes, axis=1) + 1              # + empty value byte
    hl = jnp.where(payload > 0xFF, 3, 2)              # header length
    total = hl + payload
    col = jnp.arange(L, dtype=jnp.int32)[None, :]
    rows = jnp.zeros((n, L), dtype=jnp.uint8)
    rows = rows.at[:, 0].set(jnp.where(hl == 3, 0xF9, 0xF8).astype(jnp.uint8))
    rows = rows.at[:, 1].set(
        jnp.where(hl == 3, payload >> 8, payload & 0xFF).astype(jnp.uint8)
    )
    # byte 2 = low len byte for f9 rows; f8 rows overwrite it with their
    # first child marker below (csum[:, 0] == 0 puts it exactly at hl == 2)
    rows = rows.at[:, 2].set((payload & 0xFF).astype(jnp.uint8))
    # child markers: 0xa0 when present else 0x80, at hl + csum
    marker = jnp.where(present == 1, 0xA0, 0x80).astype(jnp.uint8)
    flat = rows.reshape(-1)
    midx = (jnp.arange(n, dtype=jnp.int32)[:, None] * L + hl[:, None] + csum).reshape(-1)
    flat = flat.at[midx].set(marker.reshape(-1))
    # empty branch value right after the children
    vidx = jnp.arange(n, dtype=jnp.int32) * L + (total - 1)
    flat = flat.at[vidx].set(jnp.uint8(0x80))
    # splice child digests at marker+1
    dig = digest_buf[ch_src]
    off = hl[ch_row] + csum[ch_row, ch_nib] + 1
    sidx = (ch_row * L + off)[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
    flat = flat.at[sidx.reshape(-1)].set(dig.reshape(-1))
    rows = flat.reshape(n, L)
    # keccak padding from the computed total length
    counts = total // RATE + 1
    rows = rows ^ jnp.where(col == total[:, None], 0x01, 0).astype(jnp.uint8)
    rows = rows ^ jnp.where(col == (counts * RATE - 1)[:, None], 0x80, 0).astype(jnp.uint8)
    d = masked_absorb_words(_bytes_to_words(rows), b_tier, counts.astype(jnp.int32))
    return digest_buf.at[slots].set(_digests_to_bytes(d))


@lru_cache(maxsize=None)
def _jitted(kind: str, b_tier: int, sharding_key=None):
    """One compiled program per (kind, block tier); shapes add tiers via the
    caller's padding. ``sharding_key`` is an opaque hashable handle the mesh
    layer uses to get distinctly-sharded variants (see ``FusedMeshEngine``)."""
    fn = {
        "plain": _plain_level,
        "splice": _splice_level,
        "packed": _packed_level,
        "branch": _branch_level,
    }[kind]
    donate = {"plain": 3, "splice": 6, "packed": 8, "branch": 5}[kind]
    return jax.jit(partial(fn, b_tier=b_tier), donate_argnums=donate)


def _tier(n: int, min_tier: int, growth: int = 4) -> int:
    t = min_tier
    while t < n:
        t *= growth
    return t


def _pow2(n: int, floor: int = 2) -> int:
    t = floor
    while t < n:
        t *= 2
    return t


class _Bucket:
    """One pending device dispatch: rows of equal-ish shape within a level."""

    __slots__ = ("templates", "counts", "slots", "holes", "nb_max")

    def __init__(self):
        self.templates: list[bytes] = []
        self.counts: list[int] = []
        self.slots: list[int] = []
        self.holes: list[tuple[int, int, int]] = []  # (row, byte_off, src_slot)
        self.nb_max = 1

    def add(self, template: bytes, nb: int, slot: int, holes) -> None:
        row = len(self.templates)
        self.templates.append(template)
        self.counts.append(nb)
        self.slots.append(slot)
        self.nb_max = max(self.nb_max, nb)
        for byte_off, src_slot in holes:
            self.holes.append((row, byte_off, src_slot))


class FusedLevelEngine:
    """Device-resident digest buffer + per-level dispatch.

    Usage: ``begin(max_slots)`` → repeated ``dispatch_level(bucket)`` deepest
    level first → ``finish()`` returns the (S, 32) numpy digest array (the
    single D2H of the whole commit). Slot 0 is a reserved dummy target for
    padding rows.
    """

    effective_kind = "device"

    # hole budget per dispatch = _HOLE_FACTOR * batch tier; levels with more
    # holes (branch-heavy near-root levels) are split across dispatches
    _HOLE_FACTOR = 4
    # row cap per dispatch: keeps flat byte indices (row * L + off) well
    # under 2^31 — scatter indices are int32 on the TPU, and a silent wrap
    # would drop splices and corrupt roots (2^21 rows * 544 B = 2^30.09)
    _MAX_ROWS = 1 << 21
    # declared menu ceilings (ops/warmup.py, mirroring KeccakDevice): levels
    # with more rows split across dispatches so one giant level can never
    # mint a batch tier above the menu; block tiers past the ceiling raise
    # (an MPT node tops out ~533 B = 4 rate blocks — 64 is generous slack,
    # and there is no per-row CPU fallback mid-fused-commit to hide behind)
    MAX_BATCH_ROWS = 1 << 16
    MAX_BLOCK_TIER = 64

    def __init__(self, min_tier: int = 1024):
        self.min_tier = min_tier
        self._buf = None
        self._n_slots = 0
        self.dispatches = 0  # device program calls since begin()
        # ladder caps hoisted out of the dispatch path (PR 10 follow-up):
        # the ladder walk used to rerun on EVERY dispatch_level/_split call;
        # it is now computed once per (ceilings, min_tier, mesh) key — the
        # key guard keeps tests that mutate MAX_BATCH_ROWS post-init exact
        self._caps_key: tuple | None = None
        self._caps()

    def _caps(self) -> tuple[int, list[int]]:
        """(row cap, batch-tier ladder) under the declared ceilings,
        memoized by the inputs that define them. The row cap is the
        LARGEST tier on the batch ladder (x4 growth from the
        device-count-rounded floor) that still fits under the ceilings.
        Splitting at a raw ceiling minted a tier ABOVE it whenever the
        mesh-rounded floor put the ladder off the pow2 grid (e.g. 6
        devices: 1026 → 4104 → 16416 → 65664 > MAX_BATCH_ROWS) — a chunk
        split must never create a shape the warm-up menu doesn't declare
        or the mesh can't divide."""
        key = (self._MAX_ROWS, self.MAX_BATCH_ROWS, self.min_tier,
               self._batch_multiple())
        if self._caps_key != key:
            ceiling = min(self._MAX_ROWS, self.MAX_BATCH_ROWS)
            t = max(self.min_tier, key[3])
            ladder = [t]
            while t * 4 <= ceiling:
                t *= 4
                ladder.append(t)
            self._caps_key = key
            self._caps_value = (ladder[-1], ladder)
        return self._caps_value

    def _row_cap(self) -> int:
        return self._caps()[0]

    def _hole_budget(self, n: int) -> int:
        """Hole budget for an ``n``-row level: _HOLE_FACTOR x the smallest
        ladder tier holding ``n`` — looked up on the hoisted ladder
        instead of re-walking it per dispatch/split call."""
        cap, ladder = self._caps()
        for t in ladder:
            if n <= t:
                return self._HOLE_FACTOR * t
        return self._HOLE_FACTOR * cap  # over the cap: callers split by rows

    def _check_batch_tier(self, n_tier: int) -> int:
        """Invariant guard on every minted batch tier: divisible by the
        mesh device count AND inside the declared menu ceiling. A
        violation here would silently shard unevenly or compile an
        off-menu program mid-commit — fail loudly instead."""
        mult = self._batch_multiple()
        # the floor tier itself is always admissible (a min_tier configured
        # above the ceiling has nothing smaller to fall back to)
        ceiling = max(min(self._MAX_ROWS, self.MAX_BATCH_ROWS),
                      max(self.min_tier, mult))
        assert n_tier % mult == 0, (
            f"batch tier {n_tier} not divisible by the {mult}-device mesh")
        assert n_tier <= ceiling, (
            f"batch tier {n_tier} exceeds the declared ceiling {ceiling}")
        return n_tier

    def _check_block_tier(self, b_tier: int) -> int:
        if b_tier > self.MAX_BLOCK_TIER:
            raise ValueError(
                f"node of {b_tier} rate blocks exceeds the declared "
                f"block-tier ceiling {self.MAX_BLOCK_TIER} "
                f"(ops/warmup.py shape menu)")
        return b_tier

    # -- lifecycle ---------------------------------------------------------

    def begin(self, max_slots: int) -> None:
        s_tier = _pow2(max_slots + 1, floor=max(self.min_tier, 2))
        self._buf = self._device_put(np.zeros((s_tier, 32), dtype=np.uint8))
        self._n_slots = 1  # slot 0 = dummy
        self.dispatches = 0

    def _count_dispatch(self, levels: int = 1) -> None:
        """One device program actually ran, carrying ``levels`` staged
        levels — the number the whole-subtrie kernel family exists to
        shrink (fused_* metrics + the bench's dispatches/block)."""
        from ..metrics import fused_metrics

        self.dispatches += 1
        fused_metrics.record_dispatch(levels)

    def alloc_slot(self) -> int:
        slot = self._n_slots
        self._n_slots += 1
        return slot

    def ensure(self, max_slots: int) -> None:
        """Grow the resident digest buffer to ``max_slots`` slots,
        preserving written digests (the pipelined rebuild only learns a
        window's slot high-water mark when its sweep lands). Pow2 tiers
        keep the copy-program count logarithmic."""
        need = max_slots + 1
        cur = 0 if self._buf is None else self._buf.shape[0]
        if need <= cur:
            return
        new_tier = _pow2(need, floor=max(self.min_tier, 2, cur))
        grown = self._device_put(np.zeros((new_tier, 32), dtype=np.uint8))
        if cur:
            grown = grown.at[:cur].set(self._buf)
        self._buf = grown

    def finish(self) -> np.ndarray:
        buf, self._buf = self._buf, None
        return np.asarray(buf)

    def fetch_slots(self, slots: np.ndarray) -> np.ndarray:
        """Small D2H: gather specific digest slots (e.g. per-job roots)
        without pulling the whole buffer; ends the commit."""
        ids = np.zeros((_pow2(max(len(slots), 1), floor=8),), dtype=np.int32)
        ids[: len(slots)] = slots
        out = np.asarray(jnp.take(self._buf, self._device_put(ids), axis=0))
        self._buf = None
        return out[: len(slots)]

    # -- mesh seam (overridden by FusedMeshEngine) -------------------------

    def _device_put(self, arr: np.ndarray):
        return jnp.asarray(arr)

    def _put_batch(self, arr: np.ndarray):
        return jnp.asarray(arr)

    def _sharding_key(self):
        return None

    def _batch_multiple(self) -> int:
        return 1

    # -- dispatch ----------------------------------------------------------

    def dispatch_level(self, bucket: _Bucket) -> None:
        """Queue one level bucket on the device (async, no sync)."""
        n = len(bucket.templates)
        if n == 0:
            return
        b_tier = self._check_block_tier(_pow2(bucket.nb_max, floor=2))
        hole_budget = self._hole_budget(n + 1)
        over_holed = bucket.holes and len(bucket.holes) > hole_budget
        if over_holed or n + 1 > self._row_cap():
            for part in self._split(bucket, hole_budget):
                self._dispatch_one(part, b_tier)
            return
        self._dispatch_one(bucket, b_tier)

    def _split(self, bucket: _Bucket, hole_budget: int):
        """Split an oversized bucket by rows; within-level order is free."""
        holes_by_row: dict[int, list[tuple[int, int]]] = {}
        for row, off, src in bucket.holes:
            holes_by_row.setdefault(row, []).append((off, src))
        part = _Bucket()
        for row in range(len(bucket.templates)):
            row_holes = holes_by_row.get(row, [])
            if part.templates and (
                len(part.holes) + len(row_holes) > hole_budget
                or len(part.templates) + 2 > self._row_cap()
            ):
                yield part
                part = _Bucket()
            part.add(bucket.templates[row], bucket.counts[row], bucket.slots[row], row_holes)
        if part.templates:
            yield part

    def _dispatch_one(self, bucket: _Bucket, b_tier: int) -> None:
        n = len(bucket.templates)
        mult = self._batch_multiple()
        n_tier = self._check_batch_tier(
            _tier(max(n + 1, mult), max(self.min_tier, mult), growth=4))
        L = b_tier * RATE

        templates = np.zeros((n_tier, L), dtype=np.uint8)
        for i, t in enumerate(bucket.templates):
            tl = len(t)
            templates[i, :tl] = np.frombuffer(t, dtype=np.uint8)
            # keccak multi-rate padding at the message's own final block
            templates[i, tl] ^= 0x01
            templates[i, bucket.counts[i] * RATE - 1] ^= 0x80
        counts = np.zeros((n_tier,), dtype=np.int32)
        counts[:n] = bucket.counts
        counts[n:] = 1  # padding rows absorb one zero block into dummy slot 0
        slots = np.zeros((n_tier,), dtype=np.int32)
        slots[:n] = bucket.slots

        key = self._sharding_key()
        if not bucket.holes:
            fn = _jitted("plain", b_tier, key)
            self._buf = _timed_call(
                "fused.plain", (b_tier, n_tier), fn,
                self._put_batch(templates), self._put_batch(counts),
                self._put_batch(slots), self._buf,
            )
            self._count_dispatch()
            return
        h_tier = _pow2(len(bucket.holes), floor=self._HOLE_FACTOR * self.min_tier)
        hole_node = np.full((h_tier,), n, dtype=np.int32)  # padding row target
        hole_byte = np.zeros((h_tier,), dtype=np.int32)
        hole_src = np.zeros((h_tier,), dtype=np.int32)
        for i, (row, off, src) in enumerate(bucket.holes):
            hole_node[i] = row
            hole_byte[i] = off
            hole_src[i] = src
        fn = _jitted("splice", b_tier, key)
        self._buf = _timed_call(
            "fused.splice", (b_tier, n_tier, h_tier), fn,
            self._put_batch(templates), self._put_batch(counts),
            self._put_batch(hole_node), self._put_batch(hole_byte),
            self._put_batch(hole_src), self._put_batch(slots), self._buf,
        )
        self._count_dispatch()

    # -- raw turbo dispatch (arrays straight from native/triebuild.cpp) ----

    def _pad_rows(self, n: int, *arrays):
        """Pad row-indexed arrays to the batch tier; returns (n_tier, padded)."""
        mult = self._batch_multiple()
        n_tier = self._check_batch_tier(
            _tier(max(n + 1, mult), max(self.min_tier, mult), growth=4))
        out = []
        for arr, fill in arrays:
            p = np.full((n_tier,), fill, dtype=arr.dtype)
            p[:n] = arr
            out.append(p)
        return n_tier, out

    @staticmethod
    def _filter_triples(triples, lo: int, hi: int):
        """Select (row, coord, src) triples with lo <= row < hi, rebased."""
        if triples is None:
            return None
        m = (triples[0] >= lo) & (triples[0] < hi)
        if not m.any():
            return None
        return np.stack((triples[0][m] - lo, triples[1][m], triples[2][m]))

    def _pad_holes(self, holes, n: int, floor: int, growth_mult):
        """Pad (row, off/nib, src) triples; padding rows target row ``n``
        (always a padding row since n_tier >= n+1) and dummy slot 0."""
        h = holes.shape[1] if holes is not None else 0
        mult = self._batch_multiple()
        h_tier = -(-floor // mult) * mult  # hole arrays shard over the mesh too
        while h_tier < h:
            h_tier *= growth_mult
        assert h_tier % mult == 0, (
            f"hole tier {h_tier} not divisible by the {mult}-device mesh")
        rows = np.full((h_tier,), n, dtype=np.int32)
        offs = np.zeros((h_tier,), dtype=np.int32)
        srcs = np.zeros((h_tier,), dtype=np.int32)
        if h:
            rows[:h], offs[:h], srcs[:h] = holes[0], holes[1], holes[2]
        return rows, offs, srcs

    def dispatch_packed(
        self,
        flat: np.ndarray,
        row_off: np.ndarray,
        row_len: np.ndarray,
        slots: np.ndarray,
        holes: np.ndarray | None,
        b_tier: int,
    ) -> None:
        """One level of tightly-packed RLP rows from the native builder.

        ``flat``: concatenated row bytes (the only bulk H2D of the level);
        ``holes``: (3, H) int32 [row, byte_off, src_slot] or None."""
        n = len(row_off)
        if n == 0:
            return
        self._check_block_tier(b_tier)
        if n + 1 > self._row_cap():
            # menu/row-cap clamp: split the level by row ranges (within-
            # level order is free), rebasing the packed bytes and holes
            cap = self._row_cap() - 1
            for lo in range(0, n, cap):
                hi = min(lo + cap, n)
                base = int(row_off[lo])
                end = int(row_off[hi - 1] + row_len[hi - 1])
                self.dispatch_packed(
                    flat[base:end], row_off[lo:hi] - base, row_len[lo:hi],
                    slots[lo:hi], self._filter_triples(holes, lo, hi), b_tier)
            return
        counts = (row_len // RATE + 1).astype(np.int32)
        n_tier, (row_off_p, row_len_p, counts_p, slots_p) = self._pad_rows(
            n, (row_off.astype(np.uint32), 0), (row_len.astype(np.uint32), 0),
            (counts, 1), (slots.astype(np.int32), 0),
        )
        flat_tier = _pow2(max(len(flat), 1), floor=4096)
        flat_p = np.zeros((flat_tier,), dtype=np.uint8)
        flat_p[: len(flat)] = flat
        hr, ho, hs = self._pad_holes(holes, n, floor=256, growth_mult=4)
        fn = _jitted("packed", b_tier, self._sharding_key())
        self._buf = _timed_call(
            "fused.packed", (b_tier, n_tier, flat_tier, len(hr)), fn,
            self._device_put(flat_p), self._put_batch(row_off_p),
            self._put_batch(row_len_p), self._put_batch(counts_p),
            self._put_batch(hr), self._put_batch(ho), self._put_batch(hs),
            self._put_batch(slots_p), self._buf,
        )
        self._count_dispatch()

    def dispatch_branch(
        self, masks: np.ndarray, slots: np.ndarray, children: np.ndarray
    ) -> None:
        """One level of all-hashed-children branches: 2-byte masks + child
        (row, nibble, src-slot) triples; the RLP bytes are constructed on
        device (``_branch_level``)."""
        n = len(masks)
        if n == 0:
            return
        if n + 1 > self._row_cap():
            cap = self._row_cap() - 1
            for lo in range(0, n, cap):
                hi = min(lo + cap, n)
                self.dispatch_branch(masks[lo:hi], slots[lo:hi],
                                     self._filter_triples(children, lo, hi))
            return
        n_tier, (masks_p, slots_p) = self._pad_rows(
            n, (masks.astype(np.int32), 0), (slots.astype(np.int32), 0)
        )
        # children <= 16n; tier as a multiple of the batch tier to bound the
        # number of compiled (n_tier, h_tier) combinations
        cr, cn, cs = self._pad_holes(children, n, floor=2 * n_tier, growth_mult=2)
        fn = _jitted("branch", 4, self._sharding_key())
        self._buf = _timed_call(
            "fused.branch", (n_tier, len(cr)), fn,
            self._put_batch(masks_p), self._put_batch(slots_p),
            self._put_batch(cr), self._put_batch(cn), self._put_batch(cs), self._buf,
        )
        self._count_dispatch()


@lru_cache(maxsize=64)
def _staged_packed(b_tier: int, n_pow: int, h_pow: int, u8_len: int,
                   i32_len: int, s_tier: int):
    """One compiled per-LEVEL program over the staged whole-commit buffers.

    Round-2 postmortem: the first mega variant unrolled EVERY level into one
    XLA graph; it compiled for ~19 s on the CPU backend and never finished
    over the axon tunnel's serialized remote compile — wedging the tunnel
    exactly like round 1's compile storm (VERDICT weak #1). This variant
    keeps the mega engine's wire win (two H2D uploads per commit, zero
    mid-commit D2H — dispatches of device-resident buffers are cheap) but
    compiles SMALL per-level programs shared across levels: static shapes
    are pow2 row/hole tiers, while the level's location in the staging
    buffers (offsets) and its live row/hole counts arrive as traced scalars.
    Program count is O(log levels), each one a single masked-absorb graph.
    """

    def run(u8, i32, digest_buf, flat_off, len_o, slot_o, hidx_o, hsrc_o,
            n_valid, h_valid):
        L = b_tier * RATE
        raw = jax.lax.dynamic_slice(u8, (len_o,), (2 * n_pow,))
        raw = raw.reshape(n_pow, 2).astype(jnp.uint32)
        ridx = jnp.arange(n_pow, dtype=jnp.int32)
        vrow = ridx < n_valid
        row_len = jnp.where(vrow, raw[:, 0] | (raw[:, 1] << 8), 0)
        row_off = (jnp.cumsum(row_len) - row_len).astype(jnp.int32)
        counts = (row_len // RATE + 1).astype(jnp.int32)
        slots = jnp.where(
            vrow, jax.lax.dynamic_slice(i32, (slot_o,), (n_pow,)), 0)
        # rows gather straight from the staging buffer (no slice
        # materialization, no padding of the staged bytes)
        col = jnp.arange(L, dtype=jnp.int32)[None, :]
        idx = jnp.minimum(flat_off + row_off[:, None] + col, u8.shape[0] - 1)
        rows = jnp.where(col < row_len[:, None].astype(jnp.int32), u8[idx], 0)
        rl = row_len[:, None].astype(jnp.int32)
        rows = rows ^ jnp.where(col == rl, 0x01, 0).astype(jnp.uint8)
        last = (counts * RATE - 1)[:, None]
        rows = rows ^ jnp.where(col == last, 0x80, 0).astype(jnp.uint8)
        # splice child digests; junk hole entries retarget the level's
        # always-padding row (row n_valid-1 has row_len 0)
        hidxr = jax.lax.dynamic_slice(i32, (hidx_o,), (h_pow,))
        hsrcr = jax.lax.dynamic_slice(i32, (hsrc_o,), (h_pow,))
        hv = jnp.arange(h_pow, dtype=jnp.int32) < h_valid
        dump = (n_valid - 1) * L
        hidx = jnp.where(hv, hidxr, dump)
        hsrc = jnp.where(hv, hsrcr, 0)
        dig = digest_buf[hsrc]
        fr = rows.reshape(-1)
        sidx = hidx[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
        rows = fr.at[sidx.reshape(-1)].set(dig.reshape(-1)).reshape(n_pow, L)
        d = masked_absorb_words(_bytes_to_words(rows), b_tier, counts)
        return digest_buf.at[slots].set(_digests_to_bytes(d))

    return jax.jit(run, donate_argnums=2)


@lru_cache(maxsize=64)
def _staged_branch(n_pow: int, ch_pow: int, u8_len: int, i32_len: int,
                   s_tier: int):
    """Per-level staged branch program (see `_staged_packed`)."""

    def run(u8, i32, digest_buf, mask_o, slot_o, chidx_o, chsrc_o,
            n_valid, ch_valid):
        raw = jax.lax.dynamic_slice(u8, (mask_o,), (2 * n_pow,))
        raw = raw.reshape(n_pow, 2).astype(jnp.uint32)
        vrow = jnp.arange(n_pow, dtype=jnp.int32) < n_valid
        masks = jnp.where(vrow, (raw[:, 0] | (raw[:, 1] << 8)), 0)
        slots = jnp.where(
            vrow, jax.lax.dynamic_slice(i32, (slot_o,), (n_pow,)), 0)
        crn_r = jax.lax.dynamic_slice(i32, (chidx_o,), (ch_pow,))
        cs_r = jax.lax.dynamic_slice(i32, (chsrc_o,), (ch_pow,))
        cv = jnp.arange(ch_pow, dtype=jnp.int32) < ch_valid
        dump = (n_valid - 1) * 16
        crn = jnp.where(cv, crn_r, dump)
        cs = jnp.where(cv, cs_r, 0)
        return _branch_level(masks.astype(jnp.int32), slots, crn // 16,
                             crn % 16, cs, digest_buf, b_tier=4)

    return jax.jit(run, donate_argnums=2)


class MegaFusedEngine(FusedLevelEngine):
    """Whole-commit staging variant of the fused engine.

    The axon tunnel's H2D cost is dominated by a ~40-70 ms fixed latency
    PER TRANSFER (bandwidth only ramps past ~4 MB) — so the per-level
    engine's ~18 dispatches x ~5 small arrays each pay seconds in transfer
    latency alone. This engine records every level dispatch, concatenates
    all inputs into TWO staging buffers (u8 bytes, i32 indices), uploads
    them in ONE device_put each, then runs one SMALL compiled program per
    level over the resident buffers (`_staged_packed`/`_staged_branch`),
    digest buffer donated through the chain. D2H stays a single
    digest/root fetch.

    Reference analogue: the same per-level batching seam
    (crates/stages/stages/src/stages/hashing_account.rs:29-32), collapsed
    to one device round trip per MerkleStage chunk.
    """

    def __init__(self, min_tier: int = 1024):
        super().__init__(min_tier=min_tier)
        self._plan: list[tuple] = []
        self._u8_parts: list[np.ndarray] = []
        self._i32_parts: list[np.ndarray] = []
        self._u8_off = 0
        self._i32_off = 0
        # per-commit H2D accounting (bench hotstate's bytes/block signal)
        self.staged_u8_bytes = 0
        self.staged_i32_bytes = 0

    def begin(self, max_slots: int) -> None:
        self._s_tier = _pow2(max_slots + 1, floor=max(self.min_tier, 2))
        self._n_slots = 1
        self._plan, self._u8_parts, self._i32_parts = [], [], []
        self._u8_off = self._i32_off = 0
        self._buf = None
        self.dispatches = 0
        self.staged_u8_bytes = 0
        self.staged_i32_bytes = 0

    def ensure(self, max_slots: int) -> None:
        """Staged variant: before ``_execute`` the buffer is only a planned
        shape, so growth is free — just raise the tier."""
        if self._buf is None:
            self._s_tier = max(self._s_tier,
                               _pow2(max_slots + 1, floor=max(self.min_tier, 2)))
        else:  # already materialized (post-fetch reuse): real copy-grow
            super().ensure(max_slots)

    # program-shape tiers are pow2 from these floors: compile count stays
    # O(log workload) while the STAGED bytes remain tight (padding never
    # crosses the wire; the programs mask junk rows/holes via n_valid)
    _ROW_FLOOR = 2048
    _HOLE_FLOOR = 2048

    @staticmethod
    def _step(n: int, floor: int) -> int:
        """Quantize the final staging-buffer length: 4 steps per octave —
        ≤12.5% wire waste, logarithmic buffer-shape variety (the buffer
        length is part of every level program's signature)."""
        if n <= floor:
            return floor
        e = (n - 1).bit_length() - 1  # n in (2^e, 2^(e+1)]
        base = 1 << e
        for frac in (5, 6, 7, 8):
            v = base * frac // 4
            if v >= n:
                return v
        return base * 2

    def _stage_u8(self, arr: np.ndarray) -> int:
        arr = np.ascontiguousarray(arr, dtype=np.uint8).ravel()
        off = self._u8_off
        self._u8_parts.append(arr)
        self._u8_off += arr.size
        self.staged_u8_bytes += int(arr.size)
        return off

    def _stage_i32(self, *arrays: np.ndarray) -> int:
        off = self._i32_off
        for a in arrays:
            a = np.ascontiguousarray(a).astype(np.int32, copy=False).ravel()
            self._i32_parts.append(a)
            self._i32_off += a.size
            self.staged_i32_bytes += int(a.size) * 4
        return off

    def dispatch_packed(self, flat, row_off, row_len, slots, holes, b_tier) -> None:
        n = len(row_off)
        if n == 0:
            return
        self._check_block_tier(b_tier)
        L = b_tier * RATE
        if n + 1 > self._row_cap():
            # int32 scatter indices (row * L + byte) wrap past 2^31, and the
            # warm-up menu caps the batch tier — split the level by row
            # ranges (within-level order is free)
            cap = self._row_cap() - 1
            for lo in range(0, n, cap):
                hi = min(lo + cap, n)
                base = int(row_off[lo])
                end = int(row_off[hi - 1] + row_len[hi - 1])
                self.dispatch_packed(
                    flat[base:end], row_off[lo:hi] - base, row_len[lo:hi],
                    slots[lo:hi], self._filter_triples(holes, lo, hi), b_tier)
            return
        # tight staging + one explicit padding row (the hole dump target)
        row_len_p = np.zeros((n + 1,), dtype="<u2")
        row_len_p[:n] = row_len
        slots_p = np.zeros((n + 1,), dtype=np.int32)
        slots_p[:n] = slots
        h = holes.shape[1] if holes is not None else 0
        hidx = np.full((h + 1,), n * L, dtype=np.int32)
        hsrc = np.zeros((h + 1,), dtype=np.int32)
        if h:
            hidx[:h] = holes[0] * L + holes[1]
            hsrc[:h] = holes[2]
        flat_off = self._stage_u8(np.asarray(flat, dtype=np.uint8))
        len_o = self._stage_u8(row_len_p.view(np.uint8))
        slot_o = self._stage_i32(slots_p)
        hidx_o = self._stage_i32(hidx)
        hsrc_o = self._stage_i32(hsrc)
        self._plan.append(("packed", b_tier,
                           _pow2(n + 1, floor=self._ROW_FLOOR),
                           _pow2(h + 1, floor=self._HOLE_FLOOR),
                           flat_off, len_o, slot_o, hidx_o, hsrc_o,
                           n + 1, h + 1))

    def dispatch_branch(self, masks, slots, children) -> None:
        n = len(masks)
        if n == 0:
            return
        if n + 1 > self._row_cap():
            cap = self._row_cap() - 1
            for lo in range(0, n, cap):
                hi = min(lo + cap, n)
                self.dispatch_branch(masks[lo:hi], slots[lo:hi],
                                     self._filter_triples(children, lo, hi))
            return
        masks_p = np.zeros((n + 1,), dtype="<u2")
        masks_p[:n] = masks
        slots_p = np.zeros((n + 1,), dtype=np.int32)
        slots_p[:n] = slots
        c = children.shape[1] if children is not None else 0
        chidx = np.full((c + 1,), n * 16, dtype=np.int32)
        chsrc = np.zeros((c + 1,), dtype=np.int32)
        if c:
            chidx[:c] = children[0] * 16 + children[1]
            chsrc[:c] = children[2]
        mask_o = self._stage_u8(masks_p.view(np.uint8))
        slot_o = self._stage_i32(slots_p)
        chidx_o = self._stage_i32(chidx)
        chsrc_o = self._stage_i32(chsrc)
        self._plan.append(("branch",
                           _pow2(n + 1, floor=self._ROW_FLOOR),
                           _pow2(c + 1, floor=self._HOLE_FLOOR),
                           mask_o, slot_o, chidx_o, chsrc_o, n + 1, c + 1))

    def _buffer_lens(self) -> tuple[int, int]:
        """Final staged lengths: every program's dynamic_slice must fit
        in-bounds (a clamped slice start would silently misalign the level),
        then quantized so buffer-shape variety stays logarithmic."""
        u8_need = self._u8_off
        i32_need = self._i32_off
        for e in self._plan:
            if e[0] == "packed":
                (_, _b, n_pow, h_pow, _f, len_o, slot_o, hidx_o, hsrc_o,
                 _n, _h) = e
                u8_need = max(u8_need, len_o + 2 * n_pow)
                i32_need = max(i32_need, slot_o + n_pow,
                               hidx_o + h_pow, hsrc_o + h_pow)
            else:
                _, n_pow, ch_pow, mask_o, slot_o, chidx_o, chsrc_o, _n, _c = e
                u8_need = max(u8_need, mask_o + 2 * n_pow)
                i32_need = max(i32_need, slot_o + n_pow,
                               chidx_o + ch_pow, chsrc_o + ch_pow)
        return (self._step(u8_need, 1 << 16), self._step(i32_need, 1 << 12))

    def _execute(self) -> None:
        if self._buf is not None:
            return
        u8_len, i32_len = self._buffer_lens()
        u8 = np.zeros((u8_len,), dtype=np.uint8)
        off = 0
        for part in self._u8_parts:
            u8[off:off + part.size] = part
            off += part.size
        i32 = np.zeros((i32_len,), dtype=np.int32)
        off = 0
        for part in self._i32_parts:
            i32[off:off + part.size] = part
            off += part.size
        u8d = self._device_put(u8)
        i32d = self._device_put(i32)
        buf = self._device_put(np.zeros((self._s_tier, 32), dtype=np.uint8))
        s32 = np.int32
        for e in self._plan:
            if e[0] == "packed":
                (_, b_tier, n_pow, h_pow, flat_off, len_o, slot_o, hidx_o,
                 hsrc_o, n_valid, h_valid) = e
                fn = _staged_packed(b_tier, n_pow, h_pow, u8_len, i32_len,
                                    self._s_tier)
                buf = _timed_call(
                    "mega.packed", (b_tier, n_pow, h_pow, u8_len, i32_len),
                    fn, u8d, i32d, buf, s32(flat_off), s32(len_o),
                    s32(slot_o), s32(hidx_o), s32(hsrc_o),
                    s32(n_valid), s32(h_valid))
                self._count_dispatch()
            else:
                (_, n_pow, ch_pow, mask_o, slot_o, chidx_o, chsrc_o,
                 n_valid, c_valid) = e
                fn = _staged_branch(n_pow, ch_pow, u8_len, i32_len,
                                    self._s_tier)
                buf = _timed_call(
                    "mega.branch", (n_pow, ch_pow, u8_len, i32_len),
                    fn, u8d, i32d, buf, s32(mask_o), s32(slot_o),
                    s32(chidx_o), s32(chsrc_o), s32(n_valid),
                    s32(c_valid))
                self._count_dispatch()
        self._buf = buf
        self._plan, self._u8_parts, self._i32_parts = [], [], []

    def finish(self) -> np.ndarray:
        self._execute()
        return super().finish()

    def fetch_slots(self, slots: np.ndarray) -> np.ndarray:
        self._execute()
        return super().fetch_slots(slots)


class FusedMeshEngine(FusedLevelEngine):
    """Fused level commit SPMD-sharded over a 1-axis device mesh.

    Templates/counts/slots shard over the batch axis (each device hashes its
    level shard); the digest buffer is replicated — the scatter of a level's
    sharded digests into the replicated buffer makes XLA insert the
    all-gather (rides ICI on hardware), which is exactly the child-digest
    exchange a multi-chip trie commit needs. This is the committer's real
    level loop over the mesh, not a toy reduction (VERDICT round 1, weak #2).
    """

    def __init__(self, mesh, min_tier: int = 1024):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # ``mesh``: a jax.sharding.Mesh, or a parallel/mesh.py HashMesh
        # descriptor — then the engine snapshots the LIVE sub-mesh at
        # construction (one commit = one membership; a device lost
        # mid-commit is the SupervisedBackend journal-replay's job)
        live_snapshot = getattr(mesh, "live_snapshot", None)
        if live_snapshot is not None:
            mesh, _ = live_snapshot()
            if mesh is None:
                raise RuntimeError("HashMesh has no live devices")
        # every tier must stay divisible by the device count: tiers grow by
        # x4 (batch) / x2 (holes, slots) from their floors, so rounding the
        # floor up to a device-count multiple keeps all of them shardable.
        # self.mesh must be set BEFORE super().__init__: the base class
        # hoists the ladder caps at construction, which asks for
        # _batch_multiple() — the mesh's device count here.
        mult = mesh.devices.size
        self.mesh = mesh
        axis = mesh.axis_names[0]
        self._batch_sharding = NamedSharding(mesh, P(axis))
        self._replicated = NamedSharding(mesh, P())
        super().__init__(min_tier=-(-min_tier // mult) * mult)

    def _device_put(self, arr: np.ndarray):
        return jax.device_put(arr, self._replicated)

    def _put_batch(self, arr: np.ndarray):
        return jax.device_put(arr, self._batch_sharding)

    def _sharding_key(self):
        return self.mesh

    def _batch_multiple(self) -> int:
        return self.mesh.devices.size


# -- whole-subtrie fused kernels (ONE dispatch per k levels) ------------------


class InjectedSubtrieWedge(RuntimeError):
    """Fault injection wedged a k-level fused chunk dispatch
    (RETH_TPU_FAULT_SUBTRIE_WEDGE) — the engine must replay the whole
    staged journal bit-identically on the per-level path."""


class InjectedSubtrieAbort(RuntimeError):
    """Fault injection poisoned the WHOLE device path for this engine
    (RETH_TPU_FAULT_SUBTRIE_ABORT): the fused chunk AND its per-level
    replay both fail, so the commit must land on the CPU twin."""


class SubtrieFaultInjector:
    """Fault policies for the whole-subtrie engine, in the style of
    ``ops/supervisor.py``'s FaultInjector.

    ``wedge_at``: the Nth fused (multi-level) chunk dispatch of the
    process raises :class:`InjectedSubtrieWedge` (one-shot) — the engine
    replays its journal on the per-level path, roots bit-identical.
    ``abort_at``: the Nth chunk dispatch raises AND every subsequent
    per-level replay dispatch raises too — drills the final rung: the
    journal replays on the CPU twin.

    Env form (:meth:`from_env`): ``RETH_TPU_FAULT_SUBTRIE_WEDGE`` /
    ``RETH_TPU_FAULT_SUBTRIE_ABORT``.
    """

    def __init__(self, wedge_at: int = 0, abort_at: int = 0):
        import threading

        self.wedge_at = wedge_at
        self.abort_at = abort_at
        self.chunks = 0
        self.wedges = 0
        self.aborts = 0
        self._abort_armed = False
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env=None) -> "SubtrieFaultInjector | None":
        import os

        env = os.environ if env is None else env
        wedge = int(env.get("RETH_TPU_FAULT_SUBTRIE_WEDGE", "0") or 0)
        abort = int(env.get("RETH_TPU_FAULT_SUBTRIE_ABORT", "0") or 0)
        if not (wedge or abort):
            return None
        return cls(wedge_at=wedge, abort_at=abort)

    def on_chunk(self, mode: str, levels: int) -> None:
        """Called before every subtrie device dispatch. ``mode`` is
        "fused" for k-level chunks and "perlevel" for the fallback
        replay's single-level dispatches."""
        from .. import tracing

        if mode == "perlevel":
            with self._lock:
                armed = self._abort_armed
            if armed:
                tracing.fault_event("RETH_TPU_FAULT_SUBTRIE_ABORT",
                                    target="ops::fused_commit",
                                    rung="perlevel")
                raise InjectedSubtrieAbort(
                    "injected subtrie abort: per-level replay poisoned "
                    f"(RETH_TPU_FAULT_SUBTRIE_ABORT={self.abort_at})")
            return
        with self._lock:
            self.chunks += 1
            n = self.chunks
        if self.wedge_at and n == self.wedge_at:
            with self._lock:
                self.wedges += 1
            tracing.fault_event("RETH_TPU_FAULT_SUBTRIE_WEDGE",
                                target="ops::fused_commit", chunk=n,
                                levels=levels)
            raise InjectedSubtrieWedge(
                f"injected subtrie wedge on chunk #{n} "
                f"(RETH_TPU_FAULT_SUBTRIE_WEDGE={self.wedge_at})")
        if self.abort_at and n == self.abort_at:
            with self._lock:
                self.aborts += 1
                self._abort_armed = True
            tracing.fault_event("RETH_TPU_FAULT_SUBTRIE_ABORT",
                                target="ops::fused_commit", chunk=n,
                                levels=levels)
            raise InjectedSubtrieAbort(
                f"injected subtrie abort on chunk #{n} "
                f"(RETH_TPU_FAULT_SUBTRIE_ABORT={self.abort_at})")


_PARAM_W = 10  # param-table row width (i32): kind + offsets + valid counts


def _ladder_tier(n: int, floor: int, mult: int) -> int:
    """x2 ladder from the ``mult``-rounded floor (stays divisible by the
    mesh device count, mirroring ``FusedMeshEngine``'s tier discipline)."""
    t = -(-max(1, floor) // max(1, mult)) * max(1, mult)
    while t < n:
        t *= 2
    return t


@lru_cache(maxsize=128)
def _subtrie_program(b_tier: int, n_pow: int, h_pow: int, steps_pow: int,
                     u8_len: int, i32_len: int, s_tier: int, mesh=None):
    """ONE compiled program hashing up to ``steps_pow`` staged levels.

    This is the Sakura shape (arxiv 1608.00492): the depth loop runs
    INSIDE the jit — ``lax.fori_loop`` with the resident digest buffer as
    the carry, each step splicing child digests written by earlier steps
    — so a whole k-level chunk costs ONE dispatch instead of one per
    depth. The loop body is traced ONCE (a ``lax.cond`` selecting the
    packed or branch shape per step from the i32 param table), so trace
    and compile size are constant in k — the round-2 mega postmortem
    (every level unrolled → 19 s compile → wedged tunnel) does not recur.
    Static shapes are the chunk-wide (rows, aux, steps) tiers plus the
    staging-buffer lengths; live counts arrive via the param table and
    junk rows/holes mask to the dummy slot, exactly like the per-level
    staged programs — digests for real slots are bit-identical to the
    per-level path by construction.

    ``mesh``: a jax Mesh — the k-level SPMD variant. Staged buffers are
    replicated; the per-step row block gets a sharding constraint over
    the batch axis. The k-level packers keep each subtrie's rows
    contiguous, so row-range shards ≈ subtrie shards: parent composition
    goes through the REPLICATED digest buffer (XLA inserts the
    all-gather), never through a neighbour's row shard.
    """
    L = b_tier * RATE
    constraint = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        constraint = NamedSharding(mesh, P(mesh.axis_names[0], None))

    def _shard(rows):
        if constraint is not None:
            return jax.lax.with_sharding_constraint(rows, constraint)
        return rows

    def packed_step(u8, i32, buf, p):
        flat_off, len_o, slot_o = p[1], p[2], p[3]
        hrow_o, hbyte_o, hsrc_o = p[4], p[5], p[6]
        n_valid, h_valid = p[7], p[8]
        raw = jax.lax.dynamic_slice(u8, (len_o,), (2 * n_pow,))
        raw = raw.reshape(n_pow, 2).astype(jnp.uint32)
        ridx = jnp.arange(n_pow, dtype=jnp.int32)
        vrow = ridx < n_valid
        row_len = jnp.where(vrow, raw[:, 0] | (raw[:, 1] << 8), 0)
        row_off = (jnp.cumsum(row_len) - row_len).astype(jnp.int32)
        counts = (row_len // RATE + 1).astype(jnp.int32)
        slots = jnp.where(
            vrow, jax.lax.dynamic_slice(i32, (slot_o,), (n_pow,)), 0)
        col = jnp.arange(L, dtype=jnp.int32)[None, :]
        idx = jnp.minimum(flat_off + row_off[:, None] + col, u8.shape[0] - 1)
        rows = jnp.where(col < row_len[:, None].astype(jnp.int32), u8[idx], 0)
        rl = row_len[:, None].astype(jnp.int32)
        rows = rows ^ jnp.where(col == rl, 0x01, 0).astype(jnp.uint8)
        last = (counts * RATE - 1)[:, None]
        rows = rows ^ jnp.where(col == last, 0x80, 0).astype(jnp.uint8)
        # splice child digests; junk hole entries retarget the level's
        # always-padding row (row n_valid-1 has row_len 0, slot 0). Hole
        # targets are staged as (row, byte) pairs — NOT row*L+byte — so
        # one chunk-wide L can serve levels staged at different b_tiers.
        hv = jnp.arange(h_pow, dtype=jnp.int32) < h_valid
        hrow = jnp.where(
            hv, jax.lax.dynamic_slice(i32, (hrow_o,), (h_pow,)), n_valid - 1)
        hbyte = jnp.where(
            hv, jax.lax.dynamic_slice(i32, (hbyte_o,), (h_pow,)), 0)
        hsrc = jnp.where(
            hv, jax.lax.dynamic_slice(i32, (hsrc_o,), (h_pow,)), 0)
        dig = buf[hsrc]
        fr = rows.reshape(-1)
        sidx = (hrow * L + hbyte)[:, None] \
            + jnp.arange(32, dtype=jnp.int32)[None, :]
        rows = _shard(
            fr.at[sidx.reshape(-1)].set(dig.reshape(-1)).reshape(n_pow, L))
        d = masked_absorb_words(_bytes_to_words(rows), b_tier, counts)
        return buf.at[slots].set(_digests_to_bytes(d))

    def branch_step(u8, i32, buf, p):
        mask_o, slot_o, chidx_o, chsrc_o = p[1], p[2], p[3], p[4]
        n_valid, ch_valid = p[7], p[8]
        raw = jax.lax.dynamic_slice(u8, (mask_o,), (2 * n_pow,))
        raw = raw.reshape(n_pow, 2).astype(jnp.uint32)
        vrow = jnp.arange(n_pow, dtype=jnp.int32) < n_valid
        masks = jnp.where(vrow, raw[:, 0] | (raw[:, 1] << 8), 0)
        slots = jnp.where(
            vrow, jax.lax.dynamic_slice(i32, (slot_o,), (n_pow,)), 0)
        cv = jnp.arange(h_pow, dtype=jnp.int32) < ch_valid
        crn = jnp.where(
            cv, jax.lax.dynamic_slice(i32, (chidx_o,), (h_pow,)),
            (n_valid - 1) * 16)
        cs = jnp.where(
            cv, jax.lax.dynamic_slice(i32, (chsrc_o,), (h_pow,)), 0)
        return _branch_level(masks.astype(jnp.int32), slots, crn // 16,
                             crn % 16, cs, buf, b_tier=b_tier)

    def run(u8, i32, params, buf, n_steps):
        def body(s, carry):
            p = jax.lax.dynamic_index_in_dim(params, s, axis=0,
                                             keepdims=False)
            return jax.lax.cond(
                p[0] == 0,
                lambda b: packed_step(u8, i32, b, p),
                lambda b: branch_step(u8, i32, b, p),
                carry)
        return jax.lax.fori_loop(0, n_steps, body, buf)

    return jax.jit(run, donate_argnums=3)


class SubtrieFusedEngine(MegaFusedEngine):
    """Whole-subtrie k-level fused engine: ONE device dispatch per chunk
    of k staged levels, not one per depth (ROADMAP item 3).

    Staging follows :class:`MegaFusedEngine` (two H2D uploads per flush,
    tight bytes, zero mid-commit D2H), but execution goes one step
    further: instead of one small program PER level, consecutive staged
    levels group into chunks of ``k`` and each chunk runs as ONE
    :func:`_subtrie_program` dispatch whose depth loop carries the
    resident digest buffer — dispatches per commit drop from O(depth) to
    O(depth / k). ``flush_window()`` lets the rebuild pipeline execute
    each packed window eagerly (the digest buffer stays resident across
    windows), preserving the sweep/hash overlap.

    Degradation ladder (journal-replay based — staging arrays are host
    numpy, retained until the terminal fetch, so replay is exact):

      fused chunks → per-level (the same program at k=1) → CPU twin

    A failed chunk dispatch (watchdog escape, injected
    ``RETH_TPU_FAULT_SUBTRIE_WEDGE``) rebuilds the whole digest buffer by
    replaying the journal per-level; if the device path is gone entirely
    (``RETH_TPU_FAULT_SUBTRIE_ABORT``), the journal replays on the CPU
    twin. Roots are bit-identical on every rung — hashing is
    deterministic and the journal holds every staged byte. An attached
    warm-up manager routes un-warm (fused.subtrie, k, tier, mesh) shapes
    to the per-level path instead of compiling mid-commit.

    Chunking discipline: steps sharing a chunk share ONE static
    (b_tier, rows, aux) shape — the chunk b_tier is the max over its
    steps (capped at ``_CHUNK_BTIER_CAP``; bigger-block levels dispatch
    solo) and row/aux tiers are chunk-wide ladders, so program variety
    stays O(log workload) and padded rows mask to the dummy slot.
    """

    effective_kind = "device"
    _CHUNK_BTIER_CAP = 8

    def __init__(self, min_tier: int = 1024, k: int | None = None,
                 warmup=None, injector=None, row_floor: int | None = None,
                 hole_floor: int | None = None):
        import os as _os

        super().__init__(min_tier=min_tier)
        if k is None:
            k = int(_os.environ.get("RETH_TPU_SUBTRIE_LEVELS", "0") or 8)
        self.k = max(1, int(k))
        self.warmup = warmup
        self.injector = (injector if injector is not None
                         else SubtrieFaultInjector.from_env())
        if row_floor:
            self._ROW_FLOOR = int(row_floor)
        if hole_floor:
            self._HOLE_FLOOR = int(hole_floor)
        self._mode = "fused"
        self._journal: list[tuple[np.ndarray, np.ndarray, list]] = []
        self._buf_np: np.ndarray | None = None
        self.levels_staged = 0
        # delta commits (hot-state arena): the journal only covers THIS
        # epoch, so the internal replay-from-zeros ladder would silently
        # lose prior epochs' resident rows — in delta mode any device
        # fault re-raises and the OWNER (DigestArena) takes the full-
        # upload rung instead (ISSUE 19's external ladder).
        self._delta = False

    # -- mesh seam (overridden by SubtrieMeshEngine) -----------------------

    def _mesh_arg(self):
        return None

    def _mesh_size(self) -> int:
        return 1

    # -- lifecycle ---------------------------------------------------------

    def begin(self, max_slots: int) -> None:
        super().begin(max_slots)
        self._mode = "fused"
        self._journal = []
        self._buf_np = None
        self.levels_staged = 0
        self._delta = False

    def begin_delta(self, max_slots: int) -> None:
        """Open a DELTA commit: keep the resident digest buffer from the
        previous epoch and stage only this epoch's dirty rows (holes may
        splice prior-epoch slots). Preconditions — the engine must still
        be on the fused rung with a materialized buffer; anything else is
        an :class:`ArenaFault` the owner answers with a full upload."""
        if self._mode != "fused" or self._buf is None:
            raise ArenaFault(
                f"delta precondition lost (mode={self._mode}, "
                f"resident={self._buf is not None})")
        self._plan, self._u8_parts, self._i32_parts = [], [], []
        self._u8_off = self._i32_off = 0
        self.dispatches = 0
        self.staged_u8_bytes = 0
        self.staged_i32_bytes = 0
        self._journal = []
        self._buf_np = None
        self.levels_staged = 0
        self._delta = True
        self.ensure(max_slots)

    def ensure(self, max_slots: int) -> None:
        if self._mode == "cpu":
            need = max_slots + 1
            if self._buf_np is not None and self._buf_np.shape[0] >= need:
                return
            tier = _pow2(need, floor=max(self.min_tier, 2, self._s_tier))
            grown = np.zeros((tier, 32), dtype=np.uint8)
            if self._buf_np is not None:
                grown[: self._buf_np.shape[0]] = self._buf_np
            self._buf_np = grown
            self._s_tier = tier
            return
        super().ensure(max_slots)
        if self._buf is not None:
            self._s_tier = int(self._buf.shape[0])

    # -- staging (k-level layout: hole targets as (row, byte) pairs) -------

    def dispatch_packed(self, flat, row_off, row_len, slots, holes, b_tier):
        n = len(row_off)
        if n == 0:
            return
        self._check_block_tier(b_tier)
        if n + 1 > self._row_cap():
            cap = self._row_cap() - 1
            for lo in range(0, n, cap):
                hi = min(lo + cap, n)
                base = int(row_off[lo])
                end = int(row_off[hi - 1] + row_len[hi - 1])
                self.dispatch_packed(
                    flat[base:end], row_off[lo:hi] - base, row_len[lo:hi],
                    slots[lo:hi], self._filter_triples(holes, lo, hi), b_tier)
            return
        row_len_p = np.zeros((n + 1,), dtype="<u2")
        row_len_p[:n] = row_len
        slots_p = np.zeros((n + 1,), dtype=np.int32)
        slots_p[:n] = slots
        h = holes.shape[1] if holes is not None else 0
        hrow = np.full((h + 1,), n, dtype=np.int32)  # dump: the padding row
        hbyte = np.zeros((h + 1,), dtype=np.int32)
        hsrc = np.zeros((h + 1,), dtype=np.int32)
        if h:
            hrow[:h], hbyte[:h], hsrc[:h] = holes[0], holes[1], holes[2]
        flat_off = self._stage_u8(np.asarray(flat, dtype=np.uint8))
        len_o = self._stage_u8(row_len_p.view(np.uint8))
        slot_o = self._stage_i32(slots_p)
        hrow_o = self._stage_i32(hrow)
        hbyte_o = self._stage_i32(hbyte)
        hsrc_o = self._stage_i32(hsrc)
        self._plan.append(("packed", b_tier, flat_off, len_o, slot_o,
                           hrow_o, hbyte_o, hsrc_o, n + 1, h + 1))
        self.levels_staged += 1

    def dispatch_branch(self, masks, slots, children) -> None:
        n = len(masks)
        if n == 0:
            return
        if n + 1 > self._row_cap():
            cap = self._row_cap() - 1
            for lo in range(0, n, cap):
                hi = min(lo + cap, n)
                self.dispatch_branch(masks[lo:hi], slots[lo:hi],
                                     self._filter_triples(children, lo, hi))
            return
        masks_p = np.zeros((n + 1,), dtype="<u2")
        masks_p[:n] = masks
        slots_p = np.zeros((n + 1,), dtype=np.int32)
        slots_p[:n] = slots
        c = children.shape[1] if children is not None else 0
        chidx = np.full((c + 1,), n * 16, dtype=np.int32)
        chsrc = np.zeros((c + 1,), dtype=np.int32)
        if c:
            chidx[:c] = children[0] * 16 + children[1]
            chsrc[:c] = children[2]
        mask_o = self._stage_u8(masks_p.view(np.uint8))
        slot_o = self._stage_i32(slots_p)
        chidx_o = self._stage_i32(chidx)
        chsrc_o = self._stage_i32(chsrc)
        self._plan.append(("branch", mask_o, slot_o, chidx_o, chsrc_o,
                           n + 1, c + 1))
        self.levels_staged += 1

    # -- chunk planning ----------------------------------------------------

    @staticmethod
    def _step_btier(e) -> int:
        return e[1] if e[0] == "packed" else 4

    def _chunk_plan(self, plan: list, k: int) -> list[tuple]:
        """[(entries, b_tier, n_pow, h_pow)] — consecutive steps grouped
        up to ``k`` per chunk; within-a-commit order is the dependency
        order (deeper levels staged first), so consecutive grouping
        preserves parent composition exactly."""
        mult = self._batch_multiple()
        groups: list[list] = []
        cur: list = []
        cur_big = False
        for e in plan:
            big = self._step_btier(e) > self._CHUNK_BTIER_CAP
            if cur and (len(cur) >= k or big or cur_big):
                groups.append(cur)
                cur = []
            cur.append(e)
            cur_big = big
        if cur:
            groups.append(cur)
        chunks = []
        for entries in groups:
            b_tier = max(self._step_btier(e) for e in entries)
            n_pow = _ladder_tier(max(e[-2] for e in entries),
                                 self._ROW_FLOOR, mult)
            h_pow = _ladder_tier(max(e[-1] for e in entries),
                                 self._HOLE_FLOOR, mult)
            chunks.append((entries, b_tier, n_pow, h_pow))
        return chunks

    def _chunk_buffer_lens(self, chunks: list[tuple]) -> tuple[int, int]:
        """Final staged lengths covering every chunk-wide dynamic_slice
        (a clamped slice start would silently misalign a level — the
        chunk-wide row/aux tiers read PAST each level's own staging, so
        the buffers must be long enough for the widest reader)."""
        u8_need = self._u8_off
        i32_need = self._i32_off
        for entries, _b, n_pow, h_pow in chunks:
            for e in entries:
                if e[0] == "packed":
                    (_t, _bt, _f, len_o, slot_o, hrow_o, hbyte_o, hsrc_o,
                     _n, _h) = e
                    u8_need = max(u8_need, len_o + 2 * n_pow)
                    i32_need = max(i32_need, slot_o + n_pow,
                                   hrow_o + h_pow, hbyte_o + h_pow,
                                   hsrc_o + h_pow)
                else:
                    _t, mask_o, slot_o, chidx_o, chsrc_o, _n, _c = e
                    u8_need = max(u8_need, mask_o + 2 * n_pow)
                    i32_need = max(i32_need, slot_o + n_pow,
                                   chidx_o + h_pow, chsrc_o + h_pow)
        return (self._step(u8_need, 1 << 16), self._step(i32_need, 1 << 12))

    # -- execution ---------------------------------------------------------

    def flush_window(self) -> None:
        """Execute everything staged so far (the rebuild pipeline calls
        this per packed window, so device hashing overlaps the next
        window's native sweep). The digest buffer stays resident."""
        self._execute()

    def _execute(self) -> None:
        plan = self._plan
        if not plan:
            if (self._mode != "cpu" and self._buf is None
                    and self._buf_np is None):
                self._buf = self._device_put(
                    np.zeros((self._s_tier, 32), dtype=np.uint8))
            return
        k_plan = 1 if self._mode == "perlevel" else self.k
        chunks = self._chunk_plan(plan, k_plan)
        u8_len, i32_len = self._chunk_buffer_lens(chunks)
        u8 = np.zeros((u8_len,), dtype=np.uint8)
        off = 0
        for part in self._u8_parts:
            u8[off:off + part.size] = part
            off += part.size
        i32 = np.zeros((i32_len,), dtype=np.int32)
        off = 0
        for part in self._i32_parts:
            i32[off:off + part.size] = part
            off += part.size
        self._plan, self._u8_parts, self._i32_parts = [], [], []
        self._u8_off = self._i32_off = 0
        # the journal IS the failover: replay is exact because every
        # staged byte is retained until the terminal fetch
        self._journal.append((u8, i32, plan))
        if self._mode == "cpu":
            self._run_plan_numpy(u8, i32, plan)
            return
        if self._buf is None:
            self._buf = self._device_put(
                np.zeros((self._s_tier, 32), dtype=np.uint8))
        mult = self._batch_multiple()
        route_tier = -(-self._ROW_FLOOR // mult) * mult
        if (self._mode == "fused" and self.k > 1 and self.warmup is not None
                and not self.warmup.route_bucket(
                    "fused.subtrie", self.k, route_tier,
                    self._mesh_size())):
            # degraded routing: the k-shape isn't warm — this flush runs
            # per-level (same staged bytes, k=1 chunks); the engine stays
            # on "fused" so later flushes promote once the shape warms
            from ..metrics import fused_metrics

            fused_metrics.record_fallback()
            chunks = self._chunk_plan(plan, 1)
        mode = "perlevel" if (self._mode == "perlevel"
                              or len(chunks) >= len(plan)) else "fused"
        try:
            self._run_chunks(u8, i32, chunks, u8_len, i32_len, mode)
        except BaseException as e:  # noqa: BLE001 — degraded below
            if self._delta:
                raise  # external ladder: the arena owner full-uploads
            self._degrade(e)

    def _run_chunks(self, u8: np.ndarray, i32: np.ndarray, chunks: list,
                    u8_len: int, i32_len: int, mode: str) -> None:
        u8d = self._device_put(u8)
        i32d = self._device_put(i32)
        s_tier = int(self._buf.shape[0])
        for entries, b_tier, n_pow, h_pow in chunks:
            steps_pow = _pow2(len(entries), floor=8)
            params = np.zeros((steps_pow, _PARAM_W), dtype=np.int32)
            for i, e in enumerate(entries):
                if e[0] == "packed":
                    (_t, _bt, flat_off, len_o, slot_o, hrow_o, hbyte_o,
                     hsrc_o, n_valid, h_valid) = e
                    params[i] = (0, flat_off, len_o, slot_o, hrow_o,
                                 hbyte_o, hsrc_o, n_valid, h_valid, 0)
                else:
                    _t, mask_o, slot_o, chidx_o, chsrc_o, n_valid, c_valid = e
                    params[i] = (1, mask_o, slot_o, chidx_o, chsrc_o, 0, 0,
                                 n_valid, c_valid, 0)
            if self.injector is not None:
                self.injector.on_chunk(mode, len(entries))
            fn = _subtrie_program(b_tier, n_pow, h_pow, steps_pow,
                                  u8_len, i32_len, s_tier, self._mesh_arg())
            self._buf = _timed_call(
                "fused.subtrie",
                (b_tier, n_pow, h_pow, steps_pow, u8_len, i32_len,
                 self._mesh_size()),
                fn, u8d, i32d, self._device_put(params), self._buf,
                np.int32(len(entries)))
            self._count_dispatch(len(entries))

    # -- degradation ladder ------------------------------------------------

    def _degrade(self, err: BaseException) -> None:
        from .. import tracing
        from ..metrics import fused_metrics

        fused_metrics.record_fallback()
        if self._mode == "fused":
            tracing.fault_event("subtrie_fallback",
                                target="ops::fused_commit",
                                rung="perlevel",
                                error=f"{type(err).__name__}: {err}"[:200])
            self._mode = "perlevel"
            try:
                self._replay_journal_device()
                return
            except BaseException as e2:  # noqa: BLE001 — final rung below
                fused_metrics.record_fallback()
                err = e2
        tracing.fault_event("subtrie_fallback", target="ops::fused_commit",
                            rung="cpu",
                            error=f"{type(err).__name__}: {err}"[:200])
        self._mode = "cpu"
        self._buf = None
        self._buf_np = np.zeros((self._s_tier, 32), dtype=np.uint8)
        for u8, i32, plan in self._journal:
            self._run_plan_numpy(u8, i32, plan)

    def _replay_journal_device(self) -> None:
        """Per-level rung: rebuild the digest buffer by replaying EVERY
        journaled flush through the same program at k=1 (hashing is
        deterministic, so the rebuilt buffer is bit-identical)."""
        self._buf = self._device_put(
            np.zeros((self._s_tier, 32), dtype=np.uint8))
        for u8, i32, plan in self._journal:
            chunks = self._chunk_plan(plan, 1)
            self._run_chunks(u8, i32, chunks, u8.size, i32.size, "perlevel")

    def _run_plan_numpy(self, u8: np.ndarray, i32: np.ndarray,
                        plan: list) -> None:
        """CPU-twin rung: interpret the staged plan with the numpy
        backend's own level math (bit-identical to the device path)."""
        from ..trie.turbo import _NumpyBackend

        nb = _NumpyBackend()
        nb._buf = self._buf_np
        for e in plan:
            if e[0] == "packed":
                (_t, b_tier, flat_off, len_o, slot_o, hrow_o, hbyte_o,
                 hsrc_o, n_valid, h_valid) = e
                n = n_valid - 1
                raw = u8[len_o:len_o + 2 * n].astype(np.uint32)
                row_len = (raw[0::2] | (raw[1::2] << 8)).astype(np.uint32)
                row_off = (np.cumsum(row_len) - row_len).astype(np.uint32)
                slots = i32[slot_o:slot_o + n].astype(np.int64)
                total = int(row_off[-1] + row_len[-1]) if n else 0
                flat = u8[flat_off:flat_off + total]
                h = h_valid - 1
                holes = None
                if h:
                    holes = (i32[hrow_o:hrow_o + h],
                             i32[hbyte_o:hbyte_o + h],
                             i32[hsrc_o:hsrc_o + h])
                nb.dispatch_packed(flat, row_off, row_len, slots, holes,
                                   b_tier)
            else:
                _t, mask_o, slot_o, chidx_o, chsrc_o, n_valid, c_valid = e
                n = n_valid - 1
                raw = u8[mask_o:mask_o + 2 * n].astype(np.uint16)
                masks = (raw[0::2] | (raw[1::2] << 8)).astype(np.uint16)
                slots = i32[slot_o:slot_o + n].astype(np.int64)
                c = c_valid - 1
                crn = i32[chidx_o:chidx_o + c]
                children = np.stack([crn // 16, crn % 16,
                                     i32[chsrc_o:chsrc_o + c]])
                nb.dispatch_branch(masks, slots, children)

    # -- terminal fetches --------------------------------------------------

    def _record_commit(self) -> None:
        from ..metrics import fused_metrics

        fused_metrics.record_commit(dispatches=self.dispatches,
                                    levels=self.levels_staged, k=self.k,
                                    mode=self._mode)

    def finish(self) -> np.ndarray:
        self._execute()
        self._record_commit()
        if self._mode == "cpu":
            buf, self._buf_np = self._buf_np, None
            self._journal = []
            return buf
        self._journal = []
        return FusedLevelEngine.finish(self)

    def fetch_slots(self, slots: np.ndarray) -> np.ndarray:
        self._execute()
        self._record_commit()
        if self._mode == "cpu":
            out = self._buf_np[np.asarray(slots, dtype=np.int64)]
            self._buf_np = None
            self._journal = []
            return out
        self._journal = []
        return FusedLevelEngine.fetch_slots(self, slots)

    def peek_slots(self, slots: np.ndarray) -> np.ndarray:
        """Small D2H like :meth:`fetch_slots`, but the digest buffer stays
        RESIDENT — the terminal fetch of a delta epoch (the rows live on
        so later epochs can hole-splice them)."""
        self._execute()
        self._record_commit()
        self._journal = []
        if self._mode == "cpu":  # defensive: delta never degrades to cpu
            return self._buf_np[np.asarray(slots, dtype=np.int64)].copy()
        ids = np.zeros((_pow2(max(len(slots), 1), floor=8),), dtype=np.int32)
        ids[: len(slots)] = slots
        out = np.asarray(jnp.take(self._buf, self._device_put(ids), axis=0))
        return out[: len(slots)]


class SubtrieMeshEngine(SubtrieFusedEngine):
    """k-level fused commit over a device mesh: the staged buffers and
    the resident digest buffer are replicated, and each step's row block
    carries a batch-axis sharding constraint. The k-level packers keep a
    subtrie's rows contiguous (``_pack_window`` concatenates per sweep),
    so row-range shards approximate shard-by-subtrie — and parent
    composition always reads the REPLICATED digest buffer, so it never
    crosses a row shard regardless of placement (the all-gather XLA
    inserts after each step's scatter is the only communication)."""

    def __init__(self, mesh, min_tier: int = 1024, k: int | None = None,
                 warmup=None, injector=None, row_floor: int | None = None,
                 hole_floor: int | None = None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        live_snapshot = getattr(mesh, "live_snapshot", None)
        if live_snapshot is not None:
            mesh, _ = live_snapshot()
            if mesh is None:
                raise RuntimeError("HashMesh has no live devices")
        mult = mesh.devices.size
        self.mesh = mesh
        self._replicated = NamedSharding(mesh, P())
        super().__init__(min_tier=-(-min_tier // mult) * mult, k=k,
                         warmup=warmup, injector=injector,
                         row_floor=row_floor, hole_floor=hole_floor)

    def _device_put(self, arr: np.ndarray):
        return jax.device_put(arr, self._replicated)

    def _batch_multiple(self) -> int:
        return self.mesh.devices.size

    def _mesh_arg(self):
        return self.mesh

    def _mesh_size(self) -> int:
        return self.mesh.devices.size


# -- hot-state plane, device half: the persistent digest arena ----------------


class ArenaFault(RuntimeError):
    """A delta-commit precondition or device fault under the hot-state
    arena — NEVER handled inside the engine (the journal only covers the
    current epoch, so the internal replay ladder cannot rebuild resident
    rows). The arena owner catches it, evicts, and re-runs the commit on
    the classic full-upload path (then per-level, then the CPU twin —
    the same ladder as before, entered one rung higher)."""


class DigestArena:
    """Epoch-tagged registry of digest rows resident in ONE persistent
    :class:`SubtrieFusedEngine` across blocks — the hot-state plane's
    device half (ISSUE 19; SonicDB S6's commitment-structure residency).

    The classic sparse finish builds a throwaway engine per commit: every
    block re-stages and re-uploads its whole dirty set and the buffer
    dies with ``finish()``. Under the arena the engine (and its device
    buffer) survives: slots are allocated monotonically across epochs,
    ``_slot_of`` maps node digest -> (slot, last_live_epoch), and a new
    epoch's templates hole-splice resident slots for unchanged sibling
    digests instead of treating the buffer as empty. The terminal fetch
    is :meth:`SubtrieFusedEngine.peek_slots` (this epoch's rows only),
    which keeps the buffer resident.

    Safety ladder (roots bit-identical on every rung):

    - ``begin_delta`` refuses unless the engine is still on the fused
      rung with a materialized buffer (:class:`ArenaFault`);
    - any device fault during a delta epoch re-raises out of the engine
      (``_delta`` external ladder) — :meth:`on_fault` evicts wholesale
      and the commit re-runs on the full-upload path;
    - rows idle for ``max_epoch_age`` epochs are retired at lookup, and
      the whole arena evicts when ``next_slot`` outgrows ``max_rows`` —
      so the buffer is bounded and the leak invariant
      ``leaked_rows() == 0`` (every allocated row is registered or
      retired) is checkable after every epoch (the chaos cache dimension
      asserts it post-storm).

    Single-writer: concurrent sparse finishes (speculation leg, the
    continuous producer) contend via :meth:`try_acquire`; the loser just
    takes the classic path for that block.
    """

    def __init__(self, max_rows: int = 1 << 20, max_epoch_age: int = 64):
        self.max_rows = max(1024, int(max_rows))
        self.max_epoch_age = max(1, int(max_epoch_age))
        self.engine: SubtrieFusedEngine | None = None
        self.epoch = 0
        self.next_slot = 1  # slot 0 = the engines' dummy slot
        self._slot_of: dict[bytes, tuple[int, int]] = {}
        self.retired = 0
        self._commit_lock = threading.Lock()
        # counters (mirrored into hotstate_* metrics by the committer)
        self.resident_hits = 0
        self.lookup_misses = 0
        self.evictions = 0
        self.faults = 0
        self.delta_epochs = 0
        self.full_epochs = 0
        self.contended = 0

    @classmethod
    def from_env(cls, env=None) -> "DigestArena":
        env = os.environ if env is None else env
        return cls(
            max_rows=int(env.get("RETH_TPU_HOT_ARENA_ROWS", "0")
                         or (1 << 20)),
            max_epoch_age=int(env.get("RETH_TPU_HOT_ARENA_EPOCHS", "0")
                              or 64))

    # -- single-writer seam ------------------------------------------------

    def try_acquire(self) -> bool:
        if self._commit_lock.acquire(blocking=False):
            return True
        self.contended += 1
        return False

    def release(self) -> None:
        self._commit_lock.release()

    # -- epoch lifecycle ---------------------------------------------------

    def begin_epoch(self, evict_storm: bool = False) -> bool:
        """Open a commit epoch; True = the arena is empty and this epoch
        must be a FULL upload (``engine.begin``), False = delta."""
        self.epoch += 1
        if evict_storm:
            self.evict("evict_storm")
        elif self.next_slot >= self.max_rows:
            self.evict("max_rows")
        fresh = self.next_slot == 1 or self.engine is None
        if fresh:
            self.full_epochs += 1
        else:
            self.delta_epochs += 1
        return fresh

    def evict(self, reason: str = "") -> None:
        """Wholesale eviction: drop the engine (and its device buffer)
        and forget every registered row — the next epoch full-uploads."""
        self.engine = None
        self._slot_of.clear()
        self.next_slot = 1
        self.retired = 0
        self.evictions += 1
        if reason:
            from .. import tracing

            tracing.fault_event("hotstate_arena_evict",
                                target="ops::fused_commit", reason=reason,
                                epoch=self.epoch)

    def invalidate(self, reason: str = "") -> None:
        """Tree-side wholesale invalidation (deep reorg / reorg storm):
        waits out any in-flight commit, then evicts — the same stand-down
        that parks the preserved trie and clears the node cache."""
        with self._commit_lock:
            self.evict(reason)

    def on_fault(self, err: BaseException) -> None:
        """A delta epoch died mid-flight (device fault, ArenaFault, any
        exception out of the committer's arena path): count it, evict —
        the caller re-runs the SAME commit on the full-upload path."""
        self.faults += 1
        from .. import tracing
        from ..metrics import fused_metrics

        fused_metrics.record_fallback()
        tracing.fault_event("hotstate_arena_fault",
                            target="ops::fused_commit",
                            error=f"{type(err).__name__}: {err}"[:200],
                            epoch=self.epoch)
        self.evict("fault")

    # -- row registry ------------------------------------------------------

    def alloc(self) -> int:
        slot = self.next_slot
        self.next_slot += 1
        return slot

    def lookup(self, digest: bytes) -> int:
        """Resident slot for ``digest`` (0 = not resident). Rows idle for
        ``max_epoch_age`` epochs retire here; hits refresh the tag."""
        ent = self._slot_of.get(digest)
        if ent is None:
            self.lookup_misses += 1
            return 0
        slot, last = ent
        if self.epoch - last > self.max_epoch_age:
            del self._slot_of[digest]
            self.retired += 1
            self.lookup_misses += 1
            return 0
        self._slot_of[digest] = (slot, self.epoch)
        self.resident_hits += 1
        return slot

    def note(self, digest: bytes, slot: int) -> None:
        """Register this epoch's freshly hashed row; a duplicate digest
        retires the superseded slot (the leak invariant's other half)."""
        old = self._slot_of.get(digest)
        if old is not None and old[0] != slot:
            self.retired += 1
        self._slot_of[digest] = (slot, self.epoch)

    def leaked_rows(self) -> int:
        """Allocated-but-unaccounted rows; 0 is an invariant the chaos
        cache dimension asserts after every storm."""
        return self.next_slot - 1 - len(self._slot_of) - self.retired

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "epoch": self.epoch, "resident_rows": len(self._slot_of),
            "next_slot": self.next_slot, "retired": self.retired,
            "leaked_rows": self.leaked_rows(),
            "resident_hits": self.resident_hits,
            "lookup_misses": self.lookup_misses,
            "evictions": self.evictions, "faults": self.faults,
            "delta_epochs": self.delta_epochs,
            "full_epochs": self.full_epochs, "contended": self.contended,
        }
