"""Fused multi-level trie commit — child digests stay in HBM between levels.

The round-1 committer paid one host↔device round trip per trie depth level:
host RLP-encodes a level (needs child digests), uploads, hashes, downloads
digests, repeats. Over the axon tunnel (~60 ms D2H latency floor) a 10-level
commit burned ~0.6 s in latency alone. This module removes every mid-commit
D2H:

- The host builds per-level **RLP byte templates**: complete node RLP with
  zero-filled 32-byte *holes* where a hashed child's digest goes. Crucially
  this needs NO digest values — whether a child is inlined (<32 B RLP) or
  hashed (0xa0 + 32-byte ref) depends only on lengths, so the template and
  every hole offset are host-computable bottom-up without syncing.
- The device keeps a resident **digest buffer** (S, 32) u8 in HBM. Each
  level dispatch gathers child digests from the buffer, scatter-splices
  them into the level's templates, runs the masked keccak absorb, and
  scatters the level's digests back into the buffer. Dispatches chain
  through the donated buffer, so XLA executes them in order and the host
  never blocks — template building for level d-1 overlaps device hashing
  of level d.
- ONE D2H at the end (the digest buffer) yields every node hash.

Shape discipline (compile-count bounded, see memory: axon-tunnel-pitfalls):
batch tiers grow x4 from ``min_tier``; block tiers are {2, 4, 8, ...}; the
hole tier is fixed at 4x the batch tier (levels with more holes are split
across dispatches). Program count for a bench-style workload with a single
forced batch tier is <=3.

Reference analogue: the rayon subtrie hash loop
(crates/trie/sparse/src/arena/mod.rs:2500-2548) and the per-level batching
seam this replaces (crates/stages/stages/src/stages/hashing_account.rs:29-32).
"""

from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp

from ..primitives.keccak import RATE
from ..trie.node import HASH_REF_HOLE  # noqa: F401  (re-export; defined jax-free)
from .keccak_jax import masked_absorb_words


def _bytes_to_words(t):
    """(N, L) u8 templates -> (N, L//4) u32 little-endian lane words."""
    w = t.reshape(t.shape[0], -1, 4).astype(jnp.uint32)
    return w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)


def _digests_to_bytes(d):
    """(N, 8) u32 digests -> (N, 32) u8 (little-endian per word)."""
    b = jnp.stack([(d >> (8 * k)) & 0xFF for k in range(4)], axis=-1)
    return b.astype(jnp.uint8).reshape(d.shape[0], 32)


def _plain_level(templates, counts, slots, digest_buf, *, b_tier: int):
    d = masked_absorb_words(_bytes_to_words(templates), b_tier, counts)
    return digest_buf.at[slots].set(_digests_to_bytes(d))


def _splice_level(
    templates, counts, hole_node, hole_byte, hole_src, slots, digest_buf, *, b_tier: int
):
    L = b_tier * RATE
    dig = digest_buf[hole_src]  # (H, 32) u8 gather from resident buffer
    flat = templates.reshape(-1)
    idx = (hole_node * L + hole_byte)[:, None] + jnp.arange(32, dtype=jnp.int32)[None, :]
    flat = flat.at[idx.reshape(-1)].set(dig.reshape(-1))
    d = masked_absorb_words(_bytes_to_words(flat.reshape(templates.shape)), b_tier, counts)
    return digest_buf.at[slots].set(_digests_to_bytes(d))


@lru_cache(maxsize=None)
def _jitted(kind: str, b_tier: int, sharding_key=None):
    """One compiled program per (kind, block tier); shapes add tiers via the
    caller's padding. ``sharding_key`` is an opaque hashable handle the mesh
    layer uses to get distinctly-sharded variants (see ``FusedMeshEngine``)."""
    fn = {"plain": _plain_level, "splice": _splice_level}[kind]
    donate = {"plain": 3, "splice": 6}[kind]
    return jax.jit(partial(fn, b_tier=b_tier), donate_argnums=donate)


def _tier(n: int, min_tier: int, growth: int = 4) -> int:
    t = min_tier
    while t < n:
        t *= growth
    return t


def _pow2(n: int, floor: int = 2) -> int:
    t = floor
    while t < n:
        t *= 2
    return t


class _Bucket:
    """One pending device dispatch: rows of equal-ish shape within a level."""

    __slots__ = ("templates", "counts", "slots", "holes", "nb_max")

    def __init__(self):
        self.templates: list[bytes] = []
        self.counts: list[int] = []
        self.slots: list[int] = []
        self.holes: list[tuple[int, int, int]] = []  # (row, byte_off, src_slot)
        self.nb_max = 1

    def add(self, template: bytes, nb: int, slot: int, holes) -> None:
        row = len(self.templates)
        self.templates.append(template)
        self.counts.append(nb)
        self.slots.append(slot)
        self.nb_max = max(self.nb_max, nb)
        for byte_off, src_slot in holes:
            self.holes.append((row, byte_off, src_slot))


class FusedLevelEngine:
    """Device-resident digest buffer + per-level dispatch.

    Usage: ``begin(max_slots)`` → repeated ``dispatch_level(bucket)`` deepest
    level first → ``finish()`` returns the (S, 32) numpy digest array (the
    single D2H of the whole commit). Slot 0 is a reserved dummy target for
    padding rows.
    """

    # hole budget per dispatch = _HOLE_FACTOR * batch tier; levels with more
    # holes (branch-heavy near-root levels) are split across dispatches
    _HOLE_FACTOR = 4
    # row cap per dispatch: keeps flat byte indices (row * L + off) well
    # under 2^31 — scatter indices are int32 on the TPU, and a silent wrap
    # would drop splices and corrupt roots (2^21 rows * 544 B = 2^30.09)
    _MAX_ROWS = 1 << 21

    def __init__(self, min_tier: int = 1024):
        self.min_tier = min_tier
        self._buf = None
        self._n_slots = 0

    # -- lifecycle ---------------------------------------------------------

    def begin(self, max_slots: int) -> None:
        s_tier = _pow2(max_slots + 1, floor=max(self.min_tier, 2))
        self._buf = self._device_put(np.zeros((s_tier, 32), dtype=np.uint8))
        self._n_slots = 1  # slot 0 = dummy

    def alloc_slot(self) -> int:
        slot = self._n_slots
        self._n_slots += 1
        return slot

    def finish(self) -> np.ndarray:
        buf, self._buf = self._buf, None
        return np.asarray(buf)

    # -- mesh seam (overridden by FusedMeshEngine) -------------------------

    def _device_put(self, arr: np.ndarray):
        return jnp.asarray(arr)

    def _put_batch(self, arr: np.ndarray):
        return jnp.asarray(arr)

    def _sharding_key(self):
        return None

    def _batch_multiple(self) -> int:
        return 1

    # -- dispatch ----------------------------------------------------------

    def dispatch_level(self, bucket: _Bucket) -> None:
        """Queue one level bucket on the device (async, no sync)."""
        n = len(bucket.templates)
        if n == 0:
            return
        b_tier = _pow2(bucket.nb_max, floor=2)
        hole_budget = self._HOLE_FACTOR * _tier(n + 1, self.min_tier)
        over_holed = bucket.holes and len(bucket.holes) > hole_budget
        if over_holed or n + 1 > self._MAX_ROWS:
            for part in self._split(bucket, hole_budget):
                self._dispatch_one(part, b_tier)
            return
        self._dispatch_one(bucket, b_tier)

    def _split(self, bucket: _Bucket, hole_budget: int):
        """Split an oversized bucket by rows; within-level order is free."""
        holes_by_row: dict[int, list[tuple[int, int]]] = {}
        for row, off, src in bucket.holes:
            holes_by_row.setdefault(row, []).append((off, src))
        part = _Bucket()
        for row in range(len(bucket.templates)):
            row_holes = holes_by_row.get(row, [])
            if part.templates and (
                len(part.holes) + len(row_holes) > hole_budget
                or len(part.templates) + 2 > self._MAX_ROWS
            ):
                yield part
                part = _Bucket()
            part.add(bucket.templates[row], bucket.counts[row], bucket.slots[row], row_holes)
        if part.templates:
            yield part

    def _dispatch_one(self, bucket: _Bucket, b_tier: int) -> None:
        n = len(bucket.templates)
        mult = self._batch_multiple()
        n_tier = _tier(max(n + 1, mult), max(self.min_tier, mult), growth=4)
        L = b_tier * RATE

        templates = np.zeros((n_tier, L), dtype=np.uint8)
        for i, t in enumerate(bucket.templates):
            tl = len(t)
            templates[i, :tl] = np.frombuffer(t, dtype=np.uint8)
            # keccak multi-rate padding at the message's own final block
            templates[i, tl] ^= 0x01
            templates[i, bucket.counts[i] * RATE - 1] ^= 0x80
        counts = np.zeros((n_tier,), dtype=np.int32)
        counts[:n] = bucket.counts
        counts[n:] = 1  # padding rows absorb one zero block into dummy slot 0
        slots = np.zeros((n_tier,), dtype=np.int32)
        slots[:n] = bucket.slots

        key = self._sharding_key()
        if not bucket.holes:
            fn = _jitted("plain", b_tier, key)
            self._buf = fn(
                self._put_batch(templates), self._put_batch(counts),
                self._put_batch(slots), self._buf,
            )
            return
        h_tier = _pow2(len(bucket.holes), floor=self._HOLE_FACTOR * self.min_tier)
        hole_node = np.full((h_tier,), n, dtype=np.int32)  # padding row target
        hole_byte = np.zeros((h_tier,), dtype=np.int32)
        hole_src = np.zeros((h_tier,), dtype=np.int32)
        for i, (row, off, src) in enumerate(bucket.holes):
            hole_node[i] = row
            hole_byte[i] = off
            hole_src[i] = src
        fn = _jitted("splice", b_tier, key)
        self._buf = fn(
            self._put_batch(templates), self._put_batch(counts),
            self._put_batch(hole_node), self._put_batch(hole_byte),
            self._put_batch(hole_src), self._put_batch(slots), self._buf,
        )


class FusedMeshEngine(FusedLevelEngine):
    """Fused level commit SPMD-sharded over a 1-axis device mesh.

    Templates/counts/slots shard over the batch axis (each device hashes its
    level shard); the digest buffer is replicated — the scatter of a level's
    sharded digests into the replicated buffer makes XLA insert the
    all-gather (rides ICI on hardware), which is exactly the child-digest
    exchange a multi-chip trie commit needs. This is the committer's real
    level loop over the mesh, not a toy reduction (VERDICT round 1, weak #2).
    """

    def __init__(self, mesh, min_tier: int = 1024):
        from jax.sharding import NamedSharding, PartitionSpec as P

        # every tier must stay divisible by the device count: tiers grow by
        # x4 (batch) / x2 (holes, slots) from their floors, so rounding the
        # floor up to a device-count multiple keeps all of them shardable
        mult = mesh.devices.size
        super().__init__(min_tier=-(-min_tier // mult) * mult)
        self.mesh = mesh
        axis = mesh.axis_names[0]
        self._batch_sharding = NamedSharding(mesh, P(axis))
        self._replicated = NamedSharding(mesh, P())

    def _device_put(self, arr: np.ndarray):
        return jax.device_put(arr, self._replicated)

    def _put_batch(self, arr: np.ndarray):
        return jax.device_put(arr, self._batch_sharding)

    def _sharding_key(self):
        return self.mesh

    def _batch_multiple(self) -> int:
        return self.mesh.devices.size
