"""BlockchainTests runner: JSON fixture -> fresh node -> replay -> verify.

Mirrors the reference's flow (testing/ef-tests/src/cases/blockchain_test.rs):
init a throwaway provider from ``pre`` + genesis header, decode each
block's RLP, run the real pipeline (execution + hashing + Merkle stages,
so the state root in every header is recomputed from the trie, not
trusted), then check ``lastblockhash`` and the ``postState`` account
values. ``expectException`` blocks must fail import/validation.
"""

from __future__ import annotations

import json

from ..consensus.validation import ConsensusError, EthBeaconConsensus
from ..primitives.keccak import keccak256
from ..primitives.types import Account, Block, Header
from ..stages import default_stages
from ..stages.api import Pipeline, StageError
from ..storage.genesis import GenesisMismatch, import_chain, init_genesis
from ..storage.kv import MemDb
from ..storage.provider import ProviderFactory


class ConformanceFailure(AssertionError):
    pass


def _int(v) -> int:
    if isinstance(v, int):
        return v
    return int(v, 16) if v.startswith("0x") else int(v)


def _bytes(v: str) -> bytes:
    return bytes.fromhex(v[2:] if v.startswith("0x") else v)


def _b32(v) -> bytes:
    return _int(v).to_bytes(32, "big")


def header_from_json(h: dict) -> Header:
    """ef-tests header field names -> Header."""
    kw = dict(
        parent_hash=_bytes(h["parentHash"]),
        ommers_hash=_bytes(h["uncleHash"]),
        beneficiary=_bytes(h["coinbase"]),
        state_root=_bytes(h["stateRoot"]),
        transactions_root=_bytes(h["transactionsTrie"]),
        receipts_root=_bytes(h["receiptTrie"]),
        logs_bloom=_bytes(h["bloom"]),
        difficulty=_int(h["difficulty"]),
        number=_int(h["number"]),
        gas_limit=_int(h["gasLimit"]),
        gas_used=_int(h["gasUsed"]),
        timestamp=_int(h["timestamp"]),
        extra_data=_bytes(h["extraData"]),
        mix_hash=_bytes(h["mixHash"]),
        nonce=_bytes(h["nonce"]),
    )
    if "baseFeePerGas" in h:
        kw["base_fee_per_gas"] = _int(h["baseFeePerGas"])
    if "withdrawalsRoot" in h:
        kw["withdrawals_root"] = _bytes(h["withdrawalsRoot"])
    if "blobGasUsed" in h:
        kw["blob_gas_used"] = _int(h["blobGasUsed"])
    if "excessBlobGas" in h:
        kw["excess_blob_gas"] = _int(h["excessBlobGas"])
    if "parentBeaconBlockRoot" in h:
        kw["parent_beacon_block_root"] = _bytes(h["parentBeaconBlockRoot"])
    if "requestsHash" in h:
        kw["requests_hash"] = _bytes(h["requestsHash"])
    return Header(**kw)


def _parse_pre(pre: dict):
    alloc: dict[bytes, Account] = {}
    storage: dict[bytes, dict[bytes, int]] = {}
    codes: dict[bytes, bytes] = {}
    for addr_hex, acct in pre.items():
        addr = _bytes(addr_hex)
        code = _bytes(acct.get("code", "0x") or "0x")
        code_hash = keccak256(code)
        alloc[addr] = Account(
            nonce=_int(acct.get("nonce", "0x0")),
            balance=_int(acct.get("balance", "0x0")),
            code_hash=code_hash,
        )
        if code:
            codes[code_hash] = code
        slots = {
            _b32(k): _int(v)
            for k, v in acct.get("storage", {}).items()
            if _int(v) != 0
        }
        if slots:
            storage[addr] = slots
    return alloc, storage, codes


def run_blockchain_test(name: str, case: dict, committer=None) -> None:
    """Run one BlockchainTests case; raises ConformanceFailure on mismatch."""
    if committer is None:
        from ..primitives.keccak import keccak256_batch_np
        from ..trie.committer import TrieCommitter

        committer = TrieCommitter(hasher=keccak256_batch_np)
    alloc, storage, codes = _parse_pre(case["pre"])
    genesis = header_from_json(case["genesisBlockHeader"])
    factory = ProviderFactory(MemDb())
    try:
        ghash = init_genesis(factory, genesis, alloc, storage, codes,
                             committer=committer)
    except GenesisMismatch as e:
        raise ConformanceFailure(f"{name}: genesis init failed: {e}") from e
    declared = case["genesisBlockHeader"].get("hash")
    if declared and ghash != _bytes(declared):
        raise ConformanceFailure(
            f"{name}: genesis hash {ghash.hex()} != declared {declared}"
        )

    # the network label pins the rule set (reference ForkSpec): every
    # block executes and validates under exactly that fork
    chainspec = None
    network = case.get("network")
    if network:
        from ..chainspec import NETWORK_TO_FORK, pinned_spec

        fork = NETWORK_TO_FORK.get(network)
        if fork is None:
            raise ConformanceFailure(f"{name}: unknown network {network!r}")
        chainspec = pinned_spec(fork)
    from ..evm import EvmConfig

    evm_config = EvmConfig(chain_id=1, chainspec=chainspec)
    consensus = EthBeaconConsensus(committer, chainspec=chainspec)

    def _stages():
        return default_stages(committer=committer, consensus=consensus,
                              evm_config=evm_config)

    pipeline = Pipeline(factory, _stages())

    def _fork():
        """Throwaway copy of the chain state: an expectException block is
        tried against the fork so a PARTIAL import (e.g. body written,
        Merkle stage rejects the root) can never corrupt the canonical
        progression the remaining blocks replay on (the official harness
        rolls invalid blocks back the same way). MemDb's MVCC makes this
        an O(#tables) fork — published table dicts are immutable; writers
        clone on first touch — so no deep copy is needed."""
        db = MemDb()
        db._tables = dict(factory.db._tables)
        return ProviderFactory(db)

    for i, blk in enumerate(case.get("blocks", ())):
        expect_fail = "expectException" in blk
        run_factory = _fork() if expect_fail else factory
        run_pipeline = (Pipeline(run_factory, _stages())
                        if expect_fail else pipeline)
        try:
            block = Block.decode(_bytes(blk["rlp"]))
            import_chain(run_factory, [block], consensus)
            run_pipeline.run(block.header.number)
        except (ConsensusError, StageError, ValueError, KeyError, TypeError,
                IndexError) as e:  # malformed RLP surfaces as Type/IndexError
            if expect_fail:
                continue
            raise ConformanceFailure(f"{name}: block {i} rejected: {e}") from e
        if expect_fail:
            raise ConformanceFailure(
                f"{name}: block {i} accepted but expected {blk['expectException']}"
            )

    with factory.provider() as p:
        tip = p.last_block_number()
        tip_hash = p.canonical_hash(tip)
        if "lastblockhash" in case and tip_hash != _bytes(case["lastblockhash"]):
            raise ConformanceFailure(
                f"{name}: lastblockhash {tip_hash.hex()} != "
                f"{case['lastblockhash']}"
            )
        for addr_hex, want in case.get("postState", {}).items():
            addr = _bytes(addr_hex)
            acct = p.account(addr)
            if acct is None:
                if _int(want.get("balance", "0x0")) or _int(want.get("nonce", "0x0")):
                    raise ConformanceFailure(f"{name}: missing account {addr_hex}")
                continue
            if acct.balance != _int(want.get("balance", "0x0")):
                raise ConformanceFailure(
                    f"{name}: {addr_hex} balance {acct.balance} != "
                    f"{_int(want.get('balance', '0x0'))}"
                )
            if acct.nonce != _int(want.get("nonce", "0x0")):
                raise ConformanceFailure(f"{name}: {addr_hex} nonce mismatch")
            code = _bytes(want.get("code", "0x") or "0x")
            if keccak256(code) != acct.code_hash:
                raise ConformanceFailure(f"{name}: {addr_hex} code mismatch")
            for slot_hex, val in want.get("storage", {}).items():
                got = p.storage(addr, _b32(slot_hex))
                if got != _int(val):
                    raise ConformanceFailure(
                        f"{name}: {addr_hex} slot {slot_hex}: {got} != {_int(val)}"
                    )


def run_fixture_file(path: str, committer=None) -> list[str]:
    """Run every case in a fixture file; returns the list of case names."""
    with open(path) as f:
        cases = json.load(f)
    for name, case in cases.items():
        run_blockchain_test(name, case, committer=committer)
    return list(cases)
