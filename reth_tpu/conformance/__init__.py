"""Conformance testing: ef-tests-format BlockchainTests runner + generator.

Reference analogue: testing/ef-tests (reference
testing/ef-tests/src/cases/blockchain_test.rs:1-50), which runs the
official ethereum/tests fixtures. This image has no network access to
fetch that corpus, so the suite here is two parts:

- :mod:`runner` — consumes the standard BlockchainTests JSON shape
  (pre/genesisBlockHeader/blocks[].rlp/postState/lastblockhash), so the
  official corpus drops in unchanged when available.
- :mod:`generate` — produces a deterministic in-repo corpus (100+ cases
  across EVM/storage/precompile/tx-type scenarios) whose expectations are
  cross-committed between the executor and the trie layer: every header
  state root in a fixture is recomputed from scratch by the MerkleStage
  on replay, so executor/trie/codec regressions fail the suite.
"""

from .runner import ConformanceFailure, run_blockchain_test, run_fixture_file

__all__ = ["ConformanceFailure", "run_blockchain_test", "run_fixture_file"]
