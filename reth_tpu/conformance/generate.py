"""Deterministic BlockchainTests fixture generator.

Each scenario builds a consensus-valid chain with :class:`ChainBuilder`
(executing through the real EVM and sealing real roots), then serializes
it into the standard ef-tests JSON shape the runner consumes. The value
of replay: the runner re-executes every block through the full pipeline
and recomputes every state root bottom-up in the trie — a disagreement
anywhere in codec/EVM/trie/stages fails the case. Scenario coverage maps
to the GeneralStateTests families the reference runs (arithmetic,
storage, create/selfdestruct, precompiles, value transfers, reverts,
access lists, blob txs, set-code txs).
"""

from __future__ import annotations

import json

from ..primitives.keccak import keccak256
from ..primitives.types import Account, Block, Header, Transaction
from ..testing import ChainBuilder, Wallet

_STORE = bytes.fromhex("5f355f5500")            # sstore(0, calldata[0])
_ADDER = bytes.fromhex("5f356001015f5260205ff3")  # return calldata[0]+1
_REVERTER = bytes.fromhex("5f5ffd")               # revert(0,0)
_SELFDESTRUCT = bytes.fromhex("5f35ff")           # selfdestruct(calldata[0])


def _initcode(runtime: bytes) -> bytes:
    n = len(runtime)
    return (
        bytes([0x61, n >> 8, n & 0xFF, 0x60, 0x0D, 0x5F, 0x39,
               0x61, n >> 8, n & 0xFF, 0x5F, 0xF3])
        + b"\x00" + runtime
    )


def _call_precompile(which: int, data: bytes) -> bytes:
    """Runtime that staticcalls precompile ``which`` with ``data`` embedded
    and stores success at slot 0 (exercises the precompile in-chain)."""
    push_data = b"".join(
        bytes([0x60, b, 0x60, i, 0x53]) for i, b in enumerate(data)  # mstore8
    )
    n = len(data)
    return (
        push_data
        + bytes([0x60, 0x20, 0x5F, 0x60, n, 0x5F, 0x60, which, 0x61, 0xFF, 0xFF])
        + bytes([0xFA])          # staticcall(0xffff, which, 0, n, 0, 32)
        + bytes([0x5F, 0x55])    # sstore(0, success)
        + b"\x00"
    )


def _hex(b: bytes) -> str:
    return "0x" + b.hex()


def _hex_int(v: int) -> str:
    return hex(v)


def _account_json(acct: Account, storage: dict, code: bytes) -> dict:
    return {
        "balance": _hex_int(acct.balance),
        "nonce": _hex_int(acct.nonce),
        "code": _hex(code),
        "storage": {
            _hex_int(int.from_bytes(k, "big")): _hex_int(v)
            for k, v in storage.items()
        },
    }


def _header_json(h: Header) -> dict:
    out = {
        "parentHash": _hex(h.parent_hash),
        "uncleHash": _hex(h.ommers_hash),
        "coinbase": _hex(h.beneficiary),
        "stateRoot": _hex(h.state_root),
        "transactionsTrie": _hex(h.transactions_root),
        "receiptTrie": _hex(h.receipts_root),
        "bloom": _hex(h.logs_bloom),
        "difficulty": _hex_int(h.difficulty),
        "number": _hex_int(h.number),
        "gasLimit": _hex_int(h.gas_limit),
        "gasUsed": _hex_int(h.gas_used),
        "timestamp": _hex_int(h.timestamp),
        "extraData": _hex(h.extra_data),
        "mixHash": _hex(h.mix_hash),
        "nonce": _hex(h.nonce),
        "hash": _hex(h.hash),
    }
    if h.base_fee_per_gas is not None:
        out["baseFeePerGas"] = _hex_int(h.base_fee_per_gas)
    if h.withdrawals_root is not None:
        out["withdrawalsRoot"] = _hex(h.withdrawals_root)
    if h.blob_gas_used is not None:
        out["blobGasUsed"] = _hex_int(h.blob_gas_used)
    if h.excess_blob_gas is not None:
        out["excessBlobGas"] = _hex_int(h.excess_blob_gas)
    if h.parent_beacon_block_root is not None:
        out["parentBeaconBlockRoot"] = _hex(h.parent_beacon_block_root)
    if h.requests_hash is not None:
        out["requestsHash"] = _hex(h.requests_hash)
    return out


def builder_to_fixture(builder: ChainBuilder, network: str | None = None) -> dict:
    """Serialize a sealed chain; the network label comes from the builder
    (which executed under exactly that rule set) unless overridden."""
    network = network or builder.network or "Cancun"
    pre = {
        _hex(addr): _account_json(
            acct,
            builder.storage_at_genesis.get(addr, {}),
            builder.codes_at_genesis.get(acct.code_hash, b""),
        )
        for addr, acct in builder.accounts_at_genesis.items()
    }
    post = {
        _hex(addr): _account_json(
            acct,
            builder.storages.get(addr, {}),
            builder.codes.get(acct.code_hash, b""),
        )
        for addr, acct in builder.accounts.items()
    }
    return {
        "network": network,
        "pre": pre,
        "genesisBlockHeader": _header_json(builder.genesis),
        "genesisRLP": _hex(builder.blocks[0].encode()),
        "blocks": [{"rlp": _hex(b.encode())} for b in builder.blocks[1:]],
        "postState": post,
        "lastblockhash": _hex(builder.tip.hash),
    }


def _contract_addr(builder: ChainBuilder, runtime: bytes) -> bytes:
    h = keccak256(runtime)
    return next(a for a, acc in builder.accounts.items() if acc.code_hash == h)


# -- scenarios (each returns a sealed ChainBuilder) --------------------------


def _scn_transfers(seed: int, network: str | None = None) -> ChainBuilder:
    a, b = Wallet(0xA0000 + seed), Wallet(0xB0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20),
                        b.address: Account(balance=10**19)},
                       network=network)
    for i in range(1 + seed % 3):
        bld.build_block([
            a.transfer(b.address, 10**15 + seed * 1000 + i),
            b.transfer(bytes([seed + 1] * 20), 12345 + i),
        ])
    return bld


def _scn_storage(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0xC0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(_initcode(_STORE))])
    c = _contract_addr(bld, _STORE)
    writes = [a.call(c, (seed * 7 + i + 1).to_bytes(32, "big")) for i in range(3)]
    bld.build_block(writes[:2])
    bld.build_block([writes[2], a.call(c, b"\x00" * 32)])  # final zero-out
    return bld


def _scn_create_call(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0xD0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(_initcode(_ADDER)), a.deploy(_initcode(_STORE))])
    adder = _contract_addr(bld, _ADDER)
    store = _contract_addr(bld, _STORE)
    bld.build_block([
        a.call(adder, seed.to_bytes(32, "big")),
        a.call(store, (seed + 99).to_bytes(32, "big")),
    ])
    return bld


def _scn_revert(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0xE0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(_initcode(_REVERTER))])
    rev = _contract_addr(bld, _REVERTER)
    bld.build_block([a.call(rev, b""), a.transfer(b"\x05" * 20, seed + 1)])
    return bld


def _scn_selfdestruct(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0xF0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(_initcode(_SELFDESTRUCT))])
    sd = _contract_addr(bld, _SELFDESTRUCT)
    # same-tx create+destruct vs later-call destruct (EIP-6780 split)
    bld.build_block([
        a.call(sd, (0xBEEF00 + seed).to_bytes(32, "big"), value=777),
    ])
    return bld


def _scn_precompiles(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0x1A0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    which = (2, 3, 4, 6, 9)[seed % 5]
    data = bytes([seed & 0xFF]) * (8 + seed % 16)
    if which == 6:
        data = (1).to_bytes(32, "big") + (2).to_bytes(32, "big") + b"\x00" * 64
    if which == 9:
        data = b"\x00" * 213  # zero rounds
    runtime = _call_precompile(which, data)
    bld.build_block([a.deploy(_initcode(runtime))])
    c = _contract_addr(bld, runtime)
    bld.build_block([a.call(c, b"", gas_limit=500_000)])
    return bld


def _scn_access_list(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0x1B0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(_initcode(_STORE))])
    c = _contract_addr(bld, _STORE)
    tx = a.sign_tx(Transaction(
        tx_type=1, chain_id=1, nonce=a.nonce, gas_price=10**9 + 10**8,
        gas_limit=100_000, to=c, data=(seed + 5).to_bytes(32, "big"),
        access_list=((c, (b"\x00" * 32,)),),
    ))
    bld.build_block([tx])
    return bld


def _scn_blob_tx(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0x1C0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**21)}, cancun=True, network=network)
    tx = a.sign_tx(Transaction(
        tx_type=3, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=50_000,
        to=bytes([seed % 250 + 1] * 20), value=seed,
        max_fee_per_blob_gas=1000,
        blob_versioned_hashes=tuple(
            b"\x01" + bytes([seed & 0xFF, i]) + b"\x00" * 29
            for i in range(1 + seed % 3)
        ),
    ))
    bld.build_block([tx])
    bld.build_block([])  # excess-blob-gas rollover block
    return bld


def _scn_setcode_tx(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0x1D0000 + seed)
    b = Wallet(0x1E0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20),
                        b.address: Account(balance=10**19)},
                       network=network)
    bld.build_block([a.deploy(_initcode(_STORE))])
    c = _contract_addr(bld, _STORE)
    auth = b.authorize(c, nonce=0)
    tx = a.sign_tx(Transaction(
        tx_type=4, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=200_000,
        to=b.address, data=(seed + 1).to_bytes(32, "big"),
        authorization_list=(auth,),
    ))
    bld.build_block([tx])
    return bld


def _scn_deep_state(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0x1F0000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**21)}, network=network)
    txs = [a.transfer(keccak256(bytes([seed, i]))[:20], 10**10 + i)
           for i in range(12)]
    bld.build_block(txs[:6])
    bld.build_block(txs[6:])
    return bld


def _scn_empty_blocks(seed: int, network: str | None = None) -> ChainBuilder:
    a = Wallet(0x200000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    for i in range(2 + seed % 4):
        bld.build_block([] if i % 2 else [a.transfer(b"\x31" * 20, seed + i)])
    return bld


def _mass_zero_runtime(n: int) -> bytes:
    """sstore(i, 0) for i = n..1 in a loop — one tx zeroing ``n`` live
    slots, so the total refund (n x 4800) exceeds the EIP-3529 cap of
    gas_used/5 and the clamp must bind."""
    return bytes([
        0x60, n,                    # counter = n
        0x5B,                       # 0x02: loop
        0x80, 0x15, 0x60, 0x12, 0x57,   # if counter == 0 goto exit
        0x5F, 0x81, 0x55,           # sstore(counter, 0)
        0x60, 0x01, 0x90, 0x03,     # counter -= 1
        0x60, 0x02, 0x56,           # goto loop
        0x5B, 0x00,                 # 0x12: exit
    ])


def _scn_gas_edge(seed: int, network: str | None = None) -> ChainBuilder:
    """Refund-cap adversaries (EIP-3529): one tx zeroes MANY pre-existing
    slots so the refund exceeds gas_used/5 and the cap binds (a clamp bug
    changes the sealed gas_used); plus an exact intrinsic-gas transfer
    (21000) that must succeed with zero slack."""
    a = Wallet(0x210000 + seed)
    n = 8 + seed % 5
    zeroer = _mass_zero_runtime(n)
    zaddr = bytes([0x5D]) + bytes(18) + bytes([seed + 1])
    bld = ChainBuilder(
        {a.address: Account(balance=10**20),
         zaddr: Account(code_hash=keccak256(zeroer))},
        genesis_storage={zaddr: {i.to_bytes(32, "big"): i + 7
                                 for i in range(1, n + 1)}},
        codes={keccak256(zeroer): zeroer},
        network=network,
    )
    bld.build_block([a.call(zaddr, b"", gas_limit=500_000)])
    # exact intrinsic gas: gas_limit == 21000, must land
    bld.build_block([a.transfer(bytes([0x44] * 20), seed + 1, gas_limit=21_000)])
    return bld


_CREATE2_CHILD_INIT = _initcode(b"\x00")  # deploys a 1-byte STOP runtime


def _create2_factory_runtime() -> bytes:
    """sstore(salt, create2(0, mem[0:n], salt)) with the child initcode
    embedded in the factory's own code (salt = calldata word 0)."""
    n = len(_CREATE2_CHILD_INIT)
    header = bytes([
        0x60, n, 0x60, 0x11, 0x5F, 0x39,        # codecopy(0, 0x11, n)
        0x5F, 0x35,                              # salt
        0x60, n, 0x5F, 0x5F, 0xF5,               # create2(0, 0, n, salt)
        0x5F, 0x35, 0x55,                        # sstore(salt, addr)
        0x00,                                    # stop
    ])
    assert len(header) == 0x11
    return header + _CREATE2_CHILD_INIT


def _scn_create_collision(seed: int, network: str | None = None) -> ChainBuilder:
    """CREATE2 address collision: the second deployment with the SAME salt
    must fail (stores 0), a fresh salt succeeds — exercises the
    created-account collision rules and address derivation."""
    a = Wallet(0x220000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    factory = _create2_factory_runtime()
    bld.build_block([a.deploy(_initcode(factory))])
    f = _contract_addr(bld, factory)
    salt = (0x5A17 + seed).to_bytes(32, "big")
    bld.build_block([a.call(f, salt, gas_limit=300_000)])
    # the colliding create burns its frame's 63/64 (EIP-684); 2M gas leaves
    # the factory enough to SSTORE the returned zero, erasing the slot
    bld.build_block([
        a.call(f, salt, gas_limit=2_000_000),
        a.call(f, (0xF0E0 + seed).to_bytes(32, "big"), gas_limit=300_000),
    ])
    return bld


def _scn_delegation_chain(seed: int, network: str | None = None) -> ChainBuilder:
    """EIP-7702 adversaries: re-delegation in a later block, an
    invalid-nonce tuple that must be skipped, and delegation revocation
    (authorize the zero address)."""
    a = Wallet(0x230000 + seed)
    b = Wallet(0x240000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20),
                        b.address: Account(balance=10**19)},
                       network=network)
    bld.build_block([a.deploy(_initcode(_STORE)), a.deploy(_initcode(_ADDER))])
    store = _contract_addr(bld, _STORE)
    adder = _contract_addr(bld, _ADDER)
    # delegate b -> store; include one stale-nonce tuple (skipped)
    good = b.authorize(store, nonce=0)
    stale = b.authorize(adder, nonce=77)  # wrong nonce: must be ignored
    bld.build_block([a.sign_tx(Transaction(
        tx_type=4, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=200_000,
        to=b.address, data=(seed + 1).to_bytes(32, "big"),
        authorization_list=(stale, good),
    ))])
    # re-delegate b -> adder in a later block (auth nonce advanced to 1)
    redel = b.authorize(adder, nonce=1)
    bld.build_block([a.sign_tx(Transaction(
        tx_type=4, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=200_000,
        to=b.address, data=(seed + 2).to_bytes(32, "big"),
        authorization_list=(redel,),
    ))])
    # revoke (delegate to the zero address)
    revoke = b.authorize(b"\x00" * 20, nonce=2)
    bld.build_block([a.sign_tx(Transaction(
        tx_type=4, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=200_000,
        to=b.address, data=b"", authorization_list=(revoke,),
    ))])
    return bld


def _scn_blob_accounting(seed: int, network: str | None = None) -> ChainBuilder:
    """EIP-4844 blob-gas market: blob-heavy blocks push excess_blob_gas
    up, empty blocks decay it — every header's blobGasUsed/excessBlobGas
    pair is sealed and replayed."""
    a = Wallet(0x250000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**21)}, cancun=True, network=network)
    def blob_tx(n_blobs, tag):
        return a.sign_tx(Transaction(
            tx_type=3, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
            max_priority_fee_per_gas=10**9, gas_limit=50_000,
            to=bytes([0x66] * 20), value=tag,
            max_fee_per_blob_gas=10**10,
            blob_versioned_hashes=tuple(
                b"\x01" + bytes([tag & 0xFF, i]) + b"\x00" * 29
                for i in range(n_blobs)),
        ))
    # two full-blob blocks (6 blobs each) drive excess up
    bld.build_block([blob_tx(3, seed), blob_tx(3, seed + 1)])
    bld.build_block([blob_tx(6, seed + 2)])
    # decay over empties
    bld.build_block([])
    bld.build_block([])
    return bld


def _revert_outer_runtime(inner: bytes) -> bytes:
    """call(inner) then sstore(1, 42): the inner frame's writes must be
    journal-unwound while the outer's survive."""
    return (
        bytes([0x5F, 0x5F, 0x5F, 0x5F, 0x5F, 0x73]) + inner  # push20 inner
        + bytes([0x61, 0xFF, 0xFF, 0xF1,                     # call
                 0x50,                                        # pop status
                 0x60, 0x2A, 0x60, 0x01, 0x55,                # sstore(1, 42)
                 0x00])
    )


def _scn_deep_revert(seed: int, network: str | None = None) -> ChainBuilder:
    """Nested-frame journaling: the callee SSTOREs then REVERTs (its write
    unwinds), the caller keeps executing and commits its own write; a
    second tx reverts at the TOP level after a successful inner call (all
    writes unwind)."""
    a = Wallet(0x260000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    # inner: sstore(0, 1) then revert(0,0)
    inner_rt = bytes([0x60, 0x01, 0x5F, 0x55, 0x5F, 0x5F, 0xFD])
    bld.build_block([a.deploy(_initcode(inner_rt))])
    inner = _contract_addr(bld, inner_rt)
    outer_rt = _revert_outer_runtime(inner)
    bld.build_block([a.deploy(_initcode(outer_rt))])
    outer = _contract_addr(bld, outer_rt)
    bld.build_block([a.call(outer, b"", gas_limit=300_000)])
    # top-level revert wrapping a SUCCESSFUL store call: everything unwinds
    store_rt = _STORE
    bld.build_block([a.deploy(_initcode(store_rt))])
    store = _contract_addr(bld, store_rt)
    top_rt = (
        bytes([0x5F, 0x5F, 0x60, 0x20, 0x5F, 0x5F, 0x73]) + store
        + bytes([0x61, 0xFF, 0xFF, 0xF1, 0x50, 0x5F, 0x5F, 0xFD])  # revert
    )
    bld.build_block([a.deploy(_initcode(top_rt))])
    top = _contract_addr(bld, top_rt)
    bld.build_block([a.call(top, (seed + 7).to_bytes(32, "big"),
                            gas_limit=300_000),
                     a.transfer(bytes([0x77] * 20), seed + 1)])
    return bld


def _scn_invalid_blocks(seed: int, network: str | None = None) -> dict:
    """Invalid-block rejection family (the official suites' InvalidBlocks
    shape): a valid 2-block chain followed by a TAMPERED third block that
    must be rejected — bad state root, bad gas used, bad transactions
    root, or broken parent linkage, rotating by seed. Returns a finished
    fixture (the tampered block cannot come from ChainBuilder, which only
    seals valid chains)."""
    a = Wallet(0x270000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    for i in range(2):
        bld.build_block([a.transfer(bytes([0x41]) * 20, 100 + seed + i)])
    fix2 = builder_to_fixture(bld)  # snapshot BEFORE block 3 exists
    b3 = bld.build_block([a.transfer(bytes([0x41]) * 20, 102 + seed)])
    h = b3.header
    variant = seed % 4
    if variant == 0:
        patch = {"state_root": bytes([0x13]) * 32}
        exc = "InvalidStateRoot"
    elif variant == 1:
        patch = {"gas_used": h.gas_used + 1}
        exc = "InvalidGasUsed"
    elif variant == 2:
        patch = {"transactions_root": bytes([0x21]) * 32}
        exc = "InvalidTransactionsRoot"
    else:
        patch = {"parent_hash": bytes([0x55]) * 32}
        exc = "UnknownParent"
    bad = Block(Header(**{**h.__dict__, **patch}), b3.transactions, (),
                b3.withdrawals)
    fix2["blocks"].append({"rlp": _hex(bad.encode()), "expectException": exc})
    return fix2


def _scn_push0_boundary(seed: int, network: str | None = None) -> ChainBuilder:
    """EIP-3855 fork boundary: the same contract call succeeds under
    Shanghai and halts (invalid opcode, all gas burnt) under Paris —
    sealed under each network's own rules so replay pins the divergence
    in gas, receipts, and state."""
    a = Wallet(0x300000 + seed)
    # runtime built WITHOUT PUSH0 so deployment works pre-Shanghai:
    # PUSH0 PUSH1 01 SSTORE STOP — storage write only where PUSH0 exists
    runtime = bytes.fromhex("5f60015500")
    init = (bytes([0x60, len(runtime), 0x60, 0x0C, 0x60, 0x00, 0x39,
                   0x60, len(runtime), 0x60, 0x00, 0xF3])
            + runtime)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(init)])
    c = _contract_addr(bld, runtime)
    bld.build_block([a.call(c, seed.to_bytes(32, "big"))])
    return bld


def _scn_cancun_ops_boundary(seed: int, network: str | None = None) -> ChainBuilder:
    """EIP-1153/5656 boundary: TSTORE and MCOPY halt under Shanghai,
    execute under Cancun (alternating by seed)."""
    a = Wallet(0x310000 + seed)
    if seed % 2 == 0:  # TSTORE(0,1); SSTORE(1, TLOAD(0)); STOP
        runtime = bytes.fromhex("600160005d60005c60015500")
    else:  # MSTORE8(0,7); MCOPY(0x20,0,0x20); SSTORE(2, MLOAD(0x20)); STOP
        runtime = bytes.fromhex("60076000536020600060205e60205160025500")
    init = (bytes([0x60, len(runtime), 0x60, 0x0C, 0x60, 0x00, 0x39,
                   0x60, len(runtime), 0x60, 0x00, 0xF3])
            + runtime)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(init)])
    c = _contract_addr(bld, runtime)
    bld.build_block([a.call(c, b"")])
    return bld


def _scn_selfdestruct_boundary(seed: int, network: str | None = None) -> ChainBuilder:
    """EIP-6780 boundary: a PRE-EXISTING contract selfdestructs in a later
    transaction — deleted under Shanghai, surviving (balance-move only)
    under Cancun. The post-state accounts differ across the two fixtures."""
    a = Wallet(0x320000 + seed)
    sd = bytes.fromhex("600035ff")  # selfdestruct(calldata[0]) sans PUSH0
    init = (bytes([0x60, len(sd), 0x60, 0x0C, 0x60, 0x00, 0x39,
                   0x60, len(sd), 0x60, 0x00, 0xF3]) + sd)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.deploy(init)])
    c = _contract_addr(bld, sd)
    bld.build_block([a.transfer(c, 777 + seed)])  # fund it
    ben = bytes([0x44] * 19 + [seed + 1])
    bld.build_block([a.call(c, ben.rjust(32, b"\x00"), gas_limit=200_000)])
    return bld


def _scn_future_tx_rejected(seed: int, network: str | None = None) -> dict:
    """Fork gating of tx envelopes: a block smuggling a next-fork tx type
    (blob tx under Shanghai / set-code tx under Cancun) must be REJECTED,
    not mis-executed (expectException)."""
    from ..primitives.rlp import rlp_encode as _rlp
    from ..testing import ordered_trie_root

    a = Wallet(0x330000 + seed)
    bld = ChainBuilder({a.address: Account(balance=10**20)}, network=network)
    bld.build_block([a.transfer(bytes([0x42]) * 20, 1000 + seed)])
    fix = builder_to_fixture(bld)
    if network == "Shanghai":
        bad_tx = a.sign_tx(Transaction(
            tx_type=3, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
            max_priority_fee_per_gas=10**9, gas_limit=50_000,
            to=bytes([0x66] * 20), max_fee_per_blob_gas=10**10,
            blob_versioned_hashes=(b"\x01" + bytes(31),)))
        exc = "TxTypeNotActivated"
    else:  # Cancun rejecting a Prague set-code tx
        auth = a.authorize(bytes([0x55] * 20), nonce=a.nonce + 1)
        bad_tx = a.sign_tx(Transaction(
            tx_type=4, chain_id=1, nonce=a.nonce, max_fee_per_gas=10**10,
            max_priority_fee_per_gas=10**9, gas_limit=100_000,
            to=bytes([0x55] * 20), authorization_list=(auth,)))
        exc = "TxTypeNotActivated"
    parent = bld.tip
    from ..consensus.validation import calc_next_base_fee
    from ..primitives.types import EMPTY_ROOT_HASH

    bad = Block(
        Header(**{**parent.__dict__,
                  "parent_hash": parent.hash,
                  "number": parent.number + 1,
                  "timestamp": parent.timestamp + 12,
                  "base_fee_per_gas": calc_next_base_fee(parent),
                  "transactions_root": ordered_trie_root(
                      [bad_tx.encode()], bld.committer),
                  "receipts_root": EMPTY_ROOT_HASH,
                  "gas_used": 21_000}),
        (bad_tx,), (), () if network != "Paris" else None)
    fix["blocks"].append({"rlp": _hex(bad.encode()), "expectException": exc})
    return fix


SCENARIOS = {
    "transfers": _scn_transfers,
    "storage": _scn_storage,
    "createCall": _scn_create_call,
    "revert": _scn_revert,
    "selfdestruct": _scn_selfdestruct,
    "precompiles": _scn_precompiles,
    "accessList": _scn_access_list,
    "blobTx": _scn_blob_tx,
    "setCodeTx": _scn_setcode_tx,
    "deepState": _scn_deep_state,
    "emptyBlocks": _scn_empty_blocks,
    # adversarial families (round-4: gas edges, collisions, 7702 chains,
    # 4844 accounting, nested-revert journaling)
    "gasEdge": _scn_gas_edge,
    "createCollision": _scn_create_collision,
    "delegationChain": _scn_delegation_chain,
    "blobAccounting": _scn_blob_accounting,
    "deepRevert": _scn_deep_revert,
    "invalidBlocks": _scn_invalid_blocks,
    # fork-boundary families (round-5: per-network generation; the same
    # scenario sealed under adjacent forks pins the divergence)
    "push0Boundary": _scn_push0_boundary,
    "cancunOpsBoundary": _scn_cancun_ops_boundary,
    "selfdestructBoundary": _scn_selfdestruct_boundary,
    "futureTxRejected": _scn_future_tx_rejected,
}

# Networks each family is generated under. Most bytecode scenarios use
# PUSH0, so Shanghai is their floor; blob families span the EIP-7691
# reschedule (Cancun 3/6 vs Prague 6/9 — the excess math differs);
# 7702 families are Prague-only. Boundary families deliberately include
# the fork where the feature does NOT exist.
SCENARIO_NETWORKS: dict[str, list[str]] = {
    "transfers": ["Paris", "Shanghai", "Cancun", "Prague"],
    "emptyBlocks": ["Paris", "Shanghai", "Cancun", "Prague"],
    "storage": ["Shanghai", "Cancun", "Prague"],
    "createCall": ["Shanghai", "Cancun", "Prague"],
    "revert": ["Shanghai", "Cancun", "Prague"],
    "selfdestruct": ["Cancun", "Prague"],
    "precompiles": ["Shanghai", "Cancun", "Prague"],
    "accessList": ["Shanghai", "Cancun", "Prague"],
    "deepState": ["Shanghai", "Cancun", "Prague"],
    "gasEdge": ["Shanghai", "Cancun", "Prague"],
    "createCollision": ["Shanghai", "Cancun", "Prague"],
    "deepRevert": ["Shanghai", "Cancun", "Prague"],
    "invalidBlocks": ["Shanghai", "Cancun", "Prague"],
    "blobTx": ["Cancun", "Prague"],
    "blobAccounting": ["Cancun", "Prague"],
    "setCodeTx": ["Prague"],
    "delegationChain": ["Prague"],
    "push0Boundary": ["Paris", "Shanghai"],
    "cancunOpsBoundary": ["Shanghai", "Cancun"],
    "selfdestructBoundary": ["Shanghai", "Cancun"],
    "futureTxRejected": ["Shanghai", "Cancun"],
}


def generate_suite(seeds_per_scenario: int = 10) -> dict[str, dict]:
    """The full generated corpus: scenario x seed -> fixture case, cycling
    each family through its eligible networks across seeds."""
    suite: dict[str, dict] = {}
    for name, fn in SCENARIOS.items():
        networks = SCENARIO_NETWORKS[name]
        for seed in range(seeds_per_scenario):
            network = networks[seed % len(networks)]
            made = fn(seed, network=network)
            suite[f"{name}_{network}_{seed}"] = (
                made if isinstance(made, dict) else builder_to_fixture(made))
    return suite


def _suite_cache_path(seeds_per_scenario: int) -> str | None:
    import hashlib
    import os

    if os.environ.get("RETH_TPU_CONFORMANCE_CACHE", "1") == "0":
        return None
    try:
        with open(__file__, "rb") as f:
            key = hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        return None
    cache_dir = os.environ.get("RETH_TPU_CONFORMANCE_CACHE_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "tests", ".conformance_cache")
    return os.path.join(cache_dir, f"suite-{seeds_per_scenario}x-{key}.json")


def load_or_generate_suite(seeds_per_scenario: int = 10) -> dict[str, dict]:
    """``generate_suite`` behind a content-addressed disk cache.

    Generating the corpus executes every chain through the real EVM and
    seals real roots — minutes of CPU for hundreds of cases — but the
    output is pure deterministic data in the ef-tests JSON shape the
    runner consumes from disk anyway (``run_fixture_file`` is
    json.load → run_blockchain_test). The cache key is the sha256 of
    THIS file, so editing any scenario regenerates; the replay itself
    (the actual conformance check) always runs in full against the
    current pipeline. ``RETH_TPU_CONFORMANCE_CACHE=0`` disables, or
    delete tests/.conformance_cache/ to force regeneration.
    """
    import os

    path = _suite_cache_path(seeds_per_scenario)
    if path:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            pass
    suite = generate_suite(seeds_per_scenario)
    if path:
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(suite, f, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return suite


def write_suite(path: str, seeds_per_scenario: int = 10) -> int:
    suite = generate_suite(seeds_per_scenario)
    with open(path, "w") as f:
        json.dump(suite, f)
    return len(suite)
