"""Peer sessions: framed TCP transport + status handshake + requests.

Reference analogue: crates/net/network session machinery
(src/session/mod.rs) and the p2p client traits
(crates/net/p2p: HeadersClient/BodiesClient). Request/response
correlation uses eth/66-style request ids.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading

from . import wire
from .wire import MessageId, Status, decode_message, encode_message


class PeerError(Exception):
    pass


class PeerConnection:
    """One established peer session over a socket."""

    def __init__(self, sock: socket.socket, status: Status):
        self.sock = sock
        self.status = status  # the REMOTE peer's status
        self._req_ids = itertools.count(1)
        self._lock = threading.Lock()
        # unsolicited gossip received while awaiting a response (drained by
        # the owner; bounded so a chatty peer cannot balloon memory)
        self.gossip: list = []
        self.MAX_GOSSIP_BUFFER = 1024

    # -- framing ---------------------------------------------------------------

    @staticmethod
    def _recv_exact(sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise PeerError("peer disconnected")
            buf += chunk
        return buf

    @classmethod
    def recv_frame(cls, sock) -> bytes:
        (length,) = struct.unpack("<I", cls._recv_exact(sock, 4))
        if length > 64 * 1024 * 1024:
            raise PeerError("oversized frame")
        return cls._recv_exact(sock, length)

    def send(self, msg) -> None:
        data = encode_message(msg)
        with self._lock:
            self.sock.sendall(data)

    def recv(self):
        return decode_message(self.recv_frame(self.sock))

    # -- handshake -------------------------------------------------------------

    @classmethod
    def connect(cls, host: str, port: int, our_status: Status,
                timeout: float = 10.0) -> "PeerConnection":
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.sendall(encode_message(our_status))
        remote = decode_message(cls.recv_frame(sock))
        if not isinstance(remote, Status):
            raise PeerError("expected status handshake")
        _validate_status(our_status, remote)
        return cls(sock, remote)

    @classmethod
    def accept(cls, sock: socket.socket, our_status: Status) -> "PeerConnection":
        remote = decode_message(cls.recv_frame(sock))
        if not isinstance(remote, Status):
            raise PeerError("expected status handshake")
        _validate_status(our_status, remote)
        sock.sendall(encode_message(our_status))
        return cls(sock, remote)

    # -- typed requests (HeadersClient / BodiesClient analogues) ---------------

    def _await_response(self, kind, rid: int, max_frames: int = 256):
        """Receive until the matching (type, request_id) response arrives;
        interleaved gossip is buffered, not treated as a protocol error."""
        for _ in range(max_frames):
            msg = self.recv()
            if isinstance(msg, kind) and msg.request_id == rid:
                return msg
            if isinstance(msg, (wire.TransactionsMsg, wire.NewPooledTxHashes,
                                wire.NewBlockHashes)):
                if len(self.gossip) < self.MAX_GOSSIP_BUFFER:
                    self.gossip.append(msg)
                continue
            raise PeerError(f"unexpected {type(msg).__name__} awaiting {kind.__name__}")
        raise PeerError("response never arrived")

    def get_headers(self, start, limit: int, reverse: bool = False,
                    skip: int = 0) -> list:
        rid = next(self._req_ids)
        self.send(wire.GetBlockHeaders(rid, start, limit, skip, reverse))
        return self._await_response(wire.BlockHeaders, rid).headers

    def get_bodies(self, hashes: list[bytes]) -> list:
        rid = next(self._req_ids)
        self.send(wire.GetBlockBodies(rid, hashes))
        return self._await_response(wire.BlockBodies, rid).bodies

    def get_receipts(self, hashes: list[bytes]) -> list[list[bytes]]:
        rid = next(self._req_ids)
        self.send(wire.GetReceipts(rid, hashes))
        return self._await_response(wire.ReceiptsMsg, rid).receipts

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def _validate_status(ours: Status, theirs: Status) -> None:
    if theirs.network_id != ours.network_id:
        raise PeerError(f"network id mismatch: {theirs.network_id}")
    if theirs.genesis != ours.genesis:
        raise PeerError("genesis mismatch")
