"""Peer sessions: RLPx-encrypted transport + status handshake + requests.

Reference analogue: crates/net/network session machinery
(src/session/mod.rs) over crates/net/ecies + eth-wire: every session runs
the ECIES auth/ack handshake, the p2p Hello (snappy from v5), then the
eth/68 Status exchange before any request traffic. Request/response
correlation uses eth/66-style request ids.
"""

from __future__ import annotations

import itertools
import socket
import threading

from ..primitives.secp256k1 import random_priv as random_node_key
from . import rlpx, wire
from .rlpx import BASE_PROTOCOL_OFFSET, DISCONNECT_ID, PING_ID, PONG_ID, RlpxSession
from .wire import Status

CLIENT_ID = "reth-tpu/0.2"
ETH_CAPS = [("eth", 68), ("eth", 69), ("snap", 1)]
# capability message-id spaces are assigned alphabetically after the base
# protocol; the NEGOTIATED eth version sets the span (eth/68: 17 ids,
# eth/69 adds BlockRangeUpdate: 18), snap/1 follows (devp2p rule) —
# always use the per-session `PeerConnection.snap_offset`
ETH_MSG_COUNT = {68: 17, 69: 18}


def _negotiate_eth(caps) -> int | None:
    """Highest shared eth version (devp2p: advertise all, shared max wins)."""
    ours = {v for name, v in ETH_CAPS if name == "eth"}
    shared = [v for name, v in caps if name == "eth" and v in ours]
    return max(shared) if shared else None


class PeerError(Exception):
    pass


class PeerDisconnected(PeerError):
    """Graceful devp2p Disconnect — not a protocol violation."""


class PeerConnection:
    """One established encrypted peer session (RLPx + Hello + Status)."""

    def __init__(self, session: RlpxSession, status: Status):
        self.session = session
        self.status = status  # the REMOTE peer's status
        caps = (session.remote_hello or {}).get("caps", [])
        self.eth_version = _negotiate_eth(caps)
        self.snap_enabled = any(name == "snap" and v >= 1 for name, v in caps)
        self.snap_offset = (BASE_PROTOCOL_OFFSET
                            + ETH_MSG_COUNT.get(self.eth_version, 17))
        self.block_range: tuple[int, int, bytes] | None = None  # eth/69
        self._req_ids = itertools.count(1)
        self._lock = threading.Lock()
        # unsolicited gossip received while awaiting a response (drained by
        # the owner; bounded so a chatty peer cannot balloon memory)
        self.gossip: list = []
        self.MAX_GOSSIP_BUFFER = 1024

    @property
    def node_id(self) -> bytes:
        return self.session.remote_node_id

    # -- message transport ------------------------------------------------------

    def send(self, msg) -> None:
        mid, payload = wire.encode_eth(msg)
        with self._lock:
            self.session.send_msg(BASE_PROTOCOL_OFFSET + mid, payload)

    def send_snap(self, msg) -> None:
        from . import snap as snap_mod

        mid, payload = snap_mod.encode_snap(msg)
        with self._lock:
            self.session.send_msg(self.snap_offset + mid, payload)

    def _dispatch(self, mid: int, body: bytes):
        """One (mid, body) -> decoded message, or None when it was a p2p
        housekeeping frame handled inline (ping/pong)."""
        if self.snap_enabled and mid >= self.snap_offset:
            from . import snap as snap_mod

            return snap_mod.decode_snap(mid - self.snap_offset, body)
        if mid >= BASE_PROTOCOL_OFFSET:
            return wire.decode_eth(mid - BASE_PROTOCOL_OFFSET, body)
        if mid == PING_ID:
            with self._lock:
                self.session.send_msg(PONG_ID, b"\xc0")
            return None
        if mid == PONG_ID:
            return None
        if mid == DISCONNECT_ID:
            raise PeerDisconnected("peer disconnected")
        raise PeerError(f"unexpected p2p message {mid:#x}")

    def recv(self):
        """Next eth/snap message; p2p pings are answered inline, disconnects
        surface as PeerError."""
        while True:
            mid, body = self.session.recv_msg()
            msg = self._dispatch(mid, body)
            if msg is not None:
                return msg

    def feed(self, data: bytes) -> list:
        """Swarm receive path: buffered ciphertext in, decoded messages
        out (non-blocking; p2p housekeeping handled inline)."""
        msgs = []
        for frame in self.session.feed_frames(data):
            msg = self._dispatch(*self.session.parse_frame(frame))
            if msg is not None:
                msgs.append(msg)
        return msgs

    # -- handshake -------------------------------------------------------------

    @classmethod
    def _finish_handshake(cls, session: RlpxSession, node_priv: int,
                          our_status: Status, fork_filter=None) -> "PeerConnection":
        session.hello(node_priv, CLIENT_ID, ETH_CAPS)
        version = _negotiate_eth(session.remote_hello["caps"])
        if version is None:
            session.disconnect()
            raise PeerError("peer lacks eth/68 capability")
        import dataclasses

        our_status = dataclasses.replace(our_status, version=version)
        mid, payload = wire.encode_eth(our_status)
        session.send_msg(BASE_PROTOCOL_OFFSET + mid, payload)
        rmid, rbody = session.recv_msg()
        if rmid != BASE_PROTOCOL_OFFSET + wire.MessageId.STATUS:
            session.disconnect()
            raise PeerError("expected status handshake")
        remote = wire.decode_eth(wire.MessageId.STATUS, rbody)
        try:
            if remote.version != version:
                # message-id spaces derive from the negotiated version: a
                # mismatched Status would silently desync the multiplexing
                raise PeerError(
                    f"status version {remote.version} != negotiated {version}")
            _validate_status(our_status, remote, fork_filter)
        except PeerError:
            session.disconnect()
            raise
        return cls(session, remote)

    @classmethod
    def connect(cls, host: str, port: int, our_status: Status,
                remote_pub: tuple[int, int], node_priv: int | None = None,
                timeout: float = 10.0, fork_filter=None) -> "PeerConnection":
        """Dial a peer (its public key comes from discovery / the enode)."""
        key = node_priv or random_node_key()
        sock = socket.create_connection((host, port), timeout=timeout)
        try:
            session = rlpx.initiate(sock, key, remote_pub)
            return cls._finish_handshake(session, key, our_status, fork_filter)
        except Exception:
            sock.close()
            raise

    @classmethod
    def accept(cls, sock: socket.socket, our_status: Status,
               node_priv: int, fork_filter=None) -> "PeerConnection":
        session = rlpx.respond(sock, node_priv)
        return cls._finish_handshake(session, node_priv, our_status, fork_filter)

    # -- typed requests (HeadersClient / BodiesClient analogues) ---------------

    def _await_response(self, kind, rid: int, max_frames: int = 256):
        """Receive until the matching (type, request_id) response arrives;
        interleaved gossip is buffered, not treated as a protocol error."""
        for _ in range(max_frames):
            msg = self.recv()
            if isinstance(msg, kind) and msg.request_id == rid:
                return msg
            if isinstance(msg, wire.BlockRangeUpdate):
                self.block_range = (msg.earliest, msg.latest, msg.latest_hash)
                continue
            if isinstance(msg, (wire.TransactionsMsg, wire.NewPooledTxHashes,
                                wire.NewBlockHashes)):
                if len(self.gossip) < self.MAX_GOSSIP_BUFFER:
                    self.gossip.append(msg)
                continue
            raise PeerError(f"unexpected {type(msg).__name__} awaiting {kind.__name__}")
        raise PeerError("response never arrived")

    def get_headers(self, start, limit: int, reverse: bool = False,
                    skip: int = 0) -> list:
        rid = next(self._req_ids)
        self.send(wire.GetBlockHeaders(rid, start, limit, skip, reverse))
        return self._await_response(wire.BlockHeaders, rid).headers

    def get_bodies(self, hashes: list[bytes]) -> list:
        rid = next(self._req_ids)
        self.send(wire.GetBlockBodies(rid, hashes))
        return self._await_response(wire.BlockBodies, rid).bodies

    def get_receipts(self, hashes: list[bytes]) -> list[list[bytes]]:
        rid = next(self._req_ids)
        self.send(wire.GetReceipts(rid, hashes))
        return self._await_response(wire.ReceiptsMsg, rid).receipts

    # -- snap/1 requests (state-range client) ----------------------------------

    def _snap_request(self, req, resp_cls):
        if not self.snap_enabled:
            raise PeerError("peer does not support snap/1")
        self.send_snap(req)
        return self._await_response(resp_cls, req.request_id)

    def get_account_range(self, root: bytes, origin: bytes, limit: bytes,
                          response_bytes: int | None = None):
        from . import snap as s

        req = s.GetAccountRange(next(self._req_ids), root, origin, limit,
                                response_bytes or s.SOFT_RESPONSE_LIMIT)
        return self._snap_request(req, s.AccountRange)

    def get_storage_ranges(self, root: bytes, account_hashes: list[bytes],
                           origin: bytes = b"", limit: bytes = b""):
        from . import snap as s

        req = s.GetStorageRanges(next(self._req_ids), root, account_hashes,
                                 origin, limit)
        return self._snap_request(req, s.StorageRanges)

    def get_byte_codes(self, hashes: list[bytes]):
        from . import snap as s

        return self._snap_request(
            s.GetByteCodes(next(self._req_ids), hashes), s.ByteCodes)

    def get_trie_nodes(self, root: bytes, paths: list[list[bytes]]):
        from . import snap as s

        return self._snap_request(
            s.GetTrieNodes(next(self._req_ids), root, paths), s.TrieNodes)

    def close(self):
        self.session.close()
        for fn in getattr(self, "_on_close", ()):
            try:
                fn()
            except Exception:  # noqa: BLE001 — bookkeeping must not
                # block socket teardown
                pass
        self._on_close = ()


def _validate_status(ours: Status, theirs: Status, fork_filter=None) -> None:
    if theirs.network_id != ours.network_id:
        raise PeerError(f"network id mismatch: {theirs.network_id}")
    if theirs.genesis != ours.genesis:
        raise PeerError("genesis mismatch")
    if fork_filter is not None:
        # EIP-2124: reject peers whose fork history is incompatible
        try:
            fork_filter(theirs.fork_id)
        except ValueError as e:
            raise PeerError(f"incompatible fork id: {e}") from None
