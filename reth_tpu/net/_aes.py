"""Optional-dependency shim over the ``cryptography`` AES primitives.

RLPx framing, the ECIES handshake, and discv5 packet crypto need OpenSSL
AES (CTR/ECB/GCM) from the third-party ``cryptography`` package — but
nothing else in the repo does, and the package is absent in minimal
containers. Importing the net stack (or anything that pulls it in, e.g.
``era.py`` for its snappy codec) must therefore never require it: the
real import is attempted here ONCE, and when it fails every AES entry
point below raises a clear ``ModuleNotFoundError`` at FIRST USE instead
of at import time. Tests gate on :data:`HAVE_CRYPTOGRAPHY` /
``pytest.importorskip("cryptography")``.
"""

from __future__ import annotations

try:
    from cryptography.hazmat.primitives.ciphers import (  # noqa: F401
        Cipher,
        algorithms,
        modes,
    )
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM  # noqa: F401

    HAVE_CRYPTOGRAPHY = True
except ModuleNotFoundError:  # optional dep absent: defer failure to use
    HAVE_CRYPTOGRAPHY = False

    _MSG = ("the 'cryptography' package is required for RLPx/ECIES/discv5 "
            "AES but is not installed; encrypted networking is unavailable")

    class _MissingCallable:
        """Stands in for Cipher/AESGCM: constructing one raises."""

        def __init__(self, *args, **kwargs):
            raise ModuleNotFoundError(_MSG)

    class _MissingNamespace:
        """Stands in for algorithms/modes: any attribute access raises."""

        def __getattr__(self, name):
            raise ModuleNotFoundError(_MSG)

    Cipher = AESGCM = _MissingCallable
    algorithms = _MissingNamespace()
    modes = _MissingNamespace()
