"""discv4: UDP Kademlia node discovery (ping/pong/findnode/neighbors).

Reference analogue: crates/net/discv4/src/lib.rs. Packet layout (devp2p):

  hash(32) = keccak256(signature || type || data)
  signature(65) = sign(keccak256(type || data)) as r(32)||s(32)||v(1)
  type(1), data = RLP list per message

Messages: Ping [vsn=4, from, to, expiration], Pong [to, ping-hash,
expiration], FindNode [target-pubkey, expiration], Neighbors [[nodes],
expiration]; endpoint = [ip, udp-port, tcp-port]. Node identity =
uncompressed secp256k1 public key; Kademlia distance =
xor(keccak(id-a), keccak(id-b)). Only bonded peers (recent pong) get
findnode answers (endpoint-proof rule).
"""

from __future__ import annotations

import ipaddress
import socket
import threading
import time

from ..primitives import secp256k1
from ..primitives.keccak import keccak256
from ..primitives.rlp import decode_int, encode_int, rlp_decode_prefix, rlp_encode
from ..primitives.secp256k1 import pubkey_from_bytes, pubkey_from_priv, pubkey_to_bytes

PING, PONG, FINDNODE, NEIGHBORS = 0x01, 0x02, 0x03, 0x04
VSN = 4
EXPIRATION = 20
BUCKET_SIZE = 16
BOND_TTL = 12 * 3600
ALPHA = 3  # lookup concurrency


class DiscError(ValueError):
    pass


def _endpoint(ip: str, udp: int, tcp: int) -> list:
    return [ipaddress.ip_address(ip).packed, encode_int(udp), encode_int(tcp)]


def _decode_endpoint(f) -> tuple[str, int, int]:
    return (str(ipaddress.ip_address(bytes(f[0]))) if f[0] else "0.0.0.0",
            decode_int(f[1]), decode_int(f[2]))


def encode_packet(priv: int, ptype: int, data: list) -> bytes:
    body = bytes([ptype]) + rlp_encode(data)
    y, r, s = secp256k1.sign(keccak256(body), priv)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([y])
    return keccak256(sig + body) + sig + body


def decode_packet(raw: bytes) -> tuple[bytes, bytes, int, list]:
    """-> (packet-hash, sender node id, type, fields)."""
    if len(raw) < 32 + 65 + 1:
        raise DiscError("packet too short")
    h, sig, body = raw[:32], raw[32:97], raw[97:]
    if keccak256(sig + body) != h:
        raise DiscError("bad packet hash")
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:64], "big")
    node = secp256k1.ecrecover(keccak256(body), sig[64], r, s,
                               allow_high_s=True, return_pubkey=True)
    fields, _ = rlp_decode_prefix(body[1:])
    return h, node, body[0], fields


def log_distance(a: bytes, b: bytes) -> int:
    """Kademlia bucket index: bit length of xor(keccak(a), keccak(b))."""
    x = int.from_bytes(keccak256(a), "big") ^ int.from_bytes(keccak256(b), "big")
    return x.bit_length()


class NodeRecord:
    __slots__ = ("node_id", "ip", "udp_port", "tcp_port", "last_pong")

    def __init__(self, node_id: bytes, ip: str, udp_port: int, tcp_port: int):
        self.node_id = node_id
        self.ip = ip
        self.udp_port = udp_port
        self.tcp_port = tcp_port
        self.last_pong = 0.0

    @property
    def bonded(self) -> bool:
        return time.monotonic() - self.last_pong < BOND_TTL if self.last_pong else False

    def enode(self) -> str:
        return f"enode://{self.node_id.hex()}@{self.ip}:{self.tcp_port}"


class KademliaTable:
    """256 xor-distance buckets of at most BUCKET_SIZE live records."""

    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.buckets: dict[int, list[NodeRecord]] = {}
        self.by_id: dict[bytes, NodeRecord] = {}

    def add(self, rec: NodeRecord) -> NodeRecord:
        existing = self.by_id.get(rec.node_id)
        if existing is not None:
            existing.ip, existing.udp_port, existing.tcp_port = (
                rec.ip, rec.udp_port, rec.tcp_port)
            return existing
        d = log_distance(self.local_id, rec.node_id)
        bucket = self.buckets.setdefault(d, [])
        if len(bucket) >= BUCKET_SIZE:
            # evict the stalest unbonded entry; full-of-bonded drops the new
            stale = min((r for r in bucket if not r.bonded),
                        key=lambda r: r.last_pong, default=None)
            if stale is None:
                return rec
            bucket.remove(stale)
            self.by_id.pop(stale.node_id, None)
        bucket.append(rec)
        self.by_id[rec.node_id] = rec
        return rec

    def closest(self, target_id: bytes, n: int = BUCKET_SIZE) -> list[NodeRecord]:
        t = int.from_bytes(keccak256(target_id), "big")
        return sorted(
            self.by_id.values(),
            key=lambda r: t ^ int.from_bytes(keccak256(r.node_id), "big"),
        )[:n]

    def __len__(self) -> int:
        return len(self.by_id)


class Discv4:
    """One discovery endpoint: UDP listener + Kademlia table + lookups."""

    def __init__(self, node_priv: int, host: str = "127.0.0.1", port: int = 0,
                 tcp_port: int = 0):
        self.priv = node_priv
        self.node_id = pubkey_to_bytes(pubkey_from_priv(node_priv))
        self.host = host
        self.tcp_port = tcp_port
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.settimeout(0.2)
        self.port = self.sock.getsockname()[1]
        self.table = KademliaTable(self.node_id)
        self._pending_pings: dict[bytes, NodeRecord] = {}  # ping-hash -> rec
        self._neighbors_waiters: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.sock.close()

    def enode(self) -> str:
        tcp = self.tcp_port or self.port
        url = f"enode://{self.node_id.hex()}@{self.host}:{tcp}"
        if self.port != tcp:
            url += f"?discport={self.port}"  # standard split-port form
        return url

    # -- outbound -----------------------------------------------------------

    def _send(self, addr, ptype: int, data: list) -> bytes:
        pkt = encode_packet(self.priv, ptype, data)
        self.sock.sendto(pkt, addr)
        return pkt[:32]

    def _expiration(self) -> bytes:
        return encode_int(int(time.time()) + EXPIRATION)

    def ping(self, rec: NodeRecord) -> None:
        data = [
            encode_int(VSN),
            _endpoint(self.host, self.port, self.tcp_port or self.port),
            _endpoint(rec.ip, rec.udp_port, rec.tcp_port),
            self._expiration(),
        ]
        pkt = encode_packet(self.priv, PING, data)
        with self._lock:
            # register BEFORE sendto: on loopback the PONG can beat the
            # sender back to the bookkeeping and the bond would be lost
            self._pending_pings[pkt[:32]] = rec
        self.sock.sendto(pkt, (rec.ip, rec.udp_port))

    def find_node(self, rec: NodeRecord, target_id: bytes) -> None:
        self._send((rec.ip, rec.udp_port), FINDNODE,
                   [target_id, self._expiration()])

    def bootstrap(self, enodes: list[str]) -> None:
        from .server import parse_enode

        for url in enodes:
            url, _, query = url.partition("?")
            pub, host, tcp = parse_enode(url)
            udp = tcp
            if query.startswith("discport="):
                udp = int(query[len("discport="):])
            rec = NodeRecord(pubkey_to_bytes(pub), host, udp, tcp)
            with self._lock:
                rec = self.table.add(rec)
            self.ping(rec)

    def lookup(self, target_id: bytes | None = None, rounds: int = 3,
               wait: float = 0.5) -> list[NodeRecord]:
        """Iterative FINDNODE toward ``target_id`` (default: self — the
        bootstrap self-lookup that populates the table)."""
        target = target_id or self.node_id
        seen: set[bytes] = set()
        for _ in range(rounds):
            with self._lock:
                candidates = [r for r in self.table.closest(target, ALPHA * 2)
                              if r.bonded and r.node_id not in seen]
            for rec in candidates[:ALPHA]:
                seen.add(rec.node_id)
                self.find_node(rec, target)
            time.sleep(wait)
        with self._lock:
            return self.table.closest(target)

    # -- inbound ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                raw, addr = self.sock.recvfrom(1500)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                h, node, ptype, fields = decode_packet(raw)
                self._handle(h, node, ptype, fields, addr)
            except Exception:  # noqa: BLE001 — packet fields are attacker-
                # controlled; any parse error drops the packet, not the loop
                continue

    def _expired(self, exp_field) -> bool:
        return decode_int(exp_field) < time.time()

    def _handle(self, h: bytes, node: bytes, ptype: int, f: list, addr) -> None:
        if node == self.node_id:
            return
        if ptype == PING:
            if self._expired(f[3]):
                return
            # observed ip/udp (anti-spoof) + the sender's DECLARED tcp port
            try:
                _, _, tcp = _decode_endpoint(f[1])
            except (ValueError, IndexError):
                tcp = addr[1]
            rec = NodeRecord(node, addr[0], addr[1], tcp or addr[1])
            with self._lock:
                rec = self.table.add(rec)
            self._send(addr, PONG,
                       [_endpoint(addr[0], addr[1], addr[1]), h, self._expiration()])
            if not rec.bonded:
                self.ping(rec)  # bond both ways
        elif ptype == PONG:
            ping_hash = bytes(f[1])
            with self._lock:
                rec = self._pending_pings.pop(ping_hash, None)
            if rec is not None and rec.node_id == node:
                rec.last_pong = time.monotonic()
        elif ptype == FINDNODE:
            if self._expired(f[1]):
                return
            with self._lock:
                rec = self.table.by_id.get(node)
                if rec is None or not rec.bonded:
                    return  # endpoint proof required
                closest = self.table.closest(bytes(f[0]))
            nodes = [
                _endpoint(r.ip, r.udp_port, r.tcp_port) + [r.node_id]
                for r in closest
            ]
            self._send(addr, NEIGHBORS, [nodes, self._expiration()])
        elif ptype == NEIGHBORS:
            for nf in f[0]:
                ip, udp, tcp = _decode_endpoint(nf[:3])
                nid = bytes(nf[3])
                if nid == self.node_id:
                    continue
                try:
                    pubkey_from_bytes(nid)
                except ValueError:
                    continue
                rec = NodeRecord(nid, ip, udp, tcp)
                with self._lock:
                    known = rec.node_id in self.table.by_id
                    rec = self.table.add(rec)
                if not known and not rec.bonded:
                    self.ping(rec)
