"""Peer reputation + ban list.

Reference analogue: the reputation weights and ban handling in
crates/net/network/src/peers.rs + crates/net/banlist. Every peer carries
a score; protocol violations apply weighted penalties, and crossing the
ban threshold drops the session and refuses reconnects until the ban
expires. Scores decay back toward zero so transient flakiness heals.
"""

from __future__ import annotations

import time

BANNED_REPUTATION = -50_00
DEFAULT_BAN_SECONDS = 30 * 60

# penalty weights (shape mirrors the reference's ReputationChangeKind)
REPUTATION_CHANGE = {
    "bad_message": -16_00,       # undecodable / protocol-violating message
    "bad_block": -25_00,         # invalid block or header chain
    "bad_transactions": -8_00,
    "timeout": -4_00,
    "failed_to_connect": -2_00,
    "dropped": -1_00,
    "good": 5_00,                # useful response
}

_DECAY_PER_SECOND = 10  # points recovered per second toward zero


class PeerRecord:
    __slots__ = ("reputation", "banned_until", "_last")

    def __init__(self):
        self.reputation = 0
        self.banned_until = 0.0
        self._last = time.monotonic()

    def _decay(self) -> None:
        now = time.monotonic()
        points = int((now - self._last) * _DECAY_PER_SECOND)
        if points <= 0:
            return  # keep _last: fractional credit accumulates across calls
        self._last += points / _DECAY_PER_SECOND
        if self.reputation < 0:
            self.reputation = min(0, self.reputation + points)


class PeersManager:
    """Reputation accounting keyed by node id (64-byte pubkey)."""

    def __init__(self, ban_seconds: float = DEFAULT_BAN_SECONDS):
        self.ban_seconds = ban_seconds
        self.peers: dict[bytes, PeerRecord] = {}

    def _rec(self, node_id: bytes) -> PeerRecord:
        rec = self.peers.get(node_id)
        if rec is None:
            rec = self.peers[node_id] = PeerRecord()
        rec._decay()
        return rec

    def reputation_change(self, node_id: bytes, kind: str) -> int:
        """Apply a weighted change; bans the peer past the threshold.
        Returns the new reputation."""
        rec = self._rec(node_id)
        rec.reputation += REPUTATION_CHANGE.get(kind, -1_00)
        if rec.reputation <= BANNED_REPUTATION:
            rec.banned_until = time.monotonic() + self.ban_seconds
        return rec.reputation

    def ban(self, node_id: bytes, seconds: float | None = None) -> None:
        rec = self._rec(node_id)
        rec.banned_until = time.monotonic() + (
            seconds if seconds is not None else self.ban_seconds
        )
        rec.reputation = BANNED_REPUTATION

    def unban(self, node_id: bytes) -> None:
        rec = self._rec(node_id)
        rec.banned_until = 0.0
        rec.reputation = 0

    def is_banned(self, node_id: bytes) -> bool:
        rec = self.peers.get(node_id)
        if rec is None:
            return False
        if rec.banned_until and time.monotonic() >= rec.banned_until:
            rec.banned_until = 0.0
            rec.reputation = 0  # ban served
        return bool(rec.banned_until)

    def reputation(self, node_id: bytes) -> int:
        return self._rec(node_id).reputation
