"""Sync downloaders: headers + bodies from a peer into the pipeline.

Reference analogue: crates/net/downloaders — `ReverseHeadersDownloader`
(tip→local batched header download) and `BodiesDownloader`, feeding the
staged pipeline. ``sync_from_peer`` is the full networked-sync flow:
fetch headers to the peer's tip, validate linkage, fetch bodies, insert
via import, run the pipeline.
"""

from __future__ import annotations

from ..consensus import EthBeaconConsensus
from ..primitives.types import Block
from ..storage.genesis import import_chain
from .p2p import PeerConnection, PeerError

HEADER_BATCH = 192
BODY_BATCH = 128


def download_headers(peer: PeerConnection, from_block: int, to_block: int) -> list:
    """Forward header download [from_block, to_block] in batches."""
    headers = []
    n = from_block
    while n <= to_block:
        limit = min(HEADER_BATCH, to_block - n + 1)
        batch = peer.get_headers(n, limit)
        if not batch:
            raise PeerError(f"peer returned no headers at {n}")
        for h in batch:
            if h.number != n:
                raise PeerError(f"non-contiguous header {h.number} != {n}")
            if headers and h.parent_hash != headers[-1].hash:
                raise PeerError(f"broken parent link at {h.number}")
            headers.append(h)
            n += 1
    return headers


def download_bodies(peer: PeerConnection, headers: list) -> list[Block]:
    """Fetch bodies for ``headers``; returns sealed blocks, validated."""
    blocks = []
    for i in range(0, len(headers), BODY_BATCH):
        chunk = headers[i : i + BODY_BATCH]
        bodies = peer.get_bodies([h.hash for h in chunk])
        if len(bodies) != len(chunk):
            raise PeerError("missing bodies in response")
        for header, body in zip(chunk, bodies):
            blocks.append(Block(header, body.transactions, body.ommers, body.withdrawals))
    return blocks


def sync_from_peer(factory, peer: PeerConnection, pipeline=None,
                   consensus: EthBeaconConsensus | None = None) -> int:
    """Sync to the peer's head; returns the new local tip.

    The networked version of `reth import`: headers (with linkage checks)
    → bodies → import (pre-execution validation) → staged pipeline.
    """
    consensus = consensus or EthBeaconConsensus()
    with factory.provider() as p:
        local_tip = p.last_block_number()
    # peer head number: ask for its head header by hash
    head = peer.get_headers(peer.status.head, 1)
    if not head:
        return local_tip
    target = head[0].number
    if target <= local_tip:
        return local_tip
    headers = download_headers(peer, local_tip + 1, target)
    blocks = download_bodies(peer, headers)
    tip = import_chain(factory, blocks, consensus)
    if pipeline is not None:
        pipeline.run(tip)
    return tip
