"""Sync downloaders: headers + bodies from a peer into the pipeline.

Reference analogue: crates/net/downloaders — `ReverseHeadersDownloader`
(tip→local batched header download) and `BodiesDownloader`, feeding the
staged pipeline. ``sync_from_peer`` is the full networked-sync flow:
fetch headers to the peer's tip, validate linkage, fetch bodies, insert
via import, run the pipeline.
"""

from __future__ import annotations

from ..consensus import EthBeaconConsensus
from ..primitives.types import Block
from ..storage.genesis import import_chain
from .p2p import PeerConnection, PeerError

HEADER_BATCH = 192
BODY_BATCH = 128


def download_headers(peer: PeerConnection, from_block: int, to_block: int) -> list:
    """Forward header download [from_block, to_block] in batches."""
    headers = []
    n = from_block
    while n <= to_block:
        limit = min(HEADER_BATCH, to_block - n + 1)
        batch = peer.get_headers(n, limit)[:limit]  # cap over-long responses
        if not batch:
            raise PeerError(f"peer returned no headers at {n}")
        for h in batch:
            if h.number != n:
                raise PeerError(f"non-contiguous header {h.number} != {n}")
            if headers and h.parent_hash != headers[-1].hash:
                raise PeerError(f"broken parent link at {h.number}")
            headers.append(h)
            n += 1
    return headers


def download_bodies(peer: PeerConnection, headers: list) -> list[Block]:
    """Fetch bodies for ``headers``; returns sealed blocks, validated."""
    blocks = []
    for i in range(0, len(headers), BODY_BATCH):
        chunk = headers[i : i + BODY_BATCH]
        bodies = peer.get_bodies([h.hash for h in chunk])
        if len(bodies) != len(chunk):
            raise PeerError("missing bodies in response")
        for header, body in zip(chunk, bodies):
            blocks.append(Block(header, body.transactions, body.ommers, body.withdrawals))
    return blocks


def sync_from_peer(factory, peer: PeerConnection, pipeline=None,
                   consensus: EthBeaconConsensus | None = None,
                   committer=None, extra_peers: tuple = ()) -> int:
    """Sync to the peer's head; returns the new local tip.

    With no ``pipeline`` given, the ONLINE stage set drives the whole
    sync — the pipeline's Headers/Bodies stages pull from the peer with
    checkpointed, per-chunk commits (reference OnlineStages). A supplied
    pipeline keeps the legacy flow: bulk download → import → run.
    """
    consensus = consensus or EthBeaconConsensus(committer)
    with factory.provider() as p:
        local_tip = p.last_block_number()
        finish_cp = p.stage_checkpoint("Finish")
    # peer head number: ask for its head header by hash
    head = peer.get_headers(peer.status.head, 1)
    if not head:
        return local_tip
    target = head[0].number
    if pipeline is None:
        # online path: progress is measured by the PIPELINE (a crash after
        # a Headers chunk leaves last_block_number ahead of the real sync)
        if target <= finish_cp:
            return local_tip
        from ..stages import Pipeline, online_stages

        with factory.provider_rw() as p:
            # a legacy-imported DB holds headers/bodies without download
            # checkpoints: baseline them to what is ACTUALLY present (not
            # the Finish checkpoint — a crash between import and pipeline
            # completion leaves bodies above it, and re-inserting a body
            # renumbers its transactions and corrupts the tx tables)
            if p.stage_checkpoint("Headers") < local_tip:
                p.save_stage_checkpoint("Headers", local_tip)
            b_cp = p.stage_checkpoint("Bodies")
            n = b_cp + 1
            while n <= local_tip and p.block_body_indices(n) is not None:
                n += 1
            if n - 1 > b_cp:
                p.save_stage_checkpoint("Bodies", n - 1)
        Pipeline(factory, online_stages(peer, committer=committer,
                                        consensus=consensus,
                                        extra_peers=extra_peers)).run(target)
        return target
    if target <= local_tip:
        return local_tip
    headers = download_headers(peer, local_tip + 1, target)
    blocks = download_bodies(peer, headers)
    tip = import_chain(factory, blocks, consensus)
    pipeline.run(tip)
    return tip


def download_headers_reverse(peer: PeerConnection, tip_hash: bytes,
                             stop_number: int | None = None,
                             batch: int = HEADER_BATCH,
                             count: int | None = None) -> list:
    """Reverse tip→local header download (reference
    `ReverseHeadersDownloader`, crates/net/downloaders/src/headers/
    reverse_headers.rs): start from a TRUSTED tip HASH (forkchoice head —
    its number is unknown up front) and walk parent links downward in
    batches. Every header authenticates by hashing into the previously
    verified child, so a lying peer cannot inject a header anywhere in
    the range. Returns headers ASCENDING. The walk is bounded by EITHER
    ``stop_number`` (first returned number = stop_number + 1) OR ``count``
    headers (the FullBlockClient mode) — exactly one must be given.
    """
    assert (stop_number is None) != (count is None), \
        "give exactly one of stop_number/count"
    out = []  # filled tip-first (descending)
    want = tip_hash
    while True:
        limit = batch if count is None else min(batch, count - len(out))
        hdrs = peer.get_headers(want, limit, reverse=True)
        if not hdrs:
            raise PeerError(f"peer returned no headers for {want.hex()[:16]}")
        for h in hdrs[:limit]:
            if h.hash != want:
                raise PeerError(
                    f"header {h.number} does not hash-link to its child")
            if stop_number is not None and h.number <= stop_number:
                raise PeerError(
                    f"peer walked past the local chain at {h.number}")
            out.append(h)
            want = h.parent_hash
            if (stop_number is not None and h.number == stop_number + 1) \
                    or (count is not None and len(out) == count):
                return list(reversed(out))


class BodiesDownloader:
    """Concurrent body download over MULTIPLE peers with bounded in-flight
    windows (reference crates/net/downloaders/src/bodies/): the header
    range splits into fixed windows, workers (one per peer) claim windows
    from a shared queue, responses arrive out of order and re-assemble by
    index. Each response is validated against its headers (body roots);
    a bad or failing peer is penalized through the reputation sink, its
    worker retires, and its window re-queues to a healthy peer.
    """

    def __init__(self, peers: list, window: int = BODY_BATCH,
                 reporter=None, consensus=None):
        """``peers``: PeerConnection-likes with ``get_bodies``.
        ``reporter(peer, kind)``: reputation sink (kind is a
        REPUTATION_CHANGE key, e.g. "bad_message" / "timeout")."""
        self.peers = list(peers)
        self.window = window
        self.reporter = reporter or (lambda peer, kind: None)
        from ..consensus import EthBeaconConsensus

        self.consensus = consensus or EthBeaconConsensus()
        self.stats: dict[int, int] = {}  # peer index -> windows served

    def download(self, headers: list) -> list[Block]:
        if not headers:
            return []
        import threading

        windows = [headers[i:i + self.window]
                   for i in range(0, len(headers), self.window)]
        results: list[list[Block] | None] = [None] * len(windows)
        # window states: "todo" | "inflight" | "done". A failed window
        # returns to "todo"; healthy workers WAIT while anything is
        # inflight elsewhere instead of exiting on an empty claim — a
        # transient failure re-queues to a live peer, never to nobody.
        state = {i: "todo" for i in range(len(windows))}
        cond = threading.Condition()

        def fetch_window(peer, idx: int) -> list[Block]:
            chunk = windows[idx]
            bodies = peer.get_bodies([h.hash for h in chunk])
            if len(bodies) != len(chunk):
                raise PeerError("missing bodies in response")
            out = []
            for header, body in zip(chunk, bodies):
                blk = Block(header, body.transactions, body.ommers,
                            body.withdrawals)
                # roots bind the body to ITS header: a peer cannot serve
                # the wrong (or tampered) body undetected
                from ..consensus import ConsensusError

                try:
                    self.consensus.validate_block_pre_execution(blk)
                except ConsensusError as e:
                    raise PeerError(f"body {header.number} invalid: {e}")
                out.append(blk)
            return out

        def claim() -> int | None:
            """Next todo window; None when every window is done. Blocks
            while windows are only in flight at OTHER workers (they may
            fail and re-queue here)."""
            with cond:
                while True:
                    todo_idx = next((i for i, s in state.items()
                                     if s == "todo"), None)
                    if todo_idx is not None:
                        state[todo_idx] = "inflight"
                        return todo_idx
                    if all(s == "done" for s in state.values()):
                        return None
                    cond.wait(timeout=0.2)

        def worker(pi: int, peer) -> None:
            while True:
                idx = claim()
                if idx is None:
                    return
                try:
                    got = fetch_window(peer, idx)
                except Exception:  # noqa: BLE001 — ANY failure must
                    # release the inflight window or waiters starve
                    # penalize, re-queue the window, retire this peer
                    self.reporter(peer, "bad_message")
                    with cond:
                        state[idx] = "todo"
                        cond.notify_all()
                    return
                with cond:
                    results[idx] = got
                    state[idx] = "done"
                    self.stats[pi] = self.stats.get(pi, 0) + 1
                    cond.notify_all()

        threads = [threading.Thread(target=worker, args=(i, p), daemon=True)
                   for i, p in enumerate(self.peers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        missing = [i for i, r in enumerate(results) if r is None]
        if missing:
            raise PeerError(
                f"{len(missing)} body windows unserved (all peers failed)")
        return [blk for window_result in results for blk in window_result]


class FullBlockClient:
    """Composable full-block fetch over any header+body client.

    Reference analogue: `FullBlockClient` (crates/net/p2p/src/full_block.rs)
    — wraps a client exposing ``get_headers``/``get_bodies`` and returns
    SEALED blocks: the header is guaranteed to match the requested hash,
    bodies are validated against their headers (transaction/ommers/
    withdrawals roots) with bounded retries for mismatched responses.
    The engine uses this to fill parent gaps during live sync.
    """

    MAX_RETRIES = 3

    def __init__(self, client, consensus=None):
        from ..consensus import EthBeaconConsensus

        self.client = client
        self.consensus = consensus or EthBeaconConsensus()

    def get_full_block(self, block_hash: bytes) -> Block:
        """One sealed block by hash; raises PeerError after retries."""
        return self.get_full_block_range(block_hash, 1)[0]

    def get_full_block_range(self, start_hash: bytes,
                             count: int) -> list[Block]:
        """``count`` sealed blocks ending at ``start_hash``, DESCENDING by
        number (reference semantics: walk parent links downward)."""
        headers = list(reversed(download_headers_reverse(
            self.client, start_hash, count=count)))
        blocks: list[Block | None] = [None] * len(headers)
        remaining = list(range(len(headers)))
        from ..consensus import ConsensusError

        from ..trie.state_root import ordered_trie_root

        for _attempt in range(self.MAX_RETRIES):
            want = [headers[i] for i in remaining]
            bodies = self.client.get_bodies([h.hash for h in want])
            # eth GetBlockBodies OMITS unknown hashes (no gaps): align by
            # each body's TRANSACTIONS ROOT instead of position — a
            # mid-list omission skips its header, and a corrupt body that
            # matches no header is discarded instead of starving the scan
            k = 0
            still = []
            pending = list(remaining)
            while pending:
                if k >= len(bodies):
                    still.extend(pending)
                    break
                body = bodies[k]
                tx_root = ordered_trie_root(
                    [tx.encode() for tx in body.transactions])
                j = next((idx for idx, i in enumerate(pending)
                          if headers[i].transactions_root == tx_root), None)
                if j is None:
                    k += 1  # unmatchable (corrupt/foreign) body: discard
                    continue
                # headers skipped over were OMITTED by the peer
                still.extend(pending[:j])
                i = pending[j]
                pending = pending[j + 1:]
                blk = Block(headers[i], body.transactions, body.ommers,
                            body.withdrawals)
                try:
                    self.consensus.validate_block_pre_execution(blk)
                    blocks[i] = blk
                except ConsensusError:
                    still.append(i)  # tx root matched but ommers/blob
                    # gas/withdrawals did not: refetch this one
                k += 1
            remaining = sorted(still)
            if not remaining:
                return blocks
        raise PeerError(
            f"{len(remaining)} bodies failed validation after retries")
