"""Sync downloaders: headers + bodies from a peer into the pipeline.

Reference analogue: crates/net/downloaders — `ReverseHeadersDownloader`
(tip→local batched header download) and `BodiesDownloader`, feeding the
staged pipeline. ``sync_from_peer`` is the full networked-sync flow:
fetch headers to the peer's tip, validate linkage, fetch bodies, insert
via import, run the pipeline.
"""

from __future__ import annotations

from ..consensus import EthBeaconConsensus
from ..primitives.types import Block
from ..storage.genesis import import_chain
from .p2p import PeerConnection, PeerError

HEADER_BATCH = 192
BODY_BATCH = 128


def download_headers(peer: PeerConnection, from_block: int, to_block: int) -> list:
    """Forward header download [from_block, to_block] in batches."""
    headers = []
    n = from_block
    while n <= to_block:
        limit = min(HEADER_BATCH, to_block - n + 1)
        batch = peer.get_headers(n, limit)[:limit]  # cap over-long responses
        if not batch:
            raise PeerError(f"peer returned no headers at {n}")
        for h in batch:
            if h.number != n:
                raise PeerError(f"non-contiguous header {h.number} != {n}")
            if headers and h.parent_hash != headers[-1].hash:
                raise PeerError(f"broken parent link at {h.number}")
            headers.append(h)
            n += 1
    return headers


def download_bodies(peer: PeerConnection, headers: list) -> list[Block]:
    """Fetch bodies for ``headers``; returns sealed blocks, validated."""
    blocks = []
    for i in range(0, len(headers), BODY_BATCH):
        chunk = headers[i : i + BODY_BATCH]
        bodies = peer.get_bodies([h.hash for h in chunk])
        if len(bodies) != len(chunk):
            raise PeerError("missing bodies in response")
        for header, body in zip(chunk, bodies):
            blocks.append(Block(header, body.transactions, body.ommers, body.withdrawals))
    return blocks


def sync_from_peer(factory, peer: PeerConnection, pipeline=None,
                   consensus: EthBeaconConsensus | None = None,
                   committer=None) -> int:
    """Sync to the peer's head; returns the new local tip.

    With no ``pipeline`` given, the ONLINE stage set drives the whole
    sync — the pipeline's Headers/Bodies stages pull from the peer with
    checkpointed, per-chunk commits (reference OnlineStages). A supplied
    pipeline keeps the legacy flow: bulk download → import → run.
    """
    consensus = consensus or EthBeaconConsensus(committer)
    with factory.provider() as p:
        local_tip = p.last_block_number()
        finish_cp = p.stage_checkpoint("Finish")
    # peer head number: ask for its head header by hash
    head = peer.get_headers(peer.status.head, 1)
    if not head:
        return local_tip
    target = head[0].number
    if pipeline is None:
        # online path: progress is measured by the PIPELINE (a crash after
        # a Headers chunk leaves last_block_number ahead of the real sync)
        if target <= finish_cp:
            return local_tip
        from ..stages import Pipeline, online_stages

        with factory.provider_rw() as p:
            # a legacy-imported DB holds headers/bodies without download
            # checkpoints: baseline them to what is ACTUALLY present (not
            # the Finish checkpoint — a crash between import and pipeline
            # completion leaves bodies above it, and re-inserting a body
            # renumbers its transactions and corrupts the tx tables)
            if p.stage_checkpoint("Headers") < local_tip:
                p.save_stage_checkpoint("Headers", local_tip)
            b_cp = p.stage_checkpoint("Bodies")
            n = b_cp + 1
            while n <= local_tip and p.block_body_indices(n) is not None:
                n += 1
            if n - 1 > b_cp:
                p.save_stage_checkpoint("Bodies", n - 1)
        Pipeline(factory, online_stages(peer, committer=committer,
                                        consensus=consensus)).run(target)
        return target
    if target <= local_tip:
        return local_tip
    headers = download_headers(peer, local_tip + 1, target)
    blocks = download_bodies(peer, headers)
    tip = import_chain(factory, blocks, consensus)
    pipeline.run(tip)
    return tip
