"""Session manager: peer-session lifecycle, caps, and event routing.

Reference analogue: crates/net/network — `SessionManager`
(src/session/mod.rs) inside the `Swarm` (src/swarm.rs) driven by
`NetworkManager` (src/manager.rs:108). There, every connection moves
through pending-handshake → active → closed under a central manager that
enforces inbound/outbound caps, stamps sessions with identity and
counters, and publishes `SessionEvent`s the rest of the node consumes
(peer discovery feedback, metrics, tx propagation targets).

The transport here stays thread-per-peer (idiomatic Python I/O); this
layer owns the ARCHITECTURE: capacity reservation happens before the
handshake (so a flood cannot exhaust handshake resources), activation
binds the session to its RLPx identity, closure records the reason, and
every transition fans out to registered listeners.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum


class SessionState(Enum):
    PENDING = "pending"        # reserved; handshake in progress
    ACTIVE = "active"          # authenticated, serving requests
    CLOSED = "closed"


@dataclass
class Session:
    direction: str                      # "inbound" | "outbound"
    state: SessionState = SessionState.PENDING
    peer: object = None                 # PeerConnection once active
    node_id: bytes | None = None
    established_at: float = 0.0
    closed_at: float = 0.0
    close_reason: str | None = None
    messages_in: int = 0
    messages_out: int = 0

    @property
    def uptime(self) -> float:
        if not self.established_at:
            return 0.0  # closed before activation (failed handshake)
        end = self.closed_at or time.monotonic()
        return max(0.0, end - self.established_at)


class SessionLimitExceeded(Exception):
    """No capacity for a new session in the requested direction."""


class SessionManager:
    """Tracks every session from reservation to closure."""

    def __init__(self, max_inbound: int = 30, max_outbound: int = 100):
        self.max_inbound = max_inbound
        self.max_outbound = max_outbound
        self._lock = threading.Lock()
        self.sessions: list[Session] = []
        self.listeners: list = []       # callables(event: str, session)
        self.total_established = 0
        self.total_closed = 0

    # -- lifecycle -------------------------------------------------------------

    def reserve(self, direction: str) -> Session:
        """Claim capacity BEFORE the handshake (reference: incoming
        connections count against the cap from accept time, so a dial
        flood cannot starve the handshake path). Raises
        SessionLimitExceeded at the cap."""
        cap = self.max_inbound if direction == "inbound" else self.max_outbound
        with self._lock:
            live = sum(1 for s in self.sessions
                       if s.direction == direction
                       and s.state is not SessionState.CLOSED)
            if live >= cap:
                raise SessionLimitExceeded(
                    f"{direction} session limit {cap} reached")
            s = Session(direction=direction)
            self.sessions.append(s)
            return s

    def activate(self, session: Session, peer) -> None:
        """Handshake completed: bind identity, publish Established."""
        with self._lock:
            session.peer = peer
            session.node_id = getattr(peer, "node_id", None)
            session.state = SessionState.ACTIVE
            session.established_at = time.monotonic()
            self.total_established += 1
        self._emit("established", session)

    def close(self, session: Session, reason: str = "disconnected") -> None:
        with self._lock:
            if session.state is SessionState.CLOSED:
                return
            session.state = SessionState.CLOSED
            session.closed_at = time.monotonic()
            session.close_reason = reason
            session.peer = None  # do not pin the connection object
            self.total_closed += 1
        self._emit("closed", session)
        self.prune_closed()

    def prune_closed(self, keep: int = 256) -> None:
        """Bound the closed-session history (diagnostics window)."""
        with self._lock:
            closed = [s for s in self.sessions
                      if s.state is SessionState.CLOSED]
            if len(closed) > keep:
                doomed = set(map(id, closed[:-keep]))
                self.sessions = [s for s in self.sessions
                                 if id(s) not in doomed]

    # -- queries ---------------------------------------------------------------

    def active(self, direction: str | None = None) -> list[Session]:
        with self._lock:
            return [s for s in self.sessions
                    if s.state is SessionState.ACTIVE
                    and (direction is None or s.direction == direction)]

    def counts(self) -> dict:
        with self._lock:
            out = {"inbound": 0, "outbound": 0, "pending": 0}
            for s in self.sessions:
                if s.state is SessionState.ACTIVE:
                    out[s.direction] += 1
                elif s.state is SessionState.PENDING:
                    out["pending"] += 1
            out["established_total"] = self.total_established
            out["closed_total"] = self.total_closed
            return out

    # -- events ----------------------------------------------------------------

    def _emit(self, event: str, session: Session) -> None:
        for fn in list(self.listeners):
            try:
                fn(event, session)
            except Exception:  # noqa: BLE001 — a listener must never
                # break session management
                continue
