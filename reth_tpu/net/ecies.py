"""ECIES transport encryption + the RLPx EIP-8 auth/ack handshake.

Reference analogue: crates/net/ecies/src/algorithm.rs — the encrypted
channel every devp2p session starts with. Scheme (devp2p spec):

- ECIES encrypt(recipient-pubkey, msg): ephemeral key e; shared x =
  ecdh(e, recipient); kE||kM = NIST-SP-800-56 concat-KDF(x, 32);
  AES-128-CTR(kE, random iv) over msg; tag = HMAC-SHA256(sha256(kM),
  iv || ciphertext || shared-mac-data). Wire form:
  0x04||ephemeral-pub(64) || iv(16) || ciphertext || tag(32).
- EIP-8 handshake: auth = 2-byte size prefix ++ ECIES over RLP
  [sig(65), initiator-pubkey(64), nonce(32), vsn=4] (the size prefix is
  the HMAC's shared-mac-data); sig = ecdsa(ephemeral-priv is RECOVERED
  by the peer from: sign(static-shared-x XOR initiator-nonce) with the
  initiator's EPHEMERAL key). ack = same framing over RLP
  [recipient-ephemeral-pubkey(64), nonce(32), vsn=4].

AES comes from the `cryptography` package (OpenSSL); everything else is
this repo's own secp256k1/keccak/RLP primitives.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import struct

from ..primitives import secp256k1
from ..primitives.rlp import rlp_decode_prefix, rlp_encode
from ..primitives.secp256k1 import (
    ecdh_x,
    pubkey_from_bytes,
    pubkey_from_priv,
    pubkey_to_bytes,
    random_priv,
)
from ._aes import Cipher, algorithms, modes  # optional-dep shim

AUTH_VSN = 4


class EciesError(ValueError):
    pass


def _kdf(secret: bytes, length: int) -> bytes:
    """NIST SP 800-56 concatenation KDF over SHA-256."""
    out = b""
    counter = 1
    while len(out) < length:
        out += hashlib.sha256(struct.pack(">I", counter) + secret).digest()
        counter += 1
    return out[:length]


def _aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    c = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return c.update(data) + c.finalize()


def encrypt(recipient_pub: tuple[int, int], msg: bytes,
            shared_mac_data: bytes = b"") -> bytes:
    eph_priv = random_priv()
    shared = ecdh_x(eph_priv, recipient_pub)
    keys = _kdf(shared, 32)
    ke, km = keys[:16], hashlib.sha256(keys[16:]).digest()
    iv = os.urandom(16)
    ct = _aes_ctr(ke, iv, msg)
    tag = hmac_mod.new(km, iv + ct + shared_mac_data, hashlib.sha256).digest()
    return b"\x04" + pubkey_to_bytes(pubkey_from_priv(eph_priv)) + iv + ct + tag


def decrypt(priv: int, data: bytes, shared_mac_data: bytes = b"") -> bytes:
    if len(data) < 1 + 64 + 16 + 32 or data[0] != 0x04:
        raise EciesError("malformed ECIES envelope")
    eph_pub = pubkey_from_bytes(data[1:65])
    iv = data[65:81]
    ct = data[81:-32]
    tag = data[-32:]
    keys = _kdf(ecdh_x(priv, eph_pub), 32)
    ke, km = keys[:16], hashlib.sha256(keys[16:]).digest()
    want = hmac_mod.new(km, iv + ct + shared_mac_data, hashlib.sha256).digest()
    if not hmac_mod.compare_digest(tag, want):
        raise EciesError("ECIES MAC mismatch")
    return _aes_ctr(ke, iv, ct)


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def _eip8_wrap(recipient_pub, payload_fields: list) -> bytes:
    """EIP-8 envelope: random pad, 2-byte size prefix as MAC data."""
    plain = rlp_encode(payload_fields) + os.urandom(100 + os.urandom(1)[0] % 100)
    # size = ECIES overhead (113) + plaintext
    size = struct.pack(">H", len(plain) + 113)
    return size + encrypt(recipient_pub, plain, shared_mac_data=size)


def _eip8_unwrap(priv: int, data: bytes) -> list:
    if len(data) < 2:
        raise EciesError("truncated handshake message")
    size = struct.unpack(">H", data[:2])[0]
    if len(data) - 2 != size:
        raise EciesError("handshake size prefix mismatch")
    plain = decrypt(priv, data[2:], shared_mac_data=data[:2])
    fields, _consumed = rlp_decode_prefix(plain)  # EIP-8: ignore padding
    return fields


class Handshake:
    """One side of the RLPx auth/ack exchange; produces the frame secrets.

    Usage (initiator):  h = Handshake(static_priv); auth = h.auth(peer_pub);
    secrets = h.finalize_initiator(ack_bytes).
    Usage (recipient):  h = Handshake(static_priv);
    ack, secrets = h.on_auth(auth_bytes).
    """

    def __init__(self, static_priv: int, eph_priv: int | None = None,
                 nonce: bytes | None = None):
        self.static_priv = static_priv
        self.eph_priv = eph_priv or random_priv()
        self.nonce = nonce or os.urandom(32)
        self._auth_bytes: bytes | None = None
        self._ack_bytes: bytes | None = None
        self.remote_pub: tuple[int, int] | None = None

    # -- initiator ----------------------------------------------------------

    def auth(self, recipient_pub: tuple[int, int]) -> bytes:
        self.remote_pub = recipient_pub
        token = ecdh_x(self.static_priv, recipient_pub)
        digest = _xor(token, self.nonce)
        y, r, s = secp256k1.sign(digest, self.eph_priv)
        sig = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([y])
        fields = [sig, pubkey_to_bytes(pubkey_from_priv(self.static_priv)),
                  self.nonce, bytes([AUTH_VSN])]
        self._auth_bytes = _eip8_wrap(recipient_pub, fields)
        return self._auth_bytes

    def finalize_initiator(self, ack_bytes: bytes) -> "FrameSecrets":
        f = _eip8_unwrap(self.static_priv, ack_bytes)
        remote_eph = pubkey_from_bytes(f[0])
        remote_nonce = f[1]
        self._ack_bytes = ack_bytes
        eph_shared = ecdh_x(self.eph_priv, remote_eph)
        return derive_secrets(
            eph_shared, self.nonce, remote_nonce,
            self._auth_bytes, ack_bytes, initiator=True,
        )

    # -- recipient ----------------------------------------------------------

    def on_auth(self, auth_bytes: bytes) -> tuple[bytes, "FrameSecrets"]:
        f = _eip8_unwrap(self.static_priv, auth_bytes)
        sig, initiator_pub_raw, init_nonce = f[0], f[1], f[2]
        initiator_pub = pubkey_from_bytes(initiator_pub_raw)
        self.remote_pub = initiator_pub
        token = ecdh_x(self.static_priv, initiator_pub)
        digest = _xor(token, init_nonce)
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        remote_eph_raw = secp256k1.ecrecover(
            digest, sig[64], r, s, allow_high_s=True, return_pubkey=True
        )
        remote_eph = pubkey_from_bytes(remote_eph_raw)
        fields = [pubkey_to_bytes(pubkey_from_priv(self.eph_priv)),
                  self.nonce, bytes([AUTH_VSN])]
        ack = _eip8_wrap(initiator_pub, fields)
        eph_shared = ecdh_x(self.eph_priv, remote_eph)
        secrets = derive_secrets(
            eph_shared, init_nonce, self.nonce, auth_bytes, ack, initiator=False,
        )
        return ack, secrets


class FrameSecrets:
    """aes/mac secrets + seeded egress/ingress MAC states (net/rlpx.py)."""

    def __init__(self, aes: bytes, mac: bytes, egress_seed: bytes,
                 ingress_seed: bytes):
        from ..primitives.keccak import Keccak256

        self.aes = aes
        self.mac = mac
        self.egress_mac = Keccak256(egress_seed)
        self.ingress_mac = Keccak256(ingress_seed)


def derive_secrets(eph_shared: bytes, init_nonce: bytes, resp_nonce: bytes,
                   auth_bytes: bytes, ack_bytes: bytes,
                   initiator: bool) -> FrameSecrets:
    """devp2p secret schedule (both sides derive identical aes/mac keys;
    the MAC seeds swap roles by direction)."""
    from ..primitives.keccak import keccak256

    shared = keccak256(eph_shared + keccak256(resp_nonce + init_nonce))
    aes = keccak256(eph_shared + shared)
    mac = keccak256(eph_shared + aes)
    seed_out = _xor(mac, resp_nonce) + auth_bytes
    seed_in = _xor(mac, init_nonce) + ack_bytes
    if not initiator:
        seed_out, seed_in = seed_in, seed_out
    return FrameSecrets(aes, mac, seed_out, seed_in)
