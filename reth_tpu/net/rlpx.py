"""RLPx framed transport: AES-CTR frames, keccak MACs, Hello, snappy.

Reference analogue: crates/net/eth-wire/src (RLPx multiplexing + p2p
handshake + snappy) over crates/net/ecies. After the ECIES auth/ack
handshake (net/ecies.py) every message travels in MAC-authenticated
AES-256-CTR frames:

  header (16B): frame-size (3B BE) ++ RLP [capability-id=0, context-id=0]
                zero-padded; encrypted with the session-long CTR stream.
  header-mac (16B): egress-mac.update(aes-ecb(mac-key, egress-mac[:16])
                XOR header-ciphertext); take 16 bytes.
  frame-data: ciphertext of the padded (16B multiple) message, then
  frame-mac over it (same construction, seeded with frame-mac[:16]).

Message payload = msg-id (single RLP int) ++ snappy(body) once both
sides have Hello'd with p2p version >= 5. p2p base protocol messages
(Hello 0x00, Disconnect 0x01, Ping 0x02, Pong 0x03) are never compressed
before Hello completes.
"""

from __future__ import annotations

import os
import socket
import struct

from ..primitives.rlp import decode_int, encode_int, rlp_decode, rlp_encode
from ..primitives.secp256k1 import pubkey_from_priv, pubkey_to_bytes
from . import snappy
from ._aes import Cipher, algorithms, modes  # optional-dep shim
from .ecies import FrameSecrets, Handshake

P2P_VERSION = 5
MAX_FRAME = 16 * 1024 * 1024

HELLO_ID = 0x00
DISCONNECT_ID = 0x01
PING_ID = 0x02
PONG_ID = 0x03
BASE_PROTOCOL_OFFSET = 0x10  # capability messages start here


class RlpxError(ConnectionError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RlpxError("connection closed")
        buf += chunk
    return buf


class RlpxSession:
    """An established encrypted session: send_msg/recv_msg of (id, body).

    Build with :func:`initiate` or :func:`respond`."""

    def __init__(self, sock: socket.socket, secrets: FrameSecrets,
                 remote_pub: tuple[int, int]):
        self.sock = sock
        self.remote_pub = remote_pub
        self.remote_node_id = pubkey_to_bytes(remote_pub)
        self._egress_mac = secrets.egress_mac
        self._ingress_mac = secrets.ingress_mac
        self._mac_cipher = Cipher(algorithms.AES(secrets.mac), modes.ECB())
        # one CTR stream per direction for the life of the session
        zero_iv = b"\x00" * 16
        self._enc = Cipher(algorithms.AES(secrets.aes), modes.CTR(zero_iv)).encryptor()
        self._dec = Cipher(algorithms.AES(secrets.aes), modes.CTR(zero_iv)).decryptor()
        self.snappy_enabled = False
        self.remote_hello: dict | None = None
        # non-blocking receive state (swarm mode): buffered ciphertext +
        # the header/body phase of the in-flight frame. The CTR stream and
        # rolling MACs are strictly ordered, so feed_frames consumes bytes
        # exactly once, in order.
        self._rx = bytearray()
        self._rx_size: int | None = None   # None = waiting for a header
        self._rx_padded = 0
        # swarm mode send sink: frames are fully encrypted under the
        # caller's lock, then handed to the sink (an outbox) instead of
        # blocking in sendall
        self._send_sink = None

    # -- MAC construction ---------------------------------------------------

    def _mac_step(self, mac, data16: bytes) -> bytes:
        enc = self._mac_cipher.encryptor()
        aes_block = enc.update(mac.digest()[:16])
        mac.update(bytes(a ^ b for a, b in zip(aes_block, data16)))
        return mac.digest()[:16]

    def _frame_mac(self, mac, ciphertext: bytes) -> bytes:
        mac.update(ciphertext)
        seed = mac.digest()[:16]
        return self._mac_step(mac, seed)

    # -- frames -------------------------------------------------------------

    def send_frame(self, payload: bytes) -> None:
        if len(payload) > MAX_FRAME:
            raise RlpxError("frame too large")
        header = struct.pack(">I", len(payload))[1:] + rlp_encode([b"", b""])
        header = header.ljust(16, b"\x00")
        header_ct = self._enc.update(header)
        header_mac = self._mac_step(self._egress_mac, header_ct)
        padded = payload + b"\x00" * (-len(payload) % 16)
        frame_ct = self._enc.update(padded)
        frame_mac = self._frame_mac(self._egress_mac, frame_ct)
        data = header_ct + header_mac + frame_ct + frame_mac
        if self._send_sink is not None:
            self._send_sink(data)
        else:
            self.sock.sendall(data)

    def recv_frame(self) -> bytes:
        header_ct = _recv_exact(self.sock, 16)
        header_mac = _recv_exact(self.sock, 16)
        if self._mac_step(self._ingress_mac, header_ct) != header_mac:
            raise RlpxError("bad header MAC")
        header = self._dec.update(header_ct)
        size = int.from_bytes(header[:3], "big")
        if size > MAX_FRAME:
            raise RlpxError("frame too large")
        padded = size + (-size % 16)
        frame_ct = _recv_exact(self.sock, padded)
        frame_mac = _recv_exact(self.sock, 16)
        if self._frame_mac(self._ingress_mac, frame_ct) != frame_mac:
            raise RlpxError("bad frame MAC")
        return self._dec.update(frame_ct)[:size]

    def feed_frames(self, data: bytes) -> list[bytes]:
        """Non-blocking counterpart of recv_frame: buffer ciphertext and
        return every complete frame it now contains (swarm receive path)."""
        self._rx += data
        frames: list[bytes] = []
        while True:
            if self._rx_size is None:
                if len(self._rx) < 32:
                    break
                header_ct = bytes(self._rx[:16])
                header_mac = bytes(self._rx[16:32])
                del self._rx[:32]
                if self._mac_step(self._ingress_mac, header_ct) != header_mac:
                    raise RlpxError("bad header MAC")
                header = self._dec.update(header_ct)
                size = int.from_bytes(header[:3], "big")
                if size > MAX_FRAME:
                    raise RlpxError("frame too large")
                self._rx_size = size
                self._rx_padded = size + (-size % 16)
            else:
                total = self._rx_padded + 16
                if len(self._rx) < total:
                    break
                frame_ct = bytes(self._rx[:self._rx_padded])
                frame_mac = bytes(self._rx[self._rx_padded:total])
                del self._rx[:total]
                if self._frame_mac(self._ingress_mac, frame_ct) != frame_mac:
                    raise RlpxError("bad frame MAC")
                frames.append(self._dec.update(frame_ct)[:self._rx_size])
                self._rx_size = None
        return frames

    # -- messages -----------------------------------------------------------

    def send_msg(self, msg_id: int, body: bytes) -> None:
        if self.snappy_enabled and msg_id >= BASE_PROTOCOL_OFFSET:
            body = snappy.compress(body)
        self.send_frame(rlp_encode(encode_int(msg_id)) + body)

    def parse_frame(self, frame: bytes) -> tuple[int, bytes]:
        """One received frame -> (msg_id, body) with snappy handling."""
        if not frame:
            raise RlpxError("empty frame")
        # msg-id is a single RLP item (0x80 = 0)
        if frame[0] < 0x80:
            msg_id, body = frame[0], frame[1:]
        elif frame[0] == 0x80:
            msg_id, body = 0, frame[1:]
        else:
            raise RlpxError("malformed message id")
        if self.snappy_enabled and msg_id >= BASE_PROTOCOL_OFFSET:
            body = snappy.decompress(body)
        return msg_id, body

    def recv_msg(self) -> tuple[int, bytes]:
        return self.parse_frame(self.recv_frame())

    # -- p2p base protocol --------------------------------------------------

    def hello(self, node_priv: int, client_id: str,
              caps: list[tuple[str, int]], port: int = 0) -> dict:
        """Exchange Hello messages; enables snappy; returns the remote's."""
        ours = rlp_encode([
            encode_int(P2P_VERSION), client_id.encode(),
            [[name.encode(), encode_int(v)] for name, v in caps],
            encode_int(port),
            pubkey_to_bytes(pubkey_from_priv(node_priv)),
        ])
        self.send_msg(HELLO_ID, ours)
        msg_id, body = self.recv_msg()
        if msg_id == DISCONNECT_ID:
            reason = rlp_decode(body)
            code = decode_int(reason[0] if isinstance(reason, list) else reason)
            raise RlpxError(f"peer disconnected during hello (reason {code})")
        if msg_id != HELLO_ID:
            raise RlpxError(f"expected Hello, got msg {msg_id}")
        f = rlp_decode(body)
        remote = {
            "p2p_version": decode_int(f[0]),
            "client_id": f[1].decode(errors="replace"),
            "caps": [(c[0].decode(errors="replace"), decode_int(c[1])) for c in f[2]],
            "port": decode_int(f[3]),
            "node_id": f[4],
        }
        self.remote_hello = remote
        if remote["node_id"] != self.remote_node_id:
            raise RlpxError("hello node-id does not match handshake identity")
        self.snappy_enabled = min(P2P_VERSION, remote["p2p_version"]) >= 5
        return remote

    def disconnect(self, reason: int = 0x08) -> None:
        try:
            self.send_msg(DISCONNECT_ID, rlp_encode([encode_int(reason)]))
        except OSError:
            pass

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def initiate(sock: socket.socket, node_priv: int,
             remote_pub: tuple[int, int]) -> RlpxSession:
    """Dial-side ECIES handshake over an open socket."""
    h = Handshake(node_priv)
    auth = h.auth(remote_pub)
    sock.sendall(auth)
    size = _recv_exact(sock, 2)
    ack = size + _recv_exact(sock, struct.unpack(">H", size)[0])
    secrets = h.finalize_initiator(ack)
    return RlpxSession(sock, secrets, remote_pub)


def respond(sock: socket.socket, node_priv: int) -> RlpxSession:
    """Listen-side ECIES handshake over an accepted socket."""
    size = _recv_exact(sock, 2)
    auth = size + _recv_exact(sock, struct.unpack(">H", size)[0])
    h = Handshake(node_priv)
    ack, secrets = h.on_auth(auth)
    sock.sendall(ack)
    return RlpxSession(sock, secrets, h.remote_pub)


def node_id(priv: int) -> bytes:
    return pubkey_to_bytes(pubkey_from_priv(priv))
