"""DNS node discovery: signed ENR trees in TXT records (EIP-1459).

Reference analogue: crates/net/dns — `DnsDiscoveryService` walking
`enrtree://` links, resolving branch/leaf TXT records, verifying the
root signature against the tree key (src/tree.rs, src/sync.rs).

Tree grammar (each entry one TXT record):

  root:    enrtree-root:v1 e=<enr-root> l=<link-root> seq=<seq> sig=<b64>
  branch:  enrtree-branch:<h1>,<h2>,...
  leaf:    enr:<base64-record>   |   enrtree://<b32-pubkey>@<domain>

A subdomain's name is base32(keccak256(record-text)[:16], no padding).
The root signature is a 65-byte recoverable secp256k1 signature over
keccak256 of the root text up to (excluding) " sig=". DNS itself is
pluggable: any `resolve_txt(fqdn) -> str | None` callable — tests use a
dict, production can use a real resolver without new dependencies.
"""

from __future__ import annotations

import base64

from ..primitives import secp256k1
from ..primitives.keccak import keccak256
from ..primitives.secp256k1 import compress_pubkey, decompress_pubkey
from .enr import Enr

ROOT_PREFIX = "enrtree-root:v1"
BRANCH_PREFIX = "enrtree-branch:"
LINK_PREFIX = "enrtree://"
MAX_BRANCH_FANOUT = 13  # keeps branch TXT records under 370 bytes


class DnsDiscError(ValueError):
    pass


def _b32(data: bytes) -> str:
    return base64.b32encode(data).decode().rstrip("=").lower()


def _b32_key(pub: tuple[int, int]) -> str:
    return _b32(compress_pubkey(pub))


def _subdomain(record_text: str) -> str:
    return _b32(keccak256(record_text.encode())[:16])


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).decode().rstrip("=")


def _unb64(text: str) -> bytes:
    return base64.urlsafe_b64decode(text + "=" * (-len(text) % 4))


def link_url(pub: tuple[int, int], domain: str) -> str:
    return f"{LINK_PREFIX}{_b32_key(pub)}@{domain}"


def parse_link(url: str) -> tuple[tuple[int, int], str]:
    if not url.startswith(LINK_PREFIX):
        raise DnsDiscError("not an enrtree link")
    key_b32, _, domain = url[len(LINK_PREFIX):].partition("@")
    pad = "=" * (-len(key_b32) % 8)
    pub = decompress_pubkey(base64.b32decode(key_b32.upper() + pad))
    return pub, domain


class EnrTree:
    """Builder: ENRs + links -> the TXT record map for a domain."""

    def __init__(self, priv: int, seq: int = 1):
        self.priv = priv
        self.seq = seq

    def _hash_subtree(self, entries: list[str], records: dict[str, str]) -> str:
        """Insert entries, folding into branch records; returns root hash."""
        if not entries:
            return _subdomain("")  # conventional empty marker
        if len(entries) == 1:
            h = _subdomain(entries[0])
            records[h] = entries[0]
            return h
        hashes = []
        for e in entries:
            h = _subdomain(e)
            records[h] = e
            hashes.append(h)
        while len(hashes) > 1:
            nxt = []
            for i in range(0, len(hashes), MAX_BRANCH_FANOUT):
                branch = BRANCH_PREFIX + ",".join(hashes[i:i + MAX_BRANCH_FANOUT])
                bh = _subdomain(branch)
                records[bh] = branch
                nxt.append(bh)
            hashes = nxt
        return hashes[0]

    def build(self, domain: str, enrs: list[Enr],
              links: list[str] = ()) -> dict[str, str]:
        """-> {fqdn: txt} for the whole signed tree."""
        records: dict[str, str] = {}
        enr_root = self._hash_subtree([e.to_base64() for e in enrs], records)
        link_root = self._hash_subtree(list(links), records)
        unsigned = f"{ROOT_PREFIX} e={enr_root} l={link_root} seq={self.seq}"
        digest = keccak256(unsigned.encode())
        y, r, s = secp256k1.sign(digest, self.priv)
        sig = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([y])
        root = f"{unsigned} sig={_b64(sig)}"
        out = {domain: root}
        for sub, txt in records.items():
            out[f"{sub}.{domain}"] = txt
        return out


class DnsResolver:
    """Client: walk a domain's signed tree, yield verified ENRs.

    ``resolve_txt(fqdn) -> str | None`` abstracts DNS; pass a dict's
    ``.get`` in tests."""

    def __init__(self, resolve_txt, max_records: int = 1000):
        self.resolve_txt = resolve_txt
        self.max_records = max_records

    def _verify_root(self, root_txt: str, pub: tuple[int, int] | None) -> dict:
        if not root_txt.startswith(ROOT_PREFIX):
            raise DnsDiscError("missing enrtree-root")
        fields = dict(kv.split("=", 1) for kv in root_txt.split(" ")[1:])
        for k in ("e", "l", "seq", "sig"):
            if k not in fields:
                raise DnsDiscError(f"root missing {k}=")
        unsigned = root_txt[:root_txt.index(" sig=")]
        sig = _unb64(fields["sig"])
        if len(sig) != 65:
            raise DnsDiscError("bad root signature length")
        digest = keccak256(unsigned.encode())
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        recovered = secp256k1.ecrecover(digest, sig[64], r, s,
                                        allow_high_s=True, return_pubkey=True)
        if pub is not None and recovered != (
                pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")):
            raise DnsDiscError("root signature does not match tree key")
        return fields

    def resolve_tree(self, link: str, _seen: set[str] | None = None) -> list[Enr]:
        """Resolve an enrtree:// link (verifying the root against its key),
        following link subtrees into other domains."""
        pub, domain = parse_link(link)
        seen = _seen if _seen is not None else set()
        if domain in seen:
            return []
        seen.add(domain)
        root_txt = self.resolve_txt(domain)
        if root_txt is None:
            return []
        fields = self._verify_root(root_txt, pub)
        out: list[Enr] = []
        out.extend(self._walk(domain, fields["e"], seen))
        for sub_link in self._walk_links(domain, fields["l"]):
            out.extend(self.resolve_tree(sub_link, seen))
        return out

    def _walk_entries(self, domain: str, h: str, seen: set[str]):
        stack = [h]
        count = 0
        while stack and count < self.max_records:
            sub = stack.pop()
            txt = self.resolve_txt(f"{sub}.{domain}")
            if txt is None:
                continue
            if _subdomain(txt) != sub:
                continue  # hash mismatch: poisoned record, skip
            count += 1
            if txt.startswith(BRANCH_PREFIX):
                stack.extend(x for x in txt[len(BRANCH_PREFIX):].split(",") if x)
            else:
                yield txt

    def _walk(self, domain: str, h: str, seen: set[str]) -> list[Enr]:
        out = []
        for txt in self._walk_entries(domain, h, seen):
            if txt.startswith("enr:"):
                try:
                    out.append(Enr.from_base64(txt))
                except Exception:  # noqa: BLE001 — bad record in tree
                    continue
        return out

    def _walk_links(self, domain: str, h: str) -> list[str]:
        return [txt for txt in self._walk_entries(domain, h, set())
                if txt.startswith(LINK_PREFIX)]
