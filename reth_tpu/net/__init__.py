"""P2P networking: eth-wire messages, peer sessions, sync downloaders.

Reference analogue: crates/net — eth-wire message types/codecs
(eth-wire-types), the session/server machinery (network), download
abstractions (p2p) and the reverse-headers/bodies downloaders
(downloaders). Transport here is length-prefixed frames over TCP; the
RLPx ECIES/AES encryption layer is a later milestone (no AES primitive
in-image) — the message vocabulary, handshake semantics, request/
response correlation, and sync logic are the compatible parts.
"""

from .wire import (
    EthMessage,
    MessageId,
    Status,
    decode_message,
    encode_message,
)
from .p2p import PeerConnection
from .server import NetworkManager
from .downloader import (
    BodiesDownloader,
    FullBlockClient,
    download_headers_reverse,
    sync_from_peer,
)

__all__ = [
    "EthMessage",
    "MessageId",
    "Status",
    "decode_message",
    "encode_message",
    "PeerConnection",
    "NetworkManager",
    "sync_from_peer",
    "BodiesDownloader",
    "FullBlockClient",
    "download_headers_reverse",
]
