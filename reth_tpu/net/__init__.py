"""P2P networking: eth-wire messages, peer sessions, sync downloaders.

Reference analogue: crates/net — eth-wire message types/codecs
(eth-wire-types), the session/server machinery (network), download
abstractions (p2p) and the reverse-headers/bodies downloaders
(downloaders). Transport here is length-prefixed frames over TCP; the
RLPx layer is fully encrypted: EIP-8 ECIES handshake (validated against
the EIP's own vectors in tests/test_external_vectors.py) and AES-256-CTR
frames with keccak ingress/egress MACs (net/rlpx.py).
"""

from .wire import (
    EthMessage,
    MessageId,
    Status,
    decode_message,
    encode_message,
)
from .p2p import PeerConnection
from .server import NetworkManager
from .downloader import (
    BodiesDownloader,
    FullBlockClient,
    download_headers_reverse,
    sync_from_peer,
)

__all__ = [
    "EthMessage",
    "MessageId",
    "Status",
    "decode_message",
    "encode_message",
    "PeerConnection",
    "NetworkManager",
    "sync_from_peer",
    "BodiesDownloader",
    "FullBlockClient",
    "download_headers_reverse",
]
