"""eth wire protocol messages (eth/68 vocabulary) + frame codec.

Reference analogue: crates/net/eth-wire-types — the `EthMessage` enum
(src/message.rs:312, ids :624) and per-message RLP shapes. Frames are
``u32 length | u8 msg_id | rlp payload`` (the RLPx snappy/AES layers are
a later milestone).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..primitives.rlp import decode_int, encode_int, rlp_decode, rlp_encode
from ..primitives.types import Block, Header, Receipt, Transaction, Withdrawal


class MessageId:
    STATUS = 0x00
    NEW_BLOCK_HASHES = 0x01
    TRANSACTIONS = 0x02
    GET_BLOCK_HEADERS = 0x03
    BLOCK_HEADERS = 0x04
    GET_BLOCK_BODIES = 0x05
    BLOCK_BODIES = 0x06
    NEW_BLOCK = 0x07
    NEW_POOLED_TX_HASHES = 0x08
    GET_POOLED_TRANSACTIONS = 0x09
    POOLED_TRANSACTIONS = 0x0A
    GET_RECEIPTS = 0x0F
    RECEIPTS = 0x10
    BLOCK_RANGE_UPDATE = 0x11  # eth/69


@dataclass
class Status:
    """eth status handshake. eth/68 carries total difficulty + head hash;
    eth/69 replaces TD with the served block range (earliest, latest,
    latest hash) — `version` selects the wire shape."""

    version: int = 68
    network_id: int = 1
    total_difficulty: int = 0
    head: bytes = b"\x00" * 32
    genesis: bytes = b"\x00" * 32
    fork_id: tuple[bytes, int] = (b"\x00" * 4, 0)
    earliest: int = 0  # eth/69: first block this node can serve
    latest: int = 0    # eth/69: tip number (head keeps the tip hash)

    def encode_payload(self):
        fid = [self.fork_id[0], encode_int(self.fork_id[1])]
        if self.version >= 69:
            return [
                encode_int(self.version), encode_int(self.network_id),
                self.genesis, fid, encode_int(self.earliest),
                encode_int(self.latest), self.head,
            ]
        return [
            encode_int(self.version), encode_int(self.network_id),
            encode_int(self.total_difficulty), self.head, self.genesis, fid,
        ]

    @classmethod
    def decode_payload(cls, f):
        version = decode_int(f[0])
        if version >= 69:
            return cls(
                version, decode_int(f[1]), 0, bytes(f[6]), bytes(f[2]),
                (bytes(f[3][0]), decode_int(f[3][1])),
                decode_int(f[4]), decode_int(f[5]),
            )
        return cls(
            version, decode_int(f[1]), decode_int(f[2]), f[3], f[4],
            (f[5][0], decode_int(f[5][1])),
        )


@dataclass
class BlockRangeUpdate:
    """eth/69: the served block range changed (replaces TD gossip)."""

    earliest: int
    latest: int
    latest_hash: bytes

    def encode_payload(self):
        return [encode_int(self.earliest), encode_int(self.latest),
                self.latest_hash]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), decode_int(f[1]), bytes(f[2]))


@dataclass
class GetBlockHeaders:
    request_id: int
    start: int | bytes     # number or hash
    limit: int
    skip: int = 0
    reverse: bool = False

    def encode_payload(self):
        start = self.start if isinstance(self.start, bytes) and len(self.start) == 32 \
            else encode_int(self.start)
        return [encode_int(self.request_id),
                [start, encode_int(self.limit), encode_int(self.skip),
                 encode_int(1 if self.reverse else 0)]]

    @classmethod
    def decode_payload(cls, f):
        rid, (start, limit, skip, rev) = decode_int(f[0]), f[1]
        s = start if len(start) == 32 else decode_int(start)
        return cls(rid, s, decode_int(limit), decode_int(skip), bool(decode_int(rev)))


@dataclass
class BlockHeaders:
    request_id: int
    headers: list[Header]

    def encode_payload(self):
        return [encode_int(self.request_id), [h.rlp_fields() for h in self.headers]]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), [Header.decode_fields(h) for h in f[1]])


@dataclass
class GetBlockBodies:
    request_id: int
    hashes: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id), list(self.hashes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), list(f[1]))


@dataclass
class BlockBody:
    transactions: tuple[Transaction, ...] = ()
    ommers: tuple[Header, ...] = ()
    withdrawals: tuple[Withdrawal, ...] | None = None

    def rlp_fields(self):
        from ..primitives.types import body_rlp_fields

        return body_rlp_fields(self.transactions, self.ommers, self.withdrawals)

    @classmethod
    def decode_fields(cls, f):
        from ..primitives.types import body_from_fields

        return cls(*body_from_fields(f))


@dataclass
class BlockBodies:
    request_id: int
    bodies: list[BlockBody]

    def encode_payload(self):
        return [encode_int(self.request_id), [b.rlp_fields() for b in self.bodies]]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), [BlockBody.decode_fields(b) for b in f[1]])


@dataclass
class GetReceipts:
    request_id: int
    hashes: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id), list(self.hashes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), list(f[1]))


@dataclass
class ReceiptsMsg:
    request_id: int
    receipts: list[list[bytes]]  # per block: encoded receipts

    def encode_payload(self):
        return [encode_int(self.request_id), [list(rs) for rs in self.receipts]]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), [list(rs) for rs in f[1]])


@dataclass
class TransactionsMsg:
    transactions: list[Transaction]

    def encode_payload(self):
        from ..primitives.types import _tx_block_item

        return [_tx_block_item(tx) for tx in self.transactions]

    @classmethod
    def decode_payload(cls, f):
        from ..primitives.types import _tx_from_block_item

        return cls([_tx_from_block_item(t) for t in f])


@dataclass
class NewPooledTxHashes:
    """eth/68 announcement: types + sizes + hashes."""

    types: bytes
    sizes: list[int]
    hashes: list[bytes]

    def encode_payload(self):
        return [self.types, [encode_int(s) for s in self.sizes], list(self.hashes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(f[0], [decode_int(s) for s in f[1]], list(f[2]))


@dataclass
class GetPooledTransactions:
    request_id: int
    hashes: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id), list(self.hashes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), list(f[1]))


@dataclass
class PooledTransactions:
    request_id: int
    transactions: list[Transaction]

    def encode_payload(self):
        from ..primitives.types import _tx_block_item

        return [encode_int(self.request_id),
                [_tx_block_item(tx) for tx in self.transactions]]

    @classmethod
    def decode_payload(cls, f):
        from ..primitives.types import _tx_from_block_item

        return cls(decode_int(f[0]), [_tx_from_block_item(t) for t in f[1]])


@dataclass
class NewBlockHashes:
    entries: list[tuple[bytes, int]]  # (hash, number)

    def encode_payload(self):
        return [[h, encode_int(n)] for h, n in self.entries]

    @classmethod
    def decode_payload(cls, f):
        return cls([(e[0], decode_int(e[1])) for e in f])


EthMessage = (
    Status | GetBlockHeaders | BlockHeaders | GetBlockBodies | BlockBodies
    | GetReceipts | ReceiptsMsg | TransactionsMsg | NewPooledTxHashes
    | GetPooledTransactions | PooledTransactions | NewBlockHashes
)

_BY_ID = {
    MessageId.STATUS: Status,
    MessageId.NEW_BLOCK_HASHES: NewBlockHashes,
    MessageId.TRANSACTIONS: TransactionsMsg,
    MessageId.GET_BLOCK_HEADERS: GetBlockHeaders,
    MessageId.BLOCK_HEADERS: BlockHeaders,
    MessageId.GET_BLOCK_BODIES: GetBlockBodies,
    MessageId.BLOCK_BODIES: BlockBodies,
    MessageId.NEW_POOLED_TX_HASHES: NewPooledTxHashes,
    MessageId.GET_POOLED_TRANSACTIONS: GetPooledTransactions,
    MessageId.POOLED_TRANSACTIONS: PooledTransactions,
    MessageId.GET_RECEIPTS: GetReceipts,
    MessageId.RECEIPTS: ReceiptsMsg,
    MessageId.BLOCK_RANGE_UPDATE: BlockRangeUpdate,
}
_TO_ID = {v: k for k, v in _BY_ID.items()}


def encode_message(msg) -> bytes:
    payload = rlp_encode(msg.encode_payload())
    mid = _TO_ID[type(msg)]
    return struct.pack("<IB", len(payload) + 1, mid) + payload


def decode_message(frame: bytes):
    mid = frame[0]
    cls = _BY_ID.get(mid)
    if cls is None:
        raise ValueError(f"unknown message id {mid:#x}")
    return cls.decode_payload(rlp_decode(frame[1:]))


def encode_eth(msg) -> tuple[int, bytes]:
    """(eth/68 message id, RLP payload) — the RLPx capability framing
    (net/rlpx.py adds the base-protocol offset and snappy)."""
    return _TO_ID[type(msg)], rlp_encode(msg.encode_payload())


def decode_eth(mid: int, payload: bytes):
    cls = _BY_ID.get(mid)
    if cls is None:
        raise ValueError(f"unknown eth message id {mid:#x}")
    return cls.decode_payload(rlp_decode(payload))
