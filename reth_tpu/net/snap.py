"""snap/1: state-range serving + client requests (snap sync protocol).

Reference analogue: the `StateRangeProvider` storage trait the reference
exposes for snap serving (crates/storage/storage-api/src/trie.rs:73) and
the snap wire vocabulary from the devp2p spec the ecosystem shares; reth
multiplexes extra capabilities next to eth via its RLPx sub-protocol
registry (crates/net/network/src/protocol.rs). Here snap/1 rides the
same encrypted session as eth/68: capability ids are assigned
alphabetically after eth's 17 message ids.

Messages (snap/1):

  0x00 GetAccountRange  [reqid, root, origin, limit, bytes]
  0x01 AccountRange     [reqid, [[hash, slim-account]...], [proof...]]
  0x02 GetStorageRanges [reqid, root, [acct-hash...], origin, limit, bytes]
  0x03 StorageRanges    [reqid, [[[hash, value]...]...], [proof...]]
  0x04 GetByteCodes     [reqid, [code-hash...], bytes]
  0x05 ByteCodes        [reqid, [code...]]
  0x06 GetTrieNodes     [reqid, root, [[path...]...], bytes]
  0x07 TrieNodes        [reqid, [node...]]

Accounts travel in the "slim" encoding: empty storage root / empty code
hash collapse to empty strings. Range responses carry boundary proofs
(origin + last returned key) so the requester can verify completeness
against the state root.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..primitives.keccak import keccak256
from ..primitives.nibbles import unpack_nibbles
from ..primitives.rlp import decode_int, encode_int, rlp_decode, rlp_encode
from ..primitives.types import EMPTY_ROOT_HASH, Account

EMPTY_CODE_HASH = keccak256(b"")

# snap/1 message ids (offset within the capability)
GET_ACCOUNT_RANGE = 0x00
ACCOUNT_RANGE = 0x01
GET_STORAGE_RANGES = 0x02
STORAGE_RANGES = 0x03
GET_BYTE_CODES = 0x04
BYTE_CODES = 0x05
GET_TRIE_NODES = 0x06
TRIE_NODES = 0x07

SNAP_MSG_COUNT = 8
SOFT_RESPONSE_LIMIT = 2 * 1024 * 1024
MAX_CODES_SERVE = 1024


def slim_account(acc: Account) -> bytes:
    """Snap "slim" account body: empty root/code-hash become b""."""
    root = b"" if acc.storage_root == EMPTY_ROOT_HASH else acc.storage_root
    code = b"" if acc.code_hash == EMPTY_CODE_HASH else acc.code_hash
    return rlp_encode([encode_int(acc.nonce), encode_int(acc.balance), root, code])


def unslim_account(raw: bytes) -> Account:
    f = rlp_decode(raw)
    return Account(
        nonce=decode_int(f[0]), balance=decode_int(f[1]),
        storage_root=bytes(f[2]) or EMPTY_ROOT_HASH,
        code_hash=bytes(f[3]) or EMPTY_CODE_HASH,
    )


# -- message dataclasses ------------------------------------------------------


@dataclass
class GetAccountRange:
    request_id: int
    root: bytes
    origin: bytes
    limit: bytes
    response_bytes: int = SOFT_RESPONSE_LIMIT

    def encode_payload(self):
        return [encode_int(self.request_id), self.root, self.origin,
                self.limit, encode_int(self.response_bytes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), bytes(f[1]), bytes(f[2]), bytes(f[3]),
                   decode_int(f[4]))


@dataclass
class AccountRange:
    request_id: int
    accounts: list[tuple[bytes, bytes]]  # (hashed key, slim body)
    proof: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id),
                [[h, body] for h, body in self.accounts],
                list(self.proof)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]),
                   [(bytes(e[0]), bytes(e[1])) for e in f[1]],
                   [bytes(p) for p in f[2]])


@dataclass
class GetStorageRanges:
    request_id: int
    root: bytes
    account_hashes: list[bytes]
    origin: bytes
    limit: bytes
    response_bytes: int = SOFT_RESPONSE_LIMIT

    def encode_payload(self):
        return [encode_int(self.request_id), self.root,
                list(self.account_hashes), self.origin, self.limit,
                encode_int(self.response_bytes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), bytes(f[1]), [bytes(h) for h in f[2]],
                   bytes(f[3]), bytes(f[4]), decode_int(f[5]))


@dataclass
class StorageRanges:
    request_id: int
    slots: list[list[tuple[bytes, bytes]]]  # per account: (hashed slot, rlp value)
    proof: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id),
                [[[h, v] for h, v in acct] for acct in self.slots],
                list(self.proof)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]),
                   [[(bytes(e[0]), bytes(e[1])) for e in acct] for acct in f[1]],
                   [bytes(p) for p in f[2]])


@dataclass
class GetByteCodes:
    request_id: int
    hashes: list[bytes]
    response_bytes: int = SOFT_RESPONSE_LIMIT

    def encode_payload(self):
        return [encode_int(self.request_id), list(self.hashes),
                encode_int(self.response_bytes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), [bytes(h) for h in f[1]], decode_int(f[2]))


@dataclass
class ByteCodes:
    request_id: int
    codes: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id), list(self.codes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), [bytes(c) for c in f[1]])


@dataclass
class GetTrieNodes:
    request_id: int
    root: bytes
    paths: list[list[bytes]]  # path groups: [acct-path] or [acct-path, slot-path...]
    response_bytes: int = SOFT_RESPONSE_LIMIT

    def encode_payload(self):
        return [encode_int(self.request_id), self.root,
                [[bytes(p) for p in grp] for grp in self.paths],
                encode_int(self.response_bytes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), bytes(f[1]),
                   [[bytes(p) for p in grp] for grp in f[2]], decode_int(f[3]))


@dataclass
class TrieNodes:
    request_id: int
    nodes: list[bytes]

    def encode_payload(self):
        return [encode_int(self.request_id), list(self.nodes)]

    @classmethod
    def decode_payload(cls, f):
        return cls(decode_int(f[0]), [bytes(n) for n in f[1]])


_BY_ID = {
    GET_ACCOUNT_RANGE: GetAccountRange, ACCOUNT_RANGE: AccountRange,
    GET_STORAGE_RANGES: GetStorageRanges, STORAGE_RANGES: StorageRanges,
    GET_BYTE_CODES: GetByteCodes, BYTE_CODES: ByteCodes,
    GET_TRIE_NODES: GetTrieNodes, TRIE_NODES: TrieNodes,
}
_TO_ID = {v: k for k, v in _BY_ID.items()}


def encode_snap(msg) -> tuple[int, bytes]:
    return _TO_ID[type(msg)], rlp_encode(msg.encode_payload())


def decode_snap(mid: int, payload: bytes):
    cls = _BY_ID.get(mid)
    if cls is None:
        raise ValueError(f"unknown snap message {mid:#x}")
    return cls.decode_payload(rlp_decode(payload))


# -- server (StateRangeProvider analogue) ------------------------------------


class SnapServer:
    """Serves snap/1 ranges from the canonical hashed state.

    Responses are only meaningful for the CURRENT state root (snap
    servers may refuse stale roots — we return empty responses, which
    the spec treats as "unavailable")."""

    def __init__(self, factory, committer=None):
        from ..primitives.keccak import keccak256_batch_np
        from ..trie.committer import TrieCommitter

        self.factory = factory
        # proof spines are tiny: the numpy hasher avoids device dispatch
        # latency on the request path
        self.committer = committer or TrieCommitter(hasher=keccak256_batch_np)

    def _current_root(self, p) -> bytes:
        tip = p.last_block_number()
        h = p.header_by_number(tip)
        return h.state_root if h else b""

    def _account_proof_for(self, p, hashed_keys: list[bytes]) -> list[bytes]:
        from ..trie.incremental import IncrementalStateRoot, PrefixSet, plan_subtrie
        from ..trie.proof import _spine_nodes

        inc = IncrementalStateRoot(p, self.committer)
        paths = [unpack_nibbles(h) for h in hashed_keys]
        plan = plan_subtrie(p.account_branch, PrefixSet(paths))
        res = self.committer.commit_many(
            [(inc._scan_account_leaves(plan.dirty_ranges), dict(plan.boundaries))],
            collect_branches=False, proof_targets=[paths])
        nodes: list[bytes] = []
        seen = set()
        for path in paths:
            for n in _spine_nodes(res[0].proof_nodes, path):
                if n not in seen:
                    seen.add(n)
                    nodes.append(n)
        return nodes

    def _storage_proof_for(self, p, hashed_addr: bytes,
                           hashed_keys: list[bytes]) -> list[bytes]:
        from ..trie.incremental import IncrementalStateRoot, PrefixSet, plan_subtrie
        from ..trie.proof import _spine_nodes

        inc = IncrementalStateRoot(p, self.committer)
        paths = [unpack_nibbles(h) for h in hashed_keys]
        plan = plan_subtrie(lambda pa: p.storage_branch(hashed_addr, pa),
                            PrefixSet(paths))
        res = self.committer.commit_many(
            [(inc._scan_storage_leaves(hashed_addr, plan.dirty_ranges),
              dict(plan.boundaries))],
            collect_branches=False, proof_targets=[paths])
        nodes: list[bytes] = []
        seen = set()
        for path in paths:
            for n in _spine_nodes(res[0].proof_nodes, path):
                if n not in seen:
                    seen.add(n)
                    nodes.append(n)
        return nodes

    def account_range(self, req: GetAccountRange) -> AccountRange:
        from ..storage import tables as T

        with self.factory.provider() as p:
            if req.root != self._current_root(p):
                return AccountRange(req.request_id, [], [])
            budget = min(req.response_bytes, SOFT_RESPONSE_LIMIT)
            out: list[tuple[bytes, bytes]] = []
            size = 0
            cur = p.tx.cursor(T.Tables.HashedAccounts.name)
            entry = cur.seek(req.origin)
            while entry is not None:
                k, v = entry
                if k > req.limit and out:
                    break
                body = slim_account(T.decode_account(v))
                out.append((k, body))
                size += 32 + len(body)
                if size >= budget or k > req.limit:
                    break
                entry = cur.next()
            edges = [req.origin]
            if out:
                edges.append(out[-1][0])
            proof = self._account_proof_for(p, edges)
            return AccountRange(req.request_id, out, proof)

    def storage_ranges(self, req: GetStorageRanges) -> StorageRanges:
        from ..storage import tables as T

        with self.factory.provider() as p:
            if req.root != self._current_root(p):
                return StorageRanges(req.request_id, [], [])
            budget = min(req.response_bytes, SOFT_RESPONSE_LIMIT)
            all_slots: list[list[tuple[bytes, bytes]]] = []
            proof: list[bytes] = []
            size = 0
            origin = req.origin or b"\x00" * 32
            limit = req.limit or b"\xff" * 32
            # a proper-subset request (non-default window) must ALWAYS carry
            # boundary proofs, truncated or not — clients verify the window
            # against the storage root (snap/1 spec)
            windowed = origin != b"\x00" * 32 or limit != b"\xff" * 32
            for ha in req.account_hashes:
                acct_slots: list[tuple[bytes, bytes]] = []
                cur = p.tx.cursor(T.Tables.HashedStorages.name)
                entry = cur.seek_by_key_subkey(ha, origin)
                truncated = False
                while entry is not None:
                    key, data = entry
                    if key != ha:
                        break
                    hslot, value = data[:32], T.decode_storage_entry(data)[1]
                    if hslot > limit and acct_slots:
                        truncated = True
                        break
                    body = rlp_encode(encode_int(value))
                    acct_slots.append((hslot, body))
                    size += 32 + len(body)
                    if size >= budget or hslot > limit:
                        truncated = True
                        break
                    entry = cur.next_dup()
                all_slots.append(acct_slots)
                if truncated or windowed or size >= budget:
                    # proofs for the (possibly partial) last account range
                    edges = [origin]
                    if acct_slots:
                        edges.append(acct_slots[-1][0])
                    proof = self._storage_proof_for(p, ha, edges)
                    break
            return StorageRanges(req.request_id, all_slots, proof)

    def byte_codes(self, req: GetByteCodes) -> ByteCodes:
        with self.factory.provider() as p:
            budget = min(req.response_bytes, SOFT_RESPONSE_LIMIT)
            out, size = [], 0
            for h in req.hashes[:MAX_CODES_SERVE]:
                code = p.bytecode(h)
                if code is None:
                    continue
                out.append(code)
                size += len(code)
                if size >= budget:
                    break
            return ByteCodes(req.request_id, out)

    def trie_nodes(self, req: GetTrieNodes) -> TrieNodes:
        """Healing: fetch account/storage trie nodes by path. Node RLPs are
        regenerated through the proof machinery for the REQUESTED paths'
        spines, then matched by path."""
        with self.factory.provider() as p:
            if req.root != self._current_root(p):
                return TrieNodes(req.request_id, [])
            out: list[bytes] = []
            budget = min(req.response_bytes, SOFT_RESPONSE_LIMIT)
            size = 0
            for group in req.paths:
                if not group:
                    continue
                if len(group) == 1:
                    nodes = self._account_proof_for(p, [_pad_path(group[0])])
                else:
                    ha = group[0]
                    for sub in group[1:]:
                        nodes = self._storage_proof_for(p, ha, [_pad_path(sub)])
                        for n in nodes:
                            out.append(n)
                            size += len(n)
                        if size >= budget:
                            return TrieNodes(req.request_id, out)
                    continue
                for n in nodes:
                    out.append(n)
                    size += len(n)
                if size >= budget:
                    break
            return TrieNodes(req.request_id, out)


def _pad_path(path: bytes) -> bytes:
    """Trie-node paths may be partial; extend to a full 32-byte key for the
    spine walk (any key under the path shares the spine above it)."""
    return (path + b"\x00" * 32)[:32]


# -- range verification (client side) ----------------------------------------


def verify_account_range(root: bytes, origin: bytes,
                         rng: AccountRange) -> bool:
    """Boundary-proof check: keys sorted from origin, the origin spine
    verifies against the root, and the LAST returned account proves
    membership with its value (the proofs cover the range boundaries —
    interior completeness follows from the boundary spines in a full
    stitch, which the sync pipeline does when healing)."""
    keys = [h for h, _ in rng.accounts]
    if keys != sorted(keys) or (keys and keys[0] < origin):
        return False
    if not rng.accounts:
        return True
    by_hash = {keccak256(n): n for n in rng.proof}
    ok, _leaf = _verify_path_from(root, origin, by_hash, rng.proof)
    if not ok:
        return False
    last_h, last_body = rng.accounts[-1]
    ok, leaf = _verify_path_from(root, last_h, by_hash, rng.proof)
    if not ok:
        return False
    return leaf == unslim_account(last_body).trie_encode()


def _verify_path_from(root: bytes, hashed_key: bytes, by_hash, nodes):
    """Spine walk over an unordered node set (snap proofs are a set, not a
    root→leaf list)."""
    from ..primitives.nibbles import decode_path

    path = unpack_nibbles(hashed_key)
    cur = by_hash.get(root)
    if cur is None:
        return False, None
    depth = 0
    while True:
        node = rlp_decode(cur)
        if len(node) == 17:
            if depth == len(path):
                return True, node[16] or None
            child = node[path[depth]]
            depth += 1
            if child in (b"", []):
                return True, None
            nxt = child
        elif len(node) == 2:
            nibs, is_leaf = decode_path(node[0])
            if is_leaf:
                return True, (node[1] if path[depth:] == nibs else None)
            if path[depth:depth + len(nibs)] != nibs:
                return True, None
            depth += len(nibs)
            nxt = node[1]
        else:
            return False, None
        if isinstance(nxt, bytes) and len(nxt) == 32:
            cur = by_hash.get(nxt)
            if cur is None:
                return False, None
        else:
            cur = rlp_encode(nxt)
