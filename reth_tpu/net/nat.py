"""External-address resolution for the P2P listener (NAT handling).

Reference analogue: crates/net/nat — resolves the address advertised in
ENRs/enodes: an explicit `--nat extip:<ip>`, the listening interface, or
best-effort discovery. UPnP/PMP and external STUN-style services need
egress this environment forbids, so those strategies degrade to the
interface address with a recorded reason (the reference's `NatResolver`
falls back the same way when probing fails).
"""

from __future__ import annotations

import ipaddress
import socket
from dataclasses import dataclass


@dataclass(frozen=True)
class NatResolver:
    """Parsed `--nat` setting; ``external_ip`` resolves the advertised IP."""

    strategy: str = "any"        # any | none | extip
    explicit: str | None = None  # for extip:<ip>
    fallback_reason: str | None = None

    @classmethod
    def parse(cls, value: str) -> "NatResolver":
        v = value.strip().lower()
        if v in ("any", "none", "upnp", "natpmp"):
            reason = (f"{v} probing needs egress; using interface address"
                      if v in ("upnp", "natpmp") else None)
            return cls(strategy="any" if v != "none" else "none",
                       fallback_reason=reason)
        if v.startswith("extip:"):
            ip = value.split(":", 1)[1]
            ipaddress.ip_address(ip)  # validate; raises ValueError
            return cls(strategy="extip", explicit=ip)
        raise ValueError(f"unknown NAT strategy {value!r}")

    def external_ip(self, bind_host: str = "0.0.0.0") -> str:
        if self.strategy == "extip":
            return self.explicit  # type: ignore[return-value]
        if self.strategy == "none":
            return bind_host if bind_host not in ("0.0.0.0", "::") else "127.0.0.1"
        # "any": the interface a default route would use (no packets sent —
        # connect() on UDP just selects a source address)
        if bind_host not in ("0.0.0.0", "::", ""):
            return bind_host
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.254.254.254", 1))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"
        finally:
            s.close()
