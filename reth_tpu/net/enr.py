"""ENR: Ethereum Node Records (EIP-778), "v4" identity scheme.

Reference analogue: the reference consumes ENRs through sigp/discv5 and
`enr` crates (crates/net/discv5/src/enr.rs converts discv5 ENRs into
`NodeRecord`s; crates/net/dns resolves ENR trees). A record is

  rlp([signature, seq, k1, v1, k2, v2, ...])   keys sorted, unique

signed over rlp([seq, k1, v1, ...]) with the node's secp256k1 key
("id" = "v4" scheme). The discv5 node id is keccak256(uncompressed
64-byte pubkey).
"""

from __future__ import annotations

import base64
import ipaddress

from ..primitives import secp256k1
from ..primitives.keccak import keccak256
from ..primitives.rlp import decode_int, encode_int, rlp_decode_prefix, rlp_encode
from ..primitives.secp256k1 import (
    compress_pubkey,
    decompress_pubkey,
    pubkey_from_priv,
    pubkey_to_bytes,
)

MAX_ENR_SIZE = 300


class EnrError(ValueError):
    pass


def node_id_from_pubkey(pub: tuple[int, int]) -> bytes:
    """discv5 node id: keccak256 of the raw 64-byte public key."""
    return keccak256(pubkey_to_bytes(pub))


class Enr:
    """One node record. ``pairs`` holds raw value bytes keyed by str."""

    def __init__(self, seq: int, pairs: dict[str, bytes], signature: bytes = b""):
        self.seq = seq
        self.pairs = dict(pairs)
        self.signature = signature

    # -- typed accessors ---------------------------------------------------
    @property
    def pubkey(self) -> tuple[int, int]:
        raw = self.pairs.get("secp256k1")
        if raw is None:
            raise EnrError("record has no secp256k1 key")
        return decompress_pubkey(raw)

    @property
    def node_id(self) -> bytes:
        return node_id_from_pubkey(self.pubkey)

    @property
    def ip(self) -> str | None:
        raw = self.pairs.get("ip")
        return str(ipaddress.ip_address(raw)) if raw else None

    def _port(self, key: str) -> int | None:
        raw = self.pairs.get(key)
        return decode_int(raw) if raw else None

    @property
    def udp_port(self) -> int | None:
        return self._port("udp")

    @property
    def tcp_port(self) -> int | None:
        return self._port("tcp")

    # -- codec -------------------------------------------------------------
    def _content(self) -> list:
        items: list = [encode_int(self.seq)]
        for k in sorted(self.pairs):
            items += [k.encode(), self.pairs[k]]
        return items

    def encode(self) -> bytes:
        raw = rlp_encode([self.signature] + self._content())
        if len(raw) > MAX_ENR_SIZE:
            raise EnrError("record exceeds 300 bytes")
        return raw

    @classmethod
    def decode(cls, raw: bytes) -> "Enr":
        if len(raw) > MAX_ENR_SIZE:
            raise EnrError("record exceeds 300 bytes")
        fields, consumed = rlp_decode_prefix(raw)
        if (consumed != len(raw) or not isinstance(fields, list)
                or len(fields) < 2 or len(fields) % 2):
            raise EnrError("malformed record")
        sig = bytes(fields[0])
        seq = decode_int(fields[1])
        pairs: dict[str, bytes] = {}
        last = None
        for i in range(2, len(fields), 2):
            k = bytes(fields[i]).decode("ascii", "strict")
            if last is not None and k <= last:
                raise EnrError("keys not sorted/unique")
            last = k
            pairs[k] = bytes(fields[i + 1])
        rec = cls(seq, pairs, sig)
        rec.verify()
        return rec

    # -- v4 identity scheme -------------------------------------------------
    def sign(self, priv: int) -> "Enr":
        self.pairs["id"] = b"v4"
        self.pairs["secp256k1"] = compress_pubkey(pubkey_from_priv(priv))
        digest = keccak256(rlp_encode(self._content()))
        _y, r, s = secp256k1.sign(digest, priv)
        self.signature = r.to_bytes(32, "big") + s.to_bytes(32, "big")
        return self

    def verify(self) -> None:
        if self.pairs.get("id") != b"v4":
            raise EnrError("unsupported identity scheme")
        if len(self.signature) != 64:
            raise EnrError("bad signature length")
        digest = keccak256(rlp_encode(self._content()))
        r = int.from_bytes(self.signature[:32], "big")
        s = int.from_bytes(self.signature[32:], "big")
        pub = self.pubkey
        # non-malleable 64-byte sig: try both recovery bits
        for y in (0, 1):
            try:
                if secp256k1.ecrecover(digest, y, r, s, allow_high_s=True,
                                       return_pubkey=True) == pubkey_to_bytes(pub):
                    return
            except Exception:  # noqa: BLE001 — invalid curve point for this bit
                continue
        raise EnrError("signature does not match secp256k1 key")

    # -- text form -----------------------------------------------------------
    def to_base64(self) -> str:
        return "enr:" + base64.urlsafe_b64encode(self.encode()).rstrip(b"=").decode()

    @classmethod
    def from_base64(cls, text: str) -> "Enr":
        if not text.startswith("enr:"):
            raise EnrError("missing enr: prefix")
        b64 = text[4:]
        raw = base64.urlsafe_b64decode(b64 + "=" * (-len(b64) % 4))
        return cls.decode(raw)


def make_enr(priv: int, ip: str | None = None, udp: int | None = None,
             tcp: int | None = None, seq: int = 1, **extra: bytes) -> Enr:
    pairs: dict[str, bytes] = {}
    if ip is not None:
        pairs["ip"] = ipaddress.ip_address(ip).packed
    if udp is not None:
        pairs["udp"] = encode_int(udp)
    if tcp is not None:
        pairs["tcp"] = encode_int(tcp)
    pairs.update(extra)
    return Enr(seq, pairs).sign(priv)
