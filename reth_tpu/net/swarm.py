"""Single-event-loop peer swarm.

Reference analogue: `NetworkManager`'s polled `Swarm`
(crates/net/network/src/manager.rs:108, src/swarm.rs) — ONE task polls
the listener and every established session; per-session work never owns
a thread. Here: one `selectors` loop thread owns the accept socket and
every established inbound session's socket. Handshakes (ECIES + hello +
status: multi-round, blocking, attacker-paced) run on short-lived
threads bounded by the SessionManager's pending-capacity reservation,
then hand the established socket to the loop. Steady state is ONE
thread regardless of peer count.

Sends from any thread (request responses, broadcasts) encrypt under the
peer's lock into a bounded per-peer outbox; the loop flushes outboxes on
socket writability and a self-pipe wakes it for cross-thread enqueues.
A peer whose outbox overflows is disconnected — backpressure by
eviction, like the reference's session command channels.
"""

from __future__ import annotations

import selectors
import socket
import threading

MAX_OUTBOX = 4 * 1024 * 1024  # per-peer pending egress cap
RECV_CHUNK = 1 << 16


class Swarm:
    def __init__(self, manager, listener: socket.socket):
        self.manager = manager
        self.listener = listener
        self.selector = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        # the writer must NEVER block: wake() runs under peer._lock, and
        # a blocked wake deadlocks against the loop's outbox flush
        self._wake_w.setblocking(False)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._peers: dict[int, object] = {}  # fd -> PeerConnection
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self.listener.setblocking(False)
        self.selector.register(self.listener, selectors.EVENT_READ, "accept")
        self.selector.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="net-swarm")
        self._thread.start()

    def stop(self):
        self._stop.set()
        self.wake()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self.selector.close()
        except OSError:
            pass
        self._wake_r.close()
        self._wake_w.close()

    def wake(self):
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wakeup is already pending

    # -- peer registration -------------------------------------------------

    def register_peer(self, peer) -> None:
        """Adopt an ESTABLISHED session into the loop (called from the
        transient handshake thread)."""
        sock = peer.session.sock
        sock.setblocking(False)
        outbox = bytearray()
        peer._swarm_outbox = outbox

        def sink(data, peer=peer, outbox=outbox):
            # runs under peer._lock (send_frame callers hold it): encrypt
            # order == outbox order
            if len(outbox) + len(data) > MAX_OUTBOX:
                peer._swarm_overflow = True
            else:
                outbox += data
            self.wake()

        peer.session._send_sink = sink
        peer._swarm_overflow = False
        peer._swarm_fd = sock.fileno()
        with self._lock:
            self._peers[peer._swarm_fd] = peer
        self.selector.register(sock, selectors.EVENT_READ, "peer")
        self.wake()

    def _drop_peer(self, peer, reason: str, penalize: bool = False):
        m = self.manager
        sock = peer.session.sock
        try:
            self.selector.unregister(sock)
        except (KeyError, ValueError, OSError):
            pass
        with self._lock:
            self._peers.pop(peer._swarm_fd, None)
        if penalize:
            m.peers_manager.reputation_change(peer.node_id, "bad_message")
        slot = getattr(peer, "_session_slot", None)
        if slot is not None:
            m.sessions.close(slot, reason)
        peer.close()
        try:
            m.peers.remove(peer)
        except ValueError:
            pass

    # -- the loop ----------------------------------------------------------

    def _loop(self):
        from .p2p import PeerDisconnected, PeerError

        while not self._stop.is_set():
            try:
                events = self.selector.select(timeout=0.5)
            except OSError:
                return
            for key, mask in events:
                if key.data == "accept":
                    self._accept()
                    continue
                if key.data == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                peer = self._peers.get(key.fd)
                if peer is None:
                    try:
                        self.selector.unregister(key.fileobj)
                    except (KeyError, ValueError, OSError):
                        pass
                    continue
                if mask & selectors.EVENT_READ:
                    self._readable(peer)
            # flush every pending outbox (sends are small; a full socket
            # buffer leaves the remainder for the next pass)
            self._flush_outboxes()

    def _accept(self):
        from .sessions import SessionLimitExceeded

        while True:
            try:
                sock, _addr = self.listener.accept()
            except (BlockingIOError, OSError):
                return
            try:
                slot = self.manager.sessions.reserve("inbound")
            except SessionLimitExceeded:
                sock.close()  # at capacity: refuse BEFORE any handshake
                continue
            # the handshake is multi-round and attacker-paced: run it on a
            # transient thread (bounded by the session reservation), then
            # adopt the established session into the loop
            threading.Thread(target=self._handshake, args=(sock, slot),
                             daemon=True).start()

    def _handshake(self, sock: socket.socket, slot):
        from .p2p import PeerConnection

        m = self.manager
        sock.setblocking(True)
        sock.settimeout(15)
        try:
            peer = PeerConnection.accept(sock, m.status, m.node_priv,
                                         fork_filter=m._fork_filter)
        except Exception:  # noqa: BLE001 — handshake parses attacker-
            # controlled bytes; ANY failure must drop the peer only
            m.sessions.close(slot, "handshake failed")
            sock.close()
            return
        if m.peers_manager.is_banned(peer.node_id):
            m.sessions.close(slot, "banned")
            peer.session.disconnect(0x05)
            peer.close()
            return
        sock.settimeout(None)
        m.sessions.activate(slot, peer)
        peer._session_slot = slot
        peer._swarm_fd = sock.fileno()
        m.peers.append(peer)
        self.register_peer(peer)

    def _readable(self, peer):
        from .p2p import PeerDisconnected, PeerError

        m = self.manager
        try:
            data = peer.session.sock.recv(RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop_peer(peer, "stream error")
            return
        if not data:
            self._drop_peer(peer, "disconnected")
            return
        slot = getattr(peer, "_session_slot", None)
        try:
            msgs = peer.feed(data)
        except PeerDisconnected:
            self._drop_peer(peer, "disconnected")
            return
        except PeerError:
            self._drop_peer(peer, "protocol violation", penalize=True)
            return
        except Exception:  # noqa: BLE001 — malformed frame: drop the peer
            self._drop_peer(peer, "stream error")
            return
        for msg in msgs:
            if slot is not None:
                slot.messages_in += 1
            try:
                m._handle(peer, msg)
            except PeerError:
                self._drop_peer(peer, "protocol violation", penalize=True)
                return
            except Exception:  # noqa: BLE001 — serving must not kill the loop
                self._drop_peer(peer, "stream error")
                return
        if getattr(peer, "_swarm_overflow", False):
            self._drop_peer(peer, "send backpressure")

    def _set_write_interest(self, peer, on: bool):
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE if on else 0)
        try:
            self.selector.modify(peer.session.sock, events, "peer")
        except (KeyError, ValueError, OSError):
            pass

    def _flush_outboxes(self):
        with self._lock:
            peers = list(self._peers.values())
        for peer in peers:
            # an overflowed egress stream is DESYNCED (a frame was dropped
            # after the CTR/MAC state advanced): evict unconditionally
            if getattr(peer, "_swarm_overflow", False):
                self._drop_peer(peer, "send backpressure")
                continue
            outbox = getattr(peer, "_swarm_outbox", None)
            if not outbox:
                continue
            drop_reason = None
            with peer._lock:
                try:
                    mv = memoryview(outbox)
                    sent = peer.session.sock.send(mv)
                    mv.release()
                    del outbox[:sent]
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    drop_reason = "stream error"
            if drop_reason:
                self._drop_peer(peer, drop_reason)
            else:
                # a pending remainder wakes the loop the moment the socket
                # drains (true flush-on-writability, not timeout polling)
                self._set_write_interest(peer, bool(outbox))
