"""discv5 (v5.1): encrypted UDP node discovery with ENRs.

Reference analogue: crates/net/discv5 (the reference wraps sigp/discv5;
src/lib.rs builds the service, src/enr.rs converts records). This is a
from-scratch implementation of the wire protocol:

  packet = masking-iv(16) || AES-CTR(dest-id[:16], iv)(header) || message
  header = "discv5" || 0x0001 || flag(1) || nonce(12) || authdata-len(2)
           || authdata

Flags: 0 ordinary (authdata = src-id; message AES-GCM encrypted under the
session key, AD = masking-iv || header), 1 WHOAREYOU (authdata = id-nonce
(16) || enr-seq(8)), 2 handshake (authdata = src-id || sig-size ||
eph-key-size || id-signature || eph-pubkey || optional ENR).

Session keys (HKDF-SHA256): ikm = compressed ECDH point, salt =
challenge-data (= masking-iv || whoareyou header), info =
"discovery v5 key agreement" || src-id || dest-id -> initiator-key(16)
|| recipient-key(16). The id-signature covers sha256("discovery v5
identity proof" || challenge-data || eph-pubkey || dest-id).

Messages: PING [rid, enr-seq], PONG [rid, enr-seq, ip, port],
FINDNODE [rid, [log2-distance...]], NODES [rid, total, [ENR...]].
Kademlia distance is xor over the 32-byte node ids directly (ids are
already keccak outputs).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import socket
import threading
import time

from ..primitives import secp256k1
from ..primitives.rlp import decode_int, encode_int, rlp_decode_prefix, rlp_encode
from ..primitives.secp256k1 import (
    compress_pubkey,
    pubkey_from_priv,
    random_priv,
)
from ._aes import AESGCM, Cipher, algorithms, modes  # optional-dep shim
from .enr import Enr, make_enr, node_id_from_pubkey

PROTOCOL_ID = b"discv5"
VERSION = b"\x00\x01"
FLAG_ORDINARY, FLAG_WHOAREYOU, FLAG_HANDSHAKE = 0, 1, 2

PING, PONG, FINDNODE, NODES = 0x01, 0x02, 0x03, 0x04

ID_SIGNATURE_TEXT = b"discovery v5 identity proof"
KDF_INFO_TEXT = b"discovery v5 key agreement"

BUCKET_SIZE = 16
MAX_NODES_PER_MSG = 4  # ENRs per NODES packet (fits a 1280-byte datagram)


class Discv5Error(ValueError):
    pass


MAX_TRACKED = 1024


def _trim(d: dict, cap: int = MAX_TRACKED) -> None:
    """Evict oldest entries (insertion order) past the cap — both the
    pending-request and challenge maps are fed by unauthenticated traffic."""
    while len(d) > cap:
        d.pop(next(iter(d)))


def _aes_ctr(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    c = Cipher(algorithms.AES(key16), modes.CTR(iv16)).encryptor()
    return c.update(data) + c.finalize()


def _hkdf(salt: bytes, ikm: bytes, info: bytes, length: int = 32) -> bytes:
    prk = hmac_mod.new(salt, ikm, hashlib.sha256).digest()
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hmac_mod.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def _ecdh(priv: int, pub: tuple[int, int]) -> bytes:
    """discv5 ECDH: the COMPRESSED encoding of priv*pub (33 bytes) — unlike
    ECIES which keeps only x."""
    x, y = secp256k1._to_affine(secp256k1._jmul((pub[0], pub[1], 1), priv))
    return compress_pubkey((x, y))


def derive_session_keys(challenge_data: bytes, eph_priv: int | None,
                        eph_pub: tuple[int, int] | None,
                        static_priv: int | None, static_pub: tuple[int, int] | None,
                        src_id: bytes, dest_id: bytes) -> tuple[bytes, bytes]:
    """(initiator_key, recipient_key). The initiator supplies eph_priv +
    the peer's static pubkey; the recipient supplies its static_priv + the
    initiator's eph pubkey — both land on the same shared point."""
    if eph_priv is not None:
        shared = _ecdh(eph_priv, static_pub)
    else:
        shared = _ecdh(static_priv, eph_pub)
    info = KDF_INFO_TEXT + src_id + dest_id
    keys = _hkdf(challenge_data, shared, info, 32)
    return keys[:16], keys[16:]


def id_sign(priv: int, challenge_data: bytes, eph_pub_compressed: bytes,
            dest_id: bytes) -> bytes:
    digest = hashlib.sha256(
        ID_SIGNATURE_TEXT + challenge_data + eph_pub_compressed + dest_id
    ).digest()
    _y, r, s = secp256k1.sign(digest, priv)
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def id_verify(pub: tuple[int, int], sig: bytes, challenge_data: bytes,
              eph_pub_compressed: bytes, dest_id: bytes) -> bool:
    if len(sig) != 64:
        return False
    digest = hashlib.sha256(
        ID_SIGNATURE_TEXT + challenge_data + eph_pub_compressed + dest_id
    ).digest()
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    from ..primitives.secp256k1 import pubkey_to_bytes

    for y in (0, 1):
        try:
            if secp256k1.ecrecover(digest, y, r, s, allow_high_s=True,
                                   return_pubkey=True) == pubkey_to_bytes(pub):
                return True
        except Exception:  # noqa: BLE001 — wrong recovery bit
            continue
    return False


# -- packet codec -----------------------------------------------------------

def _header(flag: int, nonce: bytes, authdata: bytes) -> bytes:
    return (PROTOCOL_ID + VERSION + bytes([flag]) + nonce
            + len(authdata).to_bytes(2, "big") + authdata)


def mask_packet(dest_id: bytes, header: bytes, message: bytes,
                masking_iv: bytes | None = None) -> bytes:
    iv = masking_iv or os.urandom(16)
    return iv + _aes_ctr(dest_id[:16], iv, header) + message


def unmask_packet(local_id: bytes, raw: bytes) -> tuple[bytes, int, bytes, bytes, bytes]:
    """-> (masking_iv, flag, nonce, authdata, message). Header bytes are
    recovered by decrypting with OUR id as the masking key."""
    if len(raw) < 16 + 23:
        raise Discv5Error("packet too short")
    iv = raw[:16]
    # static header = 6 + 2 + 1 + 12 + 2 = 23 bytes, then authdata
    dec = Cipher(algorithms.AES(local_id[:16]), modes.CTR(iv)).decryptor()
    static = dec.update(raw[16:39])
    if static[:6] != PROTOCOL_ID or static[6:8] != VERSION:
        raise Discv5Error("bad protocol id")
    flag = static[8]
    nonce = static[9:21]
    authdata_len = int.from_bytes(static[21:23], "big")
    if len(raw) < 39 + authdata_len:
        raise Discv5Error("truncated authdata")
    authdata = dec.update(raw[39:39 + authdata_len])
    header = static + authdata
    message = raw[39 + authdata_len:]
    return iv, flag, nonce, authdata, message


# -- messages ---------------------------------------------------------------

def encode_message(mtype: int, fields: list) -> bytes:
    return bytes([mtype]) + rlp_encode(fields)


def decode_message(raw: bytes) -> tuple[int, list]:
    if not raw:
        raise Discv5Error("empty message")
    fields, consumed = rlp_decode_prefix(raw[1:])
    if consumed != len(raw) - 1:
        raise Discv5Error("trailing bytes")
    return raw[0], fields


class Session:
    __slots__ = ("initiator_key", "recipient_key", "we_initiated", "counter")

    def __init__(self, initiator_key: bytes, recipient_key: bytes,
                 we_initiated: bool):
        self.initiator_key = initiator_key
        self.recipient_key = recipient_key
        self.we_initiated = we_initiated
        self.counter = 0

    @property
    def send_key(self) -> bytes:
        return self.initiator_key if self.we_initiated else self.recipient_key

    @property
    def recv_key(self) -> bytes:
        return self.recipient_key if self.we_initiated else self.initiator_key


class RoutingTable:
    """256 xor buckets over raw 32-byte node ids."""

    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.by_id: dict[bytes, Enr] = {}

    @staticmethod
    def distance(a: bytes, b: bytes) -> int:
        return (int.from_bytes(a, "big") ^ int.from_bytes(b, "big")).bit_length()

    def add(self, enr: Enr) -> None:
        nid = enr.node_id
        if nid == self.local_id:
            return
        old = self.by_id.get(nid)
        if old is None or enr.seq >= old.seq:
            self.by_id[nid] = enr

    def at_distance(self, d: int) -> list[Enr]:
        return [e for nid, e in self.by_id.items()
                if self.distance(self.local_id, nid) == d][:BUCKET_SIZE]

    def closest(self, target: bytes, n: int = BUCKET_SIZE) -> list[Enr]:
        t = int.from_bytes(target, "big")
        return sorted(self.by_id.values(),
                      key=lambda e: t ^ int.from_bytes(e.node_id, "big"))[:n]

    def __len__(self):
        return len(self.by_id)


class Discv5:
    """One discv5 endpoint: UDP listener, sessions, routing table."""

    def __init__(self, priv: int, host: str = "127.0.0.1", port: int = 0,
                 tcp_port: int = 0):
        self.priv = priv
        self.pub = pubkey_from_priv(priv)
        self.node_id = node_id_from_pubkey(self.pub)
        self.host = host
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.settimeout(0.2)
        self.port = self.sock.getsockname()[1]
        self.enr_seq = 1
        self.enr = make_enr(priv, ip=host, udp=self.port,
                            tcp=tcp_port or self.port, seq=self.enr_seq)
        self.table = RoutingTable(self.node_id)
        self.sessions: dict[bytes, Session] = {}          # node-id -> keys
        self._pending: dict[bytes, tuple[bytes, bytes, tuple]] = {}
        #   nonce -> (dest-id, plaintext message, addr) awaiting WHOAREYOU
        self._challenges: dict[bytes, bytes] = {}         # node-id -> challenge-data
        self._req_counter = 0
        self._waiters: dict[bytes, threading.Event] = {}  # request-id -> done
        self._results: dict[bytes, list] = {}
        self._chunks: dict[bytes, list[int]] = {}         # rid -> [got, total]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.sock.close()

    # -- sending ------------------------------------------------------------

    def _next_request_id(self) -> bytes:
        with self._lock:
            self._req_counter += 1
            return self._req_counter.to_bytes(4, "big")

    def _send_ordinary(self, dest: Enr, message_pt: bytes) -> None:
        nid = dest.node_id
        addr = (dest.ip, dest.udp_port)
        nonce = os.urandom(12)
        with self._lock:
            session = self.sessions.get(nid)
            # ALWAYS remember the plaintext by nonce: if the peer lost its
            # session keys it answers WHOAREYOU referencing this nonce, and
            # the handshake retransmits the message (session repair)
            self._pending[nonce] = (nid, message_pt, addr)
            _trim(self._pending)
        header = _header(FLAG_ORDINARY, nonce, self.node_id)
        iv = os.urandom(16)
        if session is None:
            # no session yet: random payload provokes a WHOAREYOU challenge
            message = os.urandom(16)
        else:
            message = AESGCM(session.send_key).encrypt(nonce, message_pt,
                                                       iv + header)
        self.sock.sendto(mask_packet(nid, header, message, iv), addr)

    def _send_whoareyou(self, src_id: bytes, req_nonce: bytes, addr) -> None:
        id_nonce = os.urandom(16)
        known = self.table.by_id.get(src_id)
        enr_seq = known.seq if known else 0
        authdata = id_nonce + enr_seq.to_bytes(8, "big")
        header = _header(FLAG_WHOAREYOU, req_nonce, authdata)
        iv = os.urandom(16)
        with self._lock:
            self._challenges[src_id] = iv + header  # challenge-data
            _trim(self._challenges)  # spoofed src-ids must not grow memory
        self.sock.sendto(mask_packet(src_id, header, b"", iv), addr)

    def _send_handshake(self, dest_id: bytes, challenge_data: bytes,
                        enr_seq_known: int, message_pt: bytes, addr) -> None:
        eph_priv = random_priv()
        eph_pub_c = compress_pubkey(pubkey_from_priv(eph_priv))
        dest_enr = self.table.by_id.get(dest_id)
        if dest_enr is None:
            raise Discv5Error("cannot handshake with unknown record")
        ik, rk = derive_session_keys(challenge_data, eph_priv, None, None,
                                     dest_enr.pubkey, self.node_id, dest_id)
        sig = id_sign(self.priv, challenge_data, eph_pub_c, dest_id)
        authdata = (self.node_id + bytes([len(sig)]) + bytes([len(eph_pub_c)])
                    + sig + eph_pub_c)
        if enr_seq_known < self.enr_seq:
            authdata += self.enr.encode()
        nonce = os.urandom(12)
        header = _header(FLAG_HANDSHAKE, nonce, authdata)
        iv = os.urandom(16)
        message = AESGCM(ik).encrypt(nonce, message_pt, iv + header)
        with self._lock:
            self.sessions[dest_id] = Session(ik, rk, we_initiated=True)
        self.sock.sendto(mask_packet(dest_id, header, message, iv), addr)

    # -- rpc ----------------------------------------------------------------

    def ping(self, dest: Enr) -> None:
        rid = self._next_request_id()
        self._send_ordinary(dest, encode_message(
            PING, [rid, encode_int(self.enr_seq)]))

    def find_node(self, dest: Enr, distances: list[int],
                  wait: float = 0.0) -> list[Enr]:
        rid = self._next_request_id()
        ev = threading.Event()
        with self._lock:
            self._waiters[rid] = ev
            self._results[rid] = []
            self._chunks[rid] = [0, 1]
        self._send_ordinary(dest, encode_message(
            FINDNODE, [rid, [encode_int(d) for d in distances]]))
        if wait:
            ev.wait(wait)
        with self._lock:
            self._waiters.pop(rid, None)
            self._chunks.pop(rid, None)
            return self._results.pop(rid, [])

    def bootstrap(self, enrs: list[Enr | str]) -> None:
        for e in enrs:
            rec = Enr.from_base64(e) if isinstance(e, str) else e
            self.table.add(rec)
            self.ping(rec)

    def lookup(self, target: bytes | None = None, rounds: int = 3,
               wait: float = 0.5) -> list[Enr]:
        target = target or self.node_id
        seen: set[bytes] = set()
        for _ in range(rounds):
            with self._lock:
                cands = [e for e in self.table.closest(target, 6)
                         if e.node_id not in seen and e.node_id in self.sessions]
            for e in cands[:3]:
                seen.add(e.node_id)
                d = RoutingTable.distance(e.node_id, target)
                got = self.find_node(e, [d or 1, min(d + 1, 256), max(d - 1, 1)],
                                     wait=wait)
                for enr in got:
                    self.table.add(enr)
        return self.table.closest(target)

    # -- receive loop --------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                raw, addr = self.sock.recvfrom(1500)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._handle_packet(raw, addr)
            except Exception:  # noqa: BLE001 — datagrams are attacker-
                # controlled; a parse/crypto failure drops the packet only
                continue

    def _handle_packet(self, raw: bytes, addr) -> None:
        iv, flag, nonce, authdata, message = unmask_packet(self.node_id, raw)
        header = _header(flag, nonce, authdata)
        if flag == FLAG_WHOAREYOU:
            self._on_whoareyou(iv, nonce, authdata, addr)
        elif flag == FLAG_ORDINARY:
            src_id = authdata[:32]
            with self._lock:
                session = self.sessions.get(src_id)
            if session is None:
                self._send_whoareyou(src_id, nonce, addr)
                return
            try:
                pt = AESGCM(session.recv_key).decrypt(nonce, message, iv + header)
            except Exception:  # noqa: BLE001 — stale/invalid session keys
                self._send_whoareyou(src_id, nonce, addr)
                return
            self._on_message(src_id, pt, addr)
        elif flag == FLAG_HANDSHAKE:
            self._on_handshake(iv, header, nonce, authdata, message, addr)

    def _on_whoareyou(self, iv: bytes, req_nonce: bytes, authdata: bytes,
                      addr) -> None:
        if len(authdata) != 24:
            raise Discv5Error("bad whoareyou authdata")
        enr_seq = int.from_bytes(authdata[16:24], "big")
        with self._lock:
            pend = self._pending.pop(req_nonce, None)
        if pend is None:
            return
        dest_id, message_pt, dest_addr = pend
        with self._lock:
            # the peer could not decrypt our message: any session we hold
            # for it is stale — the handshake below replaces it
            self.sessions.pop(dest_id, None)
        challenge_data = iv + _header(FLAG_WHOAREYOU, req_nonce, authdata)
        self._send_handshake(dest_id, challenge_data, enr_seq, message_pt,
                             dest_addr)

    def _on_handshake(self, iv: bytes, header: bytes, nonce: bytes,
                      authdata: bytes, message: bytes, addr) -> None:
        if len(authdata) < 34:
            raise Discv5Error("short handshake authdata")
        src_id = authdata[:32]
        sig_size = authdata[32]
        eph_size = authdata[33]
        off = 34
        sig = authdata[off:off + sig_size]
        off += sig_size
        eph_pub_c = authdata[off:off + eph_size]
        off += eph_size
        record = authdata[off:]
        with self._lock:
            challenge_data = self._challenges.pop(src_id, None)
        if challenge_data is None:
            raise Discv5Error("handshake without challenge")
        if record:
            enr = Enr.decode(record)
            if enr.node_id != src_id:
                raise Discv5Error("handshake record id mismatch")
            self.table.add(enr)
        src_enr = self.table.by_id.get(src_id)
        if src_enr is None:
            raise Discv5Error("handshake from unknown node without record")
        if not id_verify(src_enr.pubkey, sig, challenge_data, eph_pub_c,
                         self.node_id):
            raise Discv5Error("bad id signature")
        from ..primitives.secp256k1 import decompress_pubkey

        eph_pub = decompress_pubkey(eph_pub_c)
        ik, rk = derive_session_keys(challenge_data, None, eph_pub, self.priv,
                                     None, src_id, self.node_id)
        pt = AESGCM(ik).decrypt(nonce, message, iv + header)
        with self._lock:
            self.sessions[src_id] = Session(ik, rk, we_initiated=False)
        self._on_message(src_id, pt, addr)

    # -- message handling ----------------------------------------------------

    def _on_message(self, src_id: bytes, pt: bytes, addr) -> None:
        mtype, f = decode_message(pt)
        if mtype == PING:
            rid = bytes(f[0])
            self._respond(src_id, addr, encode_message(PONG, [
                rid, encode_int(self.enr_seq),
                socket.inet_aton(addr[0]), encode_int(addr[1]),
            ]))
        elif mtype == PONG:
            pass  # liveness noted via session existence
        elif mtype == FINDNODE:
            rid = bytes(f[0])
            distances = [decode_int(d) for d in f[1]]
            out: list[Enr] = []
            with self._lock:
                for d in distances[:8]:
                    if d == 0:
                        out.append(self.enr)
                    else:
                        out.extend(self.table.at_distance(d))
            chunks = [out[i:i + MAX_NODES_PER_MSG]
                      for i in range(0, len(out), MAX_NODES_PER_MSG)] or [[]]
            total = len(chunks)
            for chunk in chunks:
                records = [rlp_decode_prefix(e.encode())[0] for e in chunk]
                self._respond(src_id, addr, encode_message(
                    NODES, [rid, encode_int(total), records]))
        elif mtype == NODES:
            rid = bytes(f[0])
            with self._lock:
                sink = self._results.get(rid)
                ev = self._waiters.get(rid)
                chunks = self._chunks.get(rid)
            if sink is None:
                return
            for rec_fields in f[2]:
                try:
                    enr = Enr.decode(rlp_encode(rec_fields))
                except Exception:  # noqa: BLE001 — bad record from peer
                    continue
                sink.append(enr)
            # a multi-chunk response completes only when all `total`
            # messages arrived (capped: a malicious total can't stall the
            # waiter past its timeout)
            if chunks is not None:
                chunks[0] += 1
                chunks[1] = max(chunks[1], min(decode_int(f[1]), 64))
                if chunks[0] < chunks[1]:
                    return
            if ev is not None:
                ev.set()

    def _respond(self, dest_id: bytes, addr, message_pt: bytes) -> None:
        """Encrypted reply over the established session."""
        with self._lock:
            session = self.sessions.get(dest_id)
        if session is None:
            return
        nonce = os.urandom(12)
        header = _header(FLAG_ORDINARY, nonce, self.node_id)
        iv = os.urandom(16)
        message = AESGCM(session.send_key).encrypt(nonce, message_pt, iv + header)
        self.sock.sendto(mask_packet(dest_id, header, message, iv), addr)
