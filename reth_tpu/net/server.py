"""NetworkManager: listen, serve eth requests, track peers.

Reference analogue: crates/net/network — `NetworkManager`
(src/manager.rs:108) + `EthRequestHandler` serving headers/bodies/
receipts from the provider (src/eth_requests.rs), and tx broadcast
hooks (src/transactions/). Inbound sessions are served by the ONE
event-loop swarm thread (`net/swarm.py`, reference src/swarm.rs);
handshakes run on transient threads only.
"""

from __future__ import annotations

import socket
import threading

from ..primitives.secp256k1 import pubkey_from_bytes
from . import wire
from .p2p import PeerConnection, PeerDisconnected, PeerError, random_node_key
from .rlpx import node_id as rlpx_node_id
from .wire import Status

MAX_HEADERS_SERVE = 1024
MAX_BODIES_SERVE = 256


def parse_enode(url: str) -> tuple[tuple[int, int], str, int]:
    """enode://<128-hex node id>@host:port -> (pubkey, host, port)."""
    if not url.startswith("enode://"):
        raise ValueError("not an enode url")
    ident, _, addr = url[8:].partition("@")
    host, _, port = addr.partition(":")
    return pubkey_from_bytes(bytes.fromhex(ident)), host, int(port or "30303")


class NetworkManager:
    def __init__(self, factory, status: Status, pool=None, host: str = "127.0.0.1",
                 port: int = 0, node_priv: int | None = None,
                 chain_spec=None, head_position: tuple[int, int] = (0, 0),
                 max_inbound: int = 30, max_outbound: int = 100,
                 provider_fn=None):
        self.factory = factory
        # request serving reads THIS view: a node passes its engine-tree
        # overlay provider so peers can fetch the announced in-memory tip
        # (blocks above the persistence threshold live in the tree, not
        # the DB — serving only persisted state would advertise a head
        # nobody can download)
        self._provider_fn = provider_fn or factory.provider
        self.status = status
        self.pool = pool
        self.host = host
        self.advertised_host: str | None = None  # NAT-resolved external IP
        self.port = port
        self.node_priv = node_priv or random_node_key()
        # EIP-2124 ForkFilter: reject peers on an incompatible fork during
        # the Status handshake (reference: alloy ForkFilter used by
        # crates/net/network session setup)
        self.chain_spec = chain_spec
        self.head_position = head_position
        self.peers: list[PeerConnection] = []
        from .reputation import PeersManager
        from .sessions import SessionManager

        self.peers_manager = PeersManager()
        # session lifecycle + caps + events (reference SessionManager in
        # the Swarm, src/session/mod.rs): capacity reserves BEFORE the
        # handshake, transitions fan out to listeners
        self.sessions = SessionManager(max_inbound=max_inbound,
                                       max_outbound=max_outbound)
        self._listener: socket.socket | None = None
        self._stop = threading.Event()

    def _snap_server(self):
        if getattr(self, "_snap", None) is None:
            from .snap import SnapServer

            self._snap = SnapServer(self.factory)
        return self._snap

    def _fork_filter(self, remote_fork_id: tuple[bytes, int]) -> None:
        if self.chain_spec is not None:
            self.chain_spec.validate_fork_id(remote_fork_id, *self.head_position)

    @property
    def enode(self) -> str:
        host = self.advertised_host or self.host
        return (f"enode://{rlpx_node_id(self.node_priv).hex()}"
                f"@{host}:{self.port}")

    def connect_to(self, enode_url: str, timeout: float = 10.0) -> PeerConnection:
        """Dial a peer by enode URL (encrypted RLPx session)."""
        pub, host, port = parse_enode(enode_url)
        from ..primitives.secp256k1 import pubkey_to_bytes

        if self.peers_manager.is_banned(pubkey_to_bytes(pub)):
            raise PeerError("peer is banned")
        session = self.sessions.reserve("outbound")
        try:
            peer = PeerConnection.connect(host, port, self.status, pub,
                                          node_priv=self.node_priv,
                                          timeout=timeout,
                                          fork_filter=self._fork_filter)
        except BaseException:
            self.sessions.close(session, "handshake failed")
            raise
        self.sessions.activate(session, peer)
        peer._session_slot = session
        # outbound peers have no serve loop here: closing the connection
        # must release the session slot AND drop the peer from the live
        # list (discovery dedup + broadcasts iterate it)
        def _closed(peer=peer, session=session):
            self.sessions.close(session, "closed")
            try:
                self.peers.remove(peer)
            except ValueError:
                pass

        peer._on_close = (_closed,)
        self.peers.append(peer)
        return peer

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> int:
        from .swarm import Swarm

        self._listener = socket.create_server((self.host, self.port))
        self.port = self._listener.getsockname()[1]
        # ONE event loop owns the listener and every established inbound
        # session (reference swarm, src/swarm.rs); handshakes run on
        # transient threads only
        self.swarm = Swarm(self, self._listener)
        self.swarm.start()
        return self.port

    def stop(self):
        self._stop.set()
        if getattr(self, "swarm", None) is not None:
            self.swarm.stop()
        if self._listener:
            self._listener.close()
        for p in list(self.peers):  # close releases session slots
            p.close()

    def _handle(self, peer: PeerConnection, msg):
        from . import snap as snap_mod

        if isinstance(msg, snap_mod.GetAccountRange):
            peer.send_snap(self._snap_server().account_range(msg))
            return
        if isinstance(msg, snap_mod.GetStorageRanges):
            peer.send_snap(self._snap_server().storage_ranges(msg))
            return
        if isinstance(msg, snap_mod.GetByteCodes):
            peer.send_snap(self._snap_server().byte_codes(msg))
            return
        if isinstance(msg, snap_mod.GetTrieNodes):
            peer.send_snap(self._snap_server().trie_nodes(msg))
            return
        if isinstance(msg, wire.GetBlockHeaders):
            peer.send(wire.BlockHeaders(msg.request_id, self._headers_for(msg)))
        elif isinstance(msg, wire.GetBlockBodies):
            peer.send(wire.BlockBodies(msg.request_id, self._bodies_for(msg.hashes)))
        elif isinstance(msg, wire.GetReceipts):
            peer.send(wire.ReceiptsMsg(msg.request_id, self._receipts_for(msg.hashes)))
        elif isinstance(msg, wire.BlockRangeUpdate):
            peer.block_range = (msg.earliest, msg.latest, msg.latest_hash)
        elif isinstance(msg, wire.TransactionsMsg) and self.pool is not None:
            from ..pool import PoolError

            for tx in msg.transactions:
                try:
                    self.pool.add_transaction(tx)
                except PoolError:
                    pass
        # other gossip ignored for now

    def _headers_for(self, req: wire.GetBlockHeaders):
        with self._provider_fn() as p:
            if isinstance(req.start, bytes):
                start = p.block_number(req.start)
                if start is None:
                    return []
            else:
                start = req.start
            step = -(1 + req.skip) if req.reverse else (1 + req.skip)
            out = []
            n = start
            for _ in range(min(req.limit, MAX_HEADERS_SERVE)):
                h = p.header_by_number(n)
                if h is None:
                    break
                out.append(h)
                n += step
                if n < 0:
                    break
            return out

    def _bodies_for(self, hashes):
        from .wire import BlockBody

        out = []
        with self._provider_fn() as p:
            for h in hashes[:MAX_BODIES_SERVE]:
                n = p.block_number(h)
                if n is None:
                    continue
                block = p.block_by_number(n)
                out.append(BlockBody(block.transactions, block.ommers, block.withdrawals))
        return out

    def _receipts_for(self, hashes):
        from ..storage import tables as T

        out = []
        with self._provider_fn() as p:
            for h in hashes[:MAX_BODIES_SERVE]:
                n = p.block_number(h)
                if n is None:
                    continue
                idx = p.block_body_indices(n)
                rs = []
                if idx:
                    for t in range(idx.first_tx_num, idx.next_tx_num):
                        r = p.receipt(t)
                        if r is not None:
                            rs.append(T.encode_receipt(r))
                out.append(rs)
        return out

    # -- broadcast -------------------------------------------------------------

    def broadcast_transactions(self, txs):
        for peer in list(self.peers):
            try:
                peer.send(wire.TransactionsMsg(list(txs)))
            except (PeerError, OSError):
                pass

    def announce_block_range(self, earliest: int, latest: int,
                             latest_hash: bytes):
        """eth/69 BlockRangeUpdate to every v69 peer (replaces TD gossip)."""
        for peer in list(self.peers):
            if peer.eth_version < 69:
                continue
            try:
                peer.send(wire.BlockRangeUpdate(earliest, latest, latest_hash))
            except (PeerError, OSError):
                pass
