"""Snappy raw-block codec (pure Python) for RLPx message compression.

Reference analogue: the `snap` crate behind reth's eth-wire multiplexing
(RLPx requires snappy for p2p protocol v5+). Decompression implements the
full raw format (literals + all three copy element kinds); compression
uses the standard greedy hash-table matcher, and any output we produce is
decodable by every conformant snappy implementation.

Format (raw block, not framed): uvarint total length, then elements with
a 2-bit tag: 00 literal, 01 copy (len 4-11, offset 11 bits),
10 copy (len 1-64, offset 16 bits LE), 11 copy (offset 32 bits LE).
"""

from __future__ import annotations


class SnappyError(ValueError):
    pass


def _uvarint(data: bytes, i: int) -> tuple[int, int]:
    shift = 0
    out = 0
    while True:
        if i >= len(data):
            raise SnappyError("truncated uvarint")
        b = data[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 63:
            raise SnappyError("uvarint too long")


def _put_uvarint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def decompress(data: bytes, max_len: int = 16 * 1024 * 1024) -> bytes:
    total, i = _uvarint(data, 0)
    if total > max_len:
        raise SnappyError(f"declared length {total} over limit")
    out = bytearray()
    n = len(data)
    while i < n:
        tag = data[i]
        kind = tag & 3
        i += 1
        if kind == 0:  # literal
            ln = tag >> 2
            if ln >= 60:
                extra = ln - 59
                if i + extra > n:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(data[i : i + extra], "little")
                i += extra
            ln += 1
            if i + ln > n:
                raise SnappyError("truncated literal")
            out += data[i : i + ln]
            i += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 7) + 4
            off = ((tag >> 5) << 8)
            if i >= n:
                raise SnappyError("truncated copy1")
            off |= data[i]
            i += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            if i + 2 > n:
                raise SnappyError("truncated copy2")
            off = int.from_bytes(data[i : i + 2], "little")
            i += 2
        else:
            ln = (tag >> 2) + 1
            if i + 4 > n:
                raise SnappyError("truncated copy4")
            off = int.from_bytes(data[i : i + 4], "little")
            i += 4
        if off == 0 or off > len(out):
            raise SnappyError("copy offset out of range")
        for _ in range(ln):  # overlapping copies are allowed
            out.append(out[-off])
        if len(out) > max_len:
            raise SnappyError("decompressed over limit")
    if len(out) != total:
        raise SnappyError(f"length mismatch: {len(out)} != declared {total}")
    return bytes(out)


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    n = len(chunk) - 1
    if n < 60:
        out.append(n << 2)
    elif n < (1 << 8):
        out.append(60 << 2)
        out.append(n)
    elif n < (1 << 16):
        out.append(61 << 2)
        out += n.to_bytes(2, "little")
    elif n < (1 << 24):
        out.append(62 << 2)
        out += n.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += n.to_bytes(4, "little")
    out += chunk


def compress(data: bytes) -> bytes:
    """Greedy hash-table matcher (4-byte anchors, 64KB window)."""
    out = bytearray(_put_uvarint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    i = 0
    lit_start = 0
    while i + 4 <= n:
        key = data[i : i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and data[cand : cand + 4] == key:
            # extend the match
            ln = 4
            while i + ln < n and ln < 64 and data[cand + ln] == data[i + ln]:
                ln += 1
            if lit_start < i:
                _emit_literal(out, data[lit_start:i])
            off = i - cand
            if 4 <= ln <= 11 and off < (1 << 11):
                out.append(1 | ((ln - 4) << 2) | ((off >> 8) << 5))
                out.append(off & 0xFF)
            else:
                out.append(2 | ((ln - 1) << 2))
                out += off.to_bytes(2, "little")
            i += ln
            lit_start = i
        else:
            i += 1
    if lit_start < n:
        _emit_literal(out, data[lit_start:])
    return bytes(out)
