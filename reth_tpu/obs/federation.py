"""Metrics federation: one fleet, one metrics surface.

PR 13 made the node a distributed system; its `/metrics` registries
stayed per-process — a fleet of N replicas was N unmergeable scrape
targets, and "fleet read p99" was a number nobody could compute. This
module is the pull half of the fix:

- **Replica side** (:class:`FederationSource`): wraps a
  :class:`~reth_tpu.metrics.MetricsRegistry` behind a cursor-based
  delta protocol. A pull with the source's current cursor returns only
  the metrics that CHANGED since the previous pull — counters and
  histograms delta-encoded beside their absolute values, gauges by
  value — bounded to ``max_metrics`` series per pull. A missing or
  stale cursor (first pull, replica restart, federation restart)
  returns the full absolute state and re-anchors. Served as the
  ``fleet_metricsSnapshot`` RPC (engine admission class beside the
  other ``fleet_*`` methods).
- **Full-node side** (:class:`MetricsFederation`): a background puller
  (its OWN thread — a slow or dead replica can never block the feed,
  the gateway, or the prober) walks the
  :class:`~reth_tpu.fleet.ring.FleetRouter`'s registered replicas each
  interval, applies the deltas into per-replica series — the PR 9
  sampler ring shape: counters ``(ts, cumulative, delta)``, gauges
  ``(ts, value)``, histograms ``(ts, n_delta, sum_delta,
  bucket_deltas)`` in bounded rings — and marks a replica **stale**
  (data retained, age visible) when a pull fails. Merging is
  bucket-wise: the fleet histogram's counts are the element-wise sums
  of the per-replica counts, so a federated quantile
  (:meth:`MetricsFederation.fleet_quantile`, via the shared
  :func:`~reth_tpu.metrics.histogram_quantile`) is exactly the quantile
  of the combined population — no quantile-of-quantiles averaging.

Surfaces: ``GET /metrics?scope=fleet`` appends :meth:`render` (every
pulled series per-replica-labeled + the ``replica="_fleet"`` bucket-wise
merge) to the local exposition; the ``debug_fleetMetrics`` RPC returns
:meth:`summary`; ``node/events.py`` prints the ``fleetobs[...]``
fragment from :meth:`snapshot`; and ``health.py``'s fleet SLO rules
(fleet read p99, replica-lag distribution, federation staleness) read
the installed process default (:func:`install` / :func:`get_federation`,
the ``health.py`` seam shape).
"""

from __future__ import annotations

import threading
import time

from .. import tracing
from ..metrics import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    histogram_quantile,
)

DEFAULT_INTERVAL_S = 1.0
DEFAULT_WINDOW = 120          # retained pull deltas per series
DEFAULT_MAX_METRICS = 1024    # series per pull (bounded payload)
FLEET_LABEL = "_fleet"        # the bucket-wise merged pseudo-replica


def snapshot_registry(registry: MetricsRegistry,
                      max_metrics: int = DEFAULT_MAX_METRICS) -> dict:
    """One registry as a JSON-able absolute snapshot:
    ``{name: {"k": "c"|"g", "v": value} | {"k": "h", "b": buckets,
    "c": counts, "s": sum, "n": count}}``."""
    out: dict = {}
    for name, m in registry.items():
        if len(out) >= max_metrics:
            break
        if isinstance(m, Counter):
            out[name] = {"k": "c", "v": m.value}
        elif isinstance(m, Gauge):
            out[name] = {"k": "g", "v": m.value}
        elif isinstance(m, Histogram):
            counts, total, n = m.snapshot()
            out[name] = {"k": "h", "b": list(m.buckets), "c": counts,
                         "s": total, "n": n}
    return out


class FederationSource:
    """Replica-side pull endpoint: cursor-based delta encoding over a
    registry, so steady-state federation traffic carries only what
    changed since the last pull."""

    def __init__(self, registry: MetricsRegistry | None = None, *,
                 max_metrics: int = DEFAULT_MAX_METRICS):
        import os

        self.registry = registry or REGISTRY
        self.max_metrics = max_metrics
        # cursor nonce: a replica restart mints a new one, so a stale
        # federation cursor forces a full re-anchor instead of applying
        # deltas against state the restart threw away
        self._nonce = f"{os.getpid():x}.{id(self) & 0xFFFF:x}"
        self._seq = 0
        self._last: dict[str, object] = {}
        self._lock = threading.Lock()
        self.pulls = 0

    def snapshot(self, cursor: str | None = None) -> dict:
        """One pull. With the current cursor: only changed metrics,
        delta-encoded (``d`` = counter delta, ``dn``/``ds``/``dc`` =
        histogram count/sum/bucket deltas). Otherwise: the full
        absolute state (``full: true``)."""
        with self._lock:
            full = cursor != f"{self._nonce}:{self._seq}" or not self._last
            metrics: dict = {}
            truncated = 0
            for name, m in self.registry.items():
                if len(metrics) >= self.max_metrics:
                    truncated += 1
                    continue
                if isinstance(m, Counter):
                    v = m.value
                    prev = self._last.get(name)
                    if full or prev != v:
                        entry: dict = {"k": "c", "v": v}
                        if not full and isinstance(prev, (int, float)):
                            entry["d"] = v - prev if v >= prev else v
                        metrics[name] = entry
                    self._last[name] = v
                elif isinstance(m, Gauge):
                    v = m.value
                    prev = self._last.get(name)
                    if full or prev != v:
                        metrics[name] = {"k": "g", "v": v}
                    self._last[name] = v
                elif isinstance(m, Histogram):
                    counts, total, n = m.snapshot()
                    prev = self._last.get(name)
                    if full or prev is None or prev[2] != n \
                            or prev[1] != total or prev[0] != counts:
                        entry = {"k": "h", "c": counts, "s": total, "n": n}
                        if full or prev is None:
                            entry["b"] = list(m.buckets)
                        elif n >= prev[2]:
                            entry["dn"] = n - prev[2]
                            entry["ds"] = total - prev[1]
                            entry["dc"] = [c - p for c, p
                                           in zip(counts, prev[0])]
                        metrics[name] = entry
                    self._last[name] = (counts, total, n)
            self._seq += 1
            self.pulls += 1
            return {"cursor": f"{self._nonce}:{self._seq}", "full": full,
                    "metrics": metrics, "truncated": truncated,
                    "ts": time.time()}


class _ReplicaSeries:
    """One replica's federated state: latest absolute values plus the
    bounded per-pull delta rings (the PR 9 sampler shape)."""

    __slots__ = ("cursor", "latest", "rings", "buckets", "stale",
                 "last_pull", "last_error", "pulls", "failures",
                 "truncated")

    def __init__(self):
        self.cursor: str | None = None
        self.latest: dict[str, dict] = {}
        self.rings: dict[str, object] = {}
        self.buckets: dict[str, tuple] = {}
        self.stale = True          # until the first successful pull
        self.last_pull: float | None = None
        self.last_error: str | None = None
        self.pulls = 0
        self.failures = 0
        self.truncated = 0


class MetricsFederation:
    """Full-node puller + merger over the fleet router's replicas."""

    def __init__(self, router, *, interval: float | None = None,
                 window: int = DEFAULT_WINDOW,
                 registry: MetricsRegistry | None = None):
        import os
        from collections import deque

        self._deque = deque
        self.router = router
        env_iv = os.environ.get("RETH_TPU_FLEET_METRICS_INTERVAL", "")
        self.interval = float(interval if interval is not None
                              else env_iv or DEFAULT_INTERVAL_S)
        self.window = max(2, int(window))
        self._series: dict[str, _ReplicaSeries] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.pulls = 0
        self.failures = 0
        reg = registry or REGISTRY
        self._m_pulls = reg.counter(
            "fleetobs_pulls_total", "replica metrics pulls attempted")
        self._m_failures = reg.counter(
            "fleetobs_pull_failures_total",
            "replica metrics pulls that failed (replica marked stale)")
        self._m_stale = reg.gauge(
            "fleetobs_stale_replicas",
            "replicas whose federated metrics are stale (pull failing)")
        self._m_series = reg.gauge(
            "fleetobs_federated_series", "federated metric series held")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Background puller (no-op when interval<=0: tests drive
        :meth:`pull_once` directly)."""
        if self.interval <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-federation")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.pull_once()
            except Exception:  # noqa: BLE001 — federation must never die
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- pulling ------------------------------------------------------------

    def pull_once(self, now: float | None = None) -> None:
        """One pull pass over every registered replica (including shed
        ones — a draining replica's metrics are exactly what the
        operator is staring at). Failures mark the replica stale and
        move on; the feed and gateway never feel this."""
        with self.router._lock:
            handles = [(h.id, h.url) for h in self.router.replicas.values()]
        known = {rid for rid, _ in handles}
        now = time.time() if now is None else now
        for rid, url in handles:
            with self._lock:
                series = self._series.get(rid)
                if series is None:
                    series = self._series[rid] = _ReplicaSeries()
            self.pulls += 1
            self._m_pulls.increment()
            try:
                resp = self.router._rpc(url, "fleet_metricsSnapshot",
                                        [series.cursor])
                if not isinstance(resp, dict) or "metrics" not in resp:
                    raise ValueError("malformed federation snapshot")
            except Exception as e:  # noqa: BLE001 — stale-mark, never raise
                self.failures += 1
                self._m_failures.increment()
                with self._lock:
                    was_stale = series.stale
                    series.failures += 1
                    series.stale = True
                    series.last_error = f"{type(e).__name__}: {e}"
                if not was_stale:
                    tracing.event("fleet::federation", "replica_stale",
                                  id=rid, error=series.last_error)
                continue
            with self._lock:
                self._apply(series, resp, now)
        with self._lock:
            # deregistered replicas fall out of the federated view
            for rid in [r for r in self._series if r not in known]:
                del self._series[rid]
            self._publish_locked()

    def _apply(self, series: _ReplicaSeries, resp: dict, now: float) -> None:
        # caller holds the lock
        if resp.get("full"):
            # re-anchor: a replica restart (new cursor nonce) means its
            # counters reset — drop the old rings so deltas stay honest
            series.latest.clear()
            series.rings.clear()
        series.cursor = resp.get("cursor")
        series.stale = False
        series.last_pull = now
        series.last_error = None
        series.pulls += 1
        series.truncated = int(resp.get("truncated") or 0)
        for name, entry in resp.get("metrics", {}).items():
            kind = entry.get("k")
            ring = series.rings.get(name)
            if ring is None:
                ring = series.rings[name] = self._deque(maxlen=self.window)
            if kind == "c":
                v = float(entry.get("v", 0.0))
                prev = series.latest.get(name, {}).get("v")
                delta = entry.get("d")
                if delta is None:
                    if isinstance(prev, (int, float)):
                        delta = v - prev if v >= prev else v
                    else:
                        # first sight is a BASELINE (sampler convention):
                        # the lifetime value predates the window
                        delta = 0.0
                ring.append((now, v, float(delta)))
                series.latest[name] = {"k": "c", "v": v}
            elif kind == "g":
                v = float(entry.get("v", 0.0))
                ring.append((now, v))
                series.latest[name] = {"k": "g", "v": v}
            elif kind == "h":
                counts = list(entry.get("c", ()))
                total = float(entry.get("s", 0.0))
                n = int(entry.get("n", 0))
                if entry.get("b") is not None:
                    series.buckets[name] = tuple(entry["b"])
                prev = series.latest.get(name)
                if "dc" in entry:
                    deltas = (entry["dn"], entry["ds"], tuple(entry["dc"]))
                elif prev is not None and n >= prev["n"]:
                    deltas = (n - prev["n"], total - prev["s"],
                              tuple(c - p for c, p
                                    in zip(counts, prev["c"])))
                else:
                    # first sight is a BASELINE (the sampler convention):
                    # lifetime counts predate the window
                    deltas = (0, 0.0, tuple(0 for _ in counts))
                ring.append((now,) + deltas)
                series.latest[name] = {"k": "h", "c": counts, "s": total,
                                       "n": n}

    def _publish_locked(self) -> None:
        self._m_stale.set(sum(1 for s in self._series.values() if s.stale))
        self._m_series.set(sum(len(s.latest)
                               for s in self._series.values()))

    # -- queries ------------------------------------------------------------

    def replica_latest(self, rid: str, name: str) -> dict | None:
        with self._lock:
            s = self._series.get(rid)
            return dict(s.latest[name]) if s and name in s.latest else None

    def replica_quantile(self, rid: str, name: str,
                         q: float) -> float | None:
        """One replica's lifetime quantile from its latest federated
        histogram (bench's per-replica p99 breakdown)."""
        with self._lock:
            s = self._series.get(rid)
            if s is None:
                return None
            e = s.latest.get(name)
            b = s.buckets.get(name)
        if e is None or b is None or e.get("k") != "h" or not e["n"]:
            return None
        return histogram_quantile(b, e["c"], q)

    def replica_gauge_max(self, name: str) -> float | None:
        """Max of one gauge across replicas (e.g. the worst
        ``replica_feed_lag_heads`` as the replicas themselves report
        it). None when no replica exposes it."""
        vals = []
        with self._lock:
            for s in self._series.values():
                e = s.latest.get(name)
                if e is not None and e.get("k") in ("g", "c"):
                    vals.append(float(e["v"]))
        return max(vals) if vals else None

    def fleet_counts(self, name: str) -> tuple | None:
        """Bucket-wise merge of one histogram family across every
        replica's LATEST absolute counts -> (buckets, counts, sum, n).
        The merged counts are the element-wise sums, so a quantile over
        them is the quantile of the combined population."""
        with self._lock:
            buckets = None
            merged = None
            total = 0.0
            n = 0
            for s in self._series.values():
                e = s.latest.get(name)
                if e is None or e.get("k") != "h":
                    continue
                b = s.buckets.get(name)
                if b is None:
                    continue
                if buckets is None:
                    buckets = b
                    merged = [0] * len(e["c"])
                if b != buckets or len(e["c"]) != len(merged):
                    continue  # incompatible bucket layout: skip, never lie
                merged = [m + c for m, c in zip(merged, e["c"])]
                total += e["s"]
                n += e["n"]
        if buckets is None:
            return None
        return buckets, merged, total, n

    def fleet_quantile(self, name: str, q: float,
                       samples: int | None = None) -> float | None:
        """Fleet-wide quantile of one histogram family. ``samples``
        windows it over the last N pull intervals' merged bucket deltas
        (a real windowed p99, the health-rule input); None uses the
        merged lifetime counts."""
        if samples is None:
            merged = self.fleet_counts(name)
            if merged is None or merged[3] == 0:
                return None
            return histogram_quantile(merged[0], merged[1], q)
        with self._lock:
            buckets = None
            window: list | None = None
            for s in self._series.values():
                b = s.buckets.get(name)
                ring = s.rings.get(name)
                if b is None or ring is None:
                    continue
                if buckets is None:
                    buckets = b
                    window = [0] * (len(b) + 1)
                if b != buckets:
                    continue
                for p in list(ring)[-samples:]:
                    for i, d in enumerate(p[3]):
                        if i < len(window):
                            window[i] += d
        if buckets is None or window is None or sum(window) <= 0:
            return None
        return histogram_quantile(buckets, window, q)

    # -- surfaces -----------------------------------------------------------

    def render(self) -> str:
        """The ``scope=fleet`` exposition appendix: every federated
        series re-labeled ``{replica="<id>"}`` plus the bucket-wise
        ``{replica="_fleet"}`` merge for histograms, and a staleness
        marker gauge per replica. One lock snapshot feeds both the
        per-replica lines AND the merge, so a scrape is internally
        bucket-exact even while the puller runs. Series names that
        already carry labels get the replica label spliced in."""
        with self._lock:
            snap = [(rid, dict(s.latest), dict(s.buckets), s.stale)
                    for rid, s in sorted(self._series.items())]
        lines: list[str] = []
        # family -> [buckets, merged_counts, sum, n]
        hist: dict[str, list] = {}
        for rid, latest, buckets, stale in snap:
            lines.append(
                f'fleetobs_replica_stale{{replica="{rid}"}} '
                f'{1 if stale else 0}')
            for name, e in sorted(latest.items()):
                if e["k"] in ("c", "g"):
                    lines.append(f"{self._label(name, rid)} {e['v']}")
                    continue
                b = buckets.get(name)
                if b is None:
                    continue
                cum = 0
                for edge, c in zip(b, e["c"]):
                    cum += c
                    lines.append(
                        f'{self._label(name + "_bucket", rid, le=edge)}'
                        f' {cum}')
                lines.append(
                    f'{self._label(name + "_bucket", rid, le="+Inf")}'
                    f' {e["n"]}')
                lines.append(f'{self._label(name + "_sum", rid)}'
                             f' {e["s"]}')
                lines.append(f'{self._label(name + "_count", rid)}'
                             f' {e["n"]}')
                m = hist.get(name)
                if m is None:
                    hist[name] = [b, list(e["c"]), e["s"], e["n"]]
                elif m[0] == b and len(m[1]) == len(e["c"]):
                    m[1] = [x + y for x, y in zip(m[1], e["c"])]
                    m[2] += e["s"]
                    m[3] += e["n"]
        # the fleet merge: bucket-exact sums across replicas
        for name in sorted(hist):
            b, counts, total, n = hist[name]
            cum = 0
            for edge, c in zip(b, counts):
                cum += c
                lines.append(
                    f'{self._label(name + "_bucket", FLEET_LABEL, le=edge)}'
                    f' {cum}')
            lines.append(
                f'{self._label(name + "_bucket", FLEET_LABEL, le="+Inf")}'
                f' {n}')
            lines.append(f'{self._label(name + "_sum", FLEET_LABEL)}'
                         f' {total}')
            lines.append(f'{self._label(name + "_count", FLEET_LABEL)}'
                         f' {n}')
        return "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _label(name: str, rid: str, le=None) -> str:
        extra = f'replica="{rid}"' + (f',le="{le}"' if le is not None
                                      else "")
        if name.endswith("}"):  # already-labeled series: splice
            return name[:-1] + "," + extra + "}"
        return name + "{" + extra + "}"

    def snapshot(self) -> dict:
        """The ``fleetobs[...]`` events-fragment state."""
        with self._lock:
            stale = sum(1 for s in self._series.values() if s.stale)
            ages = [time.time() - s.last_pull
                    for s in self._series.values()
                    if s.last_pull is not None]
            return {
                "replicas": len(self._series),
                "stale": stale,
                "pulls": self.pulls,
                "failures": self.failures,
                "series": sum(len(s.latest)
                              for s in self._series.values()),
                "max_pull_age_s": (round(max(ages), 2) if ages else None),
            }

    def summary(self) -> dict:
        """The ``debug_fleetMetrics`` body: per-replica pull state plus
        the fleet-wide quantiles an operator actually asks for."""
        now = time.time()
        with self._lock:
            replicas = {
                rid: {
                    "stale": s.stale,
                    "pulls": s.pulls,
                    "failures": s.failures,
                    "last_pull_age_s": (round(now - s.last_pull, 2)
                                        if s.last_pull is not None
                                        else None),
                    "last_error": s.last_error,
                    "series": len(s.latest),
                    "truncated": s.truncated,
                }
                for rid, s in sorted(self._series.items())
            }
            hist_names = sorted({n for s in self._series.values()
                                 for n, e in s.latest.items()
                                 if e.get("k") == "h"})
        quantiles = {}
        for name in hist_names:
            p99 = self.fleet_quantile(name, 0.99)
            if p99 is not None:
                merged = self.fleet_counts(name)
                quantiles[name] = {
                    "p50": round(self.fleet_quantile(name, 0.5) or 0, 6),
                    "p99": round(p99, 6),
                    "count": merged[3] if merged else 0,
                }
        return {
            "interval_s": self.interval,
            "window": self.window,
            **self.snapshot(),
            "per_replica": replicas,
            "fleet_quantiles": quantiles,
        }


# -- process-default federation (the /metrics?scope=fleet seam) ---------------

_FEDERATION: MetricsFederation | None = None


def install(federation: MetricsFederation) -> None:
    """Make ``federation`` the process default served by
    ``/metrics?scope=fleet``, ``debug_fleetMetrics``, and the fleet SLO
    rules (node/node.py; last installed wins, like health.install)."""
    global _FEDERATION
    _FEDERATION = federation


def uninstall(federation: MetricsFederation | None = None) -> None:
    global _FEDERATION
    if federation is None or _FEDERATION is federation:
        _FEDERATION = None


def get_federation() -> MetricsFederation | None:
    return _FEDERATION


__all__ = [
    "FederationSource",
    "MetricsFederation",
    "snapshot_registry",
    "install",
    "uninstall",
    "get_federation",
]
