"""Fleet-wide observability plane (PR 14): the cross-process layer over
``tracing.py`` (trace propagation), ``metrics.py`` (federation), and the
flight recorder (correlated dumps).

- :mod:`.federation` — the full node pulls every registered replica's
  metrics registry over the fleet admin channel, merges histograms
  bucket-wise into a per-replica-labeled federated view, and exposes
  fleet-wide windowed quantiles (``/metrics?scope=fleet``,
  ``debug_fleetMetrics``, the ``fleetobs[...]`` events fragment, and the
  fleet SLO rules in ``health.py``).
"""
