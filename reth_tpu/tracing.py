"""Tracing/logging: layered init with per-target filters, span timing,
block-lifecycle trace propagation, a bounded flight recorder, and
Chrome-trace/OTLP span export.

Reference analogue: crates/tracing — stdout/file layers with per-layer
env filters (src/lib.rs:1-35) and the `target:` discipline (e.g.
``trie::state_root``). Built on stdlib logging; `span()` provides the
timing-span idiom used across the reference's hot paths.

Block-lifecycle layer (this repo's observability tentpole):

- **Trace context** (:class:`TraceContext`): ``trace_id`` (the block hash
  for block lifecycles) + a process-unique span id. The context lives in
  thread-local state inside ``span()`` blocks and is carried EXPLICITLY
  across queue/pool handoffs: a producer captures
  :func:`current_context`, the consumer adopts it with
  :func:`use_context` (worker threads) or attributes completed work with
  :func:`record_span` (batch dispatchers that serve many contexts at
  once, e.g. the hash service).
- **Per-block timelines**: every span/event recorded under a trace id
  lands in a bounded per-trace timeline (:func:`block_timeline`), and
  closing a :func:`trace_block` root computes the wall-budget summary
  (:func:`block_summary` / :func:`last_block_summary`) the events
  dashboard prints: ``block N total=Xms = prewarm a + exec b + root c
  (wait d, dispatch e, encode f)``.
- **Flight recorder** (:class:`FlightRecorder`): a bounded in-memory
  ring of recent spans, events, breaker/fault transitions. Snapshots to
  JSONL on circuit-breaker open, watchdog timeout, any
  ``RETH_TPU_FAULT_*`` drill firing (:func:`fault_event`), or on demand
  (:func:`flight_dump` / the ``debug_flightRecorder`` RPC) — the wedge
  postmortem the BENCH_r01–r05 zeros never had.
- **Exporters**: the OTLP/JSON file exporter (below) now carries
  trace/span/parent ids; :class:`ChromeTraceExporter` writes the same
  spans as Chrome trace-event JSON that Perfetto / chrome://tracing load
  directly (``--trace-blocks``).

Enablement: span *recording* is off unless ``RETH_TPU_TRACE`` is set
truthy or :func:`set_trace_enabled` ran (the ``--trace-blocks`` path);
when off, ``span()`` costs what it always did (one DEBUG log call).
Events (:func:`event` / :func:`fault_event`) record into the flight
recorder regardless — breaker trips and fault drills are rare and are
exactly what a postmortem needs.

Fleet layer (the cross-PROCESS half of the same machinery):

- **Wire form** (:func:`context_to_wire` / :func:`context_from_wire`):
  a compact dict ``{"t": trace_id, "s": span_id, "r": role, "p": pid}``
  carried on witness-feed frames and as a ``traceparent`` member of
  fleet-routed JSON-RPC requests. Span ids embed the originating pid in
  their high bits (:func:`span_id_pid_bits`), so ids stay globally
  unique across a fleet and a remote ``parent`` id resolves when traces
  from several processes are merged.
- **Process role** (:func:`set_process_role`): ``full`` / ``replica`` /
  ``node`` — stamped as a resource attribute on every exported span and
  as Chrome ``process_name`` metadata, so merged multi-process traces
  stay attributable.
- **Correlated dumps**: :func:`fault_event` stamps every dump with a
  :func:`new_correlation_id` + time window and notifies registered
  fault observers (:func:`add_fault_observer`) — the fleet coordinators
  (feed server / replica) fan the dump request to their peers, every
  process dumps under the SAME correlation id, and
  :func:`merge_correlated` returns the time-aligned multi-process view
  (``debug_flightRecorder`` ``action="correlated"``).
- **Stitching** (:func:`stitch_chrome_traces`): merge exported Chrome
  traces from several processes and report distinct pids + any
  unresolved cross-process parent ids — the bench/chaos acceptance
  check that one user read really is ONE trace.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import sys
import tempfile
import threading
import time
from collections import OrderedDict, deque
from pathlib import Path


def init_tracing(
    stdout_level: str | None = None,
    file_path: str | Path | None = None,
    file_level: str = "DEBUG",
    filters: str | None = None,
) -> None:
    """Install stdout (+ optional file) handlers.

    ``filters``: comma-separated ``target=LEVEL`` pairs (the RUST_LOG
    analogue), e.g. ``"reth_tpu.trie=DEBUG,reth_tpu.engine=INFO"``; also
    read from the RETH_TPU_LOG env var.
    """
    root = logging.getLogger("reth_tpu")
    root.setLevel(logging.DEBUG)
    root.handlers.clear()
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S"
    )
    out = logging.StreamHandler(sys.stdout)
    out.setLevel((stdout_level or "INFO").upper())
    out.setFormatter(fmt)
    root.addHandler(out)
    if file_path:
        fh = logging.FileHandler(file_path)
        fh.setLevel(file_level.upper())
        fh.setFormatter(fmt)
        root.addHandler(fh)
    spec = filters if filters is not None else os.environ.get("RETH_TPU_LOG", "")
    for pair in filter(None, spec.split(",")):
        target, _, level = pair.partition("=")
        logging.getLogger(target.strip()).setLevel((level or "DEBUG").upper())


def tracer(target: str) -> logging.Logger:
    """Logger for a target (``trie.state_root`` style)."""
    return logging.getLogger(f"reth_tpu.{target}")


# -- trace context ------------------------------------------------------------

_FALSY = ("", "0", "false", "off", "no")


def _env_enabled() -> bool:
    return os.environ.get("RETH_TPU_TRACE", "").lower() not in _FALSY


_TRACE_ON = _env_enabled()
_tls = threading.local()
_span_ids = itertools.count(1)

# span ids are globally unique across a FLEET: the low 40 bits count,
# the high bits carry this process's pid — a remote parent id exported
# from another process can never collide with a local span id, so
# cross-process parent references resolve in merged Chrome/OTLP traces
_SPAN_PID_SHIFT = 40
_SPAN_PID_BITS = os.getpid() & 0x3FFFFF


def _new_span_id() -> int:
    return (_SPAN_PID_BITS << _SPAN_PID_SHIFT) | next(_span_ids)


def span_id_pid_bits(span_id: int) -> int:
    """The pid bits embedded in a span id (which process minted it) —
    how stitch checks tell a cross-process parent from a local one."""
    return span_id >> _SPAN_PID_SHIFT


# process role for multi-process attribution (full | replica | node):
# rides the wire form, OTLP resource attributes, and Chrome process
# metadata so merged fleet traces stay tellable-apart after export
_ROLE = os.environ.get("RETH_TPU_ROLE", "") or "node"


def set_process_role(role: str) -> None:
    global _ROLE
    _ROLE = role


def process_role() -> str:
    return _ROLE


class TraceContext:
    """A propagated trace position: ``trace_id`` (block hash hex for
    block lifecycles) + the current span id (None at the trace root)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str | None, span_id: int | None = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id!r}, span={self.span_id})"


def context_to_wire(ctx: TraceContext | None = None) -> dict | None:
    """Compact wire form of a trace position for cross-process handoffs
    (witness-feed frames, fleet-routed JSON-RPC ``traceparent``):
    ``{"t": trace_id, "s": span_id, "r": role, "p": pid}``. ``ctx``
    defaults to the calling thread's current context; None (no trace)
    encodes to None so untraced traffic carries zero extra bytes. A
    span-only context (a routed READ has no block trace id) still
    encodes — the remote spans stitch by parent span id even when no
    named trace exists."""
    if ctx is None:
        ctx = current_context()
    if ctx is None or (ctx.trace_id is None and ctx.span_id is None):
        return None
    return {"t": ctx.trace_id, "s": ctx.span_id, "r": _ROLE,
            "p": os.getpid()}


def context_from_wire(wire) -> TraceContext | None:
    """Decode a wire-form dict back into an adoptable context (the
    consumer half: ``use_context(context_from_wire(frame["tp"]))``).
    Tolerates None/garbage — a malformed traceparent must never fail
    the request it rode in on."""
    if not isinstance(wire, dict):
        return None
    trace = wire.get("t")
    if trace is not None and not (isinstance(trace, str) and trace):
        return None
    span = wire.get("s")
    if span is not None and not isinstance(span, int):
        return None
    if trace is None and span is None:
        return None
    return TraceContext(trace, span)


def set_trace_enabled(on: bool) -> None:
    """Master switch for span recording (``--trace-blocks`` /
    ``RETH_TPU_TRACE``). Off = ``span()`` reverts to its log-only cost."""
    global _TRACE_ON
    _TRACE_ON = bool(on)


def trace_enabled() -> bool:
    return _TRACE_ON


def current_context() -> TraceContext | None:
    """The calling thread's trace position (None outside any span, or
    with tracing disabled). Capture this BEFORE handing work to a queue
    or pool; the consumer adopts it with :func:`use_context`."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use_context(ctx: TraceContext | None):
    """Adopt a propagated context in a worker thread for the duration of
    the block — the consumer half of every queue/pool handoff."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


@contextlib.contextmanager
def span(target: str, name: str, level: int = logging.DEBUG, **fields):
    """Timed span: logs entry fields + exit duration (tracing-span idiom).

    With tracing enabled the span joins the current thread's trace
    (parent/child ids), records into the flight recorder + per-trace
    timeline, and exports to the installed OTLP/Chrome exporters."""
    log = tracer(target)
    t0 = time.time()
    parent = None
    ctx = None
    if _TRACE_ON:
        parent = getattr(_tls, "ctx", None)
        ctx = TraceContext(parent.trace_id if parent is not None else None,
                           _new_span_id())
        _tls.ctx = ctx
    err = None
    try:
        yield ctx
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        dt = time.time() - t0
        if ctx is not None:
            _tls.ctx = parent
        extra = " ".join(f"{k}={v}" for k, v in fields.items())
        log.log(level, "%s %s took %.3fms", name, extra, dt * 1e3)
        if _otlp is not None:
            _otlp.export(target, name, t0, dt, fields, err,
                         ctx=ctx, parent=parent)
        if ctx is not None:
            _record({
                "kind": "span", "target": target, "name": name,
                "ts": t0, "dur_ms": round(dt * 1e3, 3),
                "trace": ctx.trace_id, "span": ctx.span_id,
                "parent": parent.span_id if parent is not None else None,
                "thread": threading.current_thread().name,
                "fields": fields, "error": err,
            })


def record_span(target: str, name: str, start: float, duration: float, *,
                ctx: TraceContext | None = None, fields: dict | None = None,
                error: str | None = None) -> None:
    """Record an already-timed span under ``ctx`` — the attribution path
    for batch dispatchers that complete work for MANY contexts at once
    (hash-service requests, proof shards): the producer captured the
    context at submit time, the completion attributes the wall to it."""
    if not _TRACE_ON:
        return
    rec = {
        "kind": "span", "target": target, "name": name,
        "ts": start, "dur_ms": round(duration * 1e3, 3),
        "trace": ctx.trace_id if ctx is not None else None,
        "span": _new_span_id(),
        "parent": ctx.span_id if ctx is not None else None,
        "thread": threading.current_thread().name,
        "fields": fields or {}, "error": error,
    }
    _record(rec)


def event(target: str, name: str, **fields) -> None:
    """Instant event (breaker transition, probe outcome, fault firing).
    Always lands in the flight recorder — these are the rare records a
    postmortem is made of — and in the current trace's timeline when
    span recording is on."""
    ctx = getattr(_tls, "ctx", None) if _TRACE_ON else None
    _record({
        "kind": "event", "target": target, "name": name,
        "ts": time.time(), "dur_ms": 0.0,
        "trace": ctx.trace_id if ctx is not None else None,
        "span": None,
        "parent": ctx.span_id if ctx is not None else None,
        "thread": threading.current_thread().name,
        "fields": fields, "error": None,
    }, always=True)


# -- per-block timelines ------------------------------------------------------

_TL_LOCK = threading.Lock()
_TIMELINES: OrderedDict[str, list] = OrderedDict()
_SUMMARIES: OrderedDict[str, dict] = OrderedDict()
_MAX_TRACES = 64
_MAX_TIMELINE_RECORDS = 8192
_last_summary: dict | None = None


@contextlib.contextmanager
def trace_block(trace_id: str, name: str = "block",
                target: str = "engine::block", **fields):
    """Root span of one block lifecycle: ``trace_id`` (the block hash
    hex) seeds every child span on this thread and every explicitly
    propagated context; closing computes the wall-budget summary."""
    if not _TRACE_ON:
        yield None
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = TraceContext(trace_id, None)  # trace seed: root has no parent
    with _TL_LOCK:
        _TIMELINES.setdefault(trace_id, [])
        _TIMELINES.move_to_end(trace_id)
        while len(_TIMELINES) > _MAX_TRACES:
            dead, _ = _TIMELINES.popitem(last=False)
            _SUMMARIES.pop(dead, None)
    try:
        with span(target, name, **fields) as ctx:
            yield ctx
    finally:
        _tls.ctx = prev
        _finalize_block(trace_id)


def _record(rec: dict, always: bool = False) -> None:
    if _TRACE_ON or always:
        _RECORDER.record(rec)
    if _chrome is not None and (_TRACE_ON or always):
        _chrome.export(rec)
    trace = rec.get("trace")
    if trace is None:
        return
    with _TL_LOCK:
        tl = _TIMELINES.get(trace)
        if tl is not None and len(tl) < _MAX_TIMELINE_RECORDS:
            tl.append(rec)


def ensure_timeline(trace_id: str) -> None:
    """Pre-register a trace timeline so spans recorded BEFORE the block's
    root ``trace_block`` opens still land in it — cross-block speculation
    executes N+1 while N commits, ahead of N+1's own lifecycle."""
    if not _TRACE_ON:
        return
    with _TL_LOCK:
        _TIMELINES.setdefault(trace_id, [])
        _TIMELINES.move_to_end(trace_id)
        while len(_TIMELINES) > _MAX_TRACES:
            dead, _ = _TIMELINES.popitem(last=False)
            _SUMMARIES.pop(dead, None)


def block_timeline(trace_id: str) -> list[dict] | None:
    """All records of one trace (block), oldest first; None if unknown."""
    with _TL_LOCK:
        tl = _TIMELINES.get(trace_id)
        return list(tl) if tl is not None else None


def recent_traces() -> list[str]:
    """Known trace ids, oldest first."""
    with _TL_LOCK:
        return list(_TIMELINES)


def _sum_field(records, names, field) -> float:
    return sum(float(r["fields"].get(field, 0.0)) for r in records
               if r["name"] in names)


def _summarize(trace_id: str, records: list[dict]) -> dict | None:
    root = next((r for r in records
                 if r["kind"] == "span" and r["parent"] is None), None)
    if root is None:
        return None

    def dur_of(name: str) -> float:
        return sum(r["dur_ms"] for r in records
                   if r["kind"] == "span" and r["name"] == name)

    spans = [r for r in records if r["kind"] == "span"]
    # accounted wall: union of direct-child intervals over the root span
    children = sorted(((r["ts"], r["ts"] + r["dur_ms"] / 1e3)
                       for r in spans if r["parent"] == root["span"]))
    covered, cur_lo, cur_hi = 0.0, None, None
    for lo, hi in children:
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                covered += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        covered += cur_hi - cur_lo
    total_ms = root["dur_ms"]
    summary = {
        "trace": trace_id,
        "number": root["fields"].get("number"),
        # closing wall-clock time: the health engine's block-wall SLO rule
        # windows summaries by when the block finished
        "ts": root["ts"] + total_ms / 1e3,
        "total_ms": total_ms,
        "prewarm_ms": round(dur_of("prewarm"), 3),
        # an adopted speculation ran its execute leg as speculate.exec
        # inside the parent's commit window; count it as the exec wall
        "exec_ms": round(dur_of("execute") or dur_of("speculate.exec"), 3),
        "root_ms": round(dur_of("state_root"), 3),
        # hash-service attribution: queue-wait vs device dispatch (with no
        # service the direct hash.dispatch spans carry the dispatch wall)
        "wait_ms": round(_sum_field(records, ("hashsvc.request",), "wait_ms"), 3),
        "dispatch_ms": round(
            _sum_field(records, ("hashsvc.request",), "service_ms")
            if any(r["name"] == "hashsvc.request" for r in records)
            else dur_of("hash.dispatch"), 3),
        "encode_ms": round(dur_of("sparse.encode"), 3),
        "spans": len(spans),
        "coverage": round(covered * 1e3 / total_ms, 4) if total_ms else 1.0,
    }
    return summary


def _finalize_block(trace_id: str) -> None:
    global _last_summary
    records = block_timeline(trace_id)
    if not records:
        return
    summary = _summarize(trace_id, records)
    if summary is None:
        return
    with _TL_LOCK:
        _SUMMARIES[trace_id] = summary
        while len(_SUMMARIES) > _MAX_TRACES:
            _SUMMARIES.popitem(last=False)
    _last_summary = summary


def block_summary(trace_id: str) -> dict | None:
    """Wall-budget summary of one closed block trace."""
    with _TL_LOCK:
        s = _SUMMARIES.get(trace_id)
    if s is not None:
        return s
    records = block_timeline(trace_id)
    return _summarize(trace_id, records) if records else None


def last_block_summary() -> dict | None:
    """The most recently closed block's wall budget (events dashboard)."""
    return _last_summary


def recent_block_summaries(n: int | None = None) -> list[dict]:
    """Closed-block wall budgets, oldest first (bounded by the timeline
    ring) — the health engine's block-import SLO rule averages these over
    its evaluation window."""
    with _TL_LOCK:
        out = list(_SUMMARIES.values())
    return out[-n:] if n else out


def format_wall_budget(s: dict) -> str:
    """The one-line per-block budget operators read:
    ``block N total=Xms = prewarm a + exec b + root c (wait d, dispatch
    e, encode f)``."""
    return (f"block {s.get('number', '?')} total={s['total_ms']:.1f}ms = "
            f"prewarm {s['prewarm_ms']:.1f} + exec {s['exec_ms']:.1f} + "
            f"root {s['root_ms']:.1f} (wait {s['wait_ms']:.1f}, "
            f"dispatch {s['dispatch_ms']:.1f}, encode {s['encode_ms']:.1f})")


# -- flight recorder ----------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent spans/events/fault transitions, snapshotted
    to JSONL when something goes wrong (breaker open, watchdog timeout,
    a RETH_TPU_FAULT_* drill firing) or on demand."""

    def __init__(self, capacity: int = 4096, directory: str | Path | None = None):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self.directory = directory
        self.dumps: list[str] = []  # paths written, oldest first
        self.recorded = 0
        self.last_correlation_id: str | None = None

    def record(self, rec: dict) -> None:
        with self._lock:
            self._buf.append(rec)
            self.recorded += 1

    def snapshot(self, n: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._buf)
        return out[-n:] if n else out

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def _dir(self) -> Path:
        d = (self.directory or os.environ.get("RETH_TPU_FLIGHT_DIR")
             or Path(tempfile.gettempdir()) / "reth_tpu_flight")
        d = Path(d)
        d.mkdir(parents=True, exist_ok=True)
        return d

    def dump(self, reason: str, path: str | Path | None = None, *,
             correlation_id: str | None = None,
             window: tuple | list | None = None) -> str | None:
        """Write the ring (oldest first) as JSONL: one header line
        ``{"kind": "flight_snapshot", "reason", "ts", "records", "pid",
        "role", "correlation_id", "window"}`` then one line per record.
        ``correlation_id`` ties this dump to the fleet-wide set written
        for one incident; ``window`` (``[t0, t1]`` wall-clock seconds)
        filters the ring to the incident's period so a peer's dump is
        time-aligned with the initiator's. Returns the path, or None on
        an empty ring. Never raises — a diagnostics failure must not
        fail the caller."""
        try:
            records = self.snapshot()
            if window:
                t0, t1 = float(window[0]), float(window[1])
                records = [r for r in records
                           if t0 - 1.0 <= r.get("ts", 0.0) <= t1 + 1.0]
            if not records:
                return None
            if path is None:
                safe = "".join(c if c.isalnum() or c in "-_" else "_"
                               for c in reason)[:60]
                path = self._dir() / (
                    f"flight-{safe}-{int(time.time() * 1e3)}-"
                    f"{os.getpid()}.jsonl")
            path = Path(path)
            with open(path, "w") as f:
                f.write(json.dumps({
                    "kind": "flight_snapshot", "reason": reason,
                    "ts": time.time(), "records": len(records),
                    "pid": os.getpid(), "role": _ROLE,
                    "correlation_id": correlation_id,
                    "window": list(window) if window else None}) + "\n")
                for rec in records:
                    f.write(json.dumps(rec, default=str) + "\n")
            self.dumps.append(str(path))
            if correlation_id:
                self.last_correlation_id = correlation_id
            return str(path)
        except Exception:  # noqa: BLE001 — diagnostics only
            return None


_RECORDER = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _RECORDER


def flight_snapshot(n: int | None = None) -> list[dict]:
    return _RECORDER.snapshot(n)


def flight_dump(reason: str, path: str | Path | None = None, *,
                correlation_id: str | None = None,
                window: tuple | list | None = None) -> str | None:
    """Snapshot the flight recorder to JSONL now (see the triggers in the
    module docstring)."""
    return _RECORDER.dump(reason, path, correlation_id=correlation_id,
                          window=window)


def load_flight_dump(path: str | Path) -> tuple[dict, list[dict]]:
    """Parse a flight-recorder JSONL dump -> (header, records). Torn
    trailing lines (a killed process mid-write) are discarded."""
    lines = Path(path).read_text().splitlines()
    header = json.loads(lines[0])
    records = []
    for line in lines[1:]:
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:  # torn tail: the process died here
            break
    return header, records


# -- correlated dumps ---------------------------------------------------------

_corr_counter = itertools.count(1)
# the incident window a correlated dump covers: the initiator stamps
# [now - CORRELATION_WINDOW_S, now + slack] so every peer's dump is
# filtered to the same period
CORRELATION_WINDOW_S = 30.0


def new_correlation_id() -> str:
    """Fleet-unique incident id stamped on every dump of one correlated
    set: wall-ms + pid + a per-process counter."""
    return (f"{int(time.time() * 1e3):x}-{os.getpid():x}-"
            f"{next(_corr_counter):x}")


def correlated_dumps(correlation_id: str,
                     directory: str | Path | None = None) -> list[tuple]:
    """Every flight dump under ``directory`` (default: this process's
    flight dir, which a fleet shares via RETH_TPU_FLIGHT_DIR) whose
    header carries ``correlation_id`` -> [(header, records), ...]."""
    d = Path(directory) if directory is not None else _RECORDER._dir()
    out = []
    for path in sorted(d.glob("flight-*.jsonl")):
        try:
            header, records = load_flight_dump(path)
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        if header.get("correlation_id") == correlation_id:
            header = dict(header, path=str(path))
            out.append((header, records))
    return out


def merge_correlated(correlation_id: str | None = None,
                     directory: str | Path | None = None) -> dict:
    """The merged multi-process view of one correlated incident: every
    dump sharing the correlation id, records annotated with their
    originating pid/role and time-ordered — what ``debug_flightRecorder``
    ``action="correlated"`` returns. ``correlation_id`` defaults to the
    most recent one this process stamped."""
    cid = correlation_id or _RECORDER.last_correlation_id
    if cid is None:
        return {"correlation_id": None, "dumps": [], "pids": [],
                "records": []}
    dumps = correlated_dumps(cid, directory)
    records = []
    for header, recs in dumps:
        pid, role = header.get("pid"), header.get("role")
        for r in recs:
            records.append(dict(r, pid=pid, role=role))
    records.sort(key=lambda r: r.get("ts", 0.0))
    return {
        "correlation_id": cid,
        "dumps": [h["path"] for h, _ in dumps],
        "pids": sorted({h.get("pid") for h, _ in dumps
                        if h.get("pid") is not None}),
        "roles": sorted({str(h.get("role")) for h, _ in dumps}),
        "records": records,
    }


# fault observers: the fleet coordinators hang here — the feed server
# (full node) fans a dump request to every replica, a replica notifies
# the full node upstream over its feed socket. Called AFTER the local
# dump with (reason, correlation_id, window); observers must never
# raise into the faulting path.
_observer_lock = threading.Lock()
_fault_observers: list = []


def add_fault_observer(fn) -> None:
    with _observer_lock:
        if fn not in _fault_observers:
            _fault_observers.append(fn)


def remove_fault_observer(fn) -> None:
    with _observer_lock:
        if fn in _fault_observers:
            _fault_observers.remove(fn)


_fault_lock = threading.Lock()
_fault_last_dump: dict[str, float] = {}
FAULT_DUMP_INTERVAL_S = 5.0


def reset_fault_dump_limits() -> None:
    """Forget per-drill dump rate limits (tests / operator reset)."""
    with _fault_lock:
        _fault_last_dump.clear()


def fault_event(drill: str, target: str = "fault", **fields) -> str | None:
    """A RETH_TPU_FAULT_* drill (or real failure trigger) fired: record
    the event and snapshot the flight recorder, rate-limited per drill
    name so wedge-every-dispatch drills don't spray the disk. The dump
    is stamped with a fresh correlation id + incident window and every
    registered fault observer is notified so fleet peers dump under the
    SAME id. Returns the dump path when one was written."""
    event(target, drill, **fields)
    now = time.monotonic()
    with _fault_lock:
        last = _fault_last_dump.get(drill, 0.0)
        if now - last < FAULT_DUMP_INTERVAL_S:
            return None
        _fault_last_dump[drill] = now
    cid = new_correlation_id()
    wall = time.time()
    window = (wall - CORRELATION_WINDOW_S, wall + 5.0)
    path = flight_dump(drill, correlation_id=cid, window=window)
    with _observer_lock:
        observers = list(_fault_observers)
    for obs in observers:
        try:
            obs(drill, cid, window)
        except Exception:  # noqa: BLE001 — diagnostics only
            pass
    return path


# -- OTLP export (reference crates/tracing-otlp) ------------------------------
# The reference ships spans to an OTLP collector endpoint; this environment
# has no egress, so the exporter writes the SAME span model (resource +
# scope + span with name/attributes/start/end/status) as OTLP/JSON lines to
# a file a collector can tail — the transport is the only difference.

_otlp = None


def process_resource_attributes(replica_id: str | None = None) -> dict:
    """Resource attributes identifying THIS process in a merged fleet
    trace: role, pid, and the node's build identity
    (``reth_tpu_build_info`` fields) — stamped on every exported span so
    multi-process traces stay distinguishable after export."""
    attrs = {"service.role": _ROLE, "process.pid": os.getpid()}
    if replica_id:
        attrs["service.replica_id"] = replica_id
    try:
        from .metrics import build_info

        for k, v in build_info().items():
            attrs[f"build.{k}"] = v
    except Exception:  # noqa: BLE001 — identity is best-effort
        pass
    return attrs


class OtlpFileExporter:
    def __init__(self, path: str | Path, service_name: str = "reth-tpu"):
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)
        self.service_name = service_name
        self.exported = 0
        self._resource: list | None = None  # built lazily: role may be
        # set after init but before the first span exports

    def _resource_attrs(self) -> list:
        if self._resource is None:
            attrs = {"service.name": self.service_name}
            attrs.update(process_resource_attributes())
            self._resource = [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in attrs.items()
            ]
        return self._resource

    def export(self, target: str, name: str, start: float, duration: float,
               fields: dict, error: str | None,
               ctx: TraceContext | None = None,
               parent: TraceContext | None = None) -> None:
        sp = {
            "name": name,
            "startTimeUnixNano": str(int(start * 1e9)),
            "endTimeUnixNano": str(int((start + duration) * 1e9)),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in fields.items()
            ],
            "status": ({"code": 2, "message": error} if error
                       else {"code": 1}),
        }
        if ctx is not None:
            if ctx.trace_id is not None:
                sp["traceId"] = str(ctx.trace_id)
            sp["spanId"] = format(ctx.span_id or 0, "016x")
            if parent is not None and parent.span_id is not None:
                sp["parentSpanId"] = format(parent.span_id, "016x")
        span_rec = {
            "resource": {"attributes": self._resource_attrs()},
            "scopeSpans": [{
                "scope": {"name": f"reth_tpu.{target}"},
                "spans": [sp],
            }],
        }
        with self._lock:
            self._f.write(json.dumps(span_rec) + "\n")
            self.exported += 1

    def close(self) -> None:
        with self._lock:
            self._f.close()


def init_otlp(path: str | Path, service_name: str = "reth-tpu") -> OtlpFileExporter:
    """Install the OTLP/JSON file exporter for every span()."""
    global _otlp
    _otlp = OtlpFileExporter(path, service_name)
    return _otlp


def shutdown_otlp() -> None:
    global _otlp
    if _otlp is not None:
        _otlp.close()
        _otlp = None


# -- Chrome trace-event export ------------------------------------------------
# The format chrome://tracing and Perfetto's JSON importer load directly:
# one "X" (complete) event per span, instant events as "i". Written one
# event per line so the file doubles as JSON-lines for tooling; close()
# terminates it into a fully valid JSON array.

_chrome = None


class ChromeTraceExporter:
    """Spans/events as Chrome trace-event JSON (``--trace-blocks``)."""

    def __init__(self, path: str | Path):
        self._lock = threading.Lock()
        self.path = str(path)
        self._f = open(path, "w", buffering=1)
        self._f.write("[\n")
        self._tids: dict[str, int] = {}
        self.exported = 0
        self._named = False  # process metadata emitted?

    def _tid(self, thread_name: str) -> int:
        # caller holds the lock. Distinct pid/tid metadata events per
        # process so MERGED multi-process traces show named, separate
        # process/thread tracks instead of anonymous numeric ids.
        if not self._named:
            self._named = True
            self._f.write(json.dumps(
                {"name": "process_name", "ph": "M", "pid": os.getpid(),
                 "tid": 0, "args": {"name": f"{_ROLE}-{os.getpid()}"}})
                + ",\n")
        tid = self._tids.get(thread_name)
        if tid is None:
            tid = self._tids[thread_name] = len(self._tids) + 1
            self._f.write(json.dumps(
                {"name": "thread_name", "ph": "M", "pid": os.getpid(),
                 "tid": tid, "args": {"name": thread_name}}) + ",\n")
        return tid

    def export(self, rec: dict) -> None:
        args = {k: str(v) for k, v in rec.get("fields", {}).items()}
        if rec.get("trace"):
            args["trace_id"] = rec["trace"]
        if rec.get("span") is not None:
            args["span_id"] = rec["span"]
        if rec.get("parent") is not None:
            args["parent_id"] = rec["parent"]
        if rec.get("error"):
            args["error"] = rec["error"]
        ev = {
            "name": rec["name"],
            "cat": rec["target"],
            "ph": "X" if rec["kind"] == "span" else "i",
            "ts": round(rec["ts"] * 1e6, 1),
            "pid": os.getpid(),
            "args": args,
        }
        if rec["kind"] == "span":
            ev["dur"] = round(rec["dur_ms"] * 1e3, 1)
        else:
            ev["s"] = "p"  # process-scoped instant
        with self._lock:
            ev["tid"] = self._tid(rec.get("thread", "main"))
            self._f.write(json.dumps(ev) + ",\n")
            self.exported += 1

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                # terminate the array so the file is strictly valid JSON
                self._f.write(json.dumps(
                    {"name": "trace_end", "ph": "i", "ts": time.time() * 1e6,
                     "pid": os.getpid(), "tid": 0, "s": "g", "args": {}})
                    + "\n]\n")
                self._f.close()


def init_chrome_trace(path: str | Path) -> ChromeTraceExporter:
    """Install the Chrome trace-event exporter for every recorded span."""
    global _chrome
    _chrome = ChromeTraceExporter(path)
    return _chrome


def shutdown_chrome_trace() -> None:
    global _chrome
    if _chrome is not None:
        _chrome.close()
        _chrome = None


def read_chrome_trace(path: str | Path) -> list[dict]:
    """Tolerant loader for a (possibly still-open) Chrome trace file:
    each line holds one event object (JSON-lines view of the array).
    Undecodable lines (a SIGKILLed process torn mid-write) are skipped —
    postmortem tooling must read what the dead process DID flush."""
    out = []
    for line in Path(path).read_text().splitlines():
        line = line.strip().rstrip(",")
        if line in ("", "[", "]"):
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return out


def stitch_chrome_traces(paths) -> dict:
    """Merge Chrome trace files exported by SEVERAL processes and check
    the cross-process stitching contract: every ``parent_id`` minted by
    another process (its pid bits differ from the referencing event's)
    must resolve to an exported span somewhere in the merged set.

    Returns ``{"events", "pids", "span_ids", "unresolved",
    "unresolved_cross", "stitched"}`` — ``stitched`` is True when at
    least one cross-process parent reference exists AND all of them
    resolve (a fleet whose traces never cross a process boundary is NOT
    stitched, it is merely concatenated)."""
    events: list[dict] = []
    for p in paths:
        try:
            events.extend(read_chrome_trace(p))
        except OSError:
            continue
    span_ids = set()
    for e in events:
        sid = (e.get("args") or {}).get("span_id")
        if isinstance(sid, int):
            span_ids.add(sid)
    # pids that contributed SPANS — a process whose file holds only
    # metadata events did not span the trace
    pids = {e["pid"] for e in events
            if "pid" in e and e.get("ph") == "X"}
    unresolved, unresolved_cross, cross_refs = [], [], 0
    for e in events:
        parent = (e.get("args") or {}).get("parent_id")
        if not isinstance(parent, int):
            continue
        cross = span_id_pid_bits(parent) != (e.get("pid", 0) & 0x3FFFFF)
        if cross:
            cross_refs += 1
        if parent not in span_ids:
            unresolved.append(parent)
            if cross:
                unresolved_cross.append(parent)
    return {
        "events": events,
        "pids": sorted(pids),
        "span_ids": span_ids,
        "unresolved": unresolved,
        "unresolved_cross": unresolved_cross,
        "cross_refs": cross_refs,
        "stitched": cross_refs > 0 and not unresolved_cross,
    }


def init_block_tracing(chrome_path: str | Path | None = None,
                       otlp_path: str | Path | None = None,
                       flight_dir: str | Path | None = None,
                       capacity: int | None = None) -> None:
    """The ``--trace-blocks`` bundle: install the requested exporters,
    point flight-recorder dumps at a directory, and THEN enable span
    recording — exporters must exist before the first span can close,
    or a busy worker thread (the feed's witness generator on a 1-core
    host) slips whole spans into the gap: recorded in the ring and
    adopted by replicas, but missing from the exported trace."""
    if chrome_path is not None:
        init_chrome_trace(chrome_path)
    if otlp_path is not None:
        init_otlp(otlp_path)
    if flight_dir is not None:
        _RECORDER.directory = flight_dir
    if capacity is not None and capacity != _RECORDER._buf.maxlen:
        with _RECORDER._lock:
            _RECORDER._buf = deque(_RECORDER._buf, maxlen=capacity)
    set_trace_enabled(True)


def shutdown_block_tracing() -> None:
    shutdown_chrome_trace()
    shutdown_otlp()
    set_trace_enabled(_env_enabled())
