"""Tracing/logging: layered init with per-target filters + span timing.

Reference analogue: crates/tracing — stdout/file layers with per-layer
env filters (src/lib.rs:1-35) and the `target:` discipline (e.g.
``trie::state_root``). Built on stdlib logging; `span()` provides the
timing-span idiom used across the reference's hot paths.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import time
from pathlib import Path


def init_tracing(
    stdout_level: str | None = None,
    file_path: str | Path | None = None,
    file_level: str = "DEBUG",
    filters: str | None = None,
) -> None:
    """Install stdout (+ optional file) handlers.

    ``filters``: comma-separated ``target=LEVEL`` pairs (the RUST_LOG
    analogue), e.g. ``"reth_tpu.trie=DEBUG,reth_tpu.engine=INFO"``; also
    read from the RETH_TPU_LOG env var.
    """
    root = logging.getLogger("reth_tpu")
    root.setLevel(logging.DEBUG)
    root.handlers.clear()
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S"
    )
    out = logging.StreamHandler(sys.stdout)
    out.setLevel((stdout_level or "INFO").upper())
    out.setFormatter(fmt)
    root.addHandler(out)
    if file_path:
        fh = logging.FileHandler(file_path)
        fh.setLevel(file_level.upper())
        fh.setFormatter(fmt)
        root.addHandler(fh)
    spec = filters if filters is not None else os.environ.get("RETH_TPU_LOG", "")
    for pair in filter(None, spec.split(",")):
        target, _, level = pair.partition("=")
        logging.getLogger(target.strip()).setLevel((level or "DEBUG").upper())


def tracer(target: str) -> logging.Logger:
    """Logger for a target (``trie.state_root`` style)."""
    return logging.getLogger(f"reth_tpu.{target}")


@contextlib.contextmanager
def span(target: str, name: str, level: int = logging.DEBUG, **fields):
    """Timed span: logs entry fields + exit duration (tracing-span idiom)."""
    log = tracer(target)
    t0 = time.time()
    err = None
    try:
        yield
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        dt = time.time() - t0
        extra = " ".join(f"{k}={v}" for k, v in fields.items())
        log.log(level, "%s %s took %.3fms", name, extra, dt * 1e3)
        if _otlp is not None:
            _otlp.export(target, name, t0, dt, fields, err)


# -- OTLP export (reference crates/tracing-otlp) ------------------------------
# The reference ships spans to an OTLP collector endpoint; this environment
# has no egress, so the exporter writes the SAME span model (resource +
# scope + span with name/attributes/start/end/status) as OTLP/JSON lines to
# a file a collector can tail — the transport is the only difference.

_otlp = None


class OtlpFileExporter:
    def __init__(self, path: str | Path, service_name: str = "reth-tpu"):
        import json as _json
        import threading

        self._json = _json
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)
        self.service_name = service_name
        self.exported = 0

    def export(self, target: str, name: str, start: float, duration: float,
               fields: dict, error: str | None) -> None:
        span_rec = {
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{
                "scope": {"name": f"reth_tpu.{target}"},
                "spans": [{
                    "name": name,
                    "startTimeUnixNano": str(int(start * 1e9)),
                    "endTimeUnixNano": str(int((start + duration) * 1e9)),
                    "attributes": [
                        {"key": k, "value": {"stringValue": str(v)}}
                        for k, v in fields.items()
                    ],
                    "status": ({"code": 2, "message": error} if error
                               else {"code": 1}),
                }],
            }],
        }
        with self._lock:
            self._f.write(self._json.dumps(span_rec) + "\n")
            self.exported += 1

    def close(self) -> None:
        with self._lock:
            self._f.close()


def init_otlp(path: str | Path, service_name: str = "reth-tpu") -> OtlpFileExporter:
    """Install the OTLP/JSON file exporter for every span()."""
    global _otlp
    _otlp = OtlpFileExporter(path, service_name)
    return _otlp


def shutdown_otlp() -> None:
    global _otlp
    if _otlp is not None:
        _otlp.close()
        _otlp = None
