"""Tracing/logging: layered init with per-target filters + span timing.

Reference analogue: crates/tracing — stdout/file layers with per-layer
env filters (src/lib.rs:1-35) and the `target:` discipline (e.g.
``trie::state_root``). Built on stdlib logging; `span()` provides the
timing-span idiom used across the reference's hot paths.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import time
from pathlib import Path


def init_tracing(
    stdout_level: str | None = None,
    file_path: str | Path | None = None,
    file_level: str = "DEBUG",
    filters: str | None = None,
) -> None:
    """Install stdout (+ optional file) handlers.

    ``filters``: comma-separated ``target=LEVEL`` pairs (the RUST_LOG
    analogue), e.g. ``"reth_tpu.trie=DEBUG,reth_tpu.engine=INFO"``; also
    read from the RETH_TPU_LOG env var.
    """
    root = logging.getLogger("reth_tpu")
    root.setLevel(logging.DEBUG)
    root.handlers.clear()
    fmt = logging.Formatter(
        "%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S"
    )
    out = logging.StreamHandler(sys.stdout)
    out.setLevel((stdout_level or "INFO").upper())
    out.setFormatter(fmt)
    root.addHandler(out)
    if file_path:
        fh = logging.FileHandler(file_path)
        fh.setLevel(file_level.upper())
        fh.setFormatter(fmt)
        root.addHandler(fh)
    spec = filters if filters is not None else os.environ.get("RETH_TPU_LOG", "")
    for pair in filter(None, spec.split(",")):
        target, _, level = pair.partition("=")
        logging.getLogger(target.strip()).setLevel((level or "DEBUG").upper())


def tracer(target: str) -> logging.Logger:
    """Logger for a target (``trie.state_root`` style)."""
    return logging.getLogger(f"reth_tpu.{target}")


@contextlib.contextmanager
def span(target: str, name: str, level: int = logging.DEBUG, **fields):
    """Timed span: logs entry fields + exit duration (tracing-span idiom)."""
    log = tracer(target)
    t0 = time.time()
    try:
        yield
    finally:
        extra = " ".join(f"{k}={v}" for k, v in fields.items())
        log.log(level, "%s %s took %.3fms", name, extra, (time.time() - t0) * 1e3)
