"""Task runtime (graceful shutdown, critical failures) + execution cache."""

from __future__ import annotations

import threading
import time

from reth_tpu.engine import EngineTree
from reth_tpu.engine.execution_cache import CachedStateSource, ExecutionCache
from reth_tpu.evm.executor import InMemoryStateSource
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.tasks import TaskExecutor
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


# -- task runtime ------------------------------------------------------------


def test_graceful_shutdown_joins_tasks():
    ex = TaskExecutor()
    ran = threading.Event()

    def loop(shutdown):
        ran.set()
        while not shutdown.wait(0.01):
            pass

    h = ex.spawn("loop", loop)
    assert ran.wait(5) and h.alive
    stuck = ex.graceful_shutdown(timeout=5)
    assert stuck == [] and not h.alive


def test_critical_failure_surfaces():
    failures = []
    ex = TaskExecutor(on_critical_failure=lambda name, e, tb: failures.append((name, e)))

    def boom(shutdown):
        raise RuntimeError("kaboom")

    h = ex.spawn_critical("boom", boom)
    h.thread.join(5)
    assert isinstance(h.error, RuntimeError)
    assert failures and failures[0][0] == "boom"
    assert ex.critical_errors() and ex.critical_errors()[0][0] == "boom"


def test_noncritical_failure_is_captured_quietly():
    called = []
    ex = TaskExecutor(on_critical_failure=lambda *a: called.append(a))
    h = ex.spawn("oops", lambda sd: (_ for _ in ()).throw(ValueError("x")))
    h.thread.join(5)
    assert isinstance(h.error, ValueError)
    assert not called  # only CRITICAL failures fire the callback


# -- execution cache ---------------------------------------------------------


def test_cached_source_hits_and_invalidation():
    inner = InMemoryStateSource({b"\x01" * 20: Account(balance=7)},
                                {b"\x01" * 20: {b"\x02" * 32: 42}})
    cache = ExecutionCache()
    src = CachedStateSource(inner, cache)
    assert src.account(b"\x01" * 20).balance == 7
    assert src.account(b"\x01" * 20).balance == 7
    assert cache.accounts.hits == 1
    assert src.storage(b"\x01" * 20, b"\x02" * 32) == 42
    # mutate underneath + invalidate: the cache must refetch
    inner.accounts[b"\x01" * 20] = Account(balance=9)
    inner.storages[b"\x01" * 20][b"\x02" * 32] = 43

    class _Changes:
        accounts = {b"\x01" * 20: None}
        storage = {b"\x01" * 20: {b"\x02" * 32: 0}}
        wiped_storage = set()

    cache.on_block_applied(_Changes())
    assert src.account(b"\x01" * 20).balance == 9
    assert src.storage(b"\x01" * 20, b"\x02" * 32) == 43


def test_tree_cache_stays_correct_across_blocks_and_reorgs():
    """Chain of blocks re-touching the same accounts: the warm cache must
    never produce a stale balance (roots are checked per block, so any
    staleness fails validation)."""
    alice = Wallet(0xA11CE)
    bob = b"\x0b" * 20
    bld = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(5):
        bld.build_block([alice.transfer(bob, 1000 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, bld.genesis, bld.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU)
    for blk in bld.blocks[1:]:
        assert tree.on_new_payload(blk).status.name == "VALID"
    assert tree.execution_cache.stats()["account_hits"] > 0
    # side branch off block 2: anchor mismatch resets the cache, and the
    # branch still validates (no stale reads from the canonical warmth)
    fork = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    a2 = Wallet(0xA11CE)
    fork.build_block([a2.transfer(bob, 1000)])
    fork.build_block([a2.transfer(b"\x0c" * 20, 77)])
    assert tree.on_new_payload(fork.blocks[2]).status.name == "VALID"


def test_prewarm_populates_cache_and_execution_agrees():
    """A multi-tx payload triggers the prewarm pass; the canonical
    execution result (and root) is unchanged and the cache is warm."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=cpu)
    # one block with enough txs to cross the prewarm threshold
    txs = [alice.transfer(bytes([0x10 + i]) * 20, 1000 + i) for i in range(6)]
    builder.build_block(txs)
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=cpu)
    tree = EngineTree(factory, committer=cpu)
    assert tree.prewarm_threshold <= 6
    st = tree.on_new_payload(builder.blocks[1])
    assert st.status is PayloadStatusKind.VALID
    assert tree.last_prewarm is not None
    assert tree.last_prewarm.warmed == 6
    # the warm pass populated the shared cache and the sequential pass hit
    # it (sizes go back down when on_block_applied invalidates the block's
    # own writes — hits are the proof of warmth)
    stats = tree.execution_cache.stats()
    assert stats["account_hits"] > 0
