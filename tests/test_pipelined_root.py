"""Pipelined live-tip state root: hashing overlaps execution wall-clock.

VERDICT round-1 next-round #7: per-tx state updates stream into a
concurrently running root job (reference state_root_task.rs +
sparse_trie.rs strategy).
"""

from __future__ import annotations

import time

from reth_tpu.engine import EngineTree
from reth_tpu.engine.pipelined_root import PipelinedStateRoot
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def test_worker_hashes_while_producer_runs():
    calls = []

    def slow_hasher(keys):
        calls.append((time.monotonic(), list(keys)))
        return keccak256_batch_np(keys)

    job = PipelinedStateRoot(slow_hasher)
    exec_start = time.monotonic()
    for i in range(5):  # "execution": txs touching keys, with think time
        job.on_state_update([bytes([i]) * 20])
        time.sleep(0.05)
    exec_end = time.monotonic()
    digests = job.finish([bytes([i]) * 20 for i in range(5)])
    assert digests[b"\x00" * 20] == keccak256_batch_np([b"\x00" * 20])[0]
    # the worker hashed batches INSIDE the execution window
    overlapped = [t0 for t0, t1 in job.hash_spans if exec_start < t1 < exec_end]
    assert overlapped, "no hash batch completed during execution"
    assert job.batches_hashed >= 2


def test_dedup_and_stragglers():
    hashed: list[bytes] = []

    def hasher(keys):
        hashed.extend(keys)
        return keccak256_batch_np(keys)

    job = PipelinedStateRoot(hasher)
    job.on_state_update([b"a" * 20, b"b" * 20])
    job.on_state_update([b"a" * 20, b"b" * 20, b"c" * 20])  # dedup resend
    digests = job.finish([b"a" * 20, b"b" * 20, b"c" * 20, b"d" * 20])
    assert len(digests) == 4
    assert hashed.count(b"a" * 20) == 1, "resent key was hashed twice"
    assert b"d" * 20 in hashed  # straggler hashed at finish


def test_engine_root_work_overlaps_execution():
    """End-to-end through the engine tree: by the time execution finishes,
    the streamed keys are hashed — the root job's wall-clock component for
    key hashing lands inside the execution span."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    block = builder.build_block([
        alice.transfer(bytes([i + 1] * 20), 1000 + i) for i in range(8)
    ])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)

    spans = []
    real = CPU.hasher

    def recording_hasher(keys):
        t0 = time.monotonic()
        out = real(keys)
        spans.append((t0, time.monotonic(), len(keys)))
        return out

    committer = TrieCommitter(hasher=recording_hasher)
    committer.turbo_backend = "numpy"
    tree = EngineTree(factory, committer=committer)
    t_exec0 = time.monotonic()
    status = tree.on_new_payload(block)
    assert status.status.name == "VALID"
    # at least one device hash batch ran strictly before on_new_payload's
    # final root commit (i.e. streamed concurrently with execution): the
    # root job accounts >= 1 batch and the engine accepted the block
    assert spans, "no hashing recorded"
