"""Node health & SLO engine (health.py): histogram-quantile helpers,
metric time-series retention, burn-rate SLO evaluation with breach
flight dumps + the RETH_TPU_FAULT_SLO_BREACH drill, /health and the
debug health RPCs end-to-end on a dev node with a hash-service stall,
the bench perf-regression sentinel (wedged tunnel simulated -> rc=0
with a real CPU number + vs_prev), and the sampler/evaluator overhead
guard.

Reference analogue: the reference wires metrics through every layer so
the node itself knows when it is sick (PAPER.md §1); these tests pin
this repo's judgment layer end to end (ISSUE 9)."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from reth_tpu import health, tracing
from reth_tpu.health import (
    BenchBaselineStore,
    HealthEngine,
    MetricsSampler,
    SloRule,
    default_rules,
)
from reth_tpu.metrics import (
    REGISTRY,
    Histogram,
    MetricsRegistry,
    histogram_quantile,
    sample_percentile,
    update_process_metrics,
)
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _health_env(tmp_path, monkeypatch):
    """Isolate flight dumps + dump rate limits + the default engine."""
    monkeypatch.setenv("RETH_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.delenv("RETH_TPU_FAULT_SLO_BREACH", raising=False)
    rec = tracing.flight_recorder()
    rec.directory = None
    rec.dumps.clear()
    tracing.reset_fault_dump_limits()
    yield
    health.uninstall()
    rec.directory = None


# -- satellite: histogram_quantile / sample_percentile ------------------------


def test_histogram_quantile_known_distributions():
    buckets = (1.0, 2.0, 3.0, 4.0)
    # uniform: 10 observations per bucket -> median at the 2nd edge
    assert histogram_quantile(buckets, [10, 10, 10, 10, 0], 0.5) == \
        pytest.approx(2.0)
    # linear interpolation inside a bucket: rank 5 of 10 in (1, 2]
    assert histogram_quantile(buckets, [0, 10, 0, 0, 0], 0.5) == \
        pytest.approx(1.5)
    # skewed mass: 90 in the first bucket -> p50 well inside it
    assert histogram_quantile(buckets, [90, 5, 3, 1, 1], 0.5) == \
        pytest.approx(0.5 * 100 / 90, rel=1e-6)
    # overflow rank clamps to the last finite edge (Prometheus rule)
    assert histogram_quantile(buckets, [1, 0, 0, 0, 99], 0.99) == 4.0
    # first bucket interpolates from 0
    assert histogram_quantile(buckets, [4, 0, 0, 0, 0], 0.25) == \
        pytest.approx(0.25)
    # no observations
    assert histogram_quantile(buckets, [0, 0, 0, 0, 0], 0.5) is None
    with pytest.raises(ValueError):
        histogram_quantile(buckets, [1, 0, 0, 0, 0], 1.5)


def test_histogram_quantile_vs_empirical():
    """Against a known sample set pushed through a real Histogram: the
    bucketed estimate brackets the empirical percentile."""
    h = Histogram("q_test", buckets=(0.001, 0.01, 0.1, 0.5, 1.0))
    values = [0.0005] * 50 + [0.05] * 40 + [0.75] * 10
    for v in values:
        h.record(v)
    p50 = h.quantile(0.5)
    assert 0.001 <= p50 <= 0.1  # true p50 = 0.0005..0.05 boundary region
    p99 = h.quantile(0.99)
    assert 0.5 < p99 <= 1.0    # true p99 = 0.75
    assert Histogram("empty").quantile(0.5) is None


def test_sample_percentile_nearest_rank():
    samples = list(range(1, 11))
    assert sample_percentile(samples, 0) == 1
    assert sample_percentile(samples, 60) == 7  # the gas-oracle shape
    assert sample_percentile(samples, 100) == 10
    assert sample_percentile([], 50) is None
    assert sample_percentile([7], 99) == 7


# -- satellite: build-info / uptime gauges ------------------------------------


def test_build_info_and_uptime_gauges():
    reg = MetricsRegistry()
    update_process_metrics(reg)
    text = reg.render()
    assert "# TYPE reth_tpu_build_info gauge" in text
    # identity in the labels, value pinned to 1
    line = next(ln for ln in text.splitlines()
                if ln.startswith("reth_tpu_build_info{"))
    assert line.endswith(" 1.0") or line.endswith(" 1")
    assert 'version="' in line and 'backend="' in line
    assert "process_uptime_seconds" in text
    # label rendering keeps the exposition parseable: TYPE name is bare
    assert "# TYPE reth_tpu_build_info{" not in text


# -- time-series retention ----------------------------------------------------


def test_sampler_counter_delta_encoding_and_reset():
    reg = MetricsRegistry()
    c = reg.counter("work_total")
    s = MetricsSampler(reg, window=8)
    c.increment(5)
    s.sample(now=1.0)   # first sight: baseline, delta 0
    c.increment(3)
    s.sample(now=2.0)
    c.increment(2)
    s.sample(now=3.0)
    pts = s.points("work_total")
    assert [p["delta"] for p in pts] == [0, 3, 2]
    assert [p["value"] for p in pts] == [5, 8, 10]
    assert s.delta("work_total", 2) == 5
    assert s.rate("work_total", 2) == pytest.approx(5 / 2.0)
    # counter reset (restart): delta re-bases instead of going negative
    c.value = 1.0
    s.sample(now=4.0)
    assert s.points("work_total")[-1]["delta"] == 1.0


def test_sampler_gauge_and_windowed_histogram_quantile():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    s = MetricsSampler(reg, window=16)
    # pre-engine history must NOT count as a burst (baseline sample)
    for _ in range(50):
        h.record(5.0)
    g.set(3)
    s.sample(now=1.0)
    assert s.quantile("lat_seconds", 0.99, 1) is None  # empty window
    # a window of fast observations
    for _ in range(100):
        h.record(0.005)
    s.sample(now=2.0)
    assert s.quantile("lat_seconds", 0.99, 1) <= 0.01
    # then a slow interval: the one-sample window sees only the stall
    for _ in range(10):
        h.record(0.5)
    g.set(7)
    s.sample(now=3.0)
    assert s.quantile("lat_seconds", 0.99, 1) > 0.1
    # ...while the two-sample window still averages both
    assert s.quantile("lat_seconds", 0.5, 2) <= 0.01
    assert s.latest("depth") == 7
    pts = s.points("lat_seconds")
    assert pts[1]["count"] == 100 and "p99" in pts[1]


def test_sampler_window_bounded():
    reg = MetricsRegistry()
    reg.gauge("g").set(1)
    s = MetricsSampler(reg, window=4)
    for i in range(20):
        s.sample(now=float(i))
    assert len(s.points("g")) == 4
    assert s.samples == 20


# -- burn-rate evaluation -----------------------------------------------------


def _gauge_rule(**kw):
    defaults = dict(kind="gauge", budget=10.0, metric="probe_ms",
                    fast_n=2, slow_n=4, failing_factor=2.0, recovery=0.9,
                    window=2)
    defaults.update(kw)
    return SloRule("probe_latency", "probe", **defaults)


def test_slo_degraded_failing_recovery_cycle(tmp_path):
    reg = MetricsRegistry()
    g = reg.gauge("probe_ms")
    eng = HealthEngine(reg, [_gauge_rule()], interval=0)
    g.set(5.0)
    for _ in range(4):
        eng.tick()
    assert eng.status() == "ok"
    assert eng.components() == {"probe": "ok"}
    # breach: flips to degraded within ONE evaluation window
    g.set(15.0)
    eng.tick()
    assert eng.components()["probe"] == "degraded"
    assert eng.breaches_total == 1
    st = eng.slo_status()["rules"][0]
    assert st["state"] == "degraded" and st["value"] == 15.0
    assert st["series"][-1]["value"] == 15.0  # the triggering series
    # the breach dumped the flight recorder (fault_event path)
    assert st["last_breach"]["flight_dump"]
    assert os.path.exists(st["last_breach"]["flight_dump"])
    # sustained hard burn (>= failing_factor x budget, slow window too)
    g.set(25.0)
    for _ in range(4):
        eng.tick()
    assert eng.components()["probe"] == "failing"
    assert eng.status() == "failing"
    # recovery has hysteresis: back under budget -> ok
    g.set(5.0)
    for _ in range(4):
        eng.tick()
    assert eng.components()["probe"] == "ok"
    h = eng.health()
    assert h["status"] == "ok" and h["breaches_total"] >= 2
    assert h["recent_breaches"][-1]["rule"] == "probe_latency"


def test_slo_ewma_baseline_tracks_value():
    reg = MetricsRegistry()
    g = reg.gauge("probe_ms")
    eng = HealthEngine(reg, [_gauge_rule(ewma_alpha=0.5)], interval=0)
    g.set(4.0)
    eng.tick()
    g.set(8.0)
    eng.tick()
    st = eng.slo_status()["rules"][0]
    assert st["ewma"] == pytest.approx(6.0)  # 0.5*8 + 0.5*4


def test_slo_floor_rule_breaches_below_budget():
    """op='<' rules budget a floor (cache hit rate shape)."""
    reg = MetricsRegistry()
    hits = reg.counter("hits_total")
    total = reg.counter("lookups_total")
    rule = SloRule("hit_rate", "cache", "ratio", 0.5,
                   metrics_num=("hits_total",),
                   metrics_den=("lookups_total",),
                   op="<", min_den=10.0, fast_n=1, slow_n=4, window=2)
    eng = HealthEngine(reg, [rule], interval=0)
    eng.tick()  # baseline
    hits.increment(90)
    total.increment(100)
    eng.tick()
    assert eng.components()["cache"] == "ok"
    total.increment(100)  # 0 hits this window -> rate 0 < 0.5 floor
    eng.tick()
    assert eng.components()["cache"] == "degraded"


def test_slo_ratio_min_den_guards_idle_subsystems():
    reg = MetricsRegistry()
    reg.counter("errs_total").increment(5)
    reg.counter("reqs_total")
    rule = SloRule("err_rate", "svc", "ratio", 0.01,
                   metrics_num=("errs_total",), metrics_den=("reqs_total",),
                   min_den=10.0, fast_n=1, window=4)
    eng = HealthEngine(reg, [rule], interval=0)
    for _ in range(3):
        eng.tick()
    # no denominator activity: the rule must idle at ok, not divide by 0
    assert eng.components()["svc"] == "ok"
    assert eng.slo_status()["rules"][0]["value"] is None


def test_slo_breach_drill_env(monkeypatch, tmp_path):
    """RETH_TPU_FAULT_SLO_BREACH forces the named rule to breach."""
    reg = MetricsRegistry()
    reg.gauge("probe_ms").set(1.0)
    eng = HealthEngine(reg, [_gauge_rule()], interval=0)
    eng.tick()
    assert eng.status() == "ok"
    monkeypatch.setenv("RETH_TPU_FAULT_SLO_BREACH", "probe_latency")
    eng.tick()
    assert eng.components()["probe"] == "degraded"
    breach = eng.slo_status()["rules"][0]["last_breach"]
    assert breach["drill"] is True and breach["flight_dump"]
    monkeypatch.delenv("RETH_TPU_FAULT_SLO_BREACH")
    for _ in range(4):
        eng.tick()
    assert eng.status() == "ok"


def test_block_wall_rule_reads_tracing_summaries():
    reg = MetricsRegistry()
    rule = next(r for r in default_rules() if r.name == "block_import_wall")
    rule.budget = 0.001  # ms: any real block breaches
    rule.fast_n = 1
    eng = HealthEngine(reg, [rule], interval=0)
    tracing.set_trace_enabled(True)
    try:
        # a unique trace id: timelines are keyed globally, and reusing
        # another suite's id would merge the two blocks' records
        with tracing.trace_block("9e" * 32, number=7):
            with tracing.span("engine::block", "execute"):
                time.sleep(0.002)
    finally:
        tracing.set_trace_enabled(False)
    eng.tick()
    st = eng.slo_status()["rules"][0]
    assert st["value"] is not None and st["value"] > 0
    assert eng.components()["engine"] == "degraded"


def test_health_engine_metrics_published():
    reg = MetricsRegistry()
    g = reg.gauge("probe_ms")
    eng = HealthEngine(reg, [_gauge_rule()], interval=0)
    g.set(20.0)
    eng.tick()
    lines = reg.render().splitlines()
    assert "node_health_state 1" in lines        # degraded
    assert "slo_breaches_total 1.0" in lines
    assert "health_component_state_probe 1" in lines
    assert "health_ticks_total 1.0" in lines


def test_metrics_history_query():
    reg = MetricsRegistry()
    c = reg.counter("x_total")
    eng = HealthEngine(reg, [], interval=0)
    eng.tick()
    c.increment(4)
    eng.tick()
    listing = eng.metrics_history()
    assert "x_total" in listing["series"]
    series = eng.metrics_history("x_total", samples=1)
    assert series["kind"] == "counter"
    assert series["points"][-1]["delta"] == 4
    with pytest.raises(KeyError):
        eng.metrics_history("no_such_metric")


# -- gateway shed storm degrades its component --------------------------------


def test_gateway_shed_storm_degrades_component():
    from reth_tpu.rpc.gateway import GatewayFaultInjector, RpcGateway
    from reth_tpu.rpc.server import RpcError

    reg = MetricsRegistry()
    rules = [r for r in default_rules() if r.name == "gateway_shed_rate"]
    eng = HealthEngine(reg, rules, interval=0)
    gw = RpcGateway(head_supplier=lambda: b"h", registry=reg,
                    injector=GatewayFaultInjector(shed_every=2),
                    cache_size=0)
    eng.tick()  # baseline
    sheds = 0
    for i in range(40):
        try:
            gw.call("eth_blockNumber", [], lambda: "0x1")
        except RpcError as e:
            assert e.code == -32005
            sheds += 1
    assert sheds >= 19  # the storm: every 2nd admission shed
    eng.tick()
    assert eng.components()["gateway"] == "degraded"
    st = next(r for r in eng.slo_status()["rules"]
              if r["rule"] == "gateway_shed_rate")
    assert st["value"] >= 0.4
    assert st["last_breach"]["flight_dump"]  # breach dumped the recorder
    # monitoring probes classify as reads — never starved in the 2-slot
    # debug class behind a trace re-execution
    from reth_tpu.rpc.gateway import classify

    assert classify("debug_healthCheck") == "read"
    assert classify("debug_sloStatus") == "read"
    assert classify("debug_metricsHistory") == "read"
    assert classify("debug_traceTransaction") == "debug"


# -- node e2e: /health + debug RPCs + hash-service stall drill ----------------


@pytest.fixture()
def health_node():
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.ops.hash_service import HashService

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    svc = HashService(backend=cpu.hasher, min_tier=256)
    cpu.hash_service = svc
    cpu.hasher = svc.client("live")
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=cpu)
    # other suites may have left global-registry gauges non-zero (the
    # engine samples REGISTRY); pin the gauge-kind rule inputs healthy
    REGISTRY.gauge("warmup_shapes_failed").set(0)
    REGISTRY.gauge("hasher_supervisor_breaker_state").set(0)
    cfg = NodeConfig(dev=True, health=True, slo_interval=0,
                     genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=cpu)
    n.start_rpc()
    yield n, svc
    n.stop()
    svc.stop()


def _rpc(port, method, *params):
    req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                      "params": list(params)})
    out = json.loads(urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/", req.encode(),
        {"Content-Type": "application/json"}), timeout=30).read())
    if "error" in out:
        raise RuntimeError(f"{method}: {out['error']}")
    return out["result"]


def _get_health(port):
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=10)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:  # 503 when failing
        return e.code, json.loads(e.read())


def test_node_health_e2e_stall_degrade_recover(health_node):
    """The acceptance drill: healthy -> RETH_TPU_FAULT_SERVICE_STALL
    shape stall -> hash_service degrades and node health flips within
    one evaluation window, slo breach event + flight dump recorded,
    /health + debug_healthCheck + debug_sloStatus report it with the
    triggering series -> recovery returns to ok."""
    from reth_tpu.ops.hash_service import ServiceFaultInjector

    n, svc = health_node
    port = n.rpc.port
    eng = n.health
    assert eng is not None and health.get_engine() is eng

    # healthy baseline: mine a block (live-lane traffic), then evaluate
    n.miner.mine_block(timestamp=1_900_000_000)
    eng.tick()
    eng.tick()
    code, body = _get_health(port)
    assert code == 200
    assert body["components"]["hash_service"] == "ok"
    assert body["build"]["version"]
    assert _rpc(port, "debug_healthCheck")["components"][
        "hash_service"] == "ok"

    # inject the stall drill (the ServiceFaultInjector the env knob
    # builds): every coalesced dispatch sleeps, breaching the p99
    # dispatch budget
    dumps_before = len(tracing.flight_recorder().dumps)
    svc.injector = ServiceFaultInjector(stall=0.2)
    try:
        n.miner.mine_block(timestamp=1_900_000_001)
    finally:
        svc.injector = None
    eng.tick()  # one evaluation window
    assert eng.components()["hash_service"] == "degraded"
    code, body = _get_health(port)
    assert code == 200  # degraded still serves
    assert body["status"] in ("degraded", "failing")
    assert body["components"]["hash_service"] == "degraded"
    assert any(b["component"] == "hash_service"
               for b in body["recent_breaches"])
    # flight dumps: the drill's own fault_event AND the slo breach
    assert len(tracing.flight_recorder().dumps) > dumps_before
    slo = _rpc(port, "debug_sloStatus")
    breached = [r for r in slo["rules"]
                if r["component"] == "hash_service" and r["state"] != "ok"]
    assert breached
    assert any(p["value"] and p["value"] > 0.15
               for r in breached for p in r["series"])  # triggering series
    assert any(r["last_breach"] and r["last_breach"]["flight_dump"]
               for r in breached)
    # the events line carries the slo fragment
    n.event_reporter.on_canon_change([])  # ensure reporter is wired
    line = None
    n.miner.mine_block(timestamp=1_900_000_002)
    line = n.event_reporter.report_once()
    assert line is not None and "slo[" in line

    # recovery: clean traffic + enough windows for the stall deltas to
    # leave the aggregation window
    n.miner.mine_block(timestamp=1_900_000_003)
    for _ in range(14):
        eng.tick()
    assert eng.components()["hash_service"] == "ok"
    code, body = _get_health(port)
    assert body["components"]["hash_service"] == "ok"


def test_debug_metrics_history_rpc(health_node):
    n, _svc = health_node
    port = n.rpc.port
    n.miner.mine_block(timestamp=1_900_000_000)
    n.health.tick()
    n.health.tick()
    listing = _rpc(port, "debug_metricsHistory")
    assert "hash_service_dispatches_total" in listing["series"]
    series = _rpc(port, "debug_metricsHistory",
                  "hash_service_dispatches_total", 4)
    assert series["kind"] == "counter"
    assert len(series["points"]) <= 4
    assert series["points"][-1]["value"] > 0
    with pytest.raises(RuntimeError, match="no retained series"):
        _rpc(port, "debug_metricsHistory", "bogus_metric")


def test_health_endpoint_without_engine():
    """/health answers liveness + build identity even without --health."""
    from reth_tpu.rpc.server import RpcServer

    assert health.get_engine() is None
    srv = RpcServer()
    port = srv.start()
    try:
        code, body = _get_health(port)
        assert code == 200
        assert body["status"] == "unknown"
        assert body["health_engine"] == "off"
        assert body["build"]["version"]
    finally:
        srv.stop()


def test_debug_health_rpcs_error_without_engine():
    from reth_tpu.rpc.debug import DebugApi
    from reth_tpu.rpc.server import RpcError

    assert health.get_engine() is None
    api = DebugApi(eth_api=None)
    for fn in (api.debug_healthCheck, api.debug_sloStatus,
               api.debug_metricsHistory):
        with pytest.raises(RpcError, match="health engine disabled"):
            fn()


# -- perf-regression sentinel -------------------------------------------------


def test_bench_baseline_store_roundtrip(tmp_path):
    path = tmp_path / "baselines.json"
    store = BenchBaselineStore(path, keep=3)
    # no history: vs_prev pins to 1.0, never a regression
    v = store.assess("m", "exec", "cpu", "off", 100.0)
    assert v == {"vs_prev": 1.0, "regression": False, "baseline_n": 0,
                 "baseline": None}
    for x in (100.0, 110.0, 90.0):
        store.record("m", "exec", "cpu", "off", x)
    # reload from disk: median of trailing goods = 100
    store2 = BenchBaselineStore(path, keep=3)
    v = store2.assess("m", "exec", "cpu", "off", 95.0)
    assert v["vs_prev"] == pytest.approx(0.95)
    assert v["regression"] is False and v["baseline_n"] == 3
    v = store2.assess("m", "exec", "cpu", "off", 50.0)
    assert v["regression"] is True and v["vs_prev"] == pytest.approx(0.5)
    # keyed by backend/warmup: a numpy fallback never compares against
    # the device baseline
    v = store2.assess("m", "exec", "numpy", "off", 50.0)
    assert v["baseline_n"] == 0 and v["regression"] is False
    v = store2.assess("m", "exec", "cpu", {"state": "warming"}, 50.0)
    assert v["baseline_n"] == 0
    # keep=3 trims
    store2.record("m", "exec", "cpu", "off", 120.0)
    assert len(store2.runs("m", "exec", "cpu", "off")) == 3


def test_bench_baseline_store_corrupt_file_quarantined(tmp_path):
    path = tmp_path / "baselines.json"
    path.write_text("{not json")
    store = BenchBaselineStore(path)
    assert store.assess("m", "exec", "cpu", "off", 10.0)["baseline_n"] == 0
    store.record("m", "exec", "cpu", "off", 10.0)
    assert (tmp_path / "baselines.json.corrupt").exists()
    assert BenchBaselineStore(path).runs("m", "exec", "cpu",
                                         "off")[0]["value"] == 10.0


def _run_bench(tmp_path, extra_env, timeout=420):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "RETH_TPU_BENCH_BASELINE_STORE": str(tmp_path / "baselines.json"),
        "RETH_TPU_FLIGHT_DIR": str(tmp_path / "flight"),
    })
    env.update(extra_env)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=timeout,
                       cwd=REPO, env=env)
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line: rc={r.returncode} stderr={r.stderr[-500:]}"
    return r.returncode, json.loads(lines[-1])


@pytest.mark.slow  # ~10s subprocess (jax import); `make test-health` runs it
def test_bench_wedged_tunnel_rebuild_emits_cpu_number(tmp_path):
    """Satellite: the probe-timeout path (wedged tunnel simulated via the
    RETH_TPU_FAULT_PROBE_FAIL drill) emits the CPU-fallback measurement
    with rc=0, backend/warmup_state populated, and vs_prev stamped —
    never again the BENCH_r05 rc=2 / value=0 shape. The tier-1-fast
    twin below covers the DEFAULT (exec) mode's wedged-tunnel contract."""
    rc, line = _run_bench(tmp_path, {
        "RETH_TPU_BENCH_MODE": "rebuild",
        "RETH_TPU_FAULT_PROBE_FAIL": "1",
        "RETH_TPU_PROBE_ATTEMPTS": "1",
        "RETH_TPU_PROBE_TIMEOUT": "60",
        "RETH_TPU_BENCH_ACCOUNTS": "2000",
        "RETH_TPU_BENCH_SLOTS": "800",
        "RETH_TPU_BENCH_TIMEOUT": "360",
    })
    assert rc == 0
    assert line["value"] > 0
    assert line["vs_baseline"] > 0
    assert line["backend"] == "numpy"
    assert "injected probe failure" in line["device_unavailable"]
    assert line["warmup_state"] is not None
    assert line["vs_prev"] == 1.0  # first run against an empty store
    assert line["regression"] is False


def test_bench_default_exec_mode_wedged_tunnel(tmp_path):
    """The DEFAULT bench (exec, PR 7) records a real CPU number with the
    sentinel fields even with the tunnel wedged — the trajectory can't
    regress to unreadable zeros."""
    rc, line = _run_bench(tmp_path, {
        "RETH_TPU_FAULT_PROBE_FAIL": "1",
        "RETH_TPU_BENCH_EXEC_TXS": "24",
        "RETH_TPU_BENCH_EXEC_WORKERS": "2",
        "RETH_TPU_BENCH_EXEC_REPS": "30",
        "RETH_TPU_BENCH_TIMEOUT": "360",
    })
    assert rc == 0
    assert line["metric"] == "exec_parallel_txs_per_sec"
    assert line["value"] > 0
    assert line["backend"] in ("cpu", "native-cpu")
    assert line["receipts_identical"] is True
    assert line["vs_prev"] == 1.0 and line["regression"] is False
    assert "warmup_state" in line and "compile_cache" in line
    # the store recorded the run for the next round's vs_prev
    store = BenchBaselineStore(tmp_path / "baselines.json")
    assert store.runs("exec_parallel_txs_per_sec", "exec",
                      line["backend"], "off")


# -- overhead guard -----------------------------------------------------------


def test_sampler_evaluator_overhead_guard():
    """Satellite: the health engine's steady-state cost — one sampler +
    evaluator pass per interval on its own thread — steals under 1% of a
    concurrent sparse-commit wall at the default 1 Hz cadence (mirrors
    PR 6's tracing-off guard)."""
    import numpy as np

    from reth_tpu.health import DEFAULT_INTERVAL_S
    from reth_tpu.trie.sparse import ParallelSparseCommitter, SparseStateTrie

    # a representative sparse-commit wall (the hot path being guarded)
    rng = np.random.default_rng(5)
    st = SparseStateTrie()
    for _ in range(24):
        ha = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        t = st.storage_trie(ha)
        for _ in range(24):
            t.update(bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
                     bytes(rng.integers(1, 256, 8, dtype=np.uint8)))
        st.update_account(ha, b"leaf-" + ha)
    committer = ParallelSparseCommitter(workers=2)
    t0 = time.perf_counter()
    st.root(keccak256_batch_np, committer=committer)
    wall = time.perf_counter() - t0
    committer.shutdown()

    # steady-state tick cost over the FULL global registry (every metric
    # the node registers) with the default rule table
    eng = HealthEngine(REGISTRY, default_rules(), interval=0)
    eng.tick()  # baselines + lazy series allocation out of the measure
    reps = 30
    t0 = time.perf_counter()
    for _ in range(reps):
        eng.tick()
    per_tick = (time.perf_counter() - t0) / reps
    # the sampler thread steals per_tick seconds out of every interval
    stolen_fraction = per_tick / DEFAULT_INTERVAL_S
    assert stolen_fraction < 0.01, (
        f"health tick costs {per_tick * 1e3:.2f}ms per {DEFAULT_INTERVAL_S}s "
        f"interval ({stolen_fraction:.2%} of a concurrent "
        f"{wall * 1e3:.1f}ms sparse commit's cpu)")


def test_health_engine_thread_lifecycle():
    reg = MetricsRegistry()
    reg.gauge("probe_ms").set(1.0)
    eng = HealthEngine(reg, [_gauge_rule()], interval=0.02)
    eng.start()
    try:
        deadline = time.time() + 5
        while eng.ticks < 3 and time.time() < deadline:
            time.sleep(0.02)
        assert eng.ticks >= 3
    finally:
        eng.stop()
    ticks = eng.ticks
    time.sleep(0.08)
    assert eng.ticks == ticks  # thread actually stopped
