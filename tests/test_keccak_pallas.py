"""Pallas keccak kernel — bit-exactness in interpret mode (CPU)."""

import numpy as np
import pytest

from reth_tpu.primitives.keccak import keccak256, pad_batch


def to_words(msgs):
    w64 = pad_batch(msgs, 1)
    return np.ascontiguousarray(w64).view("<u4").reshape(len(msgs), 34)


def test_pallas_matches_reference_interpret():
    from reth_tpu.ops.keccak_pallas import keccak256_pallas_words

    rng = np.random.default_rng(19)
    msgs = [bytes(rng.integers(0, 256, size=int(l), dtype=np.uint8))
            for l in rng.integers(0, 135, size=300)]  # crosses one LANES tile
    out = np.asarray(keccak256_pallas_words(to_words(msgs), interpret=True))
    got = [np.ascontiguousarray(out[i]).view(np.uint8).tobytes() for i in range(len(msgs))]
    assert got == [keccak256(m) for m in msgs]


def test_pallas_exact_tile_boundary():
    from reth_tpu.ops.keccak_pallas import LANES, keccak256_pallas_words

    msgs = [bytes([i % 256] * 64) for i in range(LANES)]  # exactly one tile
    out = np.asarray(keccak256_pallas_words(to_words(msgs), interpret=True))
    assert np.ascontiguousarray(out[0]).view(np.uint8).tobytes() == keccak256(msgs[0])
    assert np.ascontiguousarray(out[-1]).view(np.uint8).tobytes() == keccak256(msgs[-1])
