"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective code is
validated on host CPU with 8 virtual devices (the driver separately
dry-run-compiles the multi-chip path via `__graft_entry__.dryrun_multichip`).
Must run before the first `import jax` anywhere in the test session.
"""

import os

# FORCE cpu — the driver environment exports JAX_PLATFORMS=axon (the real
# TPU tunnel), so a setdefault would silently run every test over the
# tunnel. Tests must be hermetic on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# Skipping the axon plugin registration needs PALLAS_AXON_POOL_IPS unset
# BEFORE interpreter start (sitecustomize) — prefer running pytest via
#   env -u PALLAS_AXON_POOL_IPS python -m pytest tests/
# when the tunnel is flaky; with a healthy tunnel this conftest suffices.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()


def pytest_configure(config):
    # tier-1 deselects these via `-m 'not slow'`; `make test-sanitizers`
    # style targets opt back in with `-m slow`
    config.addinivalue_line(
        "markers", "slow: sanitizer builds / stress runs excluded from tier-1")
