"""Full data-lifecycle integration: one node, every storage subsystem.

Chain grows via the engine; finalized history moves to static files;
changesets are pruned under PruneModes; the trie still verifies and the
RPC still serves everything it should (and refuses what it can't).
"""

from reth_tpu.engine import EngineTree
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.prune import PruneMode, PruneModes, Pruner
from reth_tpu.rpc import EthApi, RpcError
from reth_tpu.rpc.convert import data, parse_qty
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.storage.static_files import StaticFileProducer
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter
from reth_tpu.trie.incremental import verify_state_root

CPU = TrieCommitter(hasher=keccak256_batch_np)


def test_node_runs_lifecycle_automatically(tmp_path):
    """A launched Node with lifecycle config produces static files and
    prunes as the dev miner advances the chain."""
    from reth_tpu.node import Node, NodeConfig

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    cfg = NodeConfig(
        dev=True,
        datadir=tmp_path,
        genesis_header=builder.genesis,
        genesis_alloc=builder.accounts_at_genesis,
        persistence_threshold=1,
        static_file_distance=3,
        prune_modes=PruneModes(receipts=PruneMode(distance=6)),
    )
    node = Node(cfg, committer=CPU)
    for i in range(10):
        node.pool.add_transaction(alice.transfer(b"\x0b" * 20, 50 + i))
        node.miner.mine_block()
    # persisted to 9; static files should cover to 9-3=6
    assert node.tree.persisted_number == 9
    assert node.static_producer.static.highest("headers") == 6
    # receipts older than 6 blocks pruned, but still served via static files
    p = node.factory.provider()
    assert p.tx.get("Receipts", (0).to_bytes(8, "big")) is None
    assert parse_qty(node.eth_api.eth_getBlockReceipts("0x1")[0]["gasUsed"]) == 21000
    assert parse_qty(node.eth_api.eth_getBalance(data(b"\x0b" * 20), "latest")) == \
        sum(50 + i for i in range(10))


def test_full_lifecycle(tmp_path):
    import pytest

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(10):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])

    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status.value == "VALID"
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 8  # 9,10 in memory

    # 1. move finalized history (blocks <= 6) into static files
    producer = StaticFileProducer(factory, tmp_path / "static")
    moved = producer.run(to_block=6)
    assert moved["transactions"] == 6
    factory.static_files = producer.static  # wire the read fallback

    # 2. prune receipts + senders deeper than 4 blocks from the tip
    pruner = Pruner(factory, PruneModes(
        receipts=PruneMode(distance=4), sender_recovery=PruneMode(distance=4),
    ))
    progress = pruner.run(tip=8)
    assert {p.segment for p in progress} == {"SenderRecovery", "Receipts"}

    # 3. the trie still verifies cleanly over the persisted tables
    with factory.provider() as p:
        root, problems = verify_state_root(p, CPU)
        assert problems == []
        assert root == builder.blocks[8].header.state_root

    # 4. RPC serves: tip state, static-file history, receipts via fallback
    api = EthApi(tree, None, 1)
    bob = data(b"\x0b" * 20)
    assert parse_qty(api.eth_getBalance(bob, "latest")) == sum(100 + i for i in range(10))
    blk3 = api.eth_getBlockByNumber("0x3", True)  # txs come from static files
    assert len(blk3["transactions"]) == 1
    # receipts for the un-pruned window still resolve (block 5 via static)
    receipts5 = api.eth_getBlockReceipts("0x5")
    assert receipts5 is not None and len(receipts5) == 1
    # historical balance mid-chain
    assert parse_qty(api.eth_getBalance(bob, "0x4")) == sum(100 + i for i in range(4))
    # unknown block still refused
    with pytest.raises(RpcError):
        api.eth_getBalance(bob, "0x63")
