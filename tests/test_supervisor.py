"""Device hasher supervisor: health probes, circuit breaker, watchdog-bounded
dispatch, and mid-commit CPU failover (reth_tpu/ops/supervisor.py).

The acceptance drill: with fault injection wedging EVERY device dispatch, a
multi-commit run still produces correct state roots — each commit completes
on the CPU twin via journal replay, the breaker opens, and a subsequent
healthy half-open probe restores the device route. Roots are pinned against
the numpy oracle throughout. Everything here runs CPU-only
(JAX_PLATFORMS=cpu via conftest) — the injector stands in for the wedged
tunnel, which is the point: the failover machinery must be testable
without hardware.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from reth_tpu.metrics import MetricsRegistry
from reth_tpu.ops.supervisor import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    DeviceDispatchError,
    DeviceSupervisor,
    FaultInjector,
    InjectedWedge,
    ProbeResult,
    SupervisedHasher,
    probe_device,
)
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.rlp import rlp_encode
from reth_tpu.trie.committer import TrieCommitter
from reth_tpu.trie.turbo import TurboCommitter


def _fake_probe(outcomes=()):
    """Probe stub: pops from ``outcomes``, then always healthy. Still
    consults the injector so RETH_TPU_FAULT_PROBE_FAIL keeps working."""
    remaining = list(outcomes)

    def probe(budget, injector=None):
        ok = remaining.pop(0) if remaining else True
        if injector is not None and not injector.on_probe():
            ok = False
        return ProbeResult(ok, 0.001, None if ok else "fake probe failure")

    return probe


def _supervisor(**kw):
    kw.setdefault("dispatch_budget", 120.0)
    kw.setdefault("probe_fn", _fake_probe())
    kw.setdefault("registry", MetricsRegistry())
    return DeviceSupervisor(**kw)


def _jobs(seed: int, n: int = 150):
    """One commit's worth of turbo jobs: a storage trie + an account trie."""
    rng = np.random.default_rng(seed)
    jobs = []
    for j in range(2):
        keys = rng.integers(0, 256, size=(n // (j + 1), 32), dtype=np.uint8)
        keys = np.unique(keys.view("S32").ravel()).view(np.uint8).reshape(-1, 32)
        vals = [rlp_encode(bytes(rng.integers(0, 256, size=1 + i % 37,
                                              dtype=np.uint8)))
                for i in range(len(keys))]
        jobs.append((keys, vals))
    return jobs


# -- circuit breaker ---------------------------------------------------------


def test_breaker_transitions_and_backoff():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_timeout=10.0,
                        clock=lambda: now[0])
    assert br.state == CLOSED and br.allow()
    assert not br.record_failure()        # 1/2
    assert br.record_failure()            # 2/2 -> OPEN
    assert br.state == OPEN and br.trips == 1
    assert not br.allow()
    now[0] = 9.9
    assert not br.allow()
    now[0] = 10.0                         # cooldown elapsed -> HALF_OPEN
    assert br.allow() and br.state == HALF_OPEN
    assert br.record_failure()            # trial failed -> reopen, 2x backoff
    assert br.state == OPEN and br.trips == 2
    now[0] = 10.0 + 19.9
    assert not br.allow()                 # doubled cooldown still running
    now[0] = 10.0 + 20.0
    assert br.allow() and br.state == HALF_OPEN
    br.record_success()                   # trial succeeded -> CLOSED, reset
    assert br.state == CLOSED and br.failures == 0
    # backoff reset: next trip waits the base timeout again
    br.record_failure()
    br.record_failure()
    assert br.state == OPEN
    now[0] += 10.0
    assert br.allow() and br.state == HALF_OPEN
    assert br.transitions[0] == CLOSED and OPEN in br.transitions


def test_breaker_closed_success_resets_failure_count():
    br = CircuitBreaker(failure_threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()
    assert br.failures == 0 and br.state == CLOSED


# -- fault injection ---------------------------------------------------------


def test_fault_injector_from_env():
    assert FaultInjector.from_env({}) is None
    inj = FaultInjector.from_env({"RETH_TPU_FAULT_WEDGE_EVERY": "2",
                                  "RETH_TPU_FAULT_DELAY": "0.5",
                                  "RETH_TPU_FAULT_PROBE_FAIL": "1"})
    assert inj is not None and inj.active()
    assert (inj.wedge_every, inj.delay, inj.probe_fail) == (2, 0.5, 1)


def test_fault_injector_wedges_every_nth():
    inj = FaultInjector(wedge_every=2)
    inj.on_dispatch()                      # 1: passes
    with pytest.raises(InjectedWedge):
        inj.on_dispatch()                  # 2: wedged
    inj.on_dispatch()                      # 3: passes
    assert inj.wedged == 1


def test_fault_injector_probe_failures():
    inj = FaultInjector(probe_fail=2)
    assert not inj.on_probe()
    assert not inj.on_probe()
    assert inj.on_probe()                  # budget spent
    forever = FaultInjector(probe_fail=-1)
    assert not forever.on_probe() and not forever.on_probe()


# -- health probe ------------------------------------------------------------


def test_probe_device_subprocess_healthy():
    r = probe_device(budget=300)
    assert r.ok, r.diag
    assert r.latency > 0


def test_probe_device_subprocess_failure_modes():
    bad = probe_device(budget=60, code="import sys; sys.exit(3)")
    assert not bad.ok and "rc=3" in bad.diag
    wedged = probe_device(budget=0.5, code="import time; time.sleep(30)")
    assert not wedged.ok and "exceeded" in wedged.diag


def test_probe_injected_failure_skips_subprocess():
    inj = FaultInjector(probe_fail=1)
    t0 = time.monotonic()
    r = probe_device(budget=60, injector=inj)
    assert not r.ok and "injected" in r.diag
    assert time.monotonic() - t0 < 1.0     # no child process ran


# -- watchdog-bounded dispatch ----------------------------------------------


def test_watchdog_trips_on_real_timeout():
    sup = _supervisor()
    with pytest.raises(DeviceDispatchError, match="watchdog"):
        sup.run_guarded(time.sleep, 2.0, what="sleepy", budget=0.05)
    assert sup.dispatch_timeouts == 1
    assert sup.breaker.failures == 1


def test_watchdog_wraps_exceptions_and_feeds_breaker():
    sup = _supervisor(breaker=CircuitBreaker(failure_threshold=2))

    def boom():
        raise RuntimeError("tunnel reset")

    with pytest.raises(DeviceDispatchError, match="tunnel reset"):
        sup.run_guarded(boom)
    with pytest.raises(DeviceDispatchError):
        sup.run_guarded(boom)
    assert sup.breaker.state == OPEN
    assert sup.route() == "numpy"


def test_injected_delay_exercises_real_timeout_path():
    inj = FaultInjector(delay=0.3)
    sup = _supervisor(injector=inj, dispatch_budget=0.05)
    with pytest.raises(DeviceDispatchError, match="watchdog"):
        sup.run_guarded(lambda: "never", what="delayed")
    assert sup.dispatch_timeouts == 1


# -- supervised turbo commits: the acceptance drill --------------------------


def test_wedged_run_fails_over_then_recovers():
    """Wedge EVERY device dispatch across a multi-commit run: every commit
    still lands the oracle root on the CPU twin, the breaker opens, and a
    healthy half-open probe restores the device route."""
    all_jobs = [_jobs(seed) for seed in range(4)]
    oracle = TurboCommitter(backend="numpy")
    want = [[r.root for r in oracle.commit_hashed_many(jobs)]
            for jobs in all_jobs]

    now = [0.0]                            # breaker time under test control
    inj = FaultInjector(wedge_every=1)     # every dispatch wedges
    sup = _supervisor(
        injector=inj,
        breaker=CircuitBreaker(failure_threshold=2, reset_timeout=30.0,
                               clock=lambda: now[0]))
    auto = TurboCommitter(backend="auto", min_tier=64, supervisor=sup)

    for jobs, roots in zip(all_jobs, want):
        got = auto.commit_hashed_many(jobs)
        assert [r.root for r in got] == roots   # per-commit completion
    assert sup.breaker.state == OPEN
    assert sup.breaker.trips == 1
    assert sup.failovers >= 1                   # at least one mid-run failover
    assert CLOSED == sup.breaker.transitions[0]
    assert OPEN in sup.breaker.transitions

    # device heals; the open cooldown elapses; the half-open probe (healthy)
    # closes the breaker and the device route returns
    inj.wedge_every = 0
    now[0] = 30.0
    assert sup.route() == "device"
    assert sup.breaker.state == CLOSED
    assert sup.breaker.transitions[-3:] == [OPEN, HALF_OPEN, CLOSED]
    got = auto.commit_hashed_many(all_jobs[0])
    assert [r.root for r in got] == want[0]     # device commit post-recovery


def test_failed_half_open_probe_reopens_with_backoff():
    now = [0.0]
    inj = FaultInjector(wedge_every=1, probe_fail=1)
    sup = _supervisor(
        injector=inj,
        breaker=CircuitBreaker(failure_threshold=1, reset_timeout=30.0,
                               clock=lambda: now[0]))
    jobs = _jobs(7)
    want = [r.root for r in TurboCommitter(backend="numpy")
            .commit_hashed_many(jobs)]
    auto = TurboCommitter(backend="auto", min_tier=64, supervisor=sup)
    got = auto.commit_hashed_many(jobs)
    assert [r.root for r in got] == want
    assert sup.breaker.state == OPEN
    now[0] = 30.0
    assert sup.route() == "numpy"              # injected probe failure
    assert sup.breaker.state == OPEN and sup.breaker.trips == 2
    now[0] = 30.0 + 59.9
    assert sup.route() == "numpy"              # doubled cooldown not elapsed
    now[0] = 30.0 + 60.0
    assert sup.route() == "device"             # healthy probe closes it
    assert sup.breaker.state == CLOSED


def test_mid_commit_failover_at_the_sync_point():
    """Let every level dispatch 'succeed' and wedge only the terminal
    fetch — the async-dispatch reality, where a wedged tunnel is first
    OBSERVED at the sync point. The journal must replay the whole commit
    on the CPU twin."""
    jobs = _jobs(11)
    want = [r.root for r in TurboCommitter(backend="numpy")
            .commit_hashed_many(jobs)]
    # count the guarded calls of a clean supervised device commit
    counter = _supervisor()
    auto = TurboCommitter(backend="auto", min_tier=64, supervisor=counter)
    counter.injector = FaultInjector()     # counting only
    got = auto.commit_hashed_many(jobs)
    assert [r.root for r in got] == want
    n_calls = counter.injector.dispatch_count
    assert n_calls >= 3                    # init + begin + dispatches + fetch

    inj = FaultInjector(wedge_every=n_calls)   # trips exactly at the fetch
    sup = _supervisor(injector=inj,
                      breaker=CircuitBreaker(failure_threshold=3))
    auto2 = TurboCommitter(backend="auto", min_tier=64, supervisor=sup)
    got2 = auto2.commit_hashed_many(jobs)
    assert [r.root for r in got2] == want
    assert sup.failovers == 1
    assert inj.wedged == 1
    assert sup.breaker.state == CLOSED     # one trip < threshold


def test_open_breaker_routes_commits_to_cpu_without_failover():
    sup = _supervisor(breaker=CircuitBreaker(failure_threshold=1,
                                             reset_timeout=300.0))
    sup.breaker.force_open()
    jobs = _jobs(13)
    want = [r.root for r in TurboCommitter(backend="numpy")
            .commit_hashed_many(jobs)]
    auto = TurboCommitter(backend="auto", min_tier=64, supervisor=sup)
    got = auto.commit_hashed_many(jobs)
    assert [r.root for r in got] == want
    assert sup.failovers == 0              # routed, not failed over


def test_supervised_fused_committer_bucket_protocol():
    """TrieCommitter(fused=True) through the supervisor: the CPU twin's
    alloc_slot/dispatch_level replay must land the oracle root."""
    from reth_tpu.primitives.nibbles import unpack_nibbles

    rng = np.random.default_rng(3)
    keys = rng.integers(0, 256, size=(120, 32), dtype=np.uint8)
    keys = np.unique(keys.view("S32").ravel()).view(np.uint8).reshape(-1, 32)
    leaves = [(unpack_nibbles(k.tobytes()),
               rlp_encode(bytes(rng.integers(0, 256, size=1 + i % 50,
                                             dtype=np.uint8))))
              for i, k in enumerate(keys)]
    want = TrieCommitter(hasher=keccak256_batch_np).commit(leaves)
    sup = _supervisor(injector=FaultInjector(wedge_every=1),
                      breaker=CircuitBreaker(failure_threshold=100))
    fused = TrieCommitter(fused=True, min_tier=8, supervisor=sup)
    got = fused.commit(leaves)
    assert got.root == want.root
    assert got.branch_nodes == want.branch_nodes
    assert sup.failovers >= 1


# -- supervised hasher + EngineTree multi-block run --------------------------


def test_engine_tree_follows_chain_with_wedged_hasher():
    """EngineTree harness: with every device hash batch wedged, the node
    still validates a multi-block chain — every block's state root lands
    via the CPU fallback and the breaker opens."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.primitives import Account
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    alice, bob = Wallet(0xA11CE), Wallet(0xB0B)
    builder = ChainBuilder(
        {alice.address: Account(balance=10**21),
         bob.address: Account(balance=10**20)},
        committer=cpu,
    )
    for i in range(5):
        builder.build_block([alice.transfer(bob.address, 10**15 + i)])

    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=cpu)
    sup = _supervisor(
        injector=FaultInjector(wedge_every=1),
        breaker=CircuitBreaker(failure_threshold=2, reset_timeout=300.0))
    supervised = TrieCommitter(supervisor=sup)
    supervised.turbo_backend = "auto"
    tree = EngineTree(factory, committer=supervised, persistence_threshold=2)

    for blk in builder.blocks[1:]:
        st = tree.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        assert tree.on_forkchoice_updated(blk.hash).status is \
            PayloadStatusKind.VALID
    assert tree.overlay_provider().last_block_number() == 5
    assert sup.breaker.state == OPEN           # the wedges tripped it
    assert sup.dispatch_errors >= 2
    # a healthy probe at the next half-open window restores the device
    sup.injector.wedge_every = 0
    sup.breaker._open_until = 0.0              # fast-forward the cooldown
    assert sup.route() == "device"
    assert sup.breaker.state == CLOSED


def test_supervised_hasher_matches_cpu_hasher():
    msgs = [bytes([i]) * (1 + i % 200) for i in range(64)]
    want = keccak256_batch_np(msgs)
    wedged = SupervisedHasher(
        _supervisor(injector=FaultInjector(wedge_every=1),
                    breaker=CircuitBreaker(failure_threshold=10)))
    assert list(wedged(msgs)) == list(want)
    healthy = SupervisedHasher(_supervisor())
    assert [bytes(d) for d in healthy(msgs)] == [bytes(d) for d in want]


# -- observability -----------------------------------------------------------


def test_supervisor_metrics_and_snapshot():
    reg = MetricsRegistry()
    sup = _supervisor(registry=reg,
                      injector=FaultInjector(wedge_every=1),
                      breaker=CircuitBreaker(failure_threshold=1))
    with pytest.raises(DeviceDispatchError):
        sup.run_guarded(lambda: None)
    snap = sup.snapshot()
    assert snap["breaker"] == OPEN
    assert snap["trips"] == 1
    assert snap["fault_injection"] is True
    text = reg.render()
    assert "hasher_supervisor_breaker_state 2.0" in text
    assert "hasher_supervisor_breaker_trips_total 1.0" in text
    # probes feed the histogram
    sup.startup()
    assert "hasher_supervisor_probe_duration_seconds_count 1" in reg.render()


def test_trie_metrics_attribute_failover_to_numpy():
    from reth_tpu.metrics import trie_metrics

    sup = _supervisor(injector=FaultInjector(wedge_every=1),
                      breaker=CircuitBreaker(failure_threshold=100))
    auto = TurboCommitter(backend="auto", min_tier=64, supervisor=sup)
    auto.commit_hashed_many(_jobs(17))
    assert trie_metrics.last["backend"] == "numpy"  # the twin did the work
