"""Block-lifecycle observability: trace-context propagation, per-block
timelines, the flight recorder + fault-drill dumps, Chrome/OTLP span-file
validation, /metrics exposition-format checks, metrics thread safety, and
the tracing-disabled overhead guard.

Reference analogue: crates/tracing + crates/node/events — the reference
treats tracing as a first-class layer; these tests pin this repo's
equivalent end to end (ISSUE 6)."""

import json
import threading
import time

import pytest

from reth_tpu import tracing
from reth_tpu.metrics import (
    SUB_MS_BUCKETS,
    Counter,
    DeviceCompileTracker,
    Gauge,
    Histogram,
    HashServiceMetrics,
    MetricsRegistry,
)
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter


@pytest.fixture(autouse=True)
def _trace_env(tmp_path, monkeypatch):
    """Isolate tracing state per test: flight dumps under tmp, fault-dump
    rate limits cleared, exporters and the enable switch reset after."""
    monkeypatch.setenv("RETH_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    rec = tracing.flight_recorder()
    rec.directory = None
    rec.dumps.clear()
    tracing.reset_fault_dump_limits()
    tracing.set_trace_enabled(False)
    yield
    tracing.shutdown_block_tracing()
    tracing.set_trace_enabled(False)
    rec.directory = None


# -- satellite: metrics thread safety ----------------------------------------


def test_metrics_thread_safety_hammer():
    """Counter.increment / Gauge.set / Histogram.record are unsynchronized
    read-modify-writes no more: N threads x M operations lose nothing."""
    c = Counter("hammer_total")
    g = Gauge("hammer_gauge")
    h = Histogram("hammer_seconds", buckets=(0.5, 1.0))
    threads, per = 8, 5000

    def worker(i):
        for k in range(per):
            c.increment()
            g.set(float(k))
            h.record(0.25 if k % 2 == 0 else 0.75)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == threads * per
    assert h.n == threads * per
    assert h.counts[0] + h.counts[1] == threads * per  # no lost bucket inc
    assert h.total == pytest.approx(threads * per * 0.5)


def test_submillisecond_buckets():
    """Device-dispatch/service histograms resolve 50µs-1ms timings instead
    of dumping everything into a 1ms-floor first bucket."""
    assert SUB_MS_BUCKETS[0] == pytest.approx(5e-5)
    reg = MetricsRegistry()
    m = HashServiceMetrics(reg)
    m.record_dispatch(requests=1, msgs=4, occupancy=1.0,
                      service_s=2e-4, replayed=False)
    m.record_wait("live", 8e-5)
    svc = reg._metrics["hash_service_service_seconds"]
    assert svc.buckets[0] < 1e-4 < svc.buckets[-1]
    # a 200µs dispatch lands in a real bucket, not just +Inf
    idx = next(i for i, b in enumerate(svc.buckets) if 2e-4 <= b)
    assert sum(svc.counts[: idx + 1]) == 1
    wait = reg._metrics["hash_service_wait_seconds_live"]
    assert wait.counts[1] == 1  # 80µs <= 100µs bucket


# -- satellite: exposition-format validation ----------------------------------


def _parse_exposition(text: str):
    types, samples = {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            types[name] = kind
        else:
            name, _, value = line.rpartition(" ")
            samples[name] = float(value)
    return types, samples


def test_metrics_exposition_format():
    reg = MetricsRegistry()
    reg.counter("blocks_total", "help").increment(3)
    reg.gauge("head").set(9)
    h = reg.histogram("lat_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 5.0):
        h.record(v)
    text = reg.render()
    types, samples = _parse_exposition(text)
    assert types == {"blocks_total": "counter", "head": "gauge",
                     "lat_seconds": "histogram"}
    # cumulative le buckets, nondecreasing, +Inf == _count, _sum present
    les = [k for k in samples if k.startswith('lat_seconds_bucket{le="')
           and "+Inf" not in k]
    counts = [samples[k] for k in les]
    assert counts == sorted(counts) == [1, 2, 3]
    assert samples['lat_seconds_bucket{le="+Inf"}'] == samples["lat_seconds_count"] == 4
    assert samples["lat_seconds_sum"] == pytest.approx(5.0555)


def test_global_metrics_exposition_valid():
    """The real /metrics surface (every registered subsystem) stays
    format-valid: TYPE lines precede samples, histogram invariants hold."""
    from reth_tpu.metrics import REGISTRY, update_process_metrics

    update_process_metrics()
    text = REGISTRY.render()
    types, samples = _parse_exposition(text)
    for name, kind in types.items():
        if kind == "histogram":
            inf = samples[f'{name}_bucket{{le="+Inf"}}']
            assert inf == samples[f"{name}_count"]
            assert f"{name}_sum" in samples
            les = [v for k, v in samples.items()
                   if k.startswith(f'{name}_bucket{{le="') and "+Inf" not in k]
            assert les == sorted(les)  # cumulative
        else:
            # labeled gauges (the *_info convention, e.g. build_info)
            # render as name{k="v"} value under a bare TYPE line
            assert name in samples or any(
                k.startswith(name + "{") for k in samples)


# -- trace context ------------------------------------------------------------


def test_span_context_propagation():
    tracing.set_trace_enabled(True)
    rec = tracing.flight_recorder()
    before = rec.recorded
    with tracing.trace_block("aa" * 32, number=1) as root:
        assert root.trace_id == "aa" * 32
        with tracing.span("t", "child") as c1:
            assert c1.trace_id == "aa" * 32
            captured = tracing.current_context()

            # explicit handoff into a worker thread
            def worker():
                with tracing.use_context(captured):
                    with tracing.span("t", "grandchild"):
                        pass

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        tracing.record_span("t", "attributed", time.time() - 0.01, 0.01,
                            ctx=captured, fields={"wait_ms": 4.0})
    tl = tracing.block_timeline("aa" * 32)
    by_name = {r["name"]: r for r in tl}
    assert by_name["grandchild"]["parent"] == by_name["child"]["span"]
    assert by_name["attributed"]["parent"] == by_name["child"]["span"]
    assert by_name["child"]["parent"] == by_name["block"]["span"]
    assert by_name["block"]["parent"] is None
    assert all(r["trace"] == "aa" * 32 for r in tl)
    assert rec.recorded > before  # spans landed in the flight recorder
    assert tracing.block_summary("aa" * 32)["total_ms"] >= 0


def test_span_disabled_is_contextless():
    assert not tracing.trace_enabled()
    with tracing.span("t", "x") as ctx:
        assert ctx is None
        assert tracing.current_context() is None


# -- end-to-end: engine block timeline ----------------------------------------


def _make_traced_env(n_txs=6, with_service=False):
    from reth_tpu.engine import EngineTree
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    bob = Wallet(0xB0B)
    builder = ChainBuilder(
        {alice.address: Account(balance=10**21),
         bob.address: Account(balance=10**20)}, committer=cpu)
    builder.build_block([alice.transfer(bob.address, 10**15 + i)
                         for i in range(n_txs)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=cpu)
    svc = None
    committer = cpu
    if with_service:
        from reth_tpu.ops.hash_service import HashService

        committer = TrieCommitter(hasher=keccak256_batch_np)
        svc = HashService(backend=keccak256_batch_np,
                          registry=MetricsRegistry())
        committer.hash_service = svc
        committer.hasher = svc.client("live")
    tree = EngineTree(factory, committer=committer, persistence_threshold=2)
    return builder, tree, svc


def test_block_timeline_coverage_and_attribution():
    """Acceptance: tracing a block yields a timeline whose direct phase
    spans account for >=95% of the block's wall, with hash-service
    queue-wait vs dispatch attribution visible."""
    from reth_tpu.engine.tree import PayloadStatusKind

    tracing.set_trace_enabled(True)
    builder, tree, svc = _make_traced_env(n_txs=6, with_service=True)
    try:
        blk = builder.blocks[1]
        st = tree.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        trace_id = blk.hash.hex()
        tl = tracing.block_timeline(trace_id)
        assert tl, "no timeline recorded"
        names = {r["name"] for r in tl}
        # the lifecycle phases are all present
        assert {"block", "validate", "prepare", "recover_senders",
                "execute", "state_root", "finalize"} <= names
        assert "prewarm" in names  # 6 txs >= prewarm threshold
        # hash-service attribution: per-request queue-wait vs dispatch
        reqs = [r for r in tl if r["name"] == "hashsvc.request"]
        assert reqs, "no hash-service request spans in the block timeline"
        for r in reqs:
            assert "wait_ms" in r["fields"] and "service_ms" in r["fields"]
        summary = tracing.block_summary(trace_id)
        assert summary["coverage"] >= 0.95, summary
        assert summary["total_ms"] > 0
        assert summary["exec_ms"] > 0 and summary["root_ms"] > 0
        # parent ids resolve within the timeline
        ids = {r["span"] for r in tl if r["span"] is not None}
        root_id = next(r["span"] for r in tl if r["parent"] is None
                       and r["kind"] == "span")
        for r in tl:
            if r["parent"] is not None:
                assert r["parent"] in ids
        # nesting monotonic: every direct child sits inside the root span
        root = next(r for r in tl if r["span"] == root_id)
        lo, hi = root["ts"], root["ts"] + root["dur_ms"] / 1e3
        for r in tl:
            if r["kind"] == "span" and r["parent"] == root_id:
                assert r["ts"] >= lo - 0.002
                assert r["ts"] + r["dur_ms"] / 1e3 <= hi + 0.002
    finally:
        if svc is not None:
            svc.stop()


def test_chrome_and_otlp_span_files(tmp_path):
    """Exporter files: valid JSON lines, parent ids resolve, children
    nest inside their parents."""
    from reth_tpu.engine.tree import PayloadStatusKind

    chrome = tmp_path / "blocks.trace.json"
    otlp = tmp_path / "spans.otlp.jsonl"
    tracing.init_block_tracing(chrome_path=chrome, otlp_path=otlp)
    builder, tree, _ = _make_traced_env(n_txs=5)
    st = tree.on_new_payload(builder.blocks[1])
    assert st.status is PayloadStatusKind.VALID
    tracing.shutdown_block_tracing()

    # chrome file: strictly valid JSON array once closed, AND one event
    # per line for the JSONL view
    events = json.loads(chrome.read_text())
    assert tracing.read_chrome_trace(chrome) == events
    spans = [e for e in events if e.get("ph") == "X"]
    assert spans
    by_id = {e["args"]["span_id"]: e for e in spans if "span_id" in e["args"]}
    root = next(e for e in spans if e["name"] == "block")
    checked = 0
    for e in spans:
        pid = e["args"].get("parent_id")
        if pid is None:
            continue
        assert pid in by_id, f"dangling parent {pid}"
        # nesting monotonic for the block's phase spans (µs timestamps;
        # small slack — worker-attributed spans overlap phases by design)
        if pid == root["args"]["span_id"]:
            assert e["ts"] >= root["ts"] - 2e3
            assert (e["ts"] + e.get("dur", 0)
                    <= root["ts"] + root.get("dur", 0) + 2e3)
            checked += 1
    assert checked > 3

    # OTLP file: one valid JSON object per line, ids resolve
    lines = [json.loads(line) for line in otlp.read_text().splitlines()]
    assert lines
    osp = [line["scopeSpans"][0]["spans"][0] for line in lines]
    ids = {s["spanId"] for s in osp if "spanId" in s}
    for s in osp:
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
        if "parentSpanId" in s:
            assert s["parentSpanId"] in ids
    assert any("traceId" in s for s in osp)


# -- flight recorder + fault drills -------------------------------------------


def test_flight_recorder_dump_roundtrip(tmp_path):
    tracing.set_trace_enabled(True)
    with tracing.span("t", "work", leaves=3):
        tracing.event("t", "checkpoint", at="mid")
    path = tracing.flight_dump("unit_test", tmp_path / "dump.jsonl")
    header, records = tracing.load_flight_dump(path)
    assert header["reason"] == "unit_test" and header["records"] == len(records)
    names = [r["name"] for r in records]
    assert "work" in names and "checkpoint" in names


def test_service_wedge_drill_dumps_flight_recorder():
    """Acceptance: a RETH_TPU_FAULT_SERVICE_WEDGE drill emits a JSONL
    dump a test can parse to locate the failing dispatch."""
    from reth_tpu.ops.hash_service import HashService, ServiceFaultInjector

    svc = HashService(backend=keccak256_batch_np,
                      injector=ServiceFaultInjector(wedge_every=1),
                      registry=MetricsRegistry())
    try:
        out = svc.hash("live", [b"abc"])  # completes via numpy-twin replay
        assert out == keccak256_batch_np([b"abc"])
        assert svc.replays == 1
    finally:
        svc.stop()
    dumps = tracing.flight_recorder().dumps
    assert dumps, "wedge drill wrote no flight dump"
    header, records = tracing.load_flight_dump(dumps[-1])
    assert "SERVICE_WEDGE" in header["reason"]
    fault = next(r for r in records
                 if r["name"] == "RETH_TPU_FAULT_SERVICE_WEDGE_EVERY")
    assert fault["target"] == "ops::hash_service"
    assert fault["fields"]["dispatch"] == 1


def test_gateway_stall_drill_dumps_flight_recorder():
    from reth_tpu.rpc.gateway import GatewayFaultInjector, RpcGateway

    gw = RpcGateway(head_supplier=lambda: b"h",
                    injector=GatewayFaultInjector(stall=0.001),
                    registry=MetricsRegistry())
    assert gw.call("eth_blockNumber", [], lambda: "0x1") == "0x1"
    dumps = tracing.flight_recorder().dumps
    assert dumps
    header, records = tracing.load_flight_dump(dumps[-1])
    assert "GATEWAY_STALL" in header["reason"]
    assert any(r["name"] == "RETH_TPU_FAULT_GATEWAY_STALL"
               and r["target"] == "rpc::gateway" for r in records)


def test_breaker_open_dumps_flight_recorder():
    from reth_tpu.ops.supervisor import CircuitBreaker

    br = CircuitBreaker(failure_threshold=1)
    assert br.record_failure()  # opens
    dumps = tracing.flight_recorder().dumps
    assert dumps
    header, records = tracing.load_flight_dump(dumps[-1])
    assert header["reason"] == "breaker_open"
    ev = next(r for r in records if r["name"] == "breaker_open")
    assert ev["fields"]["state"] == "open"


def test_sparse_abort_drill_dumps():
    from reth_tpu.trie.sparse import (
        InjectedSparseAbort,
        ParallelSparseCommitter,
        SparseFaultInjector,
        SparseTrie,
    )

    t = SparseTrie()
    t.update(b"\x11" * 32, b"v1")
    committer = ParallelSparseCommitter(
        workers=1, injector=SparseFaultInjector(abort_at=1))
    with pytest.raises(InjectedSparseAbort):
        committer.commit([t], keccak256_batch_np)
    dumps = tracing.flight_recorder().dumps
    assert dumps and "SPARSE_ABORT" in dumps[-1]


# -- debug RPCs ---------------------------------------------------------------


def test_debug_rpc_methods():
    from reth_tpu.rpc.debug import DebugApi
    from reth_tpu.rpc.server import RpcError

    api = DebugApi(None)  # tracing surfaces need no eth backend
    with pytest.raises(RpcError):
        api.debug_blockTimeline("0x" + "ee" * 32)  # tracing disabled

    tracing.set_trace_enabled(True)
    with tracing.trace_block("cd" * 32, number=12):
        with tracing.span("t", "phase"):
            pass
    out = api.debug_blockTimeline("0x" + "cd" * 32)
    assert out["traceId"] == "cd" * 32
    assert out["summary"]["number"] == 12
    assert any(r["name"] == "phase" for r in out["spans"])
    # None = most recent trace
    assert api.debug_blockTimeline(None)["traceId"] == "cd" * 32
    with pytest.raises(RpcError):
        api.debug_blockTimeline("0x" + "00" * 32)

    fr = api.debug_flightRecorder()
    assert fr["recorded"] >= 1 and fr["records"]
    dumped = api.debug_flightRecorder("dump")
    assert dumped["path"] and dumped["path"] in dumped["dumps"]
    header, _ = tracing.load_flight_dump(dumped["path"])
    assert header["reason"] == "rpc_request"
    with pytest.raises(RpcError):
        api.debug_flightRecorder("bogus")


def test_events_dashboard_wall_budget_line():
    from types import SimpleNamespace

    from reth_tpu.node.events import NodeEventReporter

    tracing.set_trace_enabled(True)
    with tracing.trace_block("ab" * 32, number=7):
        with tracing.span("engine::prewarm", "prewarm"):
            pass
        with tracing.span("engine::execute", "execute"):
            pass
        with tracing.span("engine::tree", "state_root"):
            pass
    s = tracing.last_block_summary()
    assert s is not None and s["number"] == 7
    budget = tracing.format_wall_budget(s)
    assert budget.startswith("block 7 total=")
    assert "prewarm" in budget and "dispatch" in budget

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=cpu)
    builder.build_block([alice.transfer(b"\x0b" * 20, 5)])
    rep = NodeEventReporter(SimpleNamespace(pool=None, network=None),
                            interval=999)
    rep.on_canon_change([SimpleNamespace(block=builder.blocks[1])])
    line = rep.report_once()
    assert "block 7 total=" in line


# -- compile tracker ----------------------------------------------------------


def test_compile_tracker_splits_first_call():
    reg = MetricsRegistry()
    tr = DeviceCompileTracker(reg)
    assert tr.record("keccak.exact", (1, 1024), 0.5) is True  # compile
    assert tr.record("keccak.exact", (1, 1024), 0.001) is False
    assert tr.record("keccak.exact", (2, 1024), 0.3) is True  # new shape
    t = tr.totals()
    assert t["shapes"] == 2
    assert t["compile_wall_s"] == pytest.approx(0.8)
    assert t["execute_wall_s"] == pytest.approx(0.001)
    assert reg._metrics["keccak_compile_total"].value == 2
    assert reg._metrics["keccak_dispatch_total"].value == 1


def test_keccak_device_reports_shapes():
    jax = pytest.importorskip("jax")  # noqa: F841
    from reth_tpu.metrics import compile_tracker
    from reth_tpu.ops.keccak_jax import KeccakDevice

    # the tracker is process-global: earlier tests may already have
    # compiled these shapes, so assert on deltas (new shape OR new
    # steady-state calls), not on absolute shape counts
    before = compile_tracker.totals()
    dev = KeccakDevice(min_tier=8)
    out = dev.hash_batch([b"x" * 5, b"y" * 200])
    assert out == keccak256_batch_np([b"x" * 5, b"y" * 200])
    after = compile_tracker.totals()
    assert (after["shapes"] > before["shapes"]
            or after["execute_calls"] > before["execute_calls"])


# -- overhead guard -----------------------------------------------------------


def _sparse_workload(n_tries=24, slots=24, dirty=6, seed=5):
    import numpy as np

    from reth_tpu.trie.sparse import SparseStateTrie

    rng = np.random.default_rng(seed)
    st = SparseStateTrie()
    for _ in range(n_tries):
        ha = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        t = st.storage_trie(ha)
        for _ in range(slots):
            t.update(bytes(rng.integers(0, 256, 32, dtype=np.uint8)),
                     bytes(rng.integers(1, 256, 8, dtype=np.uint8)))
        st.update_account(ha, b"leaf-" + ha)
    return st


def test_tracing_disabled_overhead_guard():
    """Satellite: with tracing off, the instrumentation's cost (span
    count x per-span disabled cost) stays under 1% of the sparse-commit
    wall — the hot path pays for observability only when asked to."""
    from reth_tpu.trie.sparse import ParallelSparseCommitter

    # (1) wall of the instrumented workload with tracing disabled
    assert not tracing.trace_enabled()
    st = _sparse_workload()
    committer = ParallelSparseCommitter(workers=2)
    t0 = time.perf_counter()
    st.root(keccak256_batch_np, committer=committer)
    wall = time.perf_counter() - t0
    committer.shutdown()

    # (2) spans the same workload emits when tracing is ON
    tracing.set_trace_enabled(True)
    rec = tracing.flight_recorder()
    before = rec.recorded
    st2 = _sparse_workload()
    committer2 = ParallelSparseCommitter(workers=2)
    st2.root(keccak256_batch_np, committer=committer2)
    committer2.shutdown()
    n_spans = rec.recorded - before
    tracing.set_trace_enabled(False)

    # (3) per-span cost with tracing disabled
    reps = 20_000
    t0 = time.perf_counter()
    for _ in range(reps):
        with tracing.span("trie::sparse", "overhead.probe"):
            pass
    per_span = (time.perf_counter() - t0) / reps

    overhead = n_spans * per_span
    assert overhead < 0.01 * wall, (
        f"disabled tracing would cost {overhead * 1e3:.3f}ms on a "
        f"{wall * 1e3:.1f}ms commit ({n_spans} spans x "
        f"{per_span * 1e6:.2f}µs)")


# -- bench: device-unavailable reporting --------------------------------------


@pytest.mark.slow
def test_bench_device_unavailable_exits_zero_with_flight_excerpt(tmp_path):
    """Satellite: a wedged/absent tunnel yields rc=0, a backend field,
    the compile/steady split, and a flight-recorder excerpt."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           # the probe-timeout path lives in the rebuild mode (the
           # default has been the tunnel-free exec bench since PR 7)
           "RETH_TPU_BENCH_MODE": "rebuild",
           "RETH_TPU_FAULT_PROBE_FAIL": "-1",  # every probe fails
           "RETH_TPU_PROBE_ATTEMPTS": "1", "RETH_TPU_PROBE_GAP": "0",
           "RETH_TPU_BENCH_ACCOUNTS": "1500", "RETH_TPU_BENCH_SLOTS": "400",
           "RETH_TPU_BENCH_TIMEOUT": "300",
           "RETH_TPU_BENCH_BASELINE_STORE": str(tmp_path / "baselines.json"),
           "RETH_TPU_FLIGHT_DIR": str(tmp_path)}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    root = Path(__file__).resolve().parents[1]
    r = subprocess.run([sys.executable, str(root / "bench.py")],
                       capture_output=True, text=True, timeout=280,
                       cwd=root, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["backend"] == "numpy"
    assert line["value"] > 0
    assert "device_unavailable" in line
    assert "compile_wall_s" in line
    excerpt = line["flight_recorder"]
    assert excerpt and any(
        rec["name"] == "RETH_TPU_FAULT_PROBE_FAIL"
        or (rec["name"] == "probe" and not rec["fields"].get("ok", True))
        for rec in excerpt)
