"""Native batched secp256k1 recovery: parity with the pure-Python path."""

from __future__ import annotations

import os
import time

import pytest

from reth_tpu.primitives import secp256k1
from reth_tpu.primitives.keccak import keccak256
from reth_tpu.primitives.secp256k1 import (
    N,
    ecrecover,
    ecrecover_batch,
    pubkey_from_priv,
    sign,
)


@pytest.fixture(scope="module")
def signed_batch():
    items = []
    expected = []
    for i in range(120):
        priv = int.from_bytes(keccak256(bytes([i]) * 4), "big") % N or 1
        h = keccak256(b"message %d" % i)
        y, r, s = sign(h, priv)
        items.append((h, y, r, s))
        expected.append(secp256k1.address_from_priv(priv))
    return items, expected


def test_native_batch_matches_python(signed_batch):
    items, expected = signed_batch
    assert secp256k1._native_lib() is not None, "native secp did not build"
    got = ecrecover_batch(items)
    assert got == expected
    # and matches the per-signature python path exactly
    for item, addr in zip(items[:10], expected[:10]):
        assert ecrecover(item[0], item[1], item[2], item[3]) == addr


def test_batch_flags_invalid_signatures(signed_batch):
    items, expected = signed_batch
    h, y, r, s = items[0]
    bad = [
        (h, y, 0, s),                  # r out of range
        (h, y, r, N),                  # s out of range
        (h, y, r, N - 1),              # high-s (EIP-2)
        (h, y ^ 1, r, s),              # wrong parity -> wrong address
        items[1],
    ]
    got = ecrecover_batch(bad)
    assert got[0] is None and got[1] is None and got[2] is None
    assert got[3] is not None and got[3] != expected[0]
    assert got[4] == expected[1]


def test_high_s_allowed_for_precompile_semantics(signed_batch):
    items, expected = signed_batch
    h, y, r, s = items[0]
    high_s = N - s
    got = ecrecover_batch([(h, y ^ 1, r, high_s)], allow_high_s=True)
    assert got[0] == expected[0]  # flipped parity + mirrored s: same key


def test_nonsense_r_not_on_curve():
    # an x with no curve point: find one by trial
    h = keccak256(b"m")
    for cand in range(2, 40):
        got = ecrecover_batch([(h, 0, cand, 5)])
        py = None
        try:
            py = ecrecover(h, 0, cand, 5)
        except ValueError:
            pass
        assert got[0] == py  # both paths agree, valid or not


def test_native_is_much_faster(signed_batch):
    items, _ = signed_batch
    if secp256k1._native_lib() is None:
        pytest.skip("no native build")
    t0 = time.time()
    ecrecover_batch(items)
    dt_native = time.time() - t0
    t0 = time.time()
    for h, y, r, s in items[:12]:
        ecrecover(h, y, r, s)
    dt_py = (time.time() - t0) * 10  # scale to 120
    assert dt_native < dt_py / 5, (dt_native, dt_py)
