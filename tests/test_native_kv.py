"""Native C++ KV engine: persistence (WAL replay + snapshot) and scale."""

import pytest


def native_db(path=None):
    from reth_tpu.storage.native import NativeDb

    try:
        return NativeDb(path)
    except Exception as e:
        pytest.skip(f"native backend unavailable: {e}")


def test_wal_persistence_roundtrip(tmp_path):
    d = tmp_path / "kv"
    db = native_db(d)
    with db.tx_mut() as tx:
        tx.put("t", b"k1", b"v1")
        tx.put("d", b"k", b"b", dupsort=True)
        tx.put("d", b"k", b"a", dupsort=True)
    with db.tx_mut() as tx:
        tx.put("t", b"k2", b"v2")
        tx.delete("d", b"k", b"b")
    db.close()
    # reopen: state comes from WAL replay
    db2 = native_db(d)
    assert db2.tx().get("t", b"k1") == b"v1"
    assert db2.tx().get("t", b"k2") == b"v2"
    assert db2.tx().get_dups("d", b"k") == [b"a"]
    db2.close()


def test_uncommitted_wal_tail_dropped(tmp_path):
    """Abort writes nothing: reopen sees only committed batches."""
    d = tmp_path / "kv"
    db = native_db(d)
    with db.tx_mut() as tx:
        tx.put("t", b"committed", b"1")
    tx = db.tx_mut()
    tx.put("t", b"aborted", b"2")
    tx.abort()
    db.close()
    db2 = native_db(d)
    assert db2.tx().get("t", b"committed") == b"1"
    assert db2.tx().get("t", b"aborted") is None
    db2.close()


def test_snapshot_compaction(tmp_path):
    d = tmp_path / "kv"
    db = native_db(d)
    for i in range(50):
        with db.tx_mut() as tx:
            tx.put("t", bytes([i]), bytes([i]) * 3)
    db.flush()  # snapshot + truncate WAL
    with db.tx_mut() as tx:
        tx.put("t", b"\xff", b"post-snapshot")
    db.close()
    db2 = native_db(d)
    assert db2.tx().get("t", b"\x07") == b"\x07" * 3
    assert db2.tx().get("t", b"\xff") == b"post-snapshot"
    assert db2.tx().entry_count("t") == 51
    db2.close()


def test_pipeline_e2e_on_native_backend(tmp_path):
    """The full staged sync runs unchanged over the C++ engine."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage import ProviderFactory
    from reth_tpu.storage.genesis import import_chain, init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(3):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])

    factory = ProviderFactory(native_db(tmp_path / "node"))
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(3)
    p = factory.provider()
    assert p.stage_checkpoint("Finish") == 3
    assert p.header_by_number(3).state_root == builder.blocks[3].header.state_root
    assert p.account(b"\x0b" * 20).balance == 303
