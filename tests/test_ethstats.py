"""ethstats reporting against an in-process dashboard server.

Reference analogue: crates/node/ethstats service tests — hello login,
node-ping/node-pong, block + stats emits over WebSocket.
"""

import json
import socket
import threading
import time

import pytest

from reth_tpu.ethstats import EthStatsService, parse_ethstats_url, _send_masked
from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.rpc.ws import accept_handshake, read_frame, write_frame
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


class FakeDashboard:
    """Minimal ethstats server: records emits, can ping the node."""

    def __init__(self):
        self.listener = socket.create_server(("127.0.0.1", 0))
        self.port = self.listener.getsockname()[1]
        self.received = []
        self.conn = None
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        try:
            sock, _ = self.listener.accept()
        except OSError:
            return
        accept_handshake(sock)
        self.conn = sock
        while True:
            try:
                op, fin, payload = read_frame(sock)
            except Exception:
                return
            if op == 0x1:
                self.received.append(json.loads(payload))

    def ping(self):
        write_frame(self.conn, 0x1, json.dumps(
            {"emit": ["node-ping", {}]}).encode())

    def topics(self):
        return [m["emit"][0] for m in self.received]

    def close(self):
        self.listener.close()
        if self.conn:
            self.conn.close()


def test_parse_url():
    assert parse_ethstats_url("mynode:s3cret@stats.example.org:3000") == (
        "mynode", "s3cret", "stats.example.org", 3000)
    assert parse_ethstats_url("n:@host")[3] == 3000
    with pytest.raises(ValueError):
        parse_ethstats_url("nohost")


def test_hello_stats_block_and_pong():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    node = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                           genesis_alloc=builder.accounts_at_genesis),
                committer=CPU)
    dash = FakeDashboard()
    svc = EthStatsService(f"test:sec@127.0.0.1:{dash.port}", node, interval=0.2)
    try:
        svc.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not (
                "stats" in dash.topics() and "pending" in dash.topics()):
            time.sleep(0.05)
        assert dash.topics()[0] == "hello"
        hello = dash.received[0]["emit"][1]
        assert hello["id"] == "test" and hello["secret"] == "sec"
        assert "stats" in dash.topics() and "pending" in dash.topics()
        # mining a block triggers a block report via the canon listener
        node.pool.add_transaction(alice.transfer(b"\x0b" * 20, 5))
        node.miner.mine_block()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and "block" not in dash.topics():
            time.sleep(0.05)
        blocks = [m["emit"][1] for m in dash.received if m["emit"][0] == "block"]
        assert blocks and blocks[-1]["block"]["number"] >= 0
        # ping -> pong
        dash.ping()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and "node-pong" not in dash.topics():
            time.sleep(0.05)
        assert "node-pong" in dash.topics()
    finally:
        svc.stop()
        dash.close()
        node.stop()
