"""Consensus-robustness tests: the engine tree under adversarial CL
behavior.

Reference analogue: the BlockBuffer / InvalidHeaderCache unit tests
(crates/engine/tree/src/tree/block_buffer.rs tests,
invalid_headers.rs) and the engine-tree reorg tests (tree/tests.rs).
Fast invariants only — the composed reorg-storm campaigns live in
tests/test_chaos.py (`make test-chaos`); this file is `make test-reorg`.
"""

from __future__ import annotations

import threading
from types import SimpleNamespace

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.block_buffer import (
    BlockBuffer,
    InvalidHeaderCache,
    ReorgTracker,
)
from reth_tpu.engine.tree import PayloadStatusKind
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.testing_actions import TestSuite as Suite
from reth_tpu.testing_actions import ForkBuilder, tampered_block
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def make_env(n_blocks=5, threshold=2, extra_accounts=0):
    alice = Wallet(0xA11CE)
    bob = Wallet(0xB0B)
    alloc = {alice.address: Account(balance=10**21),
             bob.address: Account(balance=10**20)}
    for i in range(1, extra_accounts + 1):
        alloc[i.to_bytes(20, "big")] = Account(balance=i)
    builder = ChainBuilder(alloc, committer=CPU)
    for i in range(n_blocks):
        builder.build_block([alice.transfer(bob.address, 10**15 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    tree = EngineTree(factory, committer=CPU,
                      persistence_threshold=threshold)
    return builder, factory, tree, alice, bob


# -- BlockBuffer / InvalidHeaderCache / ReorgTracker units --------------------


def _b(h: bytes, parent: bytes, number: int = 1):
    return SimpleNamespace(hash=h, header=SimpleNamespace(
        parent_hash=parent, number=number))


def test_block_buffer_bound_evicts_lru():
    buf = BlockBuffer(limit=4, ttl=0)
    blocks = [_b(bytes([i]) * 32, bytes([100 + i]) * 32) for i in range(6)]
    for blk in blocks:
        buf.insert(blk)
    assert len(buf) == 4
    assert buf.get(blocks[0].hash) is None  # oldest two evicted
    assert buf.get(blocks[1].hash) is None
    assert buf.get(blocks[5].hash) is blocks[5]
    assert buf.evicted == 2
    # re-inserting refreshes LRU position: touch #2, insert one more,
    # #3 (now least recent) is the victim
    buf.insert(blocks[2])
    buf.insert(_b(b"\x77" * 32, b"\x78" * 32))
    assert buf.get(blocks[2].hash) is not None
    assert buf.get(blocks[3].hash) is None


def test_block_buffer_ttl_eviction():
    now = [0.0]
    buf = BlockBuffer(limit=16, ttl=5.0, clock=lambda: now[0])
    a = _b(b"\x01" * 32, b"\xaa" * 32)
    buf.insert(a)
    now[0] = 3.0
    b = _b(b"\x02" * 32, b"\xaa" * 32)
    buf.insert(b)
    assert len(buf) == 2
    now[0] = 6.0  # a expired, b not
    buf.evict_expired()
    assert buf.get(a.hash) is None
    assert buf.get(b.hash) is b


def test_block_buffer_take_children():
    buf = BlockBuffer(limit=16, ttl=0)
    parent = b"\xaa" * 32
    kids = [_b(bytes([i]) * 32, parent) for i in range(3)]
    other = _b(b"\x0f" * 32, b"\xbb" * 32)
    for blk in kids + [other]:
        buf.insert(blk)
    taken = buf.take_children_of(parent)
    assert {t.hash for t in taken} == {k.hash for k in kids}
    assert len(buf) == 1  # only the unrelated orphan remains
    assert buf.take_children_of(parent) == []


def test_invalid_cache_lru_bound_and_touch():
    cache = InvalidHeaderCache(capacity=3)
    for i in range(5):
        cache[bytes([i]) * 32] = f"bad {i}"
    assert len(cache) == 3
    assert bytes([0]) * 32 not in cache
    assert cache[bytes([4]) * 32] == "bad 4"
    assert cache.evicted == 2
    # touching an entry protects it from the next eviction
    assert bytes([2]) * 32 in cache
    cache[b"\x50" * 32] = "bad new"
    assert bytes([2]) * 32 in cache
    assert cache.get(bytes([3]) * 32) is None


def test_reorg_tracker_storm_and_backoff():
    now = [0.0]
    tr = ReorgTracker(window_s=30.0, storm_count=4, storm_depth=100,
                      backoff_s=10.0, clock=lambda: now[0])
    assert not tr.record(1) and not tr.record(1) and not tr.record(1)
    assert not tr.in_backoff()
    assert tr.record(1) is True  # 4th within the window: storm
    assert tr.in_backoff()
    assert tr.record(1) is False  # still the same storm: extend, not new
    now[0] = 21.0  # base 10s doubled by the extension
    assert not tr.in_backoff()
    # quiet window: old events age out, no storm on the next reorg
    now[0] = 60.0
    assert tr.record(2) is False
    assert tr.storms == 1


# -- orphan buffering + replay (reference BlockBuffer behavior) ---------------


def test_unknown_parent_buffers_and_replays_children():
    builder, factory, tree, *_ = make_env(3)
    b1, b2, b3 = builder.blocks[1:4]
    # grandchild then child arrive first: SYNCING, buffered
    assert tree.on_new_payload(b3).status is PayloadStatusKind.SYNCING
    assert tree.on_new_payload(b2).status is PayloadStatusKind.SYNCING
    assert len(tree.buffered) == 2
    # the missing parent arrives: the whole buffered subtree replays
    assert tree.on_new_payload(b1).status is PayloadStatusKind.VALID
    assert b2.hash in tree.blocks and b3.hash in tree.blocks
    assert len(tree.buffered) == 0
    st = tree.on_forkchoice_updated(b3.hash)
    assert st.status is PayloadStatusKind.VALID


def test_invalid_parent_propagates_into_buffer():
    builder, factory, tree, *_ = make_env(2)
    b1, b2 = builder.blocks[1:3]
    bad = tampered_block(b1, "state_root")
    child = tampered_block(b2, "reparent", salt=bad.hash)
    # the child arrives before its (soon-to-be-invalid) parent
    assert tree.on_new_payload(child).status is PayloadStatusKind.SYNCING
    assert tree.on_new_payload(bad).status is PayloadStatusKind.INVALID
    # buffered child was invalidated with its ancestor, not replayed
    assert child.hash in tree.invalid
    st = tree.on_new_payload(child)
    assert st.status is PayloadStatusKind.INVALID
    assert "invalid ancestor" in st.validation_error


# -- invalid-payload flood (acceptance drill) ---------------------------------


@pytest.mark.slow  # ~1 min of pure-python header hashing; `make test-reorg`
def test_invalid_flood_holds_cache_bound_and_node_keeps_importing():
    """Acceptance drill: 10k distinct invalid payloads — tree_invalid_cached
    plateaus at the configured bound and valid blocks still import
    afterwards. (The fast bound test below covers tier-1.)"""
    from reth_tpu.metrics import tree_metrics

    builder, factory, tree, *_ = make_env(2)
    b1, b2 = builder.blocks[1:3]
    assert tree.on_new_payload(b1).status is PayloadStatusKind.VALID
    bad = tampered_block(b2, "state_root")
    assert tree.on_new_payload(bad).status is PayloadStatusKind.INVALID
    for i in range(10_000):
        child = tampered_block(b2, "reparent",
                               salt=bad.hash + i.to_bytes(4, "big"))
        st = tree.on_new_payload(child)
        assert st.status is PayloadStatusKind.INVALID
    assert len(tree.invalid) <= tree.invalid.capacity == 512
    assert tree_metrics.last["invalid"] <= 512
    assert tree.invalid.evicted > 9_000
    # the flood changed nothing for honest traffic
    assert tree.on_new_payload(b2).status is PayloadStatusKind.VALID
    assert tree.on_forkchoice_updated(b2.hash).status is PayloadStatusKind.VALID


def test_invalid_cache_size_is_configurable_and_flood_bounded():
    """Fast flood-bound variant for tier-1: a 200-payload flood against a
    7-entry cache plateaus at the bound and honest imports continue."""
    builder, factory, *_ = make_env(1)
    tree = EngineTree(factory, committer=CPU, invalid_cache_size=7)
    b1 = builder.blocks[1]
    bad = tampered_block(b1, "state_root")
    assert tree.on_new_payload(bad).status is PayloadStatusKind.INVALID
    for i in range(60):
        child = tampered_block(b1, "reparent",
                               salt=bad.hash + i.to_bytes(4, "big"))
        assert tree.on_new_payload(child).status is PayloadStatusKind.INVALID
    assert len(tree.invalid) <= 7
    assert tree.invalid.evicted >= 50
    assert tree.on_new_payload(b1).status is PayloadStatusKind.VALID


# -- fcU cancellation of in-flight inserts (satellite regression) -------------


def _sibling_forks(extra_accounts=8):
    """Two competing height-1 blocks over one genesis, plus a child of
    fork A — the minimal reorg-away shape."""
    alice = Wallet(0xA11CE)
    alloc = {alice.address: Account(balance=10**21)}
    for i in range(1, extra_accounts + 1):
        alloc[i.to_bytes(20, "big")] = Account(balance=i)
    builder = ChainBuilder(alloc, committer=CPU)
    fork_a = builder.build_block([alice.transfer(b"\xaa" * 20, 111)])
    a_child = builder.build_block([alice.transfer(b"\xaa" * 20, 112)])

    alice_b = Wallet(0xA11CE)
    alloc_b = {alice_b.address: Account(balance=10**21)}
    for i in range(1, extra_accounts + 1):
        alloc_b[i.to_bytes(20, "big")] = Account(balance=i)
    builder_b = ChainBuilder(alloc_b, committer=CPU)
    fork_b = builder_b.build_block([alice_b.transfer(b"\xbb" * 20, 222)],
                                   timestamp=24)
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    return factory, fork_a, a_child, fork_b


def test_fcu_during_inflight_insert_aborts_sparse_root(monkeypatch):
    """A forkchoiceUpdated that reorgs away from an in-flight
    _validate_and_insert must abort the sparse root job via the
    journaled abort path (not race it to a fallback root), with the
    proof-worker wedge (RETH_TPU_FAULT_SPARSE_PROOF_WEDGE) held across
    the fcU. The insert reports SYNCING and the payload stays
    re-importable."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.metrics import tree_metrics

    factory, fork_a, a_child, fork_b = _sibling_forks()
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    assert tree.on_new_payload(fork_a).status is PayloadStatusKind.VALID
    assert tree.on_new_payload(fork_b).status is PayloadStatusKind.VALID
    assert tree.on_forkchoice_updated(fork_a.hash).status \
        is PayloadStatusKind.VALID

    # wedge every sharded proof fetch for the rest of the test — the
    # worker failure must not let the insert race into a fallback root
    monkeypatch.setenv("RETH_TPU_FAULT_SPARSE_PROOF_WEDGE", "1")
    started, release = threading.Event(), threading.Event()
    real = EthBeaconConsensus.validate_block_post_execution

    def paused(self, block, *a, **kw):
        if block.hash == a_child.hash:
            started.set()
            release.wait(10)
        return real(self, block, *a, **kw)

    monkeypatch.setattr(EthBeaconConsensus,
                        "validate_block_post_execution", paused)
    cancelled_before = tree_metrics.last.get("cancelled", 0)
    res: dict = {}
    th = threading.Thread(
        target=lambda: res.update(st=tree.on_new_payload(a_child)))
    th.start()
    assert started.wait(10), "insert never reached post_validate"
    # reorg away: fork_b abandons a_child's parent chain entirely
    assert tree.on_forkchoice_updated(fork_b.hash).status \
        is PayloadStatusKind.VALID
    with tree._inflight_lock:
        inflight = tree._inflight
    assert inflight is not None and inflight.cancel.is_set()
    task = inflight.sparse_task
    release.set()
    th.join(30)
    assert not th.is_alive()
    assert res["st"].status is PayloadStatusKind.SYNCING
    assert a_child.hash not in tree.blocks
    assert a_child.hash not in tree.invalid
    assert tree.last_sparse is None  # no fallback root was computed
    if task is not None:
        assert task.cancelled
        assert not task._thread.is_alive()
    assert tree_metrics.last.get("cancelled", 0) == cancelled_before + 1
    # the cancelled payload is NOT poisoned: with the fcU settled it
    # re-imports as a plain side-fork block (wedge still held: the
    # legitimate fallback path covers the root)
    monkeypatch.setattr(EthBeaconConsensus,
                        "validate_block_post_execution", real)
    assert tree.on_new_payload(a_child).status is PayloadStatusKind.VALID


def test_fcu_to_extending_head_does_not_cancel(monkeypatch):
    """An fcU that keeps the in-flight block's parent canonical (e.g. to
    the parent itself, or an unknown hash) must NOT abort the insert."""
    from reth_tpu.consensus import EthBeaconConsensus

    factory, fork_a, a_child, fork_b = _sibling_forks()
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    assert tree.on_new_payload(fork_a).status is PayloadStatusKind.VALID
    tree.on_forkchoice_updated(fork_a.hash)
    started, release = threading.Event(), threading.Event()
    real = EthBeaconConsensus.validate_block_post_execution

    def paused(self, block, *a, **kw):
        if block.hash == a_child.hash:
            started.set()
            release.wait(10)
        return real(self, block, *a, **kw)

    monkeypatch.setattr(EthBeaconConsensus,
                        "validate_block_post_execution", paused)
    res: dict = {}
    th = threading.Thread(
        target=lambda: res.update(st=tree.on_new_payload(a_child)))
    th.start()
    assert started.wait(10)
    # re-announcing the parent head and an unknown head: no reorg-away
    assert tree.on_forkchoice_updated(fork_a.hash).status \
        is PayloadStatusKind.VALID
    assert tree.on_forkchoice_updated(b"\x5f" * 32).status \
        is PayloadStatusKind.SYNCING
    release.set()
    th.join(30)
    assert res["st"].status is PayloadStatusKind.VALID
    assert a_child.hash in tree.blocks


# -- reorg-storm tracking + backoff -------------------------------------------


def test_reorg_storm_engages_backoff_and_disables_speculation():
    factory, fork_a, a_child, fork_b = _sibling_forks()
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    assert tree.on_new_payload(fork_a).status is PayloadStatusKind.VALID
    assert tree.on_new_payload(fork_b).status is PayloadStatusKind.VALID
    tree.on_forkchoice_updated(fork_a.hash)
    # a hostile CL flip-flops forkchoice between the two forks
    for _ in range(5):
        tree.on_forkchoice_updated(fork_b.hash)
        tree.on_forkchoice_updated(fork_a.hash)
    assert tree.reorgs.reorgs >= 10
    assert tree.reorgs.storms >= 1
    assert tree.reorgs.in_backoff()
    from reth_tpu.metrics import tree_metrics

    assert tree_metrics.last["backoff"] is True
    assert tree_metrics.last["storms"] >= 1
    # during backoff the next insert serves through the non-speculative
    # paths: no sparse task is started (last_sparse stays None), yet the
    # block is still VALID with a verified root
    st = tree.on_new_payload(a_child)
    assert st.status is PayloadStatusKind.VALID, st.validation_error
    assert tree.last_sparse is None


def test_deep_reorg_depth_is_recorded():
    builder, factory, tree, alice, bob = make_env(4, threshold=1)
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 3
    before = tree.reorgs.reorgs
    # competing fork branching at block 2 (below the persisted tip)
    alice_b = Wallet(0xA11CE)
    alloc = {alice_b.address: Account(balance=10**21),
             Wallet(0xB0B).address: Account(balance=10**20)}
    builder_b = ChainBuilder(alloc, committer=CPU)
    for i in range(2):
        builder_b.build_block([alice_b.transfer(Wallet(0xB0B).address,
                                                10**15 + i)])
    fork3 = builder_b.build_block([alice_b.transfer(b"\xbb" * 20, 999)],
                                  timestamp=100)
    assert tree.on_new_payload(fork3).status is PayloadStatusKind.SYNCING
    assert tree.on_forkchoice_updated(fork3.hash).status \
        is PayloadStatusKind.VALID
    assert tree.reorgs.reorgs > before
    assert tree.reorgs.max_depth >= 2  # blocks 3+4 abandoned


# -- fork builders (testing_actions) ------------------------------------------


def test_fork_builder_mints_valid_forks():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    fb = ForkBuilder(builder.genesis, builder.accounts_at_genesis,
                     wallet=Wallet(0xA11CE), committer=CPU)
    a = fb.block_on(fb.genesis_hash, txs=1)
    b = fb.block_on(a.hash, txs=1)
    c = fb.block_on(fb.genesis_hash, txs=1, salt=3)  # competing sibling
    assert len({a.hash, b.hash, c.hash}) == 3
    assert fb.number_of(b.hash) == 2
    assert fb.ancestor(b.hash, 2) == fb.genesis_hash
    assert fb.branch_point(b.hash, c.hash) == (0, fb.genesis_hash)
    # every minted block imports VALID on an independent node tree, and
    # the ProduceSideChain action reorgs that tree to a longer fork
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    for blk in (a, b, c):
        assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
        tree.on_forkchoice_updated(blk.hash)
    tree.on_forkchoice_updated(b.hash)
    from reth_tpu.testing_actions import ProduceSideChain

    node = SimpleNamespace(tree=tree)
    Suite(node).run(ProduceSideChain(fb, depth=1, length=2, salt=7))
    assert tree.blocks[tree.head_hash].block.header.number == 3


def test_tampered_blocks_are_rejected_by_kind():
    builder, factory, tree, *_ = make_env(2)
    b1, b2 = builder.blocks[1:3]
    assert tree.on_new_payload(b1).status is PayloadStatusKind.VALID
    for kind in ("state_root", "receipts_root", "gas_used", "gas_limit"):
        st = tree.on_new_payload(tampered_block(b2, kind))
        assert st.status is PayloadStatusKind.INVALID, kind
    orphan = tampered_block(b2, "unknown_parent", salt=b"\x09")
    assert tree.on_new_payload(orphan).status is PayloadStatusKind.SYNCING


