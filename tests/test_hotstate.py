"""Hot-state plane tests (ISSUE 19): the cross-block trie-node cache
(trie/hot_cache.py), the device-resident digest arena with delta uploads
(ops/fused_commit.py DigestArena + the arena finish in trie/sparse.py),
and the engine wiring (engine/sparse_root.py + engine/tree.py).

The acceptance drills:

- hash-keyed cache semantics: sibling forks' versions coexist at one
  (owner, path); canonical-write trims keep the fork-live versions; a
  wrong-hash lookup can never serve (staleness is structural);
- ``RETH_TPU_FAULT_HOTSTATE_POISON`` is CAUGHT by node-hash validation —
  a poisoned serve is a counted miss, never a reveal;
- randomized differential suite (10 seeds): cached reveals + arena delta
  finishes vs uncached proof-fed classic finishes over interleaved
  update/delete/wipe streams with sibling-collapse deletes and fork
  switches — roots bit-identical every round, verified against a
  from-scratch rebuild each round;
- arena drills: epoch eviction under a row budget, the fault ladder
  (mid-epoch engine fault -> evict -> SAME commit reruns on the classic
  full-upload rung), the evict-storm injector forcing every epoch onto
  the full-upload rung, and the no-leaked-rows invariant throughout;
- engine wiring: sibling-fork import through EngineTree(hot_state=True)
  serves reveals from the cache (fewer proof targets than the uncached
  twin on the same stream), the proof-pool dedupe does not double-fetch
  what the cache already unblinded, and deep-reorg stand-down clears
  both planes.
"""

from __future__ import annotations

import random

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.tree import PayloadStatusKind
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.rlp import rlp_encode
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter
from reth_tpu.trie.hot_cache import (
    ACCOUNT_OWNER,
    HotStateFaultInjector,
    TrieNodeCache,
)
from reth_tpu.trie.sparse import (
    BlindedNodeError,
    ParallelSparseCommitter,
    SparseTrie,
    _encode_rlp,
)

CPU = TrieCommitter(hasher=keccak256_batch_np)


def _arena(**kw):
    from reth_tpu.ops.fused_commit import DigestArena

    return DigestArena(**kw)


def _small_committer(arena=None) -> ParallelSparseCommitter:
    c = ParallelSparseCommitter(workers=1, arena=arena)
    # shrink the device batch floors so the tiny test tries still take
    # the fused/arena windows instead of padding to production tiers
    c.SUBTRIE_ROW_FLOOR = 8
    c.SUBTRIE_HOLE_FLOOR = 8
    return c


def _keys(n: int, salt: int = 0):
    return [keccak256(salt.to_bytes(4, "big") + i.to_bytes(8, "big"))
            for i in range(n)]


# -- TrieNodeCache unit behavior ---------------------------------------------


def test_cache_hash_keyed_versions_coexist():
    """Two forks' nodes at the SAME (owner, path) both serve — the node
    hash is part of the key, so absorbing one fork never evicts the
    sibling's live spine (the thrash a path-keyed map would have)."""
    cache = TrieNodeCache(injector=None)
    a, b = b"\xaa" * 40, b"\xbb" * 41
    cache.put(ACCOUNT_OWNER, b"\x01", a)
    cache.put(ACCOUNT_OWNER, b"\x01", b)
    assert cache.lookup(ACCOUNT_OWNER, b"\x01", keccak256(a)) == a
    assert cache.lookup(ACCOUNT_OWNER, b"\x01", keccak256(b)) == b
    assert cache.hits == 2
    # a hash no version carries is a miss, never a wrong serve
    assert cache.lookup(ACCOUNT_OWNER, b"\x01", b"\x00" * 32) is None
    assert cache.misses == 1


def test_cache_version_cap_and_invalidate_trim():
    cache = TrieNodeCache(injector=None)
    rlps = [bytes([i]) * 40 for i in range(6)]
    for r in rlps:
        cache.put(ACCOUNT_OWNER, b"", r)
    # per-path fan-out is bounded: the oldest versions aged out
    assert len(cache) == cache.VERSIONS_PER_PATH
    assert cache.lookup(ACCOUNT_OWNER, b"", keccak256(rlps[0])) is None
    assert cache.lookup(ACCOUNT_OWNER, b"", keccak256(rlps[-1])) == rlps[-1]
    # canonical-write trim: prefixes of the changed key keep only the
    # newest INVALIDATE_KEEP versions (the fork siblings' live spines)
    cache.invalidate_key(ACCOUNT_OWNER, b"\x07" * 32)
    assert len(cache) == cache.INVALIDATE_KEEP
    assert cache.lookup(ACCOUNT_OWNER, b"", keccak256(rlps[-1])) == rlps[-1]
    cache.drop_owner(ACCOUNT_OWNER)
    assert len(cache) == 0


def test_cache_clear_and_owner_isolation():
    cache = TrieNodeCache(injector=None)
    cache.put(ACCOUNT_OWNER, b"\x01", b"\xaa" * 40)
    cache.put(b"\x99" * 32, b"\x01", b"\xbb" * 40)
    cache.drop_owner(b"\x99" * 32)
    assert cache.lookup(ACCOUNT_OWNER, b"\x01",
                        keccak256(b"\xaa" * 40)) is not None
    cache.clear("test")
    assert len(cache) == 0 and cache.clears == 1


def test_poison_injector_is_caught():
    """Every poisoned serve MUST be caught by node-hash validation: the
    lookup misses (pays a proof fetch), poison_caught counts it, and the
    intact entry still serves on the next (unpoisoned) lookup."""
    inj = HotStateFaultInjector(poison_every=2)
    cache = TrieNodeCache(injector=inj)
    rlp = b"\xcd" * 40
    cache.put(ACCOUNT_OWNER, b"\x02", rlp)
    h = keccak256(rlp)
    assert cache.lookup(ACCOUNT_OWNER, b"\x02", h) == rlp   # 1st: clean
    assert cache.lookup(ACCOUNT_OWNER, b"\x02", h) is None  # 2nd: poisoned
    assert cache.poison_caught == 1
    assert cache.lookup(ACCOUNT_OWNER, b"\x02", h) == rlp   # entry intact


def test_reveal_through_unblinds_from_cache_alone():
    """A trie anchored at a blind root becomes readable purely from
    cached spine nodes — the zero-proof-fetch reveal path."""
    keys = _keys(50)
    truth = SparseTrie()
    for i, k in enumerate(keys):
        truth.update(k, rlp_encode((i + 1).to_bytes(4, "big")))
    _small_committer().commit([truth])
    cache = TrieNodeCache(injector=None)
    assert cache.harvest(truth, ACCOUNT_OWNER, keys) > 0

    blind = SparseTrie(root_hash=truth.root_hash)
    for i, k in enumerate(keys):
        assert cache.reveal_through(blind, ACCOUNT_OWNER, k)
        assert blind.get(k) == rlp_encode((i + 1).to_bytes(4, "big"))
    assert cache.hits > 0 and cache.stale_drops == 0
    # with the cache gone, the same anchor cannot unblind
    cache.clear("test")
    blind2 = SparseTrie(root_hash=truth.root_hash)
    assert not cache.reveal_through(blind2, ACCOUNT_OWNER, keys[0])


# -- randomized differential: cached vs uncached finishes --------------------


def _apply_with_reveals(blind, twin, cache, owner, fn, counters):
    """Run one mutation, unblinding on demand: cache first (validated),
    the twin's node RLP as the simulated proof fetch on a miss."""
    for _ in range(400):
        try:
            return fn()
        except BlindedNodeError as e:
            path = bytes(e.path)
            h = blind.blind_hash_at(path)
            rlp = cache.lookup(owner, path, h) if h is not None else None
            if rlp is not None and blind.reveal_at(path, rlp):
                counters["cache"] += 1
                continue
            node = twin.node_at(path)
            assert node is not None, "twin missing a node the blind needs"
            assert blind.reveal_at(path, _encode_rlp(node))
            counters["fetch"] += 1
    raise AssertionError("reveal loop did not converge")


@pytest.mark.parametrize("seed", range(1, 11))
def test_randomized_differential_cached_vs_uncached(seed):
    """10-seed differential: interleaved update/delete/wipe streams over
    two alternating sibling forks. The cached lineage reveals from the
    shared TrieNodeCache (falling back to simulated proof fetches) and
    delta-commits through a persistent DigestArena on half the seeds;
    the uncached twin re-stages everything through the classic path.
    Every round's root must be bit-identical to the twin's AND to a
    from-scratch rebuild of the reference state."""
    rng = random.Random(0x407E + seed)
    keys = _keys(36, salt=seed)
    cache = TrieNodeCache(injector=None)
    arena = _arena(max_rows=1 << 12) if seed % 2 else None
    hot_committer = _small_committer(arena=arena)
    cold_committer = _small_committer()
    counters = {"cache": 0, "fetch": 0}

    forks = {f: {"state": {}, "root": None, "twin": SparseTrie()}
             for f in ("A", "B")}
    for rnd in range(14):
        fork = forks["AB"[rnd % 2] if rng.random() < 0.8
                     else rng.choice("AB")]
        blind = (SparseTrie() if fork["root"] is None
                 else SparseTrie(root_hash=fork["root"]))
        blind.stamp_reveals = True

        ops = []
        if fork["state"] and rng.random() < 0.08:
            ops.append(("wipe", None, None))
        else:
            present = list(fork["state"])
            for k in rng.sample(keys, rng.randint(3, 9)):
                if k in fork["state"] and rng.random() < 0.35:
                    ops.append(("del", k, None))  # sibling-collapse deletes
                else:
                    v = rlp_encode(rng.randbytes(rng.randint(1, 48)))
                    ops.append(("set", k, v))
            # target a guaranteed-present key sometimes so deletions hit
            # two-child branches that collapse into extensions
            if present and rng.random() < 0.5:
                ops.append(("del", rng.choice(present), None))

        for op, k, v in ops:
            if op == "wipe":
                blind = SparseTrie()
                blind.stamp_reveals = True
                fork["twin"] = SparseTrie()
                fork["state"] = {}
                cache.drop_owner(ACCOUNT_OWNER)
                continue
            if op == "set":
                _apply_with_reveals(blind, fork["twin"], cache,
                                    ACCOUNT_OWNER,
                                    lambda k=k, v=v: blind.update(k, v),
                                    counters)
            else:
                _apply_with_reveals(blind, fork["twin"], cache,
                                    ACCOUNT_OWNER,
                                    lambda k=k: blind.delete(k),
                                    counters)
        # twin applies the same ops, then both commit on their own path
        for op, k, v in ops:
            if op == "wipe":
                continue
            if op == "set":
                fork["twin"].update(k, v)
                fork["state"][k] = v
            else:
                fork["twin"].delete(k)
                fork["state"].pop(k, None)

        (hot_root,) = hot_committer.commit([blind])
        (cold_root,) = cold_committer.commit([fork["twin"]])
        assert hot_root == cold_root, f"round {rnd}: cached diverged"
        scratch = SparseTrie()
        for k, v in fork["state"].items():
            scratch.update(k, v)
        assert scratch.root_hash_compute() == cold_root, \
            f"round {rnd}: twin diverged from rebuild"

        # absorb: canonical-write trims + fresh spine harvest
        changed = [k for op, k, _ in ops if op != "wipe"]
        for k in changed:
            cache.invalidate_key(ACCOUNT_OWNER, k)
        cache.harvest(blind, ACCOUNT_OWNER, changed)
        fork["root"] = hot_root

    assert counters["cache"] > 0, "cache never served a reveal"
    if arena is not None and arena.engine is not None:
        assert arena.leaked_rows() == 0, arena.snapshot()
        assert arena.snapshot()["delta_epochs"] > 0, arena.snapshot()


# -- arena drills ------------------------------------------------------------


def _arena_rounds(committer, trie, keys, rng, rounds=6):
    """Steady incremental commits of one trie through ``committer``;
    returns the per-round roots (for a twin comparison)."""
    roots = []
    for rnd in range(rounds):
        for k in rng.sample(keys, 6):
            trie.update(k, rlp_encode(rng.randbytes(20)))
        (r,) = committer.commit([trie])
        roots.append(r)
    return roots


def test_arena_epoch_eviction_reclaims_rows():
    """A row budget forces begin_epoch to evict: the epoch after the
    eviction runs the full-upload rung (arena_fresh), roots stay
    bit-identical to a classic twin, and no row leaks."""
    rng = random.Random(11)
    keys = _keys(48, salt=77)
    arena = _arena()
    arena.max_rows = 24  # the ctor floors at 1024; shrink for the drill
    hot = _small_committer(arena=arena)
    cold = _small_committer()
    t_hot, t_cold = SparseTrie(), SparseTrie()
    rng2 = random.Random(11)
    hot_roots = _arena_rounds(hot, t_hot, keys, rng, rounds=8)
    cold_roots = _arena_rounds(cold, t_cold, keys, rng2, rounds=8)
    assert hot_roots == cold_roots
    snap = arena.snapshot()
    assert snap["evictions"] >= 1, snap
    assert arena.leaked_rows() == 0, snap


def test_arena_fault_falls_back_to_full_upload():
    """A mid-epoch device fault must evict the arena and let the SAME
    commit rerun on the classic full-upload rungs — root unchanged, the
    fault counted, nothing leaked."""
    rng = random.Random(5)
    keys = _keys(40, salt=5)
    arena = _arena()
    hot = _small_committer(arena=arena)
    trie = SparseTrie()
    for k in keys[:12]:
        trie.update(k, rlp_encode(b"\x01" + k[:8]))
    (first,) = hot.commit([trie])
    if arena.engine is None:
        pytest.skip("no device stack: arena path unavailable")

    boom = RuntimeError("injected mid-epoch device fault")

    def explode(*a, **kw):
        raise boom

    arena.engine.dispatch_packed = explode  # next epoch faults mid-flight
    for k in keys[12:24]:
        trie.update(k, rlp_encode(b"\x02" + k[:8]))
    twin = SparseTrie()
    for k in keys[:12]:
        twin.update(k, rlp_encode(b"\x01" + k[:8]))
    for k in keys[12:24]:
        twin.update(k, rlp_encode(b"\x02" + k[:8]))
    (faulted,) = hot.commit([trie])
    assert faulted == _small_committer().commit([twin])[0]
    snap = arena.snapshot()
    assert snap["faults"] == 1 and snap["evictions"] >= 1, snap
    assert arena.leaked_rows() == 0
    # the arena recovers: the next commit re-enters the delta protocol
    for k in keys[24:30]:
        trie.update(k, rlp_encode(b"\x03" + k[:8]))
    hot.commit([trie])
    assert arena.engine is not None and arena.snapshot()["faults"] == 1


def test_evict_storm_injector_forces_full_uploads(monkeypatch):
    """RETH_TPU_FAULT_HOTSTATE_EVICT_STORM=1: every epoch starts from an
    evicted arena, so every commit runs the full-upload rung — purely a
    performance fault, roots stay bit-identical."""
    monkeypatch.setenv("RETH_TPU_FAULT_HOTSTATE_EVICT_STORM", "1")
    rng, rng2 = random.Random(3), random.Random(3)
    keys = _keys(32, salt=9)
    arena = _arena()
    hot = _small_committer(arena=arena)   # injector read from env here
    cold = _small_committer()
    assert hot.hot_injector is not None and hot.hot_injector.evict_storm
    hot_roots = _arena_rounds(hot, SparseTrie(), keys, rng, rounds=5)
    cold_roots = _arena_rounds(cold, SparseTrie(), keys, rng2, rounds=5)
    assert hot_roots == cold_roots
    snap = arena.snapshot()
    if arena.engine is not None:
        assert snap["delta_epochs"] == 0, snap
        assert snap["full_epochs"] >= 1, snap
    assert arena.leaked_rows() == 0


# -- engine wiring -----------------------------------------------------------


def _sibling_fork_env(n_blocks=3, n_wallets=12, n_txs=6):
    """Two sibling chains over the SAME genesis + wallet set (the
    preserved trie misses every interleaved import, so each block needs
    reveals) and a factory to feed them into."""
    genesis = {Wallet(0x5000 + i).address: Account(balance=10**21)
               for i in range(n_wallets)}
    half = n_wallets // 2
    chains = []
    for fork in range(2):
        ws = [Wallet(0x5000 + i) for i in range(n_wallets)]
        b = ChainBuilder(genesis, committer=CPU)
        for i in range(n_blocks):
            send, recv = (ws[:half], ws[half:]) if i % 2 == 0 else \
                         (ws[half:], ws[:half])
            b.build_block([send[j % half].transfer(
                recv[j % half].address, 10**13 + fork * 3 + i * 17 + j)
                for j in range(n_txs)])
        chains.append(b)
    order = []
    for i in range(1, n_blocks + 1):
        order.append(chains[0].blocks[i])
        order.append(chains[1].blocks[i])

    def fresh_factory():
        f = ProviderFactory(MemDb())
        init_genesis(f, chains[0].genesis, chains[0].accounts_at_genesis,
                     committer=CPU)
        return f

    return order, fresh_factory


def _import_forks(tree, order):
    agg = {"proof_targets": 0, "cache_unblinds": 0}
    for blk in order:
        st = tree.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        m = tree.last_sparse or {}
        assert m.get("strategy") == "sparse", m
        agg["proof_targets"] += m.get("proof_targets", 0)
        agg["cache_unblinds"] += m.get("cache_unblinds", 0)
    return agg


def test_engine_sibling_forks_served_from_cache():
    """EngineTree(hot_state=True) vs the uncached twin on the SAME
    interleaved sibling-fork stream: every payload VALID on both (roots
    bit-identical by the header check), the cached tree unblinds from
    the cache, and it fetches strictly fewer proof targets — the
    dedupe/cache interaction (a cache unblind never lands on the proof
    pool, an in-flight fetch is never re-consulted) shows up as that
    strict reduction."""
    order, fresh_factory = _sibling_fork_env()
    hot_tree = EngineTree(fresh_factory(), committer=CPU,
                          persistence_threshold=10**9, hot_state=True)
    assert hot_tree.hot_cache is not None
    cold_tree = EngineTree(fresh_factory(), committer=CPU,
                           persistence_threshold=10**9, hot_state=False)
    assert cold_tree.hot_cache is None
    hot = _import_forks(hot_tree, order)
    cold = _import_forks(cold_tree, order)
    assert hot["cache_unblinds"] > 0
    assert cold["cache_unblinds"] == 0
    assert hot["proof_targets"] < cold["proof_targets"], (hot, cold)
    assert len(hot_tree.hot_cache) > 0


def test_engine_poison_storm_stays_valid(monkeypatch):
    """With every other cache serve poisoned, imports stay VALID (the
    validator eats the poison as a miss and the proof path supplies the
    real node) and the catches are counted."""
    monkeypatch.setenv("RETH_TPU_FAULT_HOTSTATE_POISON", "2")
    order, fresh_factory = _sibling_fork_env()
    tree = EngineTree(fresh_factory(), committer=CPU,
                      persistence_threshold=10**9, hot_state=True)
    _import_forks(tree, order)
    assert tree.hot_cache.poison_caught > 0


def test_engine_invalidate_hot_state_clears_both_planes():
    order, fresh_factory = _sibling_fork_env(n_blocks=2)
    tree = EngineTree(fresh_factory(), committer=CPU,
                      persistence_threshold=10**9, hot_state=True)
    _import_forks(tree, order)
    assert len(tree.hot_cache) > 0
    tree._invalidate_hot_state("test_stand_down")
    assert len(tree.hot_cache) == 0
    if tree.hot_arena is not None:
        assert tree.hot_arena.engine is None
        assert tree.hot_arena.leaked_rows() == 0


# -- observability -----------------------------------------------------------


def test_hotstate_metrics_and_health_rule():
    """hotstate_* counters convert lifetime totals to increments, the
    events fragment renders from ``last``, and the health table carries
    the hit-rate-collapse floor as a degrade-only rule."""
    from reth_tpu.health import default_rules
    from reth_tpu.metrics import HotStateMetrics

    m = HotStateMetrics()
    m.record_cache({"entries": 4, "hits": 10, "misses": 2,
                    "stale_drops": 1, "poison_caught": 0, "evictions": 0,
                    "puts": 9, "clears": 0})
    m.record_cache({"entries": 5, "hits": 14, "misses": 3,
                    "stale_drops": 1, "poison_caught": 0, "evictions": 0,
                    "puts": 12, "clears": 0})
    assert m.last["hit_rate"] == pytest.approx(14 / 17, abs=1e-3)

    rules = {r.name: r for r in default_rules()}
    rule = rules["hotstate_hit_rate"]
    assert rule.op == "<" and rule.kind == "ratio"
    assert rule.failing_factor >= 1e6  # degrade-only: never pages
