"""snap/1 state-range serving over the encrypted testnet.

Reference analogue: the `StateRangeProvider` serving surface
(crates/storage/storage-api/src/trie.rs:73) + devp2p snap vocabulary,
multiplexed next to eth/68 the way reth's RLPx sub-protocol registry
does (crates/net/network/src/protocol.rs).
"""

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.net import NetworkManager, PeerConnection, Status
from reth_tpu.net import snap
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.secp256k1 import pubkey_from_priv
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


@pytest.fixture(scope="module")
def snap_net():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    alice = Wallet(0xA11CE)
    code = bytes.fromhex("6001600155")  # writes storage on every call
    contract = b"\x0c" * 20
    genesis_accounts = {
        alice.address: Account(balance=10**21),
        contract: Account(balance=1, code_hash=keccak256(code)),
    }
    builder = ChainBuilder(genesis_accounts, committer=CPU, codes={keccak256(code): code})
    for i in range(4):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 codes={keccak256(code): code}, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(4)
    status = Status(network_id=1, head=builder.tip.hash, genesis=builder.genesis.hash)
    server = NetworkManager(factory, status, node_priv=0x51A9)
    port = server.start()
    peer = PeerConnection.connect("127.0.0.1", port, status,
                                  pubkey_from_priv(server.node_priv))
    root = builder.tip.state_root
    yield server, peer, factory, root
    peer.close()
    server.stop()


def test_slim_account_roundtrip():
    acc = Account(nonce=3, balance=10**18)
    slim = snap.slim_account(acc)
    back = snap.unslim_account(slim)
    assert back.nonce == 3 and back.balance == 10**18
    assert back.storage_root == acc.storage_root
    assert back.code_hash == acc.code_hash


def test_snap_codec_roundtrips():
    msgs = [
        snap.GetAccountRange(1, b"\x01" * 32, b"\x00" * 32, b"\xff" * 32, 1000),
        snap.AccountRange(1, [(b"\x02" * 32, b"\x80")], [b"proofnode"]),
        snap.GetStorageRanges(2, b"\x01" * 32, [b"\x03" * 32], b"", b"", 500),
        snap.StorageRanges(2, [[(b"\x04" * 32, b"\x05")]], []),
        snap.GetByteCodes(3, [b"\x06" * 32], 100),
        snap.ByteCodes(3, [b"\x60\x01"]),
        snap.GetTrieNodes(4, b"\x01" * 32, [[b"\x07"], [b"\x08", b"\x09"]], 50),
        snap.TrieNodes(4, [b"node"]),
    ]
    for m in msgs:
        mid, payload = snap.encode_snap(m)
        assert snap.decode_snap(mid, payload) == m, type(m).__name__


def test_account_range_with_proofs(snap_net):
    server, peer, factory, root = snap_net
    assert peer.snap_enabled
    rng = peer.get_account_range(root, b"\x00" * 32, b"\xff" * 32)
    assert len(rng.accounts) >= 3  # alice, recipient, contract at least
    keys = [h for h, _ in rng.accounts]
    assert keys == sorted(keys)
    assert rng.proof, "range must carry boundary proofs"
    assert snap.verify_account_range(root, b"\x00" * 32, rng)
    # stale root -> empty (unavailable)
    stale = peer.get_account_range(b"\x77" * 32, b"\x00" * 32, b"\xff" * 32)
    assert stale.accounts == [] and stale.proof == []


def test_account_range_pagination(snap_net):
    server, peer, factory, root = snap_net
    # tiny byte budget: server truncates; resume from last key returns more
    first = peer.get_account_range(root, b"\x00" * 32, b"\xff" * 32,
                                   response_bytes=1)
    assert len(first.accounts) == 1
    last = first.accounts[-1][0]
    nxt = peer.get_account_range(
        root, (int.from_bytes(last, "big") + 1).to_bytes(32, "big"),
        b"\xff" * 32)
    assert nxt.accounts and nxt.accounts[0][0] > last


def test_storage_ranges_and_bytecodes(snap_net):
    server, peer, factory, root = snap_net
    contract = b"\x0c" * 20
    ha = keccak256(contract)
    with factory.provider() as p:
        acc = p.account(contract)
    rng = peer.get_storage_ranges(root, [ha])
    assert len(rng.slots) == 1
    # the contract wrote slot 1 = 1 on genesis-time... (no calls made:
    # storage may be empty — shape is what matters)
    codes = peer.get_byte_codes([acc.code_hash])
    assert codes.codes and keccak256(codes.codes[0]) == acc.code_hash


def test_trie_nodes_healing(snap_net):
    server, peer, factory, root = snap_net
    # ask for the root node by empty path: server returns the root's spine
    nodes = peer.get_trie_nodes(root, [[b""]])
    assert nodes.nodes
    assert keccak256(nodes.nodes[0]) == root
