"""Crash-safe persistence: WAL format, checkpointing, startup recovery.

Covers the PR's durable-commit layer (storage/wal.py), the fsync fixes
in kv.py/nippyjar.py, corrupt-image quarantine, the engine durability
boundary, and the reorg-across-restart satellite. Every "crash" here is
simulated the honest way for in-process tests: the live objects are
ABANDONED (no stop, no flush) and a fresh store is opened from whatever
bytes are on disk — exactly what a kill -9 leaves behind. Real-process
``os._exit`` drills live in test_chaos.py.
"""

from __future__ import annotations

import json
import os
import pickle
import struct

import pytest

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.tables import Tables
from reth_tpu.storage.wal import (
    WalStore,
    attach_wal,
    read_segment,
    SEGMENT_MAGIC,
)
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def reopen(tmp_path, name="db.bin", wal="wal"):
    """What a restart after kill -9 sees: fresh objects over disk bytes."""
    db = MemDb(tmp_path / name)
    return db, attach_wal(db, tmp_path / wal)


# -- record format ------------------------------------------------------------


def test_wal_commit_replay_roundtrip(tmp_path):
    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
        tx.put("D", b"k", b"x", dupsort=True)
        tx.put("D", b"k", b"y", dupsort=True)
    with db.tx_mut() as tx:
        tx.delete("T", b"a")
        tx.put("T", b"b", b"2")
        tx.delete("D", b"k", b"x")
    db2, dur2 = reopen(tmp_path)
    with db2.tx() as t:
        assert t.get("T", b"a") is None
        assert t.get("T", b"b") == b"2"
        assert t.get_dups("D", b"k") == [b"y"]
    assert dur2.replay_report()["records"] == 2
    assert dur2.replay_report()["torn_bytes"] == 0


def test_wal_clear_records_whole_table_replace(tmp_path):
    db, _ = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
        tx.put("T", b"b", b"2")
    with db.tx_mut() as tx:
        tx.clear("T")
        tx.put("T", b"c", b"3")
    db2, _ = reopen(tmp_path)
    with db2.tx() as t:
        assert t.get("T", b"a") is None
        assert t.get("T", b"c") == b"3"
        assert t.entry_count("T") == 1


def test_wal_torn_tail_discarded(tmp_path):
    db, dur = reopen(tmp_path)
    for i in range(3):
        with db.tx_mut() as tx:
            tx.put("T", bytes([i]), b"v%d" % i)
    seg = dur.main.dir / "00000001.wal"
    whole = seg.read_bytes()
    # truncate mid-record: the torn tail must be discarded, the two
    # complete records must survive
    seg.write_bytes(whole[:-7])
    db2, dur2 = reopen(tmp_path)
    rep = dur2.replay_report()
    assert rep["records"] == 2
    assert rep["torn_bytes"] > 0
    with db2.tx() as t:
        assert t.get("T", b"\x00") == b"v0"
        assert t.get("T", b"\x01") == b"v1"
        assert t.get("T", b"\x02") is None


def test_torn_tail_truncated_so_post_recovery_commits_survive(tmp_path):
    """Crash -> recover -> commit -> crash again. Recovery must TRUNCATE
    the torn tail off the live segment: without that, post-recovery
    appends land after unreadable garbage and the second recovery
    silently drops every one of them."""
    db, dur = reopen(tmp_path)
    for i in range(3):
        with db.tx_mut() as tx:
            tx.put("T", bytes([i]), b"v%d" % i)
    seg = dur.main.dir / "00000001.wal"
    seg.write_bytes(seg.read_bytes()[:-7])  # kill -9 mid-append
    # first recovery: two whole records survive, torn bytes gone from disk
    db2, dur2 = reopen(tmp_path)
    assert dur2.replay_report()["records"] == 2
    sizes = seg.stat().st_size
    with db2.tx_mut() as tx:
        tx.put("T", b"new", b"post-recovery")
    assert seg.stat().st_size > sizes
    # second kill -9: the post-recovery commit MUST replay
    db3, dur3 = reopen(tmp_path)
    rep = dur3.replay_report()
    assert rep["records"] == 3
    assert rep["torn_bytes"] == 0
    with db3.tx() as t:
        assert t.get("T", b"new") == b"post-recovery"
        assert t.get("T", b"\x00") == b"v0"
        assert t.get("T", b"\x02") is None


def test_midlog_corruption_quarantines_segments_and_escalates(tmp_path):
    """A torn NON-final segment is mid-log corruption: the corrupt
    segment and everything after it (durable commits we can no longer
    apply in order) are quarantined aside, the surviving prefix is
    checkpointed immediately, and recovery reports FAILED — the
    durability promise was broken, not healed."""
    import zlib

    from reth_tpu.storage.recovery import recover_on_startup

    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    with db.tx_mut() as tx:
        tx.put("T", b"b", b"2")
    dur.main.close()
    # hand-roll a later segment holding another durably committed record
    payload = pickle.dumps(
        {"seq": 9, "tables": {"T": {"rows": {b"c": b"3"}, "del": []}}},
        protocol=pickle.HIGHEST_PROTOCOL)
    seg2 = tmp_path / "wal" / "00000002.wal"
    seg2.write_bytes(SEGMENT_MAGIC + struct.pack("<Q", 2)
                     + struct.pack("<II", len(payload), zlib.crc32(payload))
                     + payload)
    # bit-rot the SECOND record of segment 1 — now mid-log, not a tail
    seg1 = tmp_path / "wal" / "00000001.wal"
    data = bytearray(seg1.read_bytes())
    data[-1] ^= 0xFF
    seg1.write_bytes(bytes(data))

    db2, dur2 = reopen(tmp_path)
    rep = dur2.replay_report()
    assert len(rep["lost_segments"]) == 2
    assert not seg1.exists() and not seg2.exists()
    assert (tmp_path / "wal" / "00000001.wal.corrupt").exists()
    assert (tmp_path / "wal" / "00000002.wal.corrupt").exists()
    with db2.tx() as t:
        assert t.get("T", b"a") == b"1"   # surviving prefix applied
        assert t.get("T", b"c") is None   # the lost segment is NOT
    # recovery escalates beyond degraded: durable commits were dropped
    report = recover_on_startup(ProviderFactory(db2), durability=dur2,
                                committer=CPU, verify_root=False)
    assert report["status"] == "failed"
    assert any("mid-log" in p for p in report["problems"])
    assert any(".wal.corrupt" in q for q in report["quarantined"])
    # the open-time checkpoint made the prefix durable: the next boot
    # replays clean instead of hitting the corrupt middle again
    db3, dur3 = reopen(tmp_path)
    rep3 = dur3.replay_report()
    assert rep3["torn_bytes"] == 0 and not rep3["lost_segments"]
    assert dur3.main.gen >= 3  # quarantined generations never reused
    with db3.tx() as t:
        assert t.get("T", b"a") == b"1"


def test_append_failure_rewinds_log_and_releases_writer_lock(
        tmp_path, monkeypatch):
    """ENOSPC/EIO mid-append: commit raises, but the writer lock is
    released immediately (not at __del__) and the half-written frame is
    truncated away so later appends don't get buried behind it."""
    import errno

    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    seg = dur.main.dir / "00000001.wal"
    good_size = seg.stat().st_size

    fail = {"on": True}
    real_fsync = os.fsync

    def flaky(fd):
        if fail["on"]:
            raise OSError(errno.EIO, "injected EIO")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", flaky)
    with pytest.raises(OSError):
        with db.tx_mut() as tx:
            tx.put("T", b"b", b"2")
    fail["on"] = False
    # failed record rewound: the segment holds exactly the good bytes
    assert seg.stat().st_size == good_size
    # writer lock released: the next write txn proceeds (no deadlock)
    with db.tx_mut() as tx:
        tx.put("T", b"c", b"3")
    # the unpublished commit is absent, the log stays well-framed
    with db.tx() as t:
        assert t.get("T", b"b") is None
    db2, dur2 = reopen(tmp_path)
    rep = dur2.replay_report()
    assert rep["records"] == 2 and rep["torn_bytes"] == 0
    with db2.tx() as t:
        assert t.get("T", b"a") == b"1"
        assert t.get("T", b"b") is None
        assert t.get("T", b"c") == b"3"


def test_fsync_file_propagates_real_io_errors(tmp_path, monkeypatch):
    import errno

    from reth_tpu.storage.wal import fsync_file

    with open(tmp_path / "x", "wb") as f:
        monkeypatch.setattr(os, "fsync", lambda fd: (_ for _ in ()).throw(
            OSError(errno.EIO, "injected EIO")))
        with pytest.raises(OSError):
            fsync_file(f)
        # "fsync unsupported here" stays best-effort (pipes, special fs)
        monkeypatch.setattr(os, "fsync", lambda fd: (_ for _ in ()).throw(
            OSError(errno.EINVAL, "not supported")))
        fsync_file(f)


def test_segment_gen_mismatch_treated_as_torn(tmp_path):
    """A mis-renamed / cross-copied segment must not replay under the
    wrong generation order: the header gen is validated against the
    filename."""
    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    dur.main.close()
    seg = tmp_path / "wal" / "00000001.wal"
    renamed = tmp_path / "wal" / "00000005.wal"
    seg.rename(renamed)
    records, torn, accepted = read_segment(renamed)
    assert records == [] and torn == renamed.stat().st_size
    db2, dur2 = reopen(tmp_path)
    assert dur2.replay_report()["records"] == 0
    assert dur2.replay_report()["torn_bytes"] > 0
    with db2.tx() as t:
        assert t.get("T", b"a") is None


def test_wal_crc_mismatch_discards_tail(tmp_path):
    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    seg = dur.main.dir / "00000001.wal"
    data = bytearray(seg.read_bytes())
    data[-1] ^= 0xFF  # bit rot inside the last payload
    seg.write_bytes(bytes(data))
    records, torn, accepted = read_segment(seg)
    assert records == [] and torn > 0 and accepted == 0


def test_wal_accept_torn_env_is_deliberately_broken(tmp_path, monkeypatch):
    """The negative-drill reader: with RETH_TPU_FAULT_WAL_ACCEPT_TORN a
    CRC-failing record is APPLIED — the invariant suite must be the one
    to catch the damage (proved end-to-end below and in test_chaos)."""
    from reth_tpu.chaos import inject_bad_crc_record

    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"good")
    inject_bad_crc_record(tmp_path / "wal",
                          {"T": {"rows": {b"a": b"evil"}, "del": []}})
    # correct reader: bad-CRC tail discarded (and truncated off disk)
    db2, _ = reopen(tmp_path)
    with db2.tx() as t:
        assert t.get("T", b"a") == b"good"
    # broken reader: applied (re-injected — the correct reader truncated
    # the torn tail so post-recovery appends stay recoverable)
    inject_bad_crc_record(tmp_path / "wal",
                          {"T": {"rows": {b"a": b"evil"}, "del": []}})
    monkeypatch.setenv("RETH_TPU_FAULT_WAL_ACCEPT_TORN", "1")
    db3, dur3 = reopen(tmp_path)
    with db3.tx() as t:
        assert t.get("T", b"a") == b"evil"
    assert dur3.replay_report()["accepted_torn"] == 1


def test_segment_header_magic(tmp_path):
    db, dur = reopen(tmp_path)
    seg = dur.main.dir / "00000001.wal"
    raw = seg.read_bytes()
    assert raw.startswith(SEGMENT_MAGIC)
    (gen,) = struct.unpack_from("<Q", raw, len(SEGMENT_MAGIC))
    assert gen == 1


# -- checkpointing ------------------------------------------------------------


def test_checkpoint_truncates_log_and_writes_manifest(tmp_path):
    db, dur = reopen(tmp_path)
    for i in range(4):
        with db.tx_mut() as tx:
            tx.put("T", bytes([i]), b"v")
    dur.checkpoint(head=(7, b"\xab" * 32))
    segs = sorted(p.name for p in (tmp_path / "wal").glob("*.wal"))
    assert segs == ["00000002.wal"]
    manifest = json.loads((tmp_path / "wal" / "MANIFEST.json").read_text())
    assert manifest["gen"] == 2
    assert manifest["head_number"] == 7
    assert manifest["head_hash"] == "ab" * 32
    # image holds everything; restart replays zero records
    db2, dur2 = reopen(tmp_path)
    assert dur2.replay_report()["records"] == 0
    with db2.tx() as t:
        assert t.get("T", b"\x03") == b"v"


def test_replay_idempotent_over_newer_image(tmp_path):
    """A flush without a checkpoint (crash between the two) leaves the
    image AHEAD of the log start — records carry absolute values, so
    replaying the whole segment over it converges bit-identically."""
    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
        tx.put("D", b"k", b"x", dupsort=True)
    with db.tx_mut() as tx:
        tx.delete("T", b"a")
        tx.put("D", b"k", b"y", dupsort=True)
    db.flush()  # image now ahead of the (untruncated) segment
    db2, _ = reopen(tmp_path)
    with db2.tx() as t:
        assert t.get("T", b"a") is None
        assert t.get_dups("D", b"k") == [b"x", b"y"]


def test_checkpoint_cadence_tracks_persisted_blocks(tmp_path):
    db, dur = reopen(tmp_path)
    dur.checkpoint_blocks = 3
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    dur.on_persisted(1, b"\x01" * 32)   # first boundary always checkpoints
    g1 = dur.main.gen
    dur.on_persisted(2, b"\x02" * 32)   # within cadence: no new gen
    assert dur.main.gen == g1
    dur.on_persisted(4, b"\x04" * 32)   # 3 blocks past: checkpoint
    assert dur.main.gen == g1 + 1


def test_storage_v2_split_store_gets_two_wals(tmp_path):
    from reth_tpu.storage.settings import SplitDb

    main = MemDb(tmp_path / "db.bin")
    aux = MemDb(tmp_path / "db-aux.bin")
    split = SplitDb(main, aux)
    dur = attach_wal(split, tmp_path / "wal")
    assert dur is not None and len(dur.stores) == 2
    with split.tx_mut() as tx:
        tx.put(Tables.Headers.name, b"\x00" * 8, b"hdr")           # main
        tx.put(Tables.AccountsHistory.name, b"\xaa", b"shard")     # aux
    main2 = MemDb(tmp_path / "db.bin")
    aux2 = MemDb(tmp_path / "db-aux.bin")
    split2 = SplitDb(main2, aux2)
    attach_wal(split2, tmp_path / "wal")
    with split2.tx() as t:
        assert t.get(Tables.Headers.name, b"\x00" * 8) == b"hdr"
        assert t.get(Tables.AccountsHistory.name, b"\xaa") == b"shard"
    assert (tmp_path / "wal-aux").is_dir()


# -- fsync durability fixes (satellite) --------------------------------------


def _count_fsyncs(monkeypatch):
    calls = []
    real = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd), real(fd))[1])
    return calls


def test_memdb_flush_fsyncs_file_and_parent_dir(tmp_path, monkeypatch):
    db = MemDb(tmp_path / "db.bin")
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    calls = _count_fsyncs(monkeypatch)
    db.flush()
    # at least the tmp file AND the parent directory
    assert len(calls) >= 2


def test_nippyjar_write_fsyncs_file_and_parent_dir(tmp_path, monkeypatch):
    from reth_tpu.storage.nippyjar import NippyJar

    calls = _count_fsyncs(monkeypatch)
    NippyJar.write(tmp_path / "x.sf", {"c": [b"row1", b"row2"]})
    assert len(calls) >= 2
    assert not list(tmp_path.glob("*.tmp"))
    jar = NippyJar.open(tmp_path / "x.sf")
    assert jar.verify() and jar.row("c", 1) == b"row2"
    jar.close()


def test_wal_append_fsyncs_before_publish(tmp_path, monkeypatch):
    db, dur = reopen(tmp_path)
    order = []
    real_append = WalStore.append

    def spy(self, delta, publish=None):
        def wrapped():
            order.append("publish")
            publish()
        order.append("append")
        real_append(self, delta, publish=wrapped if publish else None)

    monkeypatch.setattr(WalStore, "append", spy)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"1")
    assert order == ["append", "publish"]


# -- corrupt-image quarantine (satellite) -------------------------------------


def test_corrupt_image_quarantined_not_fatal(tmp_path):
    (tmp_path / "db.bin").write_bytes(b"\x80\x04this is not a pickle")
    db = MemDb(tmp_path / "db.bin")  # must NOT raise
    assert db.quarantined is not None
    assert db.quarantined.exists()
    assert not (tmp_path / "db.bin").exists()
    with db.tx() as t:
        assert t.entry_count("T") == 0


def test_corrupt_image_recovers_from_wal(tmp_path):
    db, dur = reopen(tmp_path)
    with db.tx_mut() as tx:
        tx.put("T", b"a", b"survives")
    # corrupt the image (never flushed anyway), keep the WAL
    (tmp_path / "db.bin").write_bytes(b"junk")
    db2, dur2 = reopen(tmp_path)
    assert db2.quarantined is not None
    with db2.tx() as t:
        assert t.get("T", b"a") == b"survives"


# -- node-level crash windows -------------------------------------------------


def _mk_node(tmp_path, wallet, builder, **kw):
    from reth_tpu.node import Node, NodeConfig

    cfg = NodeConfig(dev=True, datadir=tmp_path, db_backend="memdb",
                     genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis,
                     persistence_threshold=2, wal_checkpoint_blocks=3, **kw)
    return Node(cfg, committer=CPU)


def _mine(node, wallet, n, start=0):
    for i in range(n):
        node.pool.add_transaction(wallet.transfer(b"\x0b" * 20, 50 + i))
        node.miner.mine_block(timestamp=1_700_000_000 + (start + i) * 12)


def test_node_kill_loses_at_most_persistence_threshold(tmp_path):
    """Tentpole contract: abandon the node mid-flight (kill -9 shape) —
    the restart recovers the persisted tip (head - threshold), verifies
    the recovered root by recomputation, and keeps serving."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    node = _mk_node(tmp_path, alice, builder)
    _mine(node, alice, 8)
    assert node.tree.persisted_number == 6  # 8 - threshold
    head_before = node.tree.persisted_hash
    # kill -9: no stop, no flush — reopen everything from disk
    builder2 = ChainBuilder({alice.address: Account(balance=10**21)},
                            committer=CPU)
    node2 = _mk_node(tmp_path, alice, builder2)
    assert node2.tree.persisted_number == 6
    assert node2.tree.persisted_hash == head_before
    assert node2.recovery["status"] == "ok"
    assert node2.recovery["root_verified"] is True
    assert node2.recovery["replayed_records"] > 0
    # liveness: keeps mining from the recovered state
    with node2.factory.provider() as p:
        alice.nonce = p.account(alice.address).nonce
    _mine(node2, alice, 1, start=100)
    assert node2.tree.head_hash != head_before
    node2.stop()


def test_flush_cadence_without_wal(tmp_path):
    """Satellite: with the WAL off, the image is still flushed at every
    persistence advance — durability tracks the threshold, not
    process lifetime (the old behavior flushed only in Node.stop)."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    node = _mk_node(tmp_path, alice, builder, wal=False)
    assert node.durability is None
    _mine(node, alice, 6)
    assert node.tree.persisted_number == 4
    # kill -9 now: the image alone must already hold the persisted chain
    img = pickle.load(open(tmp_path / "db.bin", "rb"))
    tip = max(int.from_bytes(k, "big")
              for k in img[Tables.CanonicalHeaders.name])
    assert tip == 4


def test_graceful_stop_checkpoints_and_replays_nothing(tmp_path):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    node = _mk_node(tmp_path, alice, builder)
    _mine(node, alice, 5)
    node.stop()
    db2, dur2 = reopen(tmp_path)
    assert dur2.replay_report()["records"] == 0  # log truncated at stop
    f = ProviderFactory(db2)
    with f.provider() as p:
        assert p.last_block_number() == 3


def test_reorg_across_restart(tmp_path):
    """Satellite: unwind the persisted chain (deep reorg), kill, restart
    — the recovered node re-serves the branch-point head and accepts the
    other fork's blocks."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.storage.genesis import init_genesis

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    for i in range(6):
        builder.build_block([alice.transfer(b"\xaa" * 20, 100 + i)])
    # fork B shares blocks 1-2, diverges at 3
    alice_b = Wallet(0xA11CE)
    builder_b = ChainBuilder({alice_b.address: Account(balance=10**21)},
                             committer=CPU)
    for i in range(2):
        builder_b.build_block([alice_b.transfer(b"\xaa" * 20, 100 + i)])
    fork3 = builder_b.build_block([alice_b.transfer(b"\xbb" * 20, 999)],
                                  timestamp=900)
    assert fork3.header.parent_hash == builder.blocks[2].hash

    db, dur = reopen(tmp_path)
    factory = ProviderFactory(db)
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=1)
    tree.durability = dur
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 5
    # deep reorg to fork B: unwinds the persisted chain to block 2
    assert tree.on_new_payload(fork3).status is not PayloadStatusKind.INVALID
    st = tree.on_forkchoice_updated(fork3.hash)
    assert st.status is PayloadStatusKind.VALID
    assert tree.persisted_number == 2

    # kill -9, restart
    db2, dur2 = reopen(tmp_path)
    factory2 = ProviderFactory(db2)
    from reth_tpu.storage.recovery import recover_on_startup

    report = recover_on_startup(factory2, durability=dur2, committer=CPU)
    assert report["status"] in ("ok", "degraded")
    assert report["root_verified"] is True
    tree2 = EngineTree(factory2, committer=CPU, persistence_threshold=1)
    tree2.durability = dur2
    # re-serves the branch-point head...
    assert tree2.persisted_number == 2
    assert tree2.persisted_hash == builder.blocks[2].hash
    # ...and accepts the other fork again
    assert tree2.on_new_payload(fork3).status is PayloadStatusKind.VALID
    assert tree2.on_forkchoice_updated(
        fork3.hash).status is PayloadStatusKind.VALID
    assert tree2.head_hash == fork3.hash


def test_interrupted_unwind_healed_on_restart(tmp_path):
    """The 'unwind' crash window without a subprocess: the unwind
    marker + per-stage commits land on disk, the canonical surgery does
    not — recovery must complete the unwind to the marker target."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.storage.recovery import UNWIND_MARKER_KEY, recover_on_startup

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    for i in range(5):
        builder.build_block([alice.transfer(b"\xaa" * 20, 100 + i)])
    db, dur = reopen(tmp_path)
    factory = ProviderFactory(db)
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=1)
    tree.durability = dur
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 4
    # simulate the crash window: marker + pipeline unwind committed,
    # canonical-header surgery never ran
    with factory.provider_rw() as p:
        p.tx.put(Tables.Metadata.name, UNWIND_MARKER_KEY,
                 (2).to_bytes(8, "big"))
    Pipeline(factory, default_stages(committer=CPU)).unwind(2)

    db2, dur2 = reopen(tmp_path)
    factory2 = ProviderFactory(db2)
    report = recover_on_startup(factory2, durability=dur2, committer=CPU)
    assert any("completed interrupted unwind" in h for h in report["healed"])
    assert report["status"] == "degraded"
    assert report["head_number"] == 2
    assert report["root_verified"] is True
    with factory2.provider() as p:
        assert p.last_block_number() == 2
        assert p.tx.get(Tables.Metadata.name, UNWIND_MARKER_KEY) is None


# -- recovery catches real corruption (harness can fail) ----------------------


def test_recovery_detects_corruption_injected_via_torn_acceptance(
        tmp_path, monkeypatch):
    """Acceptance: a deliberately broken recovery (torn WAL record
    accepted) is CAUGHT by the invariant suite — the recovered root no
    longer matches recomputation, recovery reports failed."""
    from reth_tpu.chaos import inject_bad_crc_record
    from reth_tpu.storage.recovery import recover_on_startup

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    node = _mk_node(tmp_path, alice, builder)
    _mine(node, alice, 6)
    # bit-rot one hashed-account row via a bad-CRC record
    victim_key = keccak256_batch_np([alice.address])[0]
    inject_bad_crc_record(tmp_path / "wal", {
        Tables.HashedAccounts.name: {
            "rows": {victim_key: b"\xde\xad" * 30}, "del": []},
    })
    # correct reader: tail discarded, recovery ok
    db2, dur2 = reopen(tmp_path)
    report = recover_on_startup(ProviderFactory(db2), durability=dur2,
                                committer=CPU)
    assert report["status"] in ("ok", "degraded")
    assert report["root_verified"] is True
    # broken reader: record applied -> the root proof must catch it
    # (re-injected: the correct reader truncated the torn tail)
    inject_bad_crc_record(tmp_path / "wal", {
        Tables.HashedAccounts.name: {
            "rows": {victim_key: b"\xde\xad" * 30}, "del": []},
    })
    monkeypatch.setenv("RETH_TPU_FAULT_WAL_ACCEPT_TORN", "1")
    db3, dur3 = reopen(tmp_path)
    report3 = recover_on_startup(ProviderFactory(db3), durability=dur3,
                                 committer=CPU)
    assert report3["status"] == "failed"
    assert report3["root_verified"] is False
    assert any("mismatch" in p or "crash" in p for p in report3["problems"])


# -- surfaces: metrics, events line, health rule ------------------------------


def test_recovery_metrics_surface(tmp_path):
    from reth_tpu.metrics import REGISTRY, wal_metrics

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    node = _mk_node(tmp_path, alice, builder)
    _mine(node, alice, 6)
    builder2 = ChainBuilder({alice.address: Account(balance=10**21)},
                            committer=CPU)
    node2 = _mk_node(tmp_path, alice, builder2)  # kill-sim restart
    assert REGISTRY.gauge("recovery_status").value == 0
    assert REGISTRY.counter("wal_appends_total").value > 0
    assert wal_metrics.last_recovery is not None
    assert wal_metrics.last_recovery["status"] == "ok"
    node2.stop()


def test_events_line_carries_wal_fragment(tmp_path):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    node = _mk_node(tmp_path, alice, builder)
    _mine(node, alice, 4)
    line = node.event_reporter.report_once()
    assert line is not None and "wal[gen=" in line
    node.stop()


def test_health_rule_pages_on_failed_recovery():
    from reth_tpu.health import HealthEngine, default_rules
    from reth_tpu.metrics import MetricsRegistry

    rules = [r for r in default_rules() if r.name == "recovery_failed"]
    assert rules, "durability rule missing from the default table"
    reg = MetricsRegistry()
    g = reg.gauge("recovery_status")
    eng = HealthEngine(reg, rules, interval=0)
    g.set(0)
    eng.tick()
    assert eng.components().get("durability", "ok") == "ok"
    g.set(1)  # degraded recovery (healed): current health stays ok
    eng.tick()
    assert eng.components().get("durability", "ok") == "ok"
    g.set(2)  # provably-wrong recovered state: must page
    for _ in range(6):
        eng.tick()
    assert eng.components()["durability"] != "ok"


def test_jar_hygiene_quarantines_bad_digest(tmp_path):
    from reth_tpu.storage.nippyjar import NippyJar
    from reth_tpu.storage.recovery import recover_on_startup

    static = tmp_path / "static_files"
    static.mkdir()
    NippyJar.write(static / "headers_0_1.sf", {"h": [b"a", b"b"]})
    (static / "headers_2_3.sf.tmp").write_bytes(b"half-written")
    # corrupt the jar's data section in place (kept header)
    raw = bytearray((static / "headers_0_1.sf").read_bytes())
    raw[-1] ^= 0xFF
    (static / "headers_0_1.sf").write_bytes(bytes(raw))
    db = MemDb(tmp_path / "db.bin")
    report = recover_on_startup(ProviderFactory(db), committer=CPU,
                                static_dir=static, verify_root=False)
    assert report["status"] == "degraded"
    assert not (static / "headers_2_3.sf.tmp").exists()
    assert not (static / "headers_0_1.sf").exists()
    assert any("digest" in p for p in report["problems"])
    assert any(q.endswith(".corrupt") for q in report["quarantined"])
