"""Shared device hash service (reth_tpu/ops/hash_service.py).

The acceptance drill: N concurrent clients (live-tip + payload + rebuild
+ proof lanes) get digests bit-identical to direct backend calls, with a
measured coalesce factor > 1 reported through the ``hash_service_*``
metrics; a mid-dispatch device trip (supervisor wedge or injected
service fault) fails over to the numpy twin completing EVERY in-flight
future exactly once — no request lost, none double-completed. Everything
here runs CPU-only (JAX_PLATFORMS=cpu via conftest); injectors stand in
for the wedged tunnel.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from reth_tpu.metrics import MetricsRegistry
from reth_tpu.ops.hash_service import (
    LANES,
    HashService,
    LaneOverloaded,
    ServiceFaultInjector,
)
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.rlp import rlp_encode


def _svc(**kw):
    kw.setdefault("backend", keccak256_batch_np)
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("min_tier", 8)
    return HashService(**kw)


def _msgs(seed: int, n: int, lo: int = 1, hi: int = 300) -> list[bytes]:
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size=int(rng.integers(lo, hi)),
                         dtype=np.uint8).tobytes() for _ in range(n)]


@pytest.fixture
def svc():
    s = _svc()
    yield s
    s.stop()


# -- core correctness --------------------------------------------------------


def test_single_request_roundtrip(svc):
    msgs = _msgs(1, 10)
    assert svc.client("live")(msgs) == [keccak256(m) for m in msgs]


def test_lone_request_skips_coalescing_window():
    """A single pending request dispatches immediately — the synchronous
    latency path must never pay the full coalescing window."""
    svc = _svc(window_s=0.25)  # pathological window: eager path must win
    t0 = time.monotonic()
    svc.client("live")([b"solo"])
    elapsed = time.monotonic() - t0
    svc.stop()
    assert elapsed < 0.2, f"lone request waited the window ({elapsed:.3f}s)"


def test_empty_request_fast_path(svc):
    assert svc.client("proof")([]) == []
    assert svc.dispatches == 0  # no backend call for an empty batch


def test_lane_names_validated(svc):
    with pytest.raises(ValueError):
        svc.client("turbo-boost")
    with pytest.raises(ValueError):
        svc.submit("nope", [b"x"])


def test_multithreaded_stress_bit_identical_and_coalesced():
    """THE acceptance drill: concurrent live-tip + payload + rebuild +
    proof clients, many small batches each, digests bit-identical to
    direct hashing, coalesce factor > 1 on the service metrics."""
    reg = MetricsRegistry()
    svc = _svc(registry=reg, window_s=0.004, fill_target=512)
    results: dict[int, tuple[list[bytes], list[bytes]]] = {}
    errors: list[BaseException] = []

    def client_thread(i: int):
        lane = LANES[i % len(LANES)]
        client = svc.client(lane)
        try:
            for j in range(6):
                msgs = _msgs(100 * i + j, 7)
                results[(i, j)] = (msgs, client(msgs))
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client_thread, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    svc.stop()
    assert not errors
    assert len(results) == 16 * 6
    for msgs, digests in results.values():
        assert digests == [keccak256(m) for m in msgs]
    # 96 requests must have fused into far fewer dispatches
    assert svc.dispatches < 96
    assert svc.coalesce_factor() > 1.0
    rendered = reg.render()
    assert "hash_service_dispatches_total" in rendered

    def sample(name: str) -> float:
        line = next(l for l in rendered.splitlines()
                    if l.startswith(name + " "))
        return float(line.split()[1])

    assert sample("hash_service_coalesce_factor") > 1.0
    assert 0.0 < sample("hash_service_batch_occupancy") <= 1.0
    for lane in LANES:
        assert f"hash_service_queue_depth_{lane} 0" in rendered
    assert "hash_service_wait_seconds_live_count" in rendered
    assert "hash_service_service_seconds_count" in rendered


def test_mixed_lane_burst_single_dispatch():
    """Requests queued while the dispatcher is held by a lease drain as
    ONE coalesced dispatch on release, ordered live > payload > rebuild >
    proof (priority) within the fused batch."""
    seen: list[list[bytes]] = []

    def backend(msgs):
        seen.append(list(msgs))
        return keccak256_batch_np(msgs)

    svc = _svc(backend=backend, window_s=0.01, lease_bypass_s=10.0)
    futs = {}
    with svc.lease("hold"):
        for lane, payload in (("proof", b"p"), ("live", b"l"),
                              ("rebuild", b"r"), ("payload", b"b")):
            futs[lane] = svc.submit(lane, [payload])
            time.sleep(0.002)  # deterministic enqueue order
    out = {lane: f.result(5.0) for lane, f in futs.items()}
    svc.stop()
    assert out == {"proof": [keccak256(b"p")], "live": [keccak256(b"l")],
                   "rebuild": [keccak256(b"r")], "payload": [keccak256(b"b")]}
    assert len(seen) == 1  # everything fused into one dispatch
    # priority order inside the fused batch, not arrival order
    assert seen[0] == [b"l", b"b", b"r", b"p"]


def test_aging_promotes_starved_lane():
    """A proof request older than age_promote_s is drained FIRST even
    though live requests are queued ahead of it in priority."""
    seen: list[list[bytes]] = []

    def backend(msgs):
        seen.append(list(msgs))
        return keccak256_batch_np(msgs)

    svc = _svc(backend=backend, window_s=0.05, age_promote_s=0.01,
               lease_bypass_s=10.0)
    with svc.lease("hold"):
        f_proof = svc.submit("proof", [b"old"])
        time.sleep(0.03)  # let the proof request age past the threshold
        f_live = svc.submit("live", [b"new"])
    f_proof.result(5.0), f_live.result(5.0)
    svc.stop()
    assert seen[0][0] == b"old"  # aged request leads the fused batch


# -- backpressure ------------------------------------------------------------


def test_backpressure_rejects_when_asked_not_to_block():
    svc = _svc(lane_capacity=4, window_s=0.5, lease_bypass_s=10.0)
    with svc.lease("hold"):  # dispatcher paused: the queue can only grow
        svc.submit("proof", [b"a"] * 4)
        with pytest.raises(LaneOverloaded):
            svc.submit("proof", [b"b"], block=False)
        # other lanes are unaffected (per-lane bounds)
        f = svc.submit("live", [b"c"], block=False)
    assert f.result(5.0) == [keccak256(b"c")]
    svc.stop()
    assert svc.rejects == 1


def test_backpressure_blocks_then_completes():
    """A blocked submitter resumes as soon as the dispatcher drains the
    lane — bounded memory, zero lost requests."""
    svc = _svc(lane_capacity=8, window_s=0.001)
    done: list[list[bytes]] = []

    def submitter():
        for i in range(30):
            done.append(svc.client("rebuild")([b"%d" % i] * 4))

    t = threading.Thread(target=submitter)
    t.start()
    t.join(timeout=30)
    alive = t.is_alive()
    svc.stop()
    assert not alive
    assert done == [[keccak256(b"%d" % i)] * 4 for i in range(30)]


def test_backpressure_timeout():
    svc = _svc(lane_capacity=2, window_s=0.5, lease_bypass_s=10.0)
    with svc.lease("hold"):
        svc.submit("proof", [b"a", b"b"])
        with pytest.raises(LaneOverloaded):
            svc.submit("proof", [b"c"], timeout=0.05)
    svc.stop()


def test_oversized_request_admitted_alone():
    svc = _svc(lane_capacity=4, window_s=0.001)
    msgs = [b"%d" % i for i in range(64)]  # 16x the lane bound
    assert svc.client("rebuild")(msgs) == [keccak256(m) for m in msgs]
    svc.stop()


# -- exclusive lease ---------------------------------------------------------


def test_lease_pauses_device_dispatch_and_bypasses_aged():
    device_calls: list[int] = []

    def backend(msgs):
        device_calls.append(len(msgs))
        return keccak256_batch_np(msgs)

    svc = _svc(backend=backend, window_s=0.002, lease_bypass_s=0.01)
    with svc.lease("rebuild"):
        f = svc.submit("live", [b"tip"])
        out = f.result(5.0)  # completes WHILE leased, via the CPU twin
        assert out == [keccak256(b"tip")]
        assert device_calls == []  # the device was never touched
    svc.stop()
    assert svc.lease_bypasses == 1
    assert svc.leases == 1


def test_lease_backend_wraps_turbo_commit():
    """TurboCommitter(hash_service=...) holds the exclusive lease for each
    commit; roots stay bit-identical to the unleased committer, and an
    aborted commit releases the lease (no wedged service)."""
    from reth_tpu.ops.supervisor import FaultInjector, InjectedPipelineAbort
    from reth_tpu.trie.turbo import TurboCommitter

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 256, size=(400, 32), dtype=np.uint8)
    keys = np.unique(keys.view("S32").ravel()).view(np.uint8).reshape(-1, 32)
    vals = [rlp_encode(bytes(rng.integers(0, 256, size=1 + i % 29,
                                          dtype=np.uint8)))
            for i in range(len(keys))]
    jobs = [(keys[: len(keys) // 2], vals[: len(keys) // 2]),
            (keys[len(keys) // 2:], vals[len(keys) // 2:])]

    base = TurboCommitter(backend="numpy")
    want = [r.root for r in base.commit_hashed_many(jobs)]

    svc = _svc(window_s=0.001)
    leased = TurboCommitter(backend="numpy", hash_service=svc)
    # numpy backend takes no lease (it never touches the device)
    assert [r.root for r in leased.commit_hashed_many(jobs)] == want
    assert svc.leases == 0

    # a device-kind committer DOES lease; fake the engine with the numpy
    # twin so the lease path runs hardware-free
    from reth_tpu.trie.turbo import _NumpyBackend

    dev = TurboCommitter(backend="device", hash_service=svc)
    dev._device_engine = lambda: _NumpyBackend(arena=dev.arena)
    assert [r.root for r in dev.commit_hashed_many(jobs)] == want
    assert svc.leases == 1
    with svc._cond:
        assert not svc._leased  # released at the terminal fetch

    # aborted pipelined commit: the finally-path must drop the lease
    dev.supervisor = type("S", (), {"injector": FaultInjector(pipeline_abort=1)})()
    with pytest.raises(InjectedPipelineAbort):
        dev.commit_hashed_pipelined(jobs, pack_window=1, sweep_workers=1,
                                    leaves_per_sweep=64)
    with svc._cond:
        assert not svc._leased
    # and the service still works afterwards
    assert svc.client("live")([b"post"]) == [keccak256(b"post")]
    svc.stop()


# -- failover / fault injection ----------------------------------------------


def test_injected_wedge_replays_on_twin_every_future_completes():
    """RETH_TPU_FAULT_SERVICE_WEDGE_EVERY=1: every coalesced dispatch
    wedges before touching the backend; the numpy-twin replay completes
    every in-flight future exactly once with correct digests."""
    device_calls: list[int] = []

    def backend(msgs):  # pragma: no cover - must never run
        device_calls.append(len(msgs))
        return keccak256_batch_np(msgs)

    inj = ServiceFaultInjector(wedge_every=1)
    svc = _svc(backend=backend, injector=inj, window_s=0.002)
    futs = [svc.submit(LANES[i % 4], [b"w%d" % i, b"v%d" % i])
            for i in range(12)]
    outs = [f.result(10.0) for f in futs]
    svc.stop()
    assert outs == [[keccak256(b"w%d" % i), keccak256(b"v%d" % i)]
                    for i in range(12)]
    assert [f.completions for f in futs] == [1] * 12  # no double-complete
    assert device_calls == []
    assert svc.replays >= 1
    assert inj.wedged >= 1


def test_supervised_backend_mid_dispatch_trip_fails_over():
    """The service composed with the SUPERVISOR: a wedge injected inside
    the supervised hasher trips the watchdog path; the breaker sees the
    failure and the batch still completes on the CPU (either via the
    supervisor's own fallback or the service replay) — the acceptance
    criterion's mid-dispatch device trip."""
    from reth_tpu.ops.supervisor import (
        DeviceSupervisor,
        FaultInjector,
        ProbeResult,
        SupervisedHasher,
    )

    sup = DeviceSupervisor(
        dispatch_budget=30.0,
        injector=FaultInjector(wedge_every=1),
        probe_fn=lambda budget, injector=None: ProbeResult(True, 0.001),
        registry=MetricsRegistry(),
    )
    hasher = SupervisedHasher(sup, device_hasher=keccak256_batch_np)
    svc = _svc(backend=hasher, supervisor=sup, window_s=0.002)
    msgs = _msgs(3, 40)
    futs = [svc.submit("live", msgs[i:i + 4]) for i in range(0, 40, 4)]
    outs = [f.result(15.0) for f in futs]
    svc.stop()
    flat = [d for out in outs for d in out]
    assert flat == [keccak256(m) for m in msgs]
    assert [f.completions for f in futs] == [1] * 10
    assert sup.dispatch_errors >= 1  # the trip really happened mid-dispatch


def test_fault_injector_from_env(monkeypatch):
    monkeypatch.setenv("RETH_TPU_FAULT_SERVICE_WEDGE_EVERY", "3")
    monkeypatch.setenv("RETH_TPU_FAULT_SERVICE_STALL", "0.001")
    monkeypatch.setenv("RETH_TPU_FAULT_SERVICE_QUEUE_CAP", "16")
    inj = ServiceFaultInjector.from_env()
    assert inj is not None and inj.active()
    assert (inj.wedge_every, inj.stall, inj.queue_cap) == (3, 0.001, 16)
    svc = _svc(injector=inj)
    assert svc.lane_capacity == 16  # overload drill shrinks the lanes
    out = svc.client("proof")([b"a", b"b", b"c"])
    assert out == [keccak256(b"a"), keccak256(b"b"), keccak256(b"c")]
    svc.stop()
    monkeypatch.delenv("RETH_TPU_FAULT_SERVICE_WEDGE_EVERY")
    monkeypatch.delenv("RETH_TPU_FAULT_SERVICE_STALL")
    monkeypatch.delenv("RETH_TPU_FAULT_SERVICE_QUEUE_CAP")
    assert ServiceFaultInjector.from_env() is None


def test_overload_stall_drill_backs_up_then_drains():
    """RETH_TPU_FAULT_SERVICE_STALL: slow dispatches back requests up
    into the bounded lanes; everything still completes, in order, and
    the queue-depth gauge returns to zero."""
    reg = MetricsRegistry()
    inj = ServiceFaultInjector(stall=0.01)
    svc = _svc(registry=reg, injector=inj, window_s=0.001, lane_capacity=64)
    futs = [svc.submit("payload", [b"s%d" % i]) for i in range(20)]
    outs = [f.result(30.0) for f in futs]
    svc.stop()
    assert outs == [[keccak256(b"s%d" % i)] for i in range(20)]
    assert "hash_service_queue_depth_payload 0" in reg.render()


# -- lifecycle ---------------------------------------------------------------


def test_stop_drains_pending_requests():
    svc = _svc(window_s=0.2, lease_bypass_s=10.0)
    with svc.lease("hold"):
        futs = [svc.submit("proof", [b"d%d" % i]) for i in range(5)]
    svc.stop(drain=True)
    assert [f.result(1.0) for f in futs] == [[keccak256(b"d%d" % i)]
                                             for i in range(5)]


def test_stop_without_drain_fails_pending():
    from reth_tpu.ops.hash_service import ServiceStopped

    svc = _svc(window_s=10.0, lease_bypass_s=30.0)
    with svc.lease("hold"):
        fut = svc.submit("proof", [b"x"])
        svc.stop(drain=False)
    with pytest.raises(ServiceStopped):
        fut.result(1.0)


def test_snapshot_shape(svc):
    svc.client("live")([b"x"])
    s = svc.snapshot()
    assert s["dispatches"] >= 1
    assert s["queued_total"] == 0
    assert set(s["queued"]) == set(LANES)
    assert s["fault_injection"] is False


# -- client integration ------------------------------------------------------


def test_for_lane_binds_committer_clients():
    from reth_tpu.trie.committer import TrieCommitter

    svc = _svc()
    committer = TrieCommitter(hasher=keccak256_batch_np)
    committer.hash_service = svc
    committer.hasher = svc.client("live")
    proof = committer.for_lane("proof")
    assert proof is not committer
    assert proof.hasher.lane == "proof"
    assert proof.hash_service is svc
    # no service -> identity
    plain = TrieCommitter(hasher=keccak256_batch_np)
    assert plain.for_lane("proof") is plain
    # lane-bound committers produce identical roots
    leaves = [(bytes([i]) * 64, rlp_encode(b"v%d" % i)) for i in range(16)]
    assert (committer.commit(leaves).root
            == proof.commit(leaves).root
            == plain.commit(leaves).root)
    svc.stop()


def test_proof_calculator_and_sparse_use_service_lanes():
    """End-to-end: a ChainBuilder-backed multiproof through a service-lane
    committer matches the direct committer bit-for-bit."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.primitives import Account
    from reth_tpu.stages import Pipeline, default_stages
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import import_chain, init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter
    from reth_tpu.trie.proof import ProofCalculator, verify_account_proof

    direct = TrieCommitter(hasher=keccak256_batch_np)
    svc = _svc()
    via = TrieCommitter(hasher=keccak256_batch_np)
    via.hash_service = svc
    via.hasher = svc.client("live")

    a, b = Wallet(0xAA), Wallet(0xBB)
    builder = ChainBuilder({a.address: Account(balance=10**18),
                            b.address: Account(balance=10**18)})
    builder.build_block([a.transfer(b.address, 1000)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=direct)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(direct))
    Pipeline(factory, default_stages(committer=direct)).run(1)

    with factory.provider() as provider:
        want = ProofCalculator(provider, direct).account_proof(a.address)
        got = ProofCalculator(provider, via).account_proof(a.address)
    assert got.proof == want.proof
    assert got.storage_root == want.storage_root
    root = builder.blocks[1].header.state_root
    assert verify_account_proof(root, a.address, got)
    assert svc.dispatches >= 1  # the proof work really rode the service
    svc.stop()
