"""EVM edge cases: call semantics, create collisions, static violations.

Reference analogue: the slice of ethereum/tests GeneralStateTests
behaviors most likely to diverge in a from-scratch interpreter.
"""

from reth_tpu.evm.interpreter import BlockEnv, CallFrame, Interpreter, Revert, TxEnv
from reth_tpu.evm.state import EvmState
from reth_tpu.evm.executor import InMemoryStateSource
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256

A = b"\x0a" * 20


def run_code(code, value=0, gas=1_000_000, accounts=None, storages=None, codes=None,
             caller=A, addr=b"\x10" * 20, data=b""):
    src = InMemoryStateSource(accounts or {caller: Account(balance=10**18)},
                              storages, codes)
    state = EvmState(src)
    interp = Interpreter(state, BlockEnv(), TxEnv(origin=caller))
    ok, gas_left, out = interp.call(CallFrame(
        caller=caller, address=addr, code=code, data=data, value=value, gas=gas))
    return ok, gas_left, out, state


def test_staticcall_blocks_sstore():
    # target: PUSH1 1 PUSH0 SSTORE STOP
    target = bytes.fromhex("60015f5500")
    taddr = b"\x11" * 20
    # caller: STATICCALL target, push result, sstore result to slot1, STOP
    code = (bytes.fromhex("5f5f5f5f73") + taddr + bytes.fromhex("5afa")
            + bytes.fromhex("600155 00".replace(" ", "")))
    ok, _, _, state = run_code(
        code,
        accounts={A: Account(balance=1), b"\x10" * 20: Account(),
                  taddr: Account(code_hash=keccak256(target))},
        codes={keccak256(target): target},
    )
    assert ok
    # STATICCALL returned 0 (inner halted on SSTORE); nothing written there
    assert state.sload(b"\x10" * 20, (1).to_bytes(32, "big")) == 0
    assert state.sload(taddr, b"\x00" * 32) == 0


def test_nested_revert_isolated():
    """Inner call's storage write reverts; outer's survives."""
    inner = bytes.fromhex("60015f555f5ffd")  # sstore(0,1); revert
    iaddr = b"\x12" * 20
    # outer: sstore(1, 0xAA); CALL inner; STOP
    outer = (bytes.fromhex("60aa600155")
             + bytes.fromhex("5f5f5f5f5f73") + iaddr + bytes.fromhex("5af1")
             + bytes.fromhex("00"))
    ok, _, _, state = run_code(
        outer,
        accounts={A: Account(balance=1), b"\x10" * 20: Account(),
                  iaddr: Account(code_hash=keccak256(inner))},
        codes={keccak256(inner): inner},
    )
    assert ok
    assert state.sload(b"\x10" * 20, (1).to_bytes(32, "big")) == 0xAA
    assert state.sload(iaddr, b"\x00" * 32) == 0


def test_create2_collision_fails():
    src = InMemoryStateSource({A: Account(balance=10**18, nonce=1)})
    state = EvmState(src)
    interp = Interpreter(state, BlockEnv(), TxEnv(origin=A))
    initcode = bytes.fromhex("5f5ff3")  # returns empty code
    ok1, _, addr1, _ = interp.create(A, 0, initcode, 1_000_000, 0, salt=b"\x01" * 32)
    assert ok1
    # same salt + initcode -> same address, now occupied (nonce=1) -> fail
    ok2, gas_left, addr2, _ = interp.create(A, 0, initcode, 1_000_000, 0, salt=b"\x01" * 32)
    assert not ok2 and gas_left == 0


def test_call_depth_limit():
    """Self-recursive CALL bottoms out at depth 1024 without crashing."""
    myaddr = b"\x13" * 20
    # code: CALL self with all gas; STOP
    code = bytes.fromhex("5f5f5f5f5f73") + myaddr + bytes.fromhex("5af100")
    ok, _, _, state = run_code(
        code,
        accounts={A: Account(balance=1), myaddr: Account(code_hash=keccak256(code))},
        codes={keccak256(code): code},
        addr=myaddr, gas=20_000_000,
    )
    assert ok  # outer frame completes; inner failures absorbed


def test_extcodehash_semantics():
    # EXTCODEHASH of nonexistent account -> 0
    ok, _, _, state = run_code(bytes.fromhex("73") + b"\x77" * 20 + bytes.fromhex("3f5f55"))
    assert ok
    assert state.sload(b"\x10" * 20, b"\x00" * 32) == 0
    # of an existing EOA with balance -> keccak(empty)
    eoa = b"\x78" * 20
    ok, _, _, state = run_code(
        bytes.fromhex("73") + eoa + bytes.fromhex("3f5f55"),
        accounts={A: Account(balance=1), eoa: Account(balance=5)},
    )
    assert ok
    assert state.sload(b"\x10" * 20, b"\x00" * 32) == int.from_bytes(keccak256(b""), "big")


def test_returndata_copy_oob_halts():
    # RETURNDATACOPY with no prior call and size>0 must halt
    code = bytes.fromhex("60205f5f3e00")  # returndatacopy(0,0,32)
    ok, gas_left, _, _ = run_code(code)
    assert not ok and gas_left == 0


def test_memory_expansion_gas_quadratic():
    # MSTORE at a huge offset must exhaust gas (halt), not allocate
    code = bytes.fromhex("600163ffffffff52")  # mstore(0xffffffff, 1)
    ok, gas_left, _, _ = run_code(code, gas=100_000)
    assert not ok and gas_left == 0


def test_value_transfer_in_call_and_revert():
    """CALL with value; callee reverts -> value returns."""
    inner = bytes.fromhex("5f5ffd")  # revert
    iaddr = b"\x14" * 20
    outer = (bytes.fromhex("5f5f5f5f600a73") + iaddr + bytes.fromhex("5af100"))
    ok, _, _, state = run_code(
        outer,
        accounts={A: Account(balance=1),
                  b"\x10" * 20: Account(balance=100),
                  iaddr: Account(code_hash=keccak256(inner))},
        codes={keccak256(inner): inner},
    )
    assert ok
    assert state.balance(b"\x10" * 20) == 100  # transfer rolled back
    assert state.balance(iaddr) == 0


def test_selfdestruct_same_tx_created():
    """EIP-6780: a contract created and destroyed in one tx disappears."""
    src = InMemoryStateSource({A: Account(balance=10**18)})
    state = EvmState(src)
    interp = Interpreter(state, BlockEnv(), TxEnv(origin=A))
    # initcode: selfdestruct(caller) — runs during creation
    initcode = bytes.fromhex("33ff")
    ok, _, addr, _ = interp.create(A, 5, initcode, 1_000_000, 0)
    assert ok
    state.process_destructs()  # deletion lands at end of transaction
    assert state.account(addr) is None
    assert state.balance(A) == 10**18  # value came back via beneficiary


def test_create2_redeploy_after_same_block_selfdestruct():
    """EIP-6780 scoping: a selfdestruct in tx1 must not suppress the code
    deposit of a CREATE2 redeploy at the same address in tx2."""
    src = InMemoryStateSource({A: Account(balance=10**18)})
    state = EvmState(src)
    interp = Interpreter(state, BlockEnv(), TxEnv(origin=A))
    # tx1: create a contract whose initcode selfdestructs -> dead
    ok, _, addr, _ = interp.create(A, 0, bytes.fromhex("33ff"), 1_000_000, 0,
                                   salt=b"\x02" * 32)
    assert ok
    state.process_destructs()
    assert state.account(addr) is None
    # tx2 boundary: stale _selfdestructs membership persists (block scope)
    state.begin_tx()
    assert addr in state._selfdestructs
    interp2 = Interpreter(state, BlockEnv(), TxEnv(origin=A))
    # redeploy with the SAME initcode (same CREATE2 address): it dies again
    # (created-this-tx) and must stay dead, not resurrect as empty
    ok2, _, addr2, _ = interp2.create(A, 0, bytes.fromhex("33ff"), 1_000_000, 0,
                                      salt=b"\x02" * 32)
    assert ok2 and addr2 == addr
    state.process_destructs()
    assert state.account(addr) is None
    # and an initcode that survives deposits real code despite the stale
    # membership: PUSH1 1 PUSH0 MSTORE8 PUSH1 1 PUSH0 RETURN → runtime 0x01
    state.begin_tx()
    interp3 = Interpreter(state, BlockEnv(), TxEnv(origin=A))
    live_init = bytes.fromhex("60015f5360015ff3")
    ok3, _, addr3, _ = interp3.create(A, 0, live_init, 1_000_000, 0,
                                      salt=b"\x03" * 32)
    assert ok3
    # now selfdestruct it (same tx -> dead), then in a LATER tx redeploy the
    # exact same (initcode, salt): guard must allow the code deposit
    state.selfdestruct(addr3, A)
    state.process_destructs()
    assert state.account(addr3) is None
    state.begin_tx()
    interp4 = Interpreter(state, BlockEnv(), TxEnv(origin=A))
    ok4, _, addr4, _ = interp4.create(A, 0, live_init, 1_000_000, 0,
                                      salt=b"\x03" * 32)
    assert ok4 and addr4 == addr3
    assert state.code(addr4) == b"\x01"  # deposited despite stale membership


def test_gas_opcode_63_64_rule():
    """CALL forwards at most 63/64 of remaining gas."""
    # inner: burn everything (invalid opcode)
    inner = bytes.fromhex("fe")
    iaddr = b"\x15" * 20
    outer = bytes.fromhex("5f5f5f5f5f73") + iaddr + bytes.fromhex("5af100")
    ok, gas_left, _, _ = run_code(
        outer,
        accounts={A: Account(balance=1), iaddr: Account(code_hash=keccak256(inner))},
        codes={keccak256(inner): inner},
        gas=640_000,
    )
    assert ok
    # outer keeps >= 1/64 of the gas at the call site
    assert gas_left > 640_000 // 64 - 1000


def test_depth_1024_chain_without_recursion_limit():
    """The trampoline (explicit generator frame stack) runs an EVM
    depth-limit call chain at CPython's DEFAULT recursion limit — no
    setrecursionlimit anywhere (round-4: de-recursed interpreter)."""
    import sys

    from reth_tpu.primitives.keccak import keccak256

    assert sys.getrecursionlimit() <= 1100  # nobody raised it
    # PUSH0 x5 ADDRESS GAS CALL STOP — calls itself until depth 1024
    rt = bytes([0x5F] * 5 + [0x30, 0x5A, 0xF1, 0x00])
    caller, contract = b"\x11" * 20, b"\x22" * 20
    src = InMemoryStateSource(
        {caller: Account(balance=10**18),
         contract: Account(code_hash=keccak256(rt))},
        codes={keccak256(rt): rt},
    )
    state = EvmState(src)
    depths = []
    interp = Interpreter(state, BlockEnv(), TxEnv(origin=caller),
                         tracer=lambda pc, op, gas, st, mem, d: depths.append(d))
    # enough gas that the 63/64 rule cannot stop the chain before the
    # EVM depth cap: the chain MUST terminate at MAX_CALL_DEPTH
    ok, gas_left, _ = interp.call(CallFrame(
        caller=caller, address=contract, code=rt, data=b"", value=0,
        gas=100_000_000_000))
    assert ok
    assert max(depths) == 1024  # hit the cap exactly, then unwound


# -- EIP-6110 deposit log decoding + system-call failure propagation ---------


def _abi_encode_deposit(pubkey: bytes, wc: bytes, amount: bytes,
                        signature: bytes, index: bytes) -> bytes:
    """ABI-encode DepositEvent(bytes,bytes,bytes,bytes,bytes) data exactly
    the way the mainnet deposit contract does: 5-offset head, then per
    field a length word + right-padded payload."""
    fields = [pubkey, wc, amount, signature, index]
    head, tail = b"", b""
    offset = 32 * len(fields)
    for f in fields:
        head += offset.to_bytes(32, "big")
        padded = f + b"\x00" * (-len(f) % 32)
        tail += len(f).to_bytes(32, "big") + padded
        offset += 32 + len(padded)
    return head + tail


def _real_deposit_fields():
    """A mainnet-shaped deposit: 48-byte BLS pubkey, 32-byte withdrawal
    credentials, 8-byte LE gwei amount (32 ETH), 96-byte signature,
    8-byte LE index."""
    pubkey = bytes.fromhex(
        "b0b9d0f95f3a7a9e1c5c9c2e51f92a47f05c3f5e1a2ab4f7e6f2b8d1c4a5e6f7"
        "08192a3b4c5d6e7f8091a2b3c4d5e6f7")
    wc = b"\x01" + b"\x00" * 11 + b"\x42" * 20
    amount = (32 * 10**9).to_bytes(8, "little")
    signature = bytes(range(96))
    index = (7).to_bytes(8, "little")
    return pubkey, wc, amount, signature, index


def test_decode_deposit_log_real_layout():
    from reth_tpu.evm.executor import _decode_deposit_log

    fields = _real_deposit_fields()
    data = _abi_encode_deposit(*fields)
    assert len(data) == 576                 # the canonical contract layout
    request = _decode_deposit_log(data)
    assert request == b"".join(fields)
    assert len(request) == 192              # EIP-6110 deposit request size


def test_decode_deposit_log_rejects_malformed():
    import pytest

    from reth_tpu.evm.executor import BlockExecutionError, _decode_deposit_log

    fields = _real_deposit_fields()
    good = _abi_encode_deposit(*fields)
    with pytest.raises(BlockExecutionError, match="truncated"):
        _decode_deposit_log(good[:100])
    with pytest.raises(BlockExecutionError, match="length"):
        bad = bytearray(good)
        bad[160 + 31] = 49                  # pubkey length 48 -> 49
        _decode_deposit_log(bytes(bad))
    with pytest.raises(BlockExecutionError, match="offset"):
        bad = bytearray(good)
        bad[31] = 0xA1                      # unaligned first offset
        _decode_deposit_log(bytes(bad))
    with pytest.raises(BlockExecutionError):
        _decode_deposit_log(b"")


def test_collect_requests_extracts_deposits():
    from reth_tpu.evm.executor import (
        BlockExecutor, DEPOSIT_EVENT_TOPIC, EvmConfig,
        MAINNET_DEPOSIT_CONTRACT)
    from reth_tpu.evm.spec import LATEST_SPEC
    from reth_tpu.primitives.types import Log, Receipt

    fields = _real_deposit_fields()
    log = Log(address=MAINNET_DEPOSIT_CONTRACT,
              topics=(DEPOSIT_EVENT_TOPIC,),
              data=_abi_encode_deposit(*fields))
    noise = Log(address=b"\x99" * 20, topics=(DEPOSIT_EVENT_TOPIC,),
                data=b"\x00" * 576)         # wrong address: ignored
    receipts = [Receipt(logs=(noise, log)), Receipt(logs=(log,))]
    executor = BlockExecutor(InMemoryStateSource({}), EvmConfig())
    state = EvmState(InMemoryStateSource({}))
    requests = executor._collect_requests(state, BlockEnv(), LATEST_SPEC,
                                          receipts)
    assert requests == [b"\x00" + b"".join(fields) * 2]


def test_system_call_revert_and_halt_invalidate_block():
    import pytest

    from reth_tpu.evm.executor import (
        BEACON_ROOTS_ADDRESS, BlockExecutionError, BlockExecutor, EvmConfig,
        InvalidTransaction)
    from reth_tpu.evm.spec import LATEST_SPEC

    # PUSH1 0 PUSH1 0 REVERT — a beacon-roots contract that always reverts
    revert_code = bytes.fromhex("60006000fd")
    src = InMemoryStateSource(
        {BEACON_ROOTS_ADDRESS: Account(code_hash=keccak256(revert_code))},
        None, {keccak256(revert_code): revert_code})
    executor = BlockExecutor(src, EvmConfig())
    state = EvmState(src)
    with pytest.raises(BlockExecutionError, match="reverted"):
        executor._system_call(state, BlockEnv(), LATEST_SPEC,
                              BEACON_ROOTS_ADDRESS, b"\x11" * 32)
    # INVALID opcode halts: same propagation
    halt_code = bytes.fromhex("fe")
    src2 = InMemoryStateSource(
        {BEACON_ROOTS_ADDRESS: Account(code_hash=keccak256(halt_code))},
        None, {keccak256(halt_code): halt_code})
    with pytest.raises(BlockExecutionError, match="failed|halted"):
        BlockExecutor(src2, EvmConfig())._system_call(
            EvmState(src2), BlockEnv(), LATEST_SPEC,
            BEACON_ROOTS_ADDRESS, b"\x11" * 32)
    # the error is an InvalidTransaction subclass: every block-rejection
    # path (engine tree, pipeline) already treats it as block-invalid
    assert issubclass(BlockExecutionError, InvalidTransaction)
    # absent contract: still silently skipped (dev chains)
    src3 = InMemoryStateSource({})
    out = BlockExecutor(src3, EvmConfig())._system_call(
        EvmState(src3), BlockEnv(), LATEST_SPEC,
        BEACON_ROOTS_ADDRESS, b"\x11" * 32)
    assert out is None
