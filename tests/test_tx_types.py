"""Tx envelope types 1 (EIP-2930), 3 (EIP-4844), 4 (EIP-7702): codec
round-trips, sender recovery, and executor semantics (blob fee market,
authorization processing, delegated execution).

Reference analogue: alloy-consensus TxEnvelope variants + revm's Cancun/
Prague tx handling, exercised in the reference via ef-tests.
"""

from __future__ import annotations

import pytest

from reth_tpu.evm import BlockExecutor, EvmConfig
from reth_tpu.evm.executor import (
    InMemoryStateSource,
    InvalidTransaction,
    blob_base_fee,
    next_excess_blob_gas,
)
from reth_tpu.primitives.types import (
    Account,
    Block,
    DELEGATION_PREFIX,
    GAS_PER_BLOB,
    Header,
    Transaction,
)
from reth_tpu.testing import Wallet

CHAIN_ID = 1


def make_block(txs, excess_blob_gas=0):
    return Block(
        header=Header(number=1, gas_limit=30_000_000, base_fee_per_gas=7,
                      timestamp=1000, excess_blob_gas=excess_blob_gas,
                      blob_gas_used=sum(tx.blob_gas() for tx in txs)),
        transactions=tuple(txs),
    )


@pytest.fixture
def alice():
    return Wallet(0xA11CE)


@pytest.fixture
def src(alice):
    return InMemoryStateSource({alice.address: Account(balance=10**21)})


# -- codecs ------------------------------------------------------------------


@pytest.mark.parametrize("tx", [
    Transaction(tx_type=1, chain_id=1, nonce=3, gas_price=10**9, gas_limit=50_000,
                to=b"\x11" * 20, value=5,
                access_list=((b"\x22" * 20, (b"\x01" * 32, b"\x02" * 32)),),
                y_parity=1, r=123, s=456),
    Transaction(tx_type=3, chain_id=1, nonce=0, max_fee_per_gas=10**10,
                max_priority_fee_per_gas=10**9, gas_limit=100_000,
                to=b"\x33" * 20, max_fee_per_blob_gas=7,
                blob_versioned_hashes=(b"\x01" + b"\xaa" * 31,),
                y_parity=0, r=9, s=8),
], ids=["eip2930", "eip4844"])
def test_typed_tx_roundtrip(tx):
    assert Transaction.decode(tx.encode()) == tx
    assert tx.encode()[0] == tx.tx_type


def test_eip7702_roundtrip(alice):
    auth = alice.authorize(b"\x44" * 20, nonce=9)
    tx = Transaction(tx_type=4, chain_id=1, nonce=0, max_fee_per_gas=10**10,
                     gas_limit=100_000, to=b"\x55" * 20,
                     authorization_list=(auth,), y_parity=1, r=1, s=2)
    assert Transaction.decode(tx.encode()) == tx
    assert auth.recover_authority() == alice.address


def test_typed_sender_recovery(alice):
    tx = alice.sign_tx(Transaction(
        tx_type=1, chain_id=CHAIN_ID, nonce=0, gas_price=10**9,
        gas_limit=30_000, to=b"\x66" * 20, value=1,
        access_list=((b"\x66" * 20, ()),),
    ))
    assert tx.recover_sender() == alice.address


# -- type 1 execution --------------------------------------------------------


def test_eip2930_executes_and_prewarms(alice, src):
    bob = b"\x77" * 20
    tx = alice.sign_tx(Transaction(
        tx_type=1, chain_id=CHAIN_ID, nonce=0, gas_price=10**9,
        gas_limit=50_000, to=bob, value=1234,
        access_list=((bob, (b"\x00" * 32,)),),
    ))
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    assert out.receipts[0].tx_type == 1
    assert out.post_accounts[bob].balance == 1234
    # intrinsic: 21000 + 2400 (addr) + 1900 (slot)
    assert out.gas_used == 21_000 + 2400 + 1900


# -- type 3 (blob) execution --------------------------------------------------


def _blob_tx(alice, n_blobs=1, max_blob_fee=100, nonce=0, version=0x01):
    return alice.sign_tx(Transaction(
        tx_type=3, chain_id=CHAIN_ID, nonce=nonce, max_fee_per_gas=10**9,
        max_priority_fee_per_gas=1, gas_limit=21_000, to=b"\x88" * 20,
        value=0, max_fee_per_blob_gas=max_blob_fee,
        blob_versioned_hashes=tuple(
            bytes([version]) + bytes([i]) * 31 for i in range(n_blobs)
        ),
    ), bump_nonce=False)


def test_blob_tx_burns_blob_fee(alice, src):
    tx = _blob_tx(alice, n_blobs=2)
    start = src.accounts[alice.address].balance
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    sender_after = out.post_accounts[alice.address]
    fee = blob_base_fee(0)  # excess 0 -> 1 wei/blob-gas
    exec_cost = out.gas_used * tx.effective_gas_price(7)
    assert start - sender_after.balance == exec_cost + 2 * GAS_PER_BLOB * fee


def test_blob_tx_validation_errors(alice, src):
    with pytest.raises(InvalidTransaction, match="without blobs"):
        BlockExecutor(src).execute(make_block([
            alice.sign_tx(Transaction(tx_type=3, chain_id=CHAIN_ID, nonce=0,
                                      max_fee_per_gas=10**9, gas_limit=21_000,
                                      to=b"\x88" * 20), bump_nonce=False)]))
    with pytest.raises(InvalidTransaction, match="version"):
        BlockExecutor(src).execute(make_block([_blob_tx(alice, version=0x02)]))
    with pytest.raises(InvalidTransaction, match="cannot create"):
        bad = alice.sign_tx(Transaction(
            tx_type=3, chain_id=CHAIN_ID, nonce=0, max_fee_per_gas=10**9,
            gas_limit=60_000, to=None, max_fee_per_blob_gas=100,
            blob_versioned_hashes=(b"\x01" + b"\x00" * 31,),
        ), bump_nonce=False)
        BlockExecutor(src).execute(make_block([bad]))


def test_blob_fee_market_math():
    assert blob_base_fee(0) == 1
    assert next_excess_blob_gas(0, 6 * GAS_PER_BLOB) == 3 * GAS_PER_BLOB
    assert next_excess_blob_gas(0, 2 * GAS_PER_BLOB) == 0
    # monotone growth
    assert blob_base_fee(10 * 3 * GAS_PER_BLOB) > blob_base_fee(3 * GAS_PER_BLOB)


def test_blob_tx_insufficient_blob_fee(alice, src):
    # excess blob gas high enough that base fee > tx max
    blk = make_block([_blob_tx(alice, max_blob_fee=1)],
                     excess_blob_gas=40_000_000)
    with pytest.raises(InvalidTransaction, match="blob base fee"):
        BlockExecutor(src).execute(blk)


# -- type 4 (set-code) execution ---------------------------------------------

# runtime: sstore(0, 0x42) — proves the DELEGATE's code ran in authority ctx
SSTORE42 = bytes.fromhex("60425f55" + "00")


def test_setcode_tx_installs_delegation_and_executes(alice, src):
    from reth_tpu.primitives.keccak import keccak256

    delegate = b"\x99" * 20
    src.accounts[delegate] = Account(code_hash=keccak256(SSTORE42))
    src.codes[src.accounts[delegate].code_hash] = SSTORE42
    bob = Wallet(0xB0B)
    src.accounts[bob.address] = Account(balance=10**18)
    auth = bob.authorize(delegate, nonce=0)
    tx = alice.sign_tx(Transaction(
        tx_type=4, chain_id=CHAIN_ID, nonce=0, max_fee_per_gas=10**9,
        max_priority_fee_per_gas=1, gas_limit=200_000,
        to=bob.address, authorization_list=(auth,),
    ), bump_nonce=False)
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    # the authority's code is now the delegation designator
    post_bob = out.post_accounts[bob.address]
    assert post_bob.nonce == 1  # authorization bumped it
    # and the delegate's code executed in bob's storage context
    assert out.post_storage[bob.address][b"\x00" * 32] == 0x42


def test_setcode_invalid_auths_are_skipped(alice, src):
    bob = Wallet(0xB0B)
    src.accounts[bob.address] = Account(balance=10**18, nonce=5)
    wrong_nonce = bob.authorize(b"\x99" * 20, nonce=3)      # stale nonce
    wrong_chain = bob.authorize(b"\x99" * 20, nonce=5, chain_id=999)
    tx = alice.sign_tx(Transaction(
        tx_type=4, chain_id=CHAIN_ID, nonce=0, max_fee_per_gas=10**9,
        gas_limit=200_000, to=b"\x11" * 20,
        authorization_list=(wrong_nonce, wrong_chain),
    ), bump_nonce=False)
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    post_bob = out.post_accounts.get(bob.address)
    # untouched: nonce unchanged, no delegation installed
    assert post_bob is None or post_bob.nonce == 5


def test_setcode_requires_auth_list(alice, src):
    tx = alice.sign_tx(Transaction(
        tx_type=4, chain_id=CHAIN_ID, nonce=0, max_fee_per_gas=10**9,
        gas_limit=100_000, to=b"\x11" * 20,
    ), bump_nonce=False)
    with pytest.raises(InvalidTransaction, match="without authorizations"):
        BlockExecutor(src).execute(make_block([tx]))


def test_plain_transfer_to_delegated_account(alice, src):
    """EIP-7702 top-level delegation: the tx destination's delegation
    target joins accessed_addresses for FREE (the EIP extends EIP-2929's
    init — validated against the reference's hive rpc-compat chain, block
    45), so a 21000-gas transfer to a delegated EOA succeeds when the
    delegate has no code, and fails IN-BLOCK (never tx-invalid) when the
    delegate's code can't run on zero remaining gas."""
    from reth_tpu.primitives.keccak import keccak256

    carol = Wallet(0xCA01)
    designator = DELEGATION_PREFIX + b"\x99" * 20
    src.accounts[carol.address] = Account(balance=10**18,
                                          code_hash=keccak256(designator))
    src.codes[keccak256(designator)] = designator
    # delegate has no code: plain 21000 transfer works
    tx = alice.transfer(carol.address, 5)
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    assert out.gas_used == 21_000
    assert out.post_accounts[carol.address].balance == 10**18 + 5

    # delegate WITH code: zero gas left after intrinsic -> in-block OOG
    # (gas consumed, nonce bumped, block still valid)
    code = bytes.fromhex("6000600055")  # any non-empty code
    src.accounts[b"\x99" * 20] = Account(code_hash=keccak256(code))
    src.codes[keccak256(code)] = code
    dave = Wallet(0xDA7E)
    src.accounts[dave.address] = Account(balance=10**18)
    tx2 = dave.transfer(carol.address, 5)
    out2 = BlockExecutor(src).execute(make_block([tx2]))
    assert not out2.receipts[0].success
    assert out2.gas_used == 21_000
    assert out2.post_accounts[dave.address].nonce == 1


def test_call_into_delegated_account_runs_delegate_code(alice, src):
    from reth_tpu.primitives.keccak import keccak256

    delegate = b"\x99" * 20
    src.accounts[delegate] = Account(code_hash=keccak256(SSTORE42))
    src.codes[keccak256(SSTORE42)] = SSTORE42
    carol = Wallet(0xCA01)
    # pre-install the delegation designator as carol's code
    designator = DELEGATION_PREFIX + delegate
    src.accounts[carol.address] = Account(balance=10**18, code_hash=keccak256(designator))
    src.codes[keccak256(designator)] = designator
    tx = alice.call(carol.address, b"")
    out = BlockExecutor(src).execute(make_block([tx]))
    assert out.receipts[0].success
    assert out.post_storage[carol.address][b"\x00" * 32] == 0x42
