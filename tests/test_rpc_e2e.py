"""RPC end-to-end: a live node over real HTTP, driven like a user + CL.

Reference analogue: crates/e2e-test-utils node tests + rpc-e2e-tests —
launch a node, submit txs over eth_, drive blocks over engine_.
"""

import json
import urllib.request

import pytest

from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.rpc.convert import data, parse_data, parse_qty
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def rpc(port, method, *params):
    req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method, "params": list(params)})
    resp = urllib.request.urlopen(
        urllib.request.Request(
            f"http://127.0.0.1:{port}/", req.encode(),
            {"Content-Type": "application/json"},
        ),
        timeout=30,
    )
    out = json.loads(resp.read())
    if "error" in out:
        raise RuntimeError(f"{method}: {out['error']}")
    return out["result"]


@pytest.fixture()
def node():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    cfg = NodeConfig(
        dev=True,
        genesis_header=builder.genesis,
        genesis_alloc=builder.accounts_at_genesis,
    )
    n = Node(cfg, committer=CPU)
    n.start_rpc()
    yield n, alice
    n.stop()


def test_eth_basics_over_http(node):
    n, alice = node
    port = n.rpc.port
    assert rpc(port, "eth_chainId") == "0x1"
    assert rpc(port, "eth_blockNumber") == "0x0"
    assert parse_qty(rpc(port, "eth_getBalance", data(alice.address), "latest")) == 10**21
    assert rpc(port, "web3_clientVersion").startswith("reth-tpu/")
    assert rpc(port, "net_version") == "1"
    blk = rpc(port, "eth_getBlockByNumber", "0x0", False)
    assert parse_qty(blk["number"]) == 0


def test_send_tx_mine_and_receipt(node):
    n, alice = node
    port = n.rpc.port
    bob = b"\x0b" * 20
    tx = alice.transfer(bob, 12345)
    h = rpc(port, "eth_sendRawTransaction", data(tx.encode()))
    assert parse_data(h) == tx.hash
    assert rpc(port, "txpool_status")["pending"] == "0x1"
    # pending nonce reflects the pool
    assert rpc(port, "eth_getTransactionCount", data(alice.address), "pending") == "0x1"
    n.miner.mine_block()
    assert rpc(port, "eth_blockNumber") == "0x1"
    assert parse_qty(rpc(port, "eth_getBalance", data(bob), "latest")) == 12345
    rec = rpc(port, "eth_getTransactionReceipt", data(tx.hash))
    assert rec["status"] == "0x1" and parse_qty(rec["gasUsed"]) == 21000
    got = rpc(port, "eth_getTransactionByHash", data(tx.hash))
    assert got["blockNumber"] == "0x1" and got["from"] == data(alice.address)
    full = rpc(port, "eth_getBlockByNumber", "0x1", True)
    assert len(full["transactions"]) == 1


def test_engine_api_drives_chain(node):
    """Act as a consensus client: FCU+attrs → getPayload → newPayload → FCU."""
    n, alice = node
    auth = n.authrpc.port
    genesis_hash = rpc(auth, "eth_getBlockByNumber", "0x0", False)["hash"]
    # send a tx through the public port
    rpc(n.rpc.port, "eth_sendRawTransaction", data(alice.transfer(b"\x0c" * 20, 777).encode()))
    fcu = rpc(auth, "engine_forkchoiceUpdatedV2",
              {"headBlockHash": genesis_hash, "safeBlockHash": genesis_hash,
               "finalizedBlockHash": genesis_hash},
              {"timestamp": "0xc", "prevRandao": "0x" + "00" * 32,
               "suggestedFeeRecipient": "0x" + "aa" * 20, "withdrawals": []})
    assert fcu["payloadStatus"]["status"] == "VALID"
    pid = fcu["payloadId"]
    payload = rpc(auth, "engine_getPayloadV2", pid)["executionPayload"]
    assert len(payload["transactions"]) == 1
    st = rpc(auth, "engine_newPayloadV2", payload)
    assert st["status"] == "VALID", st
    fcu2 = rpc(auth, "engine_forkchoiceUpdatedV2",
               {"headBlockHash": payload["blockHash"], "safeBlockHash": genesis_hash,
                "finalizedBlockHash": genesis_hash})
    assert fcu2["payloadStatus"]["status"] == "VALID"
    assert parse_qty(rpc(n.rpc.port, "eth_getBalance", "0x" + "0c" * 20, "latest")) == 777
    caps = rpc(auth, "engine_exchangeCapabilities", [])
    assert "engine_newPayloadV3" in caps


def test_eth_call_and_logs(node):
    n, alice = node
    port = n.rpc.port
    # deploy the storage contract, then eth_call reads calldata echo? The
    # STORE contract writes; use eth_call for a balance-transfer frame (no
    # code): returns empty data with success
    out = rpc(port, "eth_call", {"from": data(alice.address), "to": "0x" + "0d" * 20,
                                 "value": "0x1"}, "latest")
    assert out == "0x"
    # deploy a LOG1-emitting contract, then call it
    from reth_tpu.primitives.keccak import keccak256
    from reth_tpu.primitives.rlp import encode_int, rlp_encode

    code = bytes.fromhex("60425f5fa100")
    deploy_initcode = bytes([0x60, len(code), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(code), 0x5F, 0xF3, 0x00]) + code
    rpc(port, "eth_sendRawTransaction", data(alice.deploy(deploy_initcode).encode()))
    n.miner.mine_block()
    contract = keccak256(rlp_encode([alice.address, encode_int(0)]))[12:]
    assert n.tree.overlay_provider().account(contract) is not None
    rpc(port, "eth_sendRawTransaction", data(alice.call(contract, b"").encode()))
    n.miner.mine_block()
    logs = rpc(port, "eth_getLogs", {"fromBlock": "0x0", "toBlock": "latest",
                                     "address": data(contract)})
    assert len(logs) == 1
    assert logs[0]["topics"] == ["0x" + "00" * 31 + "42"]


def test_contract_address_in_receipt_and_legacy_v(node):
    n, alice = node
    port = n.rpc.port
    code = bytes.fromhex("00")
    initcode = bytes([0x60, 1, 0x60, 0x0B, 0x5F, 0x39, 0x60, 1, 0x5F, 0xF3, 0x00]) + code
    deploy = alice.deploy(initcode)
    rpc(port, "eth_sendRawTransaction", data(deploy.encode()))
    n.miner.mine_block()
    rec = rpc(port, "eth_getTransactionReceipt", data(deploy.hash))
    from reth_tpu.primitives.keccak import keccak256
    from reth_tpu.primitives.rlp import encode_int, rlp_encode

    want = keccak256(rlp_encode([alice.address, encode_int(0)]))[12:]
    assert rec["contractAddress"] == data(want)


def test_pending_tx_shape(node):
    n, alice = node
    port = n.rpc.port
    tx = alice.transfer(b"\x0e" * 20, 5)
    rpc(port, "eth_sendRawTransaction", data(tx.encode()))
    got = rpc(port, "eth_getTransactionByHash", data(tx.hash))
    assert got["blockHash"] is None and got["blockNumber"] is None
    assert got["from"] == data(alice.address)


def test_pool_maintained_in_cl_driven_mode(node):
    """Txs must be evicted when blocks arrive via the engine API (no miner)."""
    n, alice = node
    auth, port = n.authrpc.port, n.rpc.port
    genesis_hash = rpc(auth, "eth_getBlockByNumber", "0x0", False)["hash"]
    tx = alice.transfer(b"\x0f" * 20, 9)
    rpc(port, "eth_sendRawTransaction", data(tx.encode()))
    fcu = rpc(auth, "engine_forkchoiceUpdatedV2",
              {"headBlockHash": genesis_hash, "safeBlockHash": genesis_hash,
               "finalizedBlockHash": genesis_hash},
              {"timestamp": "0xc", "prevRandao": "0x" + "00" * 32,
               "suggestedFeeRecipient": "0x" + "aa" * 20, "withdrawals": []})
    payload = rpc(auth, "engine_getPayloadV2", fcu["payloadId"])["executionPayload"]
    rpc(auth, "engine_newPayloadV2", payload)
    rpc(auth, "engine_forkchoiceUpdatedV2",
        {"headBlockHash": payload["blockHash"], "safeBlockHash": genesis_hash,
         "finalizedBlockHash": genesis_hash})
    assert rpc(port, "txpool_status")["pending"] == "0x0"  # evicted


def test_eth_get_proof(node):
    n, alice = node
    port = n.rpc.port
    proof = rpc(port, "eth_getProof", data(alice.address), [], "latest")
    assert parse_qty(proof["balance"]) == 10**21
    # verify against the canonical state root
    from reth_tpu.trie.proof import AccountProof, verify_account_proof
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.types import KECCAK_EMPTY, EMPTY_ROOT_HASH

    blk = rpc(port, "eth_getBlockByNumber", "latest", False)
    ap = AccountProof(
        address=alice.address,
        account=Account(
            nonce=parse_qty(proof["nonce"]), balance=parse_qty(proof["balance"]),
            storage_root=parse_data(proof["storageHash"]),
            code_hash=parse_data(proof["codeHash"]),
        ),
        proof=[parse_data(x) for x in proof["accountProof"]],
    )
    assert verify_account_proof(parse_data(blk["stateRoot"]), alice.address, ap)


def test_debug_trace_transaction(node):
    n, alice = node
    port = n.rpc.port
    # deploy + call the storage contract, then trace the call
    code = bytes.fromhex("5f355f5500")  # sstore(0, calldata[0])
    initcode = bytes([0x60, len(code), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(code), 0x5F, 0xF3, 0x00]) + code
    rpc(port, "eth_sendRawTransaction", data(alice.deploy(initcode).encode()))
    n.miner.mine_block()
    from reth_tpu.primitives.keccak import keccak256
    from reth_tpu.primitives.rlp import encode_int, rlp_encode

    contract = keccak256(rlp_encode([alice.address, encode_int(0)]))[12:]
    call_tx = alice.call(contract, (0x77).to_bytes(32, "big"))
    rpc(port, "eth_sendRawTransaction", data(call_tx.encode()))
    n.miner.mine_block()
    trace = rpc(port, "debug_traceTransaction", data(call_tx.hash))
    assert trace["failed"] is False
    ops = [l["op"] for l in trace["structLogs"]]
    assert ops == ["PUSH0", "CALLDATALOAD", "PUSH0", "SSTORE", "STOP"]
    assert trace["structLogs"][3]["stack"][-2:] == ["0x77", "0x0"]
    assert parse_qty(trace["gas"]) > 21000
    # raw accessors
    raw_h = rpc(port, "debug_getRawHeader", "0x1")
    assert raw_h.startswith("0x")
    raw_tx = rpc(port, "debug_getRawTransaction", data(call_tx.hash))
    assert parse_data(raw_tx) == call_tx.encode()


def test_engine_payload_bodies(node):
    n, alice = node
    port, auth = n.rpc.port, n.authrpc.port
    tx = alice.transfer(b"\x0b" * 20, 3)
    rpc(port, "eth_sendRawTransaction", data(tx.encode()))
    blk = n.miner.mine_block()
    bodies = rpc(auth, "engine_getPayloadBodiesByHashV1",
                 [data(blk.hash), "0x" + "77" * 32])
    assert len(bodies) == 2
    assert bodies[0]["transactions"] == [data(tx.encode())]
    assert bodies[1] is None  # unknown hash
    by_range = rpc(auth, "engine_getPayloadBodiesByRangeV1", "0x1", "0x2")
    assert len(by_range) == 2
    assert by_range[0]["transactions"] == [data(tx.encode())]
    assert by_range[1] is None  # beyond tip
    with pytest.raises(RuntimeError, match="must be >= 1"):
        rpc(auth, "engine_getPayloadBodiesByRangeV1", "0x0", "0x1")


def test_block_receipts_and_tx_by_index(node):
    n, alice = node
    port = n.rpc.port
    t1 = alice.transfer(b"\x0b" * 20, 1)
    t2 = alice.transfer(b"\x0b" * 20, 2)
    rpc(port, "eth_sendRawTransaction", data(t1.encode()))
    rpc(port, "eth_sendRawTransaction", data(t2.encode()))
    n.miner.mine_block()
    receipts = rpc(port, "eth_getBlockReceipts", "0x1")
    assert len(receipts) == 2
    assert receipts[0]["transactionHash"] == data(t1.hash)
    assert parse_qty(receipts[1]["gasUsed"]) == 21000
    assert parse_qty(receipts[1]["cumulativeGasUsed"]) == 42000
    got = rpc(port, "eth_getTransactionByBlockNumberAndIndex", "0x1", "0x1")
    assert got["hash"] == data(t2.hash)
    assert rpc(port, "eth_getTransactionByBlockNumberAndIndex", "0x1", "0x5") is None
    assert rpc(port, "eth_getBlockReceipts", "0x0") == []
    assert rpc(port, "eth_accounts") == []


def test_call_tracer_and_parity_trace(node):
    n, alice = node
    port = n.rpc.port
    # inner: sstore(0, 7); outer: CALL inner then STOP
    inner = bytes.fromhex("60075f5500")
    from reth_tpu.primitives.keccak import keccak256
    from reth_tpu.primitives.rlp import encode_int, rlp_encode

    def deploy(code, nonce):
        init = bytes([0x60, len(code), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(code), 0x5F, 0xF3, 0x00]) + code
        rpc(port, "eth_sendRawTransaction", data(alice.deploy(init).encode()))
        return keccak256(rlp_encode([alice.address, encode_int(nonce)]))[12:]

    inner_addr = deploy(inner, 0)
    outer = bytes.fromhex("5f5f5f5f5f73") + inner_addr + bytes.fromhex("5af100")
    outer_addr = deploy(outer, 1)
    n.miner.mine_block()
    call_tx = alice.call(outer_addr, b"")
    rpc(port, "eth_sendRawTransaction", data(call_tx.encode()))
    n.miner.mine_block()

    tree = rpc(port, "debug_traceTransaction", data(call_tx.hash), {"tracer": "callTracer"})
    assert tree["from"] == data(alice.address)
    assert tree["to"] == data(outer_addr)
    assert len(tree["calls"]) == 1
    assert tree["calls"][0]["to"] == data(inner_addr)
    assert tree["calls"][0]["type"] == "CALL"
    assert "error" not in tree

    flat = rpc(port, "trace_transaction", data(call_tx.hash))
    assert len(flat) == 2
    assert flat[0]["traceAddress"] == [] and flat[0]["subtraces"] == 1
    assert flat[1]["traceAddress"] == [0]
    assert flat[1]["action"]["to"] == data(inner_addr)
    # the inner store actually happened
    assert parse_qty(rpc(port, "eth_getStorageAt", data(inner_addr), "0x0", "latest")) == 7


def test_fee_history(node):
    n, alice = node
    port = n.rpc.port
    rpc(port, "eth_sendRawTransaction", data(alice.transfer(b"\x0b" * 20, 1).encode()))
    n.miner.mine_block()
    n.miner.mine_block()
    fh = rpc(port, "eth_feeHistory", "0x2", "latest", [50])
    assert fh["oldestBlock"] == "0x1"
    assert len(fh["baseFeePerGas"]) == 3  # 2 blocks + next
    assert len(fh["gasUsedRatio"]) == 2
    assert len(fh["reward"]) == 2


def test_error_shapes(node):
    n, _ = node
    port = n.rpc.port
    with pytest.raises(RuntimeError, match="not found"):
        rpc(port, "eth_notAMethod")
    with pytest.raises(RuntimeError, match="insufficient funds"):
        poor = Wallet(0x9999)
        rpc(port, "eth_sendRawTransaction", data(poor.transfer(b"\x01" * 20, 10**18).encode()))

def test_debug_execution_witness_stateless_roundtrip(node):
    """debug_executionWitness over HTTP feeds a stateless validator that
    reproduces the block's state root with no database."""
    from reth_tpu.engine.stateless import StatelessChain
    from reth_tpu.engine.witness import ExecutionWitness
    from reth_tpu.evm import EvmConfig
    from reth_tpu.primitives.types import Block, Header

    n, alice = node
    port = n.rpc.port
    rpc(port, "eth_sendRawTransaction", data(alice.transfer(b"\x0b" * 20, 777).encode()))
    n.miner.mine_block()
    w = ExecutionWitness.from_json(rpc(port, "debug_executionWitness", "0x1"))
    assert w.state and w.keys
    block = Block.decode(parse_data(rpc(port, "debug_getRawBlock", "0x1")))
    parent = Header.decode(parse_data(rpc(port, "debug_getRawHeader", "0x0")))
    chain = StatelessChain(config=EvmConfig(chain_id=1))
    assert chain.validate(block, w, parent) == block.header.state_root


def test_flashbots_validate_builder_submission(node):
    """Relay-side builder-block validation: a payload built by the node's
    own payload service validates, a tampered bid value is rejected."""
    from reth_tpu.rpc.convert import qty as _qty

    n, alice = node
    port = n.rpc.port
    rpc(port, "eth_sendRawTransaction", data(alice.transfer(b"\x0b" * 20, 321).encode()))
    # build (but do NOT commit) a payload on the tip via the engine API
    head = rpc(port, "eth_getBlockByNumber", "latest", False)["hash"]
    fcu = n.engine_api.engine_forkchoiceUpdatedV2(
        {"headBlockHash": head, "safeBlockHash": head,
         "finalizedBlockHash": head},
        {"timestamp": "0x63", "prevRandao": "0x" + "00" * 32,
         "suggestedFeeRecipient": "0x" + "ee" * 20, "withdrawals": []})
    payload = n.engine_api.engine_getPayloadV2(
        fcu["payloadId"])["executionPayload"]
    res = rpc(port, "flashbots_validateBuilderSubmissionV3", {
        "executionPayload": payload,
        "message": {"feeRecipient": "0x" + "ee" * 20, "value": "0x0"},
    })
    assert res["status"] == "Valid", res
    # demanding more payment than the block provides: invalid
    res = rpc(port, "flashbots_validateBuilderSubmissionV3", {
        "executionPayload": payload,
        "message": {"feeRecipient": "0x" + "ee" * 20,
                    "value": _qty(10**30)},
    })
    assert res["status"] == "Invalid" and "payment" in res["validationError"]


def test_flashbots_rejects_bogus_block_hash(node):
    """A submission whose claimed blockHash does not match the payload's
    sealed header is Invalid (reference validation.rs block-hash check)."""
    n, alice = node
    port = n.rpc.port
    head = rpc(port, "eth_getBlockByNumber", "latest", False)["hash"]
    fcu = n.engine_api.engine_forkchoiceUpdatedV2(
        {"headBlockHash": head, "safeBlockHash": head,
         "finalizedBlockHash": head},
        {"timestamp": "0x63", "prevRandao": "0x" + "00" * 32,
         "suggestedFeeRecipient": "0x" + "ee" * 20, "withdrawals": []})
    payload = n.engine_api.engine_getPayloadV2(
        fcu["payloadId"])["executionPayload"]
    payload["blockHash"] = "0x" + "13" * 32
    res = rpc(port, "flashbots_validateBuilderSubmissionV3", {
        "executionPayload": payload,
        "message": {"feeRecipient": "0x" + "ee" * 20, "value": "0x0"},
    })
    assert res["status"] == "Invalid"
    assert "block hash mismatch" in res["validationError"]


def test_flashbots_rejects_bogus_state_root(node):
    """A consistently-sealed payload carrying a WRONG post-state root is
    Invalid — the relay must re-execute and check the root, exactly like
    engine newPayload (reference validation.rs full validation)."""
    from reth_tpu.rpc.engine_api import payload_to_block

    n, alice = node
    port = n.rpc.port
    rpc(port, "eth_sendRawTransaction",
        data(alice.transfer(b"\x0b" * 20, 444).encode()))
    head = rpc(port, "eth_getBlockByNumber", "latest", False)["hash"]
    fcu = n.engine_api.engine_forkchoiceUpdatedV2(
        {"headBlockHash": head, "safeBlockHash": head,
         "finalizedBlockHash": head},
        {"timestamp": "0x63", "prevRandao": "0x" + "00" * 32,
         "suggestedFeeRecipient": "0x" + "ee" * 20, "withdrawals": []})
    payload = n.engine_api.engine_getPayloadV2(
        fcu["payloadId"])["executionPayload"]
    # tamper the state root, then RE-SEAL the claimed hash so the
    # block-hash check passes and the state-root check must catch it
    payload["stateRoot"] = "0x" + "37" * 32
    resealed = payload_to_block(payload, n.tree.committer)
    payload["blockHash"] = "0x" + resealed.header.hash.hex()
    res = rpc(port, "flashbots_validateBuilderSubmissionV3", {
        "executionPayload": payload,
        "message": {"feeRecipient": "0x" + "ee" * 20, "value": "0x0"},
    })
    assert res["status"] == "Invalid"
    assert "state root mismatch" in res["validationError"]
