"""discv4 UDP discovery: packet codec, Kademlia table, 3-node discovery
over real localhost UDP sockets.
"""

from __future__ import annotations

import time

import pytest

from reth_tpu.net.discv4 import (
    BUCKET_SIZE,
    Discv4,
    DiscError,
    KademliaTable,
    NodeRecord,
    decode_packet,
    encode_packet,
    log_distance,
)
from reth_tpu.primitives.rlp import encode_int
from reth_tpu.primitives.secp256k1 import pubkey_from_priv, pubkey_to_bytes


def _nid(priv: int) -> bytes:
    return pubkey_to_bytes(pubkey_from_priv(priv))


def test_packet_roundtrip_and_auth():
    pkt = encode_packet(0x123456, 0x01, [encode_int(4), b"x"])
    h, node, ptype, fields = decode_packet(pkt)
    assert node == _nid(0x123456)
    assert ptype == 0x01
    assert fields[0] == b"\x04" and fields[1] == b"x"
    # tampering breaks the hash
    bad = bytearray(pkt)
    bad[40] ^= 1
    with pytest.raises(DiscError):
        decode_packet(bytes(bad))


def test_kademlia_table_closest_and_eviction():
    local = _nid(1)
    table = KademliaTable(local)
    recs = [NodeRecord(_nid(i), "127.0.0.1", 1000 + i, 1000 + i)
            for i in range(2, 60)]
    for r in recs:
        table.add(r)
    assert len(table) <= len(recs)
    target = _nid(5)
    closest = table.closest(target, 8)
    assert len(closest) == 8
    # verify actual xor ordering
    dists = [log_distance(target, r.node_id) for r in closest]
    assert dists == sorted(dists) or True  # log-distance is coarse; exact
    # xor ordering is what closest() sorts by — spot-check the head
    assert closest[0].node_id == min(
        (r.node_id for r in table.by_id.values()),
        key=lambda nid: (
            int.from_bytes(__import__("reth_tpu.primitives.keccak",
                                      fromlist=["keccak256"]).keccak256(target), "big")
            ^ int.from_bytes(__import__("reth_tpu.primitives.keccak",
                                        fromlist=["keccak256"]).keccak256(nid), "big")
        ),
    )


@pytest.fixture
def three_nodes():
    nodes = [Discv4(priv, host="127.0.0.1") for priv in (0xD1, 0xD2, 0xD3)]
    for n in nodes:
        n.start()
    yield nodes
    for n in nodes:
        n.stop()


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_bonding_and_discovery(three_nodes):
    a, b, c = three_nodes
    # a and c each know only b (the bootnode)
    a.bootstrap([b.enode()])
    c.bootstrap([b.enode()])
    assert _wait(lambda: any(r.bonded for r in a.table.by_id.values()))
    assert _wait(lambda: any(r.bonded for r in c.table.by_id.values()))
    # lookups through b let a and c find each other
    a.lookup()
    c.lookup()
    assert _wait(lambda: c.node_id in a.table.by_id), "a never discovered c"
    assert _wait(lambda: a.node_id in c.table.by_id), "c never discovered a"
    # discovered records carry dialable endpoints
    rec = a.table.by_id[c.node_id]
    assert rec.udp_port == c.port
    assert rec.enode().startswith("enode://")


def test_findnode_requires_bond(three_nodes):
    a, b, _ = three_nodes
    # a asks b for neighbors WITHOUT bonding first: must be ignored
    rec = NodeRecord(b.node_id, "127.0.0.1", b.port, b.port)
    a.find_node(rec, a.node_id)
    time.sleep(0.5)
    assert b.node_id not in a.table.by_id or not a.table.by_id[b.node_id].bonded
