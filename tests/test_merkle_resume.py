"""ETL collector + chunked resumable MerkleStage rebuild.

Covers VERDICT round-1 next-round #5: kill -9 mid-rebuild, restart, same
root (real SIGKILL over the durable native KV engine), plus in-process
chunk-boundary resume and >buffer ETL spills.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from reth_tpu.etl import Collector
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.types import Account
from reth_tpu.stages import default_stages
from reth_tpu.stages.api import ExecInput, Pipeline
from reth_tpu.stages.merkle import MerkleStage
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.storage.kv import MemDb
from reth_tpu.storage.provider import ProviderFactory
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie.committer import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)
CPU.turbo_backend = "numpy"


# -- ETL ---------------------------------------------------------------------


def test_etl_sorted_iteration_with_spills():
    col = Collector(buffer_bytes=512)  # force many spill files
    items = [(os.urandom(8), os.urandom(16)) for _ in range(500)]
    for k, v in items:
        col.insert(k, v)
    got = list(col)
    assert got == sorted(items, key=lambda kv: kv[0])
    assert len(col._files) > 1, "expected disk spills"
    col.close()


def test_etl_duplicate_keys_stable_order():
    with Collector(buffer_bytes=64) as col:
        for i in range(50):
            col.insert(b"same", bytes([i]))
        assert [v for _, v in col] == [bytes([i]) for i in range(50)]


def test_etl_empty():
    with Collector() as col:
        assert list(col) == []


# -- chunked rebuild ---------------------------------------------------------

STORE = bytes.fromhex("5f355f5500")


def _initcode(runtime):
    n = len(runtime)
    return bytes([0x60, n, 0x60, 0x0B, 0x5F, 0x39, 0x60, n, 0x5F, 0xF3]) + b"\x00" + runtime


def _build_chain():
    a = Wallet(0xAAA1)
    bld = ChainBuilder({a.address: Account(balance=10**21)}, committer=CPU)
    bld.build_block([a.deploy(_initcode(STORE))])
    contract = next(
        addr for addr, acc in bld.accounts.items()
        if bld.codes.get(acc.code_hash) == STORE
    )
    bld.build_block(
        [a.transfer(bytes([i + 1] * 20), 10**10 + i) for i in range(10)]
        + [a.call(contract, (0xAB01).to_bytes(32, "big"))]
    )
    bld.build_block([a.transfer(bytes([i + 11] * 20), 10**10 + i) for i in range(10)])
    return bld


def _synced_factory(bld):
    factory = ProviderFactory(MemDb())
    init_genesis(factory, bld.genesis, dict(bld.accounts_at_genesis),
                 dict(bld.storage_at_genesis), dict(bld.codes_at_genesis),
                 committer=CPU)
    import_chain(factory, bld.blocks[1:])
    return factory


def test_chunked_rebuild_matches_header_root():
    bld = _build_chain()
    factory = _synced_factory(bld)
    stages = default_stages(committer=CPU)
    for s in stages:
        if isinstance(s, MerkleStage):
            s.chunk_leaves = 4  # force many chunks
    Pipeline(factory, stages).run(bld.tip.number)  # raises on root mismatch
    with factory.provider() as p:
        assert p.stage_progress(MerkleStage.id) is None  # progress cleared


def test_chunked_rebuild_resumes_after_interruption():
    """Drive the chunked stage to a mid-rebuild progress blob, then finish
    with a FRESH stage instance (all context from the persisted blob)."""
    bld = _build_chain()
    factory = _synced_factory(bld)
    # run the earlier stages so hashed tables exist
    stages = default_stages(committer=CPU)
    pre = [s for s in stages if not isinstance(s, MerkleStage)]
    merkle_idx = next(i for i, s in enumerate(stages) if isinstance(s, MerkleStage))
    Pipeline(factory, stages[:merkle_idx]).run(bld.tip.number)

    stage = MerkleStage(CPU, chunk_leaves=4)
    target = bld.tip.number
    for _ in range(3):  # a few chunks, committing each
        with factory.provider_rw() as p:
            out = stage.execute(p, ExecInput(target, 0))
        assert not out.done
    with factory.provider() as p:
        blob = p.stage_progress(MerkleStage.id)
        assert blob is not None, "expected mid-rebuild progress"

    # "crash": new stage object, resume purely from the blob
    resumed = MerkleStage(CPU, chunk_leaves=4)
    for _ in range(500):
        with factory.provider_rw() as p:
            out = resumed.execute(p, ExecInput(target, 0))
        if out.done:
            break
    assert out.done and out.checkpoint == target
    with factory.provider() as p:
        assert p.stage_progress(MerkleStage.id) is None
    # and the trie tables it left behind satisfy the full verifier
    from reth_tpu.trie.incremental import verify_state_root

    with factory.provider_rw() as p:
        root, problems = verify_state_root(p, CPU)
    assert problems == []
    assert root == bld.tip.state_root


def test_stale_target_progress_restarts_rebuild():
    """Progress bound to an older sync target is discarded, not stitched
    into a mixed-state root (review finding)."""
    bld = _build_chain()
    factory = _synced_factory(bld)
    stages = default_stages(committer=CPU)
    merkle_idx = next(i for i, s in enumerate(stages) if isinstance(s, MerkleStage))
    Pipeline(factory, stages[:merkle_idx]).run(bld.tip.number)

    stage = MerkleStage(CPU, chunk_leaves=4)
    old_target = bld.tip.number - 1
    for _ in range(2):  # leave stale progress behind for old_target
        with factory.provider_rw() as p:
            stage.execute(p, ExecInput(old_target, 0))
    with factory.provider() as p:
        assert p.stage_progress(MerkleStage.id) is not None

    # full pipeline to the REAL tip must restart the rebuild and succeed
    run_stages = default_stages(committer=CPU)
    for s in run_stages:
        if isinstance(s, MerkleStage):
            s.chunk_leaves = 4
    Pipeline(factory, run_stages).run(bld.tip.number)
    with factory.provider() as p:
        assert p.stage_progress(MerkleStage.id) is None


def test_pipeline_abort_mid_queue_resumes_bit_identical(monkeypatch):
    """Kill the OVERLAPPED rebuild pipeline mid-queue (fault injection via
    RETH_TPU_FAULT_PIPELINE_ABORT): the aborted chunk's transaction rolls
    back, earlier committed chunks survive, and a fresh stage instance
    resumes from the persisted progress to the bit-identical root."""
    from reth_tpu.ops.supervisor import InjectedPipelineAbort

    bld = _build_chain()
    factory = _synced_factory(bld)
    stages = default_stages(committer=CPU)
    merkle_idx = next(i for i, s in enumerate(stages) if isinstance(s, MerkleStage))
    Pipeline(factory, stages[:merkle_idx]).run(bld.tip.number)

    stage = MerkleStage(CPU, chunk_leaves=4)
    target = bld.tip.number
    for _ in range(2):  # committed chunks that the abort must NOT lose
        with factory.provider_rw() as p:
            out = stage.execute(p, ExecInput(target, 0))
        assert not out.done
    with factory.provider() as p:
        before = p.stage_progress(MerkleStage.id)
    assert before is not None, "expected mid-rebuild progress"

    # every pipelined (multi-subtrie) commit now dies at its first packed
    # window — the in-process analogue of a crash while the sweep queue is
    # full. Single-job chunks take the serial path and still commit, so
    # snapshot progress before each attempt: the abort must roll back to
    # EXACTLY the last committed chunk, losing nothing else.
    monkeypatch.setenv("RETH_TPU_FAULT_PIPELINE_ABORT", "1")
    aborted = False
    snap = before
    for _ in range(300):
        with factory.provider() as p:
            snap = p.stage_progress(MerkleStage.id)
        try:
            with factory.provider_rw() as p:
                out = stage.execute(p, ExecInput(target, 0))
        except InjectedPipelineAbort:
            aborted = True
            break
        if out.done:
            break
    assert aborted, "injected pipeline abort never fired"
    with factory.provider() as p:
        # the dying chunk rolled back; the committed prefix set is intact
        assert p.stage_progress(MerkleStage.id) == snap

    monkeypatch.delenv("RETH_TPU_FAULT_PIPELINE_ABORT")
    resumed = MerkleStage(CPU, chunk_leaves=4)  # fresh instance: blob only
    for _ in range(500):
        with factory.provider_rw() as p:
            out = resumed.execute(p, ExecInput(target, 0))
        if out.done:
            break
    assert out.done and out.checkpoint == target
    with factory.provider() as p:
        assert p.stage_progress(MerkleStage.id) is None
    from reth_tpu.trie.incremental import verify_state_root

    with factory.provider_rw() as p:
        root, problems = verify_state_root(p, CPU)
    assert problems == []
    assert root == bld.tip.state_root


_KILL_SCRIPT = "tests/helpers/merkle_resume_child.py"


def test_kill9_mid_rebuild_then_restart(tmp_path):
    """Real SIGKILL over the durable native KV engine: first run is killed
    mid-rebuild; the rerun must resume from the persisted chunk progress
    and finish with the correct root."""
    datadir = str(tmp_path / "db")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def spawn(mode, slow=False):
        e = dict(env)
        if slow:
            e["MERKLE_CHILD_SLOW"] = "1"
        return subprocess.Popen(
            [sys.executable, _KILL_SCRIPT, datadir, mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=e, text=True,
        )

    p = spawn("init")
    out, _ = p.communicate(timeout=120)
    assert p.returncode == 0, out

    p = spawn("rebuild", slow=True)
    # wait for the CHUNK progress marker, then kill: guarantees the kill
    # lands AFTER a committed chunk regardless of machine load (a fixed
    # sleep killed during interpreter startup under parallel test runs)
    killed_mid_run = False
    # readline() blocks: a watchdog kills a wedged child so the test
    # stays bounded no matter what
    import threading

    watchdog = threading.Timer(120, p.kill)
    watchdog.start()
    try:
        while True:
            line = p.stdout.readline()
            if not line:  # child finished before any chunk boundary
                break
            if "CHUNK" in line:
                killed_mid_run = p.poll() is None
                if killed_mid_run:
                    p.send_signal(signal.SIGKILL)
                break
    finally:
        watchdog.cancel()
    p.wait(timeout=60)

    p = spawn("rebuild")
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    assert "REBUILD_OK" in out
    if killed_mid_run:
        assert "RESUMED_FROM_PROGRESS" in out, out
