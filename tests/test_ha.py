"""Leader/standby high availability (reth_tpu/fleet/standby.py +
election.py): RTST1 wire vetting with the on-disk WAL discipline, the
promotion ladder, heartbeat-loss failover, epoch fencing, feed-client
reconnect hardening, and the leader-kill chaos drills."""

import json
import os
import pickle
import socket
import subprocess
import sys
import threading
import time
import urllib.request
import zlib
from pathlib import Path

import pytest

from reth_tpu.fleet.election import (
    STATES,
    HeartbeatMonitor,
    PromotionStateMachine,
    fence_check,
    fencing_disabled,
    probe_feed_hello,
)
from reth_tpu.fleet.feed import (
    FEED_MAGIC,
    ST_MAGIC,
    WitnessFeedClient,
    WitnessFeedServer,
    send_frame,
    recv_frame,
)
from reth_tpu.fleet.standby import StandbyFaultInjector, StandbyNode
from reth_tpu.rpc.gateway import classify
from reth_tpu.storage.kv import MemDb
from reth_tpu.storage.wal import WalStore

H1 = b"\x11" * 32
H2 = b"\x22" * 32


def _rpc(port, method, params):
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=15).read())


# -- promotion state machine --------------------------------------------------


def test_promotion_ladder_is_monotonic():
    seen = []
    sm = PromotionStateMachine(on_transition=lambda s, w: seen.append(s))
    assert sm.state == "following"
    assert not sm.advance("following")           # no self-loop
    assert sm.advance("catching-up", "hb loss")
    assert not sm.advance("following")           # never demotes
    assert sm.advance("promoting")
    assert sm.advance("leading")
    assert sm.is_leading()
    assert not sm.advance("catching-up")         # terminal forwardness
    assert not sm.advance("emperor")             # unknown state refused
    assert seen == ["catching-up", "promoting", "leading"]
    hist = [h["state"] for h in sm.snapshot()["history"]]
    assert hist == list(STATES)
    assert all(h["at"] > 0 for h in sm.snapshot()["history"])


def test_promotion_failed_is_terminal():
    sm = PromotionStateMachine()
    sm.advance("catching-up")
    assert sm.advance("failed", "root mismatch")
    assert sm.state == "failed"
    assert not sm.advance("promoting")
    assert not sm.advance("leading")
    assert not sm.is_leading()


def test_heartbeat_monitor_fires_once_per_arm_then_rearms_on_beat():
    losses = []
    mon = HeartbeatMonitor(timeout_s=0.1, on_loss=losses.append,
                           interval_s=0.02)
    mon.start()
    try:
        deadline = time.time() + 10
        while not losses and time.time() < deadline:
            time.sleep(0.01)
        assert len(losses) == 1
        time.sleep(0.3)
        assert len(losses) == 1                  # fired once per arm
        mon.note()                               # a beat re-arms the deadline
        deadline = time.time() + 10
        while len(losses) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert len(losses) == 2
        assert mon.beats == 1 and mon.losses == 2
    finally:
        mon.stop()


# -- epoch fencing ------------------------------------------------------------


def _feed_server(epoch, rpc_port=12345):
    srv = WitnessFeedServer(None, chain_id=1)
    srv.epoch = epoch
    srv.rpc_port = rpc_port
    port = srv.start()
    return srv, port


def _dead_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_probe_feed_hello_carries_epoch_and_rpc_port():
    srv, port = _feed_server(3)
    try:
        hello = probe_feed_hello("127.0.0.1", port, timeout_s=5)
        assert hello["type"] == "hello"
        assert hello["epoch"] == 3
        assert hello["rpc_port"] == 12345        # replica re-anchor target
    finally:
        srv.stop()


def test_fence_check_detects_superseding_epoch():
    srv, port = _feed_server(3)
    try:
        rep = fence_check(2, [("127.0.0.1", port)], timeout_s=5)
        assert rep["fenced"] and rep["peer_epoch"] == 3
        assert rep["probed"] == 1
        assert rep["peer"] == f"127.0.0.1:{port}"
        # equal epoch does not fence (a node is never behind itself)
        rep = fence_check(3, [("127.0.0.1", port)], timeout_s=5)
        assert not rep["fenced"] and rep["peer_epoch"] is None
    finally:
        srv.stop()


def test_fence_check_unreachable_peer_is_not_fencing():
    rep = fence_check(1, [("127.0.0.1", _dead_port())], timeout_s=0.5)
    assert not rep["fenced"] and rep["probed"] == 0


def test_fence_check_no_fence_fault_reports_but_does_not_fence(monkeypatch):
    monkeypatch.setenv("RETH_TPU_FAULT_HA_NO_FENCE", "1")
    assert fencing_disabled()
    srv, port = _feed_server(9)
    try:
        rep = fence_check(1, [("127.0.0.1", port)], timeout_s=5)
        assert rep["disabled"] and not rep["fenced"]
        assert rep["peer_epoch"] == 9            # the fact is still reported
    finally:
        srv.stop()


# -- admission-class pinning (fleet_promote must never queue behind debug) ----


def test_ha_admin_methods_ride_engine_admission_class():
    assert classify("fleet_promote") == "engine"
    assert classify("fleet_standbyStatus") == "engine"
    assert classify("engine_forkchoiceUpdatedV3") == "engine"
    assert classify("debug_traceBlockByNumber") == "debug"  # the contrast


# -- RTST1 wire vetting: corruption handled exactly like on-disk replay -------


def _frame(kind, **kw):
    f = {"type": kind, "st": ST_MAGIC, "epoch": 1}
    f.update(kw)
    return f


def _wal_frame(gen, seq, delta, *, epoch=1, store=0, corrupt=False):
    payload = pickle.dumps({"seq": seq, "tables": delta},
                           protocol=pickle.HIGHEST_PROTOCOL)
    crc = zlib.crc32(payload)
    if corrupt:
        payload = payload[:-1] + bytes([payload[-1] ^ 0xFF])
    return _frame("st_wal", epoch=epoch, store=store, gen=gen, seq=seq,
                  payload=payload, crc=crc)


def _rows(table, rows):
    return {table: {"rows": rows}}


def _anchor(sb, *, gen=1, seq=0, epoch=1, tables=None, head=None):
    """In-stream image: the anchor every wire-vetting case starts from."""
    sb._on_record(_frame(
        "st_resync", epoch=epoch, store=0,
        tables=tables if tables is not None else {"accounts": {}},
        gen=gen, seq=seq, head=head))


@pytest.fixture
def standby(tmp_path):
    sb = StandbyNode("127.0.0.1", 1, datadir=tmp_path / "sb",
                     auto_promote=False, heartbeat_timeout_s=60,
                     standby_id="t-standby")
    yield sb
    for st in sb.stores.values():
        try:
            st.wal.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def test_standby_resync_anchors_then_stream_applies(standby):
    _anchor(standby, tables={"accounts": {b"a": b"1"}}, head=(1, H1))
    assert standby.resyncs_applied == 1
    st = standby.stores[0]
    assert not st.awaiting_resync and st.pos == (1, 0)
    assert standby.applied_head == (1, H1)
    assert st.db._tables["accounts"][b"a"] == b"1"
    standby._on_record(_wal_frame(1, 1, _rows("accounts", {b"b": b"2"})))
    assert standby.records_applied == 1 and st.pos == (1, 1)
    assert st.db._tables["accounts"][b"b"] == b"2"
    # the standby re-appended the shipped record into its OWN WAL
    assert st.wal.appends == 1


def test_standby_rejects_corrupt_payload_and_reanchors(standby):
    _anchor(standby)
    standby._on_record(
        _wal_frame(1, 1, _rows("t", {b"k": b"v"}), corrupt=True))
    assert standby.crc_rejected == 1
    assert standby.records_applied == 0
    assert standby.stores[0].awaiting_resync
    assert standby.resyncs_requested == 1
    # records streaming while the image is pending are not applied
    standby._on_record(_wal_frame(1, 1, _rows("t", {b"k": b"v"})))
    assert standby.records_applied == 0
    # the fresh image re-anchors and the stream continues
    _anchor(standby, gen=1, seq=1)
    standby._on_record(_wal_frame(1, 2, _rows("t", {b"k": b"v"})))
    assert standby.records_applied == 1


def test_standby_rejects_undecodable_payload_as_torn(standby):
    _anchor(standby)
    garbage = b"\x80\x05 not a pickle"
    standby._on_record(_frame("st_wal", store=0, gen=1, seq=1,
                              payload=garbage, crc=zlib.crc32(garbage)))
    assert standby.crc_rejected == 1 and standby.records_applied == 0


def test_standby_epoch_ladder_stale_refused_higher_adopted(standby):
    _anchor(standby)
    st = standby.stores[0]
    # a HIGHER epoch in-stream is a new leader lineage: adopt + re-anchor
    standby._on_record(_wal_frame(1, 1, _rows("t", {}), epoch=2))
    assert standby.leader_epoch == 2
    assert st.awaiting_resync and standby.resyncs_requested == 1
    assert standby.records_applied == 0
    _anchor(standby, epoch=2)
    assert not st.awaiting_resync
    # a STALE epoch is a fenced old leader still talking: refused
    standby._on_record(_wal_frame(1, 1, _rows("t", {b"k": b"v"}), epoch=1))
    assert standby.stale_epoch_rejected == 1
    assert standby.records_applied == 0
    assert b"k" not in st.db._tables.get("t", {})


def test_standby_rejects_out_of_order_generation(standby):
    _anchor(standby, gen=3, seq=5)
    standby._on_record(_wal_frame(2, 6, _rows("t", {})))
    assert standby.gen_rejected == 1
    assert standby.records_applied == 0
    assert standby.stores[0].awaiting_resync


def test_standby_duplicate_skipped_gap_reanchors(standby):
    _anchor(standby)
    standby._on_record(_wal_frame(1, 1, _rows("t", {b"a": b"1"})))
    standby._on_record(_wal_frame(1, 1, _rows("t", {b"a": b"X"})))
    assert standby.records_duplicate == 1
    assert standby.stores[0].db._tables["t"][b"a"] == b"1"  # first wins
    standby._on_record(_wal_frame(1, 3, _rows("t", {b"c": b"3"})))  # skips 2
    assert standby.gap_detected == 1
    assert standby.stores[0].awaiting_resync
    assert standby.records_applied == 1


def test_standby_heartbeat_tracks_leader_head_and_lag(standby):
    _anchor(standby, head=(3, H1))
    standby._on_record(_frame("st_heartbeat", head=(7, H2)))
    assert standby.monitor.beats == 1
    assert standby.leader_head == (7, H2)
    assert standby.lag_heads() == 4
    s = standby.status()
    assert s["lag_heads"] == 4 and s["state"] == "following"
    assert s["applied_head"]["number"] == 3


def test_standby_manifest_checkpoints_own_wal(standby):
    _anchor(standby)
    standby._on_record(_wal_frame(1, 1, _rows("t", {b"a": b"1"})))
    ck0 = standby.stores[0].wal.checkpoints
    standby._on_record(_frame(
        "st_manifest", store=0,
        manifest={"gen": 2, "head_number": 4, "head_hash": "ab" * 32}))
    assert standby.manifests_applied == 1
    assert standby.persisted_head == (4, "ab" * 32)
    assert standby.stores[0].wal.checkpoints == ck0 + 1
    assert standby.stores[0].pos == (2, 1)  # gen tracks the leader's


def test_standby_datadir_survives_restart(tmp_path):
    d = tmp_path / "sb"
    sb = StandbyNode("127.0.0.1", 1, datadir=d, auto_promote=False)
    _anchor(sb, tables={"accounts": {b"a": b"1"}})
    sb._on_record(_wal_frame(1, 1, _rows("accounts", {b"b": b"2"})))
    sb._on_record(
        _wal_frame(1, 2, {"accounts": {"del": [b"a"]}}))
    for st in sb.stores.values():
        st.wal.close()
    # a killed-and-restarted standby replays its OWN WAL back to the
    # last complete shipped commit
    sb2 = StandbyNode("127.0.0.1", 1, datadir=d, auto_promote=False)
    t = sb2.stores[0].db._tables["accounts"]
    assert t.get(b"b") == b"2" and b"a" not in t
    for st in sb2.stores.values():
        st.wal.close()


def test_wal_manifest_persists_leader_epoch(tmp_path):
    db = MemDb(tmp_path / "db.bin")
    wal = WalStore.open(db, tmp_path / "wal")
    wal.append(_rows("t", {b"k": b"v"}))
    wal.epoch = 7
    wal.checkpoint(head=(3, b"\xaa" * 32))
    wal.close()
    db2 = MemDb(tmp_path / "db.bin")
    wal2 = WalStore.open(db2, tmp_path / "wal")
    assert wal2.epoch == 7                       # the fencing token survives
    wal2.close()


def test_wal_observer_ships_exact_on_disk_payload(tmp_path, standby):
    """The leader's post-fsync observer ships the RAW record payload; a
    standby anchored at the same position applies it bit-for-bit."""
    db = MemDb(tmp_path / "leader.bin")
    wal = WalStore.open(db, tmp_path / "leader-wal")
    shipped = []
    wal.observer = lambda gen, seq, payload: shipped.append(
        (gen, seq, payload))
    wal.append(_rows("t", {b"k": b"v"}))
    wal.close()
    assert len(shipped) == 1
    gen, seq, payload = shipped[0]
    _anchor(standby, gen=gen, seq=seq - 1)
    standby._on_record(_frame("st_wal", store=0, gen=gen, seq=seq,
                              payload=payload, crc=zlib.crc32(payload)))
    assert standby.records_applied == 1
    assert standby.stores[0].db._tables["t"][b"k"] == b"v"


# -- fault injectors ----------------------------------------------------------


def test_standby_fault_injector_from_env():
    assert StandbyFaultInjector.from_env({}) is None
    inj = StandbyFaultInjector.from_env({"RETH_TPU_FAULT_STANDBY_WEDGE": "3"})
    assert inj.wedge and inj.wedge_after == 3
    assert not inj.on_record("st_wal")
    assert not inj.on_record("st_wal")
    assert inj.on_record("st_wal")               # 3rd record onward dropped
    assert inj.on_record("st_fcu")
    assert inj.dropped == 2
    inj = StandbyFaultInjector.from_env(
        {"RETH_TPU_FAULT_STANDBY_LAG": "0.001"})
    assert inj.lag_s == 0.001 and not inj.wedge
    assert not inj.on_record("st_wal")
    assert inj.lagged == 1


def test_standby_wedge_freezes_replication_not_heartbeats(tmp_path):
    inj = StandbyFaultInjector(wedge=True, wedge_after=2)
    sb = StandbyNode("127.0.0.1", 1, datadir=tmp_path / "sb",
                     auto_promote=False, injector=inj)
    try:
        _anchor(sb)                              # 1st record: passes
        sb._on_record(_wal_frame(1, 1, _rows("t", {b"a": b"1"})))
        assert sb.records_applied == 0 and inj.dropped == 1
        sb._on_record(_frame("st_heartbeat", head=(5, H1)))
        assert sb.monitor.beats == 1             # a live but stuck standby
        assert sb.status()["wedged"]
    finally:
        for st in sb.stores.values():
            st.wal.close()


def test_standby_never_promotes_before_seeing_a_leader(tmp_path):
    """A standby that starts first (leader still booting) must not fire
    heartbeat-loss promotion over an empty datadir."""
    sb = StandbyNode("127.0.0.1", 1, datadir=tmp_path / "sb",
                     auto_promote=True, heartbeat_timeout_s=60)
    try:
        sb._on_heartbeat_loss(99.0)
        time.sleep(0.2)
        assert sb.promotion.state == "following"
    finally:
        for st in sb.stores.values():
            st.wal.close()


# -- admin RPC surface --------------------------------------------------------


def test_fleet_standby_status_rpc(tmp_path):
    sb = StandbyNode("127.0.0.1", 1, datadir=tmp_path / "sb",
                     auto_promote=False, standby_id="t-status")
    port = sb.rpc.start()
    try:
        _anchor(sb)
        res = _rpc(port, "fleet_standbyStatus", [])["result"]
        assert res["state"] == "following"
        assert res["resyncs_applied"] == 1
        assert res["id"] == "t-status"
        assert res["leader_epoch"] == 1
        assert res["node"] is None
    finally:
        sb.rpc.stop()
        for st in sb.stores.values():
            st.wal.close()


# -- feed-client reconnect hardening ------------------------------------------


class _FlakyFeed:
    """A feed endpoint that refuses the first ``flaps`` connections
    (accept-then-close mid-handshake), then serves real sessions and
    captures upstream frames."""

    def __init__(self, flaps=3, head=None, epoch=1):
        self.flaps = flaps
        self.head = head
        self.epoch = epoch
        self.upstream = []
        self.attempts = 0
        self.sessions = 0
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._conns = []
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            self.attempts += 1
            if self.attempts <= self.flaps:
                sock.close()
                continue
            self.sessions += 1
            self._conns.append(sock)
            threading.Thread(target=self._serve, args=(sock,),
                             daemon=True).start()

    def _serve(self, sock):
        try:
            sock.sendall(FEED_MAGIC)
            send_frame(sock, {"type": "hello", "chain_id": 1,
                              "head": self.head, "epoch": self.epoch,
                              "rpc_port": None, "spec": None})
            if self.head is not None:
                send_frame(sock, {"type": "head", "number": self.head[0],
                                  "hash": self.head[1]})
            while not self._stop.is_set():
                self.upstream.append(recv_frame(sock))
        except Exception:  # noqa: BLE001 - session death ends the serve
            pass

    def drop_all(self):
        for s in self._conns:
            # shutdown (not just close): the serve thread blocked in
            # recv holds the fd open, so close alone never sends FIN
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self.drop_all()


def test_client_reconnects_through_flapping_server():
    srv = _FlakyFeed(flaps=3)
    hellos = []
    cli = WitnessFeedClient("127.0.0.1", srv.port, on_hello=hellos.append,
                            backoff_s=0.02, backoff_max_s=0.2)
    cli.start()
    try:
        assert cli.connected.wait(30)
        assert srv.attempts >= 4                 # 3 refused + the real one
        assert cli.connections == 1              # only real sessions count
        assert hellos and hellos[0]["epoch"] == 1
        assert cli.endpoint == ("127.0.0.1", srv.port)
    finally:
        cli.stop()
        srv.stop()


def test_client_resubscribes_from_last_seen_head():
    srv = _FlakyFeed(flaps=0, head=(5, b"\x55" * 32))
    cli = WitnessFeedClient("127.0.0.1", srv.port,
                            backoff_s=0.02, backoff_max_s=0.2)
    cli.start()
    try:
        assert cli.connected.wait(15)
        deadline = time.time() + 15
        while cli.last_seen_head is None and time.time() < deadline:
            time.sleep(0.01)
        assert cli.last_seen_head == (5, b"\x55" * 32)
        assert cli.resubscribes == 0             # nothing seen pre-session
        srv.drop_all()                           # transport dies mid-stream
        deadline = time.time() + 30
        while cli.resubscribes == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert cli.resubscribes >= 1
        assert cli.connections >= 2
        deadline = time.time() + 15
        while not any(f.get("type") == "resubscribe" for f in srv.upstream) \
                and time.time() < deadline:
            time.sleep(0.01)
        subs = [f for f in srv.upstream if f.get("type") == "resubscribe"]
        assert subs and subs[0]["number"] == 5   # from the LAST SEEN head
    finally:
        cli.stop()
        srv.stop()


def test_client_rotates_to_failover_endpoint():
    """The HA failover ladder: the primary feed is dead, the standby's
    takeover endpoint serves — the client rotates onto it."""
    srv = _FlakyFeed(flaps=0, epoch=2)
    hellos = []
    cli = WitnessFeedClient("127.0.0.1", _dead_port(),
                            on_hello=hellos.append,
                            backoff_s=0.02, backoff_max_s=0.2,
                            endpoints=[("127.0.0.1", srv.port)])
    cli.start()
    try:
        assert cli.connected.wait(30)
        assert cli.endpoint == ("127.0.0.1", srv.port)
        assert hellos[0]["epoch"] == 2           # the promoted lineage
    finally:
        cli.stop()
        srv.stop()


# -- live replication + promotion + fencing (in-process) ----------------------


def _mk_node(datadir, wallet, *, ha_peer_feeds=(), start_rpc=True):
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.primitives.types import Account
    from reth_tpu.testing import ChainBuilder
    from reth_tpu.trie.committer import TrieCommitter

    committer = TrieCommitter(hasher=keccak256_batch_np)
    committer.turbo_backend = "numpy"
    builder = ChainBuilder({wallet.address: Account(balance=10**21)},
                           committer=committer)
    node = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                           genesis_alloc=builder.accounts_at_genesis,
                           fleet=True, wal=True, datadir=str(datadir),
                           db_backend="memdb", persistence_threshold=1,
                           http_port=0, authrpc_port=0,
                           ha_peer_feeds=tuple(ha_peer_feeds)),
                committer=committer)
    if start_rpc:
        node.start_rpc()
    return node


def test_leader_standby_replication_promotion_and_fencing(tmp_path):
    """The tentpole, in-process: WAL-shipped replication into the
    standby's own datadir, promotion with root verification over the
    recovered head, a bumped epoch on the takeover feed, and the old
    leader fencing itself on restart."""
    from reth_tpu.engine.tree import PayloadStatusKind
    from reth_tpu.testing import Wallet

    wallet = Wallet(0xAB5B)
    leader = _mk_node(tmp_path / "leader", wallet)
    leader_alive = True
    sb = old = None
    try:
        fport = leader.feed_server.port
        sb = StandbyNode("127.0.0.1", fport, datadir=tmp_path / "standby",
                         auto_promote=False, heartbeat_timeout_s=60,
                         standby_id="t-ha")
        sb.start()
        sink = b"\x0c" * 20
        for i in range(4):
            leader.pool.add_transaction(wallet.transfer(sink, 1000 + i))
            leader.miner.mine_block(timestamp=1_700_000_000 + i * 12)
        deadline = time.time() + 90
        while time.time() < deadline:
            if (sb.applied_head and sb.applied_head[0] == 4
                    and sb.records_applied > 0
                    and not any(st.awaiting_resync
                                for st in sb.stores.values())):
                break
            time.sleep(0.05)
        assert sb.applied_head and sb.applied_head[0] == 4, sb.status()
        assert sb.resyncs_applied >= 1           # first connect = image
        assert sb.lag_heads() == 0

        leader.stop()                            # the leader dies
        leader_alive = False
        old_epoch = sb.leader_epoch
        assert sb.promote("drill") is True, sb.status()
        assert sb.promotion.is_leading()
        st = sb.status()
        assert st["state"] == "leading"
        assert st["leader_epoch"] == old_epoch + 1
        rec = st["node"]["recovery"]
        assert rec["root_verified"] is True      # recomputed at takeover
        assert rec["status"] != "failed"
        assert st["promote_ms"] and st["promote_ms"] > 0
        # the takeover feed advertises the bumped epoch (fencing token)
        hello = probe_feed_hello("127.0.0.1", st["node"]["feed_port"],
                                 timeout_s=5)
        assert hello["epoch"] == old_epoch + 1
        # the promoted node serves the replicated chain (threshold=1:
        # at most the last in-flight block is shed)
        res = _rpc(st["node"]["http_port"], "eth_blockNumber", [])
        assert int(res["result"], 16) >= 3

        # a restarted old leader probes the takeover feed and fences
        old = _mk_node(
            tmp_path / "leader", wallet, start_rpc=False,
            ha_peer_feeds=(f"127.0.0.1:{st['node']['feed_port']}",))
        assert old.fence_report and old.fence_report["fenced"], \
            old.fence_report
        assert old.tree.fenced
        r = old.tree.on_forkchoice_updated(b"\x00" * 32)
        assert r.status is PayloadStatusKind.INVALID
        assert "fenced" in (r.validation_error or "")
    finally:
        if old is not None:
            old.stop()
        if sb is not None:
            sb.stop()
        if leader_alive:
            leader.stop()


# -- chaos drills + bench (multi-process, slow) -------------------------------

_HA_INVARIANTS = ("promoted", "root_verified", "loss_bound",
                  "root_twin_identical", "replicas_reanchored",
                  "no_failed_reads", "old_leader_fenced")


@pytest.mark.slow
def test_ha_chaos_leader_kill_single_seed(tmp_path):
    from reth_tpu.chaos import make_ha_scenario, run_ha_scenario

    scn = make_ha_scenario(1)
    assert scn["domain"] == "ha" and scn["replicas"] == 2
    res = run_ha_scenario(scn, tmp_path, timeout=420)
    assert res.get("ok") is True, res
    inv = res.get("invariants", {})
    for k in _HA_INVARIANTS:
        assert inv.get(k) is True, (k, res)


@pytest.mark.slow
def test_ha_chaos_campaign_ten_seeds(tmp_path):
    from reth_tpu.chaos import run_campaign

    results = run_campaign(range(1, 11), tmp_path, domain="ha")
    assert len(results) == 10
    bad = [r for r in results if not r.get("ok")]
    assert not bad, bad


@pytest.mark.slow
def test_ha_chaos_negative_no_fence_drill_fails(tmp_path):
    """RETH_TPU_FAULT_HA_NO_FENCE disables the old leader's fencing
    probe; the invariant suite must notice the split brain — proof the
    drills can fail."""
    from reth_tpu.chaos import make_ha_scenario, run_ha_scenario

    scn = make_ha_scenario(2)
    scn["no_fence"] = True
    res = run_ha_scenario(scn, tmp_path, timeout=420)
    assert res.get("invariants", {}).get("old_leader_fenced") is False, res
    assert res.get("ok") is not True, res


@pytest.mark.slow
def test_bench_ha_mode_end_to_end(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("RETH_TPU_FAULT_")}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(JAX_PLATFORMS="cpu", RETH_TPU_BENCH_MODE="ha",
               RETH_TPU_BENCH_HA_BLOCKS="4")
    repo = Path(__file__).resolve().parent.parent
    r = subprocess.run([sys.executable, str(repo / "bench.py")],
                       capture_output=True, text=True, timeout=560,
                       env=env, cwd=repo)
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "ha_promote_ms"
    assert line.get("error") is None, line
    assert line["value"] > 0
    assert line["reads_failed"] == 0
    assert line["promoted_reads_failed"] == 0
    assert line["replicas_reanchored"] is True
    assert line["leader_epoch"] == 2
    assert r.returncode == 0, (line, r.stderr[-800:])
