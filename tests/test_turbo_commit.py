"""Turbo commit path (native sweep + array backends): parity tests.

Pins native/triebuild.cpp + TurboCommitter (numpy and device backends)
against the Python TrieCommitter, which is itself pinned to the naive
oracle (tests/test_trie.py). Covers inline leaves (deep shared prefixes
with tiny values — the <32-byte RLP case), branch-with-inline-child rows,
TrieUpdates branch metadata, and the SPMD mesh backend.
"""

from __future__ import annotations

import numpy as np
import pytest

from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.nibbles import unpack_nibbles
from reth_tpu.primitives.rlp import rlp_encode
from reth_tpu.trie.committer import TrieCommitter
from reth_tpu.trie.turbo import TurboCommitter


def _job(n, seed, val_len=(1, 100)):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 256, size=(n, 32), dtype=np.uint8)
    keys = np.unique(keys.view("S32").ravel()).view(np.uint8).reshape(-1, 32)
    rng.shuffle(keys)
    values = [
        rlp_encode(bytes(rng.integers(0, 256, size=int(rng.integers(*val_len)), dtype=np.uint8)))
        for _ in range(len(keys))
    ]
    return keys, values


def _baseline_result(jobs, collect=False):
    base = TrieCommitter(hasher=keccak256_batch_np)
    py_jobs = [
        ([(unpack_nibbles(k.tobytes()), v) for k, v in zip(keys, values)], None)
        for keys, values in jobs
    ]
    return base.commit_many(py_jobs, collect_branches=collect)


@pytest.fixture(scope="module")
def turbo_np():
    return TurboCommitter(backend="numpy")


@pytest.mark.parametrize("n", [1, 2, 30, 500, 3000])
def test_turbo_numpy_root_parity(turbo_np, n):
    jobs = [_job(n, seed=n)]
    got = turbo_np.commit_hashed_many(jobs)
    want = _baseline_result(jobs)
    assert got[0].root == want[0].root


def test_turbo_many_jobs(turbo_np):
    jobs = [_job(40, seed=10 + i, val_len=(1, 32)) for i in range(8)] + [_job(900, seed=99)]
    got = turbo_np.commit_hashed_many(jobs)
    want = _baseline_result(jobs)
    assert [r.root for r in got] == [r.root for r in want]


def test_turbo_empty_job(turbo_np):
    from reth_tpu.primitives.types import EMPTY_ROOT_HASH

    keys = np.zeros((0, 32), dtype=np.uint8)
    got = turbo_np.commit_hashed_many([(keys, []), _job(5, seed=1)])
    assert got[0].root == EMPTY_ROOT_HASH
    assert got[1].root == _baseline_result([_job(5, seed=1)])[0].root


def test_turbo_inline_leaves(turbo_np):
    """Keys sharing 60 nibbles with 1-byte values produce <32-byte leaf RLPs
    (inline) and a branch row with literal inline-child bytes."""
    prefix = bytes(range(30))
    keys = np.array(
        [list(prefix + bytes([i, 7])) for i in range(6)]
        + [list(bytes(31) + bytes([9]))],
        dtype=np.uint8,
    )
    values = [rlp_encode(b"\x01")] * len(keys)
    got = turbo_np.commit_hashed_many([(keys, values)])
    want = _baseline_result([(keys, values)])
    assert got[0].root == want[0].root


def test_turbo_branch_meta(turbo_np):
    jobs = [_job(400, seed=4)]
    got = turbo_np.commit_hashed_many(jobs, collect_branches=True)
    want = _baseline_result(jobs, collect=True)
    assert got[0].root == want[0].root
    assert got[0].branch_nodes == want[0].branch_nodes


def test_turbo_duplicate_keys_rejected(turbo_np):
    keys = np.zeros((2, 32), dtype=np.uint8)
    with pytest.raises(ValueError, match="duplicate"):
        turbo_np.commit_hashed_many([(keys, [b"\x01", b"\x02"])])


def test_turbo_device_backend_parity(turbo_np):
    dev = TurboCommitter(backend="device", min_tier=64)
    jobs = [_job(60, seed=21, val_len=(1, 40)) for _ in range(3)] + [_job(800, seed=22)]
    got = dev.commit_hashed_many(jobs, collect_branches=True)
    want = turbo_np.commit_hashed_many(jobs, collect_branches=True)
    assert [r.root for r in got] == [r.root for r in want]
    assert got[-1].branch_nodes == want[-1].branch_nodes


def test_turbo_device_inline_leaves():
    dev = TurboCommitter(backend="device", min_tier=16)
    prefix = bytes(range(30))
    keys = np.array([list(prefix + bytes([i, 7])) for i in range(6)], dtype=np.uint8)
    values = [rlp_encode(b"\x01")] * len(keys)
    got = dev.commit_hashed_many([(keys, values)])
    want = _baseline_result([(keys, values)])
    assert got[0].root == want[0].root


@pytest.mark.parametrize("n_dev", [8, 6])
def test_turbo_mesh_backend_parity(turbo_np, n_dev):
    """Mesh sharding incl. a non-power-of-two device count (6): every tier
    (batch, holes, children) must round to a device-count multiple."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    dev = TurboCommitter(backend="device", min_tier=64, mesh=mesh)
    jobs = [_job(600, seed=31)]
    got = dev.commit_hashed_many(jobs)
    want = turbo_np.commit_hashed_many(jobs)
    assert got[0].root == want[0].root


def test_turbo_start_depth_subtrie_parity(turbo_np):
    """start_depth=2 must yield the embedded subtree: root AND branch-node
    paths (subtrie-relative, skipping the prefix nibbles — review finding)
    equal to the general committer over prefix-stripped paths."""
    rng = np.random.default_rng(77)
    keys = rng.integers(0, 256, size=(64, 32), dtype=np.uint8)
    keys[:, 0] = 0x12  # shared 2-nibble prefix
    values = [rlp_encode(bytes([i + 1])) for i in range(64)]
    got = turbo_np.commit_hashed_many([(keys, values)], collect_branches=True,
                                      start_depth=2)[0]
    base = TrieCommitter(hasher=keccak256_batch_np)
    leaves = [(unpack_nibbles(k.tobytes())[2:], v) for k, v in zip(keys, values)]
    want = base.commit(leaves, collect_branches=True)
    assert got.root == want.root
    assert got.branch_nodes == want.branch_nodes
    assert any(len(p) >= 1 for p in got.branch_nodes), "expected deep branches"


def test_turbo_oversized_value_rejected(turbo_np):
    keys = np.arange(32, dtype=np.uint8).reshape(1, 32)
    with pytest.raises(ValueError, match="triebuild failed"):
        turbo_np.commit_hashed_many([(keys, [b"\x01" * 70000])])


def test_full_state_root_turbo_matches_general(tmp_path):
    """End-to-end: a synced provider's turbo full rebuild equals the general
    committer's root AND the header root (storage tries + account trie,
    with storage roots flowing into the account values)."""
    from reth_tpu.consensus.validation import EthBeaconConsensus
    from reth_tpu.primitives.types import Account
    from reth_tpu.stages import default_stages
    from reth_tpu.stages.api import Pipeline
    from reth_tpu.storage.genesis import import_chain, init_genesis
    from reth_tpu.storage.kv import MemDb
    from reth_tpu.storage.provider import ProviderFactory
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie.incremental import full_state_root, full_state_root_turbo

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    store = bytes.fromhex("5f355f5500")  # sstore(0, calldata[0])
    init = bytes([0x60, len(store), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(store),
                  0x5F, 0xF3]) + b"\x00" + store
    b = ChainBuilder({alice.address: Account(balance=10**21)}, committer=cpu)
    b.build_block([alice.deploy(init)])
    contract = next(iter(a for a, acc in b.accounts.items() if acc.code_hash != Account().code_hash and a != alice.address))
    b.build_block([alice.call(contract, (0xBEEF).to_bytes(32, "big")),
                   alice.transfer(b"\x42" * 20, 777)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, b.genesis, dict(b.accounts_at_genesis), committer=cpu)
    import_chain(factory, b.blocks[1:], EthBeaconConsensus(cpu))
    Pipeline(factory, default_stages(committer=cpu)).run(b.tip.number)
    with factory.provider_rw() as p:
        want = full_state_root(p, cpu)
    with factory.provider_rw() as p:
        got = full_state_root_turbo(p, backend="numpy")
    assert got == want == b.tip.state_root
