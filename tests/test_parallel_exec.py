"""Optimistic parallel execution (engine/optimistic.py): randomized
differential equivalence with the serial executor across conflict rates,
worker counts, coinbase-sensitive ranks, and mid-block reverts; the
RETH_TPU_FAULT_EXEC_* drills; a threaded stress run over the shared
native core; and the conflict-check micro-benchmark (the O(wave^2) ->
aggregate-isdisjoint satellite)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from reth_tpu.engine.bal import TxAccess
from reth_tpu.engine.optimistic import (
    AsyncStateReader,
    execute_block_optimistic,
)
from reth_tpu.evm import BlockExecutor, EvmConfig
from reth_tpu.evm.executor import (
    BEACON_ROOTS_ADDRESS,
    InMemoryStateSource,
    InvalidTransaction,
)
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256
from reth_tpu.primitives.types import Block, Header, Transaction

CFG = EvmConfig(chain_id=1)
COINBASE = b"\xc0" * 20

# PUSH0 CALLDATALOAD PUSH0 SSTORE STOP — slot0 = calldata word
STORE_CODE = bytes.fromhex("5f355f5500")
# PUSH0 PUSH0 REVERT
REVERT_CODE = bytes.fromhex("5f5ffd")
# PUSH20 <coinbase> BALANCE POP STOP — a genuine coinbase read
READ_COINBASE = bytes([0x73]) + COINBASE + bytes.fromhex("315000")
CODES = {keccak256(STORE_CODE): STORE_CODE,
         keccak256(REVERT_CODE): REVERT_CODE,
         keccak256(READ_COINBASE): READ_COINBASE}


def _sender(i: int) -> bytes:
    return bytes([0xA0]) + i.to_bytes(19, "big")


def _tx(nonce, to, value=0, data=b"", gas_limit=200_000, **kw):
    return Transaction(tx_type=2, chain_id=1, nonce=nonce,
                       max_fee_per_gas=100 * 10**9,
                       max_priority_fee_per_gas=10**9, gas_limit=gas_limit,
                       to=to, value=value, data=data, **kw)


def _block(txs, gas_limit=1_000_000_000, **hkw):
    header = Header(number=1, gas_limit=gas_limit, base_fee_per_gas=7,
                    beneficiary=COINBASE, **hkw)
    return Block(header, tuple(txs), (), ())


def _assert_equal(serial, out):
    assert [r.encode_2718() for r in serial.receipts] == \
           [r.encode_2718() for r in out.receipts]
    assert serial.gas_used == out.gas_used
    assert serial.post_accounts == out.post_accounts
    assert serial.post_storage == out.post_storage
    assert serial.changes.accounts == out.changes.accounts
    assert serial.changes.storage == out.changes.storage
    assert serial.changes.wiped_storage == out.changes.wiped_storage
    assert serial.requests == out.requests


def _run_both(accounts, txs, senders, workers=4, block=None, codes=None):
    def mk():
        return InMemoryStateSource(dict(accounts),
                                   codes=dict(codes or CODES))

    blk = block if block is not None else _block(txs)
    serial = BlockExecutor(mk(), CFG).execute(blk, senders)
    out, stats = execute_block_optimistic(mk(), blk, senders, CFG,
                                          max_workers=workers)
    _assert_equal(serial, out)
    return serial, out, stats


def test_disjoint_ranks_commit_native():
    n = 24
    senders = [_sender(i) for i in range(n)]
    accounts = {s: Account(balance=10**20) for s in senders}
    txs = []
    for i in range(n):
        if i % 2:
            c = bytes([0x5C]) + i.to_bytes(19, "big")
            accounts[c] = Account(code_hash=keccak256(STORE_CODE))
            txs.append(_tx(0, c, data=(0xAB00 + i).to_bytes(32, "big")))
        else:
            txs.append(_tx(0, bytes([0xD0]) + i.to_bytes(19, "big"),
                           value=1 + i, gas_limit=21_000))
    _, _, stats = _run_both(accounts, txs, senders, workers=8)
    assert stats["fallback"] is None
    assert stats["native"] == n  # everything took the native core
    assert stats["conflicts"] == 0
    assert stats["rounds"] <= 3  # static keys + one read-feedback retry


@pytest.mark.parametrize("conflict_rate", [0.0, 0.3, 0.7])
@pytest.mark.parametrize("workers", [1, 4])
def test_randomized_differential(conflict_rate, workers):
    """Random mixes of transfers, shared-slot stores (conflicting ranks),
    private stores, coinbase-sensitive reads, reverting calls, and
    same-sender nonce chains — receipts/logs/gas/state bit-identical."""
    rng = np.random.default_rng(int(conflict_rate * 10) * 7 + workers)
    n = 28
    senders, txs = [], []
    accounts = {}
    shared = b"\x5e" * 20
    accounts[shared] = Account(code_hash=keccak256(STORE_CODE))
    reader = b"\x5d" * 20
    accounts[reader] = Account(code_hash=keccak256(READ_COINBASE))
    reverter = b"\x5b" * 20
    accounts[reverter] = Account(code_hash=keccak256(REVERT_CODE))
    chain_sender = _sender(999)
    accounts[chain_sender] = Account(balance=10**20)
    chain_nonce = 0
    for i in range(n):
        roll = rng.random()
        if roll < conflict_rate:
            s = _sender(i)
            accounts[s] = Account(balance=10**20)
            senders.append(s)
            txs.append(_tx(0, shared, data=int(
                rng.integers(1, 1 << 60)).to_bytes(32, "big")))
        elif roll < conflict_rate + 0.1:
            senders.append(chain_sender)  # same-sender chain: serializes
            txs.append(_tx(chain_nonce, bytes([0xD0]) * 20, value=1 + i,
                           gas_limit=21_000))
            chain_nonce += 1
        elif roll < conflict_rate + 0.15:
            s = _sender(i)
            accounts[s] = Account(balance=10**20)
            senders.append(s)
            txs.append(_tx(0, reader))  # coinbase-sensitive
        elif roll < conflict_rate + 0.2:
            s = _sender(i)
            accounts[s] = Account(balance=10**20)
            senders.append(s)
            txs.append(_tx(0, reverter))  # mid-block revert
        else:
            s = _sender(i)
            accounts[s] = Account(balance=10**20)
            c = bytes([0x5C]) + i.to_bytes(19, "big")
            accounts[c] = Account(code_hash=keccak256(STORE_CODE))
            senders.append(s)
            txs.append(_tx(0, c, data=int(
                rng.integers(1, 1 << 60)).to_bytes(32, "big")))
    _, _, stats = _run_both(accounts, txs, senders, workers=workers)
    assert stats["fallback"] is None
    assert stats["native"] + stats["python"] == n


def test_mid_block_revert_receipts_identical():
    senders = [_sender(i) for i in range(3)]
    accounts = {s: Account(balance=10**20) for s in senders}
    reverter = b"\x5b" * 20
    accounts[reverter] = Account(code_hash=keccak256(REVERT_CODE))
    txs = [_tx(0, bytes([0xD1]) * 20, value=5, gas_limit=21_000),
           _tx(0, reverter),
           _tx(0, bytes([0xD2]) * 20, value=7, gas_limit=21_000)]
    serial, out, _ = _run_both(accounts, txs, senders)
    assert [r.success for r in out.receipts] == [True, False, True]


def test_coinbase_sensitive_rank_goes_python():
    senders = [_sender(i) for i in range(4)]
    accounts = {s: Account(balance=10**20) for s in senders}
    reader = b"\x5d" * 20
    accounts[reader] = Account(code_hash=keccak256(READ_COINBASE))
    txs = [_tx(0, bytes([0xD0 + i]) * 20, value=1 + i, gas_limit=21_000)
           for i in range(3)] + [_tx(0, reader)]
    _, _, stats = _run_both(accounts, txs, senders)
    assert stats["python"] >= 1  # the coinbase reader left the native path


def test_same_sender_nonce_chain():
    s = _sender(7)
    accounts = {s: Account(balance=10**20)}
    txs = [_tx(k, bytes([0xD0 + k]) * 20, value=1 + k, gas_limit=21_000)
           for k in range(3)]
    _run_both(accounts, txs, [s, s, s])


def test_invalid_block_raises_same_as_serial():
    s = _sender(1)
    accounts = {s: Account(balance=10**20)}
    txs = [_tx(0, b"\xd1" * 20, value=1, gas_limit=21_000),
           _tx(5, b"\xd2" * 20, value=2, gas_limit=21_000)]  # nonce gap
    block = _block(txs)

    def mk():
        return InMemoryStateSource(dict(accounts), codes=dict(CODES))

    with pytest.raises(InvalidTransaction):
        BlockExecutor(mk(), CFG).execute(block, [s, s])
    with pytest.raises(InvalidTransaction):
        execute_block_optimistic(mk(), block, [s, s], CFG)


def test_system_calls_and_requests_match_serial():
    """A block with a parent beacon root and a present beacon-roots
    contract: the pre-block system call's writes (and the Prague
    requests collection) must fold identically to the serial path."""
    senders = [_sender(i) for i in range(4)]
    accounts = {s: Account(balance=10**20) for s in senders}
    accounts[BEACON_ROOTS_ADDRESS] = Account(
        code_hash=keccak256(STORE_CODE))
    txs = [_tx(0, bytes([0xD0 + i]) * 20, value=1 + i, gas_limit=21_000)
           for i in range(4)]
    block = _block(txs, parent_beacon_block_root=b"\x42" * 32)
    serial, out, stats = _run_both(accounts, txs, senders, block=block)
    assert stats["fallback"] is None
    # the system call's slot write is part of the compared post state
    assert BEACON_ROOTS_ADDRESS in serial.post_storage


def test_blob_tx_takes_python_path():
    senders = [_sender(i) for i in range(3)]
    accounts = {s: Account(balance=10**20) for s in senders}
    blob = Transaction(
        tx_type=3, chain_id=1, nonce=0, max_fee_per_gas=100 * 10**9,
        max_priority_fee_per_gas=10**9, gas_limit=21_000,
        to=b"\xd9" * 20, value=1, max_fee_per_blob_gas=10,
        blob_versioned_hashes=(b"\x01" + b"\x00" * 31,))
    txs = [_tx(0, b"\xd1" * 20, value=3, gas_limit=21_000), blob,
           _tx(0, b"\xd2" * 20, value=4, gas_limit=21_000)]
    _, _, stats = _run_both(accounts, txs, senders)
    assert stats["python"] >= 1  # type-3 is statically native-ineligible


def test_python_engine_without_native(monkeypatch):
    """RETH_TPU_EXEC_NATIVE=0: the pure-Python Block-STM path — parallel
    speculation, read-set validation, speculative commit of clean ranks
    — still bit-identical."""
    monkeypatch.setenv("RETH_TPU_EXEC_NATIVE", "0")
    n = 10
    senders = [_sender(i) for i in range(n)]
    accounts = {s: Account(balance=10**20) for s in senders}
    txs = [_tx(0, bytes([0xD0]) + i.to_bytes(19, "big"), value=1 + i,
               gas_limit=21_000) for i in range(n)]
    _, _, stats = _run_both(accounts, txs, senders)
    assert stats["native"] == 0
    assert stats["python"] == n
    assert stats["speculative"] == n  # disjoint: every speculation commits


def test_conflict_storm_drill(monkeypatch):
    """RETH_TPU_FAULT_EXEC_CONFLICT_STORM: every rank is treated as
    invalidated — the all-conflict worst case runs fully serial through
    the re-execution ladder, output still bit-identical."""
    monkeypatch.setenv("RETH_TPU_FAULT_EXEC_CONFLICT_STORM", "1")
    n = 8
    senders = [_sender(i) for i in range(n)]
    accounts = {s: Account(balance=10**20) for s in senders}
    txs = [_tx(0, bytes([0xD0]) + i.to_bytes(19, "big"), value=1 + i,
               gas_limit=21_000) for i in range(n)]
    _, _, stats = _run_both(accounts, txs, senders)
    assert stats["native"] == 0
    assert stats["serial_rerun"] == n
    assert stats["speculative"] == 0


def test_rank_wedge_drill_falls_back_serial(monkeypatch):
    """RETH_TPU_FAULT_EXEC_RANK_WEDGE: a wedged speculative worker trips
    the rank timeout; the scheduler abandons the attempt and the serial
    fallback still produces the identical block."""
    monkeypatch.setenv("RETH_TPU_FAULT_EXEC_RANK_WEDGE", "1")
    monkeypatch.setenv("RETH_TPU_FAULT_EXEC_WEDGE_S", "1.5")
    monkeypatch.setenv("RETH_TPU_EXEC_RANK_TIMEOUT", "0.1")
    monkeypatch.setenv("RETH_TPU_EXEC_NATIVE", "0")  # force python ranks
    n = 4
    senders = [_sender(i) for i in range(n)]
    accounts = {s: Account(balance=10**20) for s in senders}
    txs = [_tx(0, bytes([0xD0]) + i.to_bytes(19, "big"), value=1 + i,
               gas_limit=21_000) for i in range(n)]
    serial, out, stats = _run_both(accounts, txs, senders)
    assert stats["fallback"]  # the ladder's last rung ran
    assert "wedged" in stats["fallback"]


def test_threaded_stress_shared_native_core():
    """Concurrent schedulers over the one shared libevmexec: each thread
    executes its own block and must match its own serial run."""
    errs: list = []

    def worker(seed):
        try:
            n = 12
            senders = [bytes([0xB0 + seed]) + i.to_bytes(19, "big")
                       for i in range(n)]
            accounts = {s: Account(balance=10**20) for s in senders}
            txs = []
            for i in range(n):
                c = bytes([0x50 + seed]) + i.to_bytes(19, "big")
                accounts[c] = Account(code_hash=keccak256(STORE_CODE))
                txs.append(_tx(0, c, data=(seed * 1000 + i).to_bytes(32,
                                                                     "big")))
            _run_both(accounts, txs, senders, workers=2)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_async_reader_prefetches_and_stops():
    src = InMemoryStateSource(
        {_sender(0): Account(balance=5)}, {_sender(0): {b"\x01" * 32: 9}})
    reader = AsyncStateReader(src, workers=1)
    reader.request([_sender(0), (_sender(0), b"\x01" * 32)])
    deadline = time.time() + 5
    while time.time() < deadline and reader.prefetched < 2:
        time.sleep(0.01)
    assert reader.accounts[_sender(0)].balance == 5
    assert reader.slots[(_sender(0), b"\x01" * 32)] == 9
    reader.stop()


def test_conflict_check_microbench():
    """Satellite: the aggregate-isdisjoint conflict predicate must beat a
    per-pair scan by a wide margin on a big conflict-free wave (the
    documented O(wave^2) hot cost)."""
    n = 800
    accs = []
    for i in range(n):
        a = TxAccess(index=i)
        a.account_writes = {i.to_bytes(2, "big") + bytes(18)}
        a.account_reads = set(a.account_writes)
        a.slot_writes = {(b"\x5c" * 20, i.to_bytes(32, "big"))}
        a.slot_reads = set(a.slot_writes)
        accs.append(a)

    t0 = time.perf_counter()
    hits = 0
    for i, a in enumerate(accs):  # the seed's shape: scan every pair
        mine_a = a.account_reads | a.account_writes
        mine_s = a.slot_reads | a.slot_writes
        for b in accs[:i]:
            if b.account_writes & mine_a or b.slot_writes & mine_s:
                hits += 1
    t_pair = time.perf_counter() - t0

    t0 = time.perf_counter()
    accts: set = set()
    slots: set = set()
    agg_hits = 0
    for a in accs:
        if a.conflicts_with_write_sets(accts, slots):
            agg_hits += 1
        accts |= a.account_writes
        slots |= a.slot_writes
    t_agg = time.perf_counter() - t0

    assert hits == 0 and agg_hits == 0  # the wave really is conflict-free
    assert t_agg * 2 < t_pair, (t_agg, t_pair)


def test_exec_metrics_recorded():
    from reth_tpu.metrics import REGISTRY, exec_metrics

    before = REGISTRY.counter("exec_parallel_blocks_total").value
    exec_metrics.record_optimistic(
        {"rounds": 2, "native": 10, "python": 1, "speculative": 1,
         "serial_rerun": 0, "conflicts": 3, "misses": 1, "prefetched": 40,
         "workers": 4, "wall_s": 0.01, "fallback": None})
    assert REGISTRY.counter("exec_parallel_blocks_total").value == before + 1
    assert exec_metrics.last["native"] == 10
    exec_metrics.record_bal({"waves": 3, "parallel": 5, "serial": 2,
                             "native": 6})
    assert exec_metrics.last_bal["waves"] == 3
    assert REGISTRY.counter("exec_bal_waves_total").value >= 3


def test_engine_tree_parallel_exec_roots():
    """An EngineTree with --parallel-exec validates real payloads with
    roots identical to the builder's, recording per-block stats."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.engine import EngineTree
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    wallets = [Wallet(0x7000 + i) for i in range(5)]
    builder = ChainBuilder(
        {w.address: Account(balance=10**20) for w in wallets},
        committer=CPU)
    builder.build_block([w.transfer(bytes([0xE0 + i]) * 20, 100 + i)
                         for i, w in enumerate(wallets)])
    builder.build_block([wallets[0].transfer(wallets[1].address, 10**19),
                         wallets[1].transfer(wallets[2].address, 77),
                         wallets[3].transfer(b"\xe9" * 20, 1),
                         wallets[4].transfer(b"\xea" * 20, 2)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    tree = EngineTree(factory, CPU, EthBeaconConsensus(CPU),
                      parallel_exec=True)
    tree.prewarm_threshold = 2
    for block in builder.blocks[1:]:
        status = tree.on_new_payload(block)
        assert status.status.name == "VALID", status.validation_error
        tree.on_forkchoice_updated(block.header.hash)
    assert tree.last_exec is not None
    assert tree.last_exec["fallback"] is None
    assert tree.last_exec["native"] + tree.last_exec["python"] == 4
    assert tree.last_prewarm is None  # the prewarm pass was folded in


def test_payload_builder_parallel_matches_serial():
    """build_payload with --parallel-exec seals a bit-identical block."""
    from reth_tpu.consensus import EthBeaconConsensus
    from reth_tpu.engine import EngineTree
    from reth_tpu.payload.builder import PayloadAttributes, build_payload
    from reth_tpu.pool.pool import PoolConfig, TransactionPool
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    wallets = [Wallet(0x8000 + i) for i in range(8)]
    builder = ChainBuilder(
        {w.address: Account(balance=10**20) for w in wallets},
        committer=CPU)

    def mk_tree(par):
        factory = ProviderFactory(MemDb())
        init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                     committer=CPU)
        return EngineTree(factory, CPU, EthBeaconConsensus(CPU),
                          parallel_exec=par)

    def mk_pool(tree):
        pool = TransactionPool(lambda: tree.overlay_provider(),
                               PoolConfig(chain_id=1))
        for i, w in enumerate(wallets):
            pool.add_transaction(
                Wallet(w.priv).transfer(bytes([0xF0 + i]) * 20, 1000 + i))
        return pool

    attrs = PayloadAttributes(timestamp=builder.genesis.timestamp + 12,
                              suggested_fee_recipient=COINBASE)
    t_ser = mk_tree(False)
    b_ser, f_ser = build_payload(t_ser, mk_pool(t_ser),
                                 builder.genesis.hash, attrs)
    t_par = mk_tree(True)
    b_par, f_par = build_payload(t_par, mk_pool(t_par),
                                 builder.genesis.hash, attrs)
    assert b_ser.hash == b_par.hash
    assert f_ser == f_par
    assert len(b_par.transactions) == len(wallets)
