"""Execution witness generation + stateless validation (reference
debug_executionWitness / invalid-block witness hook / sparse-trie
strategy, re-executed here with NO state source)."""

import numpy as np
import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.engine.stateless import (
    StatelessChain,
    StatelessValidationError,
)
from reth_tpu.engine.witness import ExecutionWitness, generate_witness
from reth_tpu.evm import EvmConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.types import Header
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

# PUSH0 CALLDATALOAD PUSH0 SSTORE STOP — stores calldata word0 at slot 0
STORE_CODE = bytes.fromhex("5f355f5500")


def initcode_for(runtime: bytes) -> bytes:
    n = len(runtime)
    return bytes([0x60, n, 0x60, 0x0B, 0x5F, 0x39, 0x60, n, 0x5F, 0xF3]) \
        + b"\x00" + runtime


def build_chain():
    """Transfers, a contract deploy, storage writes AND a slot zeroing
    (delete path), across several blocks."""
    alice = Wallet(0xA11CE)
    bob = Wallet(0xB0B)
    builder = ChainBuilder({
        alice.address: Account(balance=10**21),
        bob.address: Account(balance=10**21),
    }, committer=CPU)
    builder.build_block([alice.transfer(b"\x0c" * 20, 1000)])
    deploy = alice.deploy(initcode_for(STORE_CODE))
    builder.build_block([deploy])
    contract = [a for a, acc in builder.accounts.items()
                if builder.codes.get(acc.code_hash) == STORE_CODE][0]
    builder.build_block([
        alice.call(contract, (0xBEEF).to_bytes(32, "big")),
        bob.transfer(alice.address, 7),
    ])
    # zero the slot: storage delete path
    builder.build_block([alice.call(contract, (0).to_bytes(32, "big"))])
    builder.build_block([bob.transfer(b"\x0d" * 20, 55)])
    return builder


def test_witness_closed_and_stateless_chain_validates():
    builder = build_chain()
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 builder.storage_at_genesis, builder.codes_at_genesis,
                 committer=CPU)
    chain = StatelessChain(config=EvmConfig(chain_id=builder.chain_id))
    consensus = EthBeaconConsensus(CPU)
    for n in range(1, len(builder.blocks)):
        block = builder.blocks[n]
        parent = builder.blocks[n - 1].header
        # witness from the provider view at n-1 (current tip)
        with factory.provider() as p:
            w = generate_witness(p, block, CPU,
                                 parent_header=parent,
                                 config=EvmConfig(chain_id=builder.chain_id))
        # round-trip through the JSON wire form
        w2 = ExecutionWitness.from_json(w.to_json())
        root = chain.validate(block, w2, parent)
        assert root == block.header.state_root
        # advance the stateful node to n for the next witness
        import_chain(factory, [block], consensus)
        Pipeline(factory, default_stages(committer=CPU)).run(n)
    # the preserved trie chained across all blocks after the first
    assert chain.preserved.hits == len(builder.blocks) - 2


def test_stateless_rejects_tampered_block():
    builder = build_chain()
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 builder.storage_at_genesis, builder.codes_at_genesis,
                 committer=CPU)
    block = builder.blocks[1]
    parent = builder.genesis
    with factory.provider() as p:
        w = generate_witness(p, block, CPU, parent_header=parent,
                             config=EvmConfig(chain_id=builder.chain_id))
    # tamper: claim a different state root
    import dataclasses
    bad_header = dataclasses.replace(block.header, state_root=b"\xde" * 32)
    bad_block = dataclasses.replace(block, header=bad_header)
    chain = StatelessChain(config=EvmConfig(chain_id=builder.chain_id))
    with pytest.raises(StatelessValidationError, match="root mismatch"):
        chain.validate(bad_block, w, parent)


def test_incomplete_witness_detected():
    builder = build_chain()
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 builder.storage_at_genesis, builder.codes_at_genesis,
                 committer=CPU)
    block = builder.blocks[1]
    with factory.provider() as p:
        w = generate_witness(p, block, CPU, parent_header=builder.genesis,
                             config=EvmConfig(chain_id=builder.chain_id))
    # drop a state node: validation must fail loudly, not mis-validate
    assert len(w.state) > 1
    w.state = w.state[:1]
    chain = StatelessChain(config=EvmConfig(chain_id=builder.chain_id))
    with pytest.raises(StatelessValidationError):
        chain.validate(block, w, builder.genesis)


# PUSH0 CALLDATALOAD BLOCKHASH PUSH0 SSTORE STOP — stores BLOCKHASH(word0)
BLOCKHASH_CODE = bytes.fromhex("5f35405f5500")


def _blockhash_chain():
    """Chain whose last block SSTOREs BLOCKHASH(n-3) — the witness must ship
    the ancestor headers down to that depth or stateless replay computes 0."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    builder.build_block([alice.deploy(initcode_for(BLOCKHASH_CODE))])
    contract = [a for a, acc in builder.accounts.items()
                if builder.codes.get(acc.code_hash) == BLOCKHASH_CODE][0]
    builder.build_block([alice.transfer(b"\x0c" * 20, 1)])
    builder.build_block([alice.transfer(b"\x0c" * 20, 2)])
    # block 4 reads BLOCKHASH(1): depth 3 — beyond just the parent header
    builder.build_block([alice.call(contract, (1).to_bytes(32, "big"))])
    return builder


def _blockhash_witness():
    """(builder, block-4, its witness) with the chain synced to block 3."""
    builder = _blockhash_chain()
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 builder.storage_at_genesis, builder.codes_at_genesis,
                 committer=CPU)
    import_chain(factory, builder.blocks[1:4], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(3)
    block = builder.blocks[4]
    with factory.provider() as p:
        w = generate_witness(p, block, CPU,
                             parent_header=builder.blocks[3].header,
                             config=EvmConfig(chain_id=builder.chain_id))
    return builder, block, w


def test_witness_ships_blockhash_ancestor_headers():
    builder, block, w = _blockhash_witness()
    # parent (3) + ancestors 2 and 1: the chain down to the read number
    assert len(w.headers) == 3
    chain = StatelessChain(config=EvmConfig(chain_id=builder.chain_id))
    root = chain.validate(block, w, builder.blocks[3].header)
    assert root == block.header.state_root


def test_stateless_rejects_unlinked_witness_headers():
    builder, block, w = _blockhash_witness()
    import dataclasses
    cfg = EvmConfig(chain_id=builder.chain_id)
    # (a) ancestor header replaced by a forged one: linkage check trips
    forged = dataclasses.replace(
        Header.decode(w.headers[1]), state_root=b"\xfe" * 32)
    w_forged = ExecutionWitness(state=w.state, codes=w.codes, keys=w.keys,
                                headers=[w.headers[0], forged.encode(),
                                         w.headers[2]])
    with pytest.raises(StatelessValidationError, match="hash-linked"):
        StatelessChain(config=cfg).validate(
            block, w_forged, builder.blocks[3].header)
    # (b) an extra header outside the ancestor chain: rejected outright
    stray = dataclasses.replace(builder.blocks[2].header, number=9999)
    w_stray = ExecutionWitness(state=w.state, codes=w.codes, keys=w.keys,
                               headers=list(w.headers) + [stray.encode()])
    with pytest.raises(StatelessValidationError, match="not in ancestor"):
        StatelessChain(config=cfg).validate(
            block, w_stray, builder.blocks[3].header)


def test_witness_includes_touched_codes():
    builder = build_chain()
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 builder.storage_at_genesis, builder.codes_at_genesis,
                 committer=CPU)
    consensus = EthBeaconConsensus(CPU)
    import_chain(factory, builder.blocks[1:3], consensus)
    Pipeline(factory, default_stages(committer=CPU)).run(2)
    # block 3 calls the contract: its code must ship in the witness
    block = builder.blocks[3]
    with factory.provider() as p:
        w = generate_witness(p, block, CPU,
                             parent_header=builder.blocks[2].header,
                             config=EvmConfig(chain_id=builder.chain_id))
    assert STORE_CODE in w.codes
    assert any(len(k) == 20 for k in w.keys)      # address preimages
    assert any(len(k) == 32 for k in w.keys)      # slot preimages


# PUSH1 32 CALLDATALOAD (value) PUSH0 CALLDATALOAD (key) SSTORE STOP —
# stores storage[calldata word0] = calldata word1
KV_CODE = bytes.fromhex("6020355f355500")


def _kv_set(wallet, kv, key: int, value: int):
    data = key.to_bytes(32, "big") + value.to_bytes(32, "big")
    return wallet.call(kv, data)


def test_witness_closed_across_consecutive_block_deletion_collapse():
    """The cross-block closure contract the replica fleet leans on:
    block n touches only slot A; block n+1 zeroes A, collapsing A's
    branch into sibling B's leaf — a leaf block n's witness never
    revealed (it shipped only A's spine; B sat behind a hash ref). The
    PRODUCER must close block n+1's witness (reveal B during
    generation), so a StatelessChain carrying the preserved sparse trie
    from block n replays n+1 with no BlindedNodeError and a root
    bit-identical to the full node's header."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    builder.build_block([alice.deploy(initcode_for(KV_CODE))])
    kv = [a for a, acc in builder.accounts.items()
          if builder.codes.get(acc.code_hash) == KV_CODE][0]
    # slots A=1 and B=2 share the storage trie; with exactly two leaves
    # the root branch collapses into B's leaf the moment A deletes
    builder.build_block([_kv_set(alice, kv, 1, 0xAA),
                         _kv_set(alice, kv, 2, 0xBB)])
    builder.build_block([_kv_set(alice, kv, 1, 0xA2)])    # block n: A only
    builder.build_block([_kv_set(alice, kv, 1, 0)])       # n+1: delete A
    assert builder.storages[kv] == {(2).to_bytes(32, "big"): 0xBB}

    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 builder.storage_at_genesis, builder.codes_at_genesis,
                 committer=CPU)
    consensus = EthBeaconConsensus(CPU)
    cfg = EvmConfig(chain_id=builder.chain_id)
    chain = StatelessChain(config=cfg)
    witnesses = []
    for n in range(1, len(builder.blocks)):
        block = builder.blocks[n]
        with factory.provider() as p:
            w = generate_witness(p, block, CPU,
                                 parent_header=builder.blocks[n - 1].header,
                                 config=cfg)
        witnesses.append(w)
        # preserved-trie replay: no BlindedNodeError, root == header
        root = chain.validate(block, w, builder.blocks[n - 1].header)
        assert root == block.header.state_root
        import_chain(factory, [block], consensus)
        Pipeline(factory, default_stages(committer=CPU)).run(n)
    # the trie really chained block-to-block (no silent re-anchors)
    assert chain.preserved.hits == len(builder.blocks) - 2
    # the producer CLOSED block n+1's witness: a FRESH chain (no
    # preserved trie at all) must also replay it from the wire form
    fresh = StatelessChain(config=cfg)
    w_last = ExecutionWitness.from_json(witnesses[-1].to_json())
    root = fresh.validate(builder.blocks[-1], w_last,
                          builder.blocks[-2].header)
    assert root == builder.blocks[-1].header.state_root
    # and closure is what made that possible: block n's witness alone
    # (A's spine only) genuinely lacked B's leaf, so n+1's witness must
    # be strictly richer than a naive touched-keys multiproof
    from reth_tpu.primitives.keccak import keccak256
    prev_nodes = {keccak256(x) for x in witnesses[-2].state}
    last_nodes = {keccak256(x) for x in witnesses[-1].state}
    assert last_nodes - prev_nodes, "n+1 witness revealed nothing new"
