"""Auxiliary components: event streams, NAT resolution, state cache,
process metrics, node events dashboard (reference crates/tokio-util,
crates/net/nat, rpc-eth-types EthStateCache, node/metrics, node/events)."""

import threading
import time

import pytest

from reth_tpu.events import EventSender
from reth_tpu.net.nat import NatResolver


def test_event_stream_fanout_and_lag():
    sender = EventSender(buffer=4)
    a = sender.new_listener()
    b = sender.new_listener()
    for i in range(3):
        sender.notify(i)
    assert a.next(0) == 0 and a.next(0) == 1 and a.next(0) == 2
    # b lags: overflow drops its OLDEST events, producer never blocks
    for i in range(3, 10):
        sender.notify(i)
    got = [b.next(0) for _ in range(4)]
    assert got == [6, 7, 8, 9]
    assert b.dropped == 6
    # close wakes blocked consumers with end-of-stream
    done = []

    def consume():
        done.extend(list(a))

    t = threading.Thread(target=consume)
    t.start()
    sender.notify("last")
    time.sleep(0.05)
    sender.close()
    t.join(timeout=5)
    assert not t.is_alive()
    assert done[-1] == "last"


def test_event_stream_unsubscribe():
    sender = EventSender()
    s = sender.new_listener()
    s.unsubscribe()
    sender.notify("x")
    assert s.next(0) is None


def test_nat_resolver():
    assert NatResolver.parse("extip:1.2.3.4").external_ip() == "1.2.3.4"
    with pytest.raises(ValueError):
        NatResolver.parse("extip:not-an-ip")
    with pytest.raises(ValueError):
        NatResolver.parse("bogus")
    none = NatResolver.parse("none")
    assert none.external_ip("0.0.0.0") == "127.0.0.1"
    assert none.external_ip("10.1.2.3") == "10.1.2.3"
    anyr = NatResolver.parse("any")
    ip = anyr.external_ip("0.0.0.0")
    assert ip.count(".") == 3
    # upnp needs egress: degrades with an explicit reason, never errors
    up = NatResolver.parse("upnp")
    assert up.fallback_reason and up.external_ip("0.0.0.0")


def test_process_metrics_gauges():
    from reth_tpu.metrics import MetricsRegistry, update_process_metrics

    reg = MetricsRegistry()
    update_process_metrics(reg)
    text = reg.render()
    assert "process_resident_memory_bytes" in text
    assert "process_open_fds" in text
    assert "process_uptime_seconds" in text


def test_eth_state_cache_hits_and_reorg_safety():
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.rpc.state_cache import EthStateCache
    from reth_tpu.storage import MemDb, ProviderFactory
    from reth_tpu.storage.genesis import import_chain, init_genesis
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    builder.build_block([alice.transfer(b"\x0b" * 20, 5)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:])
    from reth_tpu.stages import Pipeline, default_stages

    Pipeline(factory, default_stages(committer=CPU)).run(1)
    cache = EthStateCache(max_blocks=8)
    with factory.provider() as p:
        b1, senders = cache.block_with_senders(p, 1)
        assert b1.header.number == 1 and len(senders) == 1
        again, _ = cache.block_with_senders(p, 1)
        assert again is b1  # served from cache
        rec = cache.receipts(p, 1)
        assert len(rec) == 1 and cache.receipts(p, 1) is rec
        assert cache.block_with_senders(p, 99) is None


def test_node_event_reporter_line():
    from types import SimpleNamespace

    from reth_tpu.node.events import NodeEventReporter
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.primitives import Account
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    builder.build_block([alice.transfer(b"\x0b" * 20, 5)])
    fake_node = SimpleNamespace(pool=None, network=None)
    rep = NodeEventReporter(fake_node, interval=999)
    eb = SimpleNamespace(block=builder.blocks[1])
    stream = rep.sender.new_listener()
    rep.on_canon_change([eb])
    line = rep.report_once()
    assert "number=1" in line and "txs=1" in line
    assert rep.report_once() is None  # window drained
    ev = stream.next(0)
    assert ev.number == 1 and ev.txs == 1


def test_otlp_file_exporter(tmp_path):
    """span() exports OTLP/JSON span records once the exporter is
    installed (reference crates/tracing-otlp; file transport here)."""
    import json

    from reth_tpu.tracing import init_otlp, shutdown_otlp, span

    path = tmp_path / "spans.jsonl"
    exp = init_otlp(path, service_name="test-node")
    try:
        with span("trie.state_root", "commit", leaves=42):
            pass
        try:
            with span("engine", "boom"):
                raise ValueError("x")
        except ValueError:
            pass
    finally:
        shutdown_otlp()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 2 and exp.exported == 2
    first = lines[0]["scopeSpans"][0]
    assert first["scope"]["name"] == "reth_tpu.trie.state_root"
    sp = first["spans"][0]
    assert sp["name"] == "commit"
    assert {"key": "leaves", "value": {"stringValue": "42"}} in sp["attributes"]
    assert int(sp["endTimeUnixNano"]) >= int(sp["startTimeUnixNano"])
    assert lines[1]["scopeSpans"][0]["spans"][0]["status"]["code"] == 2


def test_bb_bench_cli(capsys):
    from reth_tpu.cli import main

    assert main(["bb-bench", "--transfers", "20", "--stores", "8"]) == 0
    out = capsys.readouterr().out
    assert "Mgas/s" in out and "execution_mgas_per_sec" in out


def test_nippyjar_standalone_roundtrip(tmp_path):
    """The standalone immutable column format: arbitrary columns +
    metadata, per-column tiers, integrity verification, corruption
    detection (reference crates/storage/nippy-jar)."""
    import os

    from reth_tpu.storage.nippyjar import NippyJar

    cols = {
        "k": [os.urandom(32) for _ in range(25)],
        "v": [b"payload-" * 40 + bytes([i]) for i in range(25)],
    }
    path = tmp_path / "data.jar"
    NippyJar.write(path, cols, metadata={"purpose": "test", "epoch": 7})
    jar = NippyJar.open(path)
    assert jar.count == 25 and jar.columns == ["k", "v"]
    assert jar.metadata == {"purpose": "test", "epoch": 7}
    assert jar.row("k", 13) == cols["k"][13]
    assert list(jar.column_rows("v")) == cols["v"]
    assert jar.verify() is True
    import pytest as _pytest

    with _pytest.raises(IndexError):
        jar.row("k", 25)
    jar.close()
    # flip one payload byte: verify() must catch it
    raw = bytearray(path.read_bytes())
    raw[-3] ^= 0xFF
    path.write_bytes(bytes(raw))
    jar2 = NippyJar.open(path)
    assert jar2.verify() is False
    jar2.close()
