"""EXTERNAL conformance vectors — ground truth this repo did not generate.

Breaks the generated-fixture circularity (round-3 verdict #3): every
expected value here was produced by OTHER implementations — geth
(`cast proof` / `geth init` outputs recorded in the reference's in-tree
tests, crates/trie/db/tests/proof.rs), the EIP-8 specification's
handshake test vectors (crates/net/ecies/src/algorithm.rs), and the
canonical Ethereum mainnet/Holesky genesis data. A disagreement anywhere
in keccak, RLP, secure-trie structure, proof spine extraction, ECIES, or
signature recovery fails these tests against data we cannot have
"agreed with ourselves" about.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.types import EMPTY_ROOT_HASH, Header
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.trie import TrieCommitter
from reth_tpu.trie.incremental import full_state_root
from reth_tpu.trie.proof import ProofCalculator

FIXTURES = Path(__file__).parent / "fixtures"
CPU = TrieCommitter(hasher=keccak256_batch_np)


def _hx(s: str) -> bytes:
    return bytes.fromhex(s.removeprefix("0x"))


def _load_alloc(path, with_storage=False):
    spec = json.loads((FIXTURES / path).read_text())
    alloc = {}
    storage = {}
    codes = {}
    for addr_hex, entry in spec["alloc"].items():
        addr = _hx(addr_hex) if addr_hex.startswith("0x") else bytes.fromhex(addr_hex)
        bal = entry.get("balance", "0")
        bal = int(bal, 16) if bal.startswith("0x") else int(bal)
        code = _hx(entry["code"]) if entry.get("code") else b""
        ch = keccak256(code) if code else keccak256(b"")
        alloc[addr] = Account(nonce=int(entry.get("nonce", "0"), 0),
                              balance=bal, code_hash=ch)
        if code:
            codes[ch] = code
        if with_storage and entry.get("storage"):
            storage[addr] = {
                _hx(k): int(v, 16) for k, v in entry["storage"].items()
            }
    return spec, alloc, storage, codes


def _state_factory(alloc, storage):
    factory = ProviderFactory(MemDb())
    with factory.provider_rw() as p:
        batch = list(alloc.items())
        digests = CPU.hasher([a for a, _ in batch])
        for (a, acct), ha in zip(batch, digests):
            p.put_hashed_account(bytes(ha), acct)
        for a, slots in storage.items():
            ha = bytes(CPU.hasher([a])[0])
            sk = list(slots.items())
            sds = CPU.hasher([s for s, _ in sk])
            for (s, v), hs in zip(sk, sds):
                p.put_hashed_storage(ha, bytes(hs), v)
        root = full_state_root(p, CPU)
    return factory, root


# -- geth-derived trie + proof vectors ---------------------------------------


@pytest.fixture(scope="module")
def geth_proofs():
    return json.loads((FIXTURES / "geth_proofs.json").read_text())


@pytest.fixture(scope="module")
def testspec_state():
    _, alloc, storage, _ = _load_alloc("proof-genesis.json")
    return _state_factory(alloc, storage)


def test_testspec_account_proofs_match_geth(geth_proofs, testspec_state):
    """Byte-for-byte account-proof equality with geth's proof RPC over the
    reference's 4-account test genesis (proof.rs testspec_proofs)."""
    factory, root = testspec_state
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        for case in geth_proofs["testspec"]:
            ap = calc.account_proof(_hx(case["address"]))
            assert [b"" + n for n in ap.proof] == [_hx(n) for n in case["proof"]], \
                f"proof mismatch for {case['address']}"


@pytest.fixture(scope="module")
def mainnet_state():
    spec, alloc, storage, _ = _load_alloc("mainnet-genesis.json")
    factory, root = _state_factory(alloc, storage)
    return spec, factory, root


def test_mainnet_genesis_state_root_and_hash(mainnet_state):
    """THE canonical external vector: the Ethereum mainnet genesis state
    root and block hash, recomputed from the full 8893-account alloc."""
    spec, factory, root = mainnet_state
    assert root == _hx("0xd7f8974fb5ac78d9ac099b9ad5018bedc2ce0a72dad1827a1709da30580f0544")
    assert root == _hx(spec["stateRoot"])
    header = Header(
        parent_hash=_hx(spec["parentHash"]),
        beneficiary=_hx(spec["coinbase"]),
        state_root=root,
        difficulty=int(spec["difficulty"], 16),
        number=int(spec["number"], 16),
        gas_limit=int(spec["gasLimit"], 16),
        gas_used=int(spec["gasUsed"], 16),
        timestamp=int(spec["timestamp"], 16),
        extra_data=_hx(spec["extraData"]),
        mix_hash=_hx(spec["mixHash"]),
        nonce=_hx(spec["nonce"]).rjust(8, b"\x00"),
        base_fee_per_gas=None,
        withdrawals_root=None,
    )
    assert header.hash == _hx(
        "0xd4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3")


def test_mainnet_genesis_account_proofs_match_geth(geth_proofs, mainnet_state):
    """`cast proof ... --block 0` vectors over mainnet genesis: an existent
    and a nonexistent account (proof.rs mainnet_genesis_account_proof*)."""
    _, factory, root = mainnet_state
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        for key in ("mainnet_existent", "mainnet_nonexistent"):
            case = geth_proofs[key]
            ap = calc.account_proof(_hx(case["address"]))
            assert [b"" + n for n in ap.proof] == [_hx(n) for n in case["proof"]], key


def test_holesky_deposit_contract_proof_matches_geth(geth_proofs):
    """Holesky genesis deposit-contract: storage root, code hash, and the
    `cast proof` account + storage proofs for slots 0x22/0x23/0x24 and a
    nonexistent slot (proof.rs holesky_deposit_contract_proof)."""
    _, alloc, storage, codes = _load_alloc("holesky-genesis.json", with_storage=True)
    case = geth_proofs["holesky_deposit"]
    target = _hx(case["address"])
    assert alloc[target].code_hash == _hx(case["code_hash"])
    factory, root = _state_factory(alloc, storage)
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        slots = [int(sp["slot"], 16).to_bytes(32, "big")
                 for sp in case["storage_proofs"]]
        ap = calc.account_proof(target, slots)
        assert ap.storage_root == _hx(case["storage_root"])
        assert [b"" + n for n in ap.proof] == [_hx(n) for n in case["account_proof"]]
        for sp, got in zip(case["storage_proofs"], ap.storage_proofs):
            assert got.value == int(sp["value"], 16)
            assert [b"" + n for n in got.proof] == [_hx(n) for n in sp["proof"]], sp["slot"]


# -- EIP-8 RLPx handshake vectors --------------------------------------------

EIP8_SERVER_KEY = 0xB71C71A67E1177AD4E901695E1B4B9EE17AE16C6668D313EAC2F96DBCDA3F291
EIP8_SERVER_EPH = 0xE238EB8E04FEE6511AB04C6DD3C89CE097B11F25D584863AC2B6D5B35B1847E4
EIP8_SERVER_NONCE = _hx("0x559aead08264d5795d3909718cdd05abd49572e84fe55590eef31a88a08fdffd")
EIP8_CLIENT_KEY = 0x49A7B37AA6F6645917E7B807E9D1C00D4FA71F18343B0D4122A4D2DF64DD6FEE
EIP8_CLIENT_EPH = 0x869D6ECF5211F1CC60418A13B9D870B22959D0C16F02BEC714C960DD2298A32D
EIP8_CLIENT_NONCE = _hx("0x7e968bba13b6c50e2c4cd7f241cc0d64d1ac25c7f5952df231ac6a2bda8ee5d6")

EIP8_AUTH_2 = _hx(
    "0x01b304ab7578555167be8154d5cc456f567d5ba302662433674222360f08d5f1534499d3678b513b"
    "0fca474f3a514b18e75683032eb63fccb16c156dc6eb2c0b1593f0d84ac74f6e475f1b8d56116b84"
    "9634a8c458705bf83a626ea0384d4d7341aae591fae42ce6bd5c850bfe0b999a694a49bbbaf3ef6c"
    "da61110601d3b4c02ab6c30437257a6e0117792631a4b47c1d52fc0f8f89caadeb7d02770bf999cc"
    "147d2df3b62e1ffb2c9d8c125a3984865356266bca11ce7d3a688663a51d82defaa8aad69da39ab6"
    "d5470e81ec5f2a7a47fb865ff7cca21516f9299a07b1bc63ba56c7a1a892112841ca44b6e0034dee"
    "70c9adabc15d76a54f443593fafdc3b27af8059703f88928e199cb122362a4b35f62386da7caad09"
    "c001edaeb5f8a06d2b26fb6cb93c52a9fca51853b68193916982358fe1e5369e249875bb8d0d0ec3"
    "6f917bc5e1eafd5896d46bd61ff23f1a863a8a8dcd54c7b109b771c8e61ec9c8908c733c0263440e"
    "2aa067241aaa433f0bb053c7b31a838504b148f570c0ad62837129e547678c5190341e4f1693956c"
    "3bf7678318e2d5b5340c9e488eefea198576344afbdf66db5f51204a6961a63ce072c8926c")

EIP8_AUTH_3 = _hx(
    "0x01b8044c6c312173685d1edd268aa95e1d495474c6959bcdd10067ba4c9013df9e40ff45f5bfd6f7"
    "2471f93a91b493f8e00abc4b80f682973de715d77ba3a005a242eb859f9a211d93a347fa64b597bf"
    "280a6b88e26299cf263b01b8dfdb712278464fd1c25840b995e84d367d743f66c0e54a586725b7bb"
    "f12acca27170ae3283c1073adda4b6d79f27656993aefccf16e0d0409fe07db2dc398a1b7e8ee93b"
    "cd181485fd332f381d6a050fba4c7641a5112ac1b0b61168d20f01b479e19adf7fdbfa0905f63352"
    "bfc7e23cf3357657455119d879c78d3cf8c8c06375f3f7d4861aa02a122467e069acaf513025ff19"
    "6641f6d2810ce493f51bee9c966b15c5043505350392b57645385a18c78f14669cc4d960446c1757"
    "1b7c5d725021babbcd786957f3d17089c084907bda22c2b2675b4378b114c601d858802a55345a15"
    "116bc61da4193996187ed70d16730e9ae6b3bb8787ebcaea1871d850997ddc08b4f4ea668fbf3740"
    "7ac044b55be0908ecb94d4ed172ece66fd31bfdadf2b97a8bc690163ee11f5b575a4b44e36e2bfb2"
    "f0fce91676fd64c7773bac6a003f481fddd0bae0a1f31aa27504e2a533af4cef3b623f4791b2cca6"
    "d490")

EIP8_ACK_2 = _hx(
    "0x01ea0451958701280a56482929d3b0757da8f7fbe5286784beead59d95089c217c9b917788989470"
    "b0e330cc6e4fb383c0340ed85fab836ec9fb8a49672712aeabbdfd1e837c1ff4cace34311cd7f4de"
    "05d59279e3524ab26ef753a0095637ac88f2b499b9914b5f64e143eae548a1066e14cd2f4bd7f814"
    "c4652f11b254f8a2d0191e2f5546fae6055694aed14d906df79ad3b407d94692694e259191cde171"
    "ad542fc588fa2b7333313d82a9f887332f1dfc36cea03f831cb9a23fea05b33deb999e85489e645f"
    "6aab1872475d488d7bd6c7c120caf28dbfc5d6833888155ed69d34dbdc39c1f299be1057810f34fb"
    "e754d021bfca14dc989753d61c413d261934e1a9c67ee060a25eefb54e81a4d14baff922180c395d"
    "3f998d70f46f6b58306f969627ae364497e73fc27f6d17ae45a413d322cb8814276be6ddd13b885b"
    "201b943213656cde498fa0e9ddc8e0b8f8a53824fbd82254f3e2c17e8eaea009c38b4aa0a3f306e8"
    "797db43c25d68e86f262e564086f59a2fc60511c42abfb3057c247a8a8fe4fb3ccbadde17514b7ac"
    "8000cdb6a912778426260c47f38919a91f25f4b5ffb455d6aaaf150f7e5529c100ce62d6d92826a7"
    "1778d809bdf60232ae21ce8a437eca8223f45ac37f6487452ce626f549b3b5fdee26afd2072e4bc7"
    "5833c2464c805246155289f4")

EIP8_ACK_3 = _hx(
    "0x01f004076e58aae772bb101ab1a8e64e01ee96e64857ce82b1113817c6cdd52c09d26f7b90981cd7"
    "ae835aeac72e1573b8a0225dd56d157a010846d888dac7464baf53f2ad4e3d584531fa203658fab0"
    "3a06c9fd5e35737e417bc28c1cbf5e5dfc666de7090f69c3b29754725f84f75382891c561040ea1d"
    "dc0d8f381ed1b9d0d4ad2a0ec021421d847820d6fa0ba66eaf58175f1b235e851c7e2124069fbc20"
    "2888ddb3ac4d56bcbd1b9b7eab59e78f2e2d400905050f4a92dec1c4bdf797b3fc9b2f8e84a482f3"
    "d800386186712dae00d5c386ec9387a5e9c9a1aca5a573ca91082c7d68421f388e79127a5177d4f8"
    "590237364fd348c9611fa39f78dcdceee3f390f07991b7b47e1daa3ebcb6ccc9607811cb17ce51f1"
    "c8c2c5098dbdd28fca547b3f58c01a424ac05f869f49c6a34672ea2cbbc558428aa1fe48bbfd6115"
    "8b1b735a65d99f21e70dbc020bfdface9f724a0d1fb5895db971cc81aa7608baa0920abb0a565c9c"
    "436e2fd13323428296c86385f2384e408a31e104670df0791d93e743a3a5194ee6b076fb6323ca59"
    "3011b7348c16cf58f66b9633906ba54a2ee803187344b394f75dd2e663a57b956cb830dd7a908d4f"
    "39a2336a61ef9fda549180d4ccde21514d117b6c6fd07a9102b5efe710a32af4eeacae2cb3b1dec0"
    "35b9593b48b9d3ca4c13d245d5f04169b0b1")


def test_eip8_auth_vectors_decode():
    """The EIP-8 spec's auth messages (versions 4 and 56, with and without
    extra list elements) must decode against the spec's server key."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    from reth_tpu.net.ecies import Handshake

    for raw in (EIP8_AUTH_2, EIP8_AUTH_3):
        h = Handshake(EIP8_SERVER_KEY, eph_priv=EIP8_SERVER_EPH,
                      nonce=EIP8_SERVER_NONCE)
        ack, secrets = h.on_auth(raw)
        assert secrets is not None and len(ack) > 2


def test_eip8_ack_vectors_decode():
    """The EIP-8 spec's ack messages must decode against the spec's client
    key after the client sends its auth."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    from reth_tpu.net.ecies import Handshake, pubkey_from_priv

    server_pub = pubkey_from_priv(EIP8_SERVER_KEY)
    for raw in (EIP8_ACK_2, EIP8_ACK_3):
        h = Handshake(EIP8_CLIENT_KEY, eph_priv=EIP8_CLIENT_EPH,
                      nonce=EIP8_CLIENT_NONCE)
        h.auth(server_pub)
        secrets = h.finalize_initiator(raw)
        assert secrets is not None


def test_eip8_fixed_key_loopback():
    """Full handshake with the EIP-8 fixed keys: both sides derive the
    SAME frame secrets (MAC/AES seeds agree)."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    from reth_tpu.net.ecies import Handshake, pubkey_from_priv

    client = Handshake(EIP8_CLIENT_KEY, eph_priv=EIP8_CLIENT_EPH,
                       nonce=EIP8_CLIENT_NONCE)
    server = Handshake(EIP8_SERVER_KEY, eph_priv=EIP8_SERVER_EPH,
                       nonce=EIP8_SERVER_NONCE)
    auth = client.auth(pubkey_from_priv(EIP8_SERVER_KEY))
    ack, s_secrets = server.on_auth(auth)
    c_secrets = client.finalize_initiator(ack)
    assert c_secrets.aes == s_secrets.aes
    assert c_secrets.mac == s_secrets.mac


# -- EIP-152 blake2f precompile vectors --------------------------------------


def test_blake2f_eip152_official_vectors():
    """The EIP-152 specification's own test vectors (4-7) against the
    0x09 precompile: external ground truth for the blake2 compression
    implementation (primitives/blake2.py)."""
    from reth_tpu.evm.interpreter import _precompile

    blake2f = _precompile(b"\x00" * 19 + b"\x09")
    state = bytes.fromhex(
        "48c9bdf267e6096a3ba7ca8485ae67bb2bf894fe72f36e3cf1361d5f3af54fa5"
        "d182e6ad7f520e511f6c3e2b8c68059b6bbd41fbabd9831f79217e1319cde05b"
        "6162630000000000000000000000000000000000000000000000000000000000"
        + "00" * 96 + "0300000000000000" + "0000000000000000")
    # vector 5: rounds=12, final=1 — blake2b("abc") state
    ok, _, out = blake2f(bytes.fromhex("0000000c") + state + b"\x01", 10**5)
    assert ok and out.hex() == (
        "ba80a53f981c4d0d6a2797b69f12f6e94c212f14685ac4b74b12bb6fdbffa2d1"
        "7d87c5392aab792dc252d5de4533cc9518d38aa8dbf1925ab92386edd4009923")
    # vector 6: rounds=12, final=0
    ok, _, out = blake2f(bytes.fromhex("0000000c") + state + b"\x00", 10**5)
    assert ok and out.hex() == (
        "75ab69d3190a562c51aef8d88f1c2775876944407270c42c9844252c26d28752"
        "98743e7f6d5ea2f2d3e8d226039cd31b4e426ac4f2d3d666a610c2116fde4735")
    # vector 7: rounds=1, final=1
    ok, _, out = blake2f(bytes.fromhex("00000001") + state + b"\x01", 10**5)
    assert ok and out.hex() == (
        "b63a380cb2897d521994a85234ee2c181b5f844d2c624c002677e9703449d2fb"
        "a551b3a8333bcdf5f2f7e08993d53923de3d64fcc68c034e717b9293fed7a421")
    # vector 4: malformed final-block flag (2) must ERROR (EIP-152): a
    # successful-but-empty return would be a consensus divergence
    ok, _, out = blake2f(bytes.fromhex("0000000c") + state + b"\x02", 10**5)
    assert not ok


# -- secp256k1 cross-validation against the `cryptography` library -----------


def test_secp256k1_cross_validates_with_openssl():
    """The from-scratch secp256k1 (primitives/secp256k1.py) against the
    in-image `cryptography` package (OpenSSL-backed): our signatures
    verify under their ECDSA, and their signatures recover to the right
    address under our ecrecover — 32 random messages each way."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    import os

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed,
        decode_dss_signature,
        encode_dss_signature,
    )

    from reth_tpu.primitives import secp256k1

    rng_priv = [0xA11CE, 0xB0B, 2, secp256k1.N - 2]
    for priv in rng_priv:
        pub = secp256k1.pubkey_from_priv(priv)
        pub_c = ec.EllipticCurvePublicNumbers(
            pub[0], pub[1], ec.SECP256K1()).public_key()
        sk = ec.derive_private_key(priv, ec.SECP256K1())
        addr = secp256k1.address_from_priv(priv)
        for _ in range(8):
            h = os.urandom(32)
            # ours -> theirs
            _y, r, s = secp256k1.sign(h, priv)
            pub_c.verify(encode_dss_signature(r, s), h,
                         ec.ECDSA(Prehashed(hashes.SHA256())))
            # theirs -> ours (try both parities; high-s allowed: OpenSSL
            # does not canonicalize to low-s)
            r2, s2 = decode_dss_signature(
                sk.sign(h, ec.ECDSA(Prehashed(hashes.SHA256()))))
            recovered = []
            for yp in (0, 1):
                try:
                    recovered.append(
                        secp256k1.ecrecover(h, yp, r2, s2, allow_high_s=True))
                except ValueError:
                    continue
            assert addr in recovered
