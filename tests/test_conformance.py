"""Conformance suite: 320 generated BlockchainTests cases through the
runner (full pipeline replay: decode RLP -> execute -> rebuild roots),
including the round-4 adversarial families (gas edges, CREATE2
collisions, 7702 delegation chains, 4844 blob accounting, nested-revert
journaling). External ground-truth vectors live in
tests/test_external_vectors.py.

Reference analogue: testing/ef-tests/tests/tests.rs per-suite macros.
"""

from __future__ import annotations

import json

import pytest

from reth_tpu.conformance import ConformanceFailure, run_blockchain_test
from reth_tpu.conformance.generate import SCENARIOS, load_or_generate_suite
from reth_tpu.conformance.runner import run_fixture_file

_PER_SCENARIO = 20


@pytest.fixture(scope="module")
def suite():
    # cached on disk keyed by the generator's source hash — regeneration
    # costs minutes of EVM execution for what is deterministic input
    # data; the replay below is the actual conformance check
    return load_or_generate_suite(_PER_SCENARIO)


def test_suite_size(suite):
    assert len(suite) >= 300


@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_scenario_cases_pass(suite, scenario):
    ran = 0
    for name, case in suite.items():
        if name.startswith(f"{scenario}_"):
            run_blockchain_test(name, case)
            ran += 1
    assert ran == _PER_SCENARIO


def test_corrupted_post_state_fails(suite):
    case = json.loads(json.dumps(suite["transfers_Paris_0"]))  # deep copy
    addr = next(iter(case["postState"]))
    case["postState"][addr]["balance"] = "0xdeadbeef"
    with pytest.raises(ConformanceFailure, match="balance"):
        run_blockchain_test("corrupted", case)


def test_corrupted_block_rlp_fails(suite):
    case = json.loads(json.dumps(suite["storage_Shanghai_0"]))
    blk = bytearray(bytes.fromhex(case["blocks"][0]["rlp"][2:]))
    blk[-1] ^= 0xFF  # flip a byte in the last tx
    case["blocks"][0]["rlp"] = "0x" + blk.hex()
    with pytest.raises(ConformanceFailure):
        run_blockchain_test("corrupted-rlp", case)


def test_expect_exception_honored(suite):
    """A block marked expectException must be rejected, and acceptance is a
    failure: reuse a valid block at the wrong height."""
    case = json.loads(json.dumps(suite["transfers_Paris_0"]))
    good = case["blocks"][0]
    # re-importing the same height must be rejected -> expectException OK
    case["blocks"] = [good, {**good, "expectException": "InvalidBlock"}]
    run_blockchain_test("expect-exc", case)

    case2 = json.loads(json.dumps(suite["transfers_Paris_0"]))
    case2["blocks"] = [{**case2["blocks"][0], "expectException": "InvalidBlock"}]
    del case2["postState"]
    with pytest.raises(ConformanceFailure, match="accepted"):
        run_blockchain_test("expect-exc-bad", case2)


def test_fixture_file_roundtrip(tmp_path, suite):
    path = tmp_path / "suite.json"
    small = {k: suite[k] for k in list(suite)[:3]}
    path.write_text(json.dumps(small))
    assert len(run_fixture_file(str(path))) == 3


def test_fixture_shape_is_ef_compatible(suite):
    """The JSON shape matches what the official corpus uses, so real
    ethereum/tests fixtures drop into the same runner."""
    case = suite["storage_Shanghai_0"]
    assert {"pre", "genesisBlockHeader", "blocks", "postState",
            "lastblockhash", "network"} <= set(case)
    gh = case["genesisBlockHeader"]
    for k in ("parentHash", "stateRoot", "transactionsTrie", "receiptTrie",
              "bloom", "gasLimit", "coinbase", "baseFeePerGas"):
        assert k in gh
    assert all("rlp" in b for b in case["blocks"])
