"""Engine tree tests: newPayload/FCU flow, reorgs, persistence.

Reference analogue: the engine-tree integration tests
(crates/engine/tree/src/tree/tests.rs) — synthetic payloads driven
through the handler, tree state asserted.
"""

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.tree import PayloadStatusKind
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.primitives.types import Block, Header
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def make_env(n_blocks=5):
    alice = Wallet(0xA11CE)
    bob = Wallet(0xB0B)
    builder = ChainBuilder(
        {alice.address: Account(balance=10**21), bob.address: Account(balance=10**20)},
        committer=CPU,
    )
    for i in range(n_blocks):
        builder.build_block([alice.transfer(bob.address, 10**15 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    return builder, factory, tree, alice, bob


def test_new_payload_chain_valid():
    builder, factory, tree, *_ = make_env()
    for blk in builder.blocks[1:]:
        st = tree.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
    assert len(tree.blocks) == 5


def test_fcu_advances_and_persists():
    builder, factory, tree, *_ = make_env()
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
        st = tree.on_forkchoice_updated(blk.hash)
        assert st.status is PayloadStatusKind.VALID
    # threshold 2: blocks 1..3 persisted, 4..5 in memory
    assert tree.persisted_number == 3
    p = factory.provider()
    assert p.last_block_number() == 3
    assert p.header_by_number(3).state_root == builder.blocks[3].header.state_root
    assert p.stage_checkpoint("Finish") == 3
    # overlay view still sees the in-memory tip
    ov = tree.overlay_provider()
    assert ov.last_block_number() == 5
    assert ov.header_by_number(5).hash == builder.blocks[5].hash


def test_unknown_parent_is_syncing():
    builder, factory, tree, *_ = make_env(2)
    st = tree.on_new_payload(builder.blocks[2])  # parent (block 1) not sent
    assert st.status is PayloadStatusKind.SYNCING


def test_invalid_state_root_rejected_and_descendants():
    builder, factory, tree, *_ = make_env(2)
    blk1 = builder.blocks[1]
    bad_header = Header(**{**blk1.header.__dict__, "state_root": b"\x13" * 32})
    bad = Block(bad_header, blk1.transactions, (), blk1.withdrawals)
    st = tree.on_new_payload(bad)
    assert st.status is PayloadStatusKind.INVALID
    assert "state root mismatch" in st.validation_error
    # a child of the invalid block is rejected as invalid ancestor
    child_header = Header(**{**builder.blocks[2].header.__dict__, "parent_hash": bad.hash})
    child = Block(child_header, builder.blocks[2].transactions, (), builder.blocks[2].withdrawals)
    st2 = tree.on_new_payload(child)
    assert st2.status is PayloadStatusKind.INVALID
    # FCU to the invalid head also reports invalid
    assert tree.on_forkchoice_updated(bad.hash).status is PayloadStatusKind.INVALID


def test_reorg_between_forks():
    """Two competing blocks at the same height; FCU flips between them."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)

    # fork A: transfer 111; fork B (different timestamp): transfer 222
    fork_a = builder.build_block([alice.transfer(b"\xaa" * 20, 111)])
    # rebuild from genesis for fork B
    alice_b = Wallet(0xA11CE)
    builder_b = ChainBuilder({alice_b.address: Account(balance=10**21)}, committer=CPU)
    fork_b = builder_b.build_block([alice_b.transfer(b"\xbb" * 20, 222)], timestamp=24)

    assert tree.on_new_payload(fork_a).status is PayloadStatusKind.VALID
    assert tree.on_new_payload(fork_b).status is PayloadStatusKind.VALID
    assert tree.on_forkchoice_updated(fork_a.hash).status is PayloadStatusKind.VALID
    assert tree.overlay_provider().account(b"\xaa" * 20).balance == 111
    assert tree.overlay_provider().account(b"\xbb" * 20) is None
    # reorg to fork B
    assert tree.on_forkchoice_updated(fork_b.hash).status is PayloadStatusKind.VALID
    assert tree.overlay_provider().account(b"\xbb" * 20).balance == 222
    assert tree.overlay_provider().account(b"\xaa" * 20) is None


def test_replay_persisted_block_is_valid():
    builder, factory, tree, *_ = make_env()
    for blk in builder.blocks[1:]:
        tree.on_new_payload(blk)
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 3
    # CL re-sends an already-persisted payload after a restart
    st = tree.on_new_payload(builder.blocks[2])
    assert st.status is PayloadStatusKind.VALID


def test_overlay_provider_unknown_head_raises():
    builder, factory, tree, *_ = make_env(1)
    with pytest.raises(KeyError):
        tree.overlay_provider(b"\x77" * 32)


def test_deep_reorg_unwinds_persisted_chain():
    """A fork branching below the persisted tip triggers a pipeline unwind."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(4):
        builder.build_block([alice.transfer(b"\xaa" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=1)
    for blk in builder.blocks[1:]:
        assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
        tree.on_forkchoice_updated(blk.hash)
    assert tree.persisted_number == 3  # blocks 1..3 persisted, 4 in memory

    # competing fork branching at block 2 (persisted, below the tip)
    alice_b = Wallet(0xA11CE)
    builder_b = ChainBuilder({alice_b.address: Account(balance=10**21)}, committer=CPU)
    for i in range(2):
        builder_b.build_block([alice_b.transfer(b"\xaa" * 20, 100 + i)])
    fork3 = builder_b.build_block([alice_b.transfer(b"\xbb" * 20, 999)], timestamp=100)
    assert fork3.header.parent_hash == builder.blocks[2].hash  # same prefix
    st = tree.on_new_payload(fork3)
    assert st.status is PayloadStatusKind.SYNCING  # buffered: parent below tip
    st = tree.on_forkchoice_updated(fork3.hash)
    assert st.status is PayloadStatusKind.VALID, st.validation_error
    p = tree.overlay_provider()
    assert p.account(b"\xbb" * 20).balance == 999
    assert p.account(b"\xaa" * 20).balance == 100 + 101  # only blocks 1-2


def test_canon_notifications():
    builder, factory, tree, *_ = make_env(2)
    seen = []
    tree.canon_listeners.append(lambda chain: seen.append([b.number for b in chain]))
    tree.on_new_payload(builder.blocks[1])
    tree.on_forkchoice_updated(builder.blocks[1].hash)
    tree.on_new_payload(builder.blocks[2])
    tree.on_forkchoice_updated(builder.blocks[2].hash)
    assert seen == [[1], [1, 2]]
