"""Networking tests: wire codecs + in-process testnet sync over real TCP.

Reference analogue: the in-process `Testnet` fixture
(crates/net/network/src/test_utils/testnet.rs:57) — full sessions over
localhost, no external infra.
"""

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.net import NetworkManager, PeerConnection, Status, sync_from_peer
from reth_tpu.net.rlpx import node_id
from reth_tpu.primitives.secp256k1 import pubkey_from_priv
from reth_tpu.net import wire
from reth_tpu.net.p2p import PeerError
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def test_wire_roundtrips():
    h = ChainBuilder({}, committer=CPU).genesis
    msgs = [
        Status(68, 1, 0, b"\x01" * 32, b"\x02" * 32, (b"\xaa\xbb\xcc\xdd", 0)),
        wire.GetBlockHeaders(7, 100, 10, 0, True),
        wire.GetBlockHeaders(8, b"\x03" * 32, 1),
        wire.BlockHeaders(7, [h]),
        wire.GetBlockBodies(9, [b"\x04" * 32]),
        wire.BlockBodies(9, [wire.BlockBody((), (), ())]),
        wire.GetReceipts(1, [b"\x05" * 32]),
        wire.ReceiptsMsg(1, [[b"rc1", b"rc2"], []]),
        wire.NewPooledTxHashes(b"\x02", [120], [b"\x06" * 32]),
        wire.NewBlockHashes([(b"\x07" * 32, 5)]),
    ]
    for m in msgs:
        frame = wire.encode_message(m)
        got = wire.decode_message(frame[4:])
        assert got == m, type(m).__name__


def make_synced_node(n_blocks=8):
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    for i in range(n_blocks):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(n_blocks)
    return factory, builder


@pytest.fixture()
def testnet():
    """A serving node + a fresh node sharing genesis, over localhost TCP."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    factory_a, builder = make_synced_node()
    status = Status(network_id=1, head=builder.tip.hash, genesis=builder.genesis.hash)
    server = NetworkManager(factory_a, status, node_priv=0xA11CE5)
    port = server.start()

    factory_b = ProviderFactory(MemDb())
    init_genesis(factory_b, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    yield server, port, status, factory_b, builder
    server.stop()


def test_handshake_and_header_requests(testnet):
    server, port, status, factory_b, builder = testnet
    peer = PeerConnection.connect("127.0.0.1", port, status,
                                  pubkey_from_priv(server.node_priv))
    assert peer.status.head == builder.tip.hash
    headers = peer.get_headers(1, 5)
    assert [h.number for h in headers] == [1, 2, 3, 4, 5]
    assert headers[0].hash == builder.blocks[1].hash
    # by-hash + reverse
    rev = peer.get_headers(builder.blocks[4].hash, 3, reverse=True)
    assert [h.number for h in rev] == [4, 3, 2]
    bodies = peer.get_bodies([builder.blocks[2].hash])
    assert len(bodies) == 1 and len(bodies[0].transactions) == 1
    receipts = peer.get_receipts([builder.blocks[2].hash])
    assert len(receipts) == 1 and len(receipts[0]) == 1
    peer.close()


def test_genesis_mismatch_rejected(testnet):
    server, port, status, *_ = testnet
    bad = Status(network_id=1, genesis=b"\x66" * 32)
    with pytest.raises(PeerError):
        PeerConnection.connect("127.0.0.1", port, bad,
                               pubkey_from_priv(server.node_priv))


def test_full_sync_from_peer(testnet):
    """The headline networking flow: a fresh node syncs over TCP and
    reproduces the exact state roots."""
    server, port, status, factory_b, builder = testnet
    our_status = Status(network_id=1, head=builder.genesis.hash,
                        genesis=builder.genesis.hash)
    peer = PeerConnection.connect("127.0.0.1", port, our_status,
                                  pubkey_from_priv(server.node_priv))
    pipeline = Pipeline(factory_b, default_stages(committer=CPU))
    tip = sync_from_peer(factory_b, peer, pipeline, EthBeaconConsensus(CPU))
    assert tip == 8
    p = factory_b.provider()
    assert p.stage_checkpoint("Finish") == 8
    assert p.header_by_number(8).state_root == builder.tip.state_root
    assert p.account(b"\x0b" * 20).balance == sum(100 + i for i in range(8))
    # idempotent: second sync is a no-op
    assert sync_from_peer(factory_b, peer, pipeline) == 8
    peer.close()


def test_tx_broadcast_into_pool(testnet):
    from reth_tpu.engine import EngineTree
    from reth_tpu.pool import TransactionPool

    server, port, status, factory_b, builder = testnet
    # hang a pool off the SERVER and gossip a tx to it
    tree = EngineTree(server.factory, committer=CPU)
    pool = TransactionPool(lambda: tree.overlay_provider())
    pool.base_fee = 10**9
    server.pool = pool
    alice = Wallet(0xA11CE)
    alice.nonce = 8  # after 8 mined txs
    tx = alice.transfer(b"\x0c" * 20, 5)
    peer = PeerConnection.connect("127.0.0.1", port, status,
                                  pubkey_from_priv(server.node_priv))
    peer.send(wire.TransactionsMsg([tx]))
    import time

    for _ in range(100):
        if pool.contains(tx.hash):
            break
        time.sleep(0.05)
    assert pool.contains(tx.hash)
    peer.close()


def test_enode_dial_and_discovery_assisted_sync(testnet):
    """Dial by enode URL (discv4-style identity) and sync over the
    encrypted session — the discovery -> RLPx -> eth/68 pipeline."""
    import time

    from reth_tpu.net.discv4 import Discv4

    server, port, status, factory_b, builder = testnet
    # discovery: server advertises; a fresh node bootstraps off it
    d_server = Discv4(server.node_priv, tcp_port=port)
    d_server.start()
    client_net = NetworkManager(factory_b, Status(
        network_id=1, head=builder.genesis.hash, genesis=builder.genesis.hash))
    d_client = Discv4(client_net.node_priv)
    d_client.start()
    try:
        d_client.bootstrap([d_server.enode()])
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            rec = d_client.table.by_id.get(d_server.node_id)
            if rec is not None and rec.bonded:
                break
            time.sleep(0.05)
        rec = d_client.table.by_id[d_server.node_id]
        assert rec.bonded, "bonding with the bootnode failed"
        # the discovered record's enode is directly dialable over RLPx
        peer = client_net.connect_to(rec.enode())
        assert peer.session.snappy_enabled
        pipeline = Pipeline(factory_b, default_stages(committer=CPU))
        tip = sync_from_peer(factory_b, peer, pipeline, EthBeaconConsensus(CPU))
        assert tip == 8
        with factory_b.provider() as p:
            assert p.header_by_number(8).state_root == builder.tip.state_root
        peer.close()
    finally:
        d_server.stop()
        d_client.stop()


def test_online_pipeline_sync(testnet):
    """Headers/Bodies as PIPELINE stages (reference OnlineStages): a fresh
    node syncs purely through the staged pipeline pulling from the peer."""
    server, port, status, factory_b, builder = testnet
    our_status = Status(network_id=1, head=builder.genesis.hash,
                        genesis=builder.genesis.hash)
    peer = PeerConnection.connect("127.0.0.1", port, our_status,
                                  pubkey_from_priv(server.node_priv))
    tip = sync_from_peer(factory_b, peer, committer=CPU)  # no pipeline arg
    assert tip == 8
    with factory_b.provider() as p:
        assert p.stage_checkpoint("Headers") == 8
        assert p.stage_checkpoint("Bodies") == 8
        assert p.stage_checkpoint("Finish") == 8
        assert p.header_by_number(8).state_root == builder.tip.state_root
    # unwind through the online set (reverse order incl. Bodies/Headers),
    # then resync from the same peer
    from reth_tpu.stages import Pipeline, online_stages

    pipeline = Pipeline(factory_b, online_stages(peer, committer=CPU))
    pipeline.unwind(6)
    with factory_b.provider() as p:
        assert p.stage_checkpoint("Headers") == 6
        assert p.header_by_number(8) is None
        assert p.canonical_hash(7) is None
    pipeline.run(8)
    with factory_b.provider() as p:
        assert p.header_by_number(8).state_root == builder.tip.state_root
    peer.close()


def test_fork_id_filter_rejects_incompatible_peer(testnet):
    """EIP-2124: a peer whose fork history diverges is dropped during the
    Status handshake, even with matching genesis + network id."""
    from reth_tpu.chainspec import MAINNET, dev_spec

    server, port, status, factory_b, builder = testnet
    server.chain_spec = dev_spec(chain_id=1, genesis_hash=builder.genesis.hash)
    server.head_position = (8, builder.tip.timestamp)
    ok_fid = server.chain_spec.fork_id(8, builder.tip.timestamp)

    good = Status(network_id=1, head=builder.genesis.hash,
                  genesis=builder.genesis.hash, fork_id=ok_fid)
    peer = PeerConnection.connect("127.0.0.1", port, good,
                                  pubkey_from_priv(server.node_priv))
    peer.close()

    # a mainnet-history fork hash against a dev-spec server: incompatible.
    # The server sends its Status before validating ours, so the dial
    # itself may succeed — the session is dead by the first request.
    bad = Status(network_id=1, head=builder.genesis.hash,
                 genesis=builder.genesis.hash,
                 fork_id=(bytes.fromhex("668db0af"), 0))
    with pytest.raises((PeerError, OSError)):
        p = PeerConnection.connect("127.0.0.1", port, bad,
                                   pubkey_from_priv(server.node_priv))
        p.get_headers(1, 1)

    # client-side filter: dialing a peer with an incompatible fork id fails
    with pytest.raises(PeerError):
        PeerConnection.connect(
            "127.0.0.1", port, bad, pubkey_from_priv(server.node_priv),
            fork_filter=lambda fid: MAINNET.validate_fork_id(fid, 7_987_396))


def test_eth69_negotiation_and_block_range(testnet):
    """Both sides advertise eth/68+69: the session negotiates 69, the
    Status travels in the TD-less v69 shape, and BlockRangeUpdate gossip
    lands on the peer object."""
    server, port, status, factory_b, builder = testnet
    import dataclasses

    st69 = dataclasses.replace(status, earliest=0, latest=8)
    peer = PeerConnection.connect("127.0.0.1", port, st69,
                                  pubkey_from_priv(server.node_priv))
    assert peer.eth_version == 69
    assert peer.status.version == 69
    assert peer.snap_enabled and peer.snap_offset == 0x10 + 18
    # requests still work over the renumbered snap space
    assert [h.number for h in peer.get_headers(1, 3)] == [1, 2, 3]
    # range gossip: server records it on its side of the session
    import time as _t

    peer.send(wire.BlockRangeUpdate(0, 8, builder.tip.hash))
    deadline = _t.monotonic() + 5
    server_peer = None
    while _t.monotonic() < deadline:
        if server.peers and server.peers[-1].block_range:
            server_peer = server.peers[-1]
            break
        _t.sleep(0.05)
    assert server_peer is not None
    assert server_peer.block_range == (0, 8, builder.tip.hash)
    peer.close()


def test_status_v69_codec_roundtrip():
    st = Status(version=69, network_id=7, genesis=b"\x09" * 32,
                head=b"\x08" * 32, fork_id=(b"\xaa\xbb\xcc\xdd", 123),
                earliest=4, latest=99)
    frame = wire.encode_message(st)
    got = wire.decode_message(frame[4:])
    assert got == st
    bru = wire.BlockRangeUpdate(1, 2, b"\x03" * 32)
    frame = wire.encode_message(bru)
    assert wire.decode_message(frame[4:]) == bru


def test_online_sync_with_two_peers(testnet):
    """Testnet sync where the body windows are served by TWO live peer
    connections concurrently (reference concurrent bodies downloader)."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    server, port, status, factory_b, builder = testnet
    our_status = Status(network_id=1, head=builder.genesis.hash,
                        genesis=builder.genesis.hash)
    peer1 = PeerConnection.connect("127.0.0.1", port, our_status,
                                   pubkey_from_priv(server.node_priv))
    peer2 = PeerConnection.connect("127.0.0.1", port, our_status,
                                   pubkey_from_priv(server.node_priv))
    tip = sync_from_peer(factory_b, peer1, committer=CPU,
                         extra_peers=(peer2,))
    assert tip == 8
    p = factory_b.provider()
    assert p.stage_checkpoint("Finish") == 8
    assert p.header_by_number(8).state_root == builder.tip.state_root
    peer1.close()
    peer2.close()


def test_session_manager_caps_and_events(testnet):
    """Session lifecycle over real connections: caps enforced BEFORE the
    handshake, events published on establish/close, counters tracked
    (reference SessionManager in the Swarm)."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    server, port, status, factory_b, builder = testnet
    server.sessions.max_inbound = 2
    events = []
    server.sessions.listeners.append(
        lambda ev, s: events.append((ev, s.direction)))
    our_status = Status(network_id=1, head=builder.genesis.hash,
                        genesis=builder.genesis.hash)

    p1 = PeerConnection.connect("127.0.0.1", port, our_status,
                                pubkey_from_priv(server.node_priv))
    p2 = PeerConnection.connect("127.0.0.1", port, our_status,
                                pubkey_from_priv(server.node_priv))
    import time

    deadline = time.time() + 5
    while len(server.sessions.active("inbound")) < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert len(server.sessions.active("inbound")) == 2
    assert ("established", "inbound") in events
    # third connection: refused at the cap, before any handshake
    with pytest.raises((PeerError, OSError)):
        PeerConnection.connect("127.0.0.1", port, our_status,
                               pubkey_from_priv(server.node_priv), timeout=3)
    assert len(server.sessions.active("inbound")) == 2
    # activity is counted per session
    p1.get_headers(1, 2)
    assert sum(s.messages_in for s in server.sessions.active()) >= 1
    # closure publishes an event and frees capacity
    p1.close()
    deadline = time.time() + 5
    while len(server.sessions.active("inbound")) != 1 and time.time() < deadline:
        time.sleep(0.05)
    assert len(server.sessions.active("inbound")) == 1
    assert ("closed", "inbound") in events
    counts = server.sessions.counts()
    assert counts["established_total"] >= 2 and counts["closed_total"] >= 1
    p3 = PeerConnection.connect("127.0.0.1", port, our_status,
                                pubkey_from_priv(server.node_priv))
    p3.close()
    p2.close()


def test_outbound_session_released_on_close(testnet):
    """Regression (round-4 review): closing an outbound connection must
    release its session slot or the outbound cap leaks permanently."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    server, port, status, factory_b, builder = testnet
    from reth_tpu.net.server import NetworkManager
    from reth_tpu.storage import MemDb, ProviderFactory

    dialer = NetworkManager(ProviderFactory(MemDb()),
                            Status(network_id=1, head=builder.genesis.hash,
                                   genesis=builder.genesis.hash),
                            max_outbound=2)
    for _ in range(5):  # reconnect loop: would exhaust the cap if leaked
        p = dialer.connect_to(server.enode)
        assert len(dialer.sessions.active("outbound")) == 1
        p.close()
        assert len(dialer.sessions.active("outbound")) == 0
    assert dialer.sessions.counts()["closed_total"] >= 5


def test_node_serves_in_memory_tip_over_p2p(tmp_path):
    """A LAUNCHED node advertises its live head in the handshake Status
    and serves tree blocks above the persistence threshold — a fresh peer
    syncs to the full tip, not just the persisted chain (round-4 fix)."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    import time

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.storage.genesis import init_genesis

    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    cfg = NodeConfig(dev=True, datadir=tmp_path,
                     genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis,
                     persistence_threshold=2, p2p_port=0, discovery=False)
    node = Node(cfg, committer=CPU)
    node.start_network()
    try:
        for i in range(6):
            node.pool.add_transaction(alice.transfer(b"\x0b" * 20, 50 + i))
            node.miner.mine_block()
        assert node.tree.persisted_number == 4  # 5,6 in memory only
        assert node.network.status.head == node.tree.head_hash

        factory_b = ProviderFactory(MemDb())
        init_genesis(factory_b, builder.genesis,
                     builder.accounts_at_genesis, committer=CPU)
        from reth_tpu.net.server import NetworkManager as NM

        dialer = NM(factory_b, Status(network_id=1,
                                      head=builder.genesis.hash,
                                      genesis=builder.genesis.hash))
        peer = dialer.connect_to(node.network.enode)
        tip = sync_from_peer(factory_b, peer, committer=CPU)
        assert tip == 6
        with factory_b.provider() as p:
            assert p.header_by_number(6).hash == node.tree.head_hash
        peer.close()
    finally:
        node.stop()


def test_swarm_soak_flat_thread_count(testnet):
    """Round-5 event-loop network core (reference src/swarm.rs): 30
    concurrent inbound sessions are served by ONE loop thread — the
    steady-state thread count must not grow with the peer count, and
    every peer must still get served."""
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    import threading
    import time

    server, port, status, _factory_b, builder = testnet
    peers = []
    try:
        from reth_tpu.primitives.secp256k1 import pubkey_from_priv

        for i in range(30):
            peers.append(PeerConnection.connect(
                "127.0.0.1", port, status,
                pubkey_from_priv(server.node_priv),
                node_priv=0xB000 + i))
        # wait until all handshake threads have finished and the swarm
        # has adopted every session
        deadline = time.time() + 10
        while time.time() < deadline and len(server.peers) < 30:
            time.sleep(0.05)
        assert len(server.peers) == 30
        baseline = threading.active_count()
        # every peer served through the single loop
        for p in peers:
            headers = p.get_headers(0, 2)
            assert headers and headers[0].hash == builder.genesis.hash
        # more traffic must not spawn serving threads
        for p in peers:
            assert p.get_headers(1, 1)
        assert threading.active_count() <= baseline
        assert server.swarm._thread.is_alive()
    finally:
        for p in peers:
            p.close()
