"""Chaos drill engine: crash points, composed fault scenarios, invariants.

Fast tests cover the crash-point framework (spec parsing, nth counting,
real ``os._exit`` in a throwaway subprocess) and both scenario
generators' determinism. The ``@slow`` drills are the real thing:
subprocess dev nodes killed at every declared crash point (plus raw
SIGKILL) under composed ``RETH_TPU_FAULT_*`` injectors, restarted, and
held to the invariant suite — plus the Engine-API consensus domain:
seeded reorg storms (``child_consensus_victim``) verified live against
a fault-free ForkBuilder twin and then through the same restart suite.
``make test-chaos`` runs them all; tier-1 keeps its budget via
``-m 'not slow'``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from reth_tpu.chaos import (
    CRASH_POINTS,
    FAULT_MENU,
    HOTSTATE_FAULTS,
    crash_spec,
    make_consensus_scenario,
    make_scenario,
    run_scenario,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(extra=None):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("RETH_TPU_FAULT_")}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra or {})
    return env


# -- crash-point framework ----------------------------------------------------


def test_crash_spec_parsing(monkeypatch):
    monkeypatch.delenv("RETH_TPU_FAULT_CRASH_AT", raising=False)
    assert crash_spec() is None
    monkeypatch.setenv("RETH_TPU_FAULT_CRASH_AT", "wal-append")
    assert crash_spec() == ("wal-append", 1)
    monkeypatch.setenv("RETH_TPU_FAULT_CRASH_AT", "checkpoint-swap:4")
    assert crash_spec() == ("checkpoint-swap", 4)
    monkeypatch.setenv("RETH_TPU_FAULT_CRASH_AT", "unwind:bogus")
    assert crash_spec() == ("unwind", 1)


def test_crash_point_fires_on_nth_hit_subprocess():
    """crash_point really dies with os._exit(137) — and only on the nth
    visit. A throwaway interpreter, no node stack needed."""
    code = (
        "from reth_tpu.chaos import crash_point\n"
        "crash_point('wal-append')\n"   # hit 1: survives
        "print('alive')\n"
        "crash_point('wal-append')\n"   # hit 2: dies
        "print('unreachable')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=_env({"RETH_TPU_FAULT_CRASH_AT": "wal-append:2"}),
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 137
    assert "alive" in r.stdout
    assert "unreachable" not in r.stdout


def test_crash_point_ignores_other_points(monkeypatch):
    from reth_tpu import chaos

    monkeypatch.setenv("RETH_TPU_FAULT_CRASH_AT", "jar-rename")
    chaos.reset_crash_counts()
    chaos.crash_point("wal-append")  # different point: must not exit
    chaos.reset_crash_counts()


def test_declared_points_are_wired():
    """Every declared crash point has a live call site — a renamed point
    silently never firing would rot the drill matrix."""
    import reth_tpu.chaos  # noqa: F401 - CRASH_POINTS source

    wired = set()
    for rel in ("reth_tpu/storage/wal.py", "reth_tpu/storage/nippyjar.py",
                "reth_tpu/engine/tree.py"):
        src = open(os.path.join(REPO, rel)).read()
        for p in CRASH_POINTS:
            if f'crash_point("{p}")' in src:
                wired.add(p)
    assert wired == set(CRASH_POINTS)


# -- scenario generator -------------------------------------------------------


def test_make_scenario_deterministic_and_diverse():
    a, b = make_scenario(42), make_scenario(42)
    assert a == b
    scns = [make_scenario(s) for s in range(1, 40)]
    modes = {s["mode"] for s in scns}
    assert modes == {"point", "kill"}
    points = {s.get("point") for s in scns if s["mode"] == "point"}
    assert points >= set(CRASH_POINTS) - {None}
    known = set().union(*[set(f) for f in FAULT_MENU])
    for s in scns:
        assert s["faults"] and set(s["faults"]) <= known
        assert s["blocks"] >= s.get("kill_after", 0)


def test_make_consensus_scenario_deterministic_and_diverse():
    a, b = make_consensus_scenario(7), make_consensus_scenario(7)
    assert a == b
    scns = [make_consensus_scenario(s) for s in range(1, 60)]
    assert {s["mode"] for s in scns} == {"complete", "kill", "point"}
    known = set().union(*[set(f) for f in FAULT_MENU], HOTSTATE_FAULTS)
    for s in scns:
        assert s["domain"] == "consensus"
        assert s["faults"] and set(s["faults"]) <= known
        assert s["rounds"] > 0
        # hot-state injectors only land on cached seeds
        if not s.get("hot_state"):
            assert not (set(s["faults"]) & set(HOTSTATE_FAULTS))
    assert any(s.get("hot_state") for s in scns)
    assert any(set(s["faults"]) & set(HOTSTATE_FAULTS) for s in scns)
    # unwind crash points must come with a forced deep reorg (the point
    # only fires inside a persisted-chain unwind)
    for s in scns:
        if s.get("point") == "unwind":
            assert s["force_deep_reorg"]
    assert any(s["force_deep_reorg"] for s in scns)
    # storage-domain seeds stay stable: separate rng streams
    assert make_scenario(7) == make_scenario(7)


def test_fault_menu_names_real_injectors():
    """Menu entries must reference env vars the codebase actually
    parses, or a composition drills nothing."""
    import subprocess as sp

    names = sorted(set().union(*[set(f) for f in FAULT_MENU]))
    src = sp.run(["grep", "-rl", "--include=*.py", "RETH_TPU_FAULT_",
                  os.path.join(REPO, "reth_tpu")],
                 capture_output=True, text=True).stdout
    blob = "".join(open(f).read() for f in src.splitlines())
    for name in names:
        assert name in blob, f"{name} not parsed anywhere"


# -- subprocess kill drills (make test-chaos) ---------------------------------


def _drill(tmp_path, point: str, nth: int, blocks: int = 8,
           reorg_at: int = 0, timeout: int = 240):
    datadir = tmp_path / f"drill-{point}"
    datadir.mkdir()
    cmd = [sys.executable, "-m", "reth_tpu.chaos", "victim",
           "--datadir", str(datadir), "--seed", "7", "--blocks", str(blocks),
           "--threshold", "2", "--reorg-at", str(reorg_at)]
    r = subprocess.run(
        cmd, env=_env({"RETH_TPU_FAULT_CRASH_AT": f"{point}:{nth}"}),
        capture_output=True, text=True, cwd=REPO, timeout=timeout)
    assert r.returncode == 137, (
        f"{point} never fired: rc={r.returncode} {r.stderr[-400:]}")
    rec = subprocess.run(
        [sys.executable, "-m", "reth_tpu.chaos", "recover",
         "--datadir", str(datadir), "--seed", "7", "--threshold", "2"],
        env=_env(), capture_output=True, text=True, cwd=REPO, timeout=timeout)
    verdict = None
    for line in rec.stdout.splitlines():
        if line.startswith("RESULT "):
            verdict = json.loads(line[len("RESULT "):])
    assert verdict is not None, f"no verdict: {rec.stderr[-400:]}"
    assert verdict["ok"], (point, verdict["invariants"],
                           verdict.get("recovery_report"))
    return verdict


# acceptance: kill -9 at EVERY declared crash point recovers to a
# consistent head losing <= persistence_threshold blocks, with the
# recovered state root verified bit-identical by recomputation (and by
# a fault-free twin replay)
@pytest.mark.slow  # subprocess node (~8s each); `make test-chaos` runs it
@pytest.mark.parametrize("point,nth,reorg_at", [
    ("wal-append", 9, 0),
    ("checkpoint-swap", 2, 0),
    ("advance-persistence", 3, 0),
    ("unwind", 1, 5),
    ("jar-rename", 2, 0),
])
def test_kill_drill_every_crash_point(tmp_path, point, nth, reorg_at):
    verdict = _drill(tmp_path, point, nth, reorg_at=reorg_at)
    inv = verdict["invariants"]
    assert inv["root_recomputed"] and inv["twin_root"] and inv["loss_bound"]


@pytest.mark.slow
def test_kill_drill_external_sigkill(tmp_path):
    """Raw SIGKILL mid-mining (no crash point cooperation at all)."""
    scn = {"seed": 11, "faults": {}, "mode": "kill", "kill_after": 5,
           "blocks": 9, "reorg_at": 0, "threshold": 2, "hash_service": False}
    res = run_scenario(scn, tmp_path)
    assert res["ok"], (res.get("error"), res.get("invariants"))


@pytest.mark.slow  # ~1 min: the full seeded matrix; `make test-chaos` runs it
def test_chaos_campaign_ten_seeds(tmp_path):
    """Acceptance: a 10+-scenario seeded campaign of composed injectors
    x kill/restart passes the full invariant suite. Failing seeds print
    an exact replay command."""
    from reth_tpu.chaos import run_campaign

    results = run_campaign(range(1, 11), tmp_path)
    bad = [r for r in results if not r.get("ok")]
    assert not bad, [
        (r["seed"], r.get("error") or r.get("invariants")) for r in bad]


# -- Engine-API consensus domain (make test-chaos) ----------------------------


@pytest.mark.slow
def test_consensus_storm_scenario_completes(tmp_path):
    """One full reorg-storm scenario run to completion: the victim's
    live fault-free-twin invariants hold under the composed injectors,
    and the restart invariant suite passes afterwards."""
    scn = make_consensus_scenario(1)
    assert scn["mode"] == "complete"  # pin: seed 1 runs the whole storm
    res = run_scenario(scn, tmp_path)
    assert res["ok"], (res.get("error"), res.get("invariants"))


@pytest.mark.slow  # ~2 min: the full seeded matrix; `make test-chaos` runs it
def test_consensus_campaign_ten_seeds(tmp_path):
    """Acceptance: a 10-seed Engine-API adversarial campaign — reorg
    storms (side forks, deep reorgs across the persistence threshold,
    orphans, duplicates, invalid floods, hostile fcU targets) composed
    with the PR 1-11 injectors and crash points/SIGKILLs — passes the
    full invariant suite: canonical chain + roots bit-identical to the
    fault-free twin, no leaked lease/lock, health back to ok within the
    SLO window, node mines again. Failing seeds print a replay command."""
    from reth_tpu.chaos import run_campaign

    results = run_campaign(range(1, 11), tmp_path, domain="consensus")
    bad = [r for r in results if not r.get("ok")]
    assert not bad, [
        (r["seed"], r.get("error") or r.get("invariants")) for r in bad]


@pytest.mark.slow
def test_deep_reorg_across_threshold_sigkill_restart(tmp_path):
    """Satellite acceptance: a deep reorg across the persistence
    threshold followed by SIGKILL + restart — recovered head, re-served
    branch point, and root verification all consistent."""
    scn = {"domain": "consensus", "seed": 33, "faults": {}, "mode": "kill",
           "kill_after": 8, "rounds": 0, "threshold": 2,
           "hash_service": False, "force_deep_reorg": True}
    res = run_scenario(scn, tmp_path)
    assert res["ok"], (res.get("error"), res.get("invariants"))
    inv = res["invariants"]
    assert inv["root_recomputed"] and inv["twin_root"] and inv["loss_bound"]
    # the storm really reorged below the persistence threshold before the
    # kill (marker written ahead of the unwinding fcU), and the recovered
    # chain re-serves the branch point: the head sits at-or-above every
    # reorg target with its ancestry twin-verified
    rec = (tmp_path / "scn-33" / "chaos_blocks.jsonl").read_text()
    markers = [json.loads(l)["reorg_to"] for l in rec.splitlines()
               if "reorg_to" in l]
    assert markers, "no deep-reorg intent recorded before the kill"
    assert res["recovered"]["number"] >= min(markers)


@pytest.mark.slow
def test_torn_record_accepted_is_caught_end_to_end(tmp_path):
    """Acceptance: a deliberately broken recovery (torn WAL record
    accepted via RETH_TPU_FAULT_WAL_ACCEPT_TORN) is caught by the
    invariant suite — proving the harness can fail."""
    datadir = tmp_path / "torn"
    datadir.mkdir()
    r = subprocess.run(
        [sys.executable, "-m", "reth_tpu.chaos", "victim",
         "--datadir", str(datadir), "--seed", "3", "--blocks", "6",
         "--threshold", "2"],
        env=_env(), capture_output=True, text=True, cwd=REPO, timeout=240)
    assert r.returncode == 0, r.stderr[-400:]
    from reth_tpu.chaos import inject_bad_crc_record
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.primitives.secp256k1 import address_from_priv
    from reth_tpu.storage.tables import Tables

    victim_addr = address_from_priv(0xA11CE + 3)
    hkey = keccak256_batch_np([victim_addr])[0]

    def inject():
        # bit-rot a hashed account through a bad-CRC record appended to
        # the live segment (each graceful stop truncates the log, so the
        # record must be re-injected after every recover run)
        inject_bad_crc_record(datadir / "wal", {
            Tables.HashedAccounts.name: {
                "rows": {hkey: b"\xde\xad" * 24}, "del": []}})

    def recover(extra_env):
        rec = subprocess.run(
            [sys.executable, "-m", "reth_tpu.chaos", "recover",
             "--datadir", str(datadir), "--seed", "3", "--threshold", "2"],
            env=_env(extra_env), capture_output=True, text=True, cwd=REPO,
            timeout=240)
        for line in rec.stdout.splitlines():
            if line.startswith("RESULT "):
                return json.loads(line[len("RESULT "):])
        raise AssertionError(f"no verdict: {rec.stderr[-400:]}")

    # correct reader: tail discarded, everything passes
    inject()
    good = recover({})
    assert good["ok"], good["invariants"]
    # broken reader: the corruption lands — the suite must catch it
    inject()
    bad = recover({"RETH_TPU_FAULT_WAL_ACCEPT_TORN": "1"})
    assert not bad["ok"]
    assert not (bad["invariants"]["root_recomputed"]
                and bad["invariants"]["head_consistent"])
