"""Txpool + payload builder + local miner tests."""

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.local import LocalMiner
from reth_tpu.payload import PayloadAttributes, PayloadBuilderService, build_payload
from reth_tpu.pool import PoolError, TransactionPool
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

ALICE = 0xA11CE
BOB = 0xB0B


def make_node():
    alice, bob = Wallet(ALICE), Wallet(BOB)
    builder = ChainBuilder(
        {alice.address: Account(balance=10**21), bob.address: Account(balance=10**20)},
        committer=CPU,
    )
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis, committer=CPU)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    pool = TransactionPool(lambda: tree.overlay_provider())
    pool.base_fee = 10**9
    return tree, pool, alice, bob


def test_pool_validation():
    tree, pool, alice, bob = make_node()
    tx = alice.transfer(bob.address, 100)
    h = pool.add_transaction(tx)
    assert pool.contains(h) and len(pool) == 1
    with pytest.raises(PoolError, match="already known"):
        pool.add_transaction(tx)
    # insufficient funds
    poor = Wallet(0xDEAD)
    with pytest.raises(PoolError, match="insufficient funds"):
        pool.add_transaction(poor.transfer(bob.address, 10**18))


def test_pool_nonce_too_low_after_mining():
    tree, pool, alice, bob = make_node()
    pool.add_transaction(alice.transfer(bob.address, 1))
    LocalMiner(tree, pool).mine_block()
    stale = Wallet(ALICE)  # nonce 0 again
    with pytest.raises(PoolError, match="nonce too low"):
        pool.add_transaction(stale.transfer(bob.address, 2))


def test_pool_nonce_gap_and_ordering():
    tree, pool, alice, bob = make_node()
    t0 = alice.transfer(bob.address, 1)          # nonce 0
    t1 = alice.transfer(bob.address, 2)          # nonce 1
    alice.nonce = 5
    t5 = alice.transfer(bob.address, 3)          # nonce 5 (gap)
    b0 = bob.transfer(alice.address, 1, max_priority_fee_per_gas=5 * 10**9)
    for t in (t1, t5, b0, t0):  # shuffled insertion
        pool.add_transaction(t)
    best = list(pool.best_transactions(10**9))
    # bob pays a higher tip -> first; alice nonce-ordered; gap tx excluded
    assert [t.hash for t in best] == [b0.hash, t0.hash, t1.hash]
    content = pool.content()
    assert t5.hash in [t.hash for t in content["queued"].get(alice.address, {}).values()]


def test_pool_replacement_rules():
    tree, pool, alice, bob = make_node()
    t0 = alice.transfer(bob.address, 1)
    pool.add_transaction(t0)
    alice.nonce = 0
    cheap = alice.transfer(bob.address, 2)  # same nonce, same fee
    with pytest.raises(PoolError, match="underpriced"):
        pool.add_transaction(cheap)
    alice.nonce = 0
    bumped = alice.transfer(bob.address, 2, max_fee_per_gas=200 * 10**9)
    pool.add_transaction(bumped)
    assert not pool.contains(t0.hash)
    assert pool.contains(bumped.hash)


def test_payload_builder_and_miner():
    tree, pool, alice, bob = make_node()
    for i in range(3):
        pool.add_transaction(alice.transfer(bob.address, 1000 + i))
    miner = LocalMiner(tree, pool)
    block = miner.mine_block()
    assert block.header.number == 1
    assert len(block.transactions) == 3
    assert tree.head_hash == block.hash
    # mined txs evicted from the pool
    assert len(pool) == 0
    # balances visible at the new head
    p = tree.overlay_provider()
    assert p.account(bob.address).balance == 10**20 + 3000 + 3
    # mine an empty follow-up block
    b2 = miner.mine_block()
    assert b2.header.number == 2 and len(b2.transactions) == 0


def test_payload_service_ids():
    tree, pool, alice, bob = make_node()
    pool.add_transaction(alice.transfer(bob.address, 5))
    svc = PayloadBuilderService(tree, pool)
    pid = svc.new_payload_job(tree.head_hash, PayloadAttributes(timestamp=12))
    block = svc.get_payload(pid)
    assert block is not None and len(block.transactions) == 1
    # the built payload is accepted by the engine
    from reth_tpu.engine.tree import PayloadStatusKind

    assert tree.on_new_payload(block).status is PayloadStatusKind.VALID


def test_gas_limit_respected():
    tree, pool, alice, bob = make_node()
    # many txs; cap block gas artificially small via parent gas limit is
    # fixed, so instead check cumulative gas never exceeds the limit
    for i in range(5):
        pool.add_transaction(alice.transfer(bob.address, i + 1))
    block, _fees = build_payload(tree, pool, tree.head_hash, PayloadAttributes(timestamp=12))
    assert block.header.gas_used == 5 * 21000
    assert block.header.gas_used <= block.header.gas_limit

def test_payload_job_better_payload_swap():
    """Deadline-driven job: first build is synchronous; later rebuilds swap
    only strictly-better payloads (reference BasicPayloadJob semantics)."""
    tree, pool, alice, _bob = make_node()
    svc = PayloadBuilderService(tree, pool, deadline=5.0, interval=10.0)
    pool.add_transaction(alice.transfer(b"\x01" * 20, 100))
    pid = svc.new_payload_job(tree.head_hash, PayloadAttributes(timestamp=12))
    job = svc.jobs[pid]
    assert len(job.best.transactions) == 1  # synchronous first build
    fees_before = job.best_fees
    # a juicier tx arrives: an explicit rebuild must swap
    pool.add_transaction(alice.transfer(b"\x02" * 20, 100,
                                        max_priority_fee_per_gas=5 * 10**9))
    assert job.rebuild() is True
    assert job.best_fees > fees_before
    assert len(job.best.transactions) == 2
    best_fees = job.best_fees
    # nothing new: rebuild must NOT swap (equal fees is not better)
    assert job.rebuild() is False
    assert job.best_fees == best_fees
    block = svc.get_payload(pid)  # resolve stops the job
    assert len(block.transactions) == 2
    assert job.rebuild() is False  # resolved jobs are frozen


def test_payload_job_empty_fallback():
    """A failing full build must still yield an (empty) payload."""
    tree, pool, _alice, _bob = make_node()

    class ExplodingPool:
        def best_transactions(self, base_fee=None):
            raise RuntimeError("pool exploded")

    svc = PayloadBuilderService(tree, ExplodingPool(), deadline=0.1)
    pid = svc.new_payload_job(tree.head_hash, PayloadAttributes(timestamp=12))
    block = svc.get_payload(pid)
    assert block is not None and len(block.transactions) == 0


def test_pool_rejects_wrong_chain_id():
    """Wrong-chain txs are rejected at admission (reference
    EthTransactionValidator chain-id check)."""
    tree, _pool, alice, bob = make_node()
    from reth_tpu.pool import PoolConfig

    pool = TransactionPool(lambda: tree.overlay_provider(),
                           PoolConfig(chain_id=1))
    pool.base_fee = 10**9
    with pytest.raises(PoolError, match="wrong chain id"):
        pool.add_transaction(alice.transfer(bob.address, 1, chain_id=5))
    # legacy pre-EIP-155 txs carry no chain id and must pass
    from reth_tpu.primitives.types import Transaction

    legacy = alice.sign_tx(Transaction(
        tx_type=0, chain_id=None, nonce=alice.nonce - 1, gas_price=10**10,
        gas_limit=21_000, to=bob.address, value=7,
    ))
    assert pool.add_transaction(legacy)


def test_remove_invalid_drops_tx_and_sender_index():
    tree, pool, alice, bob = make_node()
    h0 = pool.add_transaction(alice.transfer(bob.address, 1))
    h1 = pool.add_transaction(alice.transfer(bob.address, 2))
    pool.remove_invalid(h0)
    assert not pool.contains(h0) and pool.contains(h1)
    # the sender index dropped the nonce entry too
    assert 0 not in pool.by_sender[alice.address]
    # removing an unknown hash is a no-op
    pool.remove_invalid(b"\x99" * 32)
    # best_transactions skips the gap: nonce 1 is not yieldable
    assert [t for t in pool.best_transactions(10**9)] == []


def test_remove_invalid_mid_best_transactions():
    """A consumer may evict txs WHILE iterating best_transactions (the
    payload builder does exactly this); iteration must not crash and must
    not yield the evicted tx."""
    tree, pool, alice, bob = make_node()
    t0 = alice.transfer(bob.address, 1)
    t1 = alice.transfer(bob.address, 2)
    t2 = alice.transfer(bob.address, 3)
    for t in (t0, t1, t2):
        pool.add_transaction(t)
    it = pool.best_transactions(10**9)
    first = next(it)
    assert first.hash == t0.hash
    pool.remove_invalid(t1.hash)  # evict the NEXT nonce mid-iteration
    rest = list(it)
    assert [t.hash for t in rest] == []  # nonce gap: t2 not yieldable
    assert pool.contains(t2.hash)  # but t2 stays pooled


def test_builder_evicts_unexecutable_and_skips_failed_sender():
    """A pooled tx that is provably unexecutable at build time is evicted
    (reference mark_invalid), and later nonces of the same sender are
    skipped in this build but kept pooled."""
    tree, pool, alice, bob = make_node()
    a0 = alice.transfer(bob.address, 1)
    # a1 passes admission (alice holds 10**21 now) but will be
    # underfunded at build time once an external block drains her
    a1 = alice.transfer(bob.address, 5 * 10**20)
    a2 = alice.transfer(bob.address, 2)
    for t in (a0, a1, a2):
        pool.add_transaction(t)
    # external block: alice (nonce 0) moves 95% of her balance away —
    # consumes a0's nonce AND defunds a1; no maintenance pass runs
    ext = Wallet(ALICE)
    chain = ChainBuilder(
        {ext.address: Account(balance=10**21), bob.address: Account(balance=10**20)},
        committer=CPU,
    )
    blk = chain.build_block(
        [ext.transfer(b"\xcc" * 20, 95 * 10**19, gas_limit=21_000)])
    from reth_tpu.engine.tree import PayloadStatusKind

    assert tree.on_new_payload(blk).status is PayloadStatusKind.VALID
    tree.on_forkchoice_updated(blk.hash)
    assert pool.contains(a1.hash)  # stale txs still pooled

    block, _fees = build_payload(
        tree, pool, tree.head_hash, PayloadAttributes(timestamp=30))
    # a1 (now the head nonce) is provably unexecutable -> evicted; a2 is
    # the same sender's later nonce -> skipped this build but kept pooled
    assert [t.hash for t in block.transactions] == []
    assert not pool.contains(a1.hash)  # evicted by the builder
    assert pool.contains(a2.hash)      # nonce-gapped, kept for a later build
