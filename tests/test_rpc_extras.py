"""otterscan / miner / bundle / gas-oracle namespaces over a live node.

Reference analogue: crates/rpc/rpc/src/otterscan.rs, miner.rs,
eth/bundle.rs, rpc-eth-types gas_oracle.rs.
"""

import pytest

from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.rpc.convert import data, parse_qty
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

from test_rpc_e2e import rpc

CPU = TrieCommitter(hasher=keccak256_batch_np)


@pytest.fixture()
def node():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    cfg = NodeConfig(dev=True, genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=CPU)
    n.start_rpc()
    yield n, alice
    n.stop()


def _mine_transfers(n, alice, count=3):
    bob = b"\x0b" * 20
    hashes = []
    for i in range(count):
        tx = alice.transfer(bob, 1000 + i)
        rpc(n.rpc.port, "eth_sendRawTransaction", data(tx.encode()))
        hashes.append(tx.hash)
        n.miner.mine_block()
    return bob, hashes


def test_ots_block_details_and_txs(node):
    n, alice = node
    port = n.rpc.port
    assert rpc(port, "ots_getApiLevel") == 8
    bob, hashes = _mine_transfers(n, alice)
    details = rpc(port, "ots_getBlockDetails", "0x1")
    assert details["block"]["transactionCount"] == 1
    assert parse_qty(details["totalFees"]) > 0
    h = rpc(port, "eth_getBlockByNumber", "0x2", False)["hash"]
    by_hash = rpc(port, "ots_getBlockDetailsByHash", h)
    assert by_hash["block"]["transactionCount"] == 1
    page = rpc(port, "ots_getBlockTransactions", 1, 0, 10)
    assert len(page["fullblock"]["transactions"]) == 1
    assert len(page["receipts"]) == 1


def test_ots_search_and_sender_nonce(node):
    n, alice = node
    port = n.rpc.port
    bob, hashes = _mine_transfers(n, alice)
    res = rpc(port, "ots_searchTransactionsBefore", data(bob), "0x0", 10)
    assert len(res["txs"]) == 3
    res2 = rpc(port, "ots_searchTransactionsAfter", data(alice.address), "0x1", 10)
    assert len(res2["txs"]) == 2  # blocks 2 and 3
    got = rpc(port, "ots_getTransactionBySenderAndNonce", data(alice.address), "0x1")
    assert got == data(hashes[1])
    assert rpc(port, "ots_hasCode", data(bob), "latest") is False


def test_ots_contract_creator_and_trace(node):
    n, alice = node
    port = n.rpc.port
    # deploy: initcode returning empty runtime is fine for creator lookup
    deploy = alice.deploy(bytes.fromhex("600060005500"))
    rpc(port, "eth_sendRawTransaction", data(deploy.encode()))
    n.miner.mine_block()
    from reth_tpu.primitives.rlp import encode_int, rlp_encode

    created = keccak256(rlp_encode([alice.address, encode_int(0)]))[12:]
    info = rpc(port, "ots_getContractCreator", data(created))
    assert info is not None
    assert info["creator"] == data(alice.address)
    assert info["hash"] == data(deploy.hash)
    trace = rpc(port, "ots_traceTransaction", data(deploy.hash))
    assert trace and trace[0]["depth"] == 0
    assert rpc(port, "ots_getTransactionError", data(deploy.hash)) == "0x"


def test_gas_oracle_tracks_tips(node):
    n, alice = node
    port = n.rpc.port
    _mine_transfers(n, alice)
    price = parse_qty(rpc(port, "eth_gasPrice"))
    tip = parse_qty(rpc(port, "eth_maxPriorityFeePerGas"))
    assert tip > 0
    assert price >= tip  # price = base fee + tip
    # cached per head: same answer without recompute
    assert parse_qty(rpc(port, "eth_gasPrice")) == price


def test_miner_namespace(node):
    n, alice = node
    port = n.rpc.port
    assert rpc(port, "miner_setExtra", "0x" + b"reth-tpu".hex()) is True
    assert rpc(port, "miner_setGasLimit", "0x1c9c380") is True
    assert rpc(port, "miner_setGasPrice", "0x3b9aca00") is True


def test_eth_call_bundle(node):
    n, alice = node
    port = n.rpc.port
    bob = b"\x0b" * 20
    tx1 = alice.transfer(bob, 500)
    alice.nonce += 0  # transfer() advanced it
    tx2 = alice.transfer(bob, 600)
    out = rpc(port, "eth_callBundle", {
        "txs": [data(tx1.encode()), data(tx2.encode())],
    })
    assert out["totalGasUsed"] == 42000
    assert len(out["results"]) == 2
    assert all("error" not in r for r in out["results"])
    # bundle simulation must NOT touch the chain
    assert rpc(port, "eth_blockNumber") == "0x0"
    assert parse_qty(rpc(port, "eth_getBalance", data(bob), "latest")) == 0


def test_miner_knobs_have_effect(node):
    n, alice = node
    port = n.rpc.port
    # extra data lands in subsequently built payloads
    rpc(port, "miner_setExtra", "0x" + b"tpu!".hex())
    from reth_tpu.payload.builder import PayloadAttributes, build_payload

    parent = n.tree.head_hash
    block, _fees = build_payload(
        n.tree, n.pool, parent,
        PayloadAttributes(timestamp=1_700_000_000),
        extra_data=n.payload_service.extra_data,
        gas_ceiling=n.payload_service.gas_ceiling)
    assert block.header.extra_data == b"tpu!"
    # price floor rejects underpriced txs at admission
    rpc(port, "miner_setGasPrice", hex(2 * 10**9))
    cheap = alice.transfer(b"\x0b" * 20, 1, max_priority_fee_per_gas=10**9)
    import urllib.error

    try:
        rpc(port, "eth_sendRawTransaction", data(cheap.encode()))
        raised = False
    except RuntimeError as e:
        raised = "underpriced" in str(e)
    assert raised
    # gas ceiling steers the next payload's gas limit downward
    rpc(port, "miner_setGasLimit", hex(20_000_000))
    block2, _ = build_payload(
        n.tree, None, parent, PayloadAttributes(timestamp=1_700_000_001),
        gas_ceiling=n.payload_service.gas_ceiling)
    assert block2.header.gas_limit < 30_000_000


def test_round4_rpc_surface(tmp_path):
    """eth_blobBaseFee, eth_createAccessList, eth_simulateV1,
    debug_traceBlockByNumber, engine_getClientVersionV1 (reference
    rpc-eth-api/src/core.rs + rpc/src/debug.rs surfaces)."""
    import json
    import time
    import urllib.request

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    cfg = NodeConfig(dev=True, genesis_header=builder.genesis,
                     genesis_alloc=builder.accounts_at_genesis)
    n = Node(cfg, committer=CPU)
    n.start_rpc()

    def rpc(method, *params):
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)})
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{n.rpc.port}/", req.encode(),
            {"Content-Type": "application/json"}), timeout=30)
        out = json.loads(r.read())
        assert "error" not in out, out
        return out["result"]

    try:
        # a storage-writing contract to trace + access-list against
        rt = bytes.fromhex("6020355f355500")
        init = bytes([0x60, len(rt), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(rt),
                      0x5F, 0xF3]) + b"\x00" + rt
        from reth_tpu.rpc.convert import data as _data

        h = rpc("eth_sendRawTransaction", _data(alice.deploy(init).encode()))
        n.miner.mine_block()
        addr = rpc("eth_getTransactionReceipt", h)["contractAddress"]
        rpc("eth_sendRawTransaction", _data(alice.call(
            bytes.fromhex(addr[2:]),
            (5).to_bytes(32, "big") + (9).to_bytes(32, "big")).encode()))
        n.miner.mine_block()

        assert int(rpc("eth_blobBaseFee"), 16) >= 0

        al = rpc("eth_createAccessList", {
            "from": "0x" + alice.address.hex(), "to": addr,
            "data": "0x" + (7).to_bytes(32, "big").hex()
                    + (1).to_bytes(32, "big").hex()}, "latest")
        assert any(e["address"].lower() == addr.lower() and e["storageKeys"]
                   for e in al["accessList"])

        sim = rpc("eth_simulateV1", {
            "blockStateCalls": [
                {"stateOverrides": {
                    "0x" + "aa" * 20: {"balance": hex(10**18)}},
                 "calls": [
                     {"from": "0x" + "aa" * 20, "to": "0x" + "bb" * 20,
                      "value": "0x5"},
                     {"from": "0x" + alice.address.hex(), "to": addr,
                      "data": "0x" + (8).to_bytes(32, "big").hex()
                              + (3).to_bytes(32, "big").hex()},
                 ]},
                {"blockOverrides": {"time": "0x77777777"},
                 "calls": [
                     {"from": "0x" + "aa" * 20, "to": "0x" + "bb" * 20,
                      "value": "0x2"}]},
            ]}, "latest")
        assert len(sim) == 2
        assert all(c["status"] == "0x1" for b in sim for c in b["calls"])
        assert int(sim[1]["timestamp"], 16) == 0x77777777

        traces = rpc("debug_traceBlockByNumber", "0x2",
                     {"tracer": "callTracer"})
        assert len(traces) == 1 and traces[0]["result"]["type"] == "CALL"

        ver = n.engine_api.engine_getClientVersionV1()
        assert ver[0]["name"] == "reth-tpu" and ver[0]["code"]
    except Exception:
        raise
    finally:
        n.stop()


def test_create_access_list_survives_revert():
    """Regression (round-4 review): a REVERTing call must still return
    the accesses it made (the journal rollback may not wipe the list) —
    reverting estimates are the API's main use case."""
    import json
    import urllib.request

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.rpc.convert import data as _data
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    n = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                        genesis_alloc=builder.accounts_at_genesis),
             committer=CPU)
    n.start_rpc()

    def rpc(method, *params):
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)})
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{n.rpc.port}/", req.encode(),
            {"Content-Type": "application/json"}), timeout=30)
        out = json.loads(r.read())
        assert "error" not in out, out
        return out["result"]

    try:
        # sload(5) then revert: PUSH1 05 SLOAD POP PUSH0 PUSH0 REVERT
        rt = bytes.fromhex("600554505f5ffd")
        init = bytes([0x60, len(rt), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(rt),
                      0x5F, 0xF3]) + b"\x00" + rt
        h = rpc("eth_sendRawTransaction", _data(alice.deploy(init).encode()))
        n.miner.mine_block()
        addr = rpc("eth_getTransactionReceipt", h)["contractAddress"]
        al = rpc("eth_createAccessList", {
            "from": "0x" + alice.address.hex(), "to": addr}, "latest")
        assert al["error"] is not None  # the call did fail
        slot5 = "0x" + (5).to_bytes(32, "big").hex()
        assert any(e["address"].lower() == addr.lower()
                   and slot5 in e["storageKeys"]
                   for e in al["accessList"]), al
        # simulateV1 charges COLD costs per call (warm sets reset)
        sim = rpc("eth_simulateV1", {"blockStateCalls": [{"calls": [
            {"from": "0x" + alice.address.hex(), "to": addr},
            {"from": "0x" + alice.address.hex(), "to": addr},
        ]}]}, "latest")
        g0 = int(sim[0]["calls"][0]["gasUsed"], 16)
        g1 = int(sim[0]["calls"][1]["gasUsed"], 16)
        assert g0 == g1  # identical cold-start gas for identical calls
    finally:
        n.stop()


def test_debug_trace_call():
    """debug_traceCall: struct logs + callTracer for an un-mined call
    (reference debug_traceCall, rpc-api/src/debug.rs:105)."""
    import json
    import urllib.request

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.rpc.convert import data as _data
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    n = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                        genesis_alloc=builder.accounts_at_genesis),
             committer=CPU)
    n.start_rpc()

    def rpc(method, *params):
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)})
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{n.rpc.port}/", req.encode(),
            {"Content-Type": "application/json"}), timeout=30)
        out = json.loads(r.read())
        assert "error" not in out, out
        return out["result"]

    try:
        rt = bytes.fromhex("6020355f355500")  # sstore(cd[0], cd[32]); stop
        init = bytes([0x60, len(rt), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(rt),
                      0x5F, 0xF3]) + b"\x00" + rt
        h = rpc("eth_sendRawTransaction", _data(alice.deploy(init).encode()))
        n.miner.mine_block()
        addr = rpc("eth_getTransactionReceipt", h)["contractAddress"]
        calldata = "0x" + (1).to_bytes(32, "big").hex() + (2).to_bytes(32, "big").hex()
        tr = rpc("debug_traceCall",
                 {"from": "0x" + alice.address.hex(), "to": addr,
                  "data": calldata}, "latest", {})
        assert not tr["failed"] and any(
            lg["op"] == "SSTORE" for lg in tr["structLogs"])
        ct = rpc("debug_traceCall",
                 {"from": "0x" + alice.address.hex(), "to": addr,
                  "data": calldata}, "latest", {"tracer": "callTracer"})
        assert ct["type"] == "CALL" and ct["to"].lower() == addr.lower()
        # the traced call was NOT mined: state unchanged
        assert rpc("eth_getStorageAt", addr, "0x1", "latest") == "0x" + "00" * 32
    finally:
        n.stop()


def test_txpool_inspect_and_content_from():
    """txpool_inspect summary strings + txpool_contentFrom filtering
    (reference crates/rpc/rpc/src/txpool.rs)."""
    import json
    import urllib.request

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.rpc.convert import data as _data
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice, bob = Wallet(0xA11CE), Wallet(0xB0B)
    builder = ChainBuilder({alice.address: Account(balance=10**21),
                            bob.address: Account(balance=10**20)},
                           committer=CPU)
    n = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                        genesis_alloc=builder.accounts_at_genesis),
             committer=CPU)
    n.start_rpc()

    def rpc(method, *params):
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)})
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{n.rpc.port}/", req.encode(),
            {"Content-Type": "application/json"}), timeout=30)
        out = json.loads(r.read())
        assert "error" not in out, out
        return out["result"]

    try:
        rpc("eth_sendRawTransaction",
            _data(alice.transfer(b"\x0b" * 20, 777).encode()))
        rpc("eth_sendRawTransaction",
            _data(bob.transfer(b"\x0c" * 20, 555).encode()))
        insp = rpc("txpool_inspect")
        a_key = "0x" + alice.address.hex()
        assert a_key in insp["pending"]
        assert "777 wei + 21000 gas \u00d7" in insp["pending"][a_key]["0"]
        frm = rpc("txpool_contentFrom", a_key)
        assert list(frm["pending"]) == ["0"]  # nonce-keyed, no addr layer
        assert frm["pending"]["0"]["value"] == hex(777)
    finally:
        n.stop()


def test_eth_get_account():
    """eth_getAccount returns the full account object, absent accounts
    included (reference eth_getAccount, rpc-eth-api/src/core.rs)."""
    import json
    import urllib.request

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    n = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                        genesis_alloc=builder.accounts_at_genesis),
             committer=CPU)
    n.start_rpc()

    def rpc(method, *params):
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)})
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{n.rpc.port}/", req.encode(),
            {"Content-Type": "application/json"}), timeout=30)
        out = json.loads(r.read())
        assert "error" not in out, out
        return out["result"]

    try:
        acct = rpc("eth_getAccount", "0x" + alice.address.hex(), "latest")
        assert int(acct["balance"], 16) == 10**21
        assert acct["codeHash"] == "0x" + keccak256(b"").hex()
        absent = rpc("eth_getAccount", "0x" + "77" * 20, "latest")
        assert int(absent["balance"], 16) == 0 and int(absent["nonce"], 16) == 0
        # a contract with storage must report the LIVE storage root (the
        # merkle-layer-owned one), matching eth_getProof — not the plain
        # execution-time placeholder (round-4 review)
        from reth_tpu.rpc.convert import data as _data

        rt = bytes.fromhex("6020355f355500")
        init = bytes([0x60, len(rt), 0x60, 0x0B, 0x5F, 0x39, 0x60, len(rt),
                      0x5F, 0xF3]) + b"\x00" + rt
        h = rpc("eth_sendRawTransaction", _data(alice.deploy(init).encode()))
        n.miner.mine_block()
        caddr = rpc("eth_getTransactionReceipt", h)["contractAddress"]
        rpc("eth_sendRawTransaction", _data(alice.call(
            bytes.fromhex(caddr[2:]),
            (1).to_bytes(32, "big") + (2).to_bytes(32, "big")).encode()))
        n.miner.mine_block()
        got = rpc("eth_getAccount", caddr, "latest")
        proof = rpc("eth_getProof", caddr, [], "latest")
        assert got["storageRoot"] == proof["storageHash"]
        assert int(got["storageRoot"], 16) != 0
    finally:
        n.stop()


def test_simulate_v1_full_blocks():
    """Round-5 eth_simulateV1 completion: each simulated entry is a full
    RPC block whose stateRoot is recomputed by the trie pipeline, blocks
    chain by parentHash, number gaps fill with empty blocks, and
    returnFullTransactions yields transaction objects (reference
    rpc-eth-types/src/simulate.rs build_simulated_block)."""
    import json
    import urllib.request

    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives import Account
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.testing import ChainBuilder, Wallet
    from reth_tpu.trie import TrieCommitter, state_root

    CPU = TrieCommitter(hasher=keccak256_batch_np)
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    n = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                        genesis_alloc=builder.accounts_at_genesis),
             committer=CPU)
    n.start_rpc()

    def rpc(method, *params):
        req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                          "params": list(params)})
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{n.rpc.port}/", req.encode(),
            {"Content-Type": "application/json"}), timeout=30)
        return json.loads(r.read())

    try:
        aa, bb = b"\xaa" * 20, b"\xbb" * 20
        sim = rpc("eth_simulateV1", {
            "returnFullTransactions": True,
            "blockStateCalls": [
                {"stateOverrides": {"0x" + aa.hex(): {"balance": hex(10**18)}},
                 "calls": [{"from": "0x" + aa.hex(), "to": "0x" + bb.hex(),
                            "value": "0x5"}]},
                {"blockOverrides": {"number": "0x5"}, "calls": []},
            ]}, "latest")["result"]
        # gap filling: entries at 1 and 5 => blocks 1,2,3,4,5
        assert [int(b["number"], 16) for b in sim] == [1, 2, 3, 4, 5]
        # chained linkage + full tx objects
        for prev, cur in zip(sim, sim[1:]):
            assert cur["parentHash"] == prev["hash"]
        tx0 = sim[0]["transactions"][0]
        assert tx0["from"] == "0x" + aa.hex() and int(tx0["value"], 16) == 5
        assert sim[0]["calls"][0]["status"] == "0x1"
        # stateRoot recomputed by the trie pipeline: base fee is zero in
        # non-validation mode, so the only delta is the 5 wei transfer
        expected_accounts = dict(builder.accounts_at_genesis)
        expected_accounts[aa] = Account(balance=10**18 - 5, nonce=1)
        expected_accounts[bb] = Account(balance=5)
        want_root, _ = state_root(expected_accounts, {}, committer=CPU)
        assert sim[0]["stateRoot"] == "0x" + want_root.hex()
        # empty gap blocks keep the same root
        assert sim[1]["stateRoot"] == sim[0]["stateRoot"]
        # validation mode enforces nonces: a stale nonce must error
        err = rpc("eth_simulateV1", {
            "validation": True,
            "blockStateCalls": [
                {"stateOverrides": {"0x" + aa.hex(): {"balance": hex(10**18),
                                                      "nonce": "0x7"}},
                 "calls": [{"from": "0x" + aa.hex(), "to": "0x" + bb.hex(),
                            "value": "0x1", "nonce": "0x0",
                            "maxFeePerGas": hex(10**10)}]}]}, "latest")
        assert "error" in err and "nonce" in err["error"]["message"]
    finally:
        n.stop()
