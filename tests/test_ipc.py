"""IPC (Unix socket) JSON-RPC transport."""

from __future__ import annotations

import json
import socket

from reth_tpu.rpc.ipc import IpcRpcServer
from reth_tpu.rpc.server import RpcServer


def test_ipc_roundtrip(tmp_path):
    rpc = RpcServer()
    rpc.register_method("test_echo", lambda x: x + 1)
    server = IpcRpcServer(rpc, tmp_path / "node.ipc")
    path = server.start()
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(path)
        for i in (1, 41):
            sock.sendall(json.dumps({"jsonrpc": "2.0", "id": i,
                                     "method": "test_echo",
                                     "params": [i]}).encode() + b"\n")
            buf = b""
            while not buf.endswith(b"\n"):
                buf += sock.recv(4096)
            assert json.loads(buf) == {"jsonrpc": "2.0", "id": i, "result": i + 1}
        sock.close()
    finally:
        server.stop()
    import os

    assert not os.path.exists(path)  # socket file cleaned up
