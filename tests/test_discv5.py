"""discv5 + ENR: record codec/signing, packet crypto, live handshakes.

Reference analogue: the reference delegates to sigp/discv5 + enr crates
(crates/net/discv5/src/lib.rs, src/enr.rs); these tests cover the same
surface in-process over localhost UDP.
"""

import time

import pytest

from reth_tpu.net.discv5 import (
    FLAG_ORDINARY,
    FLAG_WHOAREYOU,
    Discv5,
    RoutingTable,
    derive_session_keys,
    id_sign,
    id_verify,
    mask_packet,
    unmask_packet,
    _header,
)
from reth_tpu.net.enr import Enr, EnrError, make_enr, node_id_from_pubkey
from reth_tpu.primitives.secp256k1 import (
    compress_pubkey,
    decompress_pubkey,
    pubkey_from_priv,
    random_priv,
)

PRIV_A = 0xEEF77ACB6C6A6EEBC5B363A475AC583EC7ECCDB42B6481424C60F59AA326547F
PRIV_B = 0x66FB62BFBD66B9177A138C1E5CDDBE4F7C30C343E94E68DF8769459CB1CDE628


def test_compress_roundtrip():
    for priv in (PRIV_A, PRIV_B, 1, 2, random_priv()):
        pub = pubkey_from_priv(priv)
        c = compress_pubkey(pub)
        assert len(c) == 33 and c[0] in (2, 3)
        assert decompress_pubkey(c) == pub


def test_enr_roundtrip_and_verify():
    rec = make_enr(PRIV_A, ip="127.0.0.1", udp=30303, tcp=30303, seq=7)
    raw = rec.encode()
    back = Enr.decode(raw)
    assert back.seq == 7
    assert back.ip == "127.0.0.1"
    assert back.udp_port == 30303
    assert back.node_id == node_id_from_pubkey(pubkey_from_priv(PRIV_A))
    # base64 text form round-trips
    assert Enr.from_base64(rec.to_base64()).encode() == raw
    # tampering breaks the signature
    bad = make_enr(PRIV_A, ip="127.0.0.1", udp=30303)
    bad.pairs["udp"] = b"\x01\x02"
    with pytest.raises(EnrError):
        Enr.decode(bad.encode())


def test_packet_mask_roundtrip():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    dest_id = node_id_from_pubkey(pubkey_from_priv(PRIV_B))
    header = _header(FLAG_ORDINARY, b"\x01" * 12, b"\xaa" * 32)
    pkt = mask_packet(dest_id, header, b"payload")
    iv, flag, nonce, authdata, message = unmask_packet(dest_id, pkt)
    assert flag == FLAG_ORDINARY
    assert nonce == b"\x01" * 12
    assert authdata == b"\xaa" * 32
    assert message == b"payload"
    # wrong recipient cannot parse (masking key is dest-id prefix)
    other = node_id_from_pubkey(pubkey_from_priv(PRIV_A))
    with pytest.raises(Exception):
        unmask_packet(other, pkt)


def test_session_key_agreement_both_sides():
    a_pub, b_pub = pubkey_from_priv(PRIV_A), pubkey_from_priv(PRIV_B)
    a_id, b_id = node_id_from_pubkey(a_pub), node_id_from_pubkey(b_pub)
    challenge = b"\x05" * 63
    eph_priv = random_priv()
    eph_pub = pubkey_from_priv(eph_priv)
    # initiator (A, answering B's WHOAREYOU) vs recipient (B)
    ia, ra = derive_session_keys(challenge, eph_priv, None, None, b_pub, a_id, b_id)
    ib, rb = derive_session_keys(challenge, None, eph_pub, PRIV_B, None, a_id, b_id)
    assert (ia, ra) == (ib, rb)
    sig = id_sign(PRIV_A, challenge, compress_pubkey(eph_pub), b_id)
    assert id_verify(a_pub, sig, challenge, compress_pubkey(eph_pub), b_id)
    assert not id_verify(b_pub, sig, challenge, compress_pubkey(eph_pub), b_id)
    assert not id_verify(a_pub, sig, b"\x06" * 63, compress_pubkey(eph_pub), b_id)


@pytest.fixture()
def pair():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    a = Discv5(PRIV_A)
    b = Discv5(PRIV_B)
    a.start()
    b.start()
    yield a, b
    a.stop()
    b.stop()


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_handshake_establishes_sessions(pair):
    a, b = pair
    a.table.add(b.enr)
    a.ping(b.enr)  # random packet -> WHOAREYOU -> handshake(PING) -> PONG
    assert _wait(lambda: b.node_id in a.sessions and a.node_id in b.sessions)
    # B learned A's record from the handshake
    assert _wait(lambda: a.node_id in b.table.by_id)
    assert b.table.by_id[a.node_id].udp_port == a.port


def test_findnode_by_distance(pair):
    a, b = pair
    # C is known to B only
    priv_c = random_priv()
    c_enr = make_enr(priv_c, ip="127.0.0.1", udp=9, tcp=9)
    b.table.add(c_enr)
    a.table.add(b.enr)
    a.ping(b.enr)
    assert _wait(lambda: b.node_id in a.sessions)
    d = RoutingTable.distance(b.node_id, c_enr.node_id)
    got = a.find_node(b.enr, [d], wait=5.0)
    assert any(e.node_id == c_enr.node_id for e in got)
    # distance 0 returns B's own record
    got0 = a.find_node(b.enr, [0], wait=5.0)
    assert any(e.node_id == b.node_id for e in got0)


def test_lookup_discovers_via_bootstrap():
    pytest.importorskip("cryptography")  # AES for RLPx/discv5 paths
    nodes = [Discv5(random_priv()) for _ in range(4)]
    for n in nodes:
        n.start()
    try:
        boot = nodes[0]
        # everyone bonds with the bootstrap node
        for n in nodes[1:]:
            n.bootstrap([boot.enr.to_base64()])
        assert _wait(lambda: all(boot.node_id in n.sessions for n in nodes[1:]))
        assert _wait(lambda: len(boot.table) >= 3)
        # querying the exact buckets discovers every other node
        newcomer = nodes[1]
        others = [n for n in nodes[2:]]
        dists = sorted({RoutingTable.distance(boot.node_id, n.node_id)
                        for n in others})
        got = newcomer.find_node(boot.enr, dists, wait=5.0)
        assert {n.node_id for n in others} <= {e.node_id for e in got}
        # and the iterative lookup at least keeps the table populated
        newcomer.lookup(rounds=1, wait=0.5)
        assert boot.node_id in newcomer.table.by_id
    finally:
        for n in nodes:
            n.stop()
