"""Parallel sparse commit (trie/sparse.py ParallelSparseCommitter +
trie/proof.py ProofWorkerPool): randomized differential parity against
the serial root_hash_compute path (bit-identical roots across interleaved
updates/deletes/wipes, blinded-node and preserved-trie edges), encode/
proof pool sweeps, a threaded stress drill over a shared committer, and
the RETH_TPU_FAULT_SPARSE_* abort/wedge drills (engine must fall back to
the incremental committer — reference state_root_fallback)."""

import threading

import numpy as np
import pytest

from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.tables import encode_account
from reth_tpu.trie import TrieCommitter
from reth_tpu.trie.incremental import full_state_root
from reth_tpu.trie.naive import naive_trie_root
from reth_tpu.trie.proof import ProofCalculator, ProofWorkerPool
from reth_tpu.trie.sparse import (
    ParallelSparseCommitter,
    SparseFaultInjector,
    SparseStateTrie,
    SparseTrie,
)

CPU = TrieCommitter(hasher=keccak256_batch_np)


def _rand_key(rng):
    return bytes(rng.integers(0, 256, 32, dtype=np.uint8))


def _rand_val(rng, lo=1, hi=40):
    return bytes(rng.integers(0, 256, int(rng.integers(lo, hi)),
                              dtype=np.uint8))


def _build_twins(seed, n_tries=24, slots=12):
    """Two identical SparseStateTries (fed the same ops) + a leaf oracle."""
    rng = np.random.default_rng(seed)
    twins = (SparseStateTrie(), SparseStateTrie())
    oracle = {"acct": {}, "storage": {}}
    owners = []
    for _ in range(n_tries):
        ha = _rand_key(rng)
        owners.append(ha)
        oracle["storage"][ha] = {}
        for _ in range(slots):
            k, v = _rand_key(rng), _rand_val(rng)
            for st in twins:
                st.storage_trie(ha).update(k, v)
            oracle["storage"][ha][k] = v
        av = b"acct" + ha
        for st in twins:
            st.update_account(ha, av)
        oracle["acct"][ha] = av
    return twins, oracle, owners, rng


def _check_parity(twins, committer, oracle):
    """Serial twin vs parallel twin: roots bit-identical, storage tries
    match the naive oracle."""
    serial, parallel = twins
    r_ser = serial.root(keccak256_batch_np)
    r_par = parallel.root(keccak256_batch_np, committer=committer)
    assert r_ser == r_par
    assert r_ser == naive_trie_root(oracle["acct"])
    for ha, leaves in oracle["storage"].items():
        want = naive_trie_root(leaves)
        assert serial.storage_tries[ha].root_hash == want
        assert parallel.storage_tries[ha].root_hash == want


def test_randomized_differential_interleaved_churn():
    """Interleaved updates/deletes/wipes across many storage tries + the
    account trie: the packed parallel commit stays bit-identical to the
    serial path round after round (cross-round ref reuse included)."""
    twins, oracle, owners, rng = _build_twins(7)
    committer = ParallelSparseCommitter(workers=4)
    _check_parity(twins, committer, oracle)  # round 0: full build
    for _round in range(4):
        for _ in range(40):
            op = int(rng.integers(0, 4))
            ha = owners[int(rng.integers(0, len(owners)))]
            leaves = oracle["storage"][ha]
            if op == 0:  # update/insert a slot
                k = (_rand_key(rng) if rng.integers(0, 2) or not leaves
                     else list(leaves)[int(rng.integers(0, len(leaves)))])
                v = _rand_val(rng)
                for st in twins:
                    st.storage_trie(ha).update(k, v)
                leaves[k] = v
            elif op == 1 and leaves:  # delete a slot
                k = list(leaves)[int(rng.integers(0, len(leaves)))]
                for st in twins:
                    st.storage_trie(ha).delete(k)
                del leaves[k]
            elif op == 2:  # wipe the trie (SELFDESTRUCT shape)
                for st in twins:
                    st.storage_tries[ha] = SparseTrie()
                leaves.clear()
            else:  # account-leaf churn
                v = _rand_val(rng, 4, 60)
                for st in twins:
                    st.update_account(ha, v)
                oracle["acct"][ha] = v
        _check_parity(twins, committer, oracle)


def _db_state(n_accounts=48, seed=11):
    rng = np.random.default_rng(seed)
    factory = ProviderFactory(MemDb())
    addresses = [bytes(rng.integers(0, 256, 20, dtype=np.uint8))
                 for _ in range(n_accounts)]
    with factory.provider_rw() as p:
        for i, a in enumerate(addresses):
            p.put_hashed_account(keccak256(a),
                                 Account(nonce=i, balance=1000 + i))
        root = full_state_root(p, CPU)
    leaves = {keccak256(a): encode_account(Account(nonce=i, balance=1000 + i))
              for i, a in enumerate(addresses)}
    return factory, addresses, root, leaves


def test_blinded_partial_reveal_parity():
    """Anchored tries with most paths BLINDED: only revealed spines are
    touched; the packed commit must hash the same dirty set and produce
    the same root as the serial path (and the naive full oracle)."""
    factory, addrs, root, leaves = _db_state()
    serial, parallel = SparseTrie(root), SparseTrie(root)
    touched = addrs[:10]
    with factory.provider() as p:
        calc = ProofCalculator(p, CPU)
        for a in touched:
            pr = calc.account_proof(a)
            serial.reveal(pr.proof)
            parallel.reveal(pr.proof)
    for i, a in enumerate(touched):
        new = encode_account(Account(nonce=500 + i, balance=1))
        serial.update(keccak256(a), new)
        parallel.update(keccak256(a), new)
        leaves[keccak256(a)] = new
    committer = ParallelSparseCommitter(workers=4)
    r_ser = serial.root_hash_compute(keccak256_batch_np)
    r_par = committer.commit([parallel], keccak256_batch_np)[0]
    assert r_ser == r_par == naive_trie_root(leaves)


def test_preserved_trie_second_commit_hashes_less():
    """Cross-block reuse: after a packed commit, touching ONE trie must
    re-hash only its dirty spine — and stay identical to the serial twin."""
    twins, oracle, owners, rng = _build_twins(13)
    committer = ParallelSparseCommitter(workers=4)
    _check_parity(twins, committer, oracle)
    calls = []

    def counting(msgs):
        calls.append(len(msgs))
        return keccak256_batch_np(msgs)

    ha = owners[0]
    k, v = _rand_key(rng), b"post-commit"
    for st in twins:
        st.storage_trie(ha).update(k, v)
    oracle["storage"][ha][k] = v
    serial, parallel = twins
    r_par = parallel.root(counting, committer=committer)
    second_total = sum(calls)
    first_total = sum(len(l) for l in oracle["storage"].values())
    assert second_total < first_total  # only the dirty spine re-hashed
    assert r_par == serial.root(keccak256_batch_np)


@pytest.mark.parametrize("workers", [1, 2, 4, 8])
def test_encode_pool_sweep(workers):
    """Pool-size sweep: every width produces the identical root and
    records commit stats."""
    twins, oracle, _owners, _rng = _build_twins(23, n_tries=12, slots=20)
    committer = ParallelSparseCommitter(workers=workers)
    _check_parity(twins, committer, oracle)
    stats = committer.last
    assert stats["levels"] > 0 and stats["dispatches"] > 0
    assert stats["hashed"] > 0
    committer.shutdown()


def test_split_depth_sweep():
    """The upper/lower partition point must not affect the root."""
    roots = set()
    for split in (1, 2, 3):
        twins, oracle, _o, _r = _build_twins(31, n_tries=8, slots=16)
        committer = ParallelSparseCommitter(workers=4, split_depth=split)
        serial, parallel = twins
        r = parallel.root(keccak256_batch_np, committer=committer)
        assert r == serial.root(keccak256_batch_np)
        roots.add(r)
    assert len(roots) == 1


def test_live_lane_streaming_through_hash_service():
    """With a lane-bound HashClient hasher the encode pool STREAMS chunks
    into the service (submit futures); root stays bit-identical and the
    service coalesces the streamed requests."""
    from reth_tpu.metrics import MetricsRegistry
    from reth_tpu.ops.hash_service import HashService

    twins, oracle, _o, _r = _build_twins(41, n_tries=32, slots=24)
    svc = HashService(backend=keccak256_batch_np,
                      registry=MetricsRegistry())
    try:
        client = svc.client("live")
        committer = ParallelSparseCommitter(workers=4)
        serial, parallel = twins
        r_par = parallel.root(client, committer=committer)
        assert r_par == serial.root(keccak256_batch_np)
        assert committer.last["streamed"] > 0
        assert svc.dispatches > 0
        # map_chunks is the same streaming contract, exposed directly
        msgs = [b"chunk-%d" % i for i in range(8)]
        got = client.map_chunks([msgs[:3], msgs[3:]])
        assert got == keccak256_batch_np(msgs)
    finally:
        svc.stop()


def test_threaded_stress_shared_committer():
    """Many threads commit DISTINCT trie sets through ONE shared
    committer (shared encode pool): every thread's roots must match its
    serial twin — per-commit state is thread-local by construction."""
    committer = ParallelSparseCommitter(workers=4)
    errs = []

    def worker(seed):
        try:
            for round_seed in range(3):
                twins, oracle, _o, _r = _build_twins(
                    seed * 100 + round_seed, n_tries=6, slots=10)
                _check_parity(twins, committer, oracle)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    committer.shutdown()


# -- proof-worker pool --------------------------------------------------------


def _storage_db(n_accounts=10, slots_per=20, seed=5):
    rng = np.random.default_rng(seed)
    factory = ProviderFactory(MemDb())
    targets = {}
    with factory.provider_rw() as p:
        for i in range(n_accounts):
            a = bytes(rng.integers(0, 256, 20, dtype=np.uint8))
            p.put_hashed_account(keccak256(a),
                                 Account(nonce=i, balance=7 + i))
            slots = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
                     for _ in range(slots_per)]
            for s in slots:
                p.put_hashed_storage(keccak256(a), keccak256(s), i + 1)
            targets[a] = slots
        full_state_root(p, CPU)
    return factory, targets


def _proof_key(ap):
    return (ap.proof, ap.storage_root,
            [(sp.key, sp.value, sp.proof) for sp in ap.storage_proofs])


def test_proof_pool_matches_direct_multiproof():
    """Sharded fetch across workers == one direct multiproof, proof for
    proof, in request slot order."""
    factory, targets = _storage_db()
    with factory.provider() as p:
        direct = ProofCalculator(p, CPU).multiproof(targets)
    pool = ProofWorkerPool(
        lambda: ProofCalculator(factory.provider(), CPU),
        workers=4)
    try:
        sharded = pool.multiproof(targets)
    finally:
        pool.shutdown()
    assert set(direct) == set(sharded)
    for a in direct:
        assert _proof_key(direct[a]) == _proof_key(sharded[a])
    assert pool.shards_total > 1  # it actually sharded


def test_proof_pool_splits_large_slot_list_in_order():
    """A single account with a big slot list splits across shards and
    merges back in the REQUEST's slot order (eth_getProof contract)."""
    factory, targets = _storage_db(n_accounts=1, slots_per=150, seed=9)
    with factory.provider() as p:
        direct = ProofCalculator(p, CPU).multiproof(targets)
    pool = ProofWorkerPool(
        lambda: ProofCalculator(factory.provider(), CPU),
        workers=4)
    try:
        sharded = pool.multiproof(targets)
    finally:
        pool.shutdown()
    (a, slots), = targets.items()
    assert [sp.key for sp in sharded[a].storage_proofs] == slots
    assert _proof_key(direct[a]) == _proof_key(sharded[a])
    assert pool.shards_total > 1


# -- fault drills (engine falls back to the incremental committer) -----------


def _engine_env():
    from tests.test_sparse_root_engine import busy_blocks, storage_env

    alice, builder, factory = storage_env()
    return busy_blocks(alice, builder, n=3), factory


def _feed(tree, blocks):
    from reth_tpu.engine.tree import PayloadStatusKind

    stats = []
    for blk in blocks:
        st = tree.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        stats.append(dict(tree.last_sparse))
        tree.on_forkchoice_updated(blk.hash)
    return stats


def test_sparse_abort_drill_falls_back(monkeypatch):
    """RETH_TPU_FAULT_SPARSE_ABORT kills the packed commit at a dispatch
    boundary mid-finish; every block must still validate via the
    incremental fallback (state_root_fallback semantics)."""
    from reth_tpu.engine import EngineTree

    monkeypatch.setenv("RETH_TPU_FAULT_SPARSE_ABORT", "1")
    blocks, factory = _engine_env()
    tree = EngineTree(factory, committer=CPU, persistence_threshold=1)
    stats = _feed(tree, blocks)
    assert all(s["strategy"] == "fallback" for s in stats), stats
    assert all("parallel commit failed" in s["error"] for s in stats)


def test_proof_wedge_drill_falls_back(monkeypatch):
    """RETH_TPU_FAULT_SPARSE_PROOF_WEDGE wedges every sharded proof
    fetch; the worker failure surfaces as SparseRootError at finish and
    the block validates on the fallback path."""
    from reth_tpu.engine import EngineTree

    monkeypatch.setenv("RETH_TPU_FAULT_SPARSE_PROOF_WEDGE", "1")
    blocks, factory = _engine_env()
    tree = EngineTree(factory, committer=CPU, persistence_threshold=1,
                      sparse_workers=4)
    stats = _feed(tree, blocks)
    # blocks whose proof fetch wedged fall back; ones with nothing to
    # fetch may still close sparse — either way every block validated
    assert any(s["strategy"] == "fallback" for s in stats), stats


def test_injector_env_parsing(monkeypatch):
    monkeypatch.delenv("RETH_TPU_FAULT_SPARSE_ABORT", raising=False)
    monkeypatch.delenv("RETH_TPU_FAULT_SPARSE_PROOF_WEDGE", raising=False)
    assert SparseFaultInjector.from_env() is None
    monkeypatch.setenv("RETH_TPU_FAULT_SPARSE_ABORT", "3")
    inj = SparseFaultInjector.from_env()
    assert inj.abort_at == 3
    inj.on_dispatch()
    inj.on_dispatch()
    with pytest.raises(Exception):
        inj.on_dispatch()
    inj.on_dispatch()  # one-shot: past the boundary it stays quiet


def test_sparse_workers_config_and_env(tmp_path, monkeypatch):
    """[node] sparse_workers TOML + RETH_TPU_SPARSE_WORKERS resolution."""
    from reth_tpu.config import load_config
    from reth_tpu.trie.sparse import sparse_worker_count

    f = tmp_path / "reth.toml"
    f.write_text("[node]\nsparse_workers = 6\n")
    assert load_config(f).sparse_workers == 6
    assert load_config(tmp_path / "absent.toml").sparse_workers == 0
    monkeypatch.setenv("RETH_TPU_SPARSE_WORKERS", "7")
    assert sparse_worker_count(None) == 7
    assert sparse_worker_count(3) == 3  # explicit beats env
    monkeypatch.delenv("RETH_TPU_SPARSE_WORKERS")
    assert sparse_worker_count(None) >= 1


def test_engine_records_parallel_commit_stats():
    """Sparse blocks carry the packed-commit stats (levels, dispatches)
    and the proof-pool shard count in last_sparse + /metrics."""
    from reth_tpu.engine import EngineTree
    from reth_tpu.metrics import REGISTRY

    blocks, factory = _engine_env()
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10,
                      sparse_workers=2)
    stats = _feed(tree, blocks)
    assert all(s["strategy"] == "sparse" for s in stats), stats
    for s in stats:
        assert s["sparse_workers"] == 2
        assert s["commit"]["dispatches"] >= 1
        assert s["commit"]["levels"] >= 1
    assert any(s["proof_shards"] > 0 for s in stats)
    rendered = REGISTRY.render()
    assert "sparse_commit_dispatches_total" in rendered
    assert "sparse_commit_finish_seconds" in rendered
