"""Declarative e2e scenarios over a live node (Action testsuite).

Reference analogue: crates/e2e-test-utils tests — ordered actions
driving a node: produce blocks, reorg, tamper payloads, assert state.
"""

import pytest

from reth_tpu.node import Node, NodeConfig
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.testing_actions import TestSuite as Suite
from reth_tpu.testing_actions import (
    ActionError,
    AssertBalance,
    AssertChainTip,
    AssertPoolSize,
    ProduceBlocks,
    ProduceInvalidPayload,
    ReorgTo,
    SubmitTransaction,
    WaitFor,
)
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)
BOB = b"\x0b" * 20


@pytest.fixture()
def node():
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)}, committer=CPU)
    n = Node(NodeConfig(dev=True, genesis_header=builder.genesis,
                        genesis_alloc=builder.accounts_at_genesis),
             committer=CPU)
    yield n, alice
    n.stop()


def test_produce_and_assert_scenario(node):
    n, alice = node
    Suite(n).run(
        SubmitTransaction(alice, to=BOB, value=100),
        AssertPoolSize(1),
        ProduceBlocks(1),
        AssertChainTip(1),
        AssertBalance(BOB, 100),
        AssertPoolSize(0),
        SubmitTransaction(alice, to=BOB, value=50),
        ProduceBlocks(2),
        AssertChainTip(3),
        AssertBalance(BOB, 150),
    )


def test_reorg_scenario(node):
    n, alice = node
    Suite(n).run(
        SubmitTransaction(alice, to=BOB, value=100),
        ProduceBlocks(3),
        AssertChainTip(3),
        ReorgTo(1),
        AssertChainTip(1),
        AssertBalance(BOB, 100),  # tx was in block 1: survives the reorg
    )


def test_invalid_payload_scenario(node):
    n, alice = node

    def break_root(block):
        from dataclasses import replace

        bad_header = replace(block.header, state_root=b"\x13" * 32)
        return type(block)(bad_header, block.transactions, block.ommers,
                           block.withdrawals)

    Suite(n).run(
        ProduceBlocks(1),
        ProduceInvalidPayload(break_root),
        AssertChainTip(1),  # the bad payload never became canonical
    )


def test_failed_assertion_reports_action(node):
    n, alice = node
    with pytest.raises(ActionError, match="action #1 AssertChainTip"):
        Suite(n).run(ProduceBlocks(1), AssertChainTip(5))


def test_waitfor_polls(node):
    n, alice = node
    Suite(n).run(
        SubmitTransaction(alice, to=BOB, value=1),
        WaitFor(lambda nd: len(nd.pool) == 1),
    )
