"""Whole-subtrie fused tree-hash kernels (ops/fused_commit.py
SubtrieFusedEngine / SubtrieMeshEngine): ONE device dispatch per chunk of
k staged levels, not one per depth.

The acceptance drills, on the virtual 8-device CPU mesh (conftest):

- randomized k-level differential sweep: k x depth x mesh-size grid
  (including the non-pow2 6/3-device meshes) vs the per-level engines and
  the numpy twin — roots and TrieUpdates bit-identical (the compile-heavy
  full grid rides ``make test-subtrie`` via @slow; tier-1 pins the small
  corners);
- fault drills: RETH_TPU_FAULT_SUBTRIE_WEDGE proves a mid-kernel chunk
  failure replays the staged journal bit-identically on the per-level
  path; RETH_TPU_FAULT_SUBTRIE_ABORT poisons the device path entirely and
  proves the CPU-twin rung;
- the hoisted ladder-caps fix: a 64-level window with branch-heavy
  (hole-dense) near-root levels never mints an off-menu batch tier
  (extends the PR 10 ladder-clamp tests), and the memoized caps stay
  exact when tests mutate the ceilings post-init;
- warm-up integration: the menu declares (fused.subtrie, k, tier, mesh)
  shapes, and an un-warm k-shape routes the commit to the per-level path
  instead of compiling mid-commit;
- hash-service window requests: a pre-packed multi-level window runs as
  one fused dispatch on the live lane, with numpy replay on a wedge.
"""

from __future__ import annotations

import numpy as np
import pytest

from reth_tpu.metrics import MetricsRegistry, fused_metrics
from reth_tpu.ops.fused_commit import (
    FusedLevelEngine,
    SubtrieFaultInjector,
    SubtrieFusedEngine,
    SubtrieMeshEngine,
)
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.primitives.rlp import rlp_encode


def _job(n: int, seed: int):
    r = np.random.default_rng(seed)
    keys = r.integers(0, 256, (n, 32), dtype=np.uint8)
    vals = [rlp_encode(bytes(r.integers(0, 256, size=int(r.integers(1, 60)),
                                        dtype=np.uint8))) for _ in range(n)]
    return keys, vals


def _leaf_rows(seed: int, n: int = 24, lo: int = 1, hi: int = 130):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, size=int(rng.integers(lo, hi)),
                         dtype=np.uint8).tobytes() for _ in range(n)]


def _run_leaf_levels(eng, rows, per_level: int = 8):
    """Drive ``rows`` through the engine as hole-free packed levels of
    ``per_level`` rows each; returns (digest buffer, slots)."""
    eng.begin(len(rows) + 1)
    slots = np.array([eng.alloc_slot() for _ in rows], dtype=np.int32)
    flat = np.frombuffer(b"".join(rows), dtype=np.uint8)
    row_len = np.array([len(r) for r in rows], dtype=np.uint32)
    row_off = (np.cumsum(row_len) - row_len).astype(np.uint32)
    for lo in range(0, len(rows), per_level):
        hi = min(lo + per_level, len(rows))
        base = int(row_off[lo])
        end = int(row_off[hi - 1] + row_len[hi - 1])
        eng.dispatch_packed(flat[base:end], row_off[lo:hi] - base,
                            row_len[lo:hi], slots[lo:hi], None, b_tier=1)
    return eng.finish(), slots


def _small_engine(**kw):
    kw.setdefault("min_tier", 8)
    kw.setdefault("row_floor", 32)
    kw.setdefault("hole_floor", 32)
    return SubtrieFusedEngine(**kw)


# -- engine-level parity -------------------------------------------------------


def test_subtrie_leaf_levels_match_reference():
    rows = _leaf_rows(1)
    eng = _small_engine(k=8)
    d, slots = _run_leaf_levels(eng, rows)
    for s, r in zip(slots, rows):
        assert d[s].tobytes() == keccak256(r)
    # 3 staged levels fused into one dispatch at k=8
    assert eng.levels_staged == 3
    assert eng.dispatches == 1


def test_subtrie_parent_composition_across_chunks():
    """Holes reference digests written by EARLIER steps of the same fused
    program (the in-kernel carry) and by earlier chunks/windows (the
    resident buffer)."""
    child = b"\x55" * 44
    eng = _small_engine(k=2)
    eng.begin(8)
    s_child = eng.alloc_slot()
    eng.dispatch_packed(np.frombuffer(child, np.uint8),
                        np.zeros((1,), np.uint32),
                        np.array([len(child)], np.uint32),
                        np.array([s_child], np.int32), None, 1)
    eng.flush_window()  # child lands in the resident buffer
    prefix = b"\xc0" * 7
    tmpl = prefix + b"\xa0" + b"\x00" * 32
    s_mid = eng.alloc_slot()
    eng.dispatch_packed(np.frombuffer(tmpl, np.uint8),
                        np.zeros((1,), np.uint32),
                        np.array([len(tmpl)], np.uint32),
                        np.array([s_mid], np.int32),
                        np.array([[0], [len(prefix) + 1], [s_child]],
                                 np.int32), 1)
    s_top = eng.alloc_slot()
    eng.dispatch_packed(np.frombuffer(tmpl, np.uint8),
                        np.zeros((1,), np.uint32),
                        np.array([len(tmpl)], np.uint32),
                        np.array([s_top], np.int32),
                        np.array([[0], [len(prefix) + 1], [s_mid]],
                                 np.int32), 1)
    d = eng.finish()
    mid = keccak256(prefix + b"\xa0" + keccak256(child))
    assert d[s_mid].tobytes() == mid
    assert d[s_top].tobytes() == keccak256(prefix + b"\xa0" + mid)


def test_subtrie_branch_step_matches_numpy_twin():
    from reth_tpu.trie.turbo import _NumpyBackend

    rows = _leaf_rows(3, n=4, lo=40, hi=60)
    masks = np.array([0x0013, 0x8001], dtype=np.uint16)
    children = np.array([[0, 0, 0, 1, 1],
                         [0, 1, 4, 0, 15],
                         [1, 2, 3, 4, 2]], dtype=np.int32)

    def drive(eng):
        eng.begin(8)
        slots = np.array([eng.alloc_slot() for _ in rows], np.int32)
        flat = np.frombuffer(b"".join(rows), np.uint8)
        rl = np.array([len(r) for r in rows], np.uint32)
        ro = (np.cumsum(rl) - rl).astype(np.uint32)
        eng.dispatch_packed(flat, ro, rl, slots, None, 1)
        bslots = np.array([eng.alloc_slot(), eng.alloc_slot()], np.int32)
        eng.dispatch_branch(masks, bslots, children)
        return eng.finish()

    want = drive(_NumpyBackend())
    got = drive(_small_engine(k=8))
    # slot 0 is the dummy padding target (engine-private garbage);
    # every REAL slot must match the numpy twin bit-for-bit
    assert got[1:want.shape[0]].tobytes() == want[1:].tobytes()


# -- k x depth x mesh differential grid ---------------------------------------


def _turbo_differential(k: int, mesh_n: int, seeds, min_tier: int = 16):
    import jax
    from jax.sharding import Mesh

    from reth_tpu.trie.turbo import TurboCommitter

    mesh = (Mesh(np.array(jax.devices()[:mesh_n]), ("data",))
            if mesh_n > 1 else None)
    dev = TurboCommitter(backend="device", min_tier=min_tier, mesh=mesh,
                         subtrie_levels=k)
    cpu = TurboCommitter(backend="numpy")
    for seed in seeds:
        jobs = [_job(int(n), seed * 10 + i)
                for i, n in enumerate((130, 50, 9, 1))]
        got = dev.commit_hashed_many(jobs, collect_branches=True)
        want = cpu.commit_hashed_many(jobs, collect_branches=True)
        assert [r.root for r in got] == [r.root for r in want]
        assert [r.branch_nodes for r in got] == [r.branch_nodes for r in want]
        got_p = dev.commit_hashed_pipelined(jobs)
        assert [r.root for r in got_p] == [r.root for r in want]


def test_turbo_subtrie_differential_single_device():
    """Tier-1 corner of the grid: k=4 on one device, roots + TrieUpdates
    bit-identical to the numpy twin, and the commit's dispatch count
    lands in the fused histogram."""
    _turbo_differential(4, 1, seeds=(1,))
    last = fused_metrics.last
    assert last is not None and last["k"] == 4 and last["mode"] == "fused"
    assert last["dispatches"] < last["levels"]


@pytest.mark.slow
def test_turbo_subtrie_differential_grid():
    """The full randomized k x mesh grid, incl. the non-pow2 6/3-device
    meshes whose tier ladders leave the pow2 grid (make test-subtrie —
    compile-heavy)."""
    for k in (1, 2, 8):
        _turbo_differential(k, 1, seeds=(k,))
    for mesh_n in (2, 3, 6, 8):
        _turbo_differential(8, mesh_n, seeds=(mesh_n,), min_tier=18)
    _turbo_differential(2, 6, seeds=(3,), min_tier=18)


def test_subtrie_mesh_engine_parity_small():
    """Fast mesh corner: the k-level SPMD variant on 2 and 3 devices is
    bit-identical to the single-device engine."""
    import jax
    from jax.sharding import Mesh

    rows = _leaf_rows(7)
    d0, s0 = _run_leaf_levels(_small_engine(k=4), rows)
    for n in (2, 3):
        mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
        eng = SubtrieMeshEngine(mesh, min_tier=8, k=4, row_floor=32,
                                hole_floor=32)
        d, s = _run_leaf_levels(eng, rows)
        assert all(d[a].tobytes() == d0[b].tobytes()
                   for a, b in zip(s, s0))


# -- fault drills --------------------------------------------------------------


def test_subtrie_wedge_replays_per_level(monkeypatch):
    """RETH_TPU_FAULT_SUBTRIE_WEDGE: the wedged chunk replays the staged
    journal on the per-level path, digests bit-identical."""
    monkeypatch.setenv("RETH_TPU_FAULT_SUBTRIE_WEDGE", "1")
    inj = SubtrieFaultInjector.from_env()
    assert inj is not None and inj.wedge_at == 1
    rows = _leaf_rows(11)
    d0, s0 = _run_leaf_levels(_small_engine(k=8), rows)
    eng = _small_engine(k=8, injector=inj)
    d1, s1 = _run_leaf_levels(eng, rows)
    assert all(d1[a].tobytes() == d0[b].tobytes() for a, b in zip(s1, s0))
    assert eng._mode == "perlevel" and inj.wedges == 1
    assert eng.dispatches == eng.levels_staged  # one per level on replay


def test_subtrie_abort_lands_on_cpu_twin(monkeypatch):
    """RETH_TPU_FAULT_SUBTRIE_ABORT: fused AND per-level replays fail —
    the journal replays on the CPU twin, digests bit-identical."""
    monkeypatch.setenv("RETH_TPU_FAULT_SUBTRIE_ABORT", "1")
    inj = SubtrieFaultInjector.from_env()
    rows = _leaf_rows(13)
    d0, s0 = _run_leaf_levels(_small_engine(k=8), rows)
    eng = _small_engine(k=8, injector=inj)
    d1, s1 = _run_leaf_levels(eng, rows)
    assert all(d1[a].tobytes() == d0[b].tobytes() for a, b in zip(s1, s0))
    assert eng._mode == "cpu" and inj.aborts == 1


def test_subtrie_wedge_mid_pipeline_turbo():
    """The wedge drill through the REAL consumer: a pipelined turbo
    rebuild whose k-level backend wedges mid-commit still produces roots
    bit-identical to the numpy committer."""
    from reth_tpu.trie.turbo import TurboCommitter

    jobs = [_job(60, 77), _job(25, 78)]
    cpu = TurboCommitter(backend="numpy")
    want = [r.root for r in cpu.commit_hashed_many(jobs)]
    dev = TurboCommitter(backend="device", min_tier=16, subtrie_levels=4)
    orig = dev._device_engine

    def wedged_engine():
        eng = orig()
        eng.injector = SubtrieFaultInjector(wedge_at=1)
        return eng

    dev._device_engine = wedged_engine
    got = [r.root for r in dev.commit_hashed_pipelined(jobs)]
    assert got == want
    assert fused_metrics.last["mode"] == "perlevel"


# -- hoisted ladder caps (PR 10 ladder-clamp extension) ------------------------


def test_row_cap_memo_tracks_ceiling_mutation():
    assert FusedLevelEngine(min_tier=1024)._row_cap() == 65536  # at __init__
    eng = FusedLevelEngine(min_tier=18)
    assert eng._row_cap() == 18432  # ladder 18→72→…→18432 under 65536
    eng.MAX_BATCH_ROWS = 100  # tests mutate ceilings post-init: memo keys
    assert eng._row_cap() == 72
    assert eng._hole_budget(65) == 4 * 72  # ladder lookup, not a walk
    assert eng._hole_budget(1) == 4 * 18


def test_64_level_branch_heavy_window_stays_on_menu():
    """A 64-level window with hole-dense near-root levels never mints an
    off-menu batch tier: every split lands ON the hoisted ladder (the
    in-engine _check_batch_tier assertion is the guard) and digests stay
    bit-identical to the reference keccak across the splits."""
    rng = np.random.default_rng(5)
    eng = _small_engine(k=8)
    eng.MAX_BATCH_ROWS = 16  # row cap 8: every 12-row level splits
    assert eng._row_cap() == 8
    eng.begin(64 * 12 + 1)
    prev_slots: list[int] = []
    expected: dict[int, bytes] = {}
    prev_hashes: list[bytes] = []
    for depth in range(64):
        rows, holes_r, holes_b, holes_s = [], [], [], []
        slots = []
        hashes = []
        for i in range(12):
            s = eng.alloc_slot()
            slots.append(s)
            if depth and i < 10:  # branch-heavy: most rows splice a child
                prefix = bytes(rng.integers(0, 256, 8, dtype=np.uint8))
                child = (depth - 1) * 12 + i
                rows.append(prefix + b"\xa0" + b"\x00" * 32)
                holes_r.append(i)
                holes_b.append(len(prefix) + 1)
                holes_s.append(prev_slots[i])
                real = prefix + b"\xa0" + prev_hashes[i]
                del child
            else:
                real = bytes(rng.integers(0, 256,
                                          int(rng.integers(33, 100)),
                                          dtype=np.uint8))
                rows.append(real)
            hashes.append(keccak256(real))
            expected[s] = hashes[-1]
        flat = np.frombuffer(b"".join(rows), np.uint8)
        rl = np.array([len(r) for r in rows], np.uint32)
        ro = (np.cumsum(rl) - rl).astype(np.uint32)
        holes = (np.array([holes_r, holes_b, holes_s], np.int32)
                 if holes_r else None)
        eng.dispatch_packed(flat, ro, rl, np.array(slots, np.int32),
                            holes, 1)
        prev_slots, prev_hashes = slots, hashes
    d = eng.finish()
    for s, h in expected.items():
        assert d[s].tobytes() == h
    assert eng.levels_staged >= 64  # row-cap splits multiplied the steps
    assert eng.dispatches < eng.levels_staged  # ...and chunks still fused


# -- warm-up integration -------------------------------------------------------


def test_menu_declares_subtrie_shapes():
    from reth_tpu.ops.warmup import default_menu

    menu = default_menu(subtrie_ks=(8,), mesh_sizes=(4,))
    keys = [s.key() for s in menu]
    assert ("fused.subtrie", 8, 2048, 1) in keys
    assert ("fused.subtrie", 8, 2048, 4) in keys
    assert str([s for s in menu if s.program == "fused.subtrie"][0]) \
        == "fused.subtrie:8x2048"


def test_unwarm_k_shape_routes_per_level():
    from reth_tpu.ops.warmup import MenuShape, WarmupManager

    mgr = WarmupManager(menu=[MenuShape("fused.subtrie", 8, 32, 1)],
                        enable_cache=False, registry=MetricsRegistry())
    mgr._active = True  # warm-up started, nothing warm yet
    rows = _leaf_rows(21)
    eng = _small_engine(k=8, warmup=mgr)
    d, s = _run_leaf_levels(eng, rows)
    for a, r in zip(s, rows):
        assert d[a].tobytes() == keccak256(r)
    assert eng.dispatches == eng.levels_staged  # degraded: one per level
    assert eng._mode == "fused"  # degraded ROUTING, not a failover
    # promote the shape: the same engine shape fuses again
    mgr.states[("fused.subtrie", 8, 32, 1)] = "warm"
    mgr._done.set()
    eng2 = _small_engine(k=8, warmup=mgr)
    d2, s2 = _run_leaf_levels(eng2, rows)
    assert all(d2[a].tobytes() == d[b].tobytes() for a, b in zip(s2, s))
    assert eng2.dispatches < eng2.levels_staged


@pytest.mark.slow
def test_warmup_builds_subtrie_shape():
    from reth_tpu.ops.warmup import MenuShape, _build_shape

    _build_shape(MenuShape("fused.subtrie", 8, 32, 1))
    _build_shape(MenuShape("fused.subtrie", 4, 32, 2))


# -- sparse finish (multi-level dispatch per finish) --------------------------


def _sparse_state(seed: int, tries: int = 10, slots: int = 24):
    from reth_tpu.trie.sparse import SparseStateTrie

    rng = np.random.default_rng(seed)
    st = SparseStateTrie()
    for _ in range(tries):
        ha = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        t = st.storage_trie(ha)
        keys = [bytes(rng.integers(0, 256, 32, dtype=np.uint8))
                for _ in range(slots)]
        for k in keys:
            t.update(k, bytes(rng.integers(1, 256, 8, dtype=np.uint8)))
        t.delete(keys[0])
        st.update_account(ha, b"account-leaf-" + ha)
    return st


def _sparse_committer(k: int = 8):
    from reth_tpu.trie.sparse import ParallelSparseCommitter

    c = ParallelSparseCommitter(subtrie_levels=k)
    c.SUBTRIE_ROW_FLOOR = 64
    c.SUBTRIE_HOLE_FLOOR = 64
    return c


@pytest.mark.parametrize("seed", [4, 5])
def test_sparse_fused_finish_parity(seed):
    st_serial = _sparse_state(seed)
    st_fused = _sparse_state(seed)
    want = st_serial.root(keccak256_batch_np)
    c = _sparse_committer()
    got = st_fused.root(keccak256_batch_np, committer=c)
    assert got == want
    assert c.last["subtrie_k"] == 8
    assert c.last["dispatches"] <= -(-c.last["levels"] // 8) + 1
    # second block: dirty subset + cross-block clean-ref reuse
    for st in (st_serial, st_fused):
        r = np.random.default_rng(seed + 100)
        for ha, t in list(st.storage_tries.items())[:3]:
            for _ in range(4):
                t.update(bytes(r.integers(0, 256, 32, dtype=np.uint8)),
                         b"\x07\x08")
            st.update_account(ha, b"post-" + ha)
    assert st_fused.root(keccak256_batch_np, committer=c) \
        == st_serial.root(keccak256_batch_np)


def test_sparse_fused_preserves_abort_drill():
    """RETH_TPU_FAULT_SPARSE_ABORT still fires on the fused path (the
    engine-strategy fallback contract is unchanged)."""
    from reth_tpu.trie.sparse import InjectedSparseAbort, SparseFaultInjector

    st = _sparse_state(6)
    c = _sparse_committer()
    c.injector = SparseFaultInjector(abort_at=1)
    with pytest.raises(InjectedSparseAbort):
        st.root(keccak256_batch_np, committer=c)


# -- hash-service multi-level windows -----------------------------------------


def _window_levels():
    rows = [b"\x11" * 45, b"\x22" * 50]
    lv1 = {"flat": np.frombuffer(b"".join(rows), np.uint8),
           "row_off": np.array([0, 45], np.uint32),
           "row_len": np.array([45, 50], np.uint32),
           "slots": np.array([1, 2], np.int32),
           "holes": None, "b_tier": 1}
    parent = b"\xc1" * 6 + b"\xa0" + b"\x00" * 32
    lv2 = {"flat": np.frombuffer(parent, np.uint8),
           "row_off": np.array([0], np.uint32),
           "row_len": np.array([len(parent)], np.uint32),
           "slots": np.array([3], np.int32),
           "holes": np.array([[0], [7], [2]], np.int32), "b_tier": 1}
    want = {1: keccak256(rows[0]), 2: keccak256(rows[1]),
            3: keccak256(parent[:7] + keccak256(rows[1]))}
    return [lv1, lv2], want


def test_service_window_one_fused_dispatch():
    from reth_tpu.ops.hash_service import HashService

    svc = HashService(backend=keccak256_batch_np,
                      registry=MetricsRegistry(), min_tier=16,
                      subtrie_levels=8)
    try:
        window, want = _window_levels()
        buf = svc.client("live").commit_window(window, 3)
        for s, h in want.items():
            assert buf[s].tobytes() == h
        assert svc.window_dispatches == 1
        # plain traffic still coalesces beside windows
        assert svc.client("proof")([b"abc"])[0] == keccak256(b"abc")
    finally:
        svc.stop()


def test_service_window_wedge_replays_on_numpy():
    from reth_tpu.ops.hash_service import HashService, ServiceFaultInjector

    svc = HashService(backend=keccak256_batch_np,
                      registry=MetricsRegistry(), min_tier=16,
                      subtrie_levels=8,
                      injector=ServiceFaultInjector(wedge_every=1))
    try:
        window, want = _window_levels()
        fut = svc.submit_window("live", window, 3)
        buf = fut.result(timeout=30)
        for s, h in want.items():
            assert buf[s].tobytes() == h
        assert fut.completions == 1
        assert svc.replays == 1
    finally:
        svc.stop()


def test_sparse_fused_streams_through_service_window():
    """The live-tip finish with a lane-bound HashClient hasher rides the
    service's window lane — one fused dispatch per finish."""
    from reth_tpu.ops.hash_service import HashService

    st_serial = _sparse_state(8, tries=6, slots=16)
    st_fused = _sparse_state(8, tries=6, slots=16)
    want = st_serial.root(keccak256_batch_np)
    svc = HashService(backend=keccak256_batch_np,
                      registry=MetricsRegistry(), min_tier=16,
                      subtrie_levels=8)
    try:
        got = st_fused.root(svc.client("live"),
                            committer=_sparse_committer())
        assert got == want
        assert svc.window_dispatches == 1
    finally:
        svc.stop()
