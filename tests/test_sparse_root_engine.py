"""Engine sparse-trie live-tip state-root strategy tests.

Reference analogue: the state-root strategy + task tests
(crates/engine/tree/src/tree/state_root_strategy/sparse_trie.rs,
crates/trie/parallel/src/state_root_task.rs tests): root equality vs the
committer on storage-heavy / selfdestruct / reorg chains, preserved-trie
reuse across consecutive payloads (chain-state PreservedSparseTrie), the
incremental fallback (config.rs:140 state_root_fallback), and stored
trie-update equivalence with the database walk.
"""

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.engine.sparse_root import SparseRootError, SparseRootTask
from reth_tpu.engine.tree import PayloadStatusKind
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256, keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.storage.tables import Tables
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)

# store(slot=calldata[0], value=calldata[32]):
#   PUSH1 0x20 CALLDATALOAD PUSH0 CALLDATALOAD SSTORE STOP
STORE_CODE = bytes.fromhex("6020355f355500")
STORE_HASH = keccak256(STORE_CODE)
STORE_ADDR = b"\x51" * 20
# initcode: SSTORE(1, 7) then SELFDESTRUCT(caller) — a same-tx
# create+write+destroy populates changes.wiped_storage (EIP-6780)
WIPE_INITCODE = bytes.fromhex("600760015533ff")


def store_call(wallet, slot: int, value: int):
    data = slot.to_bytes(32, "big") + value.to_bytes(32, "big")
    return wallet.call(STORE_ADDR, data, gas_limit=200_000)


def storage_env(n_extra: int = 48):
    """Genesis with a storage-heavy contract + enough accounts for the
    account trie to have real branch structure."""
    alice = Wallet(0xA11CE)
    alloc = {
        alice.address: Account(balance=10**21),
        STORE_ADDR: Account(code_hash=STORE_HASH),
    }
    for i in range(1, n_extra + 1):
        alloc[i.to_bytes(20, "big")] = Account(balance=i)
    storage = {STORE_ADDR: {j.to_bytes(32, "big"): j + 1 for j in range(1, 30)}}
    builder = ChainBuilder(alloc, storage, codes={STORE_HASH: STORE_CODE},
                           committer=CPU)
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 storage=builder.storage_at_genesis,
                 codes=builder.codes_at_genesis, committer=CPU)
    return alice, builder, factory


def busy_blocks(alice, builder, n: int = 5):
    """Blocks mixing storage writes, slot zeroing (trie collapses),
    transfers (account trie churn), and a same-tx create+selfdestruct."""
    for i in range(n):
        txs = [
            store_call(alice, 100 + i, 0xBEEF + i),   # fresh slot
            store_call(alice, 1 + i, 0),              # zero an existing slot
            alice.transfer((0xE0 + i).to_bytes(20, "big"), 10**15),
        ]
        if i == 2:
            txs.append(alice.deploy(WIPE_INITCODE))   # wiped-storage path
        builder.build_block(txs)
    return builder.blocks[1:]


def feed(tree, blocks):
    stats = []
    for blk in blocks:
        st = tree.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        stats.append(dict(tree.last_sparse))
        tree.on_forkchoice_updated(blk.hash)
    return stats


def test_sparse_strategy_computes_roots():
    """Every busy block's root comes from the SPARSE path (not fallback)
    and matches the committer-built header root."""
    alice, builder, factory = storage_env()
    blocks = busy_blocks(alice, builder)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    stats = feed(tree, blocks)
    assert all(s["strategy"] == "sparse" for s in stats), stats
    assert any(s["proof_batches"] > 0 for s in stats)


def test_prewarm_seeds_sparse_proof_prefetch():
    """With the sparse strategy, the prewarm workers stream their touched
    keys into the sparse task as they finish (key-only mode, independent
    of BAL), so multiproof fetch overlaps prewarm — and the speculative
    extras never change the computed roots."""
    alice, builder, factory = storage_env()
    blocks = busy_blocks(alice, builder)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=2)
    tree.prewarm_threshold = 1  # every busy block prewarms
    stats = feed(tree, blocks)
    assert all(s["strategy"] == "sparse" for s in stats), stats
    assert tree.last_prewarm is not None
    assert tree.last_prewarm.key_sink is not None
    assert tree.last_prewarm.streamed_keys > 0
    # the sink fed real OnStateHook-shaped keys: the storage contract's
    # address must have been streamed by the store_call workers
    assert tree.last_prewarm.warmed > 0


def test_preserved_trie_reuse_across_payloads():
    """Consecutive payloads reuse the preserved sparse trie (hit on every
    block after the first)."""
    alice, builder, factory = storage_env()
    blocks = busy_blocks(alice, builder)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    stats = feed(tree, blocks)
    assert stats[0]["reused"] is False
    assert all(s["reused"] is True for s in stats[1:]), stats
    assert tree.preserved_trie.hits >= len(blocks) - 1


def test_fallback_fires_and_stays_correct(monkeypatch):
    """A SparseRootError falls back to the incremental committer and the
    block still validates (reference state_root_fallback)."""
    alice, builder, factory = storage_env()
    blocks = busy_blocks(alice, builder, n=3)

    def boom(self, out):
        self.abort()
        raise SparseRootError("injected failure")

    monkeypatch.setattr(SparseRootTask, "finish", boom)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=1)
    stats = feed(tree, blocks)
    assert all(s["strategy"] == "fallback" for s in stats)
    # the fallback wrote real state: overlay view reflects the writes
    ov = tree.overlay_provider()
    assert ov.account((0xE0).to_bytes(20, "big")).balance == 10**15


def _dump_tables(factory):
    out = {}
    with factory.provider() as p:
        for t in (Tables.AccountsTrie, Tables.StoragesTrie,
                  Tables.HashedAccounts, Tables.HashedStorages):
            out[t.name] = sorted(p.tx.cursor(t.name).walk())
    return out


def test_stored_updates_equal_incremental_walk():
    """The branch updates exported from the sparse trie leave the DB
    byte-identical to the incremental committer's re-walk — the stored
    trie, hashed tables included (settles the delete-marker question)."""
    alice_a, builder_a, factory_a = storage_env()
    blocks = busy_blocks(alice_a, builder_a)
    # same chain replayed into a second, independent env
    alice_b = Wallet(0xA11CE)
    _, _, factory_b = storage_env()

    tree_a = EngineTree(factory_a, committer=CPU, persistence_threshold=0,
                        state_root_strategy="sparse")
    tree_b = EngineTree(factory_b, committer=CPU, persistence_threshold=0,
                        state_root_strategy="pipelined")
    feed(tree_a, blocks)
    for blk in blocks:
        st = tree_b.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        tree_b.on_forkchoice_updated(blk.hash)
    assert tree_a.persisted_number == tree_b.persisted_number == len(blocks)
    assert _dump_tables(factory_a) == _dump_tables(factory_b)
    # and the persisted stored structure supports a further incremental
    # root: one more block replayed on top of the sparse-written DB
    more = busy_blocks(alice_a, builder_a, n=1)
    tree_a2 = EngineTree(factory_a, committer=CPU, persistence_threshold=0,
                         state_root_strategy="pipelined")
    for blk in more:
        st = tree_a2.on_new_payload(blk)
        assert st.status is PayloadStatusKind.VALID, st.validation_error
        tree_a2.on_forkchoice_updated(blk.hash)


def test_reorg_invalidates_preserved_trie():
    """A fork flip anchors the next payload on a different parent: the
    preserved trie must not be reused, and roots stay correct."""
    alice, builder, factory = storage_env()
    fork_a = builder.build_block([store_call(alice, 200, 111)])

    alice_b = Wallet(0xA11CE)
    alloc = {
        alice_b.address: Account(balance=10**21),
        STORE_ADDR: Account(code_hash=STORE_HASH),
    }
    for i in range(1, 49):
        alloc[i.to_bytes(20, "big")] = Account(balance=i)
    storage = {STORE_ADDR: {j.to_bytes(32, "big"): j + 1 for j in range(1, 30)}}
    builder_b = ChainBuilder(alloc, storage, codes={STORE_HASH: STORE_CODE},
                             committer=CPU)
    fork_b = builder_b.build_block([store_call(alice_b, 200, 222)],
                                   timestamp=24)
    next_b = builder_b.build_block([store_call(alice_b, 201, 333)])

    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    assert tree.on_new_payload(fork_a).status is PayloadStatusKind.VALID
    assert tree.last_sparse["strategy"] == "sparse"
    assert tree.on_new_payload(fork_b).status is PayloadStatusKind.VALID
    # fork_b's parent is genesis, but the preserved trie is anchored at
    # fork_a — no reuse, fresh anchor
    assert tree.last_sparse["reused"] is False
    tree.on_forkchoice_updated(fork_b.hash)
    st = tree.on_new_payload(next_b)
    assert st.status is PayloadStatusKind.VALID, st.validation_error
    # next_b extends fork_b, whose trie was preserved last
    assert tree.last_sparse["reused"] is True
    tree.on_forkchoice_updated(next_b.hash)
    assert tree.overlay_provider().storage(
        STORE_ADDR, (201).to_bytes(32, "big")) == 333


def test_invalid_block_does_not_poison_preserved_trie():
    """A payload rejected on state-root mismatch must not preserve its
    mutated trie; the next valid payload still computes correct roots."""
    from reth_tpu.primitives.types import Block, Header

    alice, builder, factory = storage_env()
    blocks = busy_blocks(alice, builder, n=2)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    feed(tree, [blocks[0]])
    good = blocks[1]
    bad_header = Header(**{**good.header.__dict__, "state_root": b"\x13" * 32})
    bad = Block(bad_header, good.transactions, (), good.withdrawals)
    st = tree.on_new_payload(bad)
    assert st.status is PayloadStatusKind.INVALID
    assert "state root mismatch" in st.validation_error
    # the real block still validates on the sparse path afterwards
    st2 = tree.on_new_payload(good)
    assert st2.status is PayloadStatusKind.VALID, st2.validation_error
    assert tree.last_sparse["strategy"] == "sparse"


def test_sparse_overlap_metrics_recorded():
    """Round-5 directive: every sparse block records its wall breakdown
    (proof/reveal/finish/worker_busy) and overlap fraction — the honest
    measurement of how much trie work ran while the EVM executed."""
    alice, builder, factory = storage_env()
    blocks = busy_blocks(alice, builder)
    tree = EngineTree(factory, committer=CPU, persistence_threshold=10)
    stats = feed(tree, blocks)
    for m in stats:
        assert m["strategy"] == "sparse"
        for key in ("proof", "reveal", "finish", "worker_busy",
                    "exec_wall", "overlap_fraction"):
            assert key in m, key
        assert 0.0 <= m["overlap_fraction"] <= 1.0
        assert m["finish"] >= 0.0
