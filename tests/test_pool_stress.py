"""Saturated-pool + batcher stress tests.

Reference analogue: benches/saturated_pool.rs (insertion behavior at max
capacity) + batcher.rs tests (concurrent batched insertion) + the
discard_worst semantics in pool/txpool.rs:1232.
"""

from __future__ import annotations

import threading

import pytest

from reth_tpu.engine import EngineTree
from reth_tpu.pool import PoolConfig, PoolError, TransactionPool, TxBatcher
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


def make_pool(n_senders: int, max_pool: int):
    wallets = [Wallet(0x50000 + i) for i in range(n_senders)]
    alloc = {w.address: Account(balance=10**20) for w in wallets}
    builder = ChainBuilder(alloc, committer=CPU)
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    tree = EngineTree(factory, committer=CPU)
    pool = TransactionPool(lambda: tree.overlay_provider(),
                           PoolConfig(max_pool_size=max_pool,
                                      max_account_slots=64))
    pool.base_fee = 10**9
    return wallets, pool


def tip_tx(w, tip_gwei: int):
    return w.transfer(b"\x99" * 20, 1, max_fee_per_gas=1000 * 10**9,
                      max_priority_fee_per_gas=tip_gwei * 10**9)


def test_saturated_pool_discards_worst():
    """A full pool admits better-paying txs by evicting the worst, and
    rejects underpriced ones — size stays bounded throughout."""
    wallets, pool = make_pool(n_senders=300, max_pool=100)
    # fill with tips 1..100 gwei (one tx per sender)
    for i in range(100):
        pool.add_transaction(tip_tx(wallets[i], 1 + i))
    assert len(pool) == 100
    # underpriced: tip below the current worst (1 gwei) -> rejected
    with pytest.raises(PoolError, match="underpriced"):
        pool.add_transaction(tip_tx(wallets[200], 0))
    # 150 better-paying txs: each evicts the then-worst; size stays capped
    for i in range(150):
        pool.add_transaction(tip_tx(wallets[100 + i], 200 + i))
        assert len(pool) <= 100
    assert len(pool) == 100
    tips = sorted(p.effective_tip(pool.base_fee) // 10**9
                  for p in pool.by_hash.values())
    # the survivors are the 100 best-paying: the 1..100 gwei originals and
    # the weakest third of the 200-tier were all evicted in turn
    assert tips[0] >= 250 and all(t >= 250 for t in tips)


def test_discard_worst_drops_descendants():
    """Evicting a sender's tx also drops their later nonces (gapped)."""
    wallets, pool = make_pool(n_senders=10, max_pool=4)
    victim = wallets[0]
    pool.add_transaction(tip_tx(victim, 1))        # nonce 0, worst
    pool.add_transaction(tip_tx(victim, 300))      # nonce 1 (descendant)
    pool.add_transaction(tip_tx(wallets[1], 5))
    pool.add_transaction(tip_tx(wallets[2], 5))
    assert len(pool) == 4
    pool.add_transaction(tip_tx(wallets[3], 50))   # evicts victim nonce 0
    # the descendant went with it: no nonce-gapped orphan remains
    assert victim.address not in pool.by_sender
    assert len(pool) == 3


def test_batcher_concurrent_submissions():
    """Many threads submitting through the batcher: every future resolves,
    the pool holds exactly the valid set, and batching actually occurred
    (fewer batches than transactions)."""
    wallets, pool = make_pool(n_senders=120, max_pool=10_000)
    batcher = TxBatcher(pool, max_batch=64)
    txs = []
    for w in wallets:
        for n in range(3):
            txs.append(w.transfer(b"\x88" * 20, 1 + n))
    futures = []
    fut_lock = threading.Lock()

    def submit(chunk):
        for t in chunk:
            f = batcher.submit(t)
            with fut_lock:
                futures.append(f)

    threads = [threading.Thread(target=submit, args=(txs[i::8],))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [f.result(timeout=30) for f in futures]
    assert len(results) == 360 and all(isinstance(h, bytes) for h in results)
    assert len(pool) == 360
    assert batcher.processed == 360
    assert batcher.batches < 360  # batching happened
    batcher.close()


def test_batcher_rejects_invalid_within_batch():
    """A bad tx inside a batch fails ITS future only; neighbors land."""
    wallets, pool = make_pool(n_senders=3, max_pool=100)
    batcher = TxBatcher(pool, max_batch=16)
    from reth_tpu.primitives.types import Transaction

    good1 = tip_tx(wallets[0], 2)
    signed = tip_tx(wallets[1], 2)
    bad = Transaction(**{**signed.__dict__, "r": 0})  # unrecoverable sig
    good2 = tip_tx(wallets[2], 2)
    f1, f2, f3 = batcher.submit(good1), batcher.submit(bad), batcher.submit(good2)
    assert isinstance(f1.result(30), bytes)
    assert isinstance(f3.result(30), bytes)
    with pytest.raises(PoolError, match="signature"):
        f2.result(30)
    assert len(pool) == 2
    batcher.close()


def test_discard_worst_same_sender_stays_visible():
    """Regression (round-4 review): when the evicted worst tx belongs to
    the INCOMING sender, the new tx must land in a live by_sender entry —
    not an orphaned dict invisible to best_transactions."""
    wallets, pool = make_pool(n_senders=4, max_pool=3)
    s = wallets[0]
    pool.add_transaction(tip_tx(s, 1))             # worst, nonce 0
    pool.add_transaction(tip_tx(wallets[1], 5))
    pool.add_transaction(tip_tx(wallets[2], 5))
    assert len(pool) == 3
    # same sender submits a much better tx at nonce 1: the discard evicts
    # their nonce-0 worst (and thus their whole by_sender entry)
    better = tip_tx(s, 500)
    h = pool.add_transaction(better)
    assert pool.contains(h)
    assert s.address in pool.by_sender
    assert pool.by_sender[s.address][1].tx.hash == h
    # nonce 1 is gapped (nonce 0 evicted) so not yieldable, but VISIBLE:
    # once the chain advances past nonce 0 it becomes minable — the ghost
    # bug made it permanently invisible instead
    assert h in {p.tx.hash for txs in pool.by_sender.values()
                 for p in txs.values()}
