"""Era downloader + Era pipeline stage: verified acquisition, staged
import, resume, corruption rejection (reference crates/era-downloader +
the Era stage)."""

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.era import EraError, export_era
from reth_tpu.era_sync import EraDownloader, EraSource, EraStage
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


@pytest.fixture()
def era_archive(tmp_path):
    """A 6-block chain exported as two era1 archives + checksum index."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    for i in range(6):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    # a synced source node to export from
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(6)
    src_dir = tmp_path / "source"
    src_dir.mkdir()
    export_era(factory, 1, 3, src_dir / "chain-00000.era1")
    export_era(factory, 4, 6, src_dir / "chain-00001.era1")
    assert EraSource.build_index(src_dir) == 2
    return builder, src_dir


def fresh_node(builder):
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    return factory


def test_era_stage_full_sync(era_archive, tmp_path):
    builder, src_dir = era_archive
    factory = fresh_node(builder)
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    stages = [EraStage(dl, EthBeaconConsensus(CPU))] + \
        default_stages(committer=CPU)
    Pipeline(factory, stages).run(6)
    with factory.provider() as p:
        assert p.stage_checkpoint("Finish") == 6
        assert p.header_by_number(6).state_root == \
            builder.blocks[6].header.state_root
        assert p.account(b"\x0b" * 20).balance == sum(100 + i for i in range(6))
    # the cache holds verified copies
    assert (tmp_path / "cache" / "chain-00000.era1").exists()


def test_era_stage_commits_per_archive_and_resumes(era_archive, tmp_path):
    builder, src_dir = era_archive
    factory = fresh_node(builder)
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    stage = EraStage(dl, EthBeaconConsensus(CPU))
    # drive the stage manually: first call imports ONE archive and yields
    from reth_tpu.stages.api import ExecInput

    with factory.provider_rw() as p:
        out = stage.execute(p, ExecInput(target=6, checkpoint=0))
        assert out.checkpoint == 3 and not out.done
    # restart (fresh stage object): continues from the checkpoint
    stage2 = EraStage(dl, EthBeaconConsensus(CPU))
    with factory.provider_rw() as p:
        out = stage2.execute(p, ExecInput(target=6, checkpoint=3))
        assert out.checkpoint == 6 and out.done
        assert p.header_by_number(6) is not None


def test_corrupt_archive_rejected(era_archive, tmp_path):
    builder, src_dir = era_archive
    # flip a byte in the second archive AFTER the index was built
    target = src_dir / "chain-00001.era1"
    raw = bytearray(target.read_bytes())
    raw[100] ^= 0xFF
    target.write_bytes(bytes(raw))
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    entries = dict(EraSource(src_dir).entries())
    dl.fetch("chain-00000.era1", entries["chain-00000.era1"])  # fine
    with pytest.raises(EraError, match="checksum mismatch"):
        dl.fetch("chain-00001.era1", entries["chain-00001.era1"])
    # nothing half-written in the cache
    assert not (tmp_path / "cache" / "chain-00001.era1").exists()


def test_era_partial_coverage_hands_off(era_archive, tmp_path):
    """Archives cover 1..6; a target beyond them leaves the stage done at
    6 so the online stages take over."""
    builder, src_dir = era_archive
    factory = fresh_node(builder)
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    stage = EraStage(dl, EthBeaconConsensus(CPU))
    from reth_tpu.stages.api import ExecInput

    with factory.provider_rw() as p:
        out = stage.execute(p, ExecInput(target=100, checkpoint=0))
        assert out.checkpoint == 3 and not out.done
        out = stage.execute(p, ExecInput(target=100, checkpoint=3))
        assert out.checkpoint == 6 and out.done


# -- HTTP era source ---------------------------------------------------------


def _serve_dir(root):
    """Serve a directory over HTTP WITH Range support (the stock
    http.server ignores Range; resume needs 206)."""
    import http.server
    import threading

    class H(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(root), **kw)

        def log_message(self, *a):
            pass

        def do_GET(self):
            import os
            path = self.translate_path(self.path)
            if not os.path.isfile(path):
                self.send_error(404)
                return
            data = open(path, "rb").read()
            rng = self.headers.get("Range")
            if rng and rng.startswith("bytes="):
                start = int(rng.split("=")[1].split("-")[0])
                if start >= len(data):
                    self.send_error(416)
                    return
                body = data[start:]
                self.send_response(206)
                self.send_header("Content-Range",
                                 f"bytes {start}-{len(data)-1}/{len(data)}")
            else:
                body = data
                self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _era_dir(tmp_path, n_blocks=6):
    """A directory holding one era1 archive + index.txt."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    for i in range(n_blocks):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(n_blocks)
    root = tmp_path / "pub"
    root.mkdir()
    export_era(factory, 1, n_blocks, root / "test-00000.era1")
    EraSource.build_index(root)
    return root, builder


def test_http_era_source_roundtrip(tmp_path):
    """import-era machinery over a REAL http server: index fetch, ranged
    stream, checksum verify, then a full pipeline import."""
    from reth_tpu.era_sync import EraDownloader, era_source_for

    root, chain = _era_dir(tmp_path)
    srv, url = _serve_dir(root)
    try:
        src = era_source_for(url)
        dl = EraDownloader(src, tmp_path / "cache")
        paths = dl.fetch_all()
        assert len(paths) == 1 and paths[0].exists()
        from reth_tpu.era import read_era1

        era = read_era1(paths[0])
        assert len(era.blocks) == len(chain.blocks) - 1  # sans genesis
    finally:
        srv.shutdown()


def test_http_era_source_resumes_partial(tmp_path):
    """A truncated .part resumes with a Range request instead of a full
    refetch, and the checksum still verifies."""
    from reth_tpu.era_sync import EraDownloader, era_source_for

    root, chain = _era_dir(tmp_path)
    srv, url = _serve_dir(root)
    try:
        full = (root / "test-00000.era1").read_bytes()
        cache = tmp_path / "cache"
        cache.mkdir()
        # simulate an interrupted download: half the bytes already on disk
        (cache / "test-00000.part").write_bytes(full[: len(full) // 2])
        dl = EraDownloader(era_source_for(url), cache)
        name, checksum = dl.source.entries()[0]
        p = dl.fetch(name, checksum)
        assert p.read_bytes() == full
    finally:
        srv.shutdown()


def test_http_era_source_rejects_corrupt(tmp_path):
    """A server returning corrupt bytes is caught by the checksum gate."""
    from reth_tpu.era import EraError
    from reth_tpu.era_sync import EraDownloader, era_source_for

    root, chain = _era_dir(tmp_path)
    # corrupt the archive AFTER the index was built
    p = root / "test-00000.era1"
    raw = bytearray(p.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    p.write_bytes(bytes(raw))
    srv, url = _serve_dir(root)
    try:
        dl = EraDownloader(era_source_for(url), tmp_path / "cache")
        name, checksum = dl.source.entries()[0]
        with pytest.raises(EraError, match="checksum"):
            dl.fetch(name, checksum)
    finally:
        srv.shutdown()
