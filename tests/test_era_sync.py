"""Era downloader + Era pipeline stage: verified acquisition, staged
import, resume, corruption rejection (reference crates/era-downloader +
the Era stage)."""

import pytest

from reth_tpu.consensus import EthBeaconConsensus
from reth_tpu.era import EraError, export_era
from reth_tpu.era_sync import EraDownloader, EraSource, EraStage
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256_batch_np
from reth_tpu.stages import Pipeline, default_stages
from reth_tpu.storage import MemDb, ProviderFactory
from reth_tpu.storage.genesis import import_chain, init_genesis
from reth_tpu.testing import ChainBuilder, Wallet
from reth_tpu.trie import TrieCommitter

CPU = TrieCommitter(hasher=keccak256_batch_np)


@pytest.fixture()
def era_archive(tmp_path):
    """A 6-block chain exported as two era1 archives + checksum index."""
    alice = Wallet(0xA11CE)
    builder = ChainBuilder({alice.address: Account(balance=10**21)},
                           committer=CPU)
    for i in range(6):
        builder.build_block([alice.transfer(b"\x0b" * 20, 100 + i)])
    # a synced source node to export from
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    import_chain(factory, builder.blocks[1:], EthBeaconConsensus(CPU))
    Pipeline(factory, default_stages(committer=CPU)).run(6)
    src_dir = tmp_path / "source"
    src_dir.mkdir()
    export_era(factory, 1, 3, src_dir / "chain-00000.era1")
    export_era(factory, 4, 6, src_dir / "chain-00001.era1")
    assert EraSource.build_index(src_dir) == 2
    return builder, src_dir


def fresh_node(builder):
    factory = ProviderFactory(MemDb())
    init_genesis(factory, builder.genesis, builder.accounts_at_genesis,
                 committer=CPU)
    return factory


def test_era_stage_full_sync(era_archive, tmp_path):
    builder, src_dir = era_archive
    factory = fresh_node(builder)
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    stages = [EraStage(dl, EthBeaconConsensus(CPU))] + \
        default_stages(committer=CPU)
    Pipeline(factory, stages).run(6)
    with factory.provider() as p:
        assert p.stage_checkpoint("Finish") == 6
        assert p.header_by_number(6).state_root == \
            builder.blocks[6].header.state_root
        assert p.account(b"\x0b" * 20).balance == sum(100 + i for i in range(6))
    # the cache holds verified copies
    assert (tmp_path / "cache" / "chain-00000.era1").exists()


def test_era_stage_commits_per_archive_and_resumes(era_archive, tmp_path):
    builder, src_dir = era_archive
    factory = fresh_node(builder)
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    stage = EraStage(dl, EthBeaconConsensus(CPU))
    # drive the stage manually: first call imports ONE archive and yields
    from reth_tpu.stages.api import ExecInput

    with factory.provider_rw() as p:
        out = stage.execute(p, ExecInput(target=6, checkpoint=0))
        assert out.checkpoint == 3 and not out.done
    # restart (fresh stage object): continues from the checkpoint
    stage2 = EraStage(dl, EthBeaconConsensus(CPU))
    with factory.provider_rw() as p:
        out = stage2.execute(p, ExecInput(target=6, checkpoint=3))
        assert out.checkpoint == 6 and out.done
        assert p.header_by_number(6) is not None


def test_corrupt_archive_rejected(era_archive, tmp_path):
    builder, src_dir = era_archive
    # flip a byte in the second archive AFTER the index was built
    target = src_dir / "chain-00001.era1"
    raw = bytearray(target.read_bytes())
    raw[100] ^= 0xFF
    target.write_bytes(bytes(raw))
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    entries = dict(EraSource(src_dir).entries())
    dl.fetch("chain-00000.era1", entries["chain-00000.era1"])  # fine
    with pytest.raises(EraError, match="checksum mismatch"):
        dl.fetch("chain-00001.era1", entries["chain-00001.era1"])
    # nothing half-written in the cache
    assert not (tmp_path / "cache" / "chain-00001.era1").exists()


def test_era_partial_coverage_hands_off(era_archive, tmp_path):
    """Archives cover 1..6; a target beyond them leaves the stage done at
    6 so the online stages take over."""
    builder, src_dir = era_archive
    factory = fresh_node(builder)
    dl = EraDownloader(EraSource(src_dir), tmp_path / "cache")
    stage = EraStage(dl, EthBeaconConsensus(CPU))
    from reth_tpu.stages.api import ExecInput

    with factory.provider_rw() as p:
        out = stage.execute(p, ExecInput(target=100, checkpoint=0))
        assert out.checkpoint == 3 and not out.done
        out = stage.execute(p, ExecInput(target=100, checkpoint=3))
        assert out.checkpoint == 6 and out.done
