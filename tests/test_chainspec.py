"""ChainSpec: activation schedule, EIP-2124 fork ids, ForkFilter rules.

Fork-id vectors are the published EIP-2124 mainnet test vectors (the
same ones the reference's alloy ForkId tests use), so a match here means
we interoperate with real clients' Status handshakes.
"""

import pytest

from reth_tpu.chainspec import (
    BERLIN, CANCUN, HOMESTEAD, LONDON, MAINNET, PARIS, PETERSBURG, SHANGHAI,
    SPURIOUS_DRAGON, ChainSpec, ForkCondition, dev_spec,
)


def fid(h):
    return bytes.fromhex(h)


# (head_number, expected FORK_HASH, expected FORK_NEXT) — EIP-2124 appendix
MAINNET_VECTORS = [
    (0, "fc64ec04", 1_150_000),
    (1_149_999, "fc64ec04", 1_150_000),
    (1_150_000, "97c2c34c", 1_920_000),
    (1_919_999, "97c2c34c", 1_920_000),
    (1_920_000, "91d1f948", 2_463_000),
    (2_462_999, "91d1f948", 2_463_000),
    (2_463_000, "7a64da13", 2_675_000),
    (2_674_999, "7a64da13", 2_675_000),
    (2_675_000, "3edd5b10", 4_370_000),
    (4_369_999, "3edd5b10", 4_370_000),
    (4_370_000, "a00bc324", 7_280_000),
    (7_279_999, "a00bc324", 7_280_000),
    (7_280_000, "668db0af", 9_069_000),
    (9_068_999, "668db0af", 9_069_000),
    (9_069_000, "879d6e30", 9_200_000),
    (9_199_999, "879d6e30", 9_200_000),
]

# (head_number, head_timestamp, hash, next) — post-merge era: the organic
# merge block must NOT fold into the hash (these are the fork ids real
# clients advertise today)
MAINNET_VECTORS_POSTMERGE = [
    (15_537_394, 1_668_000_000, "f0afd0e3", 1_681_338_455),  # paris
    (17_034_870, 1_681_338_455, "dce96c2d", 1_710_338_135),  # shanghai
    (19_426_587, 1_710_338_135, "9f3d2254", 1_746_612_311),  # cancun
    (22_431_084, 1_746_612_311, "c376cf8b", 0),              # prague
]


@pytest.mark.parametrize("head,ts,want_hash,want_next", MAINNET_VECTORS_POSTMERGE)
def test_mainnet_fork_id_postmerge(head, ts, want_hash, want_next):
    assert MAINNET.fork_id(head, ts) == (fid(want_hash), want_next)


@pytest.mark.parametrize("head,want_hash,want_next", MAINNET_VECTORS)
def test_mainnet_fork_id_vectors(head, want_hash, want_next):
    assert MAINNET.fork_id(head) == (fid(want_hash), want_next)


def test_fork_id_after_timestamp_forks():
    # past every scheduled fork: FORK_NEXT must be 0 and the hash stable
    h, nxt = MAINNET.fork_id(25_000_000, 1_800_000_000)
    assert nxt == 0
    assert MAINNET.fork_id(30_000_000, 1_900_000_000) == (h, nxt)


def test_spec_at_ordering():
    assert MAINNET.spec_at(0) == "frontier"
    assert MAINNET.spec_at(1_150_000) == "homestead"
    # Constantinople and Petersburg activate together; Petersburg wins
    assert MAINNET.spec_at(7_280_000) == PETERSBURG
    assert MAINNET.spec_at(20_000_000, 1_681_338_455) == SHANGHAI
    assert MAINNET.spec_at(20_000_000, 1_746_612_311) == "prague"
    assert MAINNET.is_at_least(LONDON, 12_965_000)
    assert not MAINNET.is_at_least(LONDON, 12_964_999)
    assert MAINNET.is_at_least(HOMESTEAD, 12_965_000)


def test_fork_filter_accepts_same_and_syncing_peers():
    # same fork, nothing announced
    MAINNET.validate_fork_id((fid("668db0af"), 0), 7_987_396)
    # same fork, remote announces a future fork we'll learn about
    MAINNET.validate_fork_id((fid("668db0af"), 99_999_999_999), 7_987_396)
    # we're on Byzantium pre-fork, remote already announces Petersburg
    MAINNET.validate_fork_id((fid("a00bc324"), 7_280_000), 7_279_999)
    # remote behind us but announcing the upgrade it will apply
    MAINNET.validate_fork_id((fid("a00bc324"), 7_280_000), 7_987_396)
    # remote ahead of us (we are the stale one): accept
    MAINNET.validate_fork_id((fid("668db0af"), 9_069_000), 7_279_999)
    # fully-synced remote (FORK_NEXT=0) while we're still syncing: accept —
    # this is every healthy peer during initial sync
    MAINNET.validate_fork_id((fid("c376cf8b"), 0), 7_279_999)


def test_fork_filter_rejects():
    # remote behind and NOT announcing the fork it must apply
    with pytest.raises(ValueError):
        MAINNET.validate_fork_id((fid("a00bc324"), 0), 7_987_396)
    # different chain entirely
    with pytest.raises(ValueError):
        MAINNET.validate_fork_id((fid("5cddc0e1"), 0), 7_987_396)


def test_from_genesis_config():
    spec = ChainSpec.from_genesis_config({
        "chainId": 7777, "homesteadBlock": 0, "berlinBlock": 5,
        "londonBlock": 10, "terminalTotalDifficulty": 0,
        "shanghaiTime": 100, "cancunTime": 200,
    }, genesis_hash=b"\x11" * 32)
    assert spec.chain_id == 7777
    assert spec.hardforks[BERLIN] == ForkCondition(block=5)
    assert spec.hardforks[PARIS] == ForkCondition(ttd=0)
    # ttd=0 => Paris is active from genesis, outranking London
    assert spec.spec_at(10, 99) == PARIS
    assert spec.spec_at(10, 100) == SHANGHAI
    assert spec.spec_at(10, 200) == CANCUN
    # eip155/eip158 both map onto spurious dragon without duplication
    spec2 = ChainSpec.from_genesis_config({"eip155Block": 3, "eip158Block": 3})
    assert spec2.hardforks[SPURIOUS_DRAGON] == ForkCondition(block=3)


def test_dev_spec_everything_active():
    spec = dev_spec()
    assert spec.spec_at(0, 0) == "prague"
    assert spec.fork_id(0, 0) == (spec.fork_id(100, 100)[0], 0)


def test_chain_spec_persists_across_restart(tmp_path):
    """A node relaunched from a datadir without --genesis rebuilds the same
    spec (and so keeps advertising the right fork id)."""
    from reth_tpu.node import Node, NodeConfig
    from reth_tpu.primitives.keccak import keccak256_batch_np
    from reth_tpu.trie import TrieCommitter
    from reth_tpu.primitives.types import Header, EMPTY_ROOT_HASH
    from reth_tpu.trie.state_root import state_root

    cpu = TrieCommitter(hasher=keccak256_batch_np)
    root, _ = state_root({}, {}, committer=cpu)
    genesis = Header(number=0, state_root=root, base_fee_per_gas=10**9,
                     withdrawals_root=EMPTY_ROOT_HASH)
    spec = ChainSpec.from_genesis_config(
        {"chainId": 777, "londonBlock": 5, "shanghaiTime": 99},
        genesis_hash=genesis.hash, chain_id=777)
    cfg = NodeConfig(chain_id=777, datadir=str(tmp_path),
                     genesis_header=genesis, chain_spec=spec)
    node = Node(cfg, committer=cpu)
    node.factory.db.flush()

    cfg2 = NodeConfig(chain_id=777, datadir=str(tmp_path))
    node2 = Node(cfg2, committer=cpu)
    assert cfg2.chain_spec is not None
    assert cfg2.chain_spec.fork_id(10, 100) == spec.fork_id(10, 100)
    assert cfg2.chain_spec.hardforks == spec.hardforks
