"""EIP-4844 blob pool + engine V4/V5 + getBlobs.

Blob math runs on the insecure dev KZG setup (mini-blobs sized to the
setup) — the same commit/prove/verify cycle as mainnet 4096-element
blobs, at test scale.
"""

from __future__ import annotations

import pytest

from reth_tpu.pool.blobstore import (
    BlobSidecar,
    BlobStoreError,
    DiskBlobStore,
    InMemoryBlobStore,
)
from reth_tpu.pool.pool import PoolError, TransactionPool
from reth_tpu.primitives import kzg
from reth_tpu.primitives.types import Account, Transaction
from reth_tpu.testing import Wallet


def _mini_blob(seed: int) -> bytes:
    n = kzg.active_blob_size()
    return b"".join(
        ((seed * 1000 + i) % kzg.BLS_MODULUS).to_bytes(32, "big") for i in range(n)
    )


def make_sidecar(n_blobs=1, seed=1) -> BlobSidecar:
    blobs, commitments, proofs = [], [], []
    for i in range(n_blobs):
        blob = _mini_blob(seed + i)
        c = kzg.blob_to_kzg_commitment(blob)
        p = kzg.compute_blob_kzg_proof(blob, c)
        blobs.append(blob)
        commitments.append(c)
        proofs.append(p)
    return BlobSidecar(tuple(blobs), tuple(commitments), tuple(proofs))


@pytest.fixture(scope="module")
def sidecar():
    return make_sidecar(n_blobs=2)


# -- KZG blob math -----------------------------------------------------------


def test_blob_proof_verifies_and_tamper_fails(sidecar):
    blob, c, p = sidecar.blobs[0], sidecar.commitments[0], sidecar.proofs[0]
    assert kzg.verify_blob_kzg_proof(blob, c, p)
    bad = bytearray(blob)
    bad[40] ^= 1
    assert not kzg.verify_blob_kzg_proof(bytes(bad), c, p)
    assert not kzg.verify_blob_kzg_proof(blob, sidecar.commitments[1], p)


def test_sidecar_validate_binds_versioned_hashes(sidecar):
    sidecar.validate(sidecar.versioned_hashes())
    with pytest.raises(BlobStoreError, match="versioned hashes"):
        sidecar.validate(tuple(reversed(sidecar.versioned_hashes())))


def test_sidecar_codec_roundtrip(sidecar):
    assert BlobSidecar.decode(sidecar.encode()) == sidecar


# -- stores ------------------------------------------------------------------


def test_disk_store_roundtrip(tmp_path, sidecar):
    store = DiskBlobStore(tmp_path)
    store.insert(b"\x01" * 32, sidecar)
    # cold read (fresh instance = no cache)
    cold = DiskBlobStore(tmp_path)
    assert cold.get(b"\x01" * 32) == sidecar
    assert cold.get(b"\x02" * 32) is None
    cold.delete(b"\x01" * 32)
    assert DiskBlobStore(tmp_path).get(b"\x01" * 32) is None


def test_by_versioned_hashes(sidecar):
    store = InMemoryBlobStore()
    store.insert(b"\x01" * 32, sidecar)
    vh = sidecar.versioned_hashes()
    got = store.by_versioned_hashes([vh[1], b"\x01" + b"\x00" * 31, vh[0]])
    assert got[0] == (sidecar.blobs[1], sidecar.proofs[1])
    assert got[1] is None
    assert got[2] == (sidecar.blobs[0], sidecar.proofs[0])


# -- pool --------------------------------------------------------------------


class _State:
    def __init__(self, accounts):
        self._a = accounts

    def account(self, addr):
        return self._a.get(addr)


def _blob_tx(wallet, sidecar, nonce=0, max_blob_fee=100):
    return wallet.sign_tx(Transaction(
        tx_type=3, chain_id=1, nonce=nonce, max_fee_per_gas=10**10,
        max_priority_fee_per_gas=10**9, gas_limit=21_000, to=b"\x20" * 20,
        max_fee_per_blob_gas=max_blob_fee,
        blob_versioned_hashes=sidecar.versioned_hashes(),
    ), bump_nonce=False)


@pytest.fixture
def pool_and_wallet():
    w = Wallet(0xB10B)
    pool = TransactionPool(lambda: _State({w.address: Account(balance=10**21)}))
    pool.base_fee = 10**9
    return pool, w


def test_pool_admits_valid_blob_tx(pool_and_wallet, sidecar):
    pool, w = pool_and_wallet
    tx = _blob_tx(w, sidecar)
    h = pool.add_blob_transaction(tx, sidecar)
    assert pool.contains(h)
    assert pool.get_blob_sidecar(h) == sidecar
    assert [t.hash for t in pool.best_transactions()] == [h]


def test_pool_rejects_blob_tx_without_sidecar(pool_and_wallet, sidecar):
    pool, w = pool_and_wallet
    with pytest.raises(PoolError, match="sidecar"):
        pool.add_transaction(_blob_tx(w, sidecar))


def test_pool_rejects_bad_sidecar(pool_and_wallet, sidecar):
    pool, w = pool_and_wallet
    bad = BlobSidecar(sidecar.blobs, tuple(reversed(sidecar.commitments)),
                      sidecar.proofs)
    with pytest.raises(PoolError, match="sidecar"):
        pool.add_blob_transaction(_blob_tx(w, sidecar), bad)


def test_blob_fee_market_gates_execution(pool_and_wallet, sidecar):
    pool, w = pool_and_wallet
    tx = _blob_tx(w, sidecar, max_blob_fee=5)
    h = pool.add_blob_transaction(tx, sidecar)
    pool.on_canonical_state_change(10**9, blob_base_fee=50)
    assert list(pool.best_transactions()) == []  # blob-fee gated
    pool.on_canonical_state_change(10**9, blob_base_fee=3)
    assert [t.hash for t in pool.best_transactions()] == [h]


def test_mined_sidecar_retained_then_evicted(sidecar):
    """Mined blob txs leave the pool but their sidecars stay for a
    retention window (reorg re-broadcast + engine_getBlobs after
    canonicalization — reference keeps them until finalization); the
    bounded FIFO evicts the oldest beyond the window."""
    w1, w2 = Wallet(0xB10B), Wallet(0xB20B)
    accounts = {w1.address: Account(balance=10**21),
                w2.address: Account(balance=10**21)}
    pool = TransactionPool(lambda: _State(accounts))
    pool.base_fee = 10**9
    pool.mined_sidecar_retention = 1
    h1 = pool.add_blob_transaction(_blob_tx(w1, sidecar), sidecar)
    h2 = pool.add_blob_transaction(_blob_tx(w2, sidecar), sidecar)
    # both mined: nonces advance
    accounts[w1.address] = Account(nonce=1, balance=10**21)
    accounts[w2.address] = Account(nonce=1, balance=10**21)
    pool.on_canonical_state_change(10**9)
    assert not pool.contains(h1) and not pool.contains(h2)
    retained = [h for h in (h1, h2) if pool.get_blob_sidecar(h) is not None]
    assert len(retained) == 1  # window of 1: newest kept, oldest evicted


# -- engine API ---------------------------------------------------------------


def test_engine_get_blobs(pool_and_wallet, sidecar):
    from reth_tpu.rpc.engine_api import EngineApi

    pool, w = pool_and_wallet
    pool.add_blob_transaction(_blob_tx(w, sidecar), sidecar)
    api = EngineApi(tree=None, payload_service=None, pool=pool)
    vh = sidecar.versioned_hashes()
    got = api.engine_getBlobsV1(["0x" + vh[0].hex(), "0x" + b"\x01".ljust(32, b"\x00").hex()])
    assert got[0] == {"blob": "0x" + sidecar.blobs[0].hex(),
                      "proof": "0x" + sidecar.proofs[0].hex()}
    assert got[1] is None
    # V2: all-or-nothing
    assert api.engine_getBlobsV2(["0x" + vh[0].hex(), "0x" + b"\x02".ljust(32, b"\x00").hex()]) is None
    v2 = api.engine_getBlobsV2(["0x" + vh[0].hex(), "0x" + vh[1].hex()])
    assert v2 is not None and v2[1]["proofs"] == ["0x" + sidecar.proofs[1].hex()]


def test_requests_hash():
    import hashlib

    from reth_tpu.rpc.engine_api import compute_requests_hash

    r0, r1 = b"\x00" + b"dep", b"\x01" + b"wd"
    want = hashlib.sha256(
        hashlib.sha256(r0).digest() + hashlib.sha256(r1).digest()
    ).digest()
    assert compute_requests_hash([r0, r1]) == want
    # empty/one-byte requests are skipped per EIP-7685
    assert compute_requests_hash([r0, b"\x02"]) == hashlib.sha256(
        hashlib.sha256(r0).digest()
    ).digest()
