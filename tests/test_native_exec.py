"""Native execution core: differential equivalence with the interpreter.

The native wave executor (native/evmexec.cpp) must be bit-identical to
the Python interpreter on everything it accepts, and must cleanly
decline (falling back per-tx) on everything else. Every test here runs
the same block through `execute_block_bal` with the native core ON and
OFF plus the serial `BlockExecutor`, and compares receipts (consensus
encoding), gas, post state, and changesets.
"""

from __future__ import annotations

import os

import pytest

from reth_tpu.engine.bal import execute_block_bal, record_access_list
from reth_tpu.evm import BlockExecutor, EvmConfig
from reth_tpu.evm.executor import InMemoryStateSource
from reth_tpu.primitives import Account
from reth_tpu.primitives.keccak import keccak256
from reth_tpu.primitives.types import Block, Header, Transaction
from reth_tpu.testing import Wallet


def _block(txs, senders_of, gas_limit=2_000_000_000):
    header = Header(number=1, gas_limit=gas_limit, base_fee_per_gas=7,
                    beneficiary=b"\xcb" * 20)
    return Block(header, tuple(txs), (), ())


def _assert_equal_outputs(out_a, out_b):
    assert out_a.gas_used == out_b.gas_used
    assert len(out_a.receipts) == len(out_b.receipts)
    for ra, rb in zip(out_a.receipts, out_b.receipts):
        assert ra.encode_2718() == rb.encode_2718()
    assert out_a.post_accounts == out_b.post_accounts
    assert out_a.post_storage == out_b.post_storage
    assert out_a.changes.accounts == out_b.changes.accounts
    assert out_a.changes.storage == out_b.changes.storage


def _run_all_ways(src_accounts, codes, block, senders, storages=None):
    """serial vs BAL-python vs BAL-native on identical fresh sources."""
    def mk():
        return InMemoryStateSource(
            {a: acc for a, acc in src_accounts.items()},
            {a: dict(s) for a, s in (storages or {}).items()},
            dict(codes))

    cfg = EvmConfig(chain_id=1)
    serial = BlockExecutor(mk(), cfg).execute(block, senders)
    bal = record_access_list(mk(), block, senders, cfg)
    os.environ["RETH_TPU_BAL_NATIVE"] = "0"
    try:
        py_out, py_stats = execute_block_bal(mk(), block, senders, bal, cfg)
    finally:
        os.environ.pop("RETH_TPU_BAL_NATIVE", None)
    nat_out, nat_stats = execute_block_bal(mk(), block, senders, bal, cfg)
    _assert_equal_outputs(serial, py_out)
    _assert_equal_outputs(serial, nat_out)
    return nat_stats


def test_transfers_and_stores_run_natively():
    store_code = bytes.fromhex("5f355f5500")
    wallets = [Wallet(0x40000 + i) for i in range(40)]
    accounts = {w.address: Account(balance=10**20) for w in wallets}
    contract = b"\x5c" + b"\x00" * 19
    accounts[contract] = Account(code_hash=keccak256(store_code))
    codes = {keccak256(store_code): store_code}
    txs = [w.transfer(bytes([0xD0]) + i.to_bytes(19, "big"), 1 + i)
           for i, w in enumerate(wallets[:30])]
    txs += [w.call(contract, i.to_bytes(32, "big"))
            for i, w in enumerate(wallets[30:])]
    senders = [w.address for w in wallets]
    stats = _run_all_ways(accounts, codes, _block(txs, senders), senders)
    assert stats["native"] == len(txs)  # everything took the native core


def test_conflicting_senders_and_same_slot_writes():
    """Same-sender chains (nonce progression across waves) and same-slot
    writers (inter-wave merge) must stay natively executable and exact."""
    store_code = bytes.fromhex("5f355f5500")
    a, b = Wallet(0x51000), Wallet(0x52000)
    contract = b"\x5d" + b"\x00" * 19
    accounts = {a.address: Account(balance=10**20),
                b.address: Account(balance=10**20),
                contract: Account(code_hash=keccak256(store_code))}
    codes = {keccak256(store_code): store_code}
    txs = []
    for i in range(6):  # alternating same-slot writers + same-sender chain
        txs.append(a.call(contract, (100 + i).to_bytes(32, "big")))
        txs.append(b.call(contract, (200 + i).to_bytes(32, "big")))
    senders = [a.address, b.address] * 6
    stats = _run_all_ways(accounts, codes, _block(txs, senders), senders)
    assert stats["native"] == len(txs)


def test_unsupported_ops_fall_back_per_tx():
    """A tx whose code CALLs (unsupported natively) must fall back to the
    interpreter while its neighbors stay native — and the outputs still
    match the serial reference exactly."""
    store_code = bytes.fromhex("5f355f5500")
    # caller: CALL(store, ...) — CALL is native-unsupported
    store = b"\x5e" + b"\x00" * 19
    caller_rt = (bytes.fromhex("5f5f5f5f5f73") + store
                 + bytes.fromhex("61ffff" + "f1" + "00"))
    caller = b"\x5f" + b"\x00" * 19
    ws = [Wallet(0x61000 + i) for i in range(9)]
    accounts = {w.address: Account(balance=10**20) for w in ws}
    accounts[store] = Account(code_hash=keccak256(store_code))
    accounts[caller] = Account(code_hash=keccak256(caller_rt))
    codes = {keccak256(store_code): store_code,
             keccak256(caller_rt): caller_rt}
    txs = [ws[0].transfer(b"\x01" * 20, 5),
           ws[1].call(caller, b""),  # falls back (CALL)
           ws[2].transfer(b"\x02" * 20, 7),
           ws[3].call(store, (3).to_bytes(32, "big")),
           ws[4].call(caller, b""),  # falls back again
           ws[5].transfer(b"\x03" * 20, 9)]
    senders = [ws[0].address, ws[1].address, ws[2].address, ws[3].address,
               ws[4].address, ws[5].address]
    stats = _run_all_ways(accounts, codes, _block(txs, senders), senders)
    assert stats["native"] >= 3  # the flat txs took the native core
    assert stats["serial"] >= 2  # the CALL txs fell back


def test_reverts_refunds_and_logs_match():
    """SSTORE refunds (clear), LOG emission, and REVERT outputs through
    the native core must match the interpreter's receipts exactly."""
    # sstore(0, calldata0); log1(topic=calldata0); revert if calldata0==0
    rt = bytes.fromhex(
        "5f35"        # calldata[0]
        "805f55"      # sstore(0, v)       (dup v)
        "80601f5fa1"  # log1(0,31,topic=v)
        "15600f57"    # if v==0 jump 0x0f
        "00"          # stop
        "5b5f5ffd")   # jumpdest revert(0,0)
    contract = b"\x60" + b"\x00" * 19
    ws = [Wallet(0x71000 + i) for i in range(6)]
    accounts = {w.address: Account(balance=10**20) for w in ws}
    accounts[contract] = Account(code_hash=keccak256(rt))
    codes = {keccak256(rt): rt}
    # pre-set slot so the zero-write earns the EIP-3529 clear refund
    storages = {contract: {b"\x00" * 32: 7}}
    txs = [ws[0].call(contract, (5).to_bytes(32, "big")),
           ws[1].call(contract, (0).to_bytes(32, "big")),   # clears slot
           ws[2].call(contract, (0).to_bytes(32, "big")),   # reverts? no:
           # zero value jumps to revert — both zero-calls revert, so the
           # slot-clear rolls back; mixed success/revert receipts
           ws[3].call(contract, (9).to_bytes(32, "big")),
           ws[4].transfer(ws[5].address, 123)]
    senders = [w.address for w in ws[:5]]
    _run_all_ways(accounts, codes, _block(txs, senders), senders,
                  storages=storages)


@pytest.mark.parametrize("family", ["transfers", "storage", "createCall",
                                    "deepRevert", "setCodeTx"])
def test_conformance_families_differential(family):
    """Conformance-family chains re-executed block-by-block through the
    BAL engine with the native core on: output must equal the serial
    executor for every block (native handles what it can, declines the
    rest — either way the result is identical)."""
    from reth_tpu.conformance.generate import SCENARIOS

    bld = SCENARIOS[family](0, network="Prague")
    cfg = EvmConfig(chain_id=1)

    # rebuild the chain's pre-state and replay each block both ways
    base = InMemoryStateSource(bld.accounts_at_genesis,
                               bld.storage_at_genesis, bld.codes_at_genesis)
    base2 = InMemoryStateSource(bld.accounts_at_genesis,
                                bld.storage_at_genesis, bld.codes_at_genesis)
    hashes = {0: bld.genesis.hash}
    for blk in bld.blocks[1:]:
        senders = [tx.recover_sender() for tx in blk.transactions]
        serial = BlockExecutor(base, cfg).execute(
            blk, senders, block_hashes=dict(hashes))
        bal = record_access_list(base2, blk, senders, cfg)
        nat, _stats = execute_block_bal(base2, blk, senders, bal, cfg,
                                        block_hashes=dict(hashes))
        _assert_equal_outputs(serial, nat)
        hashes[blk.header.number] = blk.hash
        for s, out in ((base, serial), (base2, nat)):
            for addr, acc in out.post_accounts.items():
                if acc is None:
                    s.accounts.pop(addr, None)
                else:
                    s.accounts[addr] = acc
            for addr in out.changes.wiped_storage:
                s.storages[addr] = {}
            for addr, slots in out.post_storage.items():
                per = s.storages.setdefault(addr, {})
                for k, v in slots.items():
                    if v:
                        per[k] = v
                    else:
                        per.pop(k, None)
            for ch, c in out.changes.new_bytecodes.items():
                s.codes[ch] = c


def test_calldatacopy_codecopy_u64_offset_overflow():
    """Src offsets near 2**64 must zero-fill, not wrap: `ss + i` overflows
    uint64 in the native core and (pre-fix) read real calldata/code bytes,
    forking it from the interpreter. Differential with offset 2**64 - 2."""
    # CALLDATACOPY(dst=0, src=2**64-2, len=32); slot0 = mem[0] (must be 0);
    # slot1 = 1 (a marker write so post-state is visibly identical);
    # then CODECOPY(dst=0, src=2**64-2, len=32); slot2 = mem[0]
    huge = (2**64 - 2).to_bytes(8, "big").hex()
    code = bytes.fromhex(
        "6020" + "67" + huge + "6000" + "37"      # CALLDATACOPY
        + "600051" + "600055"                     # slot0 = mload(0)
        + "6001" + "600155"                       # slot1 = 1
        + "6020" + "67" + huge + "6000" + "39"    # CODECOPY
        + "600051" + "600255"                     # slot2 = mload(0)
        + "00")
    contract = b"\x6a" + b"\x00" * 19
    ws = [Wallet(0x71000 + i) for i in range(3)]
    accounts = {w.address: Account(balance=10**20) for w in ws}
    accounts[contract] = Account(code_hash=keccak256(code))
    codes = {keccak256(code): code}
    # NON-ZERO calldata: a wrapped read would copy these bytes into memory
    txs = [w.call(contract, b"\xaa" * 64) for w in ws]
    senders = [w.address for w in ws]
    stats = _run_all_ways(accounts, codes, _block(txs, senders), senders)
    assert stats["native"] >= 1  # the native core actually executed these


def test_calldatacopy_partial_tail_still_copies():
    """Sanity differential for the in-range tail: src inside calldata but
    src+len past its end (copy the available bytes, zero-fill the rest)."""
    # CALLDATACOPY(dst=0, src=8, len=32); slot0 = mload(0)
    code = bytes.fromhex("6020" + "6008" + "6000" + "37"
                         + "600051" + "600055" + "00")
    contract = b"\x6b" + b"\x00" * 19
    w = Wallet(0x72000)
    accounts = {w.address: Account(balance=10**20),
                contract: Account(code_hash=keccak256(code))}
    codes = {keccak256(code): code}
    txs = [w.call(contract, bytes(range(1, 17)))]   # 16-byte calldata
    stats = _run_all_ways(accounts, codes, _block(txs, [w.address]),
                          [w.address])
    assert stats["native"] >= 1
